"""Crash-safe control plane tests (ISSUE 20): journal + router recovery.

Pins the durable-admission contracts:

* **journal semantics**: append-only JSONL segments fold to identical
  per-job state across close/reopen; a torn final line (the only damage
  an O_APPEND line-commit crash can inflict) is GC'd at reopen without
  touching committed records, while garbage anywhere earlier raises
  ``JournalError``; prefix compaction never drops a live job; the
  clean-shutdown marker is consumed so only an uninterrupted drain
  counts;
* **write-ahead admission**: a ``router.journal`` append fault fails
  the admission loudly — 503 ``journal_error``, the job is never
  registered — and the resubmission lands normally;
* **recovery window**: while the router reconciles its journal,
  submissions answer 503 ``recovering`` (+ ``Retry-After`` at the HTTP
  front door) but idempotent resubmissions still dedupe — answering
  about an already-admitted job costs no queue slot;
* **restart replay**: a cleanly-drained router leaves the marker; the
  next incarnation re-registers terminal jobs so idempotency keys keep
  deduping across the restart.  A crash journal forwarded to a DEAD
  replica base requeues the job with its pinned workdir and the resumed
  run completes byte-identically under the preserved trace id;
* the ``journal_append`` / ``router_recovered`` value lints accept the
  emitted shapes and reject kind/arithmetic violations.

Scene shape and params are shared with ``tests/test_fleet_serve.py`` so
the process-wide jit cache keeps in-process replicas warm.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from land_trendr_tpu.cli import _sigterm_to_interrupt
from land_trendr_tpu.fleet import FleetRouter, RouterConfig
from land_trendr_tpu.fleet.journal import AdmissionJournal, JournalError
from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack
from land_trendr_tpu.runtime import faults
from land_trendr_tpu.serve import Rejection, SegmentationServer, ServeConfig

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

_PARAMS = {"max_segments": 4, "vertex_count_overshoot": 2}
_TILE = 20


@pytest.fixture(scope="module")
def stack_dir(tmp_path_factory) -> str:
    d = str(tmp_path_factory.mktemp("recovery_stack") / "stack")
    write_stack(
        d,
        make_stack(
            SceneSpec(width=40, height=40, year_start=2000, year_end=2008,
                      seed=3)
        ),
    )
    return d


def _digest_workdir(workdir: str) -> dict:
    out: dict = {}
    for p in sorted(Path(workdir).glob("tile_*.npz")):
        with np.load(p) as z:
            out[p.name] = {
                name: hashlib.sha256(
                    np.ascontiguousarray(z[name]).tobytes()
                ).hexdigest()
                for name in sorted(z.files)
            }
    return out


def _job(stack_dir: str, **kw) -> dict:
    return {
        "stack_dir": stack_dir,
        "tile_size": _TILE,
        "params": dict(_PARAMS),
        "run_overrides": {"retry_backoff_s": 0.0},
        **kw,
    }


def _await_terminal(router: FleetRouter, job_id: str,
                    timeout_s: float = 300.0) -> dict:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        s = router.job_status(job_id)
        if s is not None and s["state"] not in ("queued", "routed"):
            return s
        time.sleep(0.05)
    raise TimeoutError(f"job {job_id} not terminal within {timeout_s}s")


def _events(workdir: str) -> list:
    return [
        json.loads(line)
        for line in (Path(workdir) / "events.jsonl").read_text().splitlines()
        if line.strip()
    ]


def _fold(journal: AdmissionJournal) -> str:
    return json.dumps(journal.replay(), sort_keys=True)


class _OneReplica:
    """One in-process SegmentationServer on a thread."""

    def __init__(self, tmp_path) -> None:
        self.server = SegmentationServer(ServeConfig(
            workdir=str(tmp_path / "replica"), feed_cache_mb=32,
        ))
        self.thread = threading.Thread(target=self.server.serve_forever)
        self.thread.start()
        self.bases = (f"http://127.0.0.1:{self.server.port}",)

    def stop(self) -> None:
        self.server.stop()
        self.thread.join(timeout=120)


# ---------------------------------------------------------------------------
# journal unit semantics


def test_journal_roundtrip_replays_identically(tmp_path):
    root = str(tmp_path / "j")
    j = AdmissionJournal(root)
    assert j.was_clean is False  # no prior drain: nothing to consume
    for i in range(3):
        jid = f"job-{i}"
        j.append("admitted", jid, payload={"n": i}, trace_id=f"t{i}")
        j.append("forwarded", jid, replica_base="http://x",
                 replica_job_id=f"r{i}")
    j.append("terminal", "job-0", state="done", error=None)
    folded = j.replay()
    assert folded["job-0"]["status"] == "terminal"
    assert folded["job-0"]["state"] == "done"
    assert folded["job-1"]["status"] == "forwarded"
    assert folded["job-1"]["replica_job_id"] == "r1"
    assert folded["job-1"]["payload"] == {"n": 1}
    st = j.stats()
    assert st["appends"] == 7 and st["segments"] == 1
    before = _fold(j)
    j.close()
    # a closed journal refuses appends rather than losing them silently
    with pytest.raises(JournalError, match="closed"):
        j.append("terminal", "job-1", state="done")
    j2 = AdmissionJournal(root)
    assert _fold(j2) == before, "fold must be stable across close/reopen"
    j2.close()


def test_journal_torn_tail_gc_and_corruption(tmp_path):
    root = str(tmp_path / "j")
    j = AdmissionJournal(root)
    j.append("admitted", "keep-1", payload={})
    before = _fold(j)
    j.close()
    seg = Path(root) / "seg-00000001.jsonl"
    with open(seg, "ab") as f:
        f.write(b'{"rec":"admitted","job_id":"torn-')  # mid-crash tear
    j2 = AdmissionJournal(root)
    assert _fold(j2) == before, "committed records must survive the GC"
    assert "torn-" not in j2.replay()
    j2.close()
    assert seg.read_bytes().endswith(b"\n"), "tail rewritten line-clean"
    # garbage BEFORE the final line is corruption, not crash residue
    seg.write_bytes(b"not json\n" + seg.read_bytes())
    with pytest.raises(JournalError, match="corrupt"):
        AdmissionJournal(root)


def test_journal_rotation_compaction_keeps_live_jobs(tmp_path):
    root = str(tmp_path / "j")
    j = AdmissionJournal(root, segment_bytes=1)  # floor clamps to 64KiB
    j.append("admitted", "live-0", payload={})
    i = 0
    while j.stats()["segment"] < 3:  # force >= 2 rotations
        jid = f"dead-{i:05d}"
        j.append("admitted", jid, payload={"fill": "x" * 64})
        j.append("terminal", jid, state="done")
        i += 1
    folded = j.replay()
    assert folded["live-0"]["status"] == "admitted"
    j.compact()
    after = j.replay()
    # live-0 pins segment 1, so prefix-only compaction drops NOTHING —
    # replay order can never be reordered around a live admission
    assert json.dumps(folded, sort_keys=True) == \
        json.dumps(after, sort_keys=True)
    assert j.stats()["segments"] >= 3
    # terminal-ise the pin: now the fully-terminal prefix goes away
    j.append("terminal", "live-0", state="done")
    dropped = j.compact()
    assert dropped >= 1
    assert j.stats()["segments"] + dropped >= 3
    assert all(
        s["status"] == "terminal" for s in j.replay().values()
    )
    j.close()


def test_journal_clean_marker_consumed_at_reopen(tmp_path):
    root = str(tmp_path / "j")
    j = AdmissionJournal(root)
    j.mark_clean()
    j.close()
    assert (Path(root) / "clean").exists()
    j2 = AdmissionJournal(root)
    assert j2.was_clean is True
    assert not (Path(root) / "clean").exists(), "marker must be consumed"
    j2.close()
    # the NEXT reopen (no new drain) must not still look clean
    j3 = AdmissionJournal(root)
    assert j3.was_clean is False
    j3.close()


def test_journal_append_fault_raises_journal_error(tmp_path):
    j = AdmissionJournal(str(tmp_path / "j"))
    faults.activate(faults.parse_schedule("seed=1,router.journal@0=io"))
    try:
        with pytest.raises(JournalError):
            j.append("admitted", "a-1", payload={})
    finally:
        faults.deactivate()
    assert j.stats()["appends"] == 0, "a failed append is NOT written"
    j.append("admitted", "a-1", payload={})
    assert j.replay()["a-1"]["status"] == "admitted"
    j.close()


# ---------------------------------------------------------------------------
# write-ahead admission: journal fault → 503, job never admitted


def test_journal_fault_503_then_resubmit_lands(stack_dir, tmp_path):
    replica = _OneReplica(tmp_path)
    rt_dir = str(tmp_path / "rt")
    router = FleetRouter(RouterConfig(
        workdir=rt_dir, replicas=replica.bases, health_interval_s=0.2,
        fault_schedule="seed=1,router.journal@0=io",
    ))
    rt_thread = threading.Thread(target=router.serve_forever)
    rt_thread.start()
    try:
        with pytest.raises(Rejection) as exc:
            router.submit(_job(stack_dir))
        assert exc.value.http_status == 503
        assert exc.value.reason == "journal_error"
        assert router.jobs() == [], "an un-durable job is never admitted"
        s = _await_terminal(router, router.submit(_job(stack_dir))["job_id"])
        assert s["state"] == "done", s.get("error")
    finally:
        router.stop()
        rt_thread.join(timeout=300)
        replica.stop()
    evs = _events(rt_dir)
    rejected = [e for e in evs if e.get("ev") == "job_rejected"]
    assert [e["reason"] for e in rejected] == ["journal_error"]
    kinds = sorted({e["rec"] for e in evs if e.get("ev") == "journal_append"})
    assert kinds == ["admitted", "forwarded", "terminal"]


# ---------------------------------------------------------------------------
# recovery window: 503 + Retry-After, dedupe still answers


def test_recovery_window_503_but_dedupe_answers(stack_dir, tmp_path):
    replica = _OneReplica(tmp_path)
    rt_dir = str(tmp_path / "rt")
    router = FleetRouter(RouterConfig(
        workdir=rt_dir, replicas=replica.bases, health_interval_s=0.2,
    ))
    try:
        first = router.submit(_job(stack_dir, idempotency_key="win-1"))
        # deterministic stand-in for the reconciliation window (the
        # constructor holds it only while _recover probes replicas)
        router._recovering = True
        with pytest.raises(Rejection) as exc:
            router.submit(_job(stack_dir))
        assert (exc.value.http_status, exc.value.reason) == \
            (503, "recovering")
        # the HTTP front door maps the window to 503 + Retry-After
        req = urllib.request.Request(
            f"http://127.0.0.1:{router.port}/jobs",
            data=json.dumps(_job(stack_dir)).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as http_exc:
            urllib.request.urlopen(req, timeout=30)
        assert http_exc.value.code == 503
        assert http_exc.value.headers["Retry-After"] is not None
        assert json.loads(http_exc.value.read())["error"] == "recovering"
        # idempotent resubmission dedupes THROUGH the window: no queue
        # slot is consumed answering about an already-admitted job
        again = router.submit(_job(stack_dir, idempotency_key="win-1"))
        assert again["deduped"] is True
        assert again["job_id"] == first["job_id"]
        router._recovering = False
        router.submit(_job(stack_dir))  # window lifted: admission resumes
    finally:
        router.stop()
        router.serve_forever()  # drains the queued jobs as cancelled
        replica.stop()


# ---------------------------------------------------------------------------
# restart replay: clean-drain dedupe, crash requeue → resume


def test_clean_restart_dedupes_across_incarnations(stack_dir, tmp_path):
    replica = _OneReplica(tmp_path)
    rt_dir = str(tmp_path / "rt")
    cfg = dict(
        workdir=rt_dir, replicas=replica.bases, health_interval_s=0.2,
    )
    router = FleetRouter(RouterConfig(**cfg))
    rt_thread = threading.Thread(target=router.serve_forever)
    rt_thread.start()
    try:
        snap = router.submit(_job(stack_dir, idempotency_key="restart-1"))
        s = _await_terminal(router, snap["job_id"])
        assert s["state"] == "done", s.get("error")
    finally:
        router.stop()
        rt_thread.join(timeout=300)
    assert (Path(rt_dir) / "journal" / "clean").exists(), \
        "a fully-drained stop earns the clean-shutdown marker"
    router2 = FleetRouter(RouterConfig(**cfg))
    try:
        assert router2.recovery is not None
        assert router2.recovery["clean"] is True
        assert router2.recovery["replayed"] == 0, \
            "a drained journal has nothing to reconcile"
        assert router2.recovery["deduped"] == 1
        again = router2.submit(
            _job(stack_dir, idempotency_key="restart-1")
        )
        assert again["deduped"] is True
        assert again["job_id"] == snap["job_id"]
        assert again["state"] == "done"
    finally:
        router2.stop()
        router2.serve_forever()
        replica.stop()


def test_crash_recovery_requeues_and_resumes_byte_identical(
    stack_dir, tmp_path
):
    """A fabricated crash journal (admitted + forwarded to a DEAD
    replica base) must requeue the job with its pinned workdir; the
    resumed run completes under the preserved trace id with artifacts
    byte-identical to a clean routed run, and the idempotency key still
    dedupes against the replayed job."""
    replica = _OneReplica(tmp_path)
    clean_wd = str(tmp_path / "clean_wd")
    jwd = str(tmp_path / "crash_wd")
    jid = "rt-0-00001"
    payload = _job(stack_dir, workdir=jwd, out_dir=jwd + "_o")
    try:
        router = FleetRouter(RouterConfig(
            workdir=str(tmp_path / "rt_clean"), replicas=replica.bases,
            health_interval_s=0.2,
        ))
        rt_thread = threading.Thread(target=router.serve_forever)
        rt_thread.start()
        try:
            s = _await_terminal(router, router.submit(
                _job(stack_dir, workdir=clean_wd)
            )["job_id"])
            assert s["state"] == "done", s.get("error")
        finally:
            router.stop()
            rt_thread.join(timeout=300)

        rt_crash = tmp_path / "rt_crash"
        (rt_crash / "journal").mkdir(parents=True)
        (rt_crash / "journal" / "seg-00000001.jsonl").write_text(
            json.dumps({
                "rec": "admitted", "job_id": jid, "payload": payload,
                "tenant": "t", "priority": 0, "key": "k",
                "trace_id": "testrecover00001",
                "idempotency_key": "crash-1", "workdir": jwd,
                "out_dir": jwd + "_o", "source": "http", "t": 0.0,
            }) + "\n" + json.dumps({
                "rec": "forwarded", "job_id": jid,
                "replica_base": "http://127.0.0.1:9",
                "replica_job_id": "gone-1", "t": 0.0,
            }) + "\n"
        )
        router2 = FleetRouter(RouterConfig(
            workdir=str(rt_crash), replicas=replica.bases,
            health_interval_s=0.2,
        ))
        rt_thread = threading.Thread(target=router2.serve_forever)
        rt_thread.start()
        try:
            assert router2.recovery["replayed"] == 1
            assert router2.recovery["requeued"] == 1
            assert router2.recovery["clean"] is False
            s = _await_terminal(router2, jid)
            assert s["state"] == "done", s.get("error")
            assert s["trace_id"] == "testrecover00001", \
                "the resumed run keeps the admission's trace id"
            again = router2.submit(
                {**payload, "idempotency_key": "crash-1"}
            )
            assert again["deduped"] is True and again["job_id"] == jid
        finally:
            router2.stop()
            rt_thread.join(timeout=300)
    finally:
        replica.stop()
    assert _digest_workdir(jwd) == _digest_workdir(clean_wd)
    assert _digest_workdir(jwd), "parity over zero tiles proves nothing"
    recovered = [
        e for e in _events(str(tmp_path / "rt_crash"))
        if e.get("ev") == "router_recovered"
    ]
    assert len(recovered) == 1
    assert recovered[0]["requeued"] == 1
    assert recovered[0]["relayed"] + recovered[0]["requeued"] \
        + recovered[0]["reattached"] <= recovered[0]["replayed"]


# ---------------------------------------------------------------------------
# value lints + SIGTERM drain hook


def test_journal_event_value_lints():
    from check_events_schema import journal_value_errors

    from land_trendr_tpu.obs.events import EVENT_FIELDS

    assert "journal_append" in EVENT_FIELDS
    assert "router_recovered" in EVENT_FIELDS
    ja = {"ev": "journal_append", "rec": "admitted",
          "segment": 1, "bytes": 120}
    assert journal_value_errors(ja, 1) == []
    assert journal_value_errors({**ja, "rec": "committed"}, 1)
    assert journal_value_errors({**ja, "bytes": 0}, 1)
    assert journal_value_errors({**ja, "segment": 0}, 1)
    rr = {"ev": "router_recovered", "replayed": 2, "relayed": 1,
          "requeued": 1, "reattached": 0, "deduped": 0,
          "recovery_s": 0.01, "clean": False}
    assert journal_value_errors(rr, 1) == []
    assert journal_value_errors({**rr, "requeued": 2}, 1), \
        "the reconciliation split cannot exceed what was replayed"
    # bools are not counts: the guard must not arithmetic over them
    assert journal_value_errors({**rr, "replayed": True}, 1) == []


def test_sigterm_drains_like_sigint():
    with pytest.raises(KeyboardInterrupt):
        _sigterm_to_interrupt(signal.SIGTERM, None)
