"""End-to-end runtime-driver tests: synthetic stack → tiles → rasters.

Covers the driver contract from SURVEY.md §2/§4 (stacks in, segment rasters
out on the input grid), the manifest checkpoint/resume semantics (§5), the
fused DN tile op against the precomputed-index path, and tile-level retry.
"""

import dataclasses
import json
import logging
import os

import numpy as np
import pytest

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.io.geotiff import read_geotiff
from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack
from land_trendr_tpu.ops import indices as idx
from land_trendr_tpu.ops.segment import jax_segment_pixels
from land_trendr_tpu.runtime import (
    RunConfig,
    TileManifest,
    assemble_outputs,
    load_stack_dir,
    plan_tiles,
    run_stack,
    stack_from_synthetic,
)

SPEC = SceneSpec(width=48, height=40, year_start=1990, year_end=2013, seed=11)
PARAMS = LTParams(max_segments=4, vertex_count_overshoot=2)


@pytest.fixture(scope="module")
def synth():
    return make_stack(SPEC)


@pytest.fixture(scope="module")
def rstack(synth):
    return stack_from_synthetic(synth)


def make_cfg(tmp, **kw):
    kw.setdefault("params", PARAMS)
    kw.setdefault("tile_size", 32)
    return RunConfig(
        workdir=os.path.join(tmp, "work"), out_dir=os.path.join(tmp, "out"), **kw
    )


def test_plan_tiles_covers_scene():
    tiles = plan_tiles(40, 48, 32)
    assert len(tiles) == 4
    cover = np.zeros((40, 48), np.int32)
    for t in tiles:
        cover[t.y0 : t.y0 + t.h, t.x0 : t.x0 + t.w] += 1
    assert (cover == 1).all()


def test_run_and_assemble(tmp_path, synth, rstack):
    cfg = make_cfg(tmp_path, ftv_indices=("ndvi",), write_fitted=True)
    summary = run_stack(rstack, cfg)
    assert summary["pixels"] == 40 * 48
    assert summary["tiles"] == 4 and summary["tiles_skipped_resume"] == 0

    paths = assemble_outputs(rstack, cfg)
    for product in (
        "n_vertices", "vertex_years", "vertex_fit_vals", "seg_magnitude",
        "rmse", "p_of_f", "model_valid", "fitted", "ftv_ndvi",
    ):
        assert product in paths and os.path.exists(paths[product])

    valid, _, _ = read_geotiff(paths["model_valid"])
    vyears, _, _ = read_geotiff(paths["vertex_years"])
    nverts, _, _ = read_geotiff(paths["n_vertices"])
    assert valid.shape == (40, 48)
    assert vyears.shape[0] == PARAMS.max_vertices
    assert nverts.shape == (40, 48)

    # ground truth: most disturbed pixels fit with a vertex near the event
    disturbed = synth.truth_year >= 0
    fit_on_disturbed = valid.astype(bool) & disturbed
    assert fit_on_disturbed.sum() > 0.7 * disturbed.sum()
    # for fitted disturbed pixels, some vertex year within ±2 of truth
    yr = vyears[:, fit_on_disturbed]          # (NV, n_fit); 0 in dead slots
    truth = synth.truth_year[fit_on_disturbed][None]
    live = yr > 0
    dist = np.where(live, np.abs(yr - truth), np.inf).min(axis=0)
    assert (dist <= 2).mean() > 0.8

    # fitted trajectories mosaic matches a direct kernel run on one window
    fitted, _, _ = read_geotiff(paths["fitted"])
    t = plan_tiles(40, 48, 32)[0]
    sr = {b: idx.scale_sr(rstack.dn_bands[b][:, :32, :32].reshape(len(rstack.years), -1).T)
          for b in idx.required_bands("nbr")}
    mask = np.asarray(idx.qa_valid_mask(rstack.qa[:, :32, :32].reshape(len(rstack.years), -1).T)) & np.asarray(idx.sr_valid_mask(sr))
    series = np.asarray(idx.compute_index("nbr", sr))
    ref = jax_segment_pixels(rstack.years, series, mask, PARAMS)
    # rasters are written in natural NBR orientation; the kernel fits the
    # disturbance-positive flip, so undo it for comparison
    got = -fitted[:, :32, :32].reshape(len(rstack.years), -1).T
    # The fused-DN program and the two-step path are different XLA programs;
    # in float32 fusion differences can flip knife-edge argmax decisions on a
    # small fraction of pixels (ops/segment.py float32 tolerance contract).
    diff = np.abs(got - np.asarray(ref.fitted))
    agree_px = (diff.max(axis=1) <= 1e-5).mean()
    assert agree_px > 0.97, f"only {agree_px:.1%} of pixels agree bitwise-ish"
    assert np.median(diff) < 1e-6


def test_resume_skips_done_tiles(tmp_path, rstack, caplog):
    cfg = make_cfg(tmp_path)
    run_stack(rstack, cfg)
    with caplog.at_level(logging.INFO, logger="land_trendr_tpu.runtime"):
        summary2 = run_stack(rstack, cfg)
    assert summary2["tiles_skipped_resume"] == 4
    assert summary2["pixels"] == 0


def test_resume_rejects_foreign_workdir(tmp_path, rstack):
    cfg = make_cfg(tmp_path)
    run_stack(rstack, cfg)
    cfg2 = make_cfg(tmp_path, params=LTParams(max_segments=3))
    with pytest.raises(ValueError, match="different\\s+run"):
        run_stack(rstack, cfg2)
    # resume=False discards and reruns
    cfg3 = make_cfg(tmp_path, params=LTParams(max_segments=3), resume=False)
    summary = run_stack(rstack, cfg3)
    assert summary["pixels"] == 40 * 48


def test_partial_manifest_resumes_missing_only(tmp_path, rstack):
    cfg = make_cfg(tmp_path)
    tiles = plan_tiles(*rstack.shape, cfg.tile_size)
    run_stack(rstack, cfg, tiles=tiles[:2])  # only half the scene
    with pytest.raises(RuntimeError, match="missing from manifest"):
        assemble_outputs(rstack, cfg)
    summary = run_stack(rstack, cfg)  # picks up the rest
    assert summary["tiles_skipped_resume"] == 2
    assert summary["pixels"] == sum(t.h * t.w for t in tiles[2:])
    assemble_outputs(rstack, cfg)


def test_manifest_ignores_missing_artifact(tmp_path, rstack):
    cfg = make_cfg(tmp_path)
    run_stack(rstack, cfg)
    manifest = TileManifest(cfg.workdir, cfg.fingerprint(rstack))
    os.remove(manifest.tile_path(1))  # simulate lost artifact
    summary = run_stack(rstack, cfg)
    assert summary["tiles_skipped_resume"] == 3  # tile 1 recomputed
    assemble_outputs(rstack, cfg)


def test_manifest_jsonl_structure(tmp_path, rstack):
    cfg = make_cfg(tmp_path)
    run_stack(rstack, cfg)
    manifest = TileManifest(cfg.workdir, cfg.fingerprint(rstack))
    recs = list(manifest.iter_records())
    assert recs[0]["kind"] == "header"
    tiles = [r for r in recs if r["kind"] == "tile"]
    assert len(tiles) == 4
    for r in tiles:
        assert {"tile_id", "y0", "x0", "px_per_s", "no_fit_rate"} <= set(r)


def test_output_rasters_natural_orientation(tmp_path, synth, rstack):
    """Written products undo the disturbance-positive flip: healthy-forest
    NBR fits read ≈ +0.7, and disturbance segments have negative magnitude."""
    cfg = make_cfg(tmp_path, ftv_indices=("ndvi",))
    run_stack(rstack, cfg)
    paths = assemble_outputs(rstack, cfg)
    valid, _, _ = read_geotiff(paths["model_valid"])
    vfit, _, _ = read_geotiff(paths["vertex_fit_vals"])
    mag, _, _ = read_geotiff(paths["seg_magnitude"])
    nv, _, _ = read_geotiff(paths["n_vertices"])
    fit = valid.astype(bool)
    # first vertex fit value: natural NBR, overwhelmingly positive on forest
    assert np.median(vfit[0][fit]) > 0.3
    # disturbed fitted pixels: strongest segment magnitude is a *drop*
    dist_fit = fit & (synth.truth_year >= 0)
    strongest = np.take_along_axis(mag, np.abs(mag).argmax(axis=0)[None], axis=0)[0]
    assert (strongest[dist_fit] < 0).mean() > 0.8
    # FTV rasters also natural: NDVI fits positive on fitted forest pixels
    ftv, _, _ = read_geotiff(paths["ftv_ndvi"])
    assert np.median(ftv[:, fit]) > 0.2


def test_crash_orphan_tmp_swept(tmp_path, rstack):
    """STALE tmp artifacts (a crashed writer's leftovers) are swept on
    resume; FRESH ones survive — in a shared pod workdir they may be a
    peer process's in-flight write (code-review r3)."""
    import time

    cfg = make_cfg(tmp_path)
    run_stack(rstack, cfg)
    stale = os.path.join(cfg.workdir, "tile_00099.npz.123.tmp.npz")
    fresh = os.path.join(cfg.workdir, "tile_00098.npz.456.tmp.npz")
    for p in (stale, fresh):
        with open(p, "wb") as f:
            f.write(b"partial garbage")
    old = time.time() - 3600
    os.utime(stale, (old, old))
    run_stack(rstack, cfg)  # resume sweeps only the stale artifact
    assert not os.path.exists(stale)
    assert os.path.exists(fresh)


def test_fingerprint_covers_write_fitted(rstack):
    """A toggled write_fitted must invalidate old artifacts (they lack or
    carry extra arrays), so it participates in the run fingerprint."""
    a = RunConfig(write_fitted=False).fingerprint(rstack)
    b = RunConfig(write_fitted=True).fingerprint(rstack)
    assert a != b


def test_required_bands_subset_feeds_driver(tmp_path, rstack):
    """NBR-only runs must not mask on (or ship) bands NBR never reads: a
    pixel with garbage blue DNs but clean nir/swir2 still fits."""
    bad = stack_from_synthetic(make_stack(SPEC))
    bad.dn_bands["blue"][:] = -30000  # sr ≈ -1.0, far outside [0, 1]
    cfg = make_cfg(tmp_path)
    summary = run_stack(bad, cfg)
    assert summary["fit_rate"] > 0.3  # unchanged from the clean run


def test_year_parse_landsat_product_id(tmp_path, synth):
    """Path/row digit runs ('045030') before the date must not win."""
    stack_dir = os.path.join(tmp_path, "stack")
    write_stack(stack_dir, synth)
    for n in os.listdir(stack_dir):
        year = n.split("_")[1].split(".")[0]
        os.rename(
            os.path.join(stack_dir, n),
            os.path.join(stack_dir, f"LC08_L2SP_045030_{year}.tif"),
        )
    rstack = load_stack_dir(stack_dir)
    np.testing.assert_array_equal(rstack.years, synth.years)


def test_geotiff_roundtrip_driver(tmp_path, synth):
    """Disk path: write per-year GeoTIFFs, load them back, run the driver."""
    stack_dir = os.path.join(tmp_path, "stack")
    write_stack(stack_dir, synth)
    rstack = load_stack_dir(stack_dir)
    assert rstack.n_years == len(synth.years)
    assert rstack.shape == (SPEC.height, SPEC.width)
    assert rstack.geo is not None and rstack.geo.pixel_scale is not None

    cfg = make_cfg(tmp_path)
    run_stack(rstack, cfg)
    paths = assemble_outputs(rstack, cfg)
    valid, geo, _ = read_geotiff(paths["model_valid"])
    assert valid.shape == (SPEC.height, SPEC.width)
    # outputs inherit the input grid
    assert geo.pixel_scale == rstack.geo.pixel_scale
    assert geo.tiepoint == rstack.geo.tiepoint


def test_retry_then_fail(tmp_path, rstack, monkeypatch):
    cfg = make_cfg(tmp_path, max_retries=1)
    calls = {"n": 0}

    def boom(*a, **k):
        calls["n"] += 1
        raise RuntimeError("injected device fault")

    monkeypatch.setattr("land_trendr_tpu.runtime.driver.process_tile_dn", boom)
    with pytest.raises(RuntimeError, match="failed after 2 attempts"):
        run_stack(rstack, cfg)
    assert calls["n"] == 2


def test_retry_recovers_from_transient_fault(tmp_path, rstack, monkeypatch):
    from land_trendr_tpu.ops.tile import process_tile_dn as real_op

    cfg = make_cfg(tmp_path, max_retries=2)
    calls = {"n": 0}

    def flaky(*a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient fault")
        return real_op(*a, **k)

    monkeypatch.setattr("land_trendr_tpu.runtime.driver.process_tile_dn", flaky)
    summary = run_stack(rstack, cfg)
    assert summary["pixels"] == 40 * 48


def test_writer_failure_fails_fast(tmp_path, rstack, monkeypatch):
    """A persistent artifact-write failure aborts within a couple of tiles
    (depth-1 write queue backpressure), not at the end of the whole run."""
    from land_trendr_tpu.runtime.manifest import TileManifest

    cfg = make_cfg(tmp_path)
    computed = {"n": 0}

    def bad_record(self, tile_id, arrays, meta, **kw):
        raise OSError("disk full (injected)")

    monkeypatch.setattr(TileManifest, "record", bad_record)

    from land_trendr_tpu.ops.tile import process_tile_dn as real_op

    def counting_op(*a, **k):
        computed["n"] += 1
        return real_op(*a, **k)

    monkeypatch.setattr(
        "land_trendr_tpu.runtime.driver.process_tile_dn", counting_op
    )
    with pytest.raises(OSError, match="disk full"):
        run_stack(rstack, cfg)
    # 4-tile run: failure of tile 0's write surfaces while tile 1/2 are in
    # flight — well before all tiles are computed
    assert computed["n"] <= 3


def test_chunked_kernel_through_driver(tmp_path, rstack):
    """The production chunked-kernel path (VERDICT r2 item #5): a driver run
    whose tiles exceed ``chunk_px`` routes segmentation through
    ``jax_segment_pixels_chunked`` (including the pad-to-multiple case,
    1024 px tiles with 256 px chunks would be exact — use 192 to force a
    pad) and produces rasters identical to the unchunked run."""
    cfg_plain = make_cfg(str(tmp_path / "plain"), chunk_px=None)
    cfg_chunk = make_cfg(str(tmp_path / "chunk"), chunk_px=192)  # 1024 % 192 != 0
    run_stack(rstack, cfg_plain)
    run_stack(rstack, cfg_chunk)
    p_plain = assemble_outputs(rstack, cfg_plain)
    p_chunk = assemble_outputs(rstack, cfg_chunk)
    assert set(p_plain) == set(p_chunk)

    # The DN path runs float32: chunking changes XLA's fusion choices, so
    # rare knife-edge pixels may legally flip decisions (the f32 tolerance
    # contract in ops/segment.py — measured flip rate ~0.003%).  Gate on
    # near-total agreement for decisions, and near-exactness on agreeing
    # pixels for the float products.
    valid_a, _, _ = read_geotiff(p_plain["model_valid"])
    valid_b, _, _ = read_geotiff(p_chunk["model_valid"])
    nv_a, _, _ = read_geotiff(p_plain["n_vertices"])
    nv_b, _, _ = read_geotiff(p_chunk["n_vertices"])
    agree = (valid_a == valid_b) & (nv_a == nv_b)
    assert agree.mean() >= 0.995, f"decision agreement {agree.mean():.4%}"
    for product, path_a in p_plain.items():
        a, _, _ = read_geotiff(path_a)
        b, _, _ = read_geotiff(p_chunk[product])
        sel = agree if a.ndim == 2 else np.broadcast_to(agree, a.shape)
        if a.dtype.kind in "iub":
            np.testing.assert_array_equal(a[sel], b[sel], err_msg=product)
        else:
            np.testing.assert_allclose(
                a[sel], b[sel], rtol=2e-5, atol=2e-6, err_msg=product
            )


def test_mesh_sharded_driver(tmp_path, rstack):
    """run_stack(mesh=...) shards every tile's pixel axis over the virtual
    8-device mesh and produces rasters agreeing with the single-device run
    at the f32 contract level (mesh partitioning, like chunking, legally
    flips rare knife-edge decisions)."""
    from land_trendr_tpu.parallel import make_mesh

    mesh = make_mesh()
    # tile_size 30 → 900 px per tile; 900 % 8 != 0 exercises the pad path
    cfg_one = make_cfg(str(tmp_path / "one"), tile_size=30)
    cfg_mesh = make_cfg(str(tmp_path / "mesh"), tile_size=30)
    s1 = run_stack(rstack, cfg_one)
    s2 = run_stack(rstack, cfg_mesh, mesh=mesh)
    assert s1["mesh_devices"] == 1
    assert s2["mesh_devices"] == mesh.devices.size
    assert s2["pixels"] == s1["pixels"] == 40 * 48

    p1 = assemble_outputs(rstack, cfg_one)
    p2 = assemble_outputs(rstack, cfg_mesh)
    valid_a, _, _ = read_geotiff(p1["model_valid"])
    valid_b, _, _ = read_geotiff(p2["model_valid"])
    nv_a, _, _ = read_geotiff(p1["n_vertices"])
    nv_b, _, _ = read_geotiff(p2["n_vertices"])
    agree = (valid_a == valid_b) & (nv_a == nv_b)
    assert agree.mean() >= 0.995, f"decision agreement {agree.mean():.4%}"
    for product, path_a in p1.items():
        a, _, _ = read_geotiff(path_a)
        b, _, _ = read_geotiff(p2[product])
        sel = agree if a.ndim == 2 else np.broadcast_to(agree, a.shape)
        if a.dtype.kind in "iub":
            np.testing.assert_array_equal(a[sel], b[sel], err_msg=product)
        else:
            np.testing.assert_allclose(
                a[sel], b[sel], rtol=2e-5, atol=2e-6, err_msg=product
            )


def test_mesh_resume_context_rejected(tmp_path, rstack):
    """A single-device resume must not silently mix into a mesh workdir
    (partitioning flips rare f32 knife-edges); assembly, which is
    mesh-blind, still reads the same workdir fine."""
    from land_trendr_tpu.parallel import make_mesh

    cfg = make_cfg(tmp_path, tile_size=30)
    run_stack(rstack, cfg, mesh=make_mesh())
    with pytest.raises(ValueError, match="execution context"):
        run_stack(rstack, cfg)  # same cfg, no mesh
    assemble_outputs(rstack, cfg)  # context-free consumer: OK


def test_impl_resume_context_rejected(tmp_path, rstack):
    """A resume must not mix kernel implementations (pallas/xla decisions
    differ at f32 knife edges); the resolved impl lives in the manifest
    execution context, so assembly — which never runs the kernel and may
    happen on a host with a different backend — stays impl-blind."""
    import dataclasses

    cfg = make_cfg(tmp_path, tile_size=30)
    run_stack(rstack, cfg)  # auto -> xla on the CPU test backend
    # a workdir produced by the OTHER implementation must be refused on
    # compute resume ...
    cfg_p = dataclasses.replace(cfg, impl="pallas")
    with pytest.raises(ValueError, match="execution context"):
        run_stack(rstack, cfg_p)
    # ... while the fingerprint (and so assembly) is impl-blind
    assert cfg.fingerprint(rstack) == cfg_p.fingerprint(rstack)
    assemble_outputs(rstack, cfg_p)


def test_output_compression_choice(tmp_path, rstack):
    """assemble_outputs honors RunConfig.out_compress (GDAL-era pipelines
    commonly emit LZW); rasters decode identically either way."""
    cfg = make_cfg(tmp_path, out_compress="lzw")
    run_stack(rstack, cfg)
    paths = assemble_outputs(rstack, cfg)
    rmse, _, info = read_geotiff(paths["rmse"])
    assert info.compression == 5  # LZW on disk
    assert rmse.shape == (40, 48)


def test_parallel_writers_match_single(tmp_path, rstack):
    """write_workers=3 produces the same manifest + rasters as the default
    single writer (writes are per-tile independent; only scheduling
    changes), and memory-bounding backpressure still collects every job."""
    cfg1 = make_cfg(os.path.join(tmp_path, "a"))
    cfg3 = make_cfg(os.path.join(tmp_path, "b"), write_workers=3)
    s1 = run_stack(rstack, cfg1)
    s3 = run_stack(rstack, cfg3)
    assert s1["pixels"] == s3["pixels"] and s1["fit_rate"] == s3["fit_rate"]
    p1 = assemble_outputs(rstack, cfg1)
    p3 = assemble_outputs(rstack, cfg3)
    assert set(p1) == set(p3)
    for name in ("rmse", "vertex_years", "model_valid"):
        a, _, _ = read_geotiff(p1[name])
        b, _, _ = read_geotiff(p3[name])
        np.testing.assert_array_equal(a, b)
    with pytest.raises(ValueError, match="write_workers"):
        RunConfig(write_workers=0)


def test_chunk_px_zero_rejected_at_config_time():
    """chunk_px=0 is not the disable spelling (None is): a zero chunk
    would divide-by-zero deep in the chunked kernel mid-run, so the
    config constructor rejects it (and negatives) up front."""
    with pytest.raises(ValueError, match="chunk_px"):
        RunConfig(chunk_px=0)
    with pytest.raises(ValueError, match="chunk_px"):
        RunConfig(chunk_px=-4096)
    assert RunConfig(chunk_px=None).chunk_px is None


def test_parallel_feeders_match_single(tmp_path, rstack):
    """feed_workers=3 (prefetch depth 4) produces the same manifest +
    rasters as the default: feeds are per-tile independent reads, only
    their scheduling changes — and the bounded prefetch queue must still
    consume every tile exactly once, in order."""
    cfg1 = make_cfg(os.path.join(tmp_path, "a"))
    cfg3 = make_cfg(os.path.join(tmp_path, "b"), feed_workers=3)
    s1 = run_stack(rstack, cfg1)
    s3 = run_stack(rstack, cfg3)
    assert s1["pixels"] == s3["pixels"] and s1["fit_rate"] == s3["fit_rate"]
    p1 = assemble_outputs(rstack, cfg1)
    p3 = assemble_outputs(rstack, cfg3)
    for name in ("rmse", "vertex_years", "model_valid"):
        a, _, _ = read_geotiff(p1[name])
        b, _, _ = read_geotiff(p3[name])
        np.testing.assert_array_equal(a, b)
    with pytest.raises(ValueError, match="feed_workers"):
        RunConfig(feed_workers=0)


def test_feed_failure_aborts_run(tmp_path, rstack, monkeypatch):
    """A persistent feed error propagates out of run_stack (not swallowed
    by the executor) and the writer pool shuts down.  Since PR 5 it first
    re-enters the per-tile retry budget and surfaces as the same
    TileRetriesExhausted the device-fault ladder raises, with the
    original feed error chained as the cause."""
    import land_trendr_tpu.runtime.driver as drv

    cfg = make_cfg(tmp_path, feed_workers=2, retry_backoff_s=0.0)

    def bad_feed(stack, t, tile_px, bands):
        raise OSError("stack read failed (injected)")

    monkeypatch.setattr(drv, "_feed_tile", bad_feed)
    with pytest.raises(drv.TileRetriesExhausted, match="failed after") as ei:
        run_stack(rstack, cfg)
    assert "stack read failed" in str(ei.value.__cause__)


def test_writer_failure_fails_fast_parallel(tmp_path, rstack, monkeypatch):
    """With several writer threads, a persistent artifact-write failure
    still aborts within a bounded number of tiles (backpressure collects
    the oldest in-flight job before each new submission)."""
    from land_trendr_tpu.runtime.manifest import TileManifest

    cfg = make_cfg(tmp_path, write_workers=2)

    def bad_record(self, tile_id, arrays, meta, **kw):
        raise OSError("disk full (injected)")

    monkeypatch.setattr(TileManifest, "record", bad_record)
    with pytest.raises(OSError, match="disk full"):
        run_stack(rstack, cfg)


def test_output_overviews(tmp_path, rstack):
    """out_overviews appends ReducedImage pyramid pages to every product
    raster; the reader (and therefore resume/change tooling) still sees
    the full-resolution data."""
    from tests.test_geotiff import _walk_pages

    cfg = make_cfg(tmp_path, out_overviews=1)
    run_stack(rstack, cfg)
    paths = assemble_outputs(rstack, cfg)
    pages = _walk_pages(paths["rmse"])
    assert [p[:2] for p in pages] == [(40, 48), (20, 24)]
    assert [p[2] for p in pages] == [0, 1]
    rmse, _, _ = read_geotiff(paths["rmse"])
    assert rmse.shape == (40, 48)
    with pytest.raises(ValueError, match="out_overviews"):
        RunConfig(out_overviews=-1)


def test_manifest_compress_roundtrip(tmp_path):
    """Both artifact compressions round-trip bit-identically through
    np.load; 'deflate' actually shrinks the file; bad values are rejected
    at RunConfig construction and at record()."""
    rng = np.random.default_rng(3)
    arrays = {
        "a": rng.integers(0, 50, (500, 7)).astype(np.int32),
        "b": rng.normal(size=(500, 6)).astype(np.float32),
        "c": rng.random(500) < 0.5,
    }
    sizes = {}
    for mode in ("none", "deflate"):
        m = TileManifest(os.path.join(tmp_path, mode), "f" * 16)
        m.open(resume=False)
        m.record(7, arrays, {"y0": 0}, compress=mode)
        got = m.load_tile(7)
        assert set(got) == set(arrays)
        for k in arrays:
            np.testing.assert_array_equal(got[k], arrays[k])
        sizes[mode] = os.path.getsize(m.tile_path(7))
    assert sizes["deflate"] < sizes["none"]
    with pytest.raises(ValueError, match="compress"):
        m.record(8, arrays, {}, compress="lzma")
    with pytest.raises(ValueError, match="manifest_compress"):
        RunConfig(manifest_compress="best")


def test_manifest_compress_resume_mixes(tmp_path, rstack):
    """manifest_compress is a pure speed/size trade: a run checkpointed
    with 'deflate' resumes (and assembles) under 'none' — same fingerprint,
    artifacts readable either way."""
    cfg = make_cfg(tmp_path, manifest_compress="deflate")
    two = plan_tiles(40, 48, 32)[:2]
    first = run_stack(rstack, cfg, tiles=two)
    assert first["pixels"] == sum(t.h * t.w for t in two)
    cfg2 = dataclasses.replace(cfg, manifest_compress="none")
    rest = run_stack(rstack, cfg2)
    assert rest["tiles_skipped_resume"] == 2
    paths = assemble_outputs(rstack, cfg2)
    valid, _, _ = read_geotiff(paths["model_valid"])
    assert valid.shape == (40, 48)


def test_float_stack_rejected_loudly(tmp_path):
    """A float-reflectance pre-stacked file must error, not silently cast
    reflectance [-0.2, 1] to int16 zeros."""
    from land_trendr_tpu.io.geotiff import write_geotiff

    d = str(tmp_path / "float_stack")
    os.makedirs(d)
    arr = np.random.default_rng(0).uniform(0, 1, (7, 8, 8)).astype(np.float32)
    write_geotiff(os.path.join(d, "LT_2001.tif"), arr)
    with pytest.raises(ValueError, match="16-bit DNs"):
        load_stack_dir(d)


def test_int32_stack_rejected_loudly(tmp_path):
    """Wide-integer DN exports (int32) must error, not wrap DN 43000 to
    -22536 via a silent int16 cast (code-review r3)."""
    from land_trendr_tpu.io.geotiff import write_geotiff

    d = str(tmp_path / "i32_stack")
    os.makedirs(d)
    arr = np.full((7, 8, 8), 43000, dtype=np.int32)
    write_geotiff(os.path.join(d, "LT_2001.tif"), arr)
    with pytest.raises(ValueError, match="16-bit DNs"):
        load_stack_dir(d)
