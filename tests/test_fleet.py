"""Fleet telemetry plane tests (ISSUE 11).

Pins the publish → aggregate → history → alerts contracts:

* the **publisher** writes atomic per-process snapshots (registry dump +
  host state) whose metrics round-trip losslessly;
* the **aggregate** fold: counters equal per-host sums, gauges follow
  the per-instrument policy table, histogram bucket merge has exact
  parity with observing everything in one registry, a torn snapshot is
  flagged corrupt (never a crash), a host beyond ``newer_than`` is
  listed-but-excluded, pid reuse is superseded by ``generation``, and
  two folds render byte-identical exposition;
* the **history ring**: whole-oldest-segment eviction under a byte
  budget, reopen-after-crash GC adopts a torn live tail (dropping only
  the torn line), and counter rates never go negative across a process
  restart's counter reset;
* the **alert engine**: threshold fire → hold-down → resolve on a
  scripted history, deterministically; absence rules fire on stale
  hosts; the ``alert`` / ``fleet_sample`` events validate and their
  value lints catch a bad state enum and resolved-before-firing;
* **wiring**: a real ``--publish`` run leaves a foldable snapshot and
  ``lt_fleet`` / ``lt top --dir`` render it; a publish-enabled server
  beats its fleet loop, fires a firing → resolved alert on a planted
  stale host, and surfaces it on ``/healthz`` and in the event stream.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import pytest

from land_trendr_tpu.obs import aggregate
from land_trendr_tpu.obs.alerts import (
    ALERT_STATES,
    DEFAULT_RULES,
    AlertEngine,
    AlertRule,
    load_rules,
    parse_rules,
)
from land_trendr_tpu.obs.events import EventLog, validate_events_file
from land_trendr_tpu.obs.history import HistoryRing, counter_rate
from land_trendr_tpu.obs.metrics import MetricsRegistry
from land_trendr_tpu.obs.publish import SNAP_SCHEMA, TelemetryPublisher

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


def _registry(tiles: int = 5, backlog: int = 2, burn: float = 0.1):
    r = MetricsRegistry()
    r.counter("lt_tiles_done_total", "tiles").inc(tiles)
    r.gauge("lt_feed_backlog", "backlog").set(backlog)
    r.gauge("lt_slo_burn_rate", "burn").set(burn)
    return r


def _publish(tmp_path, host: str, registry, **kw) -> TelemetryPublisher:
    pub = TelemetryPublisher(
        str(tmp_path), registry, interval_s=kw.pop("interval_s", 5.0),
        host=host, **kw,
    )
    pub.publish_now()
    return pub


# ---------------------------------------------------------------------------
# publish


def test_publisher_snapshot_shape_and_seq(tmp_path):
    reg = _registry()
    pub = _publish(
        tmp_path, "h1", reg,
        probes=lambda: {"progress": {"phase": "pipeline", "tiles_done": 3}},
    )
    snap = json.loads(Path(pub.path).read_text())
    assert snap["schema"] == SNAP_SCHEMA
    assert snap["host"] == "h1" and snap["pid"] == os.getpid()
    assert snap["seq"] == 1 and snap["generation"] > 0
    assert snap["state"]["progress"]["phase"] == "pipeline"
    names = {m["name"] for m in snap["metrics"]}
    assert {"lt_tiles_done_total", "lt_feed_backlog"} <= names
    pub.publish_now()
    assert json.loads(Path(pub.path).read_text())["seq"] == 2
    # no tmp litter: every write renamed or cleaned
    assert list(Path(tmp_path).glob("*.tmp")) == []


def test_publisher_probe_failure_degrades_not_raises(tmp_path):
    def sick():
        raise RuntimeError("probe died")

    pub = _publish(tmp_path, "h1", _registry(), probes=sick)
    snap = json.loads(Path(pub.path).read_text())
    assert snap["state"] == {}  # degraded, not dead


# ---------------------------------------------------------------------------
# aggregate


def test_fold_counters_sum_gauges_policy(tmp_path):
    _publish(tmp_path, "h1", _registry(tiles=5, backlog=2, burn=0.1))
    _publish(tmp_path, "h2", _registry(tiles=7, backlog=3, burn=0.4))
    view = aggregate.fold_dir(str(tmp_path))
    m = {i["name"]: i for i in view["metrics"]}
    assert m["lt_tiles_done_total"]["value"] == 12  # counters sum
    assert m["lt_feed_backlog"]["value"] == 5  # GAUGE_SUM policy
    assert m["lt_slo_burn_rate"]["value"] == pytest.approx(0.4)  # max
    assert view["counts"] == {
        "snapshots": 2, "folded": 2, "stale": 0, "corrupt": 0, "excluded": 0,
    }


def test_histogram_bucket_merge_parity(tmp_path):
    bounds = (0.1, 1.0, 10.0)
    obs_a, obs_b = [0.05, 0.5, 5.0, 50.0], [0.07, 0.07, 2.0]
    ra, rb, rall = MetricsRegistry(), MetricsRegistry(), MetricsRegistry()
    for r, obs in ((ra, obs_a), (rb, obs_b), (rall, obs_a + obs_b)):
        h = r.histogram("lt_tile_compute_seconds", "h", buckets=bounds)
        for v in obs:
            h.observe(v)
    _publish(tmp_path, "a", ra)
    _publish(tmp_path, "b", rb)
    merged = {
        i["name"]: i
        for i in aggregate.fold_dir(str(tmp_path))["metrics"]
    }["lt_tile_compute_seconds"]
    direct = rall.snapshot()[0]
    assert merged["buckets"] == direct["buckets"]
    assert merged["count"] == direct["count"]
    assert merged["sum"] == pytest.approx(direct["sum"])


def test_histogram_bounds_mismatch_is_flagged_conflict(tmp_path):
    ra, rb = MetricsRegistry(), MetricsRegistry()
    ra.histogram("lt_h", "h", buckets=(1.0, 2.0)).observe(1.5)
    rb.histogram("lt_h", "h", buckets=(5.0, 6.0)).observe(5.5)
    _publish(tmp_path, "a", ra)
    _publish(tmp_path, "b", rb)
    view = aggregate.fold_dir(str(tmp_path))
    assert any("bounds differ" in c for c in view["conflicts"])


def test_torn_snapshot_flagged_corrupt_not_fatal(tmp_path):
    _publish(tmp_path, "ok-host", _registry(tiles=5))
    (tmp_path / "torn-host.99.snap.json").write_text('{"schema": 1, "ho')
    view = aggregate.fold_dir(str(tmp_path))
    assert view["counts"]["corrupt"] == 1
    assert view["counts"]["folded"] == 1
    torn = [h for h in view["hosts"] if h.get("corrupt")]
    assert len(torn) == 1 and torn[0]["excluded"]  # listed, not folded
    m = {i["name"]: i for i in view["metrics"]}
    assert m["lt_tiles_done_total"]["value"] == 5  # the healthy host folds


def test_stale_host_flagged_and_newer_than_excludes(tmp_path):
    _publish(tmp_path, "fresh", _registry(tiles=5))
    old = json.loads(
        Path(_publish(tmp_path, "dead", _registry(tiles=100)).path).read_text()
    )
    old["t_wall"] = time.time() - 3600
    old["host"] = "dead"
    dead = tmp_path / "dead.1.snap.json"
    dead.write_text(json.dumps(old))
    # staleness judges the FRESHER of t_wall and mtime: a genuinely dead
    # host's file has both old
    os.utime(dead, (old["t_wall"], old["t_wall"]))
    os.unlink(aggregate.discover_snapshots(str(tmp_path))[1])  # the live dup
    now = time.time()
    # stale (beyond 3x interval) but still folded: flagged, not dropped
    view = aggregate.fold_dir(str(tmp_path), now=now)
    stale = [h for h in view["hosts"] if h["stale"]]
    assert [h["host"] for h in stale] == ["dead"]
    m = {i["name"]: i for i in view["metrics"]}
    assert m["lt_tiles_done_total"]["value"] == 105
    # beyond newer_than: excluded from the value fold, still LISTED
    view = aggregate.fold_dir(
        str(tmp_path), now=now, newer_than=now - 600
    )
    assert [h["host"] for h in view["hosts"] if h["excluded"]] == ["dead"]
    m = {i["name"]: i for i in view["metrics"]}
    assert m["lt_tiles_done_total"]["value"] == 5


def test_pid_reuse_superseded_by_generation(tmp_path):
    pub = _publish(tmp_path, "h1", _registry(tiles=100))
    old = json.loads(Path(pub.path).read_text())
    # the dead predecessor: same (host, pid), LOWER generation, stamped
    # under a different filename (a reused telemetry dir)
    old["generation"] -= 1
    (tmp_path / "h1.stale-dup.snap.json").write_text(json.dumps(old))
    view = aggregate.fold_dir(str(tmp_path))
    m = {i["name"]: i for i in view["metrics"]}
    assert m["lt_tiles_done_total"]["value"] == 100  # not 200: no double count
    sup = [h for h in view["hosts"] if h.get("superseded")]
    assert len(sup) == 1


def test_fold_byte_stable_across_folds(tmp_path):
    _publish(tmp_path, "h1", _registry(tiles=5))
    _publish(tmp_path, "h2", _registry(tiles=7))
    now = time.time()
    a = aggregate.render_prom(aggregate.fold_dir(str(tmp_path), now=now))
    b = aggregate.render_prom(aggregate.fold_dir(str(tmp_path), now=now))
    assert a == b and "lt_fleet_hosts 2" in a


# ---------------------------------------------------------------------------
# history


def test_history_ring_segment_eviction(tmp_path):
    d = str(tmp_path / "hist")
    sample = {"t": 0.0, "hosts": 1, "stale_hosts": 0, "metrics": {"x": 1.0}}
    seg_bytes = (len(json.dumps(sample, separators=(",", ":"))) + 30) * 4
    ring = HistoryRing(d, budget_bytes=seg_bytes * 2, samples_per_segment=4)
    for i in range(40):
        ring.append({**sample, "t": float(i)})
    ring.close()
    segs = HistoryRing(d).segments()
    assert 1 <= len(segs) <= 3  # whole-oldest-segment eviction kept it bounded
    samples, malformed = HistoryRing(d).read()
    assert malformed == 0
    assert samples[-1]["t"] == 39.0  # the newest survive
    assert len(samples) <= 12


def test_history_reopen_after_crash_adopts_torn_tail(tmp_path):
    d = str(tmp_path / "hist")
    os.makedirs(d)
    # a crashed writer's live segment: two good lines + one torn line
    left = Path(d) / "hist-100-999.open.jsonl"
    left.write_text(
        '{"t": 1.0, "hosts": 1}\n{"t": 2.0, "hosts": 1}\n{"t": 3.0, "ho'
    )
    old = time.time() - 3600
    os.utime(left, (old, old))
    ring = HistoryRing(d)
    assert ring.adopted_segments == 1
    assert ring.dropped_torn_lines == 1
    samples, malformed = ring.read()
    assert [s["t"] for s in samples] == [1.0, 2.0]
    assert malformed == 0  # the torn line was GC'd at adopt, not re-read
    assert not list(Path(d).glob("*.open.jsonl"))
    ring.close()


def test_history_fresh_open_of_live_sibling_left_alone(tmp_path):
    d = str(tmp_path / "hist")
    os.makedirs(d)
    sibling = Path(d) / "hist-200-888.open.jsonl"
    sibling.write_text('{"t": 5.0, "hosts": 1}\n')  # fresh mtime: live
    ring = HistoryRing(d)
    assert ring.adopted_segments == 0
    assert sibling.exists()
    samples, _ = ring.read()
    assert [s["t"] for s in samples] == [5.0]  # still readable as the tail
    ring.close()


def test_counter_rate_reset_never_negative():
    # a process restart resets the counter 100 -> 3: the reset-aware
    # rate counts the post-reset value as growth from zero, never a
    # negative increase
    samples = [
        {"t": 0.0, "metrics": {"c": 90.0}},
        {"t": 10.0, "metrics": {"c": 100.0}},
        {"t": 20.0, "metrics": {"c": 3.0}},
        {"t": 30.0, "metrics": {"c": 9.0}},
    ]
    rate = counter_rate(samples, "c", window_s=100.0, now=30.0)
    assert rate == pytest.approx((10 + 3 + 6) / 30.0)
    assert counter_rate(samples[:1], "c", 100.0, now=0.0) is None
    # monotone decrease everywhere still clamps at zero
    down = [
        {"t": 0.0, "metrics": {"c": 5.0}},
        {"t": 10.0, "metrics": {"c": 0.0}},
    ]
    assert counter_rate(down, "c", 100.0, now=10.0) == 0.0


# ---------------------------------------------------------------------------
# alerts


def test_alert_threshold_fire_holddown_resolve_deterministic():
    rule = AlertRule(
        name="q", kind="threshold", metric="q", op=">", value=10,
        for_s=2.0, hold_down_s=3.0,
    )

    def run() -> list:
        eng = AlertEngine((rule,))
        out = []
        for t in range(20):
            q = 20.0 if 5 <= t < 10 else 0.0
            out += [
                (t, tr["state"], tr["duration_s"])
                for tr in eng.evaluate(
                    [{"t": float(t), "metrics": {"q": q}}], float(t)
                )
            ]
        return out

    a, b = run(), run()
    assert a == b == [(7, "firing", 2.0), (13, "resolved", 6.0)]


def test_alert_transient_below_for_s_never_fires():
    rule = AlertRule(
        name="q", kind="threshold", metric="q", op=">", value=10, for_s=5.0,
    )
    eng = AlertEngine((rule,))
    trs = []
    for t in range(10):
        q = 20.0 if t in (2, 3) else 0.0  # a 2s transient under for_s=5
        trs += eng.evaluate([{"t": float(t), "metrics": {"q": q}}], float(t))
    assert trs == []


def test_alert_absent_rule_fires_on_stale_host_and_dark_plane():
    rule = AlertRule(name="stale", kind="absent", window_s=30.0)
    eng = AlertEngine((rule,))
    trs = eng.evaluate([{"t": 100.0, "hosts": 2, "stale_hosts": 1}], 100.0)
    assert [t["state"] for t in trs] == ["firing"]
    assert eng.active()[0]["rule"] == "stale"
    # a dark plane (no sample in the window at all) keeps it firing
    eng2 = AlertEngine((rule,))
    assert [t["state"] for t in eng2.evaluate([], 100.0)] == ["firing"]


def test_alert_rate_rule_over_history():
    rule = AlertRule(
        name="fail_rate", kind="rate", metric="lt_tiles_failed_total",
        op=">", value=0.5, window_s=100.0,
    )
    eng = AlertEngine((rule,))
    samples = [
        {"t": float(t), "metrics": {"lt_tiles_failed_total": t * 2.0}}
        for t in range(5)
    ]
    trs = eng.evaluate(samples, 4.0)
    assert [t["state"] for t in trs] == ["firing"]  # 2 fails/s > 0.5


def test_rules_parse_validation():
    with pytest.raises(ValueError, match="unknown key"):
        parse_rules([{"name": "x", "metrik": "q"}])
    with pytest.raises(ValueError, match="kind"):
        parse_rules([{"name": "x", "kind": "nope"}])
    with pytest.raises(ValueError, match="duplicate"):
        parse_rules([{"name": "x", "metric": "q"}, {"name": "x", "metric": "q"}])
    assert parse_rules('{"rules": [{"name": "x", "metric": "q"}]}')[0].name == "x"


def test_alert_event_schema_and_value_lints(tmp_path):
    from check_events_schema import ALERT_STATES as LINT_STATES
    from check_events_schema import value_lints

    assert LINT_STATES == ALERT_STATES  # the lint table cannot drift
    path = str(tmp_path / "events.jsonl")
    with EventLog(path) as log:
        log.run_start(
            fingerprint="fleet", process_index=0, process_count=1,
            tiles_total=0, tiles_todo=0, tiles_skipped_resume=0,
            mesh_devices=0, impl="serve",
        )
        log.emit("fleet_sample", hosts=2, stale_hosts=1, corrupt_snaps=0,
                 alerts_firing=1, history_samples=7)
        log.emit("alert", rule="q", state="firing", value=20.0,
                 threshold=10.0, duration_s=2.0, window_s=60.0)
        log.emit("alert", rule="q", state="resolved", value=0.0,
                 threshold=10.0, duration_s=6.0)
    assert validate_events_file(path, extra=value_lints()) == []

    # negative cases: bad enum, resolved-before-firing, double firing,
    # negative duration
    bad = str(tmp_path / "bad.jsonl")
    with EventLog(bad) as log:
        log.run_start(
            fingerprint="fleet", process_index=0, process_count=1,
            tiles_total=0, tiles_todo=0, tiles_skipped_resume=0,
            mesh_devices=0, impl="serve",
        )
        log.emit("alert", rule="a", state="flapping", value=1.0,
                 threshold=1.0, duration_s=1.0)
        log.emit("alert", rule="b", state="resolved", value=0.0,
                 threshold=1.0, duration_s=1.0)
        log.emit("alert", rule="c", state="firing", value=1.0,
                 threshold=1.0, duration_s=1.0)
        log.emit("alert", rule="c", state="firing", value=1.0,
                 threshold=1.0, duration_s=-2.0)
    errs = "\n".join(validate_events_file(bad, extra=value_lints()))
    assert "not one of" in errs
    assert "resolved without a prior firing" in errs
    assert "fired twice" in errs
    assert "duration_s is negative" in errs


# ---------------------------------------------------------------------------
# wiring: driver run, lt_fleet, lt top


@pytest.fixture(scope="module")
def publish_run(tmp_path_factory):
    """One tiny --publish run; returns (summary, workdir)."""
    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
    from land_trendr_tpu.runtime import (
        RunConfig,
        run_stack,
        stack_from_synthetic,
    )

    wd = str(tmp_path_factory.mktemp("fleet_run") / "work")
    stack = stack_from_synthetic(
        make_stack(SceneSpec(width=40, height=20, year_start=2000,
                             year_end=2006, seed=5))
    )
    cfg = RunConfig(
        workdir=wd,
        out_dir=wd + "_o",
        tile_size=20,
        params=LTParams(max_segments=4, vertex_count_overshoot=2),
        telemetry=True,
        publish=True,
        publish_interval_s=60.0,
    )
    return run_stack(stack, cfg), wd


def test_run_publishes_foldable_snapshot(publish_run):
    summary, wd = publish_run
    snap_file = summary["telemetry"]["snapshot"]
    assert os.path.exists(snap_file)
    snap = json.loads(Path(snap_file).read_text())
    assert snap["kind"] == "run"
    # the terminal flush carries the finished run's state
    assert snap["state"]["progress"]["phase"] == "done"
    assert snap["state"]["progress"]["tiles_done"] == 2
    view = aggregate.fold_dir(os.path.join(wd, "telemetry"))
    m = {i["name"]: i for i in view["metrics"]}
    assert m["lt_tiles_done_total"]["value"] == 2


def test_publish_config_validation():
    from land_trendr_tpu.runtime import RunConfig

    with pytest.raises(ValueError, match="publish requires telemetry"):
        RunConfig(publish=True)
    with pytest.raises(ValueError, match="telemetry_dir requires publish"):
        RunConfig(telemetry_dir="/tmp/t")
    with pytest.raises(ValueError, match="publish_interval_s"):
        RunConfig(telemetry=True, publish=True, publish_interval_s=0)


def test_lt_fleet_report_and_prom(publish_run, tmp_path, capsys):
    _, wd = publish_run
    import lt_fleet

    tel = os.path.join(wd, "telemetry")
    assert lt_fleet.main([tel]) == 0
    out = capsys.readouterr().out
    assert "lt fleet — 1 host(s) folded" in out
    assert "alerts: none firing" in out
    prom = str(tmp_path / "pod.prom")
    assert lt_fleet.main([tel, "--prom", prom, "--json"]) == 0
    text = Path(prom).read_text()
    assert "lt_fleet_hosts 1" in text
    assert "lt_tiles_done_total 2" in text
    view = json.loads(capsys.readouterr().out)
    assert view["counts"]["folded"] == 1
    # an empty dir is a clean exit 2, not a traceback
    assert lt_fleet.main([str(tmp_path / "empty_nonexistent")]) == 2


def test_lt_top_dir_mode(publish_run, capsys):
    _, wd = publish_run
    import lt_top

    assert lt_top.main(["--dir", os.path.join(wd, "telemetry"), "--once"]) == 0
    out = capsys.readouterr().out
    assert "lt fleet — 1 host(s) folded" in out
    # target modes are mutually exclusive and required
    assert lt_top.main(["--once"]) == 2
    assert lt_top.main(["--dir", "x", "--port", "1", "--once"]) == 2


def test_lt_top_prom_instruments_merge_policy():
    """The multi-url aggregate header shares obs.aggregate's merge
    policy: counters sum, burn-rate gauges take the max, and histogram
    families RECONSTRUCT from their cumulative ``_bucket``/``_sum``/
    ``_count`` rows into mergeable instruments (the aggregate header's
    percentile source)."""
    import lt_top

    text = (
        "# TYPE lt_slo_met_total counter\n"
        "lt_slo_met_total 3\n"
        "# TYPE lt_slo_burn_rate gauge\n"
        "lt_slo_burn_rate 0.25\n"
        "# TYPE lt_serve_job_seconds histogram\n"
        'lt_serve_job_seconds_bucket{le="1"} 2\n'
        'lt_serve_job_seconds_bucket{le="+Inf"} 2\n'
        "lt_serve_job_seconds_sum 1.5\n"
        "lt_serve_job_seconds_count 2\n"
    )
    text2 = text.replace("0.25", "0.75").replace("lt_slo_met_total 3",
                                                 "lt_slo_met_total 4")
    merged, conflicts = aggregate.merge_instruments([
        (0.0, lt_top.prom_instruments(text)),
        (1.0, lt_top.prom_instruments(text2)),
    ])
    assert conflicts == []
    by = {m["name"]: m for m in merged}
    assert by["lt_slo_met_total"]["value"] == 7
    assert by["lt_slo_burn_rate"]["value"] == 0.75
    hist = by["lt_serve_job_seconds"]
    assert hist["kind"] == "histogram"
    assert hist["sum"] == 3.0 and hist["count"] == 4
    assert hist["bounds"] == [1.0] and hist["buckets"] == [4, 0]
    # the scalar siblings fold INTO the histogram, not beside it
    assert "lt_serve_job_seconds_sum" not in by
    assert "lt_serve_job_seconds_bucket" not in by


# ---------------------------------------------------------------------------
# wiring: serve fleet loop


def test_serve_fleet_loop_alert_lifecycle(tmp_path):
    """A publish-enabled server: the fleet loop publishes + folds +
    appends history; a planted stale foreign snapshot fires the default
    host-staleness alert (event stream + /healthz + lt_alerts_*), and
    removing it resolves the alert through the hold-down — the
    firing → resolved lifecycle over a REAL server."""
    import urllib.request

    from land_trendr_tpu.serve import SegmentationServer, ServeConfig

    wd = str(tmp_path / "srv")
    cfg = ServeConfig(
        workdir=wd,
        publish=True,
        publish_interval_s=0.1,
        flight_ring_events=0,
        alert_rules=None,  # the built-in defaults
    )
    server = SegmentationServer(cfg)
    tel_dir = os.path.join(wd, "telemetry")
    try:
        # beat 1+: own snapshot folds, no alerts
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if glob_count(tel_dir) >= 1 and server.telemetry.history is not None:
                samples, _ = server.telemetry.history.read()
                if samples:
                    break
            time.sleep(0.05)
        assert glob_count(tel_dir) >= 1
        # plant a STALE foreign snapshot: 120s old — past its own
        # staleness bound (3 x 5s interval) but inside the serve loop's
        # newer_than window, so it reads stale (alertable) rather than
        # departed (excluded); the absent rule must fire
        stale = {
            "schema": SNAP_SCHEMA, "kind": "run", "host": "ghost",
            "pid": 1, "generation": 1, "seq": 1,
            "t_wall": time.time() - 120, "uptime_s": 1.0,
            "interval_s": 5.0, "metrics": [], "state": {},
        }
        ghost = Path(tel_dir) / "ghost.1.snap.json"
        ghost.write_text(json.dumps(stale))
        # both clocks old: staleness judges the fresher of t_wall/mtime
        os.utime(ghost, (stale["t_wall"], stale["t_wall"]))
        deadline = time.monotonic() + 30
        fired = False
        while time.monotonic() < deadline and not fired:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/healthz", timeout=10
            ) as r:
                h = json.loads(r.read())
            fired = any(
                a["rule"] == "fleet_host_stale" for a in h.get("alerts", [])
            )
            time.sleep(0.05)
        assert fired, "planted stale host never fired the staleness alert"
        assert h["fleet"]["stale"] >= 1
        # remove the ghost: the alert must resolve through the hold-down
        ghost.unlink()
        deadline = time.monotonic() + 60
        resolved = False
        while time.monotonic() < deadline and not resolved:
            resolved = not server.telemetry.active_alerts()
            time.sleep(0.05)
        assert resolved, "alert never resolved after the stale host left"
    finally:
        server.stop()
        server.serve_forever()  # drains nothing; runs the shared shutdown
    # the event stream carries the firing → resolved pair, schema-clean
    from check_events_schema import value_lints

    events_file = os.path.join(wd, "events.jsonl")
    assert validate_events_file(events_file, extra=value_lints()) == []
    states = [
        json.loads(line)["state"]
        for line in Path(events_file).read_text().splitlines()
        if line.strip() and json.loads(line).get("ev") == "alert"
        and json.loads(line).get("rule") == "fleet_host_stale"
    ]
    assert states[:2] == ["firing", "resolved"]
    # metrics advanced
    prom = Path(wd, "metrics.prom").read_text()
    assert "lt_alerts_fired_total 1" in prom
    assert "lt_alerts_resolved_total 1" in prom


def glob_count(d: str) -> int:
    return len(aggregate.discover_snapshots(d))


def test_serve_publish_config_validation():
    from land_trendr_tpu.serve import ServeConfig

    with pytest.raises(ValueError, match="publish requires telemetry"):
        ServeConfig(publish=True, telemetry=False)
    with pytest.raises(ValueError, match="alert_rules requires publish"):
        ServeConfig(alert_rules="/nonexistent/rules.json")
    with pytest.raises(ValueError, match="unreadable"):
        ServeConfig(publish=True, alert_rules="/nonexistent/rules.json")


def test_load_rules_file_and_defaults(tmp_path):
    p = tmp_path / "rules.json"
    p.write_text(json.dumps([
        {"name": "deep_queue", "kind": "threshold",
         "metric": "lt_serve_queue_depth", "op": ">=", "value": 10,
         "for_s": 5, "hold_down_s": 10},
    ]))
    rules = load_rules(str(p))
    assert rules[0].name == "deep_queue" and rules[0].value == 10
    assert {r.kind for r in DEFAULT_RULES} == {"absent", "slo_burn"}


def test_perf_gate_fleet_leg(tmp_path):
    """The gate's fleet leg passes against the live implementation —
    the acceptance invariant (sums exact, staleness flagged, alerts
    deterministic, folds byte-stable) wired into tier-1."""
    import perf_gate

    checks: list = []

    def check(name, ok, detail):
        checks.append({"check": name, "ok": bool(ok), "detail": detail})

    perf_gate.run_fleet_leg(str(tmp_path), check)
    failed = [c for c in checks if not c["ok"]]
    assert not failed, failed
    assert len(checks) == 8
