"""Cross-job continuous batching: packing changes launches, never bytes.

The contract under test (``land_trendr_tpu/serve/batching.py`` plus the
server's dispatcher hooks):

* a flood of same-affinity jobs coalesces behind shared launches and
  every job's artifacts stay **byte-identical** to one-run-per-job
  execution;
* mixed-affinity jobs never co-batch, and a non-matching job at the
  queue front closes the window EARLY — batching changes packing,
  never the fairness order;
* a single-job fleet keeps today's path (no batch events at all);
* a member cancelled while queued drops out of the batch without
  harming its batch-mates;
* the ``batch_launch`` value lints catch impossible packings.

The fault seams (``batch.pack`` / ``batch.demux``) and SIGKILL
mid-batch recovery are ``tools/fault_soak.py``'s cases; the speedup
claim is ``tools/batch_bench.py`` + the perf gate's banded leg.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack
from land_trendr_tpu.serve import SegmentationServer, ServeConfig
from land_trendr_tpu.serve.batching import resolve_batch

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

#: one scene shape for the whole module: identical program-cache keys
#: across tests keep every server after the first warm
_PARAMS = {"max_segments": 4, "vertex_count_overshoot": 2}
_TILE = 20


@pytest.fixture(scope="module")
def stack_dir(tmp_path_factory) -> str:
    d = str(tmp_path_factory.mktemp("batch_stack") / "stack")
    write_stack(
        d,
        make_stack(
            SceneSpec(width=40, height=40, year_start=2000, year_end=2008,
                      seed=3)
        ),
    )
    return d


@pytest.fixture(scope="module")
def reference(stack_dir, tmp_path_factory) -> dict:
    """One batch=False run of the canonical job: the one-run-per-job
    artifact digests every batched job must reproduce byte-for-byte."""
    srv_dir = str(tmp_path_factory.mktemp("batch_ref") / "srv")
    server = SegmentationServer(
        ServeConfig(workdir=srv_dir, max_jobs=1, feed_cache_mb=32,
                    batch=False)
    )
    snap = server.submit(_job(stack_dir))
    server.serve_forever()
    snap = server.job_status(snap["job_id"])
    assert snap["state"] == "done"
    ref = _digest_workdir(snap["workdir"])
    assert ref, "reference run produced no artifacts"
    return ref


def _digest_workdir(workdir: str) -> dict:
    out: dict = {}
    for p in sorted(Path(workdir).glob("tile_*.npz")):
        with np.load(p) as z:
            out[p.name] = {
                name: hashlib.sha256(
                    np.ascontiguousarray(z[name]).tobytes()
                ).hexdigest()
                for name in sorted(z.files)
            }
    return out


def _job(stack_dir: str, **kw) -> dict:
    return {
        "stack_dir": stack_dir,
        "tile_size": _TILE,
        "params": dict(_PARAMS),
        **kw,
    }


def _batch_events(srv_dir: str) -> tuple[list, list]:
    launches, demuxes = [], []
    with open(Path(srv_dir) / "events.jsonl") as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("ev") == "batch_launch":
                launches.append(rec)
            elif rec.get("ev") == "batch_demux":
                demuxes.append(rec)
    return launches, demuxes


# ---------------------------------------------------------------------------
# knob resolution


def test_resolve_batch_explicit_wins_and_auto_defaults_on(tmp_path):
    assert resolve_batch(True) is True
    assert resolve_batch(False) is False
    # no store, no profile: batching is byte-identical packing, so
    # "auto" defaults ON
    assert resolve_batch("auto") is True
    assert resolve_batch("auto", tune_store_dir=str(tmp_path),
                         scene_shape=(40, 40, 9)) is True
    with pytest.raises(ValueError, match="batch"):
        resolve_batch("yes")


def test_resolve_batch_auto_consults_tuning_store(tmp_path):
    """A stored profile carrying a ``batch`` knob pins the verdict for
    its (device, backend, shape class) — the PR-14 autotuner contract."""
    from land_trendr_tpu.tune.autotune import device_identity
    from land_trendr_tpu.tune.store import (
        TUNE_SCHEMA,
        TuningStore,
        shape_class,
    )

    device_kind, backend = device_identity()
    store = TuningStore(str(tmp_path))
    store.save({
        "schema": TUNE_SCHEMA,
        "device_kind": device_kind,
        "backend": backend,
        "shape_class": shape_class(40, 40, 9),
        "knobs": {"batch": False},
        "created_t": time.time(),
    })
    assert resolve_batch("auto", tune_store_dir=str(tmp_path),
                         scene_shape=(40, 40, 9)) is False
    # a DIFFERENT shape class misses the profile and keeps the default
    assert resolve_batch("auto", tune_store_dir=str(tmp_path),
                         scene_shape=(4000, 4000, 9)) is True
    # the explicit knob never consults the store
    assert resolve_batch(False, tune_store_dir=str(tmp_path),
                         scene_shape=(40, 40, 9)) is False


# ---------------------------------------------------------------------------
# the headline contract: coalesced launches, byte-identical artifacts


def test_flood_coalesces_and_matches_one_run_per_job(
    stack_dir, reference, tmp_path
):
    srv_dir = str(tmp_path / "srv")
    server = SegmentationServer(
        ServeConfig(workdir=srv_dir, max_jobs=3, feed_cache_mb=32,
                    batch=True, batch_window_ms=200.0)
    )
    # all three queued BEFORE the dispatcher starts: the leader's
    # window sees the whole flood
    snaps = [server.submit(_job(stack_dir)) for _ in range(3)]
    server.serve_forever()

    for snap in snaps:
        s = server.job_status(snap["job_id"])
        assert s["state"] == "done", s.get("error")
        assert _digest_workdir(s["workdir"]) == reference

    launches, demuxes = _batch_events(srv_dir)
    # ONE launch packs the leader plus both queued members (its
    # identity is the LEADER's); the fully-demuxed members then resume
    # solo — no window held, no re-pack, no further batch events
    assert len(launches) == 1
    assert launches[0]["jobs"] == 3
    assert launches[0]["tiles"] == 3 * len(reference)
    assert 0 < launches[0]["occupancy"] <= 1
    assert launches[0]["job_id"] == snaps[0]["job_id"]
    # each member got one batch_demux carrying its demuxed tile count
    assert sum(d["tiles"] for d in demuxes) == 2 * len(reference)
    member_ids = {d["job_id"] for d in demuxes}
    assert member_ids == {snaps[1]["job_id"], snaps[2]["job_id"]}

    # the event stream is schema- and value-lint clean (batch lints
    # included via check_events_schema.value_lints)
    from check_events_schema import main as lint_main

    assert lint_main([srv_dir]) == 0


def test_single_job_fleet_keeps_stock_path(stack_dir, reference, tmp_path):
    srv_dir = str(tmp_path / "srv")
    server = SegmentationServer(
        ServeConfig(workdir=srv_dir, max_jobs=1, feed_cache_mb=32,
                    batch=True, batch_window_ms=200.0)
    )
    snap = server.submit(_job(stack_dir))
    server.serve_forever()
    s = server.job_status(snap["job_id"])
    assert s["state"] == "done"
    assert _digest_workdir(s["workdir"]) == reference
    launches, demuxes = _batch_events(srv_dir)
    assert launches == [] and demuxes == [], (
        "a solo job must not pay (or log) any batch machinery"
    )


def test_mixed_affinity_never_co_batches_and_keeps_order(
    stack_dir, reference, tmp_path
):
    """A non-matching job at the queue front closes the window early:
    nothing co-batches across affinity keys, and completion follows the
    fairness order exactly as if batching did not exist."""
    srv_dir = str(tmp_path / "srv")
    server = SegmentationServer(
        ServeConfig(workdir=srv_dir, max_jobs=3, feed_cache_mb=32,
                    batch=True, batch_window_ms=200.0)
    )
    a = server.submit(_job(stack_dir))
    b = server.submit(_job(stack_dir, tile_size=10))  # different affinity
    c = server.submit(_job(stack_dir))
    server.serve_forever()

    sa, sb, sc = (
        server.job_status(s["job_id"]) for s in (a, b, c)
    )
    assert sa["state"] == sb["state"] == sc["state"] == "done"
    launches, demuxes = _batch_events(srv_dir)
    assert launches == [] and demuxes == [], (
        "jobs with different affinity keys must never share a launch"
    )
    # fairness preserved: a < b < c by completion, the submit order
    assert sa["finished_t"] <= sb["finished_t"] <= sc["finished_t"]
    assert _digest_workdir(sa["workdir"]) == reference
    assert _digest_workdir(sc["workdir"]) == reference


def test_cancelled_member_drops_out_without_harming_batch_mates(
    stack_dir, reference, tmp_path
):
    srv_dir = str(tmp_path / "srv")
    server = SegmentationServer(
        ServeConfig(workdir=srv_dir, max_jobs=3, feed_cache_mb=32,
                    batch=True, batch_window_ms=200.0)
    )
    snaps = [server.submit(_job(stack_dir)) for _ in range(3)]
    # the middle job leaves the queue before the dispatcher starts
    cancelled = server.cancel(snaps[1]["job_id"])
    assert cancelled["state"] == "cancelled"
    server.serve_forever()

    s0 = server.job_status(snaps[0]["job_id"])
    s2 = server.job_status(snaps[2]["job_id"])
    assert s0["state"] == s2["state"] == "done"
    assert server.job_status(snaps[1]["job_id"])["state"] == "cancelled"
    assert _digest_workdir(s0["workdir"]) == reference
    assert _digest_workdir(s2["workdir"]) == reference
    launches, demuxes = _batch_events(srv_dir)
    # the survivors still coalesce — just without the cancelled member
    assert launches and launches[0]["jobs"] == 2
    assert {d["job_id"] for d in demuxes} == {snaps[2]["job_id"]}


# ---------------------------------------------------------------------------
# value lints: impossible packings are schema errors, not silent data


def test_batch_launch_value_lints():
    from check_events_schema import batch_value_errors

    good = {"ev": "batch_launch", "jobs": 3, "tiles": 12,
            "occupancy": 0.87}
    assert batch_value_errors(good, 1) == []
    assert batch_value_errors({"ev": "job_done"}, 1) == []

    assert batch_value_errors(
        {"ev": "batch_launch", "jobs": 0, "tiles": 0, "occupancy": 0.5}, 1
    ), "jobs < 1 must lint (a launch coalesces at least its leader)"
    assert batch_value_errors(
        {"ev": "batch_launch", "jobs": 3, "tiles": 2, "occupancy": 0.5}, 1
    ), "tiles < jobs must lint (every job brings at least one tile)"
    for occ in (0, 1.5, -0.1):
        assert batch_value_errors(
            {"ev": "batch_launch", "jobs": 2, "tiles": 8, "occupancy": occ},
            1,
        ), f"occupancy {occ} must lint (not a fraction of the batch)"
