"""Pallas family-kernel equivalence vs the XLA reference kernel.

The Pallas path (:mod:`land_trendr_tpu.ops.segment_pallas`) must be
decision- and value-identical to the XLA kernel, which is itself
parity-tested against the oracle (tests/test_parity.py).  Mosaic only
compiles on TPU, so these tests drive ``interpret=True`` — the same trace
executed with stock JAX ops, dtype-generic — which is exactly the mode the
f64 contract relies on.  Real-hardware evidence for the compiled kernel
lives in the committed artifacts: ``PARITY_f32_tpu_pallas.json`` (99.987%
exact vertex agreement vs the f64 oracle at 1M px, identical to the XLA
kernel's artifact), ``IMPL_IDENTITY_r04.json`` (the two kernels are
bit-identical pixel-for-pixel on the chip at 1M px), and BENCH_r04.json
(the Pallas path's north-star number).
"""

import jax
import numpy as np
import pytest

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.ops.segment import jax_segment_pixels
from land_trendr_tpu.ops.segment_pallas import (
    family_stats_pallas,
    jax_segment_pixels_pallas,
    jax_segment_pixels_pallas_chunked,
)

from tools._population import make_population

NY = 40
PARAMS = LTParams()


def _population(px, seed=0):
    rng = np.random.default_rng(seed)
    years, vals, mask = make_population(rng, px, NY)
    return years.astype(np.float64), vals.astype(np.float64), mask


def _assert_outputs_equal(out_a, out_b, *, exact=True):
    """Exact on every field except ``p_of_f``, which gets rtol 1e-12.

    ``p_of_f`` is the one output whose primitive — XLA's betainc expansion
    — is not bit-stable across fusion contexts (its last-ulp rounding
    tracks the surrounding program; measured ~3e-14 rel between the fused
    in-kernel evaluation and the former standalone tail on identical
    inputs).  The oracle-parity suite itself compares p_of_f at atol 1e-9
    (``test_parity.py`` — the oracle's scipy betainc never matched XLA's
    bitwise), so 1e-12 here is strictly tighter than the contract the XLA
    kernel is held to.  Every DECISION derived from p (model choice,
    model_valid, vertices) still must match bit-for-bit via the other
    fields.
    """
    for f in out_a._fields:
        a, b = np.asarray(getattr(out_a, f)), np.asarray(getattr(out_b, f))
        if exact and f == "p_of_f":
            np.testing.assert_allclose(a, b, rtol=1e-12, atol=0, err_msg=f)
        elif exact:
            np.testing.assert_array_equal(a, b, err_msg=f)
        else:
            np.testing.assert_allclose(a, b, rtol=0, atol=1e-6, err_msg=f)


def test_f64_interpret_bit_exact_vs_xla_kernel():
    """Every output field bit-identical to the XLA kernel in f64."""
    years, vals, mask = _population(512, seed=1)
    out_x = jax_segment_pixels(years, vals, mask, PARAMS)
    out_p = jax_segment_pixels_pallas(
        years, vals, mask, PARAMS, block=256, interpret=True
    )
    _assert_outputs_equal(out_x, out_p, exact=True)


def test_f64_interpret_bit_exact_masked_edge_cases():
    """All-masked, single-valid, and min-obs-boundary pixels included."""
    years, vals, mask = _population(256, seed=2)
    mask = mask.copy()
    mask[0] = False                      # all-invalid pixel
    mask[1] = False
    mask[1, 7] = True                    # single valid year
    mask[2] = False
    mask[2, : PARAMS.min_observations_needed] = True  # exactly min-obs
    vals = vals.copy()
    vals[3, 5] = np.nan                  # non-finite input -> masked
    out_x = jax_segment_pixels(years, vals, mask, PARAMS)
    out_p = jax_segment_pixels_pallas(
        years, vals, mask, PARAMS, block=256, interpret=True
    )
    _assert_outputs_equal(out_x, out_p, exact=True)


def test_f64_interpret_param_variants():
    """Despike-off and no-one-year-recovery parameter branches."""
    years, vals, mask = _population(256, seed=3)
    for params in (
        LTParams(spike_threshold=1.0),
        LTParams(prevent_one_year_recovery=False),
        LTParams(max_segments=4),
    ):
        out_x = jax_segment_pixels(years, vals, mask, params)
        out_p = jax_segment_pixels_pallas(
            years, vals, mask, params, block=256, interpret=True
        )
        _assert_outputs_equal(out_x, out_p, exact=True)


def test_chunked_matches_unchunked_interpret():
    years, vals, mask = _population(512, seed=4)
    out_a = jax_segment_pixels_pallas(
        years, vals, mask, PARAMS, block=256, interpret=True
    )
    out_b = jax_segment_pixels_pallas_chunked(
        years, vals, mask, PARAMS, chunk=256, block=256, interpret=True
    )
    for f in out_a._fields:
        a, b = np.asarray(getattr(out_a, f)), np.asarray(getattr(out_b, f))
        # decisions must be identical; floats may re-fuse across lax.map
        if a.dtype.kind in "bi":
            np.testing.assert_array_equal(a, b, err_msg=f)
        else:
            np.testing.assert_allclose(a, b, rtol=1e-12, atol=1e-12, err_msg=f)


def test_block_clamps_to_small_batch():
    years, vals, mask = _population(128, seed=5)
    out_x = jax_segment_pixels(years, vals, mask, PARAMS)
    out_p = jax_segment_pixels_pallas(
        years, vals, mask, PARAMS, block=1024, interpret=True
    )
    _assert_outputs_equal(out_x, out_p, exact=True)


def test_family_stats_shapes_and_despiked():
    years, vals, mask = _population(256, seed=6)
    despiked, vmasks, sses = family_stats_pallas(
        years, vals, mask, PARAMS, block=256, interpret=True
    )
    nm = PARAMS.max_segments
    assert despiked.shape == (256, NY)
    assert vmasks.shape == (256, nm, NY) and vmasks.dtype == np.bool_
    assert sses.shape == (256, nm)
    assert np.isfinite(np.asarray(sses)).all()
    # family is a pruning chain: vertex counts strictly ordered (until floor)
    counts = np.asarray(vmasks).sum(axis=2)
    assert (np.diff(counts, axis=1) <= 0).all()


def test_compiled_under_x64_fails_loud():
    """The Mosaic x64 lowering bug is guarded with a clear error."""
    years, vals, mask = _population(128, seed=7)
    with pytest.raises((RuntimeError, Exception), match="x64|enable_x64"):
        jax_segment_pixels_pallas(
            years.astype(np.float32),
            vals.astype(np.float32),
            mask,
            PARAMS,
            interpret=False,
        )


def test_f32_interpret_decision_quality():
    """f32 Pallas decisions track the f64 XLA kernel (small-batch gate)."""
    years, vals, mask = _population(1024, seed=8)
    out64 = jax_segment_pixels(years, vals, mask, PARAMS)
    with jax.enable_x64(False):
        out32 = jax_segment_pixels_pallas(
            years.astype(np.float32),
            vals.astype(np.float32),
            mask,
            PARAMS,
            block=256,
            interpret=True,
        )
    vi64 = np.asarray(out64.vertex_indices)
    vi32 = np.asarray(out32.vertex_indices)
    agree = np.mean(np.all(vi64 == vi32, axis=1))
    assert agree >= 0.995, f"pixel-exact agreement {agree:.4f}"


# ---------------------------------------------------------------------------
# Primitive unit tests: the year-axis building blocks vs NumPy references
# ---------------------------------------------------------------------------


def _np_fill(vals, valid, *, exclusive, reverse):
    """Reference nearest-valid fill, O(NY^2) scalar NumPy."""
    ny, blk = vals.shape
    out = np.zeros_like(vals)
    has = np.zeros((ny, blk), bool)
    rng_i = range(ny)
    for b in range(blk):
        for i in rng_i:
            idxs = range(i - 1, -1, -1) if not reverse else range(i + 1, ny)
            if not exclusive:
                idxs = [i] + list(idxs)
            for j in idxs:
                if valid[j, b]:
                    out[i, b] = vals[j, b]
                    has[i, b] = True
                    break
    return out, has


def test_fill_primitives_match_reference():
    from land_trendr_tpu.ops import segment_pallas as SP

    rng = np.random.default_rng(0)
    ny, blk = 13, 8
    vals = rng.standard_normal((ny, blk)).astype(np.float32)
    valid = (rng.random((ny, blk)) > 0.4).astype(np.float32)
    for exclusive in (False, True):
        for reverse in (False, True):
            got_v, got_h = SP._fill(
                vals, valid, exclusive=exclusive, reverse=reverse
            )
            ref_v, ref_h = _np_fill(
                vals, valid > 0, exclusive=exclusive, reverse=reverse
            )
            np.testing.assert_array_equal(np.asarray(got_h) > 0, ref_h)
            np.testing.assert_array_equal(
                np.asarray(got_v), np.where(ref_h, ref_v, 0.0)
            )
            a2, b2, h2 = SP._fill2(
                vals, vals * 2, valid, exclusive=exclusive, reverse=reverse
            )
            np.testing.assert_array_equal(np.asarray(a2), np.asarray(got_v))
            np.testing.assert_array_equal(np.asarray(b2), np.asarray(got_v) * 2)


def test_prefix_primitives_match_numpy():
    from land_trendr_tpu.ops import segment_pallas as SP

    rng = np.random.default_rng(1)
    a = rng.integers(0, 2, (17, 6)).astype(np.int32)
    np.testing.assert_array_equal(
        np.asarray(SP._prefix_sum_incl(a)), np.cumsum(a, axis=0)
    )
    b = rng.integers(-1, 17, (17, 6)).astype(np.int32)
    np.testing.assert_array_equal(
        np.asarray(SP._prefix_max_incl(b)), np.maximum.accumulate(b, axis=0)
    )


def test_atan_poly_accuracy():
    """Compiled-mode arctan substitute stays within its measured 2e-7 bound."""
    from land_trendr_tpu.ops.segment_pallas import _atan_poly

    x = np.concatenate([
        np.linspace(-50.0, 50.0, 200_001),
        np.linspace(-1.5, 1.5, 200_001),
        np.array([0.0, 1.0, -1.0, 1e-20, -1e-20, 1e20, -1e20]),
    ]).astype(np.float32)
    got = np.asarray(_atan_poly(x))
    ref = np.arctan(x.astype(np.float64))
    err = np.abs(got.astype(np.float64) - ref)
    # measured max 1.51e-7 at |x|~1.8 (the reciprocal-reduction branch adds
    # one rounding step to the [0,1] poly's 1.0e-7); ~2 ulp at atan scale
    assert err.max() < 2.0e-7, err.max()


def test_f64_interpret_more_param_variants():
    years, vals, mask = _population(256, seed=9)
    for params in (
        LTParams(vertex_count_overshoot=5),
        LTParams(recovery_threshold=0.9),
        LTParams(p_val_threshold=0.01, best_model_proportion=0.5),
        LTParams(min_observations_needed=20),
    ):
        out_x = jax_segment_pixels(years, vals, mask, params)
        out_p = jax_segment_pixels_pallas(
            years, vals, mask, params, block=256, interpret=True
        )
        _assert_outputs_equal(out_x, out_p, exact=True)


def test_f64_interpret_ny_variants():
    """Year-axis generality: NY not a multiple of the sublane tile (8) or
    the historic 40.  DECISION fields must stay bit-exact; float outputs
    get a few-ulp budget — at NY with SIMD remainder tiles (observed at
    12: 2/512 vertex_fit values off by 1 ulp) XLA's reduction codegen
    differs between the two programs' layouts, the same fusion-context
    class as the p_of_f/betainc note on ``_assert_outputs_equal``.  The
    NY=40 suite keeps the full bit-exact gate.  Compiled-on-chip
    identity was separately verified this round at NY=12/25/61
    (vertex-identical 1.0, fitted maxdelta 0.0 — the compiled Mosaic
    paths DO agree; the ulp wiggle is CPU-interpret-vs-XLA codegen)."""
    for ny, params in [
        (12, LTParams(max_segments=3, vertex_count_overshoot=2)),
        (25, PARAMS),
        (61, PARAMS),
    ]:
        rng = np.random.default_rng(ny)
        years, vals, mask = make_population(rng, 128, ny)
        years = years.astype(np.float64)
        vals = vals.astype(np.float64)
        out_x = jax_segment_pixels(years, vals, mask, params)
        out_p = jax_segment_pixels_pallas(
            years, vals, mask, params, block=128, interpret=True
        )
        for f in out_x._fields:
            a = np.asarray(getattr(out_x, f))
            b = np.asarray(getattr(out_p, f))
            if a.dtype.kind in "bi":
                np.testing.assert_array_equal(a, b, err_msg=f"ny={ny} {f}")
            else:
                np.testing.assert_allclose(
                    b, a, rtol=1e-12, atol=0, err_msg=f"ny={ny} {f}"
                )
