"""Float32 quality gate: the f32 kernel must be *statistically equivalent*
to the f64 oracle even where exact vertex placement differs (the documented
f32 tolerance contract in ``ops/segment.py``)."""

import numpy as np

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.models.oracle import segment_series
from land_trendr_tpu.ops.segment import jax_segment_pixels

YEARS = np.arange(1984, 2022, dtype=np.float64)
NY = len(YEARS)


def test_f32_statistical_equivalence(rng):
    n_px = 256
    base = np.where(
        YEARS < 1996, 0.15, np.maximum(0.85 - 0.03 * (YEARS - 1996), 0.15)
    )
    vals = base[None, :] + rng.normal(0, 0.02, (n_px, NY))
    mask = rng.random((n_px, NY)) > 0.1
    params = LTParams()

    out = jax_segment_pixels(
        YEARS.astype(np.float32), vals.astype(np.float32), mask, params
    )
    rmse32 = np.asarray(out.rmse)
    valid32 = np.asarray(out.model_valid)

    d_rmse = []
    valid_flips = 0
    for i in range(n_px):
        ref = segment_series(YEARS, vals[i], mask[i], params)
        valid_flips += ref.model_valid != valid32[i]
        if ref.model_valid and valid32[i]:
            d_rmse.append(rmse32[i] - ref.rmse)
    d_rmse = np.asarray(d_rmse)

    # model_valid decisions agree except for rare knife-edge pixels
    assert valid_flips <= max(2, n_px // 50)
    # rmse distribution equivalent: no systematic bias, tight spread
    assert abs(np.mean(d_rmse)) < 0.02
    assert np.quantile(np.abs(d_rmse), 0.95) < 0.1
    # the f32 fits are never catastrophically worse
    assert np.max(d_rmse) < 0.25


def _mixed_population(rng, px, ny=40):
    """Small-scale version of tools/parity_f32.py::make_population."""
    years = np.arange(1984, 1984 + ny, dtype=np.int32)
    t = np.arange(ny, dtype=np.float64)[None, :]
    kind = rng.integers(0, 5, size=(px, 1))
    base = rng.uniform(0.45, 0.75, size=(px, 1))
    d_year = rng.integers(4, ny - 4, size=(px, 1))
    mag = rng.uniform(0.1, 0.5, size=(px, 1))
    rec = rng.uniform(0.02, 0.15, size=(px, 1))
    dt = np.maximum(t - d_year, 0.0)
    disturbance = np.where(t >= d_year, mag * np.exp(-rec * dt), 0.0)
    step = np.where(t >= d_year, mag, 0.0)
    trend = rng.uniform(-0.01, 0.01, size=(px, 1)) * t
    walk = np.cumsum(rng.normal(0, 0.03, size=(px, ny)), axis=1)
    traj = base - np.where(
        kind == 0, disturbance,
        np.where(kind == 1, step,
                 np.where(kind == 2, trend,
                          np.where(kind == 3, walk * 0.2, 0.0))),
    )
    traj += rng.normal(0.0, 0.012, size=(px, ny))
    mask = rng.uniform(size=(px, ny)) > 0.08
    return years, -traj, mask


def test_f32_exact_vertex_agreement_floor(rng):
    """Gate on the measured f32-vs-f64 exact-vertex agreement rate
    (PARITY_f32.json artifact: 99.997% over 1M pixels with the log-space
    model-selection score; floor 99.9% — binomial noise at 8192 px is
    ~±0.06pp at that rate, so a real regression to 99.6% (≈40× more
    disagreeing pixels) fails loudly instead of passing silently).

    This is the regression guard for the float32 selection hardening in
    ``_f_stat_p_and_logp`` — before it, betainc underflow dropped
    agreement to ~99.7% with systematic model-family misselection on
    strong-signal pixels."""
    px = 8192
    years, vals, mask = _mixed_population(rng, px)
    params = LTParams()
    out64 = jax_segment_pixels(years, vals, mask, params)
    out32 = jax_segment_pixels(years, vals.astype(np.float32), mask, params)

    agree = (
        (np.asarray(out64.model_valid) == np.asarray(out32.model_valid))
        & (np.asarray(out64.n_vertices) == np.asarray(out32.n_vertices))
        & (np.asarray(out64.vertex_indices) == np.asarray(out32.vertex_indices)).all(
            axis=1
        )
    )
    rate = agree.mean()
    assert rate >= 0.999, f"f32 exact-vertex agreement {rate:.4%} below floor"


def test_f32_tail_magnitude(rng):
    """Gate the f32 error tail's MAGNITUDE, not just its frequency
    (VERDICT r3 weak #3: a kernel change could keep ≥99.9% exact agreement
    while fattening the numerical tail on the agreeing pixels, and nothing
    would fail).

    Among pixels whose vertex decisions agree exactly with f64, the
    fitted-trajectory and rmse deltas are pure rounding accumulation.
    Measured on this test's own deterministic population (8192 px,
    consistent with PARITY_f32.json's 1M-px artifact: fitted p99 1.1e-6):

        fitted |Δ|: p99 9.4e-7, p99.9 2.2e-6, max 7.3e-6
        rmse   |Δ|: p99 9.2e-8, p99.9 4.6e-7, max 2.2e-6

    Gates sit ~4× above the measured values — far below any
    physically-meaningful reflectance difference (1 DN ≈ 2.75e-5), yet
    tight enough that an extra rounding stage (e.g. a reordered
    accumulation or a dropped compensated sum) fails loudly."""
    px = 8192
    years, vals, mask = _mixed_population(rng, px)
    params = LTParams()
    out64 = jax_segment_pixels(years, vals, mask, params)
    out32 = jax_segment_pixels(years, vals.astype(np.float32), mask, params)

    agree = (
        (np.asarray(out64.model_valid) == np.asarray(out32.model_valid))
        & (np.asarray(out64.n_vertices) == np.asarray(out32.n_vertices))
        & (np.asarray(out64.vertex_indices) == np.asarray(out32.vertex_indices)).all(
            axis=1
        )
    )
    assert agree.mean() >= 0.999  # population sanity; the floor test owns this

    d_fit = np.abs(
        np.asarray(out32.fitted, np.float64) - np.asarray(out64.fitted)
    )[agree]
    d_rmse = np.abs(
        np.asarray(out32.rmse, np.float64) - np.asarray(out64.rmse)
    )[agree]
    assert np.quantile(d_fit, 0.99) < 4e-6, "fitted-trajectory p99 tail fattened"
    assert np.quantile(d_fit, 0.999) < 1e-5, "fitted-trajectory p99.9 tail fattened"
    assert np.quantile(d_rmse, 0.99) < 5e-7, "rmse p99 tail fattened"
    assert np.quantile(d_rmse, 0.999) < 2e-6, "rmse p99.9 tail fattened"


def test_lentz_betainc_accuracy_bound():
    """Direct accuracy gate on the fixed-trip Lentz (p, log p) evaluation.

    The f32 scoring path rests on ``_betainc_p_and_logp_lentz`` staying
    within its measured envelope vs the exact regularised incomplete beta
    (round 4: max rel p error 1.8e-5, log-p abs p99 8e-6 over the full
    (a, b, x) grid this pipeline can produce — see the function docstring).
    Reference: jax betainc in float64.
    """
    import jax
    import jax.numpy as jnp

    from land_trendr_tpu.ops.segment import _betainc_p_and_logp_lentz

    rng = np.random.default_rng(0)
    a_l, b_l, x_l = [], [], []
    for n in range(6, 41):
        for m in range(1, 7):
            df1, df2 = 2 * m - 1, n - 2 * m
            if df2 < 1:
                continue
            f = 10 ** rng.uniform(-3, 4, 500)
            x = df2 / (df2 + df1 * f)
            a_l.append(np.full_like(x, df2 / 2.0))
            b_l.append(np.full_like(x, df1 / 2.0))
            x_l.append(x)
    a = np.concatenate(a_l)
    b = np.concatenate(b_l)
    x = np.concatenate(x_l)
    ref = np.asarray(
        jax.scipy.special.betainc(
            jnp.asarray(a, jnp.float64),
            jnp.asarray(b, jnp.float64),
            jnp.asarray(x, jnp.float64),
        )
    )
    p32, lp32 = _betainc_p_and_logp_lentz(
        jnp.asarray(a, jnp.float32),
        jnp.asarray(b, jnp.float32),
        jnp.asarray(x, jnp.float32),
    )
    p32 = np.asarray(p32, np.float64)
    lp32 = np.asarray(lp32, np.float64)
    healthy = ref > 1e-30
    rel = np.abs(p32[healthy] - ref[healthy]) / np.maximum(ref[healthy], 1e-38)
    # measured: 4.6e-5 under XLA CPU with the shared _lgamma_fixed
    # (round 5; the lax.lgamma form measured 6.7e-5); orders of magnitude
    # inside the selection knife-edge band the end-to-end agreement gates
    # above police
    assert rel.max() < 2e-4, rel.max()
    assert np.percentile(rel, 99) < 2e-5, np.percentile(rel, 99)
    lref = np.log(np.maximum(ref, 1e-300))
    lperr = np.abs(lp32 - lref)
    assert np.percentile(lperr, 99) < 5e-5, np.percentile(lperr, 99)
    assert lperr.max() < 1e-2, lperr.max()       # deep-tail absolute sanity


def test_lentz_iters_ny41_44_band_at_default_trips():
    """The NY=41–44 band runs at the DEFAULT 12-trip count — validate it.

    Advisor finding (round 5): ``_lentz_iters`` truncates, so
    ``2.5·sqrt((44+10)/2) = 12.99 → 12`` — NY 41–44 share the 12-trip
    count whose accuracy envelope was only measured on the NY ≤ 40 grid
    (the extended-grid gate runs NY = 100 at 18 trips, skipping this
    band).  This closes the gap: the full (a, b, x) grid those year
    counts can produce, at exactly 12 trips, holds the same envelope the
    NY ≤ 40 gate enforces."""
    import jax
    import jax.numpy as jnp

    from land_trendr_tpu.ops.segment import _betainc_p_and_logp_lentz, _lentz_iters

    # the band boundary: 44 is the last NY at the default trip count
    assert [_lentz_iters(n) for n in (41, 42, 43, 44, 45)] == [12, 12, 12, 12, 13]

    rng = np.random.default_rng(2)
    a_l, b_l, x_l = [], [], []
    for n in range(41, 45):
        for m in range(1, 7):
            df1, df2 = 2 * m - 1, n - 2 * m
            if df2 < 1:
                continue
            f = 10 ** rng.uniform(-3, 4, 500)
            x = df2 / (df2 + df1 * f)
            a_l.append(np.full_like(x, df2 / 2.0))
            b_l.append(np.full_like(x, df1 / 2.0))
            x_l.append(x)
    a = np.concatenate(a_l)
    b = np.concatenate(b_l)
    x = np.concatenate(x_l)
    ref = np.asarray(
        jax.scipy.special.betainc(
            jnp.asarray(a, jnp.float64),
            jnp.asarray(b, jnp.float64),
            jnp.asarray(x, jnp.float64),
        )
    )
    p32, lp32 = _betainc_p_and_logp_lentz(
        jnp.asarray(a, jnp.float32),
        jnp.asarray(b, jnp.float32),
        jnp.asarray(x, jnp.float32),
        iters=12,
    )
    p32 = np.asarray(p32, np.float64)
    healthy = ref > 1e-30
    rel = np.abs(p32[healthy] - ref[healthy]) / np.maximum(ref[healthy], 1e-38)
    # same envelope the extended-grid gate holds (NY ≤ 40 gate: 2e-4)
    assert rel.max() < 3e-4, rel.max()
    assert np.percentile(rel, 99) < 2e-5, np.percentile(rel, 99)
    lref = np.log(np.maximum(ref, 1e-300))
    lperr = np.abs(np.asarray(lp32, np.float64) - lref)
    assert np.percentile(lperr, 99) < 5e-5, np.percentile(lperr, 99)
    assert lperr.max() < 1e-2, lperr.max()


def test_lentz_iters_rule_covers_long_stacks():
    """The sqrt-of-dof trip rule keeps the Lentz envelope beyond NY = 40.

    Advisor finding (round 4): the fixed 12-trip count was only validated
    for NY <= 40; a 100-year stack raises a = df2/2 to 44 where 12 trips
    may not converge.  ``_lentz_iters`` now derives the count from the
    static year-axis length; this gate runs the extended grid (n up to
    100) at the derived count and holds the same envelope."""
    import jax
    import jax.numpy as jnp

    from land_trendr_tpu.ops.segment import _betainc_p_and_logp_lentz, _lentz_iters

    assert _lentz_iters(40) == 12  # default NY: exactly the validated count
    ny = 100
    iters = _lentz_iters(ny)
    assert iters == 18  # the rule actually scales (truncation, not ceil)
    rng = np.random.default_rng(1)
    a_l, b_l, x_l = [], [], []
    for n in range(6, ny + 1, 2):
        for m in range(1, 7):
            df1, df2 = 2 * m - 1, n - 2 * m
            if df2 < 1:
                continue
            f = 10 ** rng.uniform(-3, 4, 120)
            x = df2 / (df2 + df1 * f)
            a_l.append(np.full_like(x, df2 / 2.0))
            b_l.append(np.full_like(x, df1 / 2.0))
            x_l.append(x)
    a = np.concatenate(a_l)
    b = np.concatenate(b_l)
    x = np.concatenate(x_l)
    ref = np.asarray(
        jax.scipy.special.betainc(
            jnp.asarray(a, jnp.float64),
            jnp.asarray(b, jnp.float64),
            jnp.asarray(x, jnp.float64),
        )
    )
    p32, lp32 = _betainc_p_and_logp_lentz(
        jnp.asarray(a, jnp.float32),
        jnp.asarray(b, jnp.float32),
        jnp.asarray(x, jnp.float32),
        iters=iters,
    )
    p32 = np.asarray(p32, np.float64)
    healthy = ref > 1e-30
    rel = np.abs(p32[healthy] - ref[healthy]) / np.maximum(ref[healthy], 1e-38)
    assert rel.max() < 3e-4, rel.max()
    lref = np.log(np.maximum(ref, 1e-300))
    lperr = np.abs(np.asarray(lp32, np.float64) - lref)
    assert lperr.max() < 1e-2, lperr.max()
