"""Float32 quality gate: the f32 kernel must be *statistically equivalent*
to the f64 oracle even where exact vertex placement differs (the documented
f32 tolerance contract in ``ops/segment.py``)."""

import numpy as np

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.models.oracle import segment_series
from land_trendr_tpu.ops.segment import jax_segment_pixels

YEARS = np.arange(1984, 2022, dtype=np.float64)
NY = len(YEARS)


def test_f32_statistical_equivalence(rng):
    n_px = 256
    base = np.where(
        YEARS < 1996, 0.15, np.maximum(0.85 - 0.03 * (YEARS - 1996), 0.15)
    )
    vals = base[None, :] + rng.normal(0, 0.02, (n_px, NY))
    mask = rng.random((n_px, NY)) > 0.1
    params = LTParams()

    out = jax_segment_pixels(
        YEARS.astype(np.float32), vals.astype(np.float32), mask, params
    )
    rmse32 = np.asarray(out.rmse)
    valid32 = np.asarray(out.model_valid)

    d_rmse = []
    valid_flips = 0
    for i in range(n_px):
        ref = segment_series(YEARS, vals[i], mask[i], params)
        valid_flips += ref.model_valid != valid32[i]
        if ref.model_valid and valid32[i]:
            d_rmse.append(rmse32[i] - ref.rmse)
    d_rmse = np.asarray(d_rmse)

    # model_valid decisions agree except for rare knife-edge pixels
    assert valid_flips <= max(2, n_px // 50)
    # rmse distribution equivalent: no systematic bias, tight spread
    assert abs(np.mean(d_rmse)) < 0.02
    assert np.quantile(np.abs(d_rmse), 0.95) < 0.1
    # the f32 fits are never catastrophically worse
    assert np.max(d_rmse) < 0.25
