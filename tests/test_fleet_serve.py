"""Serving-fleet tests (ISSUE 13): router, fair share, autoscaling.

Pins the fleet subsystem's contracts:

* **warm-affinity routing**: repeat shapes land on the replica already
  holding the compiled program — the second same-shape job routes
  ``warm`` and runs **zero** jit compiles;
* **fair share**: deficit round-robin over per-tenant queues — a heavy
  tenant's burst cannot starve a light tenant's single job;
* **quotas**: a tenant at its quota (and a full router queue) is
  throttled 429 + ``Retry-After`` (``tenant_throttled`` event) while
  other tenants proceed;
* **replica death**: a replica SIGKILLed mid-job is detected, the job
  re-routes with its router-pinned workdir, resumes on the survivor
  and completes **byte-identical** to a clean CLI run — zero accepted
  jobs lost;
* **autoscaling**: a scripted burn-rate history drives a deterministic
  scale-up → hold-down → scale-down sequence, replayed byte-identically;
* the new ``route_decision``/``replica_up``/``replica_down``/
  ``tenant_throttled``/``scale_decision`` events schema-lint clean and
  fold in ``obs_report``'s router rollup; ``lt top`` renders the router
  aggregate.

Scene shape and params are shared with ``tests/test_serve.py`` so the
process-wide jit cache keeps in-process replicas warm across the suite.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from land_trendr_tpu.cli import main as cli_main
from land_trendr_tpu.fleet import (
    DOWN_REASONS,
    Autoscaler,
    FleetRouter,
    RouterConfig,
    parse_tenant_weights,
)
from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack
from land_trendr_tpu.serve import (
    Rejection,
    SegmentationServer,
    ServeConfig,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

#: the test_serve.py scene/params — identical program-cache keys keep
#: every in-process replica after the first warm
_PARAM_FLAGS = ["--max-segments", "4", "--vertex-count-overshoot", "2"]
_PARAMS = {"max_segments": 4, "vertex_count_overshoot": 2}
_TILE = 20


@pytest.fixture(scope="module")
def stack_dir(tmp_path_factory) -> str:
    d = str(tmp_path_factory.mktemp("fleet_stack") / "stack")
    write_stack(
        d,
        make_stack(
            SceneSpec(width=40, height=40, year_start=2000, year_end=2008,
                      seed=3)
        ),
    )
    return d


def _digest_workdir(workdir: str) -> dict:
    out: dict = {}
    for p in sorted(Path(workdir).glob("tile_*.npz")):
        with np.load(p) as z:
            out[p.name] = {
                name: hashlib.sha256(
                    np.ascontiguousarray(z[name]).tobytes()
                ).hexdigest()
                for name in sorted(z.files)
            }
    return out


def _job(stack_dir: str, **kw) -> dict:
    return {
        "stack_dir": stack_dir,
        "tile_size": _TILE,
        "params": dict(_PARAMS),
        "run_overrides": {"retry_backoff_s": 0.0},
        **kw,
    }


def _await_terminal(router: FleetRouter, job_id: str,
                    timeout_s: float = 300.0) -> dict:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        s = router.job_status(job_id)
        if s is not None and s["state"] not in ("queued", "routed"):
            return s
        time.sleep(0.05)
    raise TimeoutError(f"job {job_id} not terminal within {timeout_s}s")


def _events(workdir: str) -> list:
    return [
        json.loads(line)
        for line in (Path(workdir) / "events.jsonl").read_text().splitlines()
        if line.strip()
    ]


class _Replicas:
    """N in-process SegmentationServers on threads (cheap replicas for
    router tests; the process-wide jit cache is shared, but each server
    keeps its OWN ProgramCache accounting — exactly what the warm
    assertions read)."""

    def __init__(self, tmp_path, n: int, **serve_kw) -> None:
        self.servers = [
            SegmentationServer(ServeConfig(
                workdir=str(tmp_path / f"replica{i}"),
                feed_cache_mb=32,
                **serve_kw,
            ))
            for i in range(n)
        ]
        self.threads = [
            threading.Thread(target=s.serve_forever) for s in self.servers
        ]
        for t in self.threads:
            t.start()
        self.bases = tuple(
            f"http://127.0.0.1:{s.port}" for s in self.servers
        )

    def stop(self) -> None:
        for s in self.servers:
            s.stop()
        for t in self.threads:
            t.join(timeout=120)


# ---------------------------------------------------------------------------
# config / vocabulary validation


def test_router_config_validation(tmp_path):
    with pytest.raises(ValueError, match="loopback"):
        RouterConfig(replicas=("http://127.0.0.1:1",),
                     route_host="0.0.0.0")
    with pytest.raises(ValueError, match="needs replicas"):
        RouterConfig()
    with pytest.raises(ValueError, match="base URL"):
        RouterConfig(replicas=("127.0.0.1:80",))
    with pytest.raises(ValueError, match="NAME=WEIGHT"):
        RouterConfig(replicas=("http://x",), tenant_weights="oops")
    with pytest.raises(ValueError, match="hysteresis"):
        RouterConfig(spawn_replicas=1, autoscale=True,
                     scale_down_burn=0.9)
    with pytest.raises(ValueError, match="SPAWNED"):
        RouterConfig(replicas=("http://x",), autoscale=True)
    with pytest.raises(ValueError):  # typo'd seam = config error NOW
        RouterConfig(replicas=("http://x",),
                     fault_schedule="router.forwardd@0")
    assert parse_tenant_weights("a=3,b=1.5") == {"a": 3.0, "b": 1.5}
    # the CLI maps the same failures to the documented exit 2
    assert cli_main(["route", "--route-host", "0.0.0.0",
                     "--replica", "http://127.0.0.1:1",
                     "--workdir", str(tmp_path / "rt")]) == 2
    assert cli_main(["route", "--workdir", str(tmp_path / "rt2")]) == 2


def test_down_reason_tables_cannot_drift():
    from check_events_schema import DOWN_REASONS as LINT_REASONS
    from check_events_schema import SCALE_DIRECTIONS

    assert tuple(LINT_REASONS) == tuple(DOWN_REASONS)
    assert set(SCALE_DIRECTIONS) == {"up", "down"}


# ---------------------------------------------------------------------------
# autoscaler: scripted burn history, deterministic replay


def test_autoscaler_scripted_burn_deterministic():
    """A scripted burn-rate spike drives scale-up, the hold-down timer
    suppresses flapping, and the cooled-off burn drives scale-down —
    the whole sequence replayed byte-identically."""

    def script() -> list:
        scaler = Autoscaler(
            min_replicas=1, max_replicas=3, up_burn=0.5, down_burn=0.05,
            for_s=2.0, hold_s=10.0,
        )
        replicas, out = 1, []
        for t in range(30):
            burn = 0.9 if t < 10 else 0.0
            d = scaler.decide(burn, 0, replicas, float(t))
            if d == "up":
                replicas += 1
            elif d == "down":
                replicas -= 1
            if d:
                out.append((t, d, replicas))
        return out

    run1, run2 = script(), script()
    assert run1 == run2, "scripted history must replay identically"
    # burn >= 0.5 from t=0 holds for for_s=2 → up at t=2; hold-down
    # blocks further actions until t=12; by then the burn has cooled
    # (<= 0.05 from t=10, for_s=2 → condition ripe at t=12) → down
    assert run1 == [(2, "up", 2), (12, "down", 1)], run1
    # bounds: at max_replicas the up decision is withheld
    scaler = Autoscaler(min_replicas=1, max_replicas=2, up_burn=0.5,
                        down_burn=0.05, for_s=0.0, hold_s=0.0)
    assert scaler.decide(0.9, 0, 2, 0.0) is None
    # a backlogged queue blocks scale-down (shrinking moves burn up)
    scaler = Autoscaler(min_replicas=1, max_replicas=2, up_burn=0.5,
                        down_burn=0.05, for_s=0.0, hold_s=0.0)
    assert scaler.decide(0.0, 5, 2, 0.0) is None
    assert scaler.decide(0.0, 0, 2, 1.0) == "down"
    # a dark telemetry plane (burn None) never scales
    assert scaler.decide(None, 0, 2, 2.0) is None
    st = scaler.state()
    assert st["min_replicas"] == 1 and st["burn"] is None


# ---------------------------------------------------------------------------
# warm-affinity routing


def test_affinity_routes_repeat_shapes_warm(stack_dir, tmp_path):
    replicas = _Replicas(tmp_path, 2)
    rt_dir = str(tmp_path / "rt")
    router = FleetRouter(RouterConfig(
        workdir=rt_dir, replicas=replicas.bases, health_interval_s=0.2,
    ))
    rt_thread = threading.Thread(target=router.serve_forever)
    rt_thread.start()
    try:
        s1 = _await_terminal(
            router, router.submit(_job(stack_dir))["job_id"]
        )
        s2 = _await_terminal(
            router, router.submit(_job(stack_dir))["job_id"]
        )
    finally:
        router.stop()
        rt_thread.join(timeout=300)
        replicas.stop()
    assert s1["state"] == s2["state"] == "done"
    # the affinity contract: the repeat shape landed on the SAME
    # replica and ran ZERO jit compiles there
    assert s2["replica"] == s1["replica"]
    assert s2["result"]["summary"]["program_cache"]["misses"] == 0
    assert s2["result"]["summary"]["program_cache"]["hits"] == 1
    decisions = [e for e in _events(rt_dir) if e["ev"] == "route_decision"]
    assert len(decisions) == 2
    assert decisions[0]["warm"] is False
    assert decisions[1]["warm"] is True
    assert decisions[1]["key"] == decisions[0]["key"]
    # schema lint + obs_report router rollup over the router stream
    from check_events_schema import main as lint_main

    assert lint_main([rt_dir]) == 0
    import obs_report

    report, _spans = obs_report.fold([os.path.join(rt_dir, "events.jsonl")])
    assert report["router"]["routed"] == 2
    assert report["router"]["warm"] == 1
    assert report["router"]["warm_ratio"] == 0.5
    # lt top renders the router aggregate from the healthz shape
    import lt_top

    view = lt_top.render_router(
        {"healthz": {"router": True, "uptime_s": 1.0, "queue_depth": 0,
                     "routed": 0, "jobs_total": 2, "jobs_terminal": 2,
                     "tenants": {"default": {"queued": 0, "routed": 0,
                                             "weight": 1, "deficit": 0}},
                     "replicas": [{"replica": "r0", "state": "ready",
                                   "inflight": 0, "warm_keys": 1,
                                   "base": "http://x"}],
                     "scaler": None},
         "metrics": [], "jobs": [s1, s2]}
    )
    assert "REPLICA" in view and "TENANT" in view and "r0" in view


def test_healthz_exposes_warm_affinity_keys(stack_dir, tmp_path):
    """The serve-side satellite: after a job runs, /healthz carries the
    request-level affinity key (bounded list) a router joins against —
    not just the opaque warm_program_count."""
    from land_trendr_tpu.serve.jobs import JobRequest

    server = SegmentationServer(
        ServeConfig(workdir=str(tmp_path / "srv"), max_jobs=1,
                    feed_cache_mb=32)
    )
    server.submit(_job(stack_dir))
    server.serve_forever()
    snap = server.stats()
    expected = JobRequest.from_payload(_job(stack_dir)).affinity_key()
    assert snap["warm_keys"] == [expected]
    assert isinstance(snap["warm_program_count"], int)


# ---------------------------------------------------------------------------
# fair share + quotas


def test_fair_share_heavy_tenant_cannot_starve_light(stack_dir, tmp_path):
    """Four heavy-tenant jobs queued ahead of one light-tenant job:
    deficit round-robin must serve the light tenant on the second
    rotation, not after the heavy backlog drains."""
    replicas = _Replicas(tmp_path, 1)
    rt_dir = str(tmp_path / "rt")
    router = FleetRouter(RouterConfig(
        workdir=rt_dir, replicas=replicas.bases, replica_inflight=1,
        health_interval_s=0.2,
    ))
    # queue the whole burst BEFORE the dispatcher starts: the routing
    # order is then pure scheduler policy
    heavy = [router.submit(_job(stack_dir, tenant="heavy"))
             for _ in range(4)]
    light = router.submit(_job(stack_dir, tenant="light"))
    rt_thread = threading.Thread(target=router.serve_forever)
    rt_thread.start()
    try:
        for snap in (*heavy, light):
            s = _await_terminal(router, snap["job_id"])
            assert s["state"] == "done", s.get("error")
    finally:
        router.stop()
        rt_thread.join(timeout=300)
        replicas.stop()
    order = [
        (e["tenant"], e["job_id"])
        for e in _events(rt_dir) if e["ev"] == "route_decision"
    ]
    tenants_in_order = [t for t, _ in order]
    assert len(order) == 5
    # round-robin with equal weights: heavy, light, heavy, heavy, heavy
    assert tenants_in_order[1] == "light", (
        f"light tenant starved behind the heavy burst: {tenants_in_order}"
    )
    assert order[1][1] == light["job_id"]


def test_tenant_quota_and_queue_throttle_429(stack_dir, tmp_path):
    replicas = _Replicas(tmp_path, 1)
    rt_dir = str(tmp_path / "rt")
    router = FleetRouter(RouterConfig(
        workdir=rt_dir, replicas=replicas.bases, tenant_quota=2,
        route_queue_depth=3, health_interval_s=0.2,
    ))
    try:
        router.submit(_job(stack_dir, tenant="a"))
        router.submit(_job(stack_dir, tenant="a"))
        # tenant quota: a's third submission throttles, b's proceeds
        with pytest.raises(Rejection) as exc:
            router.submit(_job(stack_dir, tenant="a"))
        assert exc.value.http_status == 429
        assert exc.value.reason == "tenant_quota"
        router.submit(_job(stack_dir, tenant="b"))
        # router queue bound: depth 3 reached, tenant c throttles too
        with pytest.raises(Rejection) as exc:
            router.submit(_job(stack_dir, tenant="c"))
        assert exc.value.reason == "queue_full"
        # the HTTP contract: 429 + Retry-After header
        body = json.dumps(_job(stack_dir, tenant="a")).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{router.port}/jobs", data=body,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as httperr:
            urllib.request.urlopen(req, timeout=30)
        assert httperr.value.code == 429
        assert httperr.value.headers.get("Retry-After") is not None
        # malformed request: 400 job_rejected, not a throttle
        with pytest.raises(Rejection) as exc:
            router.submit({"nope": 1})
        assert exc.value.http_status == 400
    finally:
        router.stop()
        router.serve_forever()  # drains the queued jobs as cancelled
        replicas.stop()
    evs = _events(rt_dir)
    throttled = [e for e in evs if e["ev"] == "tenant_throttled"]
    assert sorted({e["reason"] for e in throttled}) == [
        "queue_full", "tenant_quota",
    ]
    assert {e["tenant"] for e in throttled} >= {"a"}
    assert [e for e in evs if e["ev"] == "job_rejected"]
    from check_events_schema import main as lint_main

    assert lint_main([rt_dir]) == 0


# ---------------------------------------------------------------------------
# replica death: re-route, resume, byte-identical artifacts


def test_replica_sigkill_reroutes_and_completes_byte_identical(
    stack_dir, tmp_path
):
    """The zero-lost-jobs contract end-to-end with REAL replica
    processes: SIGKILL the replica mid-job; the router re-routes the
    job, the survivor resumes the router-pinned manifest, and the
    artifacts are byte-identical to a clean CLI run."""
    rt_dir = str(tmp_path / "rt")
    router = FleetRouter(RouterConfig(
        workdir=rt_dir,
        spawn_replicas=2,
        health_interval_s=0.3,
        route_retries=3,
        # pace dispatches so the kill lands mid-job with durable tiles
        replica_args=(
            "--feed-cache-mb", "64",
            "--fault-schedule", "seed=5,dispatch%1.0=slow:0.3",
        ),
    ))
    rt_thread = threading.Thread(target=router.serve_forever)
    rt_thread.start()
    try:
        snap = router.submit(_job(stack_dir))
        wd = Path(snap["workdir"])
        deadline = time.monotonic() + 240
        victim = None
        while time.monotonic() < deadline and victim is None:
            if list(wd.glob("tile_*.npz")):
                with router._lock:
                    for r in router.pool:
                        if (snap["job_id"] in r.inflight
                                and r.proc is not None
                                and r.proc.poll() is None):
                            victim = r
            if victim is None:
                time.sleep(0.05)
        assert victim is not None, "no replica ever held the job"
        pre_kill = _digest_workdir(str(wd))
        assert pre_kill, "kill must land after durable work"
        os.kill(victim.proc.pid, signal.SIGKILL)
        s = _await_terminal(router, snap["job_id"], timeout_s=240.0)
    finally:
        router.stop()
        rt_thread.join(timeout=600)
    assert s["state"] == "done", s.get("error")
    assert s["attempts"] >= 2, "the job was never re-routed"
    assert s["replica"] != victim.rid
    # byte-identical to a clean CLI run of the same request — and the
    # pre-kill tiles were RESUMED, not recomputed
    resumed = _digest_workdir(str(wd))
    clean_wd = str(tmp_path / "clean_w")
    assert cli_main(["segment", stack_dir, "--tile-size", str(_TILE),
                     "--workdir", clean_wd,
                     "--out-dir", str(tmp_path / "clean_o"),
                     *_PARAM_FLAGS]) == 0
    assert resumed == _digest_workdir(clean_wd)
    assert all(resumed[k] == v for k, v in pre_kill.items())
    evs = _events(rt_dir)
    downs = [e for e in evs if e["ev"] == "replica_down"]
    assert any(
        e["replica"] == victim.rid and e["reason"] == "dead" for e in downs
    ), downs
    # zero lost jobs: every accepted job reached a terminal job_done
    dones = [e for e in evs if e["ev"] == "job_done"]
    assert [e["status"] for e in dones] == ["done"]
    from check_events_schema import main as lint_main

    assert lint_main([rt_dir]) == 0


# ---------------------------------------------------------------------------
# fixture + lt_fleet rendering


def test_router_fixture_stream_lints_clean():
    """The committed router fixture (precommit's schema-drift guard)
    stays valid against the live schema."""
    from check_events_schema import main as lint_main

    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "router.events.jsonl"
    )
    assert lint_main([fixture]) == 0


def test_lt_fleet_renders_router_snapshot():
    import lt_fleet

    view = {
        "counts": {"folded": 1, "stale": 0, "corrupt": 0, "excluded": 0,
                   "snapshots": 1},
        "generated_t": 0.0,
        "hosts": [{
            "path": "h.1.snap.json", "host": "h", "pid": 1,
            "kind": "route", "age_s": 0.5, "corrupt": False,
            "stale": False, "excluded": False,
            "state": {
                "progress": {"queue_depth": 2},
                "router": {
                    "tenants": {"a": {"queued": 2, "routed": 1,
                                      "weight": 3.0}},
                    "replicas": [{"replica": "r0", "state": "ready",
                                  "inflight": 1, "warm_keys": 2,
                                  "base": "http://127.0.0.1:9"}],
                    "scaler": {"burn": 0.1, "min_replicas": 1,
                               "max_replicas": 4, "firing": []},
                },
            },
        }],
        "metrics": [
            {"name": "lt_router_jobs_routed_total", "kind": "counter",
             "labels": {}, "value": 3.0},
        ],
        "conflicts": [],
        "alerts": [],
    }
    text = lt_fleet.render(view)
    assert "router @ h:1" in text
    assert "tenant a" in text and "replica r0" in text
    assert "scaler burn 0.1" in text
    assert "forwards 3" in text
