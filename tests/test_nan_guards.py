"""NaN-propagation guards (SURVEY.md §5 "Race detection/sanitizers").

The reference's map tasks share nothing, so there is nothing to race; the
TPU rebuild's analogous hazard is NaN/Inf leaking out of guarded divisions
in masked/degenerate lanes.  ``jax_debug_nans`` turns any NaN produced by
a primitive into an immediate error, so running the kernel under it on
adversarial inputs proves every division/log/sqrt is properly guarded —
the sanitizer pass of this framework.
"""

import jax
import numpy as np
import pytest

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.ops.ftv import jax_fit_to_vertices
from land_trendr_tpu.ops.segment import jax_segment_pixels

PARAMS = LTParams(max_segments=4, vertex_count_overshoot=2)


@pytest.fixture()
def debug_nans():
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", False)


def _years(ny=20):
    return np.arange(2000, 2000 + ny, dtype=np.int32)


ADVERSARIAL = {
    "all_masked": lambda rng, ny: (
        rng.normal(size=(4, ny)),
        np.zeros((4, ny), bool),
    ),
    "single_valid_year": lambda rng, ny: (
        rng.normal(size=(4, ny)),
        np.eye(4, ny, dtype=bool),
    ),
    "two_valid_years": lambda rng, ny: (
        rng.normal(size=(4, ny)),
        np.eye(4, ny, dtype=bool) | np.eye(4, ny, k=5, dtype=bool),
    ),
    "constant_series": lambda rng, ny: (
        np.full((4, ny), 0.37),
        np.ones((4, ny), bool),
    ),
    "exact_min_observations": lambda rng, ny: (
        rng.normal(size=(4, ny)),
        np.tile(np.arange(ny) < PARAMS.min_observations_needed, (4, 1)),
    ),
    "huge_values": lambda rng, ny: (
        rng.normal(size=(4, ny)) * 1e30,
        rng.uniform(size=(4, ny)) > 0.2,
    ),
    "tiny_values": lambda rng, ny: (
        rng.normal(size=(4, ny)) * 1e-30,
        rng.uniform(size=(4, ny)) > 0.2,
    ),
    "nan_inputs_masked_out": lambda rng, ny: (
        np.where(rng.uniform(size=(4, ny)) > 0.5, np.nan, 0.5),
        np.ones((4, ny), bool),  # kernel must drop non-finite itself
    ),
    "inf_inputs_masked_out": lambda rng, ny: (
        np.where(rng.uniform(size=(4, ny)) > 0.5, np.inf, 0.5),
        np.ones((4, ny), bool),
    ),
    "alternating_mask": lambda rng, ny: (
        rng.normal(size=(4, ny)),
        np.tile(np.arange(ny) % 2 == 0, (4, 1)),
    ),
}


@pytest.mark.parametrize("case", sorted(ADVERSARIAL))
def test_segment_no_nan_under_debug_nans(rng, debug_nans, case):
    ny = 20
    vals, mask = ADVERSARIAL[case](rng, ny)
    out = jax_segment_pixels(
        _years(ny), np.asarray(vals, np.float64), np.asarray(mask), PARAMS
    )
    jax.block_until_ready(out)
    for name, field in out._asdict().items():
        assert np.isfinite(np.asarray(field, np.float64)).all(), name


def test_ftv_no_nan_under_debug_nans(rng, debug_nans):
    ny = 20
    years = _years(ny)
    vals = rng.normal(size=(6, ny))
    mask = rng.uniform(size=(6, ny)) > 0.2
    seg = jax_segment_pixels(years, vals, mask, PARAMS)
    # secondary index with its own pathologies: constants and all-masked rows
    sec = np.full((6, ny), 2.5)
    sec_mask = mask.copy()
    sec_mask[0] = False
    ftv = jax_fit_to_vertices(
        years, sec, sec_mask, seg.vertex_indices, seg.n_vertices, PARAMS
    )
    assert np.isfinite(np.asarray(ftv)).all()
