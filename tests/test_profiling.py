"""Tracing/profiling subsystem (SURVEY.md §5): stage scopes + trace capture."""

import os

import jax
import numpy as np
import pytest

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.ops.segment import jax_segment_pixels
from land_trendr_tpu.utils.profiling import (
    STAGE_SCOPES,
    StageTimer,
    profile_op,
    trace,
)


def _batch(rng, px=8, ny=20):
    years = np.arange(2000, 2000 + ny, dtype=np.int32)
    vals = rng.normal(0.0, 0.1, size=(px, ny)).astype(np.float32) - 0.6
    mask = rng.uniform(size=(px, ny)) > 0.1
    return years, vals, mask


def test_stage_scopes_annotate_hlo(rng):
    """Every pipeline stage's named_scope survives into the lowered HLO, so
    profiler timelines can attribute time to algorithm stages."""
    years, vals, mask = _batch(rng)
    params = LTParams(max_segments=3, vertex_count_overshoot=2)
    hlo = (
        jax.jit(jax_segment_pixels, static_argnames=("params",))
        .lower(years, vals, mask, params)
        .as_text(debug_info=True)
    )
    for scope in STAGE_SCOPES:
        assert scope in hlo, f"named_scope {scope!r} missing from lowered HLO"


def test_trace_writes_profile(tmp_path, rng):
    years, vals, mask = _batch(rng)
    params = LTParams(max_segments=3, vertex_count_overshoot=2)
    logdir = str(tmp_path / "prof")
    # warm the executable OUTSIDE the trace: compiling under the host
    # profiler multiplies compile time several-fold late in the suite,
    # and the assertion is about trace files from device execution,
    # not about capturing the compile
    jax.block_until_ready(jax_segment_pixels(years, vals, mask, params))
    with trace(logdir):
        out = jax_segment_pixels(years, vals, mask, params)
        jax.block_until_ready(out)
    files = [
        os.path.join(root, f)
        for root, _, fs in os.walk(logdir)
        for f in fs
    ]
    assert files, "profiler trace produced no files"
    assert any("xplane" in f or "trace" in f for f in files)


def test_profile_op_reports(tmp_path, rng):
    years, vals, mask = _batch(rng)
    params = LTParams(max_segments=3, vertex_count_overshoot=2)
    stats = profile_op(
        lambda: jax_segment_pixels(years, vals, mask, params),
        logdir=str(tmp_path / "prof"),
        iters=2,
    )
    assert stats["wall_s_per_iter"] > 0.0
    assert stats["logdir_bytes"] > 0.0


def test_stage_timer_accumulates():
    timer = StageTimer()
    with timer.stage("feed"):
        pass
    with timer.stage("feed"):
        pass
    with timer.stage("write"):
        pass
    assert timer.counts() == {"feed": 2, "write": 1}
    totals = timer.totals()
    assert set(totals) == {"feed", "write"}
    assert all(v >= 0.0 for v in totals.values())
    s = timer.summary()
    assert set(s) == {"feed_s", "write_s"}
