"""Fetch-subsystem tests: packed ≡ per-product byte parity, async fault
retry, the model_valid rider, CLI knobs, telemetry/lint/rollup wiring,
and the fetch_bench smoke (tier-1).

The contract under test (runtime/fetch.py): ``fetch_packed`` is a pure
execution strategy — packed and per-product runs must produce
byte-identical tile artifacts across every product selection, with the
packed path costing ONE device→host transfer per tile.
"""

import json
import os

import numpy as np
import pytest

from land_trendr_tpu.cli import main as cli_main
from land_trendr_tpu.config import LTParams
from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
from land_trendr_tpu.ops.change import ChangeFilter
from land_trendr_tpu.runtime import (
    RunConfig,
    run_stack,
    stack_from_synthetic,
)
from land_trendr_tpu.runtime import fetch as fetchmod

SPEC = SceneSpec(width=48, height=40, year_start=1990, year_end=2005, seed=11)
PARAMS = LTParams(max_segments=4, vertex_count_overshoot=2)


@pytest.fixture(scope="module")
def rstack():
    return stack_from_synthetic(make_stack(SPEC))


def make_cfg(tmp, **kw):
    kw.setdefault("params", PARAMS)
    kw.setdefault("tile_size", 32)  # 48x40 scene -> edge tiles in both axes
    return RunConfig(
        workdir=os.path.join(tmp, "work"), out_dir=os.path.join(tmp, "out"),
        **kw,
    )


def load_artifacts(cfg, n_tiles):
    out = []
    for tid in range(n_tiles):
        with np.load(os.path.join(cfg.workdir, f"tile_{tid:05d}.npz")) as z:
            out.append({k: z[k] for k in z.files})
    return out


PARITY_CASES = {
    "full": dict(),
    # subset WITHOUT model_valid: the fit-rate metadata must ride the
    # payload (packed: 1 B/px in the same transfer; unpacked: fetched
    # alongside the products, not in a write-timer metadata branch)
    "subset": dict(
        products=("n_vertices", "vertex_years", "seg_magnitude", "rmse")
    ),
    # the everything-on case: f16 wire + FTV + fitted + fused change
    "f16_ftv_change": dict(
        fetch_f16=True, ftv_indices=("ndvi",), write_fitted=True,
        change_filt=ChangeFilter(),
    ),
}


@pytest.mark.parametrize("case", sorted(PARITY_CASES))
def test_packed_unpacked_byte_parity(tmp_path, rstack, case):
    kw = PARITY_CASES[case]
    cfg_p = make_cfg(str(tmp_path / "p"), fetch_packed=True, **kw)
    cfg_u = make_cfg(str(tmp_path / "u"), fetch_packed=False, **kw)
    sp = run_stack(rstack, cfg_p)
    su = run_stack(rstack, cfg_u)

    assert sp["fetch"]["packed"] is True
    assert su["fetch"]["packed"] is False
    # the tentpole claim: one transfer per tile, vs ~1 per product
    assert sp["fetch"]["transfers"] == sp["tiles"]
    assert su["fetch"]["transfers"] >= su["tiles"] * 4
    # identical run aggregates (the rider keeps fit_rate exact either way)
    assert sp["fit_rate"] == su["fit_rate"]

    packed, unpacked = (load_artifacts(c, sp["tiles"]) for c in (cfg_p, cfg_u))
    for tid, (a, b) in enumerate(zip(packed, unpacked)):
        assert sorted(a) == sorted(b)
        if "products" in kw:
            assert "model_valid" not in a  # rider must NOT leak into artifacts
        for k in a:
            assert a[k].dtype == b[k].dtype, (tid, k)
            assert a[k].shape == b[k].shape, (tid, k)
            assert a[k].tobytes() == b[k].tobytes(), (
                f"tile {tid} product {k} differs between packed and unpacked"
            )


def test_packed_parity_under_mesh(tmp_path, rstack):
    """The pack program composes with a sharded pixel axis (virtual
    8-device mesh): packed ≡ unpacked artifacts there too."""
    import jax

    from land_trendr_tpu.parallel import make_mesh

    mesh = make_mesh(jax.local_devices())
    cfg_p = make_cfg(str(tmp_path / "p"), fetch_packed=True)
    cfg_u = make_cfg(str(tmp_path / "u"), fetch_packed=False)
    sp = run_stack(rstack, cfg_p, mesh=mesh)
    run_stack(rstack, cfg_u, mesh=mesh)
    assert sp["fetch"]["transfers"] == sp["tiles"]
    for a, b in zip(
        load_artifacts(cfg_p, sp["tiles"]), load_artifacts(cfg_u, sp["tiles"])
    ):
        for k in a:
            assert a[k].tobytes() == b[k].tobytes()


def test_fetch_auto_keeps_per_product_on_cpu(tmp_path, rstack):
    """"auto" resolves to the per-product path on the CPU backend, where
    np.asarray is zero-copy and packing would be pure overhead."""
    assert fetchmod.resolve_packed("auto") is False
    summary = run_stack(rstack, make_cfg(str(tmp_path)))
    assert summary["fetch"]["packed"] is False


def test_async_fetch_fault_triggers_retry(tmp_path, rstack, monkeypatch):
    """A device error surfacing through an in-flight async fetch (i.e. at
    the drain's wait, tiles later than the dispatch) re-enters the retry
    ladder and the run completes."""
    real = fetchmod._to_host
    calls = {"n": 0}

    def flaky(arr):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected transfer fault")
        return real(arr)

    monkeypatch.setattr(fetchmod, "_to_host", flaky)
    cfg = make_cfg(str(tmp_path), fetch_packed=True, max_retries=2,
                   telemetry=True)
    summary = run_stack(rstack, cfg)
    assert summary["pixels"] == SPEC.height * SPEC.width
    evs = [json.loads(l) for l in open(summary["telemetry"]["events"])]
    retries = [e for e in evs if e["ev"] == "tile_retry"]
    assert len(retries) == 1
    assert "injected transfer fault" in retries[0]["error"]
    # the retried tile re-announced its later attempt
    assert any(
        e["ev"] == "tile_start" and e["attempt"] == 2 for e in evs
    )


def test_async_fetch_fault_exhausts_retries(tmp_path, rstack, monkeypatch):
    monkeypatch.setattr(
        fetchmod, "_to_host",
        lambda arr: (_ for _ in ()).throw(RuntimeError("persistent fault")),
    )
    cfg = make_cfg(str(tmp_path), fetch_packed=True, max_retries=1,
                   telemetry=True)
    with pytest.raises(RuntimeError, match="failed after 2 attempts"):
        run_stack(rstack, cfg)
    # the failed tile appears as a failure ONLY: tile_done waits for the
    # fetch to land, so a tile can never be done-then-failed, and the
    # aborted run_done must not count it
    evs = [
        json.loads(l)
        for l in open(os.path.join(cfg.workdir, "events.jsonl"))
    ]
    failed = {e["tile_id"] for e in evs if e["ev"] == "tile_failed"}
    done = {e["tile_id"] for e in evs if e["ev"] == "tile_done"}
    assert failed and not (failed & done) and not done
    run_done = [e for e in evs if e["ev"] == "run_done"][-1]
    assert run_done["status"] == "aborted" and run_done["tiles_done"] == 0


def test_runconfig_validates_fetch_knobs(tmp_path):
    with pytest.raises(ValueError, match="fetch_depth"):
        make_cfg(str(tmp_path), fetch_depth=0)
    with pytest.raises(ValueError, match="fetch_packed"):
        make_cfg(str(tmp_path), fetch_packed="yes")


def test_no_packed_fetch_cli(tmp_path, capsys):
    stack_dir = str(tmp_path / "stack")
    assert cli_main(["synth", stack_dir, "--size", "32",
                     "--year-start", "1990", "--year-end", "2001"]) == 0
    capsys.readouterr()
    assert cli_main([
        "segment", stack_dir, "--tile-size", "32",
        "--workdir", str(tmp_path / "work"), "--out-dir",
        str(tmp_path / "out"), "--max-segments", "4",
        "--vertex-count-overshoot", "2", "--no-packed-fetch",
    ]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["summary"]["fetch"]["packed"] is False
    assert rep["summary"]["fetch"]["tiles"] == 1

    # forcing both directions at once is an argument conflict
    assert cli_main([
        "segment", stack_dir, "--tile-size", "32",
        "--workdir", str(tmp_path / "w2"), "--out-dir",
        str(tmp_path / "o2"), "--packed-fetch", "--no-packed-fetch",
    ]) == 2
    assert "--no-packed-fetch" in capsys.readouterr().err


def test_fetch_telemetry_schema_metrics_and_rollup(tmp_path, rstack):
    """The fetch event passes the schema + value lint, advances the
    lt_fetch_* instruments, and folds into obs_report with the derived
    effective-bandwidth figure."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import check_events_schema
    import obs_report

    cfg = make_cfg(str(tmp_path), fetch_packed=True, telemetry=True)
    summary = run_stack(rstack, cfg)
    assert check_events_schema.main([cfg.workdir]) == 0

    report, _spans = obs_report.fold([summary["telemetry"]["events"]])
    fx = report["fetch"]
    assert fx["tiles"] == summary["tiles"]
    assert fx["transfers_per_tile"] == 1.0
    assert fx["packed"] is True
    assert fx["effective_gb_per_s"] is not None
    assert fx["bytes"] == summary["fetch"]["bytes"] > 0

    prom = open(summary["telemetry"]["metrics"]).read()
    for name in ("lt_fetch_bytes_total", "lt_fetch_transfers_total",
                 "lt_fetch_wait_seconds_total", "lt_fetch_backlog_max"):
        assert name in prom


def test_fetch_value_lint_catches_drift(tmp_path):
    """The value-level fetch lint: negative counters, transfers below
    tiles, and an unpack_s that exceeds the scope's write stage are all
    producer drift a type check alone cannot catch."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    from check_events_schema import main as lint_main

    from land_trendr_tpu.obs.events import EventLog

    def write_events(path, fetch_fields, stage_s):
        log = EventLog(path)
        log.run_start(
            fingerprint="x", process_index=0, process_count=1,
            tiles_total=1, tiles_todo=1, tiles_skipped_resume=0,
            mesh_devices=1, impl="xla",
        )
        log.emit("fetch", **fetch_fields)
        log.emit(
            "run_done", status="ok", tiles_done=1, pixels=1, wall_s=1.0,
            px_per_s=1.0, fit_rate=1.0, stage_s=stage_s,
        )
        log.close()

    ok = dict(tiles=2, transfers=2, bytes=10, pack_s=0.1, wait_s=0.1,
              unpack_s=0.1)
    good = str(tmp_path / "good")
    write_events(os.path.join(good, "events.jsonl"), ok, {"write_s": 0.5})
    assert lint_main([good]) == 0

    for name, bad, stage in (
        ("neg", {**ok, "bytes": -1}, {"write_s": 0.5}),
        ("short", {**ok, "transfers": 1}, {"write_s": 0.5}),
        ("unpack", ok, {"write_s": 0.01}),
    ):
        d = str(tmp_path / name)
        write_events(os.path.join(d, "events.jsonl"), bad, stage)
        assert lint_main([d]) == 1, name


def test_fetch_bench_smoke(tmp_path):
    """Tier-1 fetch_bench smoke (the satellite next to feed_bench's): the
    bench runs end to end, parity holds, and the packed path moves one
    transfer per tile."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import fetch_bench

    out = str(tmp_path / "fetch_smoke.json")
    assert fetch_bench.main(["--smoke", "--out", out]) == 0
    rep = json.load(open(out))
    assert rep["parity"]["ok"] is True
    assert rep["workload"]["transfers_per_tile_packed"] == 1
    assert rep["workload"]["artifact_products"] >= 8
    assert rep["speedup_packed_sync"] > 0
    assert rep["speedup_packed_async"] > 0
