"""Test harness configuration.

Tests run on a *virtual 8-device CPU mesh* so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-run-compiles the
multi-chip path via ``__graft_entry__.dryrun_multichip``).  The environment
variables must be set before jax is imported anywhere, hence this top-level
conftest.  x64 is enabled so the JAX kernel can be parity-checked against
the float64 CPU oracle (SURVEY.md §7 step 2: exact-parity mode in float64 on
CPU; float32 on TPU with documented tolerance).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_ENABLE_X64"] = "1"

# The container's sitecustomize preloads jax (axon TPU platform) at
# interpreter startup, before this conftest runs — so the env vars above are
# not enough on their own.  Backends initialise lazily, though, so flipping
# the config here (before any device is touched) still selects the virtual
# 8-device CPU mesh.
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import zlib

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: minutes-scale cases (spawned fleet processes) — tier-1 "
        "deselects with -m 'not slow'; CLI gate runs carry them",
    )


@pytest.fixture()
def rng(request):
    """Per-test deterministic stream: seed derives from the test's own id, so
    a failure reproduces identically when the test is run in isolation."""
    seed = zlib.crc32(request.node.nodeid.encode()) ^ 20260729
    return np.random.default_rng(seed)
