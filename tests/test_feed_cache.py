"""Feed-path decode subsystem (io/blockcache): cache parity + readahead.

The contract under test is the tentpole's acceptance bar: cached and
uncached window reads are BYTE-IDENTICAL across the full layout matrix —
compression (none / deflate / raw-deflate / LZW) × predictor ×
stripped/tiled — with the cache enabled, disabled, and squeezed to a
1-block budget (eviction churn); plus the readahead/prefetch seam, the
driver wiring (``feed_cache`` telemetry event through a real lazy run),
and the ``tools/feed_bench.py`` smoke mode.
"""

import json
import os
import zlib

import numpy as np
import pytest

from land_trendr_tpu.io import blockcache, native
from land_trendr_tpu.io import geotiff as gt
from land_trendr_tpu.io.geotiff import (
    read_geotiff,
    read_geotiff_window,
    write_geotiff,
)


@pytest.fixture(autouse=True)
def _unconfigured_blockcache():
    """Every test starts AND leaves the process-wide subsystem in the
    unconfigured (legacy) state, so ordering cannot leak cache entries or
    worker settings between tests."""
    blockcache.configure(0, None)
    blockcache.cache_clear()
    yield
    blockcache.configure(0, None)
    blockcache.cache_clear()


def _raw_deflate_writer(monkeypatch):
    """Make write_geotiff emit RAW deflate block payloads (no zlib
    wrapper) — the nonstandard-but-seen-in-the-wild stream the reader's
    ``zlib.decompress(buf, -15)`` fallback exists for.  The native encode
    path is disabled so the Python ``zlib.compress`` seam is the one that
    runs."""
    monkeypatch.setattr(native, "available", lambda: False)

    def raw_compress(data, level=6):
        c = zlib.compressobj(level, zlib.DEFLATED, -15)
        return c.compress(data) + c.flush()

    monkeypatch.setattr(gt.zlib, "compress", raw_compress)


#: windows chosen to straddle the 37-px tile / 64-row strip grid, repeat
#: (hit path), touch edges, and cover single rows/cols
_WINDOWS = (
    (0, 0, 96, 90),
    (10, 17, 50, 41),
    (10, 17, 50, 41),  # revisit: served from cache when enabled
    (63, 30, 33, 60),
    (95, 0, 1, 90),
    (0, 89, 96, 1),
)


@pytest.mark.parametrize("layout", ["tiled", "strips"])
@pytest.mark.parametrize("predictor", [True, False])
@pytest.mark.parametrize(
    "compress", ["none", "deflate", "raw-deflate", "lzw"]
)
def test_window_parity_matrix(tmp_path, rng, monkeypatch, compress, predictor, layout):
    """Byte-identity vs the full read, for every (compression × predictor
    × layout) × (cache off / cache on / 1-block budget) combination."""
    if compress == "raw-deflate":
        _raw_deflate_writer(monkeypatch)
        write_compress = "deflate"
    else:
        write_compress = compress
    p = str(tmp_path / "m.tif")
    arr = rng.integers(0, 43000, size=(96, 90), dtype=np.uint16)
    write_geotiff(
        p,
        arr,
        compress=write_compress,
        tile=37 if layout == "tiled" else None,
        predictor=predictor,
    )
    full, _, _ = read_geotiff(p)
    assert np.array_equal(full, arr)

    one_block = 37 * 37 * 2 if layout == "tiled" else 64 * 90 * 2
    for budget, workers in ((0, None), (64 << 20, 0), (one_block, 2)):
        blockcache.configure(budget, workers)
        blockcache.cache_clear()
        for y0, x0, h, w in _WINDOWS:
            got = read_geotiff_window(p, y0, x0, h, w)
            assert got.dtype == arr.dtype
            assert np.array_equal(got, full[y0 : y0 + h, x0 : x0 + w]), (
                compress, predictor, layout, budget, (y0, x0, h, w),
            )


def test_cache_hits_evictions_and_stats(tmp_path, rng):
    p = str(tmp_path / "c.tif")
    arr = rng.integers(0, 1000, size=(128, 128), dtype=np.uint16)
    write_geotiff(p, arr, compress="deflate", tile=64)
    blockcache.configure(64 << 20, 0)
    base = blockcache.stats_snapshot()
    read_geotiff_window(p, 0, 0, 128, 128)   # 4 blocks, all cold
    read_geotiff_window(p, 0, 0, 128, 128)   # all 4 from cache
    d = blockcache.stats_delta(base)
    assert d["misses"] == 4 and d["hits"] == 4
    assert d["evictions"] == 0
    assert d["decode_s"] >= 0.0
    assert blockcache.cache_bytes() == 4 * 64 * 64 * 2

    # 1-block budget: every insert evicts the previous block (churn), and
    # reads stay correct (covered by the matrix) while never exceeding it
    blockcache.configure(64 * 64 * 2, 0)
    assert blockcache.cache_bytes() <= 64 * 64 * 2  # shrink evicted down
    base = blockcache.stats_snapshot()
    read_geotiff_window(p, 0, 0, 128, 128)
    d = blockcache.stats_delta(base)
    assert d["evictions"] >= 3
    assert blockcache.cache_bytes() <= 64 * 64 * 2


def test_cache_keys_on_mtime_and_size(tmp_path, rng):
    """A rewritten file must not serve the previous contents' blocks."""
    p = str(tmp_path / "r.tif")
    a1 = rng.integers(0, 100, size=(64, 64), dtype=np.uint16)
    a2 = (a1 + 7).astype(np.uint16)
    blockcache.configure(64 << 20, 0)
    write_geotiff(p, a1, compress="deflate", tile=64)
    os.utime(p, ns=(1_000_000_000, 1_000_000_000))
    assert np.array_equal(read_geotiff_window(p, 0, 0, 64, 64), a1)
    write_geotiff(p, a2, compress="deflate", tile=64)
    os.utime(p, ns=(2_000_000_000, 2_000_000_000))
    assert np.array_equal(read_geotiff_window(p, 0, 0, 64, 64), a2)


def test_disabled_cache_stores_nothing(tmp_path, rng):
    p = str(tmp_path / "d.tif")
    write_geotiff(
        p, rng.integers(0, 9, size=(64, 64), dtype=np.uint16), tile=64
    )
    read_geotiff_window(p, 0, 0, 64, 64)  # unconfigured (autouse fixture)
    assert blockcache.cache_bytes() == 0
    assert not blockcache.cache_enabled()


def test_prefetch_window_populates_cache_and_counts_readahead(tmp_path, rng):
    from land_trendr_tpu.runtime.stack import LazyBandCube

    paths = []
    arrs = []
    for k in range(3):
        p = str(tmp_path / f"y{k}.tif")
        a = rng.integers(0, 2000, size=(128, 120), dtype=np.uint16)
        write_geotiff(p, a, compress="deflate", tile=64)
        paths.append(p)
        arrs.append(a)
    cube = LazyBandCube(paths, (128, 120), np.uint16)

    # serial config: prefetch is OFF (nothing to overlap), hint refused
    blockcache.configure(64 << 20, 1)
    assert cube.prefetch_window(0, 0, 70, 70) == 0

    blockcache.configure(64 << 20, 2)
    base = blockcache.stats_snapshot()
    queued = cube.prefetch_window(0, 0, 70, 70)
    assert queued == 3
    # drain the decode pool: prefetch is fire-and-forget, so join by
    # waiting until the hinted blocks landed
    import time

    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if blockcache.stats_delta(base)["readahead_blocks"] >= 3 * 4:
            break
        time.sleep(0.01)
    d = blockcache.stats_delta(base)
    assert d["readahead_blocks"] == 3 * 4  # 2x2 blocks x 3 years

    win = cube[:, 0:70, 0:70]  # served from the prefetched blocks
    assert np.array_equal(win, np.stack([a[0:70, 0:70] for a in arrs]))
    d = blockcache.stats_delta(base)
    assert d["readahead_hits"] == 3 * 4
    assert d["hits"] >= 3 * 4
    # a second real read hits the same entries but must not recount them
    cube[:, 0:70, 0:70]
    assert blockcache.stats_delta(base)["readahead_hits"] == 3 * 4


def test_feed_bench_smoke_mode(tmp_path):
    """The tier-1 smoke mode: tiny scene, seconds, artifact written, the
    cached configuration byte-checked against full reads."""
    from tools import feed_bench

    out = tmp_path / "FEED_smoke.json"
    ev_dir = tmp_path / "ev"
    rc = feed_bench.main([
        "--smoke", "--size", "256", "--years", "2", "--window", "96",
        "--out", str(out), "--events-dir", str(ev_dir),
    ])
    assert rc == 0
    rec = json.loads(out.read_text())
    assert rec["parity_ok"] is True
    assert rec["scene"]["windows"] > 0
    for section in (
        "baseline_serial_uncached", "parallel_uncached", "cached_parallel",
        "cached_parallel_readahead",
    ):
        assert rec[section]["wall_s"] > 0
    assert rec["cache_stats"]["hits"] > 0  # straddled windows revisit blocks
    assert rec["speedup_cached"] > 0

    # the emitted events are schema-valid and fold with the cache counters
    from tools import check_events_schema, obs_report

    assert check_events_schema.main([str(ev_dir)]) == 0
    report, _ = obs_report.fold(
        [str(ev_dir / "events.jsonl")], schema_errors={}
    )
    assert report["feed_cache"]["hits"] == rec["cache_stats"]["hits"]
    assert report["feed_cache"]["decode_s"] >= 0
    assert report["feed_cache"]["hit_rate"] is not None


def _write_c2_year(dirpath, year, arrs, rng):
    """One C2-named acquisition: SR_B5 (nir), SR_B7 (swir2), QA_PIXEL."""
    names = {
        "nir": f"LC08_L2SP_045030_{year}0715_{year}0912_02_T1_SR_B5.TIF",
        "swir2": f"LC08_L2SP_045030_{year}0715_{year}0912_02_T1_SR_B7.TIF",
        "qa": f"LC08_L2SP_045030_{year}0715_{year}0912_02_T1_QA_PIXEL.TIF",
    }
    for band, fname in names.items():
        # STRIPS of 64 rows with a 48-px driver tile: adjacent tile rows
        # share strips, so the run produces real cache hits
        write_geotiff(
            os.path.join(dirpath, fname),
            arrs[band],
            compress="deflate",
            tile=None,
        )


def test_driver_lazy_run_emits_feed_cache_event(tmp_path, rng):
    """End-to-end: a lazy C2 run with telemetry emits a feed_cache event
    whose counters show real cache traffic, and the stream lints clean."""
    from land_trendr_tpu.obs.events import iter_events, validate_events_file
    from land_trendr_tpu.runtime import RunConfig, run_stack
    from land_trendr_tpu.runtime.stack import open_stack_dir_c2_lazy

    stack_dir = tmp_path / "c2"
    stack_dir.mkdir()
    h, w = 96, 96
    for year in (2000, 2001, 2002):
        qa = np.zeros((h, w), dtype=np.uint16)
        qa[:2] = 1 << 3  # a little cloud
        _write_c2_year(
            str(stack_dir),
            year,
            {
                "nir": rng.integers(7273, 43636, (h, w), dtype=np.uint16),
                "swir2": rng.integers(7273, 43636, (h, w), dtype=np.uint16),
                "qa": qa,
            },
            rng,
        )
    stack = open_stack_dir_c2_lazy(str(stack_dir), bands=("nir", "swir2"))
    cfg = RunConfig(
        index="nbr",
        tile_size=48,
        workdir=str(tmp_path / "work"),
        out_dir=str(tmp_path / "out"),
        telemetry=True,
        feed_cache_mb=64,
        decode_workers=2,
    )
    summary = run_stack(stack, cfg)
    assert "feed_cache" in summary
    assert summary["feed_cache"]["hits"] > 0  # strips straddle tile rows

    ev_file = summary["telemetry"]["events"]
    assert validate_events_file(ev_file) == []
    fc = [r for r in iter_events(ev_file) if r["ev"] == "feed_cache"]
    assert len(fc) == 1
    assert fc[0]["hits"] == summary["feed_cache"]["hits"]
    assert fc[0]["misses"] == summary["feed_cache"]["misses"]

    from tools import check_events_schema, obs_report

    assert check_events_schema.main([cfg.workdir]) == 0
    report, _ = obs_report.fold([ev_file], schema_errors={})
    assert report["feed_cache"]["hits"] == fc[0]["hits"]

    # metrics exposition carries the lt_feed_* family
    prom = (tmp_path / "work" / "metrics.prom").read_text()
    assert "lt_feed_cache_hits_total" in prom
    assert "lt_feed_decode_seconds_total" in prom


def test_check_events_schema_flags_bad_feed_cache(tmp_path):
    """The CI lint catches value-level feed_cache drift the type schema
    cannot (negative counters, hits exceeding readahead inserts)."""
    from tools import check_events_schema

    good = {
        "ev": "run_start", "t_wall": 1.0, "t_mono": 1.0, "schema": 1,
        "fingerprint": "f", "pid": 1, "host": "h", "process_index": 0,
        "process_count": 1, "tiles_total": 1, "tiles_todo": 1,
        "tiles_skipped_resume": 0, "mesh_devices": 1, "impl": "xla",
    }
    bad_fc = {
        "ev": "feed_cache", "t_wall": 1.0, "t_mono": 1.0,
        "hits": -3, "misses": 0, "evictions": 0, "decode_s": 0.1,
        "readahead_blocks": 1, "readahead_hits": 5,
    }
    p = tmp_path / "events.jsonl"
    p.write_text(json.dumps(good) + "\n" + json.dumps(bad_fc) + "\n")
    assert check_events_schema.main([str(p)]) == 1
    errs = check_events_schema.feed_cache_value_errors(bad_fc, 2)
    assert any("negative" in e for e in errs)
    assert any("exceeds" in e for e in errs)

    ok_fc = dict(bad_fc, hits=3, readahead_hits=1)
    p.write_text(json.dumps(good) + "\n" + json.dumps(ok_fc) + "\n")
    assert check_events_schema.main([str(p)]) == 0
