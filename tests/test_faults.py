"""Robustness suite: deterministic fault injection and the hardening it
exposes (ISSUE 5).

Covers the injector itself (schedule parsing, seeded determinism), every
recovery path it drives — retry ladder with backoff, feed retry, poisoned
cached blocks, packed-fetch demotion, tile quarantine, torn manifest
artifacts, the stall watchdog, the multihost merge's dead-peer timeout —
the CLI exit-code contract (2 config / 3 quarantined / 4 stall), a true
SIGKILL crash-resume round trip, and the ``tools/fault_soak.py --smoke``
acceptance gate (every seam fired → artifacts byte-identical).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
from land_trendr_tpu.runtime import (
    RunConfig,
    StallError,
    TileManifest,
    TileRetriesExhausted,
    run_stack,
    stack_from_synthetic,
)
from land_trendr_tpu.runtime import faults

SPEC = SceneSpec(width=48, height=40, year_start=1990, year_end=2013, seed=11)
PARAMS = LTParams(max_segments=4, vertex_count_overshoot=2)


@pytest.fixture(scope="module")
def rstack():
    return stack_from_synthetic(make_stack(SPEC))


def make_cfg(tmp, **kw):
    kw.setdefault("params", PARAMS)
    kw.setdefault("tile_size", 20)
    kw.setdefault("retry_backoff_s", 0.0)
    return RunConfig(
        workdir=os.path.join(tmp, "work"), out_dir=os.path.join(tmp, "out"), **kw
    )


# -- the injector itself ---------------------------------------------------

def test_parse_schedule_grammar():
    p = faults.parse_schedule("seed=9,dispatch@1,fetch.wait@0*3=io,feed%0.5=slow:0.2")
    assert p.seed == 9
    assert p.specs[0] == faults.FaultSpec("dispatch", at=1)
    assert p.specs[1] == faults.FaultSpec("fetch.wait", at=0, times=3, error="io")
    assert p.specs[2] == faults.FaultSpec("feed", prob=0.5, error="slow", arg=0.2)


def test_parse_schedule_rejects_typos():
    with pytest.raises(ValueError, match="unknown fault seam"):
        faults.parse_schedule("dispatchh@1")
    with pytest.raises(ValueError, match="no @index or %probability"):
        faults.parse_schedule("dispatch")
    with pytest.raises(ValueError, match="unknown error kind"):
        faults.FaultPlan(specs=(faults.FaultSpec("dispatch", at=0, error="boom"),))
    # out-of-domain WHEN values are config typos, not schedules:
    # "%25" meaning 25% would fire every invocation; negative indices
    # and zero repeat counts can never mean anything
    with pytest.raises(ValueError, match="outside"):
        faults.parse_schedule("feed.decode%25")
    with pytest.raises(ValueError, match="must be >= 0"):
        faults.parse_schedule("dispatch@-1")
    with pytest.raises(ValueError, match="must be >= 1"):
        faults.parse_schedule("dispatch@0*0")
    # a bad schedule is a CONFIG error: RunConfig rejects it up front
    with pytest.raises(ValueError, match="unknown fault seam"):
        RunConfig(fault_schedule="nope@1")


def test_plan_is_deterministic_and_thread_safe():
    """Probability draws depend only on (seed, seam, index): two plans
    with the same seed fire identically, a different seed differs, and
    concurrent check() calls keep exact per-seam counters."""
    def fires(seed):
        p = faults.FaultPlan(seed, (faults.FaultSpec("dispatch", prob=0.3),))
        out = []
        for i in range(200):
            try:
                p.check("dispatch")
                out.append(False)
            except Exception:
                out.append(True)
        return out

    a, b, c = fires(1), fires(1), fires(2)
    assert a == b
    assert a != c
    assert 20 < sum(a) < 120  # p=0.3 over 200 draws

    from concurrent.futures import ThreadPoolExecutor

    p = faults.FaultPlan(0, (faults.FaultSpec("feed", at=5),))
    with ThreadPoolExecutor(8) as ex:
        res = list(ex.map(lambda _: _try(p), range(100)))
    assert sum(res) == 1  # exactly one invocation fired
    assert p.counts()["feed"] == 100


def _try(plan):
    try:
        plan.check("feed")
        return 0
    except Exception:
        return 1


def test_runconfig_validates_robustness_knobs():
    with pytest.raises(ValueError, match="retry_backoff_s"):
        RunConfig(retry_backoff_s=-1)
    with pytest.raises(ValueError, match="stall_timeout_s"):
        RunConfig(stall_timeout_s=0)
    with pytest.raises(ValueError, match="merge_timeout_s"):
        RunConfig(merge_timeout_s=-5)


# -- recovery paths through the real driver --------------------------------

def test_injected_dispatch_fault_recovers_with_telemetry(tmp_path, rstack):
    """A transient injected dispatch fault rides the retry ladder; the
    stream carries fault_injected + tile_retry and lints clean."""
    from land_trendr_tpu.obs.events import iter_events, validate_events_file
    from tools import check_events_schema

    cfg = make_cfg(tmp_path, fault_schedule="seed=1,dispatch@1", telemetry=True)
    summary = run_stack(rstack, cfg)
    assert summary["pixels"] == 40 * 48
    assert summary["faults_injected"] == [
        {"seam": "dispatch", "index": 1, "error": "runtime"}
    ]
    ev_file = summary["telemetry"]["events"]
    assert validate_events_file(ev_file) == []
    evs = [r["ev"] for r in iter_events(ev_file)]
    assert "fault_injected" in evs and "tile_retry" in evs
    assert check_events_schema.main([cfg.workdir]) == 0


def test_quarantine_continues_and_resume_completes(tmp_path, rstack):
    """A persistently-failing tile is quarantined (manifest record,
    telemetry event, summary list) and the rest of the run completes;
    a resume re-attempts exactly the quarantined tile."""
    from land_trendr_tpu.obs.events import iter_events, validate_events_file

    cfg = make_cfg(
        tmp_path,
        max_retries=1,
        quarantine_tiles=True,
        telemetry=True,
        fault_schedule="seed=1,dispatch@2*2",
    )
    summary = run_stack(rstack, cfg)
    assert summary["tiles_quarantined"] == [2]
    assert summary["pixels"] == 40 * 48 - 160  # all but the 20x8 edge tile

    ev_file = summary["telemetry"]["events"]
    assert validate_events_file(ev_file) == []
    quar = [r for r in iter_events(ev_file) if r["ev"] == "tile_quarantined"]
    assert len(quar) == 1 and quar[0]["tile_id"] == 2
    done = [r for r in iter_events(ev_file) if r["ev"] == "run_done"]
    assert done[-1]["tiles_quarantined"] == 1

    recs = list(TileManifest(cfg.workdir, cfg.fingerprint(rstack)).iter_records())
    failed = [r for r in recs if r["kind"] == "tile_failed"]
    assert len(failed) == 1 and failed[0]["tile_id"] == 2

    # the report consumer folds the robustness events too
    from tools import obs_report

    report, _spans = obs_report.fold([ev_file])
    assert report["quarantined"] == 1
    assert report["faults_injected"] == 2  # dispatch@2*2

    resume = run_stack(rstack, make_cfg(tmp_path))
    assert resume["tiles_skipped_resume"] == 5
    assert resume["pixels"] == 160 and resume["tiles_quarantined"] == []


def test_retries_exhausted_without_quarantine_raises(tmp_path, rstack):
    cfg = make_cfg(tmp_path, max_retries=1, fault_schedule="seed=1,dispatch@0*99")
    with pytest.raises(TileRetriesExhausted, match="failed after 2 attempts"):
        run_stack(rstack, cfg)


def test_feed_fault_retries_then_recovers(tmp_path, rstack):
    """A transient feed error re-enters the retry budget instead of
    aborting (pre-PR a single feed hiccup killed the run)."""
    cfg = make_cfg(tmp_path, fault_schedule="seed=1,feed@1=io")
    summary = run_stack(rstack, cfg)
    assert summary["pixels"] == 40 * 48


def test_feed_fault_exhausted_raises_retries_exhausted(tmp_path, rstack):
    """Persistent feed faults exhaust the budget into the same
    TileRetriesExhausted as device faults (CLI exit 3 — the README
    failure table's 'feed read/decode error' row), with the original
    feed error chained as the cause."""
    cfg = make_cfg(tmp_path, max_retries=1, fault_schedule="seed=1,feed%1.0=io")
    with pytest.raises(TileRetriesExhausted, match="failed after 2 attempts") as ei:
        run_stack(rstack, cfg)
    assert "injected fault at feed#" in str(ei.value.__cause__)


def test_fetch_demotion_event_and_summary(tmp_path, rstack):
    """Repeated packed-fetch failures demote to the per-product path for
    the rest of the run: summary + fetch_demoted event say so, and the
    run still completes every pixel."""
    from land_trendr_tpu.obs.events import iter_events

    cfg = make_cfg(
        tmp_path,
        fetch_packed=True,
        max_retries=4,
        telemetry=True,
        fault_schedule="seed=1,fetch.wait@0*3=io",
    )
    summary = run_stack(rstack, cfg)
    assert summary["pixels"] == 40 * 48
    assert summary["fetch"]["demoted"] is True
    assert summary["fetch"]["packed"] is False  # post-demotion state
    dem = [
        r for r in iter_events(summary["telemetry"]["events"])
        if r["ev"] == "fetch_demoted"
    ]
    assert len(dem) == 1 and dem[0]["failures"] == 3


def test_writer_path_fetch_fault_retried(tmp_path, rstack):
    """On the per-product path (CPU default — also the post-demotion
    state) transfers run inside writer threads: a transient fetch fault
    there gets the same retry budget instead of aborting the run."""
    cfg = make_cfg(tmp_path, fault_schedule="seed=1,fetch.wait@5=io")
    summary = run_stack(rstack, cfg)
    assert summary["pixels"] == 40 * 48
    assert summary["faults_injected"] == [
        {"seam": "fetch.wait", "index": 5, "error": "io"}
    ]


def test_backoff_capped_after_jitter(tmp_path, rstack, monkeypatch):
    """The 30s backoff ceiling is a hard bound operators size
    stall_timeout_s against — jitter must not push a sleep past it."""
    import land_trendr_tpu.runtime.driver as drv

    slept = []
    monkeypatch.setattr(drv.time, "sleep", lambda s: slept.append(s))
    cfg = make_cfg(
        tmp_path, retry_backoff_s=25.0, max_retries=3,
        fault_schedule="seed=1,dispatch@0*3",
    )
    run_stack(rstack, cfg)
    assert slept and all(s <= drv._BACKOFF_CAP_S for s in slept)


def test_stall_watchdog_aborts_hung_wait(tmp_path, rstack):
    """A hung device wait (injected interruptible hang) trips the
    watchdog: StallError, a schema-valid stall event, and an aborted
    run_done in the stream instead of an infinite hang."""
    from land_trendr_tpu.obs.events import iter_events, validate_events_file

    cfg = make_cfg(
        tmp_path,
        telemetry=True,
        stall_timeout_s=1.0,
        fault_schedule="seed=1,compute.wait@1=hang:60",
    )
    t0 = time.monotonic()
    with pytest.raises(StallError, match="no tile progress"):
        run_stack(rstack, cfg)
    assert time.monotonic() - t0 < 30  # aborted, not the 60s hang

    from land_trendr_tpu.obs.events import events_path

    ev_file = events_path(cfg.workdir)
    assert validate_events_file(ev_file) == []
    evs = list(iter_events(ev_file))
    stalls = [r for r in evs if r["ev"] == "stall"]
    assert len(stalls) == 1 and stalls[0]["timeout_s"] == 1.0
    assert stalls[0]["idle_s"] >= 1.0
    assert [r for r in evs if r["ev"] == "run_done"][-1]["status"] == "aborted"


def test_corrupt_cached_block_bypassed(tmp_path, rng):
    """A poisoned decoded-block cache entry is invalidated and re-decoded
    from the file — the window read returns correct bytes, never raises."""
    from land_trendr_tpu.io import blockcache
    from land_trendr_tpu.io.geotiff import read_geotiff_window, write_geotiff

    p = str(tmp_path / "scene.tif")
    arr = rng.integers(0, 30000, (96, 96), dtype=np.int16)
    write_geotiff(p, arr, compress="deflate")
    blockcache.configure(budget_bytes=32 << 20, workers=1)
    try:
        ref = read_geotiff_window(p, 8, 8, 40, 40)  # populates the cache
        base = blockcache.stats_snapshot()
        plan = faults.activate(
            faults.parse_schedule("seed=1,cache.corrupt@0")
        )
        got = read_geotiff_window(p, 8, 8, 40, 40)  # first cached hit poisoned
        faults.deactivate()
        np.testing.assert_array_equal(got, ref)
        delta = blockcache.stats_delta(base)
        assert delta["corrupt_dropped"] == 1
        assert plan.injected()[0][0] == "cache.corrupt"
    finally:
        faults.deactivate()
        blockcache.configure(budget_bytes=0, workers=None)


def test_torn_artifact_detected_on_resume(tmp_path, rstack):
    """A manifest-recorded tile whose artifact was torn post-rename (the
    crash window tmp+rename cannot close) counts as not-done on resume
    and is recomputed — resume never crashes on the unreadable file."""
    cfg = make_cfg(tmp_path, fault_schedule="seed=1,manifest.torn@1")
    with pytest.raises(OSError, match="torn artifact"):
        run_stack(rstack, cfg)
    # the torn tile IS in the manifest jsonl, but unreadable on disk
    resume = run_stack(rstack, make_cfg(tmp_path))
    assert resume["pixels"] > 0  # the torn tile (at least) recomputed
    total = run_stack(rstack, make_cfg(tmp_path))  # now everything is durable
    assert total["tiles_skipped_resume"] == 6 and total["pixels"] == 0


def test_truncated_artifact_not_counted_done(tmp_path, rstack):
    """Direct satellite check: truncating a perfectly-recorded artifact
    makes open(resume=True) recompute it instead of crashing later."""
    cfg = make_cfg(tmp_path)
    run_stack(rstack, cfg)
    manifest = TileManifest(cfg.workdir, cfg.fingerprint(rstack))
    p = manifest.tile_path(3)
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    summary = run_stack(rstack, cfg)
    assert summary["tiles_skipped_resume"] == 5  # tile 3 recomputed
    with np.load(p) as z:
        assert len(z.files) > 0  # healthy again


def test_merge_peer_fault_times_out_partial(tmp_path):
    """The merge.peer seam makes every tail probe read not-terminal: the
    bounded wait expires and the primary returns the partial merge — the
    dead-peer semantics, deterministic."""
    from land_trendr_tpu.obs.events import EventLog, events_path
    from land_trendr_tpu.parallel.multihost import merge_host_event_logs

    wd = str(tmp_path)
    for i in range(2):
        with EventLog(events_path(wd, i, 2)) as log:
            log.run_start(
                fingerprint="f" * 16, process_index=i, process_count=2,
                tiles_total=2, tiles_todo=2, tiles_skipped_resume=0,
                mesh_devices=1, impl="xla",
            )
            log.emit(
                "run_done", status="ok", tiles_done=1, pixels=10,
                wall_s=0.1, px_per_s=100.0, fit_rate=1.0,
            )
    faults.activate(faults.parse_schedule("seed=1,merge.peer%1.0"))
    try:
        t0 = time.monotonic()
        merged = merge_host_event_logs(wd, expect_hosts=2, timeout_s=0.4, poll_s=0.05)
        assert 0.3 < time.monotonic() - t0 < 5.0  # waited out the bound
        assert len(merged) == 2  # partial merge still folds what exists
    finally:
        faults.deactivate()
    # without the fault the same merge resolves immediately
    t0 = time.monotonic()
    merged = merge_host_event_logs(wd, expect_hosts=2, timeout_s=5.0, poll_s=0.05)
    assert time.monotonic() - t0 < 1.0
    assert [m["status"] for m in merged] == ["ok", "ok"]


def test_merge_peer_seam_fires_through_driver(tmp_path, rstack, monkeypatch):
    """--fault-schedule merge.peer must reach the multihost merge through
    run_stack itself: the plan stays armed past telemetry close until the
    merge completes (it previously disarmed in the loop's finally, making
    the seam dead on the driver path), then disarms."""
    import land_trendr_tpu.runtime.driver as drv

    monkeypatch.setattr(drv.jax, "process_count", lambda: 2)
    monkeypatch.setattr(drv.jax, "process_index", lambda: 0)
    cfg = make_cfg(
        tmp_path, telemetry=True, merge_timeout_s=0.5,
        fault_schedule="seed=1,merge.peer%1.0",
    )
    summary = run_stack(rstack, cfg)
    assert any(f["seam"] == "merge.peer" for f in summary["faults_injected"])
    # the dead-peer semantics: no file ever probes terminal, so the
    # bounded wait expires into the partial merge of what exists (p0)
    assert len(summary["telemetry"]["hosts"]) == 1
    assert faults.active() is None  # disarmed after the merge


def test_merge_timeout_override_used(tmp_path, rstack, monkeypatch):
    """RunConfig.merge_timeout_s reaches merge_host_event_logs (the
    multihost satellite); None keeps the wall-derived heuristic."""
    import land_trendr_tpu.runtime.driver as drv

    seen = {}

    def fake_merge(workdir, expect_hosts, timeout_s, poll_s, newer_than):
        seen["timeout_s"] = timeout_s
        return []

    monkeypatch.setattr(
        "land_trendr_tpu.parallel.multihost.merge_host_event_logs", fake_merge
    )
    monkeypatch.setattr(drv.jax, "process_count", lambda: 2)
    monkeypatch.setattr(drv.jax, "process_index", lambda: 0)
    cfg = make_cfg(tmp_path, telemetry=True, merge_timeout_s=123.0)
    run_stack(rstack, cfg)
    assert seen["timeout_s"] == 123.0


# -- CLI exit-code contract ------------------------------------------------

@pytest.fixture(scope="module")
def stack_dir(tmp_path_factory):
    from land_trendr_tpu.cli import main

    d = str(tmp_path_factory.mktemp("faultcli") / "stack")
    assert main([
        "synth", d, "--size", "24", "--year-start", "1990", "--year-end", "2013",
    ]) == 0
    return d


def _seg(stack_dir, tmp, *extra):
    from land_trendr_tpu.cli import main

    return main([
        "segment", stack_dir, "--tile-size", "20",
        "--workdir", os.path.join(tmp, "w"), "--out-dir", os.path.join(tmp, "o"),
        "--max-segments", "4", "--vertex-count-overshoot", "2",
        "--retry-backoff-s", "0", *extra,
    ])


def test_cli_exit_2_bad_fault_schedule(stack_dir, tmp_path, capsys):
    assert _seg(stack_dir, str(tmp_path), "--fault-schedule", "bogus@1") == 2
    assert "unknown fault seam" in capsys.readouterr().err


def test_cli_exit_3_quarantine(stack_dir, tmp_path, capsys):
    rc = _seg(
        stack_dir, str(tmp_path),
        "--fault-schedule", "seed=1,dispatch%1.0",
        "--quarantine-tiles", "--max-retries", "1",
    )
    assert rc == 3
    out = capsys.readouterr()
    assert "quarantined" in out.err
    doc = json.loads(out.out)
    assert doc["outputs"] is None  # assembly skipped on an incomplete manifest
    assert doc["summary"]["tiles_quarantined"]


def test_cli_exit_3_retries_exhausted(stack_dir, tmp_path, capsys):
    rc = _seg(
        stack_dir, str(tmp_path),
        "--fault-schedule", "seed=1,dispatch%1.0", "--max-retries", "1",
    )
    assert rc == 3
    assert "failed after 2 attempts" in capsys.readouterr().err


def test_cli_exit_4_stall(stack_dir, tmp_path, capsys):
    rc = _seg(
        stack_dir, str(tmp_path),
        "--fault-schedule", "seed=1,compute.wait@0=hang:60",
        "--stall-timeout-s", "1.0",
    )
    assert rc == 4
    assert "stall" in capsys.readouterr().err.lower()


# -- crash-resume (SIGKILL) and the soak gate ------------------------------

def _durable_tiles(wd: str) -> int:
    import re

    if not os.path.isdir(wd):
        return 0
    return len([
        f for f in os.listdir(wd) if re.fullmatch(r"tile_\d+\.npz", f)
    ])


def test_crash_resume_byte_identical(tmp_path):
    """Kill a real driver subprocess mid-run (SIGKILL — no atexit, no
    finally), resume in-process, and assert the artifacts are
    byte-identical to an uninterrupted run."""
    from tools.fault_soak import _digest_workdir

    wd = str(tmp_path / "crash_wd")
    worker = os.path.join(os.path.dirname(__file__), "_crash_worker.py")
    proc = subprocess.Popen(
        [sys.executable, worker, wd],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and proc.poll() is None:
            if _durable_tiles(wd) >= 1:
                # first artifact landed; the slow schedule (0.6s per
                # dispatch from tile 2 on) paces the rest — this SIGKILL
                # lands mid-run, between durable tiles
                time.sleep(0.3)
                break
            time.sleep(0.05)
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()

    assert _durable_tiles(wd) >= 1, "worker never persisted a tile"
    rs = stack_from_synthetic(make_stack(SPEC))
    resume_cfg = RunConfig(
        params=PARAMS, tile_size=20, workdir=wd, out_dir=wd + "_o",
        retry_backoff_s=0.0,
    )
    summary = run_stack(rs, resume_cfg)
    assert summary["tiles_skipped_resume"] >= 1  # the crash lost at most
    # the in-flight tiles; everything durable was reused

    clean_wd = str(tmp_path / "clean_wd")
    run_stack(rs, RunConfig(
        params=PARAMS, tile_size=20, workdir=clean_wd,
        out_dir=clean_wd + "_o", retry_backoff_s=0.0,
    ))
    assert _digest_workdir(wd) == _digest_workdir(clean_wd)


def test_fault_soak_smoke(tmp_path):
    """The acceptance gate: every injection seam fired by a seeded
    schedule recovers to byte-identical artifacts (tools/fault_soak.py
    --smoke, run in-process so tier-1 carries it)."""
    from tools.fault_soak import soak

    report = soak(smoke=True, keep=str(tmp_path / "soak"), verbose=False)
    assert report["ok"] is True
    cases = {(r["track"], r["case"]) for r in report["cases"]}
    # one case per seam family, both scene tracks
    assert {"feed_transient", "dispatch_fault", "compute_wait_fault",
            "fetch_wait_fault", "fetch_demotion", "manifest_enospc",
            "manifest_torn", "quarantine"} <= {c for _, c in cases}
    assert {"decode_transient", "cache_corrupt"} <= {
        c for t, c in cases if t == "lazy"
    }
    # the seam-coverage backfill cases (LT011): forced lease steal,
    # dead-peer partial merge, job-start fault + resubmit
    assert {"lease_forced_steal", "merge_peer_partial",
            "job_fault_then_resubmit"} <= {c for _, c in cases}


def test_soak_covered_seams_table_pins_registry_and_schedules():
    """The LT011 satellite pin from the soak's side: the exported
    ``SOAK_COVERED_SEAMS`` data table must name exactly the registered
    ``SEAMS`` — zero silent coverage gaps, zero stale rows — and every
    table entry must actually be ARMED by some schedule in the soak
    source (the ``seam@`` / ``seam%`` arming syntax), so the table
    cannot bless coverage the soak never exercises."""
    import re

    from tools.fault_soak import SOAK_COVERED_SEAMS

    assert len(SOAK_COVERED_SEAMS) == len(set(SOAK_COVERED_SEAMS))
    assert set(SOAK_COVERED_SEAMS) == set(faults.SEAMS)
    soak_src_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "fault_soak.py",
    )
    with open(soak_src_path) as f:
        src = f.read()
    for seam in SOAK_COVERED_SEAMS:
        assert re.search(re.escape(seam) + r"[@%]", src), (
            f"SOAK_COVERED_SEAMS lists {seam!r} but no soak schedule "
            "arms it — back-fill a case before blessing coverage"
        )
