"""Elastic tile lease queue (runtime/leases) + driver integration.

The shared-manifest lease protocol is pure file I/O, so two
:class:`LeaseQueue` instances over one manifest path ARE two hosts —
the unit tests drive claim/steal/renew/flag/speculate races exactly as a
pod would, in milliseconds.  The driver leg runs one real elastic run
and pins byte-identity against the static split plus the telemetry
contract (tile_leased events, lease rollup, schema-clean stream).  The
full multi-process soaks live in ``tools/elastic_soak.py`` (SIGKILL +
late join, slow-host speculation) and ``tools/fault_soak.py``'s
lease-kill case.
"""

import json
import os
import time

import pytest

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.runtime import RunConfig, TileManifest
from land_trendr_tpu.runtime.leases import LeaseQueue
from land_trendr_tpu.runtime import faults

PARAMS = LTParams(max_segments=4, vertex_count_overshoot=2)


@pytest.fixture(scope="module")
def rstack():
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
    from land_trendr_tpu.runtime import stack_from_synthetic

    return stack_from_synthetic(
        make_stack(
            SceneSpec(
                width=48, height=40, year_start=1990, year_end=2013, seed=11
            )
        )
    )


def _manifest(tmp_path, n=4):
    path = str(tmp_path / "manifest.jsonl")
    with open(path, "w") as f:
        f.write('{"kind":"header","fingerprint":"fp","run_id":"r1"}\n')
    return path


def _q(path, owner, ttl=5.0, n=4, done0=None):
    return LeaseQueue(
        path, range(n), ttl_s=ttl, owner=owner, done0=done0
    )


# ---------------------------------------------------------------------------
# protocol unit tests (two queues = two hosts)
# ---------------------------------------------------------------------------


def test_claims_partition_without_overlap(tmp_path):
    path = _manifest(tmp_path)
    a, b = _q(path, "h1:1:a"), _q(path, "h2:2:b")
    wa = a.acquire(2)
    wb = b.acquire(2)
    ids_a = {t for t, _, _ in wa}
    ids_b = {t for t, _, _ in wb}
    assert all(m == "claim" for _, m, _ in wa + wb)
    assert ids_a | ids_b == {0, 1, 2, 3}
    assert not ids_a & ids_b


def test_same_generation_race_first_writer_wins(tmp_path):
    """Both hosts append a gen-0 claim for the same tile; log order is
    the arbiter, and the loser observes the loss on re-read."""
    path = _manifest(tmp_path, n=1)
    a, b = _q(path, "h1:1:a", n=1), _q(path, "h2:2:b", n=1)
    rec = {
        "kind": "lease", "tile_id": 0, "gen": 0, "ttl_s": 5.0,
        "t_wall": time.time(), "mode": "claim",
    }
    with open(path, "a") as f:
        f.write(json.dumps({**rec, "owner": "h1:1:a"}) + "\n")
        f.write(json.dumps({**rec, "owner": "h2:2:b"}) + "\n")
    assert b.acquire(1) == []  # b's own record lost (a's is first)
    a.refresh()
    with a._lock:
        assert a._leases[0].owner == "h1:1:a"


def test_expired_lease_is_stolen_and_renewal_prevents_it(tmp_path):
    path = _manifest(tmp_path)
    a, b = _q(path, "h1:1:a", ttl=0.2), _q(path, "h2:2:b", ttl=0.2)
    a.acquire(2)
    b.acquire(2)
    time.sleep(0.3)
    a.renew(min_interval=0.0)  # a's leases live on; b's expire
    stolen = a.acquire(4)
    assert {m for _, m, _ in stolen} == {"steal"}
    assert len(stolen) == 2
    assert a.stats()["stolen"] == 2
    # the steal claimed a successor generation
    assert all(lease.gen == 1 for _, _, lease in stolen)


def test_done_record_supersedes_every_lease(tmp_path):
    path = _manifest(tmp_path, n=2)
    a = _q(path, "h1:1:a", ttl=0.01, n=2)
    a.acquire(2)
    # a live done record (appended after a's bootstrap) retires the tile
    with open(path, "a") as f:
        f.write('{"kind":"tile","tile_id":0,"owner":"h1:1:a"}\n')
    time.sleep(0.05)
    a.refresh()
    assert 0 not in {t for t, _, _ in a.acquire(2)}
    # a LATE JOINER seeds done0 from manifest.open's artifact-verified
    # set (the documented contract: historical done records are trusted
    # only when their artifact verified — torn-artifact resumes recompute)
    b = _q(path, "h2:2:b", ttl=0.01, n=2, done0={0})
    won = b.acquire(2)
    assert {t for t, _, _ in won} == {1}  # 0 is done, never re-claimed
    assert not b.run_complete()
    with open(path, "a") as f:
        f.write('{"kind":"tile","tile_id":1,"owner":"h2:2:b"}\n')
    assert b.run_complete()


def test_release_makes_tiles_immediately_claimable(tmp_path):
    path = _manifest(tmp_path, n=2)
    a, b = _q(path, "h1:1:a", ttl=60.0, n=2), _q(path, "h2:2:b", ttl=60.0, n=2)
    a.acquire(2)
    assert b.acquire(2) == []  # all leased, TTL far away
    assert a.release_held("aborted") == 2
    won = b.acquire(2)
    assert len(won) == 2  # no TTL wait after a clean release
    assert all(m == "claim" for _, m, _ in won)


def test_flag_enables_speculation_for_idle_peer_only(tmp_path):
    path = _manifest(tmp_path, n=2)
    a, b = _q(path, "h1:1:a", ttl=60.0, n=2), _q(path, "h2:2:b", ttl=60.0, n=2)
    a.acquire(2)
    # nothing flagged: an idle peer with speculate=True still gets nothing
    assert b.acquire(1, speculate=True) == []
    assert a.flag(1) is True
    won = b.acquire(1, speculate=True)
    assert [(t, m) for t, m, _ in won] == [(1, "spec")]
    assert won[0][2].gen == 1
    # at most ONE speculative claim per acquisition
    assert a.flag(0) is True
    assert len(b.acquire(4, speculate=True)) <= 1
    # speculative win accounting: b's done record lands first
    with open(path, "a") as f:
        f.write('{"kind":"tile","tile_id":1,"owner":"h2:2:b"}\n')
        f.write('{"kind":"tile","tile_id":1,"owner":"h1:1:a"}\n')
    b.refresh()  # stats() is pure bookkeeping; the fold reads the log
    assert b.stats()["spec_wins"] == 1


def test_flag_requires_holding_the_lease(tmp_path):
    path = _manifest(tmp_path, n=2)
    a, b = _q(path, "h1:1:a", n=2), _q(path, "h2:2:b", n=2)
    a.acquire(1)
    assert b.flag(0) is False  # not b's lease
    assert b.flag(1) is False  # nobody holds it


def test_torn_trailing_line_is_carried_not_fatal(tmp_path):
    path = _manifest(tmp_path)
    a = _q(path, "h1:1:a")
    with open(path, "a") as f:
        f.write('{"kind":"lease","tile_id"')  # a peer died mid-append
    a.refresh()
    assert a.stats()["malformed_lines"] == 0  # carried, not condemned
    # the NEXT append lands right behind the torn bytes with no newline
    # between them: that one record is mashed and lost to every reader —
    # which costs the claim one round (self-healing: the un-won tile is
    # simply claimed again next acquire), never a crash or a stuck tile
    won = a.acquire(4)
    won2 = a.acquire(4)
    ids = {t for t, _, _ in won} | {t for t, _, _ in won2}
    assert ids == {0, 1, 2, 3}
    assert a.stats()["malformed_lines"] == 1  # the mashed line, counted


def test_lease_expire_fault_forces_steal_under_living_owner(tmp_path):
    """The lease.expire behavioral seam: a live foreign lease reads as
    expired, driving the double-execution race deterministically."""
    path = _manifest(tmp_path, n=1)
    a, b = _q(path, "h1:1:a", ttl=60.0, n=1), _q(path, "h2:2:b", ttl=60.0, n=1)
    a.acquire(1)
    faults.activate(faults.parse_schedule("seed=1,lease.expire@0"))
    try:
        won = b.acquire(1)
    finally:
        faults.deactivate()
    assert [(t, m) for t, m, _ in won] == [(0, "steal")]


def test_lease_acquire_fault_raises(tmp_path):
    path = _manifest(tmp_path)
    a = _q(path, "h1:1:a", n=4)
    faults.activate(faults.parse_schedule("seed=1,lease.acquire@0=io"))
    try:
        with pytest.raises(OSError):
            a.acquire(2)
        assert len(a.acquire(2)) == 2  # next invocation proceeds
    finally:
        faults.deactivate()


def test_failed_record_is_terminal_this_run_only(tmp_path):
    path = _manifest(tmp_path, n=2)
    # historical tile_failed (present at construction) does NOT block —
    # resume semantics re-attempt quarantined tiles
    with open(path, "a") as f:
        f.write('{"kind":"tile_failed","tile_id":0,"attempts":3,"error":"x"}\n')
    a = _q(path, "h1:1:a", n=2)
    assert {t for t, _, _ in a.acquire(2)} == {0, 1}
    # a LIVE tile_failed (a sibling quarantining during this run) is
    # terminal run-wide: tile 0 done + tile 1 failed = run complete
    with open(path, "a") as f:
        f.write('{"kind":"tile_failed","tile_id":1,"attempts":3,"error":"x"}\n')
    a.refresh()
    assert a.stats()["failed"] == 1
    assert not a.run_complete()
    with open(path, "a") as f:
        f.write('{"kind":"tile","tile_id":0,"owner":"h1:1:a"}\n')
    assert a.run_complete()


# ---------------------------------------------------------------------------
# manifest torn-tail hardening (satellite)
# ---------------------------------------------------------------------------


def test_manifest_open_and_iter_skip_torn_tail(tmp_path):
    import numpy as np

    man = TileManifest(str(tmp_path / "wd"), "fp-torn")
    assert man.open(resume=False) == set()
    for tid in range(3):
        man.record(tid, {"a": np.arange(4, dtype=np.float32)}, {"h": 1})
    done_clean = man.open(resume=True)
    assert done_clean == {0, 1, 2}
    # a peer dies mid-append: torn trailing line, no newline
    with open(man.path, "a") as f:
        f.write('{"kind":"tile","tile_id":999,"h":20,"w"')
    done = man.open(resume=True)
    assert done == done_clean
    assert man.skipped_lines == 1
    recs = list(man.iter_records())
    assert man.skipped_lines == 1
    assert all(r.get("tile_id") != 999 for r in recs)
    # mid-file burial: more appends after the torn line — still one
    # skipped line, the later record still read
    with open(man.path, "a") as f:
        f.write('\n{"kind":"clock_anchor","run_id":"r","host":"h",'
                '"process_index":0,"pid":1,"anchor_wall":1.0,'
                '"anchor_mono":1.0}\n')
    recs = list(man.iter_records())
    assert any(r.get("kind") == "clock_anchor" for r in recs)


def test_manifest_open_requires_readable_header(tmp_path):
    wd = tmp_path / "wd"
    wd.mkdir()
    # a manifest whose only content is garbage: the fingerprint guard
    # must not be silently skipped
    (wd / "manifest.jsonl").write_text('{"kind":"head')
    man = TileManifest(str(wd), "fp")
    with pytest.raises(ValueError, match="no readable header"):
        man.open(resume=True)


# ---------------------------------------------------------------------------
# driver integration: one real elastic run
# ---------------------------------------------------------------------------


def test_elastic_run_matches_static_and_reports(tmp_path, rstack):
    """One real elastic run: lease rollup + telemetry contracts.

    Byte-parity against a static run is pinned by ``fault_soak``'s
    ``lease_acquire`` case (its digest compare is elastic vs the static
    clean run) — re-running a second full segmentation here would buy
    tier-1 nothing but wall time.
    """
    from land_trendr_tpu.runtime import run_stack

    elastic_wd = str(tmp_path / "elastic")
    summary = run_stack(rstack, RunConfig(
        params=PARAMS, tile_size=20, workdir=elastic_wd,
        out_dir=elastic_wd + "_o", retry_backoff_s=0.0,
        lease_batch=2, lease_ttl_s=10.0, telemetry=True,
    ))
    lease = summary["lease"]
    assert lease["acquired"] == summary["tiles"]
    assert lease["stolen"] == 0 and lease["speculated"] == 0
    assert summary["tiles_stolen"] == 0
    assert summary["tiles_speculated"] == 0
    # the stream: every tile leased exactly once, run_done carries the
    # rollup fields, and the whole file is schema + value-lint clean
    from land_trendr_tpu.obs.events import iter_events
    from tools.check_events_schema import main as lint_main

    events = list(iter_events(os.path.join(elastic_wd, "events.jsonl")))
    leased = [e for e in events if e["ev"] == "tile_leased"]
    assert len(leased) == summary["tiles"]
    assert all(e["gen"] == 0 for e in leased)
    run_done = [e for e in events if e["ev"] == "run_done"][-1]
    assert run_done["tiles_stolen"] == 0
    assert run_done["tiles_speculated"] == 0
    assert lint_main([elastic_wd]) == 0
    # done records carry the owner stamp (spec-win attribution)
    man = TileManifest(elastic_wd, "")
    owners = {
        r.get("owner")
        for r in man.iter_records()
        if r.get("kind") == "tile"
    }
    assert len(owners) == 1 and None not in owners


def test_speculate_requires_lease_batch():
    with pytest.raises(ValueError, match="speculate requires lease_batch"):
        RunConfig(speculate=True)
    with pytest.raises(ValueError, match="lease_ttl_s"):
        RunConfig(lease_batch=1, lease_ttl_s=0.0)
    with pytest.raises(ValueError, match="lease_batch"):
        RunConfig(lease_batch=-1)
