"""Worker process for the true multi-process ``jax.distributed`` test.

Run as: ``python _distributed_worker.py <coordinator> <num_procs> <proc_id>
<out_npz>``.  Each worker owns 4 virtual CPU devices; together the
processes form one 8-device global mesh.  The worker takes its
``host_share`` of a deterministic synthetic scene, feeds it through
``feed_global`` (its rows land only on its addressable devices), runs the
sharded segmentation program SPMD, and saves the rows it gathers back —
exactly the v5e-256 pod flow (SURVEY.md §5 distributed backend,
BASELINE configs[5]) scaled down to two localhost processes over the
loopback DCN.
"""

import sys

import jax

# Must beat the sitecustomize's jax_platforms="axon,cpu" config selection
# *before* any device/backend touch, or a down TPU tunnel hangs the worker.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402


def make_scene(px: int, ny: int):
    rng = np.random.default_rng(99)
    years = np.arange(1990, 1990 + ny, dtype=np.int32)
    t = np.arange(ny, dtype=np.float64)[None, :]
    d = rng.integers(5, ny - 5, size=(px, 1))
    vals = 0.6 - np.where(t >= d, 0.3, 0.0) + rng.normal(0, 0.01, (px, ny))
    mask = rng.uniform(size=(px, ny)) > 0.1
    return years, -vals, mask


def main() -> int:
    coordinator, num_procs, proc_id, out_path = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4],
    )

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.ops.segment import jax_segment_pixels
    from land_trendr_tpu.parallel import (
        feed_global,
        gather_local_rows,
        host_share,
        init_distributed,
        is_primary_host,
        make_mesh,
    )

    assert init_distributed(coordinator, num_procs, proc_id) is True
    assert jax.process_count() == num_procs
    assert jax.process_index() == proc_id
    assert is_primary_host() == (proc_id == 0)

    n_global = len(jax.devices())
    n_local = len(jax.local_devices())
    assert n_global == num_procs * n_local, (n_global, n_local)

    px_global = 2 * n_global  # 2 rows per device
    years, vals, mask = make_scene(px_global, ny=24)

    # each host feeds only its own contiguous row block
    rows = host_share(list(range(px_global)))
    assert len(rows) == px_global // num_procs
    mesh = make_mesh()
    gvals, gmask = feed_global(mesh, vals[rows], mask[rows])
    assert not gvals.sharding.is_fully_addressable  # genuinely multi-process

    params = LTParams(max_segments=4, vertex_count_overshoot=2)
    out = jax_segment_pixels(years, gvals, gmask, params)
    jax.block_until_ready(out)

    np.savez(
        out_path,
        rows=np.asarray(rows, dtype=np.int64),
        rmse=gather_local_rows(out.rmse),
        vertex_indices=gather_local_rows(out.vertex_indices),
        n_vertices=gather_local_rows(out.n_vertices),
        model_valid=gather_local_rows(out.model_valid),
        fitted=gather_local_rows(out.fitted),
    )
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
