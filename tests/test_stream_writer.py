"""GeoTiffStreamWriter: incremental tiled writes must decode identically to
the one-shot writer (VERDICT r3 next-round item #2 — streamed, windowed
raster assembly bounding host memory by O(tile × products))."""

import numpy as np
import pytest

from land_trendr_tpu.io.geotiff import (
    GeoMeta,
    GeoTiffStreamWriter,
    read_geotiff,
    write_geotiff,
)

from test_geotiff import _walk_pages


def _windows(h, w, th, tw):
    for y0 in range(0, h, th):
        for x0 in range(0, w, tw):
            yield y0, x0, min(th, h - y0), min(tw, w - x0)


@pytest.mark.parametrize("compress", ["deflate", "lzw", "none"])
def test_roundtrip_matches_oneshot(tmp_path, rng, compress):
    """Aligned and unaligned window grids both reproduce the array bit-for-
    bit, and decode equal to a write_geotiff file of the same data."""
    a = rng.integers(0, 4000, size=(3, 300, 517)).astype(np.uint16)
    geo = GeoMeta(pixel_scale=(30.0, 30.0, 0.0), tiepoint=(0, 0, 0, 5e5, 4e6, 0))
    for name, th, tw in [("aligned", 256, 256), ("ragged", 96, 120)]:
        p = tmp_path / f"stream_{name}.tif"
        with GeoTiffStreamWriter(
            str(p), 300, 517, 3, np.uint16, geo=geo, compress=compress
        ) as wr:
            for y0, x0, h, w in _windows(300, 517, th, tw):
                wr.write(y0, x0, np.moveaxis(a[:, y0 : y0 + h, x0 : x0 + w], 0, -1))
        got, ggeo, info = read_geotiff(str(p))
        np.testing.assert_array_equal(got, a)
        assert ggeo.pixel_scale == geo.pixel_scale
        assert info.tiled and not info.big

    ref = tmp_path / "oneshot.tif"
    write_geotiff(str(ref), a, geo=geo, compress=compress)
    ref_arr, _, _ = read_geotiff(str(ref))
    np.testing.assert_array_equal(ref_arr, a)


def test_out_of_order_windows_and_2d(tmp_path, rng):
    a = rng.normal(size=(130, 97)).astype(np.float32)
    p = tmp_path / "ooo.tif"
    wins = list(_windows(130, 97, 64, 64))
    rng.shuffle(wins)
    with GeoTiffStreamWriter(str(p), 130, 97, 1, np.float32, tile=64) as wr:
        for y0, x0, h, w in wins:
            wr.write(y0, x0, a[y0 : y0 + h, x0 : x0 + w])
    got, _, _ = read_geotiff(str(p))
    np.testing.assert_array_equal(got, a)


def test_streaming_overviews_match_oneshot_nearest(tmp_path, rng):
    """The global-parity decimation cascade reproduces write_geotiff's
    nearest pyramid page-for-page, even from unaligned windows."""
    a = rng.integers(0, 255, size=(1, 130, 97)).astype(np.uint8)
    ps = tmp_path / "stream.tif"
    with GeoTiffStreamWriter(
        str(ps), 130, 97, 1, np.uint8, tile=64, overviews=2
    ) as wr:
        for y0, x0, h, w in _windows(130, 97, 48, 80):  # unaligned on purpose
            wr.write(y0, x0, np.moveaxis(a[:, y0 : y0 + h, x0 : x0 + w], 0, -1))
    po = tmp_path / "oneshot.tif"
    write_geotiff(str(po), a, overviews=2, tile=64, resampling="nearest")
    assert _walk_pages(str(ps)) == _walk_pages(str(po)) == [
        (130, 97, 0),
        (65, 49, 1),
        (33, 25, 1),
    ]
    # pixel-identical pages, not just shapes: compare whole files' decoded
    # base pages and spot the level-1 page through the raw IFD walk
    s_arr, _, _ = read_geotiff(str(ps))
    o_arr, _, _ = read_geotiff(str(po))
    np.testing.assert_array_equal(s_arr, o_arr)
    np.testing.assert_array_equal(s_arr, a[0])


def test_incomplete_coverage_raises_and_allow_partial(tmp_path, rng):
    a = rng.integers(0, 255, size=(64, 64)).astype(np.uint8)
    p = tmp_path / "partial.tif"
    wr = GeoTiffStreamWriter(str(p), 128, 128, 1, np.uint8, tile=64)
    wr.write(0, 0, a)
    with pytest.raises(ValueError, match="not fully covered"):
        wr.close()
    p2 = tmp_path / "partial_ok.tif"
    with GeoTiffStreamWriter(
        str(p2), 128, 128, 1, np.uint8, tile=64, allow_partial=True
    ) as wr:
        wr.write(0, 0, a)
        wr.write(64, 64, a)  # diagonal: two blocks zero-filled
    got, _, _ = read_geotiff(str(p2))
    np.testing.assert_array_equal(got[:64, :64], a)
    assert (got[:64, 64:] == 0).all()


def test_overlapping_windows_rejected(tmp_path, rng):
    a = rng.integers(0, 255, size=(64, 64)).astype(np.uint8)
    wr = GeoTiffStreamWriter(
        str(tmp_path / "ovl.tif"), 64, 128, 1, np.uint8, tile=64
    )
    wr.write(0, 0, a)
    with pytest.raises(ValueError, match="written twice"):
        wr.write(0, 32, a[:, :32])


def test_bigtiff_auto_bound_and_force(tmp_path, rng):
    """Forced BigTIFF round-trips; the auto bound stays classic for small
    files and switches when the worst-case encoded size cannot fit u32."""
    a = rng.integers(0, 255, size=(40, 40)).astype(np.uint8)
    p = tmp_path / "big.tif"
    with GeoTiffStreamWriter(
        str(p), 40, 40, 1, np.uint8, tile=32, bigtiff=True
    ) as wr:
        wr.write(0, 0, a)
    got, _, info = read_geotiff(str(p))
    assert info.big
    np.testing.assert_array_equal(got, a)

    small = GeoTiffStreamWriter.__new__(GeoTiffStreamWriter)
    # _pick_layout sees only shape fields — fabricate a CONUS-scale float32
    # single-band writer and a scene-scale one without touching disk
    from land_trendr_tpu.io.geotiff import _StreamLevel, _resolve_compress

    for h, w, expect_big in [(2048, 2048, False), (100_000, 100_000, True)]:
        small.spp = 1
        small.dtype = np.dtype("<f4")
        small.tile = 256
        small.comp_id = _resolve_compress("deflate")
        small.levels = [_StreamLevel(h, w, 256)]
        assert small._pick_layout("auto") is expect_big, (h, w)


def test_compress_level_trades_size_not_content(tmp_path):
    """compress_level=1 must decode identically; files may differ in size."""
    rng = np.random.default_rng(0)
    img = (rng.integers(7000, 44000, (300, 400))).astype(np.uint16)
    paths = {}
    for lvl in (1, 6):
        p = tmp_path / f"l{lvl}.tif"
        w = GeoTiffStreamWriter(
            str(p), 300, 400, 1, np.uint16, compress="deflate",
            tile=128, compress_level=lvl,
        )
        w.write(0, 0, img[..., None])
        w.close()
        paths[lvl] = p
    a, _, _ = read_geotiff(str(paths[1]))
    b, _, _ = read_geotiff(str(paths[6]))
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, img)
