"""FTV parity: jax_fit_to_vertices vs the float64 CPU oracle."""

import jax.numpy as jnp
import numpy as np

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.models import oracle
from land_trendr_tpu.ops.ftv import jax_fit_to_vertices
from land_trendr_tpu.ops.segment import jax_segment_pixels

YEARS = np.arange(1984, 2024, dtype=np.float64)
NY = len(YEARS)


def _disturbance_series(rng, noise=0.01):
    base = rng.uniform(-0.6, -0.2)
    y = np.full(NY, base)
    d = rng.integers(8, NY - 8)
    y[d:] += rng.uniform(0.3, 0.8)
    rec = rng.uniform(0.01, 0.04)
    y[d:] -= rec * np.arange(NY - d)
    return y + rng.normal(0.0, noise, NY)


def _run_pair(rng, n_px=24, seg_noise=0.01, target_noise=0.02, mask_p=0.0):
    params = LTParams()
    seg = np.stack([_disturbance_series(rng, seg_noise) for _ in range(n_px)])
    tgt = np.stack([_disturbance_series(rng, target_noise) for _ in range(n_px)])
    seg_mask = np.ones((n_px, NY), dtype=bool)
    tgt_mask = rng.random((n_px, NY)) >= mask_p
    tgt_mask[:, 0] = tgt_mask[:, -1] = True

    out = jax_segment_pixels(
        jnp.asarray(YEARS), jnp.asarray(seg), jnp.asarray(seg_mask), params
    )
    vi = np.asarray(out.vertex_indices)
    nv = np.asarray(out.n_vertices)

    got = np.asarray(
        jax_fit_to_vertices(
            jnp.asarray(YEARS),
            jnp.asarray(tgt),
            jnp.asarray(tgt_mask),
            jnp.asarray(vi),
            jnp.asarray(nv),
            params,
        )
    )
    want = np.stack(
        [
            oracle.fit_to_vertices(YEARS, tgt[i], tgt_mask[i], vi[i], int(nv[i]), params)
            for i in range(n_px)
        ]
    )
    return got, want, nv


def test_ftv_parity_full_mask(rng):
    got, want, nv = _run_pair(rng)
    assert (nv >= 2).any()  # fixture must exercise the real fit path
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-10)


def test_ftv_parity_masked_target(rng):
    got, want, _ = _run_pair(rng, mask_p=0.25)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-10)


def test_ftv_no_vertices_falls_back_to_mean(rng):
    params = LTParams()
    tgt = _disturbance_series(rng)
    mask = np.ones(NY, dtype=bool)
    vi = np.full((1, params.max_vertices), -1, dtype=np.int32)
    got = np.asarray(
        jax_fit_to_vertices(
            jnp.asarray(YEARS),
            jnp.asarray(tgt[None]),
            jnp.asarray(mask[None]),
            jnp.asarray(vi),
            jnp.asarray([0], dtype=np.int32),
            params,
        )
    )[0]
    np.testing.assert_allclose(got, np.full(NY, tgt.mean()), rtol=1e-12)
    want = oracle.fit_to_vertices(YEARS, tgt, mask, vi[0], 0, params)
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_ftv_all_masked_target_is_zero(rng):
    params = LTParams()
    tgt = _disturbance_series(rng)
    vi = np.zeros((1, params.max_vertices), dtype=np.int32)
    vi[0, :2] = [0, NY - 1]
    got = np.asarray(
        jax_fit_to_vertices(
            jnp.asarray(YEARS),
            jnp.asarray(tgt[None]),
            jnp.zeros((1, NY), dtype=bool),
            jnp.asarray(vi),
            jnp.asarray([2], dtype=np.int32),
            params,
        )
    )[0]
    np.testing.assert_allclose(got, 0.0)


def test_ftv_vertices_collapse_to_endpoints(rng):
    # target mask kills every year the vertex indices point at except one —
    # the mapped vertex set collapses and the oracle falls back to endpoints.
    params = LTParams()
    tgt = _disturbance_series(rng)
    mask = np.zeros(NY, dtype=bool)
    mask[5:9] = True
    vi = np.full((params.max_vertices,), -1, dtype=np.int32)
    vi[0] = 7
    vi[1] = 7
    got = np.asarray(
        jax_fit_to_vertices(
            jnp.asarray(YEARS),
            jnp.asarray(tgt[None]),
            jnp.asarray(mask[None]),
            jnp.asarray(vi[None]),
            jnp.asarray([2], dtype=np.int32),
            params,
        )
    )[0]
    want = oracle.fit_to_vertices(YEARS, tgt, mask, vi, 2, params)
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-10)
