"""Behavioural tests for the CPU oracle (the normative algorithm spec).

These encode the semantics in SURVEY.md §3.1 on the synthetic-series matrix
from the build plan (§7 step 2): flat, single disturbance, disturbance +
recovery, spikes, missing years, all-masked — plus parameter edge cases.
"""

import numpy as np
import pytest

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.models.oracle import (
    PixelSegmenter,
    cull_by_angle,
    despike,
    f_stat_p_value,
    find_candidate_vertices,
    fit_to_vertices,
    segment_series,
)

YEARS = np.arange(1984, 2022, dtype=np.float64)  # 38 years
NY = len(YEARS)
ALL = np.ones(NY, dtype=bool)
P = LTParams()


def seg(values, mask=None, params=P):
    return segment_series(YEARS, np.asarray(values, float), ALL if mask is None else mask, params)


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


def test_params_validation():
    with pytest.raises(ValueError):
        LTParams(max_segments=0)
    with pytest.raises(ValueError):
        LTParams(spike_threshold=1.5)
    with pytest.raises(ValueError):
        LTParams(best_model_proportion=0.0)
    p = LTParams.from_json(LTParams(max_segments=4).to_json())
    assert p.max_segments == 4 and p.max_vertices == 5
    with pytest.raises(ValueError):
        LTParams.from_dict({"bogus": 1})


def test_params_hashable_static():
    assert hash(LTParams()) == hash(LTParams())
    assert LTParams() != LTParams(max_segments=5)


# ---------------------------------------------------------------------------
# despike
# ---------------------------------------------------------------------------


def test_despike_flattens_pure_spike():
    y = np.zeros(11)
    y[5] = 10.0  # perfect symmetric spike: prop == 1
    t = np.arange(11, dtype=float)
    out = despike(t, y, 0.9)
    assert abs(out[5]) < 1e-9
    assert np.allclose(out[[i for i in range(11) if i != 5]], 0.0)


def test_despike_threshold_one_is_noop():
    y = np.zeros(11)
    y[5] = 10.0
    t = np.arange(11, dtype=float)
    out = despike(t, y, 1.0)
    assert np.array_equal(out, y)


def test_despike_preserves_real_step():
    # A persistent step is NOT a spike: values on both sides differ.
    y = np.concatenate([np.zeros(6), np.ones(6)])
    t = np.arange(12, dtype=float)
    out = despike(t, y, 0.9)
    assert np.allclose(out, y)  # crossing ≈ dev at the step edges → prop ≤ 0.5


def test_despike_uneven_spacing_uses_interpolation():
    t = np.array([0.0, 1.0, 4.0])
    y = np.array([0.0, 10.0, 8.0])
    # interp at t=1 is 2.0; dev=8, crossing=8 → prop=0 → no dampening
    out = despike(t, y, 0.5)
    assert np.array_equal(out, y)


# ---------------------------------------------------------------------------
# vertex search / cull
# ---------------------------------------------------------------------------


def test_candidate_search_finds_breakpoint():
    t = np.arange(21, dtype=float)
    y = np.where(t < 10, 0.0, (t - 10) * 2.0)  # hinge at index 10
    verts = find_candidate_vertices(t, y, 3)
    assert verts[0] == 0 and verts[-1] == 20
    assert 10 in verts


def test_candidate_search_caps_at_n_points():
    t = np.arange(4, dtype=float)
    y = np.array([0.0, 3.0, -2.0, 1.0])
    verts = find_candidate_vertices(t, y, 10)
    assert verts == [0, 1, 2, 3]


def test_cull_keeps_sharpest_angles():
    t = np.arange(21, dtype=float)
    y = np.where(t < 10, 0.0, (t - 10) * 2.0)
    verts = find_candidate_vertices(t, y, 6)
    culled = cull_by_angle(t, y, verts, 3)
    assert culled[0] == 0 and culled[-1] == 20
    assert 10 in culled  # the real hinge survives the cull


# ---------------------------------------------------------------------------
# end-to-end segmentation
# ---------------------------------------------------------------------------


def test_flat_series_is_no_fit():
    r = seg(np.full(NY, 0.3))
    assert not r.model_valid
    assert r.n_vertices == 0
    assert np.allclose(r.fitted, 0.3)


def test_pure_noise_is_no_fit():
    rng = np.random.default_rng(7)
    r = seg(rng.normal(0.0, 1.0, NY))
    assert not r.model_valid  # no structure → F-test fails


def test_single_disturbance_step():
    # disturbance-positive convention: abrupt increase then plateau
    y = np.where(YEARS < 2000, 0.1, 0.8)
    r = seg(y)
    assert r.model_valid
    assert 2 <= r.n_vertices <= P.max_vertices
    # one of the vertices must sit at the step (1999 or 2000)
    vy = r.vertex_years[: r.n_vertices]
    assert np.any((vy == 1999) | (vy == 2000))
    assert r.rmse < 0.05
    # fitted trajectory reproduces the plateau levels
    assert abs(r.fitted[0] - 0.1) < 0.05 and abs(r.fitted[-1] - 0.8) < 0.05


def test_disturbance_then_recovery():
    # ramp up 1984-1994, abrupt disturbance 1995, slow recovery after
    y = np.piecewise(
        YEARS,
        [YEARS < 1995, YEARS >= 1995],
        [lambda x: 0.2, lambda x: np.maximum(0.9 - 0.02 * (x - 1995), 0.2)],
    )
    r = seg(y)
    assert r.model_valid
    assert r.rmse < 0.05
    # must contain at least one negative-magnitude (recovery) segment
    mags = r.seg_magnitude[: r.n_vertices - 1]
    assert (mags < 0).any() and (mags > 0).any()


def test_spike_does_not_create_vertex():
    y = np.full(NY, 0.2)
    y[10] = 0.9  # single-year spike
    y_step = y + np.where(YEARS >= 2010, 0.5, 0.0)  # plus a real disturbance
    r = seg(y_step)
    if r.model_valid:
        # despike should remove the 1994 spike; no vertex lands there
        vy = r.vertex_years[: r.n_vertices]
        assert 1994 not in vy


def test_min_observations_gate():
    mask = ALL.copy()
    mask[5:] = False  # 5 valid < min_observations_needed=6
    r = seg(np.linspace(0, 1, NY), mask)
    assert not r.model_valid and r.n_vertices == 0


def test_all_masked():
    r = seg(np.linspace(0, 1, NY), np.zeros(NY, dtype=bool))
    assert not r.model_valid
    assert np.allclose(r.fitted, 0.0)


def test_missing_years_still_fits():
    y = np.where(YEARS < 2000, 0.1, 0.8)
    mask = ALL.copy()
    mask[3:20:4] = False
    r = seg(y, mask)
    assert r.model_valid
    assert r.rmse < 0.06
    # vertices must only sit on valid years
    assert mask[r.vertex_indices[: r.n_vertices]].all()


def test_recovery_rate_filter_blocks_fast_recovery():
    # full-range recovery over 2 years: rate = range/2 per yr > 0.25*range
    y = np.where(YEARS < 2000, 0.8, np.where(YEARS < 2002, 0.8 - 0.4 * (YEARS - 1999), 0.0))
    strict = LTParams(recovery_threshold=0.25, p_val_threshold=1.0, best_model_proportion=1.0)
    loose = LTParams(recovery_threshold=10.0, p_val_threshold=1.0, best_model_proportion=1.0)
    r_strict = seg(y, params=strict)
    r_loose = seg(y, params=loose)
    # the loose fit can follow the fast recovery; the strict one cannot
    sse_strict = np.sum((y - r_strict.fitted) ** 2)
    sse_loose = np.sum((y - r_loose.fitted) ** 2)
    assert sse_loose <= sse_strict
    # strict: no fitted segment recovers faster than the limit (+ tolerance)
    rates = r_strict.seg_rate[: max(r_strict.n_vertices - 1, 0)]
    rng = np.ptp(r_strict.despiked)
    assert (rates >= -0.25 * rng - 1e-9).all()


def test_segment_attributes_consistent():
    y = np.where(YEARS < 2000, 0.1, 0.8)
    r = seg(y)
    k = r.n_vertices
    for s in range(k - 1):
        assert r.seg_duration[s] == r.vertex_years[s + 1] - r.vertex_years[s]
        np.testing.assert_allclose(
            r.seg_magnitude[s], r.vertex_fit_vals[s + 1] - r.vertex_fit_vals[s]
        )
        np.testing.assert_allclose(
            r.seg_rate[s], r.seg_magnitude[s] / r.seg_duration[s]
        )
    # padding is zeroed
    assert (r.seg_duration[max(k - 1, 0):] == 0).all()
    assert (r.vertex_indices[k:] == -1).all()


def test_fitted_trajectory_is_continuous():
    rng = np.random.default_rng(3)
    y = np.cumsum(rng.normal(0, 0.1, NY)) + np.where(YEARS >= 2005, 1.0, 0.0)
    r = seg(y, params=LTParams(p_val_threshold=1.0))
    # piecewise-linear interpolation through vertex fit vals == fitted
    k = r.n_vertices
    interp = np.interp(YEARS, r.vertex_years[:k], r.vertex_fit_vals[:k])
    np.testing.assert_allclose(r.fitted, interp, atol=1e-9)


def test_f_stat_monotonic_in_fit_quality():
    p_good = f_stat_p_value(ss0=10.0, sse=0.1, n=38, n_segments=2)
    p_bad = f_stat_p_value(ss0=10.0, sse=8.0, n=38, n_segments=2)
    assert p_good < p_bad
    assert f_stat_p_value(10.0, 11.0, 38, 2) == 1.0  # worse than mean
    assert f_stat_p_value(10.0, 0.0, 38, 2) == 0.0
    assert f_stat_p_value(10.0, 1.0, 5, 3) == 1.0  # df2 < 1


def test_more_segments_need_proportional_justification():
    # best_model_proportion=1.0 → strictly prefer lowest p
    y = np.where(YEARS < 2000, 0.1, 0.8)
    r1 = seg(y, params=LTParams(best_model_proportion=1.0))
    r2 = seg(y, params=LTParams(best_model_proportion=0.25))
    assert r2.n_vertices >= r1.n_vertices  # leniency never removes segments


def test_pixel_segmenter_facade():
    ps = PixelSegmenter()
    y = np.where(YEARS < 2000, 0.1, 0.8)
    r = ps.segment(YEARS, y)
    assert r.model_valid
    # NaNs are auto-masked
    y_nan = y.copy()
    y_nan[4] = np.nan
    r2 = ps.segment(YEARS, y_nan)
    assert r2.model_valid
    assert 4 not in r2.vertex_indices[: r2.n_vertices]


def test_ftv_fits_second_index_to_vertices():
    y1 = np.where(YEARS < 2000, 0.1, 0.8)
    r = seg(y1)
    y2 = np.where(YEARS < 2000, 0.5, 0.2) + 0.001 * (YEARS - 1984)
    ftv = fit_to_vertices(YEARS, y2, ALL, r.vertex_indices, r.n_vertices, P)
    assert ftv.shape == (NY,)
    # FTV should track y2's levels reasonably
    assert abs(ftv[0] - y2[0]) < 0.1 and abs(ftv[-1] - y2[-1]) < 0.1


def test_deterministic():
    rng = np.random.default_rng(11)
    y = np.cumsum(rng.normal(0, 0.2, NY))
    r1, r2 = seg(y), seg(y)
    np.testing.assert_array_equal(r1.vertex_indices, r2.vertex_indices)
    np.testing.assert_array_equal(r1.fitted, r2.fitted)
