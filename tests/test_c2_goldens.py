"""USGS Collection-2 golden fixtures for the ingest path.

VERDICT r4 Missing #1: the raster layer had only ever parsed files written
by this repo's own codec ("our codec reads our TIFFs").  These tests build
byte-level Landsat Collection-2 Level-2 lookalikes with an INDEPENDENT
writer — ``_RawTiffWriter`` below is implemented directly from the TIFF
6.0 / GeoTIFF specs with ``struct``, sharing no code with
``land_trendr_tpu.io.geotiff`` — and drive the full
stack → indices → segmentation path over them.

Fixture properties replicate the published C2 product structure
(LSDS-1619 Landsat 8-9 C2 L2 Science Product Guide; SURVEY.md §2 L1):

* per-band SR files + QA_PIXEL with real product-id naming
  (``LC08_L2SP_045030_20200715_20200912_02_T1_SR_B5.TIF``);
* sensor-generation band numbering: an archive that switches from LT05
  (SR_B1..B5,B7) to LC08 (SR_B2..B7) mid-series;
* **uint16** SR DNs in the valid range 7273–43636, scale 2.75e-5,
  offset -0.2; fill value 0 carried in the GDAL_NODATA ascii tag;
* QA_PIXEL (CFMask) bit semantics: fill bit 0, dilated cloud 1, cloud 3,
  shadow 4;
* stripped AND tiled variants, BOTH endiannesses, uncompressed and
  deflate with the horizontal predictor.

The fixtures are generated at test time from this spec-level writer
rather than committed as binaries — every byte is derived from reviewable
code, and the codec still never sees a file its own writer produced.
"""

from __future__ import annotations

import struct
import zlib
from pathlib import Path

import numpy as np
import pytest

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.ops import indices as idx
from land_trendr_tpu.ops.tile import process_tile_dn
from land_trendr_tpu.runtime.stack import load_stack_dir, load_stack_dir_c2

# --------------------------------------------------------------------------
# Independent spec-level TIFF writer (TIFF 6.0 baseline + GeoTIFF tags)
# --------------------------------------------------------------------------

_TYPE_SIZES = {1: 1, 2: 1, 3: 2, 4: 4, 12: 8}  # BYTE ASCII SHORT LONG DOUBLE


class _RawTiffWriter:
    """Writes one single-band uint16 raster as a classic TIFF, by the book.

    ``layout`` is ``("strips", rows_per_strip)`` or ``("tiles", tw, th)``;
    ``compression`` is 1 (none) or 8 (deflate, with horizontal predictor 2
    applied per spec: per-row differencing on 16-bit units).
    """

    def __init__(self, *, big_endian: bool, layout, compression: int = 1):
        self.bo = ">" if big_endian else "<"
        self.layout = layout
        self.compression = compression

    def _pack(self, fmt: str, *vals) -> bytes:
        return struct.pack(self.bo + fmt, *vals)

    def _encode_block(self, block: np.ndarray) -> bytes:
        dt = np.dtype(np.uint16).newbyteorder(self.bo)
        if self.compression == 1:
            return block.astype(dt).tobytes()
        # horizontal predictor: difference along each row in 16-bit units
        # (TIFF 6.0 §14), then deflate
        diff = block.astype(np.int32)
        diff[:, 1:] = diff[:, 1:] - diff[:, :-1]
        raw = (diff & 0xFFFF).astype(dt).tobytes()
        return zlib.compress(raw, 6)

    def write(self, path: Path, img: np.ndarray, *, nodata: float | None = 0.0):
        h, w = img.shape
        blocks: list[bytes] = []
        if self.layout[0] == "strips":
            rps = self.layout[1]
            for r0 in range(0, h, rps):
                blocks.append(self._encode_block(img[r0:r0 + rps]))
        else:
            tw, th = self.layout[1], self.layout[2]
            for r0 in range(0, h, th):
                for c0 in range(0, w, tw):
                    tile = np.zeros((th, tw), img.dtype)  # edge padding
                    part = img[r0:r0 + th, c0:c0 + tw]
                    tile[: part.shape[0], : part.shape[1]] = part
                    blocks.append(self._encode_block(tile))

        tags: list[tuple[int, int, int, bytes]] = []  # (tag, type, count, payload)

        def add(tag, typ, values):
            if typ == 2:  # ascii, NUL-terminated
                payload = values.encode() + b"\0"
                count = len(payload)
            else:
                values = list(values)
                count = len(values)
                fmt = {3: "H", 4: "L", 12: "d"}[typ]
                payload = b"".join(self._pack(fmt, v) for v in values)
            tags.append((tag, typ, count, payload))

        add(256, 4, [w])
        add(257, 4, [h])
        add(258, 3, [16])
        add(259, 3, [self.compression])
        add(262, 3, [1])  # BlackIsZero
        if self.layout[0] == "strips":
            off_tag, cnt_tag = 273, 279
            add(278, 4, [self.layout[1]])
        else:
            off_tag, cnt_tag = 324, 325
            add(322, 3, [self.layout[1]])
            add(323, 3, [self.layout[2]])
        add(277, 3, [1])   # SamplesPerPixel
        add(284, 3, [1])   # PlanarConfig chunky
        add(339, 3, [1])   # SampleFormat unsigned
        if self.compression == 8:
            add(317, 3, [2])  # horizontal predictor
        # GeoTIFF grid: 30 m pixels anchored at a UTM-looking origin
        add(33550, 12, [30.0, 30.0, 0.0])
        add(33922, 12, [0.0, 0.0, 0.0, 553785.0, 5189625.0, 0.0])
        if nodata is not None:
            add(42113, 2, "%g" % nodata)

        # two-pass layout: the block-offset values depend on the total IFD
        # + external-payload size, which is knowable before the values are
        # (payload SIZES are fixed) — so size everything first, then fill
        counts = [len(b) for b in blocks]
        all_tags = dict((t[0], t) for t in tags)
        all_tags[cnt_tag] = (
            cnt_tag, 4, len(blocks),
            b"".join(self._pack("L", c) for c in counts),
        )
        all_tags[off_tag] = (  # placeholder values, correct size
            off_tag, 4, len(blocks), b"\0" * (4 * len(blocks)),
        )
        n = len(all_tags)
        ifd_off = 8
        entries_end = ifd_off + 2 + n * 12 + 4
        ext_size = sum(
            len(p) + (len(p) & 1)
            for _, _, _, p in all_tags.values()
            if len(p) > 4
        )
        data_start = entries_end + ext_size
        offs = []
        pos = data_start
        for c in counts:
            offs.append(pos)
            pos += c + (c & 1)
        all_tags[off_tag] = (
            off_tag, 4, len(blocks),
            b"".join(self._pack("L", o) for o in offs),
        )

        ext: list[bytes] = []
        ext_off = entries_end

        def entry(tag, typ, count, payload) -> bytes:
            nonlocal ext_off
            if len(payload) <= 4:
                return self._pack("HHL", tag, typ, count) + payload.ljust(4, b"\0")
            off = ext_off
            ext.append(payload)
            ext_off += len(payload) + (len(payload) & 1)
            return self._pack("HHL", tag, typ, count) + self._pack("L", off)

        out = bytearray()
        out += (b"MM\0*" if self.bo == ">" else b"II*\0")
        out += self._pack("L", ifd_off)
        out += self._pack("H", n)
        for tag in sorted(all_tags):
            out += entry(*all_tags[tag])
        out += self._pack("L", 0)
        for payload in ext:
            out += payload
            if len(payload) & 1:
                out += b"\0"
        assert len(out) == data_start, (len(out), data_start)
        for i, b in enumerate(blocks):
            assert len(out) == offs[i]
            out += b
            if len(b) & 1:
                out += b"\0"
        path.write_bytes(bytes(out))


# --------------------------------------------------------------------------
# Scene synthesis: a disturbance signal in the C2 DN domain
# --------------------------------------------------------------------------

H, W = 21, 33
YEARS = list(range(1984, 1994))
DIST_YEAR_IDX = 5  # 1989
SCALE, OFFSET = 2.75e-5, -0.2


def _dn(refl: float) -> int:
    return int(round((refl - OFFSET) / SCALE))


# per-band base reflectance pre/post disturbance; values keep DNs inside
# the C2 valid range [7273, 43636]
_BAND_REFL = {
    "blue": (0.04, 0.08),
    "green": (0.06, 0.10),
    "red": (0.05, 0.14),
    "nir": (0.45, 0.18),
    "swir1": (0.20, 0.28),
    "swir2": (0.08, 0.25),
}


def _band_image(band: str, year_idx: int) -> np.ndarray:
    pre, post = _BAND_REFL[band]
    refl = post if year_idx >= DIST_YEAR_IDX else pre
    img = np.full((H, W), _dn(refl), np.uint16)
    # deterministic per-pixel texture so pixels are not literally constant
    rr, cc = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
    img += ((rr * 7 + cc * 13 + year_idx * 3) % 40).astype(np.uint16)
    img[_fill_region()] = 0  # C2 fill value
    return img


def _fill_region():
    m = np.zeros((H, W), bool)
    m[:4, :5] = True  # NW corner never observed
    return m


def _qa_image(year_idx: int) -> np.ndarray:
    qa = np.full((H, W), 1 << 6, np.uint16)  # "clear" bit, as real CFMask sets
    qa[_fill_region()] = 1 << 0  # fill
    if year_idx in (2, 7):  # a cloud band crossing the scene
        qa[8:11, :] |= (1 << 3) | (1 << 1)
    if year_idx == 7:
        qa[11:13, :] |= 1 << 4  # shadow south of the cloud
    return qa


_TM_NUM = {"blue": 1, "green": 2, "red": 3, "nir": 4, "swir1": 5, "swir2": 7}
_OLI_NUM = {"blue": 2, "green": 3, "red": 4, "nir": 5, "swir1": 6, "swir2": 7}


def _c2_name(year: int, band: str | None) -> str:
    """Product-id file name; LT05 through 1989, LC08 after (numbering shift)."""
    oli = year >= 1990
    sensor = "LC08" if oli else "LT05"
    prod = (
        "QA_PIXEL" if band is None
        else f"SR_B{(_OLI_NUM if oli else _TM_NUM)[band]}"
    )
    return (
        f"{sensor}_L2SP_045030_{year}0715_{year}0912_02_T1_{prod}.TIF"
    )


def _write_scene(root: Path, writer: _RawTiffWriter, years=YEARS) -> Path:
    root.mkdir(parents=True, exist_ok=True)
    for k, year in enumerate(years):
        for band in idx.BANDS:
            writer.write(root / _c2_name(year, band), _band_image(band, k))
        writer.write(root / _c2_name(year, None), _qa_image(k), nodata=1.0)
    return root


_VARIANTS = {
    "le_strips": _RawTiffWriter(big_endian=False, layout=("strips", 5)),
    "be_strips": _RawTiffWriter(big_endian=True, layout=("strips", 64)),
    "le_tiles": _RawTiffWriter(big_endian=False, layout=("tiles", 16, 16)),
    "be_tiles_deflate": _RawTiffWriter(
        big_endian=True, layout=("tiles", 16, 16), compression=8
    ),
    "le_strips_deflate": _RawTiffWriter(
        big_endian=False, layout=("strips", 7), compression=8
    ),
}


@pytest.fixture(scope="module")
def golden_root(tmp_path_factory):
    root = tmp_path_factory.mktemp("c2_goldens")
    for name, writer in _VARIANTS.items():
        _write_scene(root / name, writer)
    return root


# --------------------------------------------------------------------------
# Loader behaviour over the goldens
# --------------------------------------------------------------------------


def test_c2_layout_autodetected_and_loaded(golden_root):
    stack = load_stack_dir(str(golden_root / "le_strips"))
    assert stack.years.tolist() == YEARS
    assert stack.shape == (H, W)
    assert set(stack.dn_bands) == set(idx.BANDS)
    for b in idx.BANDS:
        assert stack.dn_bands[b].dtype == np.uint16, b
        assert stack.dn_bands[b].shape == (len(YEARS), H, W)
    assert stack.qa.dtype == np.uint16
    # geo grid parsed from the GeoTIFF tags
    assert stack.geo is not None
    assert stack.geo.pixel_scale[0] == 30.0


def test_all_variants_decode_identical_bytes(golden_root):
    ref = load_stack_dir(str(golden_root / "le_strips"))
    for name in _VARIANTS:
        if name == "le_strips":
            continue
        got = load_stack_dir(str(golden_root / name))
        for b in idx.BANDS:
            np.testing.assert_array_equal(
                got.dn_bands[b], ref.dn_bands[b],
                err_msg=f"{name}:{b}",
            )
        np.testing.assert_array_equal(got.qa, ref.qa, err_msg=name)


def test_sensor_generation_band_mapping(golden_root):
    """LT05 B4 and LC08 B5 must both land in 'nir' — the numbering shift."""
    stack = load_stack_dir(str(golden_root / "le_strips"))
    clear = ~_fill_region()
    for k, year in enumerate(YEARS):
        expect = _band_image("nir", k)
        np.testing.assert_array_equal(
            stack.dn_bands["nir"][k][clear], expect[clear], err_msg=str(year)
        )


def test_dn_scaling_reproduces_reflectance(golden_root):
    stack = load_stack_dir(str(golden_root / "le_strips"), bands=("nir",))
    dn = stack.dn_bands["nir"][0][10, 10]
    refl = float(idx.scale_sr(np.asarray([[dn]]), SCALE, OFFSET)[0, 0])
    assert abs(refl - _BAND_REFL["nir"][0]) < 40 * SCALE + 1e-6


def test_band_subset_skips_files(golden_root, monkeypatch):
    """bands=('nir','swir2') must not even open the other SR files."""
    opened: list[str] = []
    import land_trendr_tpu.runtime.stack as stack_mod

    real = stack_mod.read_geotiff

    def spy(path, *a, **k):
        opened.append(Path(path).name)
        return real(path, *a, **k)

    monkeypatch.setattr(stack_mod, "read_geotiff", spy)
    load_stack_dir(str(golden_root / "le_strips"), bands=("nir", "swir2"))
    assert opened and all(
        ("SR_B4" in n or "SR_B5" in n or "SR_B7" in n or "QA_PIXEL" in n)
        for n in opened
    ), opened


def test_full_pipeline_recovers_disturbance(golden_root):
    """stack → indices → segmentation end-to-end over the golden files."""
    stack = load_stack_dir(str(golden_root / "be_tiles_deflate"))
    ny = stack.n_years
    dn = {
        b: np.ascontiguousarray(
            cube.transpose(1, 2, 0).reshape(-1, ny)
        )
        for b, cube in stack.dn_bands.items()
    }
    qa = np.ascontiguousarray(stack.qa.transpose(1, 2, 0).reshape(-1, ny))
    out = process_tile_dn(
        stack.years.astype(np.float64), dn, qa,
        index="nbr", params=LTParams(), impl="xla",
    )
    valid = np.asarray(out.seg.model_valid).reshape(H, W)
    fill = _fill_region()
    assert not valid[fill].any(), "fill region must never fit a model"
    assert valid[~fill].mean() > 0.9, "clear pixels should segment"
    # the largest-magnitude vertex year should be the disturbance year
    vyears = np.asarray(out.seg.vertex_years).reshape(H, W, -1)
    mags = np.asarray(out.seg.seg_magnitude).reshape(H, W, -1)
    r, c = 15, 20  # a clear pixel
    k = int(np.argmax(mags[r, c]))
    # disturbance segment must end at/after the 1989 step
    assert YEARS[DIST_YEAR_IDX] <= vyears[r, c, k + 1] <= YEARS[DIST_YEAR_IDX] + 1
    assert mags[r, c, k] > 0.5  # NBR drop ~0.86 in disturbance-positive units


def test_qa_bits_mask_observations(golden_root):
    stack = load_stack_dir(str(golden_root / "le_strips"))
    valid = np.asarray(idx.qa_valid_mask(stack.qa))
    assert not valid[2, 9, :].any(), "cloud year rows masked"
    assert not valid[7, 12, :].any(), "shadow rows masked"
    assert valid[0][~_fill_region()].all()
    assert not valid[0][_fill_region()].any()


# --------------------------------------------------------------------------
# Archive-shape errors the loader must catch loudly
# --------------------------------------------------------------------------


def test_multiple_acquisitions_requires_composite(golden_root, tmp_path):
    root = tmp_path / "multi"
    w = _VARIANTS["le_strips"]
    _write_scene(root, w, years=YEARS[:3])
    # second acquisition for 1985
    for band in idx.BANDS:
        w.write(
            root / _c2_name(1985, band).replace("0715", "0816"),
            _band_image(band, 1),
        )
    w.write(root / _c2_name(1985, None).replace("0715", "0816"), _qa_image(1))
    with pytest.raises(ValueError, match="multiple acquisitions"):
        load_stack_dir(str(root))
    stack = load_stack_dir(str(root), composite="medoid")
    assert stack.years.tolist() == YEARS[:3]
    assert stack.dn_bands["nir"].dtype == np.uint16


def test_missing_band_raises(tmp_path):
    root = tmp_path / "missing"
    root.mkdir()
    w = _VARIANTS["le_strips"]
    for band in ("nir", "swir2"):
        w.write(root / _c2_name(1990, band), _band_image(band, 0))
    # no QA_PIXEL for the acquisition
    with pytest.raises(ValueError, match="missing bands"):
        load_stack_dir_c2(str(root))


def test_multiple_pathrows_rejected(golden_root, tmp_path):
    root = tmp_path / "two_scenes"
    w = _VARIANTS["le_strips"]
    _write_scene(root, w, years=YEARS[:2])
    other = _c2_name(1984, "nir").replace("045030", "046031")
    w.write(root / other, _band_image("nir", 0))
    with pytest.raises(ValueError, match="path/row"):
        load_stack_dir(str(root))


def test_multiband_file_rejected_in_c2_layout(tmp_path):
    """A stray 2-D+ file under a C2 name must fail, not mis-stack."""
    root = tmp_path / "threed"
    root.mkdir()
    w = _VARIANTS["le_strips"]
    for band in idx.BANDS:
        w.write(root / _c2_name(1990, band), _band_image(band, 0))
    w.write(root / _c2_name(1990, None), _qa_image(0))
    # overwrite one band with a WRONG-SIZED raster
    w.write(root / _c2_name(1990, "red"), _band_image("red", 0)[:7, :9])
    with pytest.raises(ValueError, match="raster size"):
        load_stack_dir_c2(str(root))


# --------------------------------------------------------------------------
# Header fuzzing: corrupted files must raise, never hang or misread
# --------------------------------------------------------------------------


def _corruptions(data: bytes):
    yield "truncated_header", data[:6]
    yield "truncated_ifd", data[:10]
    yield "truncated_data", data[: len(data) // 2]
    yield "bad_magic", b"XX" + data[2:]
    yield "bad_version", data[:2] + b"\x07\x00" + data[4:]
    bad_off = bytearray(data)
    bad_off[4:8] = struct.pack("<L", len(data) + 1000)  # IFD beyond EOF
    yield "ifd_beyond_eof", bytes(bad_off)
    huge = bytearray(data)
    huge[8:10] = struct.pack("<H", 0xFFFF)  # absurd entry count
    yield "huge_entry_count", bytes(huge)
    yield "empty", b""


def test_corrupt_headers_raise_cleanly(tmp_path):
    w = _VARIANTS["le_strips"]
    good = tmp_path / "good.TIF"
    w.write(good, _band_image("nir", 0))
    data = good.read_bytes()
    from land_trendr_tpu.io.geotiff import read_geotiff

    for name, blob in _corruptions(data):
        p = tmp_path / f"{name}.TIF"
        p.write_bytes(blob)
        with pytest.raises(Exception) as ei:
            read_geotiff(str(p))
        assert not isinstance(
            ei.value, (MemoryError, SystemError)
        ), f"{name}: {ei.value!r}"


# --------------------------------------------------------------------------
# Lazy (windowed) ingest: the CONUS-scale feed seam
# --------------------------------------------------------------------------


def test_lazy_stack_matches_eager_and_feeds_driver(golden_root, tmp_path):
    """open_stack_dir_c2_lazy windows must decode the same bytes as the
    eager loader, and a full driver run over the lazy stack must produce
    rasters identical to the eager run's."""
    from land_trendr_tpu.runtime.driver import (
        RunConfig, assemble_outputs, run_stack,
    )
    from land_trendr_tpu.runtime.stack import open_stack_dir_c2_lazy

    src = str(golden_root / "le_tiles")
    eager = load_stack_dir(src)
    lazy = open_stack_dir_c2_lazy(src)
    assert lazy.years.tolist() == eager.years.tolist()
    assert lazy.shape == eager.shape
    # window equivalence incl. edge windows
    for (r0, c0, h, w) in [(0, 0, 5, 7), (17, 25, 4, 8), (0, 0, H, W)]:
        for b in ("nir", "swir2"):
            np.testing.assert_array_equal(
                lazy.dn_bands[b][:, r0:r0 + h, c0:c0 + w],
                eager.dn_bands[b][:, r0:r0 + h, c0:c0 + w],
                err_msg=f"{b}@{r0},{c0}",
            )
        np.testing.assert_array_equal(
            lazy.qa[:, r0:r0 + h, c0:c0 + w],
            eager.qa[:, r0:r0 + h, c0:c0 + w],
        )

    from land_trendr_tpu.io.geotiff import read_geotiff

    outs = {}
    for name, stack in [("eager", eager), ("lazy", lazy)]:
        cfg = RunConfig(
            out_dir=str(tmp_path / name), workdir=str(tmp_path / (name + "_w")),
            tile_size=16, index="nbr", impl="xla",
        )
        run_stack(stack, cfg)
        outs[name] = assemble_outputs(stack, cfg)
    assert set(outs["eager"]) == set(outs["lazy"])
    for prod in outs["eager"]:
        a, _, _ = read_geotiff(outs["eager"][prod])
        b, _, _ = read_geotiff(outs["lazy"][prod])
        np.testing.assert_array_equal(a, b, err_msg=prod)


def test_products_subset_run(golden_root, tmp_path):
    """RunConfig.products filters manifest + assembled rasters; invalid
    names fail fast; a subset-run resume is schema-consistent."""
    from land_trendr_tpu.runtime.driver import (
        RunConfig, assemble_outputs, run_stack,
    )

    with pytest.raises(ValueError, match="unknown products"):
        RunConfig(products=("n_vertices", "bogus"))

    stack = load_stack_dir(str(golden_root / "le_strips"))
    subset = ("n_vertices", "vertex_years", "seg_magnitude", "rmse",
              "model_valid")
    cfg = RunConfig(
        out_dir=str(tmp_path / "out"), workdir=str(tmp_path / "work"),
        tile_size=16, index="nbr", impl="xla", products=subset,
    )
    run_stack(stack, cfg)
    paths = assemble_outputs(stack, cfg)
    assert set(paths) == set(subset), sorted(paths)


def test_fetch_f16_packed_run(golden_root, tmp_path):
    """fetch_f16 halves wire bytes; decisions identical, floats within
    f16 quantization of the f32 run."""
    from land_trendr_tpu.io.geotiff import read_geotiff
    from land_trendr_tpu.runtime.driver import (
        RunConfig, assemble_outputs, run_stack,
    )

    stack = load_stack_dir(str(golden_root / "le_strips"))
    outs = {}
    for name, f16 in [("f32", False), ("f16", True)]:
        cfg = RunConfig(
            out_dir=str(tmp_path / name), workdir=str(tmp_path / (name + "_w")),
            tile_size=16, index="nbr", impl="xla", fetch_f16=f16,
        )
        run_stack(stack, cfg)
        outs[name] = assemble_outputs(stack, cfg)
    for prod in outs["f32"]:
        a, _, _ = read_geotiff(outs["f32"][prod])
        b, _, _ = read_geotiff(outs["f16"][prod])
        if a.dtype.kind in "iub":  # decisions must be identical
            np.testing.assert_array_equal(a, b, err_msg=prod)
        else:
            np.testing.assert_allclose(
                b, a, rtol=1e-3, atol=1e-3, err_msg=prod
            )
