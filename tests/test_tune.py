"""Autotuned execution profiles: store lifecycle, resolution, CLI.

The tuning-store contract (land_trendr_tpu/tune): persist → reload with
zero re-probes, key-miss re-probe on device-kind change, stale-schema
invalidation, corrupt/torn profile drop + re-probe, ``"auto"`` vs
explicit precedence, and the ``lt tune --dry-run`` report-no-write
contract — plus the drift pins that keep the tuner's default table and
the schema tool's source enum honest, and the packed-upload buffer
donation's consumption semantics.
"""

from __future__ import annotations

import dataclasses
import json
import sys
from pathlib import Path

import numpy as np
import pytest

from land_trendr_tpu.runtime.driver import RunConfig
from land_trendr_tpu.tune import (
    KNOB_DEFAULTS,
    TUNABLE_KNOBS,
    TUNE_SCHEMA,
    TuningStore,
    autotune,
    profile_key,
    resolve_config,
    shape_class,
)
from land_trendr_tpu.tune import probes as probemod

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))


@pytest.fixture()
def fake_probes(monkeypatch):
    """Replace the probe schedule with one counting fake group — store
    lifecycle tests must pin WHEN probes run, not what they measure."""
    calls: list[str] = []

    def fake_feed(reps, smoke, defaults):
        calls.append("feed")
        return {"feed_workers": 3}, {
            "probes": 2, "timings": {}, "default_s": 1.0, "best_s": 0.5,
            "speedup": 2.0,
        }

    monkeypatch.setattr(
        probemod, "PROBE_GROUPS", {"feed": (fake_feed, ("feed_workers",))}
    )
    return calls


def _tune(store_dir, **kw):
    kw.setdefault("height", 512)
    kw.setdefault("width", 512)
    kw.setdefault("n_years", 40)
    kw.setdefault("device_kind", "test-device")
    kw.setdefault("backend", "cpu")
    return autotune(str(store_dir), **kw)


# -- store lifecycle -------------------------------------------------------

def test_persist_then_reload_runs_zero_probes(tmp_path, fake_probes):
    p1 = _tune(tmp_path)
    assert p1["source"] == "probed"
    assert fake_probes == ["feed"]
    assert p1["knobs"]["feed_workers"] == 3
    # defaults survive for every group the (restricted) schedule skipped
    for knob in TUNABLE_KNOBS:
        if knob != "feed_workers":
            assert p1["knobs"][knob] == KNOB_DEFAULTS[knob]
    p2 = _tune(tmp_path)
    assert p2["source"] == "store"
    assert fake_probes == ["feed"], "warm reload must run ZERO probes"
    assert p2["knobs"] == p1["knobs"], "deterministic reload"


def test_key_miss_on_device_kind_change_reprobes(tmp_path, fake_probes):
    _tune(tmp_path)
    p2 = _tune(tmp_path, device_kind="other-device")
    assert p2["source"] == "probed"
    assert fake_probes == ["feed", "feed"]
    # both keys now coexist in one store
    store = TuningStore(str(tmp_path))
    assert len(store.profiles()) == 2


def test_retune_overrides_store_hit(tmp_path, fake_probes):
    _tune(tmp_path)
    p2 = _tune(tmp_path, retune=True)
    assert p2["source"] == "probed"
    assert fake_probes == ["feed", "feed"]


def test_stale_schema_version_invalidates(tmp_path, fake_probes):
    _tune(tmp_path)
    store = TuningStore(str(tmp_path))
    key = profile_key("test-device", "cpu", shape_class(512, 512, 40))
    path = store.path_for(key)
    stale = json.loads(Path(path).read_text())
    stale["schema"] = TUNE_SCHEMA - 1
    Path(path).write_text(json.dumps(stale))
    assert store.load("test-device", "cpu", shape_class(512, 512, 40)) is None
    assert store.stats()["stale_dropped"] == 1
    assert not Path(path).exists(), "stale profile must be dropped on sight"
    # and the autotuner re-probes the now-missing key
    p = _tune(tmp_path)
    assert p["source"] == "probed"
    assert fake_probes == ["feed", "feed"]


@pytest.mark.parametrize("damage", ["torn", "not-json", "wrong-key"])
def test_corrupt_profile_dropped_and_reprobed(tmp_path, fake_probes, damage):
    _tune(tmp_path)
    store = TuningStore(str(tmp_path))
    key = profile_key("test-device", "cpu", shape_class(512, 512, 40))
    path = Path(store.path_for(key))
    raw = path.read_text()
    if damage == "torn":
        path.write_text(raw[: len(raw) // 2])
    elif damage == "not-json":
        path.write_bytes(b"\x00\xffnot json")
    else:  # a foreign profile copied under this key's filename
        foreign = json.loads(raw)
        foreign["device_kind"] = "somebody-else"
        path.write_text(json.dumps(foreign))
    assert store.load("test-device", "cpu", shape_class(512, 512, 40)) is None
    assert store.stats()["corrupt_dropped"] == 1
    assert not path.exists()
    p = _tune(tmp_path)
    assert p["source"] == "probed"
    assert fake_probes == ["feed", "feed"]


def test_probe_failure_skips_group_keeps_defaults(tmp_path, monkeypatch):
    def bad(reps, smoke, defaults):
        raise RuntimeError("probe exploded")

    def good(reps, smoke, defaults):
        return {"fetch_depth": 4}, {
            "probes": 1, "timings": {}, "default_s": 1.0, "best_s": 0.9,
            "speedup": 1.1,
        }

    monkeypatch.setattr(
        probemod, "PROBE_GROUPS",
        {"feed": (bad, ("feed_workers",)), "fetch": (good, ("fetch_depth",))},
    )
    p = _tune(tmp_path)
    assert p["groups"]["feed"]["ok"] is False
    assert "probe exploded" in p["groups"]["feed"]["error"]
    assert p["knobs"]["feed_workers"] == KNOB_DEFAULTS["feed_workers"]
    assert p["groups"]["fetch"]["ok"] is True
    assert p["knobs"]["fetch_depth"] == 4


# -- "auto" resolution -----------------------------------------------------

def test_explicit_wins_auto_pulls_profile(tmp_path, fake_probes):
    _tune(tmp_path, device_kind=None, backend=None)  # key on the REAL device
    cfg = RunConfig(
        feed_workers="auto",
        tile_size=64,  # explicit — the profile must not touch it
        tune_store_dir=str(tmp_path),
    )
    resolved, info = resolve_config(cfg, scene_shape=(512, 512, 40))
    assert resolved.feed_workers == 3
    assert resolved.tile_size == 64
    assert info["source"] == "store"
    assert info["probes"] == 0
    assert info["knobs"] == {"feed_workers": 3}
    assert "age_s" in info


def test_auto_without_store_is_byte_identical_defaults():
    cfg = RunConfig(**{k: "auto" for k in TUNABLE_KNOBS})
    resolved, info = resolve_config(cfg, scene_shape=(256, 256, 30))
    assert info["source"] == "defaults"
    assert resolved == RunConfig(), (
        "'auto' with no store must reproduce the default config exactly"
    )


def test_no_auto_is_identity_passthrough():
    cfg = RunConfig()
    resolved, info = resolve_config(cfg, scene_shape=(256, 256, 30))
    assert resolved is cfg
    assert info is None


def test_auto_key_miss_falls_back_to_defaults(tmp_path):
    cfg = RunConfig(feed_workers="auto", tune_store_dir=str(tmp_path))
    resolved, info = resolve_config(cfg, scene_shape=(64, 64, 10))
    assert resolved.feed_workers == KNOB_DEFAULTS["feed_workers"]
    assert info["source"] == "defaults"


def test_non_auto_string_rejected_at_config_time():
    with pytest.raises(ValueError, match="integer or 'auto'"):
        RunConfig(feed_workers="fast")


# -- drift pins ------------------------------------------------------------

def test_knob_defaults_match_runconfig():
    """KNOB_DEFAULTS (the tune module cannot import the driver) must
    mirror the RunConfig dataclass defaults exactly."""
    by_name = {f.name: f.default for f in dataclasses.fields(RunConfig)}
    for knob in TUNABLE_KNOBS:
        assert KNOB_DEFAULTS[knob] == by_name[knob], knob


def test_tune_sources_enum_pinned():
    from check_events_schema import TUNE_SOURCES

    assert set(TUNE_SOURCES) == {"probed", "store", "defaults"}


def test_probe_groups_cover_every_tunable_knob():
    covered = {
        k for _fn, knobs in probemod.PROBE_GROUPS.values() for k in knobs
    }
    assert covered == set(TUNABLE_KNOBS)


def test_shape_class_buckets():
    # jittered AOIs share a class; a thumbnail and a gigapixel never do
    assert shape_class(1024, 1024, 30) == shape_class(1400, 1400, 32)
    assert shape_class(256, 256, 30) != shape_class(8192, 8192, 30)
    assert shape_class(512, 512, 8) != shape_class(512, 512, 40)


# -- the lt tune CLI -------------------------------------------------------

def _cli(tmp_path, *extra):
    from land_trendr_tpu.cli import main

    return main([
        "tune", "--store-dir", str(tmp_path / "store"), "--smoke",
        "--reps", "1", *extra,
    ])


def test_cli_dry_run_reports_but_writes_nothing(tmp_path, capsys, fake_probes):
    assert _cli(tmp_path, "--dry-run") == 0
    report = json.loads(capsys.readouterr().out)
    assert report["source"] == "probed"
    assert report["persisted"] is False
    assert "feed" in report["groups"]
    store_dir = tmp_path / "store"
    assert not list(store_dir.glob("profile-*.json")), (
        "--dry-run must write nothing to the store"
    )


def test_cli_persists_then_reports_store_hit(tmp_path, capsys, fake_probes):
    assert _cli(tmp_path) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["persisted"] is True
    assert Path(report["profile_path"]).exists()
    assert _cli(tmp_path) == 0
    warm = json.loads(capsys.readouterr().out)
    assert warm["source"] == "store"
    assert warm["probes"] == 0
    assert warm["knobs"] == report["knobs"]
    assert fake_probes == ["feed"], "the warm CLI run must probe nothing"


# -- packed-upload buffer donation (SNIPPETS [2] satellite) ----------------

def test_unpack_donates_and_consumes_words():
    """The jitted unpack donates its word buffer: the declaration is
    pinned in source (behavioral equivalence rides the test_upload
    parity matrix), a fresh buffer unpacks bit-exactly, and the
    PackedUpload handle drops its reference once consumed so no later
    path can touch a deleted array."""
    import jax

    from land_trendr_tpu.runtime import feed as feedmod

    src = Path(REPO / "land_trendr_tpu/runtime/feed.py").read_text()
    assert 'donate_argnames=("words",)' in src

    rng = np.random.default_rng(3)
    dn = {"nir": rng.integers(0, 30000, (64, 5), dtype=np.int16)}
    qa = rng.integers(0, 4, (64, 5), dtype=np.uint16)

    cfg = RunConfig(upload_packed=True)
    uploader = feedmod.TileUploader(cfg, packed=True)
    handle = uploader.start(dn, qa)
    out_dn, out_qa = handle.arrays()
    np.testing.assert_array_equal(np.asarray(out_dn["nir"]), dn["nir"])
    np.testing.assert_array_equal(np.asarray(out_qa), qa)
    assert handle._words is None, "the donated buffer must be dropped"
    # a second tile gets a fresh buffer — donation never aliases tiles
    handle2 = uploader.start(dn, qa)
    out2, _ = handle2.arrays()
    np.testing.assert_array_equal(np.asarray(out2["nir"]), dn["nir"])
    del jax  # imported to assert a backend exists for device_put
