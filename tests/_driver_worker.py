"""Worker process for the true multi-process DRIVER test.

Run as: ``python _driver_worker.py <coordinator> <num_procs> <proc_id>
<workdir> <summary_json> [size] [tile] [telemetry] [overrides_json]``.
Each worker owns 4 virtual CPU
devices (``size``/``tile`` default to the test's tiny 48×40/20 scene;
``tools/multihost_bench.py`` passes larger ones for its artifact).
``overrides_json`` (optional) is a path to a JSON dict of extra
``RunConfig`` fields merged per process — how the elastic-scheduling
tests/soaks give one host a fault schedule or lease knobs.  The
worker joins the ``jax.distributed`` cluster, builds the SAME deterministic
synthetic stack as its peers, and calls the real production entry point —
``run_stack`` with a LOCAL device mesh over a SHARED workdir.  Inside
``run_stack``, ``host_share`` hands each process its half of the tiles and
the shared manifest accumulates every tile: the v5e-pod driver flow
(SURVEY.md §5 — per-host input feeding; tiles, not shards, cross hosts)
scaled down to two localhost processes.
"""

import json
import sys

import jax

# Must beat the sitecustomize's jax_platforms="axon,cpu" config selection
# *before* any device/backend touch, or a down TPU tunnel hangs the worker.
jax.config.update("jax_platforms", "cpu")


def main() -> int:
    coordinator, num_procs, proc_id, workdir, out_path = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4],
        sys.argv[5],
    )
    size = int(sys.argv[6]) if len(sys.argv) > 6 else 0
    tile = int(sys.argv[7]) if len(sys.argv) > 7 else 20
    telemetry = bool(int(sys.argv[8])) if len(sys.argv) > 8 else False
    overrides = {}
    if len(sys.argv) > 9 and sys.argv[9]:
        with open(sys.argv[9]) as f:
            overrides = json.load(f)

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
    from land_trendr_tpu.parallel import init_distributed, make_mesh
    from land_trendr_tpu.runtime import RunConfig, run_stack, stack_from_synthetic

    assert init_distributed(coordinator, num_procs, proc_id) is True
    assert jax.process_count() == num_procs

    mesh = make_mesh(jax.local_devices())  # local chips; tiles cross hosts
    spec = (
        SceneSpec(width=size, height=size, year_start=1990, year_end=2013, seed=11)
        if size
        else SceneSpec(width=48, height=40, year_start=1990, year_end=2013, seed=11)
    )
    scene = make_stack(spec)
    rs = stack_from_synthetic(scene)
    cfg = RunConfig(
        params=LTParams(max_segments=4, vertex_count_overshoot=2),
        tile_size=tile,  # default: 2×3 grid → 6 tiles, 3 per process
        workdir=workdir,
        out_dir=workdir + "_out",
        # per-process events.p<i>.jsonl in the shared workdir; the primary
        # folds every host's stream into its summary["telemetry"]["hosts"]
        telemetry=telemetry,
        **overrides,
    )
    summary = run_stack(rs, cfg, mesh=mesh)
    with open(out_path, "w") as f:
        json.dump(summary, f)
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
