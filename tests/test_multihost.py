"""Multi-host feeding path (SURVEY.md §5 distributed backend).

Single-process here, but the *same* code path a pod runs: a process feeds
its local pixel rows into a globally-sharded array, the SPMD program runs,
and the process reads back exactly its addressable rows.  On the virtual
8-device CPU mesh this process owns every shard, which is how a one-host
multi-chip machine runs in production too.
"""

import jax
import numpy as np
import pytest

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.ops.segment import jax_segment_pixels
from land_trendr_tpu.parallel import (
    feed_global,
    gather_local_rows,
    host_share,
    init_distributed,
    is_primary_host,
    make_mesh,
    pad_to_multiple,
)

PARAMS = LTParams(max_segments=4, vertex_count_overshoot=2)


def _series(rng, px, ny=24):
    years = np.arange(1990, 1990 + ny, dtype=np.int32)
    t = np.arange(ny, dtype=np.float64)[None, :]
    d = rng.integers(5, ny - 5, size=(px, 1))
    vals = 0.6 - np.where(t >= d, 0.3, 0.0) + rng.normal(0, 0.01, (px, ny))
    mask = rng.uniform(size=(px, ny)) > 0.1
    return years, -vals, mask


def test_init_distributed_single_process_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert init_distributed() is False  # no coordinator → no-op
    assert is_primary_host()


def test_host_share_partitions_in_order():
    tiles = list(range(10))
    share = host_share(tiles)
    # single process → the whole list, order preserved
    assert share == tiles


def test_host_share_preserves_item_types():
    """Tuple items (e.g. (y0, x0) tile coords) come back as the same hashable
    tuples, usable as dict/set keys."""
    tiles = [(0, 0), (0, 1), (1, 0)]
    share = host_share(tiles)
    assert share == tiles
    assert all(isinstance(t, tuple) for t in share)
    assert set(share) == set(tiles)  # hashable


def test_feed_global_places_local_rows(rng):
    mesh = make_mesh()
    n_dev = mesh.devices.size
    years, vals, mask = _series(rng, px=2 * n_dev)
    gvals, gmask = feed_global(mesh, vals, mask)
    assert gvals.shape == vals.shape
    assert gvals.sharding.is_fully_addressable
    np.testing.assert_array_equal(np.asarray(gvals), vals)
    np.testing.assert_array_equal(np.asarray(gmask), mask)
    # pixel axis is actually sharded: each device holds px/n_dev rows
    shard_rows = {s.data.shape[0] for s in gvals.addressable_shards}
    assert shard_rows == {vals.shape[0] // n_dev}


def test_multihost_feed_matches_unsharded(rng):
    """Segmentation through the multi-host feed path matches the plain
    single-device call: every discrete decision (vertices, model choice) and
    the fitted trajectories are identical; only ``betainc``'s far-tail p
    values (1e-15-scale, decision-irrelevant) may wobble with XLA's
    partition-dependent fusion choices."""
    mesh = make_mesh()
    n_dev = mesh.devices.size
    years, vals, mask = _series(rng, px=3 * n_dev - 1)
    vals_p, mask_p, n_real = pad_to_multiple(vals, mask, n_dev)
    gvals, gmask = feed_global(mesh, vals_p, mask_p)
    out_sh = jax_segment_pixels(years, gvals, gmask, PARAMS)
    out_ref = jax_segment_pixels(years, vals_p, mask_p, PARAMS)
    for field in (
        "n_vertices", "vertex_indices", "vertex_years", "model_valid",
        "fitted", "despiked", "seg_duration",
    ):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_sh, field)),
            np.asarray(getattr(out_ref, field)),
            err_msg=field,
        )
    np.testing.assert_allclose(
        np.asarray(out_sh.rmse), np.asarray(out_ref.rmse), rtol=1e-9
    )
    # p-of-F agrees at the decision level (same pixels pass the threshold)
    np.testing.assert_array_equal(
        np.asarray(out_sh.p_of_f) <= PARAMS.p_val_threshold,
        np.asarray(out_ref.p_of_f) <= PARAMS.p_val_threshold,
    )


def test_gather_local_rows_roundtrip(rng):
    mesh = make_mesh()
    n_dev = mesh.devices.size
    years, vals, mask = _series(rng, px=2 * n_dev)
    gvals, gmask = feed_global(mesh, vals, mask)
    out = jax_segment_pixels(years, gvals, gmask, PARAMS)
    local = gather_local_rows(out.rmse)
    # single process owns all shards → local rows == global rows, in order
    np.testing.assert_array_equal(local, np.asarray(out.rmse))


def test_feed_global_rejects_indivisible(rng):
    mesh = make_mesh()
    n_dev = mesh.devices.size
    if n_dev == 1:
        pytest.skip("needs a multi-device mesh")
    years, vals, mask = _series(rng, px=n_dev + 1)
    with pytest.raises(ValueError):
        feed_global(mesh, vals, mask)


# ---------------------------------------------------------------------------
# TRUE multi-process jax.distributed (VERDICT round-1 missing item #2)
# ---------------------------------------------------------------------------


def test_two_process_distributed_matches_single(tmp_path):
    """Two real processes + localhost coordinator, 4 virtual CPU devices
    each: init_distributed → host_share → feed_global → sharded segment →
    gather_local_rows, per-process rows vs a single-process run."""
    import os

    from tests._pod_launch import launch_pod

    worker = os.path.join(os.path.dirname(__file__), "_distributed_worker.py")
    outs = [str(tmp_path / f"worker{i}.npz") for i in range(2)]
    launch_pod(worker, lambda i: ["2", str(i), outs[i]])

    # single-process reference on the SAME deterministic scene
    from tests._distributed_worker import make_scene

    years, vals, mask = make_scene(16, ny=24)  # 2 procs × 4 devs × 2 rows
    params = LTParams(max_segments=4, vertex_count_overshoot=2)
    ref = jax_segment_pixels(years, vals, mask, params)

    seen_rows = []
    for i in range(2):
        got = np.load(outs[i])
        rows = got["rows"]
        seen_rows.extend(rows.tolist())
        np.testing.assert_array_equal(
            got["vertex_indices"], np.asarray(ref.vertex_indices)[rows],
            err_msg=f"worker {i} vertex_indices",
        )
        np.testing.assert_array_equal(
            got["n_vertices"], np.asarray(ref.n_vertices)[rows]
        )
        np.testing.assert_array_equal(
            got["model_valid"], np.asarray(ref.model_valid)[rows]
        )
        np.testing.assert_array_equal(
            got["fitted"], np.asarray(ref.fitted)[rows]
        )
        np.testing.assert_allclose(
            got["rmse"], np.asarray(ref.rmse)[rows], rtol=1e-9
        )
    # the two host shares tile the scene exactly
    assert sorted(seen_rows) == list(range(16))


def test_two_process_driver_shares_tiles(tmp_path):
    """TRUE multi-process DRIVER run: two jax.distributed processes, each
    with a 4-device local mesh, run ``run_stack`` over a SHARED workdir;
    ``host_share`` splits the 6 tiles 3/3, the shared manifest accumulates
    all of them, and assembly (in this process) mosaics the full scene."""
    import json
    import os
    import shutil

    from tests._pod_launch import launch_pod

    worker = os.path.join(os.path.dirname(__file__), "_driver_worker.py")
    workdir = str(tmp_path / "shared_work")
    summaries = [str(tmp_path / f"summary{i}.json") for i in range(2)]
    launch_pod(
        worker,
        # size=0/tile=20 defaults, telemetry=1: the pod flow doubles as the
        # multihost telemetry acceptance run (per-process event files in
        # the shared workdir, primary-host merge into the run summary)
        lambda i: ["2", str(i), workdir, summaries[i], "0", "20", "1"],
        # a lost-port-race attempt may have part-written the shared workdir
        before_attempt=lambda: shutil.rmtree(workdir, ignore_errors=True),
    )

    # each process did exactly half the scene on its own 4-device mesh
    per_proc = [json.load(open(p)) for p in summaries]
    assert [s["mesh_devices"] for s in per_proc] == [4, 4]
    assert sorted(s["pixels"] for s in per_proc) == [960, 960]  # 3 tiles each
    assert sum(s["pixels"] for s in per_proc) == 40 * 48

    # telemetry: one event file per process, each schema-clean, and the
    # primary's summary carries the merged per-host fold
    from land_trendr_tpu.obs import events_path, validate_events_file

    for i in range(2):
        ev = events_path(workdir, i, 2)
        assert os.path.exists(ev)
        assert validate_events_file(ev) == []
    hosts = per_proc[0]["telemetry"]["hosts"]
    assert [h["process_index"] for h in hosts] == [0, 1]
    assert all(h["status"] == "ok" for h in hosts)
    assert sum(h["pixels"] for h in hosts) == 40 * 48
    assert sum(h["tiles_done"] for h in hosts) == 6
    assert "hosts" not in per_proc[1].get("telemetry", {})  # primary-only fold

    # pod-wide correlation: both processes stamped the shared manifest
    # header's ONE run_id into their run_start — the span model's join
    # key (obs/spans; one pod run = one run_id across all host streams)
    run_ids = []
    for i in range(2):
        with open(events_path(workdir, i, 2)) as f:
            rs = json.loads(f.readline())
        assert rs["ev"] == "run_start"
        run_ids.append(rs["run_id"])
    assert run_ids[0] == run_ids[1]
    assert [h["run_id"] for h in hosts] == run_ids

    # assembly from the shared workdir sees ALL tiles (mesh-blind consumer)
    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
    from land_trendr_tpu.runtime import (
        RunConfig,
        assemble_outputs,
        stack_from_synthetic,
    )
    from land_trendr_tpu.io.geotiff import read_geotiff

    scene = make_stack(
        SceneSpec(width=48, height=40, year_start=1990, year_end=2013, seed=11)
    )
    rs = stack_from_synthetic(scene)
    cfg = RunConfig(
        params=LTParams(max_segments=4, vertex_count_overshoot=2),
        tile_size=20, workdir=workdir, out_dir=str(tmp_path / "out"),
    )
    paths = assemble_outputs(rs, cfg)
    valid, _, _ = read_geotiff(paths["model_valid"])
    assert valid.shape == (40, 48)
    # both processes' halves contributed fitted pixels
    assert valid[:, :20].any() and valid[:, 40:].any()
