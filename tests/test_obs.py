"""Telemetry subsystem tests: events, metrics, exporters, consumers.

Covers the :mod:`land_trendr_tpu.obs` contract end to end — the
schema-versioned JSONL event stream (round-trip + thread-safe append), the
Prometheus text exposition (format invariants a scraper relies on), the
file/HTTP exporters, the ``tools/check_events_schema.py`` lint and
``tools/obs_report.py`` fold/trace consumers, the multihost per-process
merge, and a real CPU-backend driver run with ``RunConfig.telemetry`` on.
These run in the tier-1 suite: the event schema is a cross-PR contract
(producer = driver, consumers = report/dashboards) and must not drift
silently.
"""

import json
import math
import os
import re
import threading
import urllib.request

import pytest

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
from land_trendr_tpu.obs import (
    SCHEMA_VERSION,
    EventLog,
    MetricsHTTPServer,
    MetricsRegistry,
    PromFileExporter,
    events_path,
    iter_events,
    metrics_path,
    validate_event,
    validate_events_file,
)
from land_trendr_tpu.runtime import RunConfig, run_stack, stack_from_synthetic
from tools import check_events_schema, obs_report

# ---------------------------------------------------------------------------
# events: schema round-trip + atomic append
# ---------------------------------------------------------------------------


def _emit_valid_stream(log: EventLog) -> None:
    """One schema-complete run scope, exercising every event type."""
    log.run_start(
        fingerprint="fp", process_index=0, process_count=1, tiles_total=2,
        tiles_todo=2, tiles_skipped_resume=0, mesh_devices=1, impl="xla",
    )
    log.emit("tile_start", tile_id=0, attempt=1)
    log.emit(
        "tile_done", tile_id=0, px=1024, compute_s=0.5, px_per_s=2048.0,
        feed_backlog=1, write_backlog=0,
    )
    log.emit("tile_retry", tile_id=1, attempt=1, error="injected")
    log.emit("tile_start", tile_id=1, attempt=2)
    log.emit(
        "tile_done", tile_id=1, px=1024, compute_s=0.25, px_per_s=4096.0,
        feed_backlog=0, write_backlog=1, device_bytes_in_use=12345,
    )
    log.emit("write_done", tile_id=0, bytes=999, record_s=0.01, no_fit_rate=0.1)
    log.emit("write_done", tile_id=1, bytes=888, record_s=0.02)
    log.emit(
        "run_done", status="ok", tiles_done=2, pixels=2048, wall_s=1.0,
        px_per_s=2048.0, fit_rate=0.9, stage_s={"feed_s": 0.1},
    )


def test_event_schema_round_trip(tmp_path):
    path = events_path(str(tmp_path))
    assert path.endswith("events.jsonl")
    with EventLog(path) as log:
        _emit_valid_stream(log)
    recs = list(iter_events(path))
    assert [r["ev"] for r in recs] == [
        "run_start", "tile_start", "tile_done", "tile_retry", "tile_start",
        "tile_done", "write_done", "write_done", "run_done",
    ]
    # every event carries both clocks, stamped at emit time, non-decreasing
    # within the stream (monotonic clock)
    monos = [r["t_mono"] for r in recs]
    assert all(isinstance(r["t_wall"], float) for r in recs)
    assert monos == sorted(monos)
    assert recs[0]["schema"] == SCHEMA_VERSION
    assert recs[0]["pid"] == os.getpid()
    assert validate_events_file(path) == []


def test_validate_event_rejects_bad_records():
    ok = {
        "ev": "tile_start", "t_wall": 1.0, "t_mono": 2.0,
        "tile_id": 3, "attempt": 1,
    }
    assert validate_event(ok) == []
    # unknown extra fields are allowed (schema growth without a bump)
    assert validate_event({**ok, "novel_field": "x"}) == []
    assert validate_event({**ok, "ev": "bogus_event"})
    assert validate_event({k: v for k, v in ok.items() if k != "tile_id"})
    assert validate_event({**ok, "tile_id": "3"})  # wrong type
    assert validate_event({**ok, "tile_id": True})  # bool is not an int here
    assert validate_event([1, 2, 3])
    # OPTIONAL numeric fields get the same bool guard as required ones
    done = {
        "ev": "tile_done", "t_wall": 1.0, "t_mono": 2.0, "tile_id": 0,
        "px": 8, "compute_s": 0.1, "px_per_s": 80.0,
        "feed_backlog": 0, "write_backlog": 0,
    }
    assert validate_event(done) == []
    assert validate_event({**done, "device_bytes_in_use": 123}) == []
    assert validate_event({**done, "device_bytes_in_use": True})
    no_mono = {k: v for k, v in ok.items() if k != "t_mono"}
    assert any("t_mono" in e for e in validate_event(no_mono))


def test_validate_events_file_flags_structure(tmp_path):
    p = tmp_path / "events.jsonl"
    p.write_text(
        json.dumps({"ev": "tile_start", "t_wall": 1.0, "t_mono": 1.0,
                    "tile_id": 0, "attempt": 1}) + "\n" + "{not json\n"
    )
    errs = validate_events_file(str(p))
    assert any("expected 'run_start'" in e for e in errs)
    assert any("malformed JSON" in e for e in errs)
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert validate_events_file(str(empty)) == ["file contains no events"]


def test_event_log_thread_safe_append(tmp_path):
    """32 threads × 50 emits: every line lands whole (no interleaving)."""
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path)
    n_threads, n_each = 32, 50

    def worker(i: int) -> None:
        for j in range(n_each):
            log.emit("tile_start", tile_id=i * n_each + j, attempt=1)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.close()
    recs = list(iter_events(path))  # raises on any torn/partial JSON line
    assert len(recs) == n_threads * n_each
    assert {r["tile_id"] for r in recs} == set(range(n_threads * n_each))
    with pytest.raises(ValueError, match="closed"):
        log.emit("tile_start", tile_id=0, attempt=1)


def test_events_path_per_process(tmp_path):
    d = str(tmp_path)
    assert events_path(d).endswith("events.jsonl")
    assert events_path(d, 1, 4).endswith("events.p1.jsonl")
    assert metrics_path(d).endswith("metrics.prom")
    assert metrics_path(d, 2, 4).endswith("metrics.p2.prom")


# ---------------------------------------------------------------------------
# metrics: exposition format invariants
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""
    r"(,[a-zA-Z_+.\"=0-9]+)*\})? (NaN|[+-]?(Inf|[0-9.e+-]+))$"
)


def test_prometheus_exposition_format():
    r = MetricsRegistry()
    c = r.counter("lt_tiles_done_total", "tiles completed")
    g = r.gauge("lt_px_per_s", "throughput")
    h = r.histogram("lt_tile_compute_seconds", "latency", buckets=(0.1, 1.0, 10.0))
    c.inc()
    c.inc(2)
    g.set(1.5e6)
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    text = r.render()
    assert text.endswith("\n")
    lines = text.splitlines()
    # node-exporter text format 0.0.4: HELP before TYPE, TYPE before samples,
    # every non-comment line is a well-formed sample
    assert lines.index("# HELP lt_tiles_done_total tiles completed") \
        < lines.index("# TYPE lt_tiles_done_total counter")
    assert "# TYPE lt_px_per_s gauge" in lines
    assert "# TYPE lt_tile_compute_seconds histogram" in lines
    for ln in lines:
        if not ln.startswith("#"):
            assert _SAMPLE_RE.match(ln), ln
    # histogram contract: cumulative buckets, +Inf == count, sum exact
    assert 'lt_tile_compute_seconds_bucket{le="0.1"} 1' in lines
    assert 'lt_tile_compute_seconds_bucket{le="1.0"} 2' in lines
    assert 'lt_tile_compute_seconds_bucket{le="10.0"} 3' in lines
    assert 'lt_tile_compute_seconds_bucket{le="+Inf"} 4' in lines
    assert "lt_tile_compute_seconds_count 4" in lines
    [sum_ln] = [l for l in lines if l.startswith("lt_tile_compute_seconds_sum")]
    assert math.isclose(float(sum_ln.split()[-1]), 55.55)
    assert "lt_tiles_done_total 3.0" in lines


def test_metrics_registry_identity_rules():
    r = MetricsRegistry()
    c = r.counter("lt_x_total", "help")
    assert r.counter("lt_x_total") is c  # get-or-create on (name, labels)
    g1 = r.gauge("lt_stage_seconds", "per stage", labels={"stage": "feed"})
    g2 = r.gauge("lt_stage_seconds", "per stage", labels={"stage": "write"})
    assert g1 is not g2
    with pytest.raises(ValueError, match="already registered"):
        r.gauge("lt_x_total")
    with pytest.raises(ValueError, match="invalid metric name"):
        r.counter("0bad")
    with pytest.raises(ValueError, match="invalid label name"):
        r.counter("lt_ok_total", labels={"0bad": "v"})
    with pytest.raises(ValueError, match="cannot decrease"):
        c.inc(-1)
    h = r.histogram("lt_h", buckets=(1.0, 2.0))
    assert r.histogram("lt_h", buckets=(2.0, 1.0)) is h  # order-insensitive
    with pytest.raises(ValueError, match="different buckets"):
        r.histogram("lt_h", buckets=(1.0, 3.0))
    g1.set(2)
    g1.set_max(1)  # watermark keeps the max
    assert g1.value == 2
    g1.set_max(5)
    assert g1.value == 5
    # escaping: label values with quotes/backslashes/newlines stay
    # parseable (a raw line-feed would break the whole scrape)
    r.gauge("lt_info", labels={"v": 'a"b\\c\nd'}).set(1)
    assert '{v="a\\"b\\\\c\\nd"}' in r.render()


def test_prom_file_exporter_atomic_refresh(tmp_path):
    r = MetricsRegistry()
    c = r.counter("lt_n_total", "n")
    path = str(tmp_path / "metrics.prom")
    exp = PromFileExporter(r, path, interval_s=0.05)
    exp.start()
    assert os.path.exists(path)  # first exposition written synchronously
    c.inc(7)
    exp.stop()  # final flush on stop
    text = open(path).read()
    assert "lt_n_total 7" in text
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]
    with pytest.raises(ValueError, match="interval_s"):
        PromFileExporter(r, path, interval_s=0)


def test_metrics_http_endpoint():
    r = MetricsRegistry()
    r.counter("lt_scraped_total", "n").inc(3)
    srv = MetricsHTTPServer(r, port=0)  # ephemeral
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        with urllib.request.urlopen(url) as resp:
            assert resp.status == 200
            assert "0.0.4" in resp.headers["Content-Type"]
            body = resp.read().decode()
        assert "lt_scraped_total 3" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/other")
    finally:
        srv.stop()

    # --metrics-host plumbing: a loopback-restricted bind still serves
    srv = MetricsHTTPServer(r, port=0, host="127.0.0.1")
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics"
        ) as resp:
            assert resp.status == 200
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# consumers: schema lint CLI + report/trace export
# ---------------------------------------------------------------------------


def test_check_events_schema_cli(tmp_path, capsys):
    good = tmp_path / "events.jsonl"
    with EventLog(str(good)) as log:
        _emit_valid_stream(log)
    assert check_events_schema.main([str(good)]) == 0
    assert check_events_schema.main([str(tmp_path)]) == 0  # workdir form
    assert "OK (schema v1)" in capsys.readouterr().out

    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"ev":"tile_done","t_wall":1.0,"t_mono":1.0}\n')
    assert check_events_schema.main([str(bad)]) == 1
    err = capsys.readouterr().err
    assert "missing required field" in err
    assert check_events_schema.main([str(tmp_path / "nope.jsonl")]) == 2
    assert check_events_schema.main([str(tmp_path / "emptydir")]) == 2


def test_obs_report_fold_and_trace(tmp_path, capsys):
    wd = tmp_path / "wd"
    wd.mkdir()
    with EventLog(events_path(str(wd))) as log:
        _emit_valid_stream(log)
    trace = str(tmp_path / "trace.json")
    assert obs_report.main([str(wd), "--trace", trace]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["event_counts"]["tile_done"] == 2
    assert report["pixels"] == 2048
    assert report["retries"] == 1 and report["failures"] == 0
    assert report["tile_compute_s"]["n"] == 2
    assert report["max_feed_backlog"] == 1 and report["max_write_backlog"] == 1
    assert report["stage_s"] == {"feed_s": 0.1}
    [host] = report["hosts"]
    assert host["status"] == "ok" and host["impl"] == "xla"

    # chrome://tracing loadability: the JSON object form with traceEvents,
    # every event a known phase with numeric non-negative timestamps
    with open(trace) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert evs and report["trace"]["events"] == len(evs)
    for e in evs:
        assert e["ph"] in ("X", "i", "C", "M")
        if e["ph"] != "M":
            assert e["ts"] >= 0
        if e["ph"] == "X":
            assert e["dur"] >= 0
    names = {e["name"] for e in evs}
    assert {"tile 0", "tile 1", "retry tile 1", "backlog"} <= names
    # device-wait slices anchored at their tile_start, not inferred
    slices = [e for e in evs if e["ph"] == "X" and e.get("cat") == "device-wait"]
    assert len(slices) == 2

    # schema gate: a malformed stream refuses to fold unless --no-validate
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"ev":"nope","t_wall":1.0,"t_mono":1.0}\n')
    assert obs_report.main([str(bad)]) == 1
    capsys.readouterr()
    assert obs_report.main([str(bad), "--no-validate"]) == 0

    # --no-validate is best-effort on the post-mortem stream of a killed
    # run: torn JSON and field-incomplete records are counted, not fatal
    torn = tmp_path / "torn.jsonl"
    with EventLog(str(torn)) as log:
        log.run_start(
            fingerprint="fp", process_index=0, process_count=1,
            tiles_total=1, tiles_todo=1, tiles_skipped_resume=0,
            mesh_devices=1, impl="xla",
        )
        log.emit("tile_done", tile_id=0, px=7, compute_s=0.1,
                 px_per_s=70.0, feed_backlog=0, write_backlog=0)
        log.emit("tile_done", tile_id=1)  # field-incomplete
    with open(torn, "a") as f:
        f.write('{"t_wall": 1.0}\n')  # parsed-but-eventless foreign line
        f.write('{"ev":"tile_done","t_wall":1.0,"t_mo')  # torn final line
    capsys.readouterr()
    assert obs_report.main([str(torn), "--no-validate"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["malformed"] == 3 and rep["pixels"] == 7
    assert None not in rep["event_counts"]
    # a field-incomplete tile_done is malformed ALONE: not double-counted
    # under event_counts, and no half-folded stats entries
    assert rep["event_counts"]["tile_done"] == 1
    assert rep["tile_compute_s"]["n"] == 1 == rep["tile_px_per_s"]["n"]


def test_obs_report_resumed_file_last_scope_only(tmp_path, capsys):
    """A resumed file's report aggregates describe the LAST scope only —
    the aborted attempt's recomputed work must not double-count (same
    semantics as ``summarize_events_file``) — while the trace keeps both
    scopes: an abort + resume timeline is what a post-mortem wants."""
    f = tmp_path / "events.jsonl"
    with EventLog(str(f)) as log:
        log.run_start(
            fingerprint="fp", process_index=0, process_count=1,
            tiles_total=2, tiles_todo=2, tiles_skipped_resume=0,
            mesh_devices=1, impl="xla",
        )
        log.emit("tile_done", tile_id=0, px=100, compute_s=0.1,
                 px_per_s=1000.0, feed_backlog=3, write_backlog=0)
        log.emit("run_done", status="aborted", tiles_done=1, pixels=100,
                 wall_s=0.2, px_per_s=500.0, fit_rate=1.0,
                 stage_s={"feed_s": 0.5})
        log.run_start(
            fingerprint="fp", process_index=0, process_count=1,
            tiles_total=2, tiles_todo=1, tiles_skipped_resume=1,
            mesh_devices=1, impl="xla",
        )
        log.emit("tile_done", tile_id=1, px=60, compute_s=0.2,
                 px_per_s=300.0, feed_backlog=1, write_backlog=1)
        log.emit("run_done", status="ok", tiles_done=1, pixels=60,
                 wall_s=0.3, px_per_s=200.0, fit_rate=1.0,
                 stage_s={"feed_s": 0.1})
    trace = str(tmp_path / "tr.json")
    assert obs_report.main([str(f), "--trace", trace]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["pixels"] == 60  # NOT 160: the aborted scope is history
    assert rep["event_counts"]["tile_done"] == 1
    assert rep["stage_s"] == {"feed_s": 0.1}
    assert rep["tile_compute_s"]["n"] == 1
    assert rep["max_feed_backlog"] == 1  # last scope's backlog, not the abort's
    [host] = rep["hosts"]
    assert host["status"] == "ok"
    with open(trace) as fh:
        names = {e["name"] for e in json.load(fh)["traceEvents"]}
    assert {"tile 0", "tile 1"} <= names  # the trace keeps BOTH scopes


def test_discover_event_files_recovers_pod_shape(tmp_path):
    """Without ``process_count``, p0's latest ``run_start`` declares the
    shape: stale p-files from a previous LARGER pod run are excluded for
    the post-hoc consumers, not just the driver's merge."""
    from land_trendr_tpu.obs import discover_event_files

    wd = str(tmp_path)
    for pi in range(4):  # previous 4-host run
        with EventLog(events_path(wd, pi, 4)) as log:
            log.run_start(
                fingerprint="old", process_index=pi, process_count=4,
                tiles_total=4, tiles_todo=1, tiles_skipped_resume=0,
                mesh_devices=1, impl="xla",
            )
    for pi in range(2):  # workdir reused by a 2-host run
        with EventLog(events_path(wd, pi, 2)) as log:
            log.run_start(
                fingerprint="new", process_index=pi, process_count=2,
                tiles_total=2, tiles_todo=1, tiles_skipped_resume=0,
                mesh_devices=1, impl="xla",
            )
    got = [os.path.basename(p) for p in discover_event_files(wd)]
    assert got == ["events.p0.jsonl", "events.p1.jsonl"]


# ---------------------------------------------------------------------------
# multihost merge
# ---------------------------------------------------------------------------


def test_merge_host_event_logs(tmp_path):
    from land_trendr_tpu.parallel.multihost import merge_host_event_logs

    wd = str(tmp_path)
    for pi in range(2):
        with EventLog(events_path(wd, pi, 2)) as log:
            log.run_start(
                fingerprint="fp", process_index=pi, process_count=2,
                tiles_total=4, tiles_todo=2, tiles_skipped_resume=0,
                mesh_devices=1, impl="xla",
            )
            for t in range(2):
                tid = pi * 2 + t
                log.emit(
                    "tile_done", tile_id=tid, px=100, compute_s=0.1,
                    px_per_s=1000.0, feed_backlog=0, write_backlog=0,
                )
            if pi == 1:
                log.emit("tile_retry", tile_id=3, attempt=1, error="x")
            log.emit(
                "run_done", status="ok", tiles_done=2, pixels=200,
                wall_s=0.5, px_per_s=400.0, fit_rate=1.0,
            )
    hosts = merge_host_event_logs(wd, expect_hosts=2)
    assert [h["process_index"] for h in hosts] == [0, 1]
    assert all(h["status"] == "ok" for h in hosts)
    assert sum(h["pixels"] for h in hosts) == 400
    assert hosts[1]["tile_retries"] == 1 and hosts[0]["tile_retries"] == 0

    # a stale single-process events.jsonl in the reused shared workdir is
    # NOT a host: it must neither satisfy expect_hosts nor join the fold
    with EventLog(events_path(wd)) as stale:
        stale.run_start(
            fingerprint="old", process_index=0, process_count=1,
            tiles_total=1, tiles_todo=1, tiles_skipped_resume=0,
            mesh_devices=1, impl="xla",
        )
        stale.emit(
            "run_done", status="ok", tiles_done=1, pixels=50,
            wall_s=0.1, px_per_s=500.0, fit_rate=1.0,
        )
    hosts = merge_host_event_logs(wd, expect_hosts=2)
    assert len(hosts) == 2 and sum(h["pixels"] for h in hosts) == 400

    # stale p-files from a previous LARGER pod run (workdir reused after
    # resizing 4 -> 2 hosts) are dead streams, not hosts
    with EventLog(events_path(wd, 2, 4)) as ghost:
        ghost.run_start(
            fingerprint="old4", process_index=2, process_count=4,
            tiles_total=1, tiles_todo=1, tiles_skipped_resume=0,
            mesh_devices=1, impl="xla",
        )
        ghost.emit(
            "run_done", status="ok", tiles_done=1, pixels=25,
            wall_s=0.1, px_per_s=250.0, fit_rate=1.0,
        )
    hosts = merge_host_event_logs(wd, expect_hosts=2)
    assert [h["process_index"] for h in hosts] == [0, 1]
    assert sum(h["pixels"] for h in hosts) == 400

    # a resumed peer mid-stream: its file still carries the PREVIOUS
    # scope's run_done, but a run_start after it means "not terminal" —
    # the primary must keep waiting, then fold the partial scope
    with EventLog(events_path(wd, 1, 2)) as log:
        log.run_start(
            fingerprint="fp2", process_index=1, process_count=2,
            tiles_total=4, tiles_todo=2, tiles_skipped_resume=2,
            mesh_devices=1, impl="xla",
        )
    stale_scope = merge_host_event_logs(
        wd, expect_hosts=2, timeout_s=0.3, poll_s=0.05
    )
    assert stale_scope[1]["status"] is None  # waited, then partial fold

    # bounded wait: a missing peer yields a partial merge, not a hang
    os.remove(events_path(wd))
    os.remove(events_path(wd, 1, 2))
    partial = merge_host_event_logs(wd, expect_hosts=2, timeout_s=0.3, poll_s=0.05)
    assert len(partial) == 1


def test_merge_host_event_logs_stale_peer_file(tmp_path):
    """``newer_than``: a reused workdir's peer file untouched since the
    current run began holds only a PREVIOUS scope — its old ``run_done``
    must not satisfy the wait, and its summary is flagged ``stale``."""
    import time

    from land_trendr_tpu.parallel.multihost import merge_host_event_logs

    wd = str(tmp_path)
    for pi in range(2):
        with EventLog(events_path(wd, pi, 2)) as log:
            log.run_start(
                fingerprint="fp", process_index=pi, process_count=2,
                tiles_total=2, tiles_todo=1, tiles_skipped_resume=0,
                mesh_devices=1, impl="xla",
            )
            log.emit(
                "run_done", status="ok", tiles_done=1, pixels=100,
                wall_s=0.1, px_per_s=1000.0, fit_rate=1.0,
            )
    # peer 1 "died before this run's run_start": its stream predates the run
    past = time.time() - 1000.0
    os.utime(events_path(wd, 1, 2), (past, past))
    hosts = merge_host_event_logs(
        wd, expect_hosts=2, timeout_s=0.3, poll_s=0.05,
        newer_than=time.time() - 500.0,
    )
    assert len(hosts) == 2
    assert "stale" not in hosts[0]
    assert hosts[1].get("stale") is True  # previous-scope fold, marked
    # without the cutoff the tail probe alone cannot tell, and the old
    # run_done passes for a live host — the behavior the guard exists for
    hosts = merge_host_event_logs(wd, expect_hosts=2)
    assert "stale" not in hosts[1]


def test_telemetry_init_unwinds_on_bind_failure(tmp_path):
    """A taken --metrics-port must not leak the exporter thread / event fd."""
    import socket

    from land_trendr_tpu.obs import Telemetry

    with socket.socket() as s:
        s.bind(("", 0))
        s.listen(1)
        port = s.getsockname()[1]
        with pytest.raises(OSError):
            Telemetry(str(tmp_path), metrics_port=port)
    assert not any(
        t.name == "lt-metrics-exporter" for t in threading.enumerate()
    )


def test_trace_process_labels_follow_file_order(tmp_path):
    """process_name metadata must share the spans' pid keying (file order),
    even when files are given in an order that disagrees with their
    recorded process_index."""
    for pi in range(2):
        with EventLog(events_path(str(tmp_path), pi, 2)) as log:
            log.run_start(
                fingerprint="fp", process_index=pi, process_count=2,
                tiles_total=1, tiles_todo=1, tiles_skipped_resume=0,
                mesh_devices=1, impl="xla",
            )
            log.emit("tile_start", tile_id=pi, attempt=1)
            log.emit(
                "tile_done", tile_id=pi, px=10, compute_s=0.1,
                px_per_s=100.0, feed_backlog=0, write_backlog=0,
            )
            log.emit(
                "run_done", status="ok", tiles_done=1, pixels=10,
                wall_s=0.2, px_per_s=50.0, fit_rate=1.0,
            )
    # deliberately reversed: file 0 = proc 1's stream
    report, spans = obs_report.fold(
        [events_path(str(tmp_path), 1, 2), events_path(str(tmp_path), 0, 2)]
    )
    out = tmp_path / "trace.json"
    obs_report.export_trace(spans, report["hosts"], str(out))
    evs = json.load(open(out))["traceEvents"]
    labels = {
        e["pid"]: e["args"]["name"]
        for e in evs if e.get("name") == "process_name"
    }
    slice_pids = {
        e["pid"]: e["name"]
        for e in evs if e["ph"] == "X"
    }
    # file 0 carries proc 1's events → pid 0's label says proc 1 and pid
    # 0's slice is tile 1 (proc 1's tile): label and spans agree
    assert labels[0] == "proc 1 @ " + report["hosts"][0]["host"]
    assert slice_pids[0] == "tile 1"
    assert labels[1].startswith("proc 0")
    assert slice_pids[1] == "tile 0"


# ---------------------------------------------------------------------------
# driver integration: RunConfig.telemetry through run_stack
# ---------------------------------------------------------------------------

SPEC = SceneSpec(width=48, height=40, year_start=1990, year_end=2013, seed=11)
PARAMS = LTParams(max_segments=4, vertex_count_overshoot=2)


@pytest.fixture(scope="module")
def rstack():
    return stack_from_synthetic(make_stack(SPEC))


def make_cfg(tmp, **kw):
    kw.setdefault("params", PARAMS)
    kw.setdefault("tile_size", 32)
    return RunConfig(
        workdir=os.path.join(tmp, "work"), out_dir=os.path.join(tmp, "out"), **kw
    )


def test_runconfig_telemetry_validation(tmp_path):
    with pytest.raises(ValueError, match="metrics_port requires telemetry"):
        make_cfg(str(tmp_path), metrics_port=0)
    with pytest.raises(ValueError, match="outside 0..65535"):
        make_cfg(str(tmp_path), telemetry=True, metrics_port=70000)
    with pytest.raises(ValueError, match="metrics_interval_s"):
        make_cfg(str(tmp_path), telemetry=True, metrics_interval_s=0)
    with pytest.raises(ValueError, match="metrics_host requires metrics_port"):
        make_cfg(str(tmp_path), telemetry=True, metrics_host="127.0.0.1")


def test_driver_telemetry_end_to_end(tmp_path, rstack):
    """A real (CPU-backend) telemetry run: valid events, well-formed
    exposition, live /metrics endpoint, summary pointers."""
    cfg = make_cfg(str(tmp_path), telemetry=True, metrics_port=0)
    summary = run_stack(rstack, cfg)
    tel = summary["telemetry"]
    assert tel["events"] == events_path(cfg.workdir)
    assert tel["metrics"] == metrics_path(cfg.workdir)
    assert isinstance(tel["metrics_port"], int)  # ephemeral port was bound

    # every event validates; lifecycle is complete and consistent
    assert validate_events_file(tel["events"]) == []
    recs = list(iter_events(tel["events"]))
    by_ev = {}
    for r in recs:
        by_ev.setdefault(r["ev"], []).append(r)
    assert len(by_ev["run_start"]) == 1
    assert len(by_ev["tile_done"]) == summary["tiles"] == 4
    assert len(by_ev["write_done"]) == 4
    assert {r["tile_id"] for r in by_ev["tile_done"]} == set(range(4))
    assert sum(r["px"] for r in by_ev["tile_done"]) == summary["pixels"]
    [done] = by_ev["run_done"]
    assert done["status"] == "ok" and done["pixels"] == summary["pixels"]
    assert set(done["stage_s"]) >= {"feed_s", "compute_s", "write_s"}
    # write_done events carry the per-tile quality metadata the manifest has
    assert all("no_fit_rate" in r for r in by_ev["write_done"])

    # the final exposition flush reflects the whole run
    text = open(tel["metrics"]).read()
    assert "lt_tiles_done_total 4" in text
    assert f"lt_pixels_total {summary['pixels']}" in text
    assert "lt_tile_compute_seconds_count 4" in text
    assert 'lt_run_info{fingerprint="' in text
    assert 'lt_stage_seconds{stage="compute"}' in text

    # events fold into a clean report + trace (the acceptance path)
    report, spans = obs_report.fold([tel["events"]])
    assert report["event_counts"]["run_done"] == 1
    trace = os.path.join(str(tmp_path), "trace.json")
    assert obs_report.export_trace(spans, report["hosts"], trace) > 0
    json.load(open(trace))


def test_driver_telemetry_retry_and_abort_events(tmp_path, rstack, monkeypatch):
    from land_trendr_tpu.ops.tile import process_tile_dn as real_op

    calls = {"n": 0}

    def flaky(*a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient fault")
        return real_op(*a, **k)

    monkeypatch.setattr("land_trendr_tpu.runtime.driver.process_tile_dn", flaky)
    cfg = make_cfg(str(tmp_path), telemetry=True, max_retries=2)
    run_stack(rstack, cfg)
    ev_file = events_path(cfg.workdir)
    assert validate_events_file(ev_file) == []
    recs = list(iter_events(ev_file))
    retries = [r for r in recs if r["ev"] == "tile_retry"]
    assert len(retries) == 1 and "transient fault" in retries[0]["error"]
    # the retried tile re-announces with attempt=2
    assert any(
        r["ev"] == "tile_start" and r["attempt"] == 2
        and r["tile_id"] == retries[0]["tile_id"] for r in recs
    )

    # hard abort: stream terminates with run_done status="aborted"
    def boom(*a, **k):
        raise RuntimeError("injected device fault")

    monkeypatch.setattr("land_trendr_tpu.runtime.driver.process_tile_dn", boom)
    cfg2 = make_cfg(os.path.join(str(tmp_path), "abort"), telemetry=True,
                    max_retries=0)
    with pytest.raises(RuntimeError, match="failed after"):
        run_stack(rstack, cfg2)
    recs2 = list(iter_events(events_path(cfg2.workdir)))
    assert validate_events_file(events_path(cfg2.workdir)) == []
    assert recs2[-1]["ev"] == "run_done" and recs2[-1]["status"] == "aborted"
    assert any(r["ev"] == "tile_failed" for r in recs2)
    # exporters shut down on the abort path too: final exposition exists
    assert os.path.exists(metrics_path(cfg2.workdir))


def test_driver_telemetry_resume_appends_new_scope(tmp_path, rstack):
    cfg = make_cfg(str(tmp_path), telemetry=True)
    run_stack(rstack, cfg)
    summary = run_stack(rstack, cfg)  # resume: all tiles done
    assert summary["tiles_skipped_resume"] == 4
    ev_file = events_path(cfg.workdir)
    assert validate_events_file(ev_file) == []
    starts = [r for r in iter_events(ev_file) if r["ev"] == "run_start"]
    assert len(starts) == 2  # one scope per run, appended to the same file
    assert starts[1]["tiles_skipped_resume"] == 4 and starts[1]["tiles_todo"] == 0
