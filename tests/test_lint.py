"""lt-lint suite: fixtures per rule, suppression mechanics, repo gate.

One POSITIVE (the rule catches it) and one NEGATIVE (clean idiomatic
code passes) fixture per rule LT001–LT005, plus the suppression
contract (inline ``# lt: noqa[rule]`` and reasoned LINT_BASELINE
entries both actually suppress; a reason-less baseline entry is an
error) and the tier-1 gate: ``tools/lt_lint.py --json`` over the real
tree exits 0 — zero unbaselined findings, every PR.  The lintkit is
stdlib-only and jax-free, so this whole module is seconds-scale.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from land_trendr_tpu.lintkit import (
    Baseline,
    BaselineError,
    ConfigDocChecker,
    EventSchemaChecker,
    HostSyncChecker,
    JitPurityChecker,
    LockDisciplineChecker,
    RepoCtx,
    default_checkers,
    run_rules,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LT_LINT = os.path.join(REPO, "tools", "lt_lint.py")


def lint_source(checker, source: str, relpath: str, tmp_path) -> list:
    """Run one rule over one fixture file inside a throwaway repo."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    repo = RepoCtx(str(tmp_path), files=[relpath])
    return list(checker.check(repo))


# ---------------------------------------------------------------------------
# LT001 — lock discipline


LT001_MODULE_POSITIVE = """
    import threading

    _lock = threading.Lock()
    _count = 0
    _sizes = {}

    def bump():
        global _count
        with _lock:
            _count += 1
            _sizes["n"] = _count

    def reset():          # mutation outside the lock
        global _count
        _count = 0

    def peek():           # torn snapshot: return read outside the lock
        return dict(_sizes)
"""

LT001_MODULE_NEGATIVE = """
    import threading

    _lock = threading.Lock()
    _count = 0
    _tl = threading.local()      # thread-local: needs no lock

    def bump():
        global _count
        with _lock:
            _count += 1
            _drain_locked()

    def _drain_locked():         # *_locked convention: caller holds it
        global _count
        _count = 0

    def peek():
        with _lock:
            return _count

    def mark():
        _tl.flag = True          # unguarded name: not lock-owned state
"""


def test_lt001_module_positive(tmp_path):
    found = lint_source(
        LockDisciplineChecker(), LT001_MODULE_POSITIVE, "mod.py", tmp_path
    )
    assert any("_count" in f.message and "assignment" in f.message for f in found)
    assert any("_sizes" in f.message and "return reads" in f.message for f in found)
    assert all(f.rule_id == "LT001" for f in found)


def test_lt001_module_negative(tmp_path):
    assert not lint_source(
        LockDisciplineChecker(), LT001_MODULE_NEGATIVE, "mod.py", tmp_path
    )


LT001_CLASS_POSITIVE = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def add(self, x):
            with self._lock:
                self._items.append(x)

        def drop(self):              # mutating call outside the lock
            self._items.clear()

        def snapshot(self):          # torn snapshot outside the lock
            return list(self._items)
"""

LT001_CLASS_NEGATIVE = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []         # __init__ happens-before sharing

        def add(self, x):
            with self._lock:
                self._items.append(x)

        def drain(self):
            with self._lock:
                return self._flush_locked()

        def _flush_locked(self):
            out = list(self._items)
            self._items.clear()
            return out
"""


def test_lt001_class_positive(tmp_path):
    found = lint_source(
        LockDisciplineChecker(), LT001_CLASS_POSITIVE, "box.py", tmp_path
    )
    assert any(".clear() call" in f.message for f in found)
    assert any("return reads" in f.message for f in found)


def test_lt001_class_negative(tmp_path):
    assert not lint_source(
        LockDisciplineChecker(), LT001_CLASS_NEGATIVE, "box.py", tmp_path
    )


def test_lt001_nested_attribute_store(tmp_path):
    # mutation THROUGH a guarded object (self._stats.hits = ...) is a
    # mutation of guarded state, same as item assignment
    src = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._stats = Stats()

            def ok(self):
                with self._lock:
                    self._stats.hits = 1

            def racy(self):
                self._stats.hits = 2
    """
    found = lint_source(LockDisciplineChecker(), src, "s.py", tmp_path)
    assert len(found) == 1
    assert "attribute assignment" in found[0].message
    # the racy() body line, not the locked ok() one
    assert "self._stats" in found[0].message


def test_lt001_inherited_lock(tmp_path):
    # the obs/metrics.py shape: the base holds the (shared) lock, the
    # subclass mutates under it — an unlocked subclass read is caught
    src = """
        import threading

        class Base:
            def __init__(self, lock):
                self._lock = lock

        class Counter(Base):
            def __init__(self, lock):
                super().__init__(lock)
                self._value = 0.0

            def inc(self):
                with self._lock:
                    self._value += 1

            def peek(self):
                return self._value
    """
    found = lint_source(LockDisciplineChecker(), src, "m.py", tmp_path)
    assert any("Counter" in f.message and "_value" in f.message for f in found)


# ---------------------------------------------------------------------------
# LT002 — host sync outside the fetch path


LT002_SOURCE = """
    import numpy as np

    def collect(dev_arrays):
        out = [np.asarray(a) for a in dev_arrays]   # blocking D2H
        dev_arrays[0].block_until_ready()
        return out, dev_arrays[1].item()
"""


def test_lt002_positive_in_scope(tmp_path):
    found = lint_source(
        HostSyncChecker(), LT002_SOURCE,
        "land_trendr_tpu/runtime/widget.py", tmp_path,
    )
    kinds = "\n".join(f.message for f in found)
    assert "np.asarray" in kinds
    assert "block_until_ready" in kinds
    assert ".item()" in kinds
    assert all(f.rule_id == "LT002" for f in found)


def test_lt002_negative_out_of_scope_and_blessed(tmp_path):
    # same code outside the scoped modules: not the rule's business
    assert not lint_source(
        HostSyncChecker(), LT002_SOURCE, "land_trendr_tpu/io/widget.py",
        tmp_path,
    )
    # and runtime/fetch.py IS the fetch path — blessed wholesale
    assert not lint_source(
        HostSyncChecker(), LT002_SOURCE, "land_trendr_tpu/runtime/fetch.py",
        tmp_path,
    )


# ---------------------------------------------------------------------------
# LT003 — jit purity


LT003_POSITIVE = """
    import functools
    import os
    import jax

    _calls = 0

    @functools.partial(jax.jit, static_argnames=("n",))
    def kernel(x, n):
        global _calls
        _calls += 1          # global mutation at trace time
        print("tracing")     # fires once, then never again
        return helper(x)

    def helper(x):           # reachable from the jitted root
        os.remove("scratch")
        return x * 2
"""

LT003_NEGATIVE = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(x):
        jax.debug.print("x={}", x)   # the sanctioned traced side-channel
        return jnp.sum(x * 2)

    def untraced_io(path):
        with open(path) as f:        # not jitted, not reachable from one
            return f.read()
"""


def test_lt003_positive(tmp_path):
    found = lint_source(JitPurityChecker(), LT003_POSITIVE, "k.py", tmp_path)
    msgs = "\n".join(f.message for f in found)
    assert "print() call" in msgs
    assert "mutation of global '_calls'" in msgs
    assert "os.remove" in msgs and "reachable" in msgs
    assert all("kernel" in f.message for f in found)


def test_lt003_negative(tmp_path):
    assert not lint_source(JitPurityChecker(), LT003_NEGATIVE, "k.py", tmp_path)


# ---------------------------------------------------------------------------
# LT004 — RunConfig / CLI / README coupling


def _write_config_repo(tmp_path, *, cli_flags, readme_rows, fields):
    (tmp_path / "land_trendr_tpu" / "runtime").mkdir(parents=True)
    field_src = "\n".join(f"    {name}: int = 0" for name in fields)
    (tmp_path / "land_trendr_tpu" / "runtime" / "driver.py").write_text(
        "import dataclasses\n\n"
        "@dataclasses.dataclass(frozen=True)\n"
        f"class RunConfig:\n{field_src}\n"
    )
    flag_src = "\n".join(f'    seg.add_argument("--{f}")' for f in cli_flags)
    (tmp_path / "land_trendr_tpu" / "cli.py").write_text(
        "def build_parser(p):\n"
        "    sub = p.add_subparsers()\n"
        '    seg = sub.add_parser("segment")\n'
        f"{flag_src}\n"
        '    pix = sub.add_parser("pixel")\n'
        '    pix.add_argument("--other-only")\n'
    )
    rows = "\n".join(f"| `{r}` | `--{r}` | 0 | a knob |" for r in readme_rows)
    (tmp_path / "README.md").write_text(
        "# t\n\n## Run configuration\n\n"
        "| field | CLI flag | default | meaning |\n|---|---|---|---|\n"
        f"{rows}\n\n## Next section\n"
    )


def test_lt004_positive(tmp_path):
    _write_config_repo(
        tmp_path,
        fields=("tile_size", "ghost_knob"),
        cli_flags=("tile-size",),          # ghost_knob: no flag
        readme_rows=("tile_size", "stale_row"),  # ghost_knob: no row
    )
    found = list(ConfigDocChecker().check(RepoCtx(str(tmp_path))))
    msgs = "\n".join(f.message for f in found)
    assert "RunConfig.ghost_knob has no CLI flag" in msgs
    assert "RunConfig.ghost_knob has no row" in msgs
    assert "'stale_row' names no RunConfig field" in msgs
    assert len(found) == 3


def test_lt004_negative(tmp_path):
    _write_config_repo(
        tmp_path,
        fields=("tile_size", "resume"),
        cli_flags=("tile-size", "no-resume"),  # negated alias accepted
        readme_rows=("tile_size", "resume"),
    )
    assert not list(ConfigDocChecker().check(RepoCtx(str(tmp_path))))


def test_lt004_other_subparser_flag_does_not_count(tmp_path):
    # --other-only exists on the pixel subparser (see _write_config_repo);
    # a field projected only there must still be flagged for segment
    _write_config_repo(
        tmp_path,
        fields=("tile_size", "other_only"),
        cli_flags=("tile-size",),
        readme_rows=("tile_size", "other_only"),
    )
    found = list(ConfigDocChecker().check(RepoCtx(str(tmp_path))))
    assert len(found) == 1
    assert "RunConfig.other_only has no CLI flag" in found[0].message


def test_lt004_helper_and_group_flags_count(tmp_path):
    # the _add_param_flags(seg) pattern: flags added inside a helper the
    # segment parser is passed to (via an argument group) still count
    (tmp_path / "land_trendr_tpu" / "runtime").mkdir(parents=True)
    (tmp_path / "land_trendr_tpu" / "runtime" / "driver.py").write_text(
        "import dataclasses\n\n"
        "@dataclasses.dataclass(frozen=True)\n"
        "class RunConfig:\n    params: int = 0\n    scale: float = 1.0\n"
    )
    (tmp_path / "land_trendr_tpu" / "cli.py").write_text(
        "def _add_param_flags(p):\n"
        '    g = p.add_argument_group("algorithm parameters")\n'
        '    g.add_argument("--params-json")\n'
        "def build_parser(p):\n"
        "    sub = p.add_subparsers()\n"
        '    seg = sub.add_parser("segment")\n'
        '    grp = seg.add_argument_group("run")\n'
        '    grp.add_argument("--scale")\n'
        "    _add_param_flags(seg)\n"
    )
    (tmp_path / "README.md").write_text(
        "## Run configuration\n\n| field | flag |\n|---|---|\n"
        "| `params` | `--params-json` |\n| `scale` | `--scale` |\n"
    )
    assert not list(ConfigDocChecker().check(RepoCtx(str(tmp_path))))


# ---------------------------------------------------------------------------
# LT005 — emit-site schema drift


def _lint_telemetry(tmp_path, source: str, schema_tool: "str | None" = None):
    rel = "land_trendr_tpu/obs/telemetry.py"
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    if schema_tool is not None:
        (tmp_path / "tools").mkdir(exist_ok=True)
        (tmp_path / "tools" / "check_events_schema.py").write_text(
            textwrap.dedent(schema_tool)
        )
    return list(EventSchemaChecker().check(RepoCtx(str(tmp_path))))


LT005_POSITIVE = """
    class Telemetry:
        def start(self, tile_id):
            self.events.emit("tile_start", tile_id=tile_id)   # no 'attempt'

        def done(self, tile_id):
            self.events.emit(
                "tile_done", tile_id=tile_id, px=1, compute_s=0.1,
                px_per_s=10.0, feed_backlog=0, write_backlog=0,
                pxx=3,                                        # typo'd field
            )

        def custom(self):
            self.events.emit("no_such_event")                 # unknown type
"""

LT005_NEGATIVE = """
    class Telemetry:
        def start(self, tile_id):
            self.events.emit("tile_start", tile_id=tile_id, attempt=1)

        def done(self, tile_id, hbm):
            fields = {}
            if hbm is not None:
                fields["device_bytes_in_use"] = hbm          # known optional
            self.events.emit(
                "tile_done", tile_id=tile_id, px=1, compute_s=0.1,
                px_per_s=10.0, feed_backlog=0, write_backlog=0, **fields,
            )

        def forward(self, **fields):
            # unresolvable splat: requiredness is skipped, not guessed
            self.events.emit("run_done", **fields)
"""


def test_lt005_positive(tmp_path):
    found = _lint_telemetry(tmp_path, LT005_POSITIVE)
    msgs = "\n".join(f.message for f in found)
    assert "never sets required field 'attempt'" in msgs
    assert "passes field 'pxx'" in msgs
    assert "unknown event type 'no_such_event'" in msgs


def test_lt005_negative(tmp_path):
    assert not _lint_telemetry(tmp_path, LT005_NEGATIVE)


def test_lt005_value_table_cross_check(tmp_path):
    found = _lint_telemetry(
        tmp_path,
        LT005_NEGATIVE,
        schema_tool="""
            NONNEG_FIELDS = {
                "fetch": ("tiles", "made_up_field"),
                "bogus_event": ("x",),
            }
        """,
    )
    msgs = "\n".join(f.message for f in found)
    assert "unknown event 'bogus_event'" in msgs
    assert "'made_up_field'" in msgs


# ---------------------------------------------------------------------------
# suppressions: noqa + baseline


def test_noqa_suppresses_on_line_and_comment_block(tmp_path):
    src = """
        import threading

        _lock = threading.Lock()
        _count = 0

        def bump():
            global _count
            with _lock:
                _count += 1

        def reset():
            global _count
            _count = 0  # lt: noqa[LT001]

        def peek():
            # single-writer startup path, readers not yet running
            # lt: noqa[LT001]
            return _count
    """
    rel = "mod.py"
    (tmp_path / rel).write_text(textwrap.dedent(src))
    repo = RepoCtx(str(tmp_path), files=[rel])
    report = run_rules(repo, [LockDisciplineChecker()])
    assert report["findings"] == []
    assert report["noqa_suppressed"] == 2


def test_noqa_other_rule_does_not_suppress(tmp_path):
    src = """
        import threading

        _lock = threading.Lock()
        _count = 0

        def bump():
            global _count
            with _lock:
                _count += 1

        def reset():
            global _count
            _count = 0  # lt: noqa[LT999]
    """
    rel = "mod.py"
    (tmp_path / rel).write_text(textwrap.dedent(src))
    repo = RepoCtx(str(tmp_path), files=[rel])
    report = run_rules(repo, [LockDisciplineChecker()])
    assert len(report["findings"]) == 1


def test_baseline_suppresses_and_reports_stale(tmp_path):
    rel = "land_trendr_tpu/runtime/widget.py"
    path = tmp_path / rel
    path.parent.mkdir(parents=True)
    path.write_text("import numpy as np\n\ndef f(a):\n    return np.asarray(a)\n")
    baseline = Baseline(
        [
            {
                "rule": "LT002", "file": rel, "contains": "np.asarray",
                "reason": "fixture: deliberately blessed",
            },
            {
                "rule": "LT001", "file": "nowhere.py",
                "reason": "fixture: stale entry",
            },
        ]
    )
    repo = RepoCtx(str(tmp_path), files=[rel])
    report = run_rules(repo, [HostSyncChecker()], baseline)
    assert report["findings"] == []
    assert len(report["baselined"]) == 1
    assert report["baselined"][0][1]["reason"] == "fixture: deliberately blessed"
    assert report["unused_baseline"] == [baseline.entries[1]]


def test_baseline_requires_reason():
    with pytest.raises(BaselineError, match="reason"):
        Baseline([{"rule": "LT001", "file": "x.py"}])


# ---------------------------------------------------------------------------
# the tier-1 repo gate + CLI surface


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, LT_LINT, *args],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )


def test_repo_tree_is_clean():
    """The acceptance gate: zero unbaselined findings over the real tree.

    Budget: the linter is stdlib-AST only (no jax import), so the whole
    repo parses and checks in low single-digit seconds.
    """
    proc = _run_cli("--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["clean"] is True
    assert report["findings"] == []
    # the deliberate exceptions stay visible, reasons attached
    assert all(e["reason"] for e in report["baselined"])
    # and none of them went stale
    assert report["unused_baseline"] == []
    assert report["files_checked"] > 50


def test_changed_files_lists_untracked_dir_contents(tmp_path):
    """A brand-new package directory must contribute its FILES to the
    --changed set: bare `git status --porcelain` collapses it to one
    'dir/' entry that matches nothing, green-lighting a new subsystem."""
    from tools.lt_lint import changed_files

    subprocess.run(["git", "init", "-q"], cwd=tmp_path, check=True)
    pkg = tmp_path / "pkg" / "sub"
    pkg.mkdir(parents=True)
    (pkg / "a.py").write_text("x = 1\n")
    (pkg / "b.py").write_text("y = 2\n")
    changed = changed_files(tmp_path)
    assert changed is not None
    assert {"pkg/sub/a.py", "pkg/sub/b.py"} <= changed


def test_cli_changed_mode_runs():
    proc = _run_cli("--changed", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout)["clean"] is True


def test_cli_single_path_and_list_rules():
    proc = _run_cli("land_trendr_tpu/io/blockcache.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule in ("LT001", "LT002", "LT003", "LT004", "LT005"):
        assert rule in proc.stdout


def test_cli_rejects_reasonless_baseline(tmp_path):
    bad = tmp_path / "baseline.json"
    bad.write_text(json.dumps({"entries": [{"rule": "LT001", "file": "x.py"}]}))
    proc = _run_cli("--baseline", str(bad))
    assert proc.returncode == 2
    assert "reason" in proc.stderr


def test_cli_exits_one_on_findings(tmp_path):
    """A planted violation fails the run — the CI contract is exit 1."""
    # lint a single out-of-tree fixture through the real CLI
    fixture = tmp_path / "land_trendr_tpu" / "runtime" / "bad.py"
    fixture.parent.mkdir(parents=True)
    fixture.write_text("import numpy as np\n\ndef f(a):\n    return np.asarray(a)\n")
    # CLI paths are repo-relative; use the module API for the tmp tree
    repo = RepoCtx(str(tmp_path))
    report = run_rules(repo, default_checkers())
    assert any(f.rule_id == "LT002" for f in report["findings"])
