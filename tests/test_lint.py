"""lt-lint suite: fixtures per rule, suppression mechanics, repo gate.

One POSITIVE (the rule catches it) and one NEGATIVE (clean idiomatic
code passes) fixture per rule LT001–LT008, plus the suppression
contract (inline ``# lt: noqa[rule]`` and reasoned LINT_BASELINE
entries both actually suppress; a reason-less baseline entry is an
error; baseline entries key on rule + file + enclosing SYMBOL, never
line numbers), the SARIF / ``--prune-baseline`` CLI contract, and the
tier-1 gate: ``tools/lt_lint.py --json`` over the real tree exits 0 —
zero unbaselined findings, every PR — within the documented wall-time
budget (the interprocedural rules must not silently blow up tier-1).
The lintkit is stdlib-only and jax-free, so this whole module is
seconds-scale.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from land_trendr_tpu.lintkit import (
    Baseline,
    BaselineError,
    BlockingUnderLockChecker,
    ConfigDocChecker,
    EventSchemaChecker,
    HostSyncChecker,
    JitPurityChecker,
    LockDisciplineChecker,
    LockOrderChecker,
    RepoCtx,
    ResourceLifecycleChecker,
    default_checkers,
    run_rules,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LT_LINT = os.path.join(REPO, "tools", "lt_lint.py")

#: the repo-gate budget: a full eight-rule run over the tree (parse +
#: call-graph build + fixpoints) takes ~7s in this container; 30s is
#: the hard bound so the interprocedural pass cannot silently turn
#: tier-1 into a minutes-scale suite on slower CI hardware
LINT_BUDGET_S = 30.0


def lint_source(checker, source: str, relpath: str, tmp_path) -> list:
    """Run one rule over one fixture file inside a throwaway repo."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    repo = RepoCtx(str(tmp_path), files=[relpath])
    return list(checker.check(repo))


# ---------------------------------------------------------------------------
# LT001 — lock discipline


LT001_MODULE_POSITIVE = """
    import threading

    _lock = threading.Lock()
    _count = 0
    _sizes = {}

    def bump():
        global _count
        with _lock:
            _count += 1
            _sizes["n"] = _count

    def reset():          # mutation outside the lock
        global _count
        _count = 0

    def peek():           # torn snapshot: return read outside the lock
        return dict(_sizes)
"""

LT001_MODULE_NEGATIVE = """
    import threading

    _lock = threading.Lock()
    _count = 0
    _tl = threading.local()      # thread-local: needs no lock

    def bump():
        global _count
        with _lock:
            _count += 1
            _drain_locked()

    def _drain_locked():         # *_locked convention: caller holds it
        global _count
        _count = 0

    def peek():
        with _lock:
            return _count

    def mark():
        _tl.flag = True          # unguarded name: not lock-owned state
"""


def test_lt001_module_positive(tmp_path):
    found = lint_source(
        LockDisciplineChecker(), LT001_MODULE_POSITIVE, "mod.py", tmp_path
    )
    assert any("_count" in f.message and "assignment" in f.message for f in found)
    assert any("_sizes" in f.message and "return reads" in f.message for f in found)
    assert all(f.rule_id == "LT001" for f in found)


def test_lt001_module_negative(tmp_path):
    assert not lint_source(
        LockDisciplineChecker(), LT001_MODULE_NEGATIVE, "mod.py", tmp_path
    )


LT001_CLASS_POSITIVE = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def add(self, x):
            with self._lock:
                self._items.append(x)

        def drop(self):              # mutating call outside the lock
            self._items.clear()

        def snapshot(self):          # torn snapshot outside the lock
            return list(self._items)
"""

LT001_CLASS_NEGATIVE = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []         # __init__ happens-before sharing

        def add(self, x):
            with self._lock:
                self._items.append(x)

        def drain(self):
            with self._lock:
                return self._flush_locked()

        def _flush_locked(self):
            out = list(self._items)
            self._items.clear()
            return out
"""


def test_lt001_class_positive(tmp_path):
    found = lint_source(
        LockDisciplineChecker(), LT001_CLASS_POSITIVE, "box.py", tmp_path
    )
    assert any(".clear() call" in f.message for f in found)
    assert any("return reads" in f.message for f in found)


def test_lt001_class_negative(tmp_path):
    assert not lint_source(
        LockDisciplineChecker(), LT001_CLASS_NEGATIVE, "box.py", tmp_path
    )


def test_lt001_nested_attribute_store(tmp_path):
    # mutation THROUGH a guarded object (self._stats.hits = ...) is a
    # mutation of guarded state, same as item assignment
    src = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._stats = Stats()

            def ok(self):
                with self._lock:
                    self._stats.hits = 1

            def racy(self):
                self._stats.hits = 2
    """
    found = lint_source(LockDisciplineChecker(), src, "s.py", tmp_path)
    assert len(found) == 1
    assert "attribute assignment" in found[0].message
    # the racy() body line, not the locked ok() one
    assert "self._stats" in found[0].message


def test_lt001_inherited_lock(tmp_path):
    # the obs/metrics.py shape: the base holds the (shared) lock, the
    # subclass mutates under it — an unlocked subclass read is caught
    src = """
        import threading

        class Base:
            def __init__(self, lock):
                self._lock = lock

        class Counter(Base):
            def __init__(self, lock):
                super().__init__(lock)
                self._value = 0.0

            def inc(self):
                with self._lock:
                    self._value += 1

            def peek(self):
                return self._value
    """
    found = lint_source(LockDisciplineChecker(), src, "m.py", tmp_path)
    assert any("Counter" in f.message and "_value" in f.message for f in found)


# ---------------------------------------------------------------------------
# LT002 — host sync outside the fetch path


LT002_SOURCE = """
    import numpy as np

    def collect(dev_arrays):
        out = [np.asarray(a) for a in dev_arrays]   # blocking D2H
        dev_arrays[0].block_until_ready()
        return out, dev_arrays[1].item()
"""


def test_lt002_positive_in_scope(tmp_path):
    found = lint_source(
        HostSyncChecker(), LT002_SOURCE,
        "land_trendr_tpu/runtime/widget.py", tmp_path,
    )
    kinds = "\n".join(f.message for f in found)
    assert "np.asarray" in kinds
    assert "block_until_ready" in kinds
    assert ".item()" in kinds
    assert all(f.rule_id == "LT002" for f in found)


def test_lt002_negative_out_of_scope_and_blessed(tmp_path):
    # same code outside the scoped modules: not the rule's business
    assert not lint_source(
        HostSyncChecker(), LT002_SOURCE, "land_trendr_tpu/io/widget.py",
        tmp_path,
    )
    # and runtime/fetch.py IS the fetch path — blessed wholesale
    assert not lint_source(
        HostSyncChecker(), LT002_SOURCE, "land_trendr_tpu/runtime/fetch.py",
        tmp_path,
    )


# ---------------------------------------------------------------------------
# LT003 — jit purity


LT003_POSITIVE = """
    import functools
    import os
    import jax

    _calls = 0

    @functools.partial(jax.jit, static_argnames=("n",))
    def kernel(x, n):
        global _calls
        _calls += 1          # global mutation at trace time
        print("tracing")     # fires once, then never again
        return helper(x)

    def helper(x):           # reachable from the jitted root
        os.remove("scratch")
        return x * 2
"""

LT003_NEGATIVE = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(x):
        jax.debug.print("x={}", x)   # the sanctioned traced side-channel
        return jnp.sum(x * 2)

    def untraced_io(path):
        with open(path) as f:        # not jitted, not reachable from one
            return f.read()
"""


def test_lt003_positive(tmp_path):
    found = lint_source(JitPurityChecker(), LT003_POSITIVE, "k.py", tmp_path)
    msgs = "\n".join(f.message for f in found)
    assert "print() call" in msgs
    assert "mutation of global '_calls'" in msgs
    assert "os.remove" in msgs and "reachable" in msgs
    assert all("kernel" in f.message for f in found)


def test_lt003_negative(tmp_path):
    assert not lint_source(JitPurityChecker(), LT003_NEGATIVE, "k.py", tmp_path)


# ---------------------------------------------------------------------------
# LT004 — RunConfig / CLI / README coupling


def _write_config_repo(tmp_path, *, cli_flags, readme_rows, fields):
    (tmp_path / "land_trendr_tpu" / "runtime").mkdir(parents=True)
    field_src = "\n".join(f"    {name}: int = 0" for name in fields)
    (tmp_path / "land_trendr_tpu" / "runtime" / "driver.py").write_text(
        "import dataclasses\n\n"
        "@dataclasses.dataclass(frozen=True)\n"
        f"class RunConfig:\n{field_src}\n"
    )
    flag_src = "\n".join(f'    seg.add_argument("--{f}")' for f in cli_flags)
    (tmp_path / "land_trendr_tpu" / "cli.py").write_text(
        "def build_parser(p):\n"
        "    sub = p.add_subparsers()\n"
        '    seg = sub.add_parser("segment")\n'
        f"{flag_src}\n"
        '    pix = sub.add_parser("pixel")\n'
        '    pix.add_argument("--other-only")\n'
    )
    rows = "\n".join(f"| `{r}` | `--{r}` | 0 | a knob |" for r in readme_rows)
    (tmp_path / "README.md").write_text(
        "# t\n\n## Run configuration\n\n"
        "| field | CLI flag | default | meaning |\n|---|---|---|---|\n"
        f"{rows}\n\n## Next section\n"
    )


def test_lt004_positive(tmp_path):
    _write_config_repo(
        tmp_path,
        fields=("tile_size", "ghost_knob"),
        cli_flags=("tile-size",),          # ghost_knob: no flag
        readme_rows=("tile_size", "stale_row"),  # ghost_knob: no row
    )
    found = list(ConfigDocChecker().check(RepoCtx(str(tmp_path))))
    msgs = "\n".join(f.message for f in found)
    assert "RunConfig.ghost_knob has no CLI flag" in msgs
    assert "RunConfig.ghost_knob has no row" in msgs
    assert "'stale_row' names no RunConfig field" in msgs
    assert len(found) == 3


def test_lt004_negative(tmp_path):
    _write_config_repo(
        tmp_path,
        fields=("tile_size", "resume"),
        cli_flags=("tile-size", "no-resume"),  # negated alias accepted
        readme_rows=("tile_size", "resume"),
    )
    assert not list(ConfigDocChecker().check(RepoCtx(str(tmp_path))))


def test_lt004_other_subparser_flag_does_not_count(tmp_path):
    # --other-only exists on the pixel subparser (see _write_config_repo);
    # a field projected only there must still be flagged for segment
    _write_config_repo(
        tmp_path,
        fields=("tile_size", "other_only"),
        cli_flags=("tile-size",),
        readme_rows=("tile_size", "other_only"),
    )
    found = list(ConfigDocChecker().check(RepoCtx(str(tmp_path))))
    assert len(found) == 1
    assert "RunConfig.other_only has no CLI flag" in found[0].message


def test_lt004_helper_and_group_flags_count(tmp_path):
    # the _add_param_flags(seg) pattern: flags added inside a helper the
    # segment parser is passed to (via an argument group) still count
    (tmp_path / "land_trendr_tpu" / "runtime").mkdir(parents=True)
    (tmp_path / "land_trendr_tpu" / "runtime" / "driver.py").write_text(
        "import dataclasses\n\n"
        "@dataclasses.dataclass(frozen=True)\n"
        "class RunConfig:\n    params: int = 0\n    scale: float = 1.0\n"
    )
    (tmp_path / "land_trendr_tpu" / "cli.py").write_text(
        "def _add_param_flags(p):\n"
        '    g = p.add_argument_group("algorithm parameters")\n'
        '    g.add_argument("--params-json")\n'
        "def build_parser(p):\n"
        "    sub = p.add_subparsers()\n"
        '    seg = sub.add_parser("segment")\n'
        '    grp = seg.add_argument_group("run")\n'
        '    grp.add_argument("--scale")\n'
        "    _add_param_flags(seg)\n"
    )
    (tmp_path / "README.md").write_text(
        "## Run configuration\n\n| field | flag |\n|---|---|\n"
        "| `params` | `--params-json` |\n| `scale` | `--scale` |\n"
    )
    assert not list(ConfigDocChecker().check(RepoCtx(str(tmp_path))))


# ---------------------------------------------------------------------------
# LT005 — emit-site schema drift


def _lint_telemetry(tmp_path, source: str, schema_tool: "str | None" = None):
    rel = "land_trendr_tpu/obs/telemetry.py"
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    if schema_tool is not None:
        (tmp_path / "tools").mkdir(exist_ok=True)
        (tmp_path / "tools" / "check_events_schema.py").write_text(
            textwrap.dedent(schema_tool)
        )
    return list(EventSchemaChecker().check(RepoCtx(str(tmp_path))))


LT005_POSITIVE = """
    class Telemetry:
        def start(self, tile_id):
            self.events.emit("tile_start", tile_id=tile_id)   # no 'attempt'

        def done(self, tile_id):
            self.events.emit(
                "tile_done", tile_id=tile_id, px=1, compute_s=0.1,
                px_per_s=10.0, feed_backlog=0, write_backlog=0,
                pxx=3,                                        # typo'd field
            )

        def custom(self):
            self.events.emit("no_such_event")                 # unknown type
"""

LT005_NEGATIVE = """
    class Telemetry:
        def start(self, tile_id):
            self.events.emit("tile_start", tile_id=tile_id, attempt=1)

        def done(self, tile_id, hbm):
            fields = {}
            if hbm is not None:
                fields["device_bytes_in_use"] = hbm          # known optional
            self.events.emit(
                "tile_done", tile_id=tile_id, px=1, compute_s=0.1,
                px_per_s=10.0, feed_backlog=0, write_backlog=0, **fields,
            )

        def forward(self, **fields):
            # unresolvable splat: requiredness is skipped, not guessed
            self.events.emit("run_done", **fields)
"""


def test_lt005_positive(tmp_path):
    found = _lint_telemetry(tmp_path, LT005_POSITIVE)
    msgs = "\n".join(f.message for f in found)
    assert "never sets required field 'attempt'" in msgs
    assert "passes field 'pxx'" in msgs
    assert "unknown event type 'no_such_event'" in msgs


def test_lt005_negative(tmp_path):
    assert not _lint_telemetry(tmp_path, LT005_NEGATIVE)


def test_lt005_value_table_cross_check(tmp_path):
    found = _lint_telemetry(
        tmp_path,
        LT005_NEGATIVE,
        schema_tool="""
            NONNEG_FIELDS = {
                "fetch": ("tiles", "made_up_field"),
                "bogus_event": ("x",),
            }
        """,
    )
    msgs = "\n".join(f.message for f in found)
    assert "unknown event 'bogus_event'" in msgs
    assert "'made_up_field'" in msgs


# ---------------------------------------------------------------------------
# LT006 — lock-order cycles (interprocedural)


LT006_POSITIVE = """
    import threading

    class Pair:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def forward(self):
            with self._a_lock:
                self._grab_b()          # a -> b, one call deep

        def _grab_b(self):
            with self._b_lock:
                pass

        def backward(self):
            with self._b_lock:
                with self._a_lock:      # b -> a: the cycle
                    pass
"""

LT006_NEGATIVE = """
    import threading

    class Pair:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def forward(self):
            with self._a_lock:
                self._grab_b()

        def _grab_b(self):
            with self._b_lock:
                pass

        def also_forward(self):         # same a-before-b order: acyclic
            with self._a_lock:
                with self._b_lock:
                    pass
"""


def test_lt006_cycle_positive(tmp_path):
    found = lint_source(LockOrderChecker(), LT006_POSITIVE, "pair.py", tmp_path)
    assert len(found) == 1
    assert "lock-order cycle" in found[0].message
    assert "Pair._a_lock" in found[0].message and "Pair._b_lock" in found[0].message
    assert found[0].rule_id == "LT006"


def test_lt006_consistent_order_negative(tmp_path):
    assert not lint_source(
        LockOrderChecker(), LT006_NEGATIVE, "pair.py", tmp_path
    )


def test_lt006_multi_item_with(tmp_path):
    # `with A, B:` acquires B while A is held — the same edge as the
    # nested form, written in Python's most common multi-lock syntax
    src = """
        import threading

        _a_lock = threading.Lock()
        _b_lock = threading.Lock()

        def forward():
            with _a_lock, _b_lock:
                pass

        def backward():
            with _b_lock:
                with _a_lock:
                    pass
    """
    found = lint_source(LockOrderChecker(), src, "m.py", tmp_path)
    assert len(found) == 1 and "lock-order cycle" in found[0].message


def test_lt006_reacquisition(tmp_path):
    # a self-call that re-takes the non-reentrant lock the call site
    # already holds: not a cycle — a deadlock on FIRST execution
    src = """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """
    found = lint_source(LockOrderChecker(), src, "box.py", tmp_path)
    assert len(found) == 1
    assert "re-acquisition deadlock" in found[0].message
    assert found[0].symbol == "Box.outer"


def test_lt006_condition_aliases_wrapped_lock(tmp_path):
    # Condition(self._lock) IS self._lock to the analysis: the
    # dispatcher idiom creates no edge and no false cycle
    src = """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items.append(x)
                    self._cond.notify_all()

            def take(self):
                with self._cond:
                    while not self._items:
                        self._cond.wait(timeout=0.2)
                    return self._items.pop()
    """
    assert not lint_source(LockOrderChecker(), src, "q.py", tmp_path)
    # and the wait-on-held-lock is not "blocking under a lock" either
    assert not lint_source(BlockingUnderLockChecker(), src, "q.py", tmp_path)


# ---------------------------------------------------------------------------
# LT007 — blocking under lock (interprocedural)


LT007_POSITIVE = """
    import threading
    import time

    _lock = threading.Lock()

    def save(path, data):
        with _lock:
            with open(path, "w") as f:   # file IO under the module lock
                f.write(data)

    def nap():
        with _lock:
            _helper()                    # blocks two calls deep

    def _helper():
        time.sleep(1)
"""

LT007_NEGATIVE = """
    import threading
    import time

    _lock = threading.Lock()
    _pending = []

    def save(path):
        with _lock:                      # detach-then-commit: IO outside
            batch = list(_pending)
            _pending.clear()
        with open(path, "w") as f:
            f.write(repr(batch))

    def nap():
        time.sleep(1)                    # no lock held: not our business
"""


def test_lt007_positive(tmp_path):
    found = lint_source(
        BlockingUnderLockChecker(), LT007_POSITIVE, "mod.py", tmp_path
    )
    msgs = "\n".join(f.message for f in found)
    assert "open() file IO while holding '_lock'" in msgs
    assert "call to _helper() blocks" in msgs and "sleep" in msgs
    assert all(f.rule_id == "LT007" for f in found)


def test_lt007_negative(tmp_path):
    assert not lint_source(
        BlockingUnderLockChecker(), LT007_NEGATIVE, "mod.py", tmp_path
    )


def test_lt007_locked_convention_checked_as_held(tmp_path):
    # *_locked documents "caller holds the lock": blocking work inside
    # is flagged even with no `with` in sight
    src = """
        def _spill_locked(path, rows):
            with open(path, "w") as f:
                f.write(repr(rows))
    """
    found = lint_source(BlockingUnderLockChecker(), src, "mod.py", tmp_path)
    assert found and "caller's lock" in found[0].message


def test_lt007_chain_through_call_cycle(tmp_path):
    # mutual recursion f<->g where g also reaches a blocking helper:
    # the chain fixpoint must find it regardless of visit order (a
    # memoized cycle guard used to poison f with a cached None)
    src = """
        import threading
        import time

        _lock = threading.Lock()

        def f():
            g()

        def g():
            f()
            _helper()

        def _helper():
            time.sleep(1)

        def locked_entry():
            with _lock:
                f()
    """
    found = lint_source(BlockingUnderLockChecker(), src, "m.py", tmp_path)
    assert any(
        f.symbol == "locked_entry" and "sleep" in f.message for f in found
    )


def test_lt007_queue_get_under_lock(tmp_path):
    # ISSUE-specified blocking effect: queue.get() holds the lock for an
    # unbounded wait; get(block=False) does not block
    src = """
        import queue
        import threading

        class Dispatcher:
            def __init__(self):
                self._lock = threading.Lock()
                self._job_queue = queue.Queue()

            def next_job(self):
                with self._lock:
                    return self._job_queue.get()

            def poll_job(self):
                with self._lock:
                    return self._job_queue.get(block=False)
    """
    found = lint_source(BlockingUnderLockChecker(), src, "d.py", tmp_path)
    assert len(found) == 1
    assert ".get() on queue" in found[0].message
    assert found[0].symbol == "Dispatcher.next_job"


def test_lt008_nested_def_owns_its_resources(tmp_path):
    # a closure creating AND discharging its own resource is clean; a
    # closure leaking one is flagged at the closure's statement tree
    clean = """
        def outer():
            def job(path):
                fh = open(path)
                try:
                    return fh.read()
                finally:
                    fh.close()
            return job
    """
    assert not lint_source(ResourceLifecycleChecker(), clean, "n.py", tmp_path)

    leaky = """
        def outer():
            def job(path):
                fh = open(path)
                return fh.read()
            return job
    """
    found = lint_source(ResourceLifecycleChecker(), leaky, "n.py", tmp_path)
    assert len(found) == 1 and "never closed" in found[0].message


def test_lt007_construction_only_exempt(tmp_path):
    # a scan reachable only from __init__ holds its lock uncontended —
    # LT001's __init__ exemption carried through the call graph
    src = """
        import threading

        class Store:
            def __init__(self, root):
                self._lock = threading.Lock()
                self._load(root)

            def _load(self, root):
                with self._lock:
                    with open(root) as f:
                        self._data = f.read()
    """
    assert not lint_source(BlockingUnderLockChecker(), src, "s.py", tmp_path)


# ---------------------------------------------------------------------------
# LT008 — resource lifecycle (path-sensitive)


LT008_POSITIVE = """
    from concurrent.futures import ThreadPoolExecutor

    def run_jobs(items):
        pool = ThreadPoolExecutor(max_workers=2)     # never shut down
        futs = [pool.submit(str, i) for i in items]
        return [f.result() for f in futs]
"""

LT008_EXC_PATH = """
    def convert(src):
        fh = open(src)
        data = transform(fh.read())    # raises -> fh leaks
        fh.close()
        return data
"""

LT008_NEGATIVE = """
    import threading

    def convert(src):
        with open(src) as fh:                        # context manager
            return fh.read()

    def guarded(src):
        fh = open(src)
        try:
            return transform(fh.read())              # try/finally owns it
        finally:
            fh.close()

    def optional(flag):
        t = threading.Timer(1.0, print) if flag else None
        try:
            work()
        finally:
            if t is not None:                        # the None-branch idiom
                t.cancel()
"""


def test_lt008_leaked_executor(tmp_path):
    found = lint_source(
        ResourceLifecycleChecker(), LT008_POSITIVE, "jobs.py", tmp_path
    )
    assert len(found) == 1
    assert "executor 'pool'" in found[0].message
    assert "certain leak" in found[0].message
    assert found[0].rule_id == "LT008"
    assert found[0].symbol == "run_jobs"


def test_lt008_exception_path_leak(tmp_path):
    found = lint_source(
        ResourceLifecycleChecker(), LT008_EXC_PATH, "conv.py", tmp_path
    )
    assert len(found) == 1
    assert "leaks if line" in found[0].message
    # the finding anchors at the creation, naming the raising line
    assert found[0].line == 3


def test_lt008_negative(tmp_path):
    assert not lint_source(
        ResourceLifecycleChecker(), LT008_NEGATIVE, "conv.py", tmp_path
    )


def test_lt008_self_attr_needs_project_discharge(tmp_path):
    # stored to self.attr: SOME `.attr.close()` must exist project-wide
    leaky = """
        class Holder:
            def __init__(self, path):
                self.log = open(path)
    """
    found = lint_source(ResourceLifecycleChecker(), leaky, "h.py", tmp_path)
    assert len(found) == 1
    assert "no '.log.<close/stop/shutdown>()' call exists" in found[0].message

    closed = """
        class Holder:
            def __init__(self, path):
                self.log = open(path)

            def close(self):
                self.log.close()
    """
    assert not lint_source(ResourceLifecycleChecker(), closed, "h.py", tmp_path)


def test_lt008_init_guard_via_teardown_method(tmp_path):
    # the server-constructor shape: a handler calling a method that
    # TRANSITIVELY discharges the attr protects the gap
    src = """
        class Server:
            def __init__(self, path):
                self.store = open(path)
                try:
                    self.port = bind_port()
                except BaseException:
                    self._teardown()
                    raise

            def _teardown(self):
                self.store.close()
    """
    assert not lint_source(ResourceLifecycleChecker(), src, "s.py", tmp_path)


def test_lt008_out_of_package_not_flagged(tmp_path):
    # tools/ and tests/ are process-scoped: their resources die with
    # the interpreter, and fixtures model leaks on purpose
    found = lint_source(
        ResourceLifecycleChecker(), LT008_POSITIVE,
        "tools/some_bench.py", tmp_path,
    )
    assert found == []


# ---------------------------------------------------------------------------
# suppressions: noqa + baseline


def test_noqa_suppresses_on_line_and_comment_block(tmp_path):
    src = """
        import threading

        _lock = threading.Lock()
        _count = 0

        def bump():
            global _count
            with _lock:
                _count += 1

        def reset():
            global _count
            _count = 0  # lt: noqa[LT001]

        def peek():
            # single-writer startup path, readers not yet running
            # lt: noqa[LT001]
            return _count
    """
    rel = "mod.py"
    (tmp_path / rel).write_text(textwrap.dedent(src))
    repo = RepoCtx(str(tmp_path), files=[rel])
    report = run_rules(repo, [LockDisciplineChecker()])
    assert report["findings"] == []
    assert report["noqa_suppressed"] == 2


def test_noqa_other_rule_does_not_suppress(tmp_path):
    src = """
        import threading

        _lock = threading.Lock()
        _count = 0

        def bump():
            global _count
            with _lock:
                _count += 1

        def reset():
            global _count
            _count = 0  # lt: noqa[LT999]
    """
    rel = "mod.py"
    (tmp_path / rel).write_text(textwrap.dedent(src))
    repo = RepoCtx(str(tmp_path), files=[rel])
    report = run_rules(repo, [LockDisciplineChecker()])
    assert len(report["findings"]) == 1


def test_noqa_suppresses_new_rules(tmp_path):
    src = """
        import os
        import threading

        _lock = threading.Lock()

        def save(fd, data):
            with _lock:
                # serialization lock: the write IS the critical section
                # lt: noqa[LT007]
                os.write(fd, data)
    """
    rel = "mod.py"
    (tmp_path / rel).write_text(textwrap.dedent(src))
    repo = RepoCtx(str(tmp_path), files=[rel])
    report = run_rules(repo, [BlockingUnderLockChecker()])
    assert report["findings"] == []
    assert report["noqa_suppressed"] >= 1


def test_symbol_baseline_suppresses_new_rules(tmp_path):
    rel = "jobs.py"
    (tmp_path / rel).write_text(textwrap.dedent(LT008_POSITIVE))
    repo = RepoCtx(str(tmp_path), files=[rel])
    entry = {
        "rule": "LT008", "file": rel, "symbol": "run_jobs",
        "reason": "fixture: process-lifetime pool by design",
    }
    report = run_rules(repo, [ResourceLifecycleChecker()], Baseline([entry]))
    assert report["findings"] == []
    assert len(report["baselined"]) == 1

    # the symbol key is load-bearing: a different symbol matches nothing
    wrong = {**entry, "symbol": "other_function"}
    repo2 = RepoCtx(str(tmp_path), files=[rel])
    report2 = run_rules(
        repo2, [ResourceLifecycleChecker()], Baseline([wrong])
    )
    assert len(report2["findings"]) == 1
    assert report2["unused_baseline"] == [wrong]


def test_symbol_baseline_is_line_number_independent(tmp_path):
    # shifting the finding by 40 lines must not invalidate the entry
    rel = "jobs.py"
    shifted = ("# filler\n" * 40) + textwrap.dedent(LT008_POSITIVE)
    (tmp_path / rel).write_text(shifted)
    repo = RepoCtx(str(tmp_path), files=[rel])
    entry = {
        "rule": "LT008", "file": rel, "symbol": "run_jobs",
        "reason": "fixture: process-lifetime pool by design",
    }
    report = run_rules(repo, [ResourceLifecycleChecker()], Baseline([entry]))
    assert report["findings"] == []
    assert len(report["baselined"]) == 1


def test_baseline_suppresses_and_reports_stale(tmp_path):
    rel = "land_trendr_tpu/runtime/widget.py"
    path = tmp_path / rel
    path.parent.mkdir(parents=True)
    path.write_text("import numpy as np\n\ndef f(a):\n    return np.asarray(a)\n")
    baseline = Baseline(
        [
            {
                "rule": "LT002", "file": rel, "contains": "np.asarray",
                "reason": "fixture: deliberately blessed",
            },
            {
                "rule": "LT001", "file": "nowhere.py",
                "reason": "fixture: stale entry",
            },
        ]
    )
    repo = RepoCtx(str(tmp_path), files=[rel])
    report = run_rules(repo, [HostSyncChecker()], baseline)
    assert report["findings"] == []
    assert len(report["baselined"]) == 1
    assert report["baselined"][0][1]["reason"] == "fixture: deliberately blessed"
    assert report["unused_baseline"] == [baseline.entries[1]]


def test_baseline_requires_reason():
    with pytest.raises(BaselineError, match="reason"):
        Baseline([{"rule": "LT001", "file": "x.py"}])


# ---------------------------------------------------------------------------
# the tier-1 repo gate + CLI surface


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, LT_LINT, *args],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )


def test_repo_tree_is_clean():
    """The acceptance gate: zero unbaselined findings over the real tree
    with all eight rules active — inside the documented wall-time budget.

    The budget assertion is load-bearing: the interprocedural pass
    (call-graph build + fixpoints) must stay seconds-scale or tier-1
    silently becomes a minutes-scale suite.  ``LINT_BUDGET_S`` is the
    bound README §Static analysis documents; ~7s measured here.
    """
    t0 = time.monotonic()
    proc = _run_cli("--json")
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < LINT_BUDGET_S, (
        f"full lt-lint run took {elapsed:.1f}s — over the documented "
        f"{LINT_BUDGET_S:.0f}s budget; the interprocedural pass has "
        "regressed (check the call-graph fixpoints before raising the bound)"
    )
    report = json.loads(proc.stdout)
    assert report["clean"] is True
    assert report["findings"] == []
    # the deliberate exceptions stay visible, reasons attached
    assert all(e["reason"] for e in report["baselined"])
    # the LT007 serialization-lock exceptions are symbol-keyed
    assert any(
        e.get("symbol") == "BlockStore.flush" for e in report["baselined"]
    )
    # and none of them went stale
    assert report["unused_baseline"] == []
    assert report["files_checked"] > 50


def test_changed_files_lists_untracked_dir_contents(tmp_path):
    """A brand-new package directory must contribute its FILES to the
    --changed set: bare `git status --porcelain` collapses it to one
    'dir/' entry that matches nothing, green-lighting a new subsystem."""
    from tools.lt_lint import changed_files

    subprocess.run(["git", "init", "-q"], cwd=tmp_path, check=True)
    pkg = tmp_path / "pkg" / "sub"
    pkg.mkdir(parents=True)
    (pkg / "a.py").write_text("x = 1\n")
    (pkg / "b.py").write_text("y = 2\n")
    changed = changed_files(tmp_path)
    assert changed is not None
    assert {"pkg/sub/a.py", "pkg/sub/b.py"} <= changed


def test_cli_changed_mode_runs():
    proc = _run_cli("--changed", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout)["clean"] is True


def test_cli_single_path_and_list_rules():
    proc = _run_cli("land_trendr_tpu/io/blockcache.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule in (
        "LT001", "LT002", "LT003", "LT004", "LT005",
        "LT006", "LT007", "LT008",
    ):
        assert rule in proc.stdout


def test_cli_sarif_output(tmp_path):
    """SARIF 2.1.0 artifact: all eight rules declared, the clean tree's
    baselined findings present as SUPPRESSED results carrying their
    written justification, zero error-level results."""
    out = tmp_path / "lint.sarif"
    proc = _run_cli("--sarif", str(out))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    sarif = json.loads(out.read_text())
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "lt-lint"
    assert len(run["tool"]["driver"]["rules"]) == 8
    errors = [r for r in run["results"] if r["level"] == "error"]
    assert errors == []
    suppressed = [r for r in run["results"] if r.get("suppressions")]
    assert len(suppressed) >= 2
    for r in suppressed:
        assert r["suppressions"][0]["justification"]
        loc = r["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(".py")
        assert loc["region"]["startLine"] >= 1


def test_cli_sarif_stdout_is_pure_json():
    proc = _run_cli("--sarif", "-")
    assert proc.returncode == 0, proc.stderr
    sarif = json.loads(proc.stdout)  # any human chatter here would fail
    assert sarif["version"] == "2.1.0"


def test_cli_rejects_json_plus_sarif_stdout():
    # both reports on stdout would concatenate two JSON documents
    proc = _run_cli("--json", "--sarif", "-")
    assert proc.returncode == 2
    assert "stdout" in proc.stderr


def test_cli_unwritable_sarif_is_config_error(tmp_path):
    # exit 2 (config), not exit 1 ("findings present"), and no traceback
    proc = _run_cli("--sarif", str(tmp_path / "no" / "dir" / "o.sarif"))
    assert proc.returncode == 2
    assert "error: --sarif" in proc.stderr
    assert "Traceback" not in proc.stderr


def test_cli_prune_baseline(tmp_path):
    """--prune-baseline drops exactly the stale entries (full runs
    only; partial runs are refused with exit 2)."""
    with open(os.path.join(REPO, "LINT_BASELINE.json")) as f:
        data = json.load(f)
    live = len(data["entries"])
    data["entries"].append(
        {"rule": "LT001", "file": "nowhere.py", "reason": "planted stale"}
    )
    bpath = tmp_path / "baseline.json"
    bpath.write_text(json.dumps(data))
    proc = _run_cli("--baseline", str(bpath), "--prune-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "pruned 1 stale" in proc.stderr
    kept = json.loads(bpath.read_text())["entries"]
    assert len(kept) == live
    assert not any(e["file"] == "nowhere.py" for e in kept)

    proc = _run_cli("--changed", "--prune-baseline")
    assert proc.returncode == 2
    assert "full run" in proc.stderr


def test_cli_rejects_reasonless_baseline(tmp_path):
    bad = tmp_path / "baseline.json"
    bad.write_text(json.dumps({"entries": [{"rule": "LT001", "file": "x.py"}]}))
    proc = _run_cli("--baseline", str(bad))
    assert proc.returncode == 2
    assert "reason" in proc.stderr


def test_cli_exits_one_on_findings(tmp_path):
    """A planted violation fails the run — the CI contract is exit 1."""
    # lint a single out-of-tree fixture through the real CLI
    fixture = tmp_path / "land_trendr_tpu" / "runtime" / "bad.py"
    fixture.parent.mkdir(parents=True)
    fixture.write_text("import numpy as np\n\ndef f(a):\n    return np.asarray(a)\n")
    # CLI paths are repo-relative; use the module API for the tmp tree
    repo = RepoCtx(str(tmp_path))
    report = run_rules(repo, default_checkers())
    assert any(f.rule_id == "LT002" for f in report["findings"])
