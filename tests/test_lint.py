"""lt-lint suite: fixtures per rule, suppression mechanics, repo gate.

One POSITIVE (the rule catches it) and one NEGATIVE (clean idiomatic
code passes) fixture per rule LT001–LT012 — the dataflow generation
LT009–LT012 includes an interprocedural purity reach two calls deep and
clock taint crossing a dict store — plus the suppression contract
(inline ``# lt: noqa[rule]`` and reasoned LINT_BASELINE entries both
actually suppress; a reason-less baseline entry is an error; baseline
entries key on rule + file + enclosing SYMBOL, never line numbers), the
registry pins (``PURE_MACHINES`` must cover exactly the machines
``replay_decisions`` dispatches through), the SARIF /
``--prune-baseline`` CLI contract, and the tier-1 gate:
``tools/lt_lint.py --json`` over the real tree exits 0 — zero
unbaselined findings, every PR — within the documented wall-time budget
(the interprocedural rules must not silently blow up tier-1).  The
lintkit is stdlib-only and jax-free, so this whole module is
seconds-scale.
"""

import ast
import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from land_trendr_tpu.lintkit import (
    Baseline,
    BaselineError,
    BlockingUnderLockChecker,
    ClockDomainChecker,
    ConfigDocChecker,
    DurableWriteChecker,
    EventSchemaChecker,
    HostSyncChecker,
    JitPurityChecker,
    LockDisciplineChecker,
    LockOrderChecker,
    RepoCtx,
    ReplayPurityChecker,
    ResourceLifecycleChecker,
    SeamCoverageChecker,
    default_checkers,
    run_rules,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LT_LINT = os.path.join(REPO, "tools", "lt_lint.py")

#: the repo-gate budget: a full twelve-rule run over the tree (parse +
#: call-graph build + lock/resource fixpoints + the LT009–LT012
#: dataflow pass) measures ~12s in this container; 30s is the hard
#: bound so the interprocedural passes cannot silently turn tier-1
#: into a minutes-scale suite on slower CI hardware.  Shared with the
#: perf-gate lint leg so the two gates cannot drift apart.
from tools.lt_lint import LINT_BUDGET_S  # noqa: E402


def lint_source(checker, source: str, relpath: str, tmp_path) -> list:
    """Run one rule over one fixture file inside a throwaway repo."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    repo = RepoCtx(str(tmp_path), files=[relpath])
    return list(checker.check(repo))


def lint_repo(checker, files: "dict[str, str]", tmp_path) -> list:
    """Run one rule over a multi-file fixture repo (the registry-driven
    rules LT009/LT011 read data tables from specific well-known paths)."""
    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    repo = RepoCtx(str(tmp_path), files=sorted(files))
    return list(checker.check(repo))


# ---------------------------------------------------------------------------
# LT001 — lock discipline


LT001_MODULE_POSITIVE = """
    import threading

    _lock = threading.Lock()
    _count = 0
    _sizes = {}

    def bump():
        global _count
        with _lock:
            _count += 1
            _sizes["n"] = _count

    def reset():          # mutation outside the lock
        global _count
        _count = 0

    def peek():           # torn snapshot: return read outside the lock
        return dict(_sizes)
"""

LT001_MODULE_NEGATIVE = """
    import threading

    _lock = threading.Lock()
    _count = 0
    _tl = threading.local()      # thread-local: needs no lock

    def bump():
        global _count
        with _lock:
            _count += 1
            _drain_locked()

    def _drain_locked():         # *_locked convention: caller holds it
        global _count
        _count = 0

    def peek():
        with _lock:
            return _count

    def mark():
        _tl.flag = True          # unguarded name: not lock-owned state
"""


def test_lt001_module_positive(tmp_path):
    found = lint_source(
        LockDisciplineChecker(), LT001_MODULE_POSITIVE, "mod.py", tmp_path
    )
    assert any("_count" in f.message and "assignment" in f.message for f in found)
    assert any("_sizes" in f.message and "return reads" in f.message for f in found)
    assert all(f.rule_id == "LT001" for f in found)


def test_lt001_module_negative(tmp_path):
    assert not lint_source(
        LockDisciplineChecker(), LT001_MODULE_NEGATIVE, "mod.py", tmp_path
    )


LT001_CLASS_POSITIVE = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def add(self, x):
            with self._lock:
                self._items.append(x)

        def drop(self):              # mutating call outside the lock
            self._items.clear()

        def snapshot(self):          # torn snapshot outside the lock
            return list(self._items)
"""

LT001_CLASS_NEGATIVE = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []         # __init__ happens-before sharing

        def add(self, x):
            with self._lock:
                self._items.append(x)

        def drain(self):
            with self._lock:
                return self._flush_locked()

        def _flush_locked(self):
            out = list(self._items)
            self._items.clear()
            return out
"""


def test_lt001_class_positive(tmp_path):
    found = lint_source(
        LockDisciplineChecker(), LT001_CLASS_POSITIVE, "box.py", tmp_path
    )
    assert any(".clear() call" in f.message for f in found)
    assert any("return reads" in f.message for f in found)


def test_lt001_class_negative(tmp_path):
    assert not lint_source(
        LockDisciplineChecker(), LT001_CLASS_NEGATIVE, "box.py", tmp_path
    )


def test_lt001_nested_attribute_store(tmp_path):
    # mutation THROUGH a guarded object (self._stats.hits = ...) is a
    # mutation of guarded state, same as item assignment
    src = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._stats = Stats()

            def ok(self):
                with self._lock:
                    self._stats.hits = 1

            def racy(self):
                self._stats.hits = 2
    """
    found = lint_source(LockDisciplineChecker(), src, "s.py", tmp_path)
    assert len(found) == 1
    assert "attribute assignment" in found[0].message
    # the racy() body line, not the locked ok() one
    assert "self._stats" in found[0].message


def test_lt001_inherited_lock(tmp_path):
    # the obs/metrics.py shape: the base holds the (shared) lock, the
    # subclass mutates under it — an unlocked subclass read is caught
    src = """
        import threading

        class Base:
            def __init__(self, lock):
                self._lock = lock

        class Counter(Base):
            def __init__(self, lock):
                super().__init__(lock)
                self._value = 0.0

            def inc(self):
                with self._lock:
                    self._value += 1

            def peek(self):
                return self._value
    """
    found = lint_source(LockDisciplineChecker(), src, "m.py", tmp_path)
    assert any("Counter" in f.message and "_value" in f.message for f in found)


# ---------------------------------------------------------------------------
# LT002 — host sync outside the fetch path


LT002_SOURCE = """
    import numpy as np

    def collect(dev_arrays):
        out = [np.asarray(a) for a in dev_arrays]   # blocking D2H
        dev_arrays[0].block_until_ready()
        return out, dev_arrays[1].item()
"""


def test_lt002_positive_in_scope(tmp_path):
    found = lint_source(
        HostSyncChecker(), LT002_SOURCE,
        "land_trendr_tpu/runtime/widget.py", tmp_path,
    )
    kinds = "\n".join(f.message for f in found)
    assert "np.asarray" in kinds
    assert "block_until_ready" in kinds
    assert ".item()" in kinds
    assert all(f.rule_id == "LT002" for f in found)


def test_lt002_negative_out_of_scope_and_blessed(tmp_path):
    # same code outside the scoped modules: not the rule's business
    assert not lint_source(
        HostSyncChecker(), LT002_SOURCE, "land_trendr_tpu/io/widget.py",
        tmp_path,
    )
    # and runtime/fetch.py IS the fetch path — blessed wholesale
    assert not lint_source(
        HostSyncChecker(), LT002_SOURCE, "land_trendr_tpu/runtime/fetch.py",
        tmp_path,
    )


# ---------------------------------------------------------------------------
# LT003 — jit purity


LT003_POSITIVE = """
    import functools
    import os
    import jax

    _calls = 0

    @functools.partial(jax.jit, static_argnames=("n",))
    def kernel(x, n):
        global _calls
        _calls += 1          # global mutation at trace time
        print("tracing")     # fires once, then never again
        return helper(x)

    def helper(x):           # reachable from the jitted root
        os.remove("scratch")
        return x * 2
"""

LT003_NEGATIVE = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(x):
        jax.debug.print("x={}", x)   # the sanctioned traced side-channel
        return jnp.sum(x * 2)

    def untraced_io(path):
        with open(path) as f:        # not jitted, not reachable from one
            return f.read()
"""


def test_lt003_positive(tmp_path):
    found = lint_source(JitPurityChecker(), LT003_POSITIVE, "k.py", tmp_path)
    msgs = "\n".join(f.message for f in found)
    assert "print() call" in msgs
    assert "mutation of global '_calls'" in msgs
    assert "os.remove" in msgs and "reachable" in msgs
    assert all("kernel" in f.message for f in found)


def test_lt003_negative(tmp_path):
    assert not lint_source(JitPurityChecker(), LT003_NEGATIVE, "k.py", tmp_path)


# ---------------------------------------------------------------------------
# LT004 — RunConfig / CLI / README coupling


def _write_config_repo(tmp_path, *, cli_flags, readme_rows, fields):
    (tmp_path / "land_trendr_tpu" / "runtime").mkdir(parents=True)
    field_src = "\n".join(f"    {name}: int = 0" for name in fields)
    (tmp_path / "land_trendr_tpu" / "runtime" / "driver.py").write_text(
        "import dataclasses\n\n"
        "@dataclasses.dataclass(frozen=True)\n"
        f"class RunConfig:\n{field_src}\n"
    )
    flag_src = "\n".join(f'    seg.add_argument("--{f}")' for f in cli_flags)
    (tmp_path / "land_trendr_tpu" / "cli.py").write_text(
        "def build_parser(p):\n"
        "    sub = p.add_subparsers()\n"
        '    seg = sub.add_parser("segment")\n'
        f"{flag_src}\n"
        '    pix = sub.add_parser("pixel")\n'
        '    pix.add_argument("--other-only")\n'
    )
    rows = "\n".join(f"| `{r}` | `--{r}` | 0 | a knob |" for r in readme_rows)
    (tmp_path / "README.md").write_text(
        "# t\n\n## Run configuration\n\n"
        "| field | CLI flag | default | meaning |\n|---|---|---|---|\n"
        f"{rows}\n\n## Next section\n"
    )


def test_lt004_positive(tmp_path):
    _write_config_repo(
        tmp_path,
        fields=("tile_size", "ghost_knob"),
        cli_flags=("tile-size",),          # ghost_knob: no flag
        readme_rows=("tile_size", "stale_row"),  # ghost_knob: no row
    )
    found = list(ConfigDocChecker().check(RepoCtx(str(tmp_path))))
    msgs = "\n".join(f.message for f in found)
    assert "RunConfig.ghost_knob has no CLI flag" in msgs
    assert "RunConfig.ghost_knob has no row" in msgs
    assert "'stale_row' names no RunConfig field" in msgs
    assert len(found) == 3


def test_lt004_negative(tmp_path):
    _write_config_repo(
        tmp_path,
        fields=("tile_size", "resume"),
        cli_flags=("tile-size", "no-resume"),  # negated alias accepted
        readme_rows=("tile_size", "resume"),
    )
    assert not list(ConfigDocChecker().check(RepoCtx(str(tmp_path))))


def test_lt004_other_subparser_flag_does_not_count(tmp_path):
    # --other-only exists on the pixel subparser (see _write_config_repo);
    # a field projected only there must still be flagged for segment
    _write_config_repo(
        tmp_path,
        fields=("tile_size", "other_only"),
        cli_flags=("tile-size",),
        readme_rows=("tile_size", "other_only"),
    )
    found = list(ConfigDocChecker().check(RepoCtx(str(tmp_path))))
    assert len(found) == 1
    assert "RunConfig.other_only has no CLI flag" in found[0].message


def test_lt004_helper_and_group_flags_count(tmp_path):
    # the _add_param_flags(seg) pattern: flags added inside a helper the
    # segment parser is passed to (via an argument group) still count
    (tmp_path / "land_trendr_tpu" / "runtime").mkdir(parents=True)
    (tmp_path / "land_trendr_tpu" / "runtime" / "driver.py").write_text(
        "import dataclasses\n\n"
        "@dataclasses.dataclass(frozen=True)\n"
        "class RunConfig:\n    params: int = 0\n    scale: float = 1.0\n"
    )
    (tmp_path / "land_trendr_tpu" / "cli.py").write_text(
        "def _add_param_flags(p):\n"
        '    g = p.add_argument_group("algorithm parameters")\n'
        '    g.add_argument("--params-json")\n'
        "def build_parser(p):\n"
        "    sub = p.add_subparsers()\n"
        '    seg = sub.add_parser("segment")\n'
        '    grp = seg.add_argument_group("run")\n'
        '    grp.add_argument("--scale")\n'
        "    _add_param_flags(seg)\n"
    )
    (tmp_path / "README.md").write_text(
        "## Run configuration\n\n| field | flag |\n|---|---|\n"
        "| `params` | `--params-json` |\n| `scale` | `--scale` |\n"
    )
    assert not list(ConfigDocChecker().check(RepoCtx(str(tmp_path))))


# ---------------------------------------------------------------------------
# LT005 — emit-site schema drift


def _lint_telemetry(tmp_path, source: str, schema_tool: "str | None" = None):
    rel = "land_trendr_tpu/obs/telemetry.py"
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    if schema_tool is not None:
        (tmp_path / "tools").mkdir(exist_ok=True)
        (tmp_path / "tools" / "check_events_schema.py").write_text(
            textwrap.dedent(schema_tool)
        )
    return list(EventSchemaChecker().check(RepoCtx(str(tmp_path))))


LT005_POSITIVE = """
    class Telemetry:
        def start(self, tile_id):
            self.events.emit("tile_start", tile_id=tile_id)   # no 'attempt'

        def done(self, tile_id):
            self.events.emit(
                "tile_done", tile_id=tile_id, px=1, compute_s=0.1,
                px_per_s=10.0, feed_backlog=0, write_backlog=0,
                pxx=3,                                        # typo'd field
            )

        def custom(self):
            self.events.emit("no_such_event")                 # unknown type
"""

LT005_NEGATIVE = """
    class Telemetry:
        def start(self, tile_id):
            self.events.emit("tile_start", tile_id=tile_id, attempt=1)

        def done(self, tile_id, hbm):
            fields = {}
            if hbm is not None:
                fields["device_bytes_in_use"] = hbm          # known optional
            self.events.emit(
                "tile_done", tile_id=tile_id, px=1, compute_s=0.1,
                px_per_s=10.0, feed_backlog=0, write_backlog=0, **fields,
            )

        def forward(self, **fields):
            # unresolvable splat: requiredness is skipped, not guessed
            self.events.emit("run_done", **fields)
"""


def test_lt005_positive(tmp_path):
    found = _lint_telemetry(tmp_path, LT005_POSITIVE)
    msgs = "\n".join(f.message for f in found)
    assert "never sets required field 'attempt'" in msgs
    assert "passes field 'pxx'" in msgs
    assert "unknown event type 'no_such_event'" in msgs


def test_lt005_negative(tmp_path):
    assert not _lint_telemetry(tmp_path, LT005_NEGATIVE)


def test_lt005_value_table_cross_check(tmp_path):
    found = _lint_telemetry(
        tmp_path,
        LT005_NEGATIVE,
        schema_tool="""
            NONNEG_FIELDS = {
                "fetch": ("tiles", "made_up_field"),
                "bogus_event": ("x",),
            }
        """,
    )
    msgs = "\n".join(f.message for f in found)
    assert "unknown event 'bogus_event'" in msgs
    assert "'made_up_field'" in msgs


# ---------------------------------------------------------------------------
# LT006 — lock-order cycles (interprocedural)


LT006_POSITIVE = """
    import threading

    class Pair:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def forward(self):
            with self._a_lock:
                self._grab_b()          # a -> b, one call deep

        def _grab_b(self):
            with self._b_lock:
                pass

        def backward(self):
            with self._b_lock:
                with self._a_lock:      # b -> a: the cycle
                    pass
"""

LT006_NEGATIVE = """
    import threading

    class Pair:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def forward(self):
            with self._a_lock:
                self._grab_b()

        def _grab_b(self):
            with self._b_lock:
                pass

        def also_forward(self):         # same a-before-b order: acyclic
            with self._a_lock:
                with self._b_lock:
                    pass
"""


def test_lt006_cycle_positive(tmp_path):
    found = lint_source(LockOrderChecker(), LT006_POSITIVE, "pair.py", tmp_path)
    assert len(found) == 1
    assert "lock-order cycle" in found[0].message
    assert "Pair._a_lock" in found[0].message and "Pair._b_lock" in found[0].message
    assert found[0].rule_id == "LT006"


def test_lt006_consistent_order_negative(tmp_path):
    assert not lint_source(
        LockOrderChecker(), LT006_NEGATIVE, "pair.py", tmp_path
    )


def test_lt006_multi_item_with(tmp_path):
    # `with A, B:` acquires B while A is held — the same edge as the
    # nested form, written in Python's most common multi-lock syntax
    src = """
        import threading

        _a_lock = threading.Lock()
        _b_lock = threading.Lock()

        def forward():
            with _a_lock, _b_lock:
                pass

        def backward():
            with _b_lock:
                with _a_lock:
                    pass
    """
    found = lint_source(LockOrderChecker(), src, "m.py", tmp_path)
    assert len(found) == 1 and "lock-order cycle" in found[0].message


def test_lt006_reacquisition(tmp_path):
    # a self-call that re-takes the non-reentrant lock the call site
    # already holds: not a cycle — a deadlock on FIRST execution
    src = """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
    """
    found = lint_source(LockOrderChecker(), src, "box.py", tmp_path)
    assert len(found) == 1
    assert "re-acquisition deadlock" in found[0].message
    assert found[0].symbol == "Box.outer"


def test_lt006_condition_aliases_wrapped_lock(tmp_path):
    # Condition(self._lock) IS self._lock to the analysis: the
    # dispatcher idiom creates no edge and no false cycle
    src = """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items.append(x)
                    self._cond.notify_all()

            def take(self):
                with self._cond:
                    while not self._items:
                        self._cond.wait(timeout=0.2)
                    return self._items.pop()
    """
    assert not lint_source(LockOrderChecker(), src, "q.py", tmp_path)
    # and the wait-on-held-lock is not "blocking under a lock" either
    assert not lint_source(BlockingUnderLockChecker(), src, "q.py", tmp_path)


# ---------------------------------------------------------------------------
# LT007 — blocking under lock (interprocedural)


LT007_POSITIVE = """
    import threading
    import time

    _lock = threading.Lock()

    def save(path, data):
        with _lock:
            with open(path, "w") as f:   # file IO under the module lock
                f.write(data)

    def nap():
        with _lock:
            _helper()                    # blocks two calls deep

    def _helper():
        time.sleep(1)
"""

LT007_NEGATIVE = """
    import threading
    import time

    _lock = threading.Lock()
    _pending = []

    def save(path):
        with _lock:                      # detach-then-commit: IO outside
            batch = list(_pending)
            _pending.clear()
        with open(path, "w") as f:
            f.write(repr(batch))

    def nap():
        time.sleep(1)                    # no lock held: not our business
"""


def test_lt007_positive(tmp_path):
    found = lint_source(
        BlockingUnderLockChecker(), LT007_POSITIVE, "mod.py", tmp_path
    )
    msgs = "\n".join(f.message for f in found)
    assert "open() file IO while holding '_lock'" in msgs
    assert "call to _helper() blocks" in msgs and "sleep" in msgs
    assert all(f.rule_id == "LT007" for f in found)


def test_lt007_negative(tmp_path):
    assert not lint_source(
        BlockingUnderLockChecker(), LT007_NEGATIVE, "mod.py", tmp_path
    )


def test_lt007_locked_convention_checked_as_held(tmp_path):
    # *_locked documents "caller holds the lock": blocking work inside
    # is flagged even with no `with` in sight
    src = """
        def _spill_locked(path, rows):
            with open(path, "w") as f:
                f.write(repr(rows))
    """
    found = lint_source(BlockingUnderLockChecker(), src, "mod.py", tmp_path)
    assert found and "caller's lock" in found[0].message


def test_lt007_chain_through_call_cycle(tmp_path):
    # mutual recursion f<->g where g also reaches a blocking helper:
    # the chain fixpoint must find it regardless of visit order (a
    # memoized cycle guard used to poison f with a cached None)
    src = """
        import threading
        import time

        _lock = threading.Lock()

        def f():
            g()

        def g():
            f()
            _helper()

        def _helper():
            time.sleep(1)

        def locked_entry():
            with _lock:
                f()
    """
    found = lint_source(BlockingUnderLockChecker(), src, "m.py", tmp_path)
    assert any(
        f.symbol == "locked_entry" and "sleep" in f.message for f in found
    )


def test_lt007_queue_get_under_lock(tmp_path):
    # ISSUE-specified blocking effect: queue.get() holds the lock for an
    # unbounded wait; get(block=False) does not block
    src = """
        import queue
        import threading

        class Dispatcher:
            def __init__(self):
                self._lock = threading.Lock()
                self._job_queue = queue.Queue()

            def next_job(self):
                with self._lock:
                    return self._job_queue.get()

            def poll_job(self):
                with self._lock:
                    return self._job_queue.get(block=False)
    """
    found = lint_source(BlockingUnderLockChecker(), src, "d.py", tmp_path)
    assert len(found) == 1
    assert ".get() on queue" in found[0].message
    assert found[0].symbol == "Dispatcher.next_job"


def test_lt008_nested_def_owns_its_resources(tmp_path):
    # a closure creating AND discharging its own resource is clean; a
    # closure leaking one is flagged at the closure's statement tree
    clean = """
        def outer():
            def job(path):
                fh = open(path)
                try:
                    return fh.read()
                finally:
                    fh.close()
            return job
    """
    assert not lint_source(ResourceLifecycleChecker(), clean, "n.py", tmp_path)

    leaky = """
        def outer():
            def job(path):
                fh = open(path)
                return fh.read()
            return job
    """
    found = lint_source(ResourceLifecycleChecker(), leaky, "n.py", tmp_path)
    assert len(found) == 1 and "never closed" in found[0].message


def test_lt007_construction_only_exempt(tmp_path):
    # a scan reachable only from __init__ holds its lock uncontended —
    # LT001's __init__ exemption carried through the call graph
    src = """
        import threading

        class Store:
            def __init__(self, root):
                self._lock = threading.Lock()
                self._load(root)

            def _load(self, root):
                with self._lock:
                    with open(root) as f:
                        self._data = f.read()
    """
    assert not lint_source(BlockingUnderLockChecker(), src, "s.py", tmp_path)


# ---------------------------------------------------------------------------
# LT008 — resource lifecycle (path-sensitive)


LT008_POSITIVE = """
    from concurrent.futures import ThreadPoolExecutor

    def run_jobs(items):
        pool = ThreadPoolExecutor(max_workers=2)     # never shut down
        futs = [pool.submit(str, i) for i in items]
        return [f.result() for f in futs]
"""

LT008_EXC_PATH = """
    def convert(src):
        fh = open(src)
        data = transform(fh.read())    # raises -> fh leaks
        fh.close()
        return data
"""

LT008_NEGATIVE = """
    import threading

    def convert(src):
        with open(src) as fh:                        # context manager
            return fh.read()

    def guarded(src):
        fh = open(src)
        try:
            return transform(fh.read())              # try/finally owns it
        finally:
            fh.close()

    def optional(flag):
        t = threading.Timer(1.0, print) if flag else None
        try:
            work()
        finally:
            if t is not None:                        # the None-branch idiom
                t.cancel()
"""


def test_lt008_leaked_executor(tmp_path):
    found = lint_source(
        ResourceLifecycleChecker(), LT008_POSITIVE, "jobs.py", tmp_path
    )
    assert len(found) == 1
    assert "executor 'pool'" in found[0].message
    assert "certain leak" in found[0].message
    assert found[0].rule_id == "LT008"
    assert found[0].symbol == "run_jobs"


def test_lt008_exception_path_leak(tmp_path):
    found = lint_source(
        ResourceLifecycleChecker(), LT008_EXC_PATH, "conv.py", tmp_path
    )
    assert len(found) == 1
    assert "leaks if line" in found[0].message
    # the finding anchors at the creation, naming the raising line
    assert found[0].line == 3


def test_lt008_negative(tmp_path):
    assert not lint_source(
        ResourceLifecycleChecker(), LT008_NEGATIVE, "conv.py", tmp_path
    )


def test_lt008_self_attr_needs_project_discharge(tmp_path):
    # stored to self.attr: SOME `.attr.close()` must exist project-wide
    leaky = """
        class Holder:
            def __init__(self, path):
                self.log = open(path)
    """
    found = lint_source(ResourceLifecycleChecker(), leaky, "h.py", tmp_path)
    assert len(found) == 1
    assert "no '.log.<close/stop/shutdown>()' call exists" in found[0].message

    closed = """
        class Holder:
            def __init__(self, path):
                self.log = open(path)

            def close(self):
                self.log.close()
    """
    assert not lint_source(ResourceLifecycleChecker(), closed, "h.py", tmp_path)


def test_lt008_init_guard_via_teardown_method(tmp_path):
    # the server-constructor shape: a handler calling a method that
    # TRANSITIVELY discharges the attr protects the gap
    src = """
        class Server:
            def __init__(self, path):
                self.store = open(path)
                try:
                    self.port = bind_port()
                except BaseException:
                    self._teardown()
                    raise

            def _teardown(self):
                self.store.close()
    """
    assert not lint_source(ResourceLifecycleChecker(), src, "s.py", tmp_path)


def test_lt008_out_of_package_not_flagged(tmp_path):
    # tools/ and tests/ are process-scoped: their resources die with
    # the interpreter, and fixtures model leaks on purpose
    found = lint_source(
        ResourceLifecycleChecker(), LT008_POSITIVE,
        "tools/some_bench.py", tmp_path,
    )
    assert found == []


# ---------------------------------------------------------------------------
# LT009 — replay purity of registered decision machines

SCHEDULING = "land_trendr_tpu/fleet/scheduling.py"

LT009_POSITIVE = {
    SCHEDULING: """
        import time

        PURE_MACHINES = (
            ("land_trendr_tpu/fleet/scheduling.py", "decide"),
            ("land_trendr_tpu/fleet/scheduling.py", "vanished"),
        )

        def decide(state, now):
            return _rank(state, now)

        def _rank(state, now):       # hop 1
            return _stamp(state)

        def _stamp(state):           # hop 2: the impurity hides here
            return {"n": len(state), "t": time.time()}
    """,
}

LT009_NEGATIVE = {
    SCHEDULING: """
        PURE_MACHINES = (
            ("land_trendr_tpu/fleet/scheduling.py", "Machine"),
        )

        class Machine:
            def decide(self, state, now):
                # now arrives as a PARAMETER — the pure contract
                return self._fold(state) + now

            def _fold(self, state):
                return sum(state)
    """,
}


def test_lt009_interprocedural_two_calls_deep(tmp_path):
    found = lint_repo(ReplayPurityChecker(), LT009_POSITIVE, tmp_path)
    reach = [f for f in found if "wall-clock read" in f.message]
    assert len(reach) == 1
    # the finding attributes to the REGISTERED root with the chain
    assert reach[0].symbol == "decide"
    assert "via decide -> _rank -> _stamp" in reach[0].message
    assert reach[0].rule_id == "LT009"
    # and the registry entry matching nothing is itself a finding
    drift = [f for f in found if "matches no function" in f.message]
    assert len(drift) == 1 and "'vanished'" in drift[0].message
    assert len(found) == 2


def test_lt009_negative_class_machine(tmp_path):
    assert not lint_repo(ReplayPurityChecker(), LT009_NEGATIVE, tmp_path)


def test_pure_machines_registry_pins_replay_dispatch_targets():
    """The satellite pin: ``PURE_MACHINES`` (the scheduling half) must
    cover exactly the machines ``fleet/capacity.py::replay_decisions``
    re-derives decisions through — and never the replay shell itself,
    which reads the log file and stamps its own wall time by design."""
    from land_trendr_tpu.fleet.scheduling import PURE_MACHINES

    with open(os.path.join(REPO, "land_trendr_tpu/fleet/capacity.py")) as f:
        tree = ast.parse(f.read())
    fn = next(
        n for n in ast.walk(tree)
        if isinstance(n, ast.FunctionDef) and n.name == "replay_decisions"
    )
    used = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Name):
            used.add(n.id)
        elif isinstance(n, ast.Attribute):
            used.add(n.attr)
    registered = {sym for _file, sym in PURE_MACHINES}
    # the dispatch targets: the DRR queue, the replica choice, the
    # autoscaler policy — each referenced by the shell AND registered
    for target, sym in (
        ("DrrQueue", "DrrQueue"),
        ("choose_replica", "choose_replica"),
        ("decide", "Autoscaler.decide"),
    ):
        assert target in used, f"replay_decisions no longer uses {target}"
        assert sym in registered, f"{sym} missing from PURE_MACHINES"
    # the shell is impure on purpose (file IO, replay wall-time stamp)
    assert "replay_decisions" not in registered
    # registry rows are (file, symbol) pairs pointing at real files
    for file, _sym in PURE_MACHINES:
        assert os.path.exists(os.path.join(REPO, file)), file


# ---------------------------------------------------------------------------
# LT010 — clock-domain taint

LT010_ARITH_POSITIVE = """
    import time

    def age(started_mono):
        # wall minus monotonic: nonsense on any host
        return time.time() - started_mono
"""

LT010_DICT_STORE_POSITIVE = """
    import time

    def span():
        t0 = time.monotonic()
        rec = {"start": t0}          # taint crosses the dict store
        wall = time.time()
        return wall - rec["start"]
"""

LT010_DECLARED_FIELD_POSITIVE = """
    import time

    def stamp(rec):
        rec["t_wall"] = time.monotonic()   # the PR-16 bug, verbatim
"""

LT010_CROSS_FUNCTION_POSITIVE = """
    import time

    def record_live(rec):
        rec["t"] = time.time()

    def record_replay(rec):
        rec["t"] = time.monotonic()   # same field, other domain
"""

LT010_NEGATIVE = """
    import time

    def to_wall(anchor_wall, anchor_mono, t_mono):
        # the blessed conversion: same-domain subtraction is a
        # duration, so the anchor idiom is naturally label-free
        return anchor_wall + (t_mono - anchor_mono)

    def span(a_mono, b_mono):
        return b_mono - a_mono

    def fields(has_wall, has_mono):
        # predicate names are ABOUT clocks, not OF them
        return has_wall != has_mono
"""


def test_lt010_wall_minus_mono(tmp_path):
    found = lint_source(
        ClockDomainChecker(), LT010_ARITH_POSITIVE, "mod.py", tmp_path
    )
    assert len(found) == 1
    assert found[0].rule_id == "LT010"
    assert "wall-clock value" in found[0].message
    assert "mono-clock value" in found[0].message
    assert "anchor_wall, anchor_mono" in found[0].message


def test_lt010_taint_crosses_dict_store(tmp_path):
    found = lint_source(
        ClockDomainChecker(), LT010_DICT_STORE_POSITIVE, "mod.py", tmp_path
    )
    assert any(
        "combined with" in f.message and "rec['start']" in f.message
        for f in found
    )


def test_lt010_declared_field_name(tmp_path):
    found = lint_source(
        ClockDomainChecker(), LT010_DECLARED_FIELD_POSITIVE, "mod.py",
        tmp_path,
    )
    assert len(found) == 1
    assert "declares the wall domain" in found[0].message
    assert "mono-clock value" in found[0].message


def test_lt010_same_field_two_domains_across_functions(tmp_path):
    found = lint_source(
        ClockDomainChecker(), LT010_CROSS_FUNCTION_POSITIVE, "mod.py",
        tmp_path,
    )
    assert len(found) == 1
    msg = found[0].message
    assert "record field 't'" in msg
    assert "record_live" in msg and "record_replay" in msg


def test_lt010_anchor_idiom_negative(tmp_path):
    assert not lint_source(
        ClockDomainChecker(), LT010_NEGATIVE, "mod.py", tmp_path
    )


def test_lt010_interprocedural_return_taint(tmp_path):
    # a helper RETURNING a monotonic read taints its call sites
    src = """
        import time

        def _now():
            return time.monotonic()

        def age(started_wall):
            return _now() - started_wall
    """
    found = lint_source(ClockDomainChecker(), src, "mod.py", tmp_path)
    assert len(found) == 1
    assert "mono-clock value '_now()'" in found[0].message


# ---------------------------------------------------------------------------
# LT011 — seam registry / fire-site / soak-coverage drift

FAULTS = "land_trendr_tpu/runtime/faults.py"
SOAK = "tools/fault_soak.py"

LT011_POSITIVE = {
    FAULTS: """
        SEAMS = ("dispatch", "feed.decode", "ghost.seam")
    """,
    "land_trendr_tpu/runtime/driver.py": """
        def run(faults, plan):
            faults.check("dispatch")
            plan.fired("feed.decode")
            faults.check("no.such")      # typo: never registered
    """,
    SOAK: """
        SOAK_COVERED_SEAMS = ("dispatch", "stale.seam")
    """,
}

LT011_NEGATIVE = {
    FAULTS: """
        SEAMS = ("dispatch", "feed.decode")
    """,
    "land_trendr_tpu/runtime/driver.py": """
        def run(faults, plan):
            faults.check("dispatch")
            plan.fired("feed.decode")

        def not_a_seam(validator):
            validator.check("dispatch-shaped string")  # untrusted receiver
    """,
    SOAK: """
        SOAK_COVERED_SEAMS = ("dispatch", "feed.decode")
    """,
}


def test_lt011_all_three_drift_directions(tmp_path):
    found = lint_repo(SeamCoverageChecker(), LT011_POSITIVE, tmp_path)
    msgs = "\n".join(f.message for f in found)
    # 1. fire site naming an unregistered seam
    assert "fires unregistered fault seam 'no.such'" in msgs
    # 2. registered but never fired
    assert "registered seam 'ghost.seam' is never fired" in msgs
    # 3a. registered but not soak-covered (both uncovered seams)
    assert "seam 'feed.decode' has no fault_soak case" in msgs
    assert "seam 'ghost.seam' has no fault_soak case" in msgs
    # 3b. soak table naming an unregistered seam
    assert "SOAK_COVERED_SEAMS names 'stale.seam'" in msgs
    assert all(f.rule_id == "LT011" for f in found)
    assert len(found) == 5


def test_lt011_agreement_negative(tmp_path):
    assert not lint_repo(SeamCoverageChecker(), LT011_NEGATIVE, tmp_path)


def test_lt011_missing_soak_table_is_a_finding(tmp_path):
    files = {k: v for k, v in LT011_NEGATIVE.items() if k != SOAK}
    files[SOAK] = "import numpy\n"  # the tool exists, the table is gone
    found = lint_repo(SeamCoverageChecker(), files, tmp_path)
    assert len(found) == 1
    assert "SOAK_COVERED_SEAMS data table missing" in found[0].message


# ---------------------------------------------------------------------------
# LT012 — durable-write atomicity

LT012_POSITIVE = """
    import json
    import os

    def publish(workdir, doc):
        path = os.path.join(workdir, "manifest.json")
        with open(path, "w") as f:       # torn-file window
            json.dump(doc, f)

    def report(args, doc):
        with open(args.out, "w") as f:   # the benchmark --out sink
            json.dump(doc, f)
"""

LT012_NEGATIVE = """
    import json
    import os

    def publish(workdir, doc):
        path = os.path.join(workdir, "manifest.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:        # the blessed tmp leg
            json.dump(doc, f)
        os.replace(tmp, path)            # rename is the commit

    def append_event(workdir, line):
        # O_APPEND line-atomic logs are a different sanctioned contract
        with open(os.path.join(workdir, "manifest.jsonl"), "a") as f:
            f.write(line)

    def scratch(doc):
        import tempfile
        fd, p = tempfile.mkstemp()
        with open(p, "w") as f:          # tempfile-derived: never durable
            json.dump(doc, f)
"""


def test_lt012_positive(tmp_path):
    found = lint_source(
        DurableWriteChecker(), LT012_POSITIVE, "tools/pub.py", tmp_path
    )
    assert len(found) == 2
    msgs = "\n".join(f.message for f in found)
    assert "artifact path fragment" in msgs and "manifest" in msgs
    assert "report output sink 'out'" in msgs
    assert all("os.replace" in f.message for f in found)
    assert all(f.rule_id == "LT012" for f in found)


def test_lt012_negative(tmp_path):
    assert not lint_source(
        DurableWriteChecker(), LT012_NEGATIVE, "tools/pub.py", tmp_path
    )


def test_lt012_write_text_flagged_and_tests_exempt(tmp_path):
    src = """
        def publish(path_obj, text):
            (path_obj / "snapshot.json").write_text(text)
    """
    # Path.write_text into an artifact tree is the same torn window...
    found = lint_source(DurableWriteChecker(), src, "tools/p.py", tmp_path)
    assert len(found) == 1
    # ...but tests/ model torn files on purpose and are exempt wholesale
    assert not lint_source(
        DurableWriteChecker(), src, "tests/fixture_gen.py", tmp_path
    )


# ---------------------------------------------------------------------------
# suppressions: noqa + baseline


def test_noqa_suppresses_on_line_and_comment_block(tmp_path):
    src = """
        import threading

        _lock = threading.Lock()
        _count = 0

        def bump():
            global _count
            with _lock:
                _count += 1

        def reset():
            global _count
            _count = 0  # lt: noqa[LT001]

        def peek():
            # single-writer startup path, readers not yet running
            # lt: noqa[LT001]
            return _count
    """
    rel = "mod.py"
    (tmp_path / rel).write_text(textwrap.dedent(src))
    repo = RepoCtx(str(tmp_path), files=[rel])
    report = run_rules(repo, [LockDisciplineChecker()])
    assert report["findings"] == []
    assert report["noqa_suppressed"] == 2


def test_noqa_other_rule_does_not_suppress(tmp_path):
    src = """
        import threading

        _lock = threading.Lock()
        _count = 0

        def bump():
            global _count
            with _lock:
                _count += 1

        def reset():
            global _count
            _count = 0  # lt: noqa[LT999]
    """
    rel = "mod.py"
    (tmp_path / rel).write_text(textwrap.dedent(src))
    repo = RepoCtx(str(tmp_path), files=[rel])
    report = run_rules(repo, [LockDisciplineChecker()])
    assert len(report["findings"]) == 1


def test_noqa_suppresses_new_rules(tmp_path):
    src = """
        import os
        import threading

        _lock = threading.Lock()

        def save(fd, data):
            with _lock:
                # serialization lock: the write IS the critical section
                # lt: noqa[LT007]
                os.write(fd, data)
    """
    rel = "mod.py"
    (tmp_path / rel).write_text(textwrap.dedent(src))
    repo = RepoCtx(str(tmp_path), files=[rel])
    report = run_rules(repo, [BlockingUnderLockChecker()])
    assert report["findings"] == []
    assert report["noqa_suppressed"] >= 1


def test_symbol_baseline_suppresses_new_rules(tmp_path):
    rel = "jobs.py"
    (tmp_path / rel).write_text(textwrap.dedent(LT008_POSITIVE))
    repo = RepoCtx(str(tmp_path), files=[rel])
    entry = {
        "rule": "LT008", "file": rel, "symbol": "run_jobs",
        "reason": "fixture: process-lifetime pool by design",
    }
    report = run_rules(repo, [ResourceLifecycleChecker()], Baseline([entry]))
    assert report["findings"] == []
    assert len(report["baselined"]) == 1

    # the symbol key is load-bearing: a different symbol matches nothing
    wrong = {**entry, "symbol": "other_function"}
    repo2 = RepoCtx(str(tmp_path), files=[rel])
    report2 = run_rules(
        repo2, [ResourceLifecycleChecker()], Baseline([wrong])
    )
    assert len(report2["findings"]) == 1
    assert report2["unused_baseline"] == [wrong]


def test_symbol_baseline_is_line_number_independent(tmp_path):
    # shifting the finding by 40 lines must not invalidate the entry
    rel = "jobs.py"
    shifted = ("# filler\n" * 40) + textwrap.dedent(LT008_POSITIVE)
    (tmp_path / rel).write_text(shifted)
    repo = RepoCtx(str(tmp_path), files=[rel])
    entry = {
        "rule": "LT008", "file": rel, "symbol": "run_jobs",
        "reason": "fixture: process-lifetime pool by design",
    }
    report = run_rules(repo, [ResourceLifecycleChecker()], Baseline([entry]))
    assert report["findings"] == []
    assert len(report["baselined"]) == 1


def test_baseline_suppresses_and_reports_stale(tmp_path):
    rel = "land_trendr_tpu/runtime/widget.py"
    path = tmp_path / rel
    path.parent.mkdir(parents=True)
    path.write_text("import numpy as np\n\ndef f(a):\n    return np.asarray(a)\n")
    baseline = Baseline(
        [
            {
                "rule": "LT002", "file": rel, "contains": "np.asarray",
                "reason": "fixture: deliberately blessed",
            },
            {
                "rule": "LT001", "file": "nowhere.py",
                "reason": "fixture: stale entry",
            },
        ]
    )
    repo = RepoCtx(str(tmp_path), files=[rel])
    report = run_rules(repo, [HostSyncChecker()], baseline)
    assert report["findings"] == []
    assert len(report["baselined"]) == 1
    assert report["baselined"][0][1]["reason"] == "fixture: deliberately blessed"
    assert report["unused_baseline"] == [baseline.entries[1]]


def test_baseline_requires_reason():
    with pytest.raises(BaselineError, match="reason"):
        Baseline([{"rule": "LT001", "file": "x.py"}])


def test_noqa_suppresses_dataflow_rules(tmp_path):
    """The suppression contract holds for the LT009–LT012 generation:
    an inline noqa at the finding's anchor line silences exactly that
    rule."""
    # LT010: anchor = the mixing expression's line
    clock = """
        import time

        def age(started_mono):
            return time.time() - started_mono  # lt: noqa[LT010]
    """
    (tmp_path / "c.py").write_text(textwrap.dedent(clock))
    repo = RepoCtx(str(tmp_path), files=["c.py"])
    report = run_rules(repo, [ClockDomainChecker()])
    assert report["findings"] == []
    assert report["noqa_suppressed"] == 1

    # LT012: anchor = the write call's line (comment-block form)
    write = """
        import json

        def publish(workdir, doc):
            # boot-time fixture seeding, no reader until after commit
            # lt: noqa[LT012]
            with open(workdir + "/manifest.json", "w") as f:
                json.dump(doc, f)
    """
    (tmp_path / "w.py").write_text(textwrap.dedent(write))
    repo = RepoCtx(str(tmp_path), files=["w.py"])
    report = run_rules(repo, [DurableWriteChecker()])
    assert report["findings"] == []
    assert report["noqa_suppressed"] == 1


def test_symbol_baseline_suppresses_lt009(tmp_path):
    """LT009 findings attribute to the registered MACHINE (not the
    helper the impurity hides in), so one symbol-keyed entry covers the
    machine wherever its call chain drifts."""
    for rel, source in LT009_POSITIVE.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    entries = [
        {
            "rule": "LT009", "file": SCHEDULING, "symbol": "decide",
            "reason": "fixture: impure machine pending PR-N cleanup",
        },
        {
            "rule": "LT009", "file": SCHEDULING, "symbol": "<registry>",
            "contains": "vanished",
            "reason": "fixture: entry for a machine mid-rename",
        },
    ]
    repo = RepoCtx(str(tmp_path), files=sorted(LT009_POSITIVE))
    report = run_rules(repo, [ReplayPurityChecker()], Baseline(entries))
    assert report["findings"] == []
    assert len(report["baselined"]) == 2
    assert report["unused_baseline"] == []


def test_contains_baseline_suppresses_lt011(tmp_path):
    """LT011 gap findings anchor at the registry/table lines, so the
    baseline keys on the seam NAME via ``contains`` — a reasoned
    per-seam exception, never a blanket one."""
    for rel, source in LT011_POSITIVE.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    entries = [
        {
            "rule": "LT011", "file": "land_trendr_tpu/runtime/driver.py",
            "contains": "'no.such'",
            "reason": "fixture: seam registration lands next PR",
        },
        {
            "rule": "LT011", "file": FAULTS, "contains": "'ghost.seam'",
            "reason": "fixture: fire site lands next PR",
        },
        {
            "rule": "LT011", "file": SOAK, "contains": "'feed.decode'",
            "reason": "fixture: soak case lands next PR",
        },
        {
            "rule": "LT011", "file": SOAK, "contains": "'ghost.seam'",
            "reason": "fixture: soak case lands next PR",
        },
        {
            "rule": "LT011", "file": SOAK, "contains": "'stale.seam'",
            "reason": "fixture: table prune lands next PR",
        },
    ]
    repo = RepoCtx(str(tmp_path), files=sorted(LT011_POSITIVE))
    report = run_rules(repo, [SeamCoverageChecker()], Baseline(entries))
    assert report["findings"] == []
    assert len(report["baselined"]) == 5
    assert report["unused_baseline"] == []


# ---------------------------------------------------------------------------
# the tier-1 repo gate + CLI surface


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, LT_LINT, *args],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )


def test_repo_tree_is_clean():
    """The acceptance gate: zero unbaselined findings over the real tree
    with all twelve rules active — inside the documented wall-time budget.

    The budget assertion is load-bearing: the interprocedural passes
    (call-graph build + lock/resource fixpoints + the LT009–LT012
    dataflow engine) must stay seconds-scale or tier-1 silently becomes
    a minutes-scale suite.  ``LINT_BUDGET_S`` is the bound README
    §Static analysis documents; ~12s measured here with twelve rules.
    """
    t0 = time.monotonic()
    proc = _run_cli("--json")
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < LINT_BUDGET_S, (
        f"full lt-lint run took {elapsed:.1f}s — over the documented "
        f"{LINT_BUDGET_S:.0f}s budget; the interprocedural pass has "
        "regressed (check the call-graph fixpoints before raising the bound)"
    )
    report = json.loads(proc.stdout)
    assert report["clean"] is True
    assert report["findings"] == []
    # the deliberate exceptions stay visible, reasons attached
    assert all(e["reason"] for e in report["baselined"])
    # the LT007 serialization-lock exceptions are symbol-keyed
    assert any(
        e.get("symbol") == "BlockStore.flush" for e in report["baselined"]
    )
    # and none of them went stale
    assert report["unused_baseline"] == []
    assert report["files_checked"] > 50


def test_changed_files_lists_untracked_dir_contents(tmp_path):
    """A brand-new package directory must contribute its FILES to the
    --changed set: bare `git status --porcelain` collapses it to one
    'dir/' entry that matches nothing, green-lighting a new subsystem."""
    from tools.lt_lint import changed_files

    subprocess.run(["git", "init", "-q"], cwd=tmp_path, check=True)
    pkg = tmp_path / "pkg" / "sub"
    pkg.mkdir(parents=True)
    (pkg / "a.py").write_text("x = 1\n")
    (pkg / "b.py").write_text("y = 2\n")
    changed = changed_files(tmp_path)
    assert changed is not None
    assert {"pkg/sub/a.py", "pkg/sub/b.py"} <= changed


def test_cli_changed_mode_runs():
    proc = _run_cli("--changed", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout)["clean"] is True


def test_cli_single_path_and_list_rules():
    proc = _run_cli("land_trendr_tpu/io/blockcache.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule in (
        "LT001", "LT002", "LT003", "LT004", "LT005", "LT006",
        "LT007", "LT008", "LT009", "LT010", "LT011", "LT012",
    ):
        assert rule in proc.stdout


def test_cli_sarif_output():
    """SARIF 2.1.0 artifact: all twelve rules declared, the clean tree's
    baselined findings present as SUPPRESSED results carrying their
    written justification, zero error-level results.

    Runs ``--sarif -`` so the one full-tree pass also proves stdout is
    pure JSON (the human summary must move aside to stderr) — full
    twelve-rule runs cost ~12s each, so the CLI tests share them."""
    proc = _run_cli("--sarif", "-")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    sarif = json.loads(proc.stdout)  # any human chatter here would fail
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "lt-lint"
    assert len(run["tool"]["driver"]["rules"]) == 12
    declared = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"LT009", "LT010", "LT011", "LT012"} <= declared
    errors = [r for r in run["results"] if r["level"] == "error"]
    assert errors == []
    suppressed = [r for r in run["results"] if r.get("suppressions")]
    assert len(suppressed) >= 2
    for r in suppressed:
        assert r["suppressions"][0]["justification"]
        loc = r["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(".py")
        assert loc["region"]["startLine"] >= 1


def test_cli_sarif_file_write(tmp_path):
    """--sarif FILE lands a parseable artifact on disk.  Scoped to a
    tests/ path so the run skips the interprocedural rules (their
    inputs exclude tests/) — the write path is what's under test."""
    out = tmp_path / "lint.sarif"
    proc = _run_cli("--sarif", str(out), "tests/test_lint.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    sarif = json.loads(out.read_text())
    assert sarif["version"] == "2.1.0"
    assert sarif["runs"][0]["properties"]["filesChecked"] == 1


def test_cli_rejects_json_plus_sarif_stdout():
    # both reports on stdout would concatenate two JSON documents
    proc = _run_cli("--json", "--sarif", "-")
    assert proc.returncode == 2
    assert "stdout" in proc.stderr


def test_cli_unwritable_sarif_is_config_error(tmp_path):
    # exit 2 (config), not exit 1 ("findings present"), and no traceback
    proc = _run_cli("--sarif", str(tmp_path / "no" / "dir" / "o.sarif"))
    assert proc.returncode == 2
    assert "error: --sarif" in proc.stderr
    assert "Traceback" not in proc.stderr


def test_cli_prune_baseline(tmp_path):
    """--prune-baseline drops exactly the stale entries (full runs
    only; partial runs are refused with exit 2)."""
    with open(os.path.join(REPO, "LINT_BASELINE.json")) as f:
        data = json.load(f)
    live = len(data["entries"])
    data["entries"].append(
        {"rule": "LT001", "file": "nowhere.py", "reason": "planted stale"}
    )
    bpath = tmp_path / "baseline.json"
    bpath.write_text(json.dumps(data))
    proc = _run_cli("--baseline", str(bpath), "--prune-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "pruned 1 stale" in proc.stderr
    kept = json.loads(bpath.read_text())["entries"]
    assert len(kept) == live
    assert not any(e["file"] == "nowhere.py" for e in kept)

    proc = _run_cli("--changed", "--prune-baseline")
    assert proc.returncode == 2
    assert "full run" in proc.stderr


def test_cli_rejects_reasonless_baseline(tmp_path):
    bad = tmp_path / "baseline.json"
    bad.write_text(json.dumps({"entries": [{"rule": "LT001", "file": "x.py"}]}))
    proc = _run_cli("--baseline", str(bad))
    assert proc.returncode == 2
    assert "reason" in proc.stderr


def test_cli_exits_one_on_findings(tmp_path):
    """A planted violation fails the run — the CI contract is exit 1."""
    # lint a single out-of-tree fixture through the real CLI
    fixture = tmp_path / "land_trendr_tpu" / "runtime" / "bad.py"
    fixture.parent.mkdir(parents=True)
    fixture.write_text("import numpy as np\n\ndef f(a):\n    return np.asarray(a)\n")
    # CLI paths are repo-relative; use the module API for the tmp tree
    repo = RepoCtx(str(tmp_path))
    report = run_rules(repo, default_checkers())
    assert any(f.rule_id == "LT002" for f in report["findings"])
