"""Sharded-execution tests on the virtual 8-device CPU mesh.

Checks the SPMD contract from SURVEY.md §3/§5: pixel-axis sharding over a
1-D mesh, identical numbers to the single-device path ("no cross-pixel
collectives" means sharding cannot change results), correct output
shardings, and zero collectives in the compiled program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.ops.segment import jax_segment_pixels
from land_trendr_tpu.parallel import (
    PIXEL_AXIS,
    make_mesh,
    pad_to_multiple,
    segment_pixels_sharded,
    shard_pixels,
    summarize_sharded,
)


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() == 8, "conftest must provide 8 virtual devices"
    return make_mesh()


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(7)
    ny, px = 30, 64
    years = np.arange(1990, 1990 + ny, dtype=np.int32)
    base = 0.55 + 0.05 * rng.standard_normal((px, ny))
    d_year = rng.integers(5, ny - 5, size=px)
    mag = rng.uniform(0.2, 0.5, size=px)
    after = np.arange(ny)[None, :] >= d_year[:, None]
    vals = base - after * mag[:, None] * np.exp(
        -0.1 * np.maximum(np.arange(ny)[None, :] - d_year[:, None], 0)
    )
    mask = rng.uniform(size=(px, ny)) > 0.1
    return years, (-vals).astype(np.float64), mask


def test_mesh_shape(mesh):
    assert mesh.axis_names == (PIXEL_AXIS,)
    assert mesh.devices.shape == (8,)


def test_pad_to_multiple():
    v = np.ones((13, 5), np.float32)
    m = np.ones((13, 5), bool)
    pv, pm, n = pad_to_multiple(v, m, 8)
    assert pv.shape == (16, 5) and pm.shape == (16, 5) and n == 13
    assert not pm[13:].any() and (pv[13:] == 0).all()
    # already aligned → unchanged objects
    pv2, pm2, n2 = pad_to_multiple(pv, pm, 8)
    assert pv2 is pv and pm2 is pm and n2 == 16


def test_sharded_matches_single_device(mesh, batch):
    years, vals, mask = batch
    ref = jax_segment_pixels(jnp.asarray(years), jnp.asarray(vals), jnp.asarray(mask))
    out = segment_pixels_sharded(years, vals, mask, mesh=mesh)
    for name, a, b in zip(ref._fields, ref, out):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"field {name}"
        )


def test_output_sharding_follows_pixel_axis(mesh, batch):
    years, vals, mask = batch
    out = segment_pixels_sharded(years, vals, mask, mesh=mesh)
    # (PX, NY) field and scalar-per-pixel field both shard over pixels
    assert out.fitted.sharding.is_equivalent_to(
        NamedSharding(mesh, P(PIXEL_AXIS, None)), ndim=2
    )
    assert out.rmse.sharding.is_equivalent_to(
        NamedSharding(mesh, P(PIXEL_AXIS)), ndim=1
    )


def test_no_collectives_in_compiled_program(mesh, batch):
    years, vals, mask = batch
    v, m = shard_pixels(mesh, jnp.asarray(vals), jnp.asarray(mask))
    y = jax.device_put(jnp.asarray(years), NamedSharding(mesh, P()))
    lowered = jax.jit(
        lambda yy, vv, mm: jax_segment_pixels(yy, vv, mm, LTParams())
    ).lower(y, v, m)
    hlo = lowered.compile().as_text()
    for coll in ("all-gather", "collective-permute", "all-to-all", "reduce-scatter"):
        assert coll not in hlo, f"unexpected collective {coll} in compiled HLO"
    # The only permitted all-reduce is the 1-bit convergence flag of
    # betainc's iterative lowering (a while-loop termination check — control
    # flow, not pixel data).  Any all-reduce over a numeric type would mean
    # pixel data crossed shards.
    for line in hlo.splitlines():
        if "all-reduce(" in line:
            assert "pred[]" in line, f"numeric all-reduce in HLO: {line.strip()}"


def test_accepts_unsharded_device_array(mesh, batch):
    """A single-device jax.Array (e.g. a previous op's output) must be
    resharded, not crash on SingleDeviceSharding having no .mesh."""
    years, vals, mask = batch
    v = jax.device_put(jnp.asarray(vals), jax.devices()[0])
    m = jax.device_put(jnp.asarray(mask), jax.devices()[0])
    out = segment_pixels_sharded(years, v, m, mesh=mesh)
    ref = jax_segment_pixels(jnp.asarray(years), jnp.asarray(vals), jnp.asarray(mask))
    np.testing.assert_array_equal(np.asarray(ref.fitted), np.asarray(out.fitted))


def test_indivisible_batch_raises(mesh, batch):
    years, vals, mask = batch
    with pytest.raises(ValueError, match="not divisible"):
        segment_pixels_sharded(years, vals[:13], mask[:13], mesh=mesh)


def test_summarize_sharded(mesh, batch):
    years, vals, mask = batch
    out = segment_pixels_sharded(years, vals, mask, mesh=mesh)
    s = summarize_sharded(out)
    assert s["pixels"] == vals.shape[0]
    assert 0.0 <= s["no_fit_rate"] <= 1.0
    assert s["fit_rate"] + s["no_fit_rate"] == pytest.approx(1.0)
    assert s["fit_rate"] > 0.5  # strong synthetic disturbances mostly fit


def test_summarize_excludes_padding(mesh, batch):
    years, vals, mask = batch
    v, m, n_real = pad_to_multiple(vals[:61], mask[:61], 8)
    out = segment_pixels_sharded(years, v, m, mesh=mesh)
    diluted = summarize_sharded(out)
    s = summarize_sharded(out, n_real=n_real)
    assert s["pixels"] == 61
    assert s["fit_rate"] > diluted["fit_rate"]  # padding rows never fit
    # real-pixel rate == the padded run's validity over the real rows
    assert s["fit_rate"] == pytest.approx(
        float(np.asarray(out.model_valid)[:61].mean())
    )
