"""Change-map product layer (ops/change.py).

Unit tests pin the segment-selection semantics on hand-built arrays;
the end-to-end test drives synthetic imagery with known disturbance
years through segment -> assemble -> change and checks the year-of-
detection map against the scene truth.
"""

import os

import numpy as np
import pytest

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.io.geotiff import read_geotiff
from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
from land_trendr_tpu.ops.change import (
    CHANGE_PRODUCTS,
    ChangeFilter,
    mmu_sieve,
    select_change,
    write_change_maps,
)
from land_trendr_tpu.runtime import (
    RunConfig,
    assemble_outputs,
    run_stack,
    stack_from_synthetic,
)

SIGN = -1.0  # NBR disturbance direction (idx.DISTURBANCE_SIGN["nbr"])


def one_pixel(
    vyears=(1990.0, 2000.0, 2005.0, 2015.0),
    vfits=(0.6, 0.1, 0.5, 0.45),
    valid=True,
    p=0.01,
    rmse=0.05,
):
    """(1, NV)/(1, NM) arrays for a fit with NV=4 vertices / NM=3 segments.

    Default trajectory: big disturbance 1990->2000 (-0.5), recovery
    2000->2005 (+0.4), slow small disturbance 2005->2015 (-0.05).
    """
    vy = np.asarray([vyears], np.float32)
    vf = np.asarray([vfits], np.float32)
    mag = vf[:, 1:] - vf[:, :-1]
    dur = vy[:, 1:] - vy[:, :-1]
    rate = np.where(dur > 0, mag / np.where(dur > 0, dur, 1), 0)
    return dict(
        vertex_years=vy,
        vertex_fit_vals=vf,
        seg_magnitude=mag.astype(np.float32),
        seg_duration=dur.astype(np.float32),
        seg_rate=rate.astype(np.float32),
        model_valid=np.asarray([valid]),
        p_of_f=np.asarray([p], np.float32),
        rmse=np.asarray([rmse], np.float32),
    )


def run(filt=ChangeFilter(), **kw):
    out = select_change(**one_pixel(**kw), sign=SIGN, filt=filt)
    return {k: np.asarray(v)[0] for k, v in out.items()}


def test_greatest_disturbance_default():
    got = run()
    assert bool(got["mask"])
    assert got["yod"] == 1991          # first year after the 1990 vertex
    assert got["mag"] == pytest.approx(-0.5)   # natural orientation drop
    assert got["dur"] == pytest.approx(10.0)
    assert got["preval"] == pytest.approx(0.6)
    assert got["rate"] == pytest.approx(-0.05)
    assert got["dsnr"] == pytest.approx(0.5 / 0.05)


def test_sort_newest_oldest():
    # two qualifying disturbances: 1990 (big) and 2005 (small)
    assert run(filt=ChangeFilter(sort="newest"))["yod"] == 2006
    assert run(filt=ChangeFilter(sort="oldest"))["yod"] == 1991
    assert run(filt=ChangeFilter(sort="greatest"))["yod"] == 1991


def test_recovery_kind():
    got = run(filt=ChangeFilter(kind="recovery"))
    assert bool(got["mask"])
    assert got["yod"] == 2001
    assert got["mag"] == pytest.approx(0.4)


def test_filters_gate_segments():
    # min_mag excludes the small 2005 disturbance
    assert run(filt=ChangeFilter(sort="newest", min_mag=0.1))["yod"] == 1991
    # max_dur=4 excludes BOTH (10y and 10y) disturbances
    assert not bool(run(filt=ChangeFilter(max_dur=4))["mask"])
    # year window selects the late one
    assert run(filt=ChangeFilter(year_min=2000))["yod"] == 2006
    # preval: late disturbance starts at 0.5 < 0.55
    assert run(filt=ChangeFilter(min_preval=0.55))["yod"] == 1991
    assert not bool(
        run(filt=ChangeFilter(min_preval=0.65))["mask"]
    )
    # p cap and model_valid gate everything
    assert not bool(run(p=0.2, filt=ChangeFilter(max_p=0.1))["mask"])
    assert not bool(run(valid=False)["mask"])
    # non-change outputs are zeroed on unchanged pixels
    got = run(valid=False)
    for k in CHANGE_PRODUCTS:
        assert not np.any(got[k])


def test_filter_validation():
    with pytest.raises(ValueError, match="kind"):
        ChangeFilter(kind="both")
    with pytest.raises(ValueError, match="sort"):
        ChangeFilter(sort="biggest")


def test_mmu_sieve_4_connectivity():
    m = np.zeros((8, 8), bool)
    m[0:3, 0:3] = True       # 9-px patch: kept at mmu=9
    m[6, 6] = True           # isolated: dropped
    m[4, 4] = True           # diagonal to nothing relevant: dropped
    out = mmu_sieve(m, 9)
    assert out[0:3, 0:3].all()
    assert not out[6, 6] and not out[4, 4]
    # mmu<=1 is identity (same object semantics fine)
    assert mmu_sieve(m, 1).sum() == m.sum()


def test_label4_matches_scipy_reference():
    """The pure-NumPy run-based labeler (ADVICE r3: drop the undeclared
    scipy dependency) must agree with scipy.ndimage.label component-for-
    component on random masks — same partition, same count (label NUMBERING
    may differ; compare via component pixel sets through a relabel)."""
    ndimage = pytest.importorskip("scipy.ndimage")
    from land_trendr_tpu.ops.change import label4

    rng = np.random.default_rng(77)
    structure = [[0, 1, 0], [1, 1, 1], [0, 1, 0]]
    for density in (0.05, 0.35, 0.65, 0.95):
        m = rng.uniform(size=(61, 83)) < density
        got, n_got = label4(m)
        ref, n_ref = ndimage.label(m, structure=structure)
        assert n_got == n_ref
        assert (got > 0).sum() == (ref > 0).sum() == m.sum()
        # same partition: each got-label maps to exactly one ref-label and
        # vice versa
        pairs = np.unique(np.stack([got[m], ref[m]]), axis=1)
        assert pairs.shape[1] == n_got
        assert len(np.unique(pairs[0])) == n_got
        assert len(np.unique(pairs[1])) == n_ref
    # degenerate shapes
    assert label4(np.zeros((4, 5), bool))[1] == 0
    one = np.ones((1, 7), bool)
    lab, n = label4(one)
    assert n == 1 and (lab == 1).all()


def test_mmu_sieve_equals_label_image_reference(rng):
    """The run-level sieve (no label image materialised) must equal the
    straightforward keep[labels] computation on random masks."""
    from land_trendr_tpu.ops.change import label4

    for density in (0.1, 0.35, 0.6, 0.9):
        m = rng.uniform(size=(121, 86)) < density
        labels, _ = label4(m)
        counts = np.bincount(labels.ravel())
        keep = counts >= 7
        keep[0] = False
        np.testing.assert_array_equal(mmu_sieve(m, 7), keep[labels], err_msg=str(density))


def test_end_to_end_change_maps(tmp_path):
    spec = SceneSpec(width=48, height=40, year_start=1990, year_end=2013, seed=11)
    synth = make_stack(spec)
    rstack = stack_from_synthetic(synth)
    cfg = RunConfig(
        params=LTParams(max_segments=4, vertex_count_overshoot=2),
        tile_size=32,
        workdir=os.path.join(tmp_path, "work"),
        out_dir=os.path.join(tmp_path, "out"),
    )
    run_stack(rstack, cfg)
    assemble_outputs(rstack, cfg)

    dest = os.path.join(tmp_path, "change")
    paths = write_change_maps(
        cfg.out_dir, dest, index="nbr", filt=ChangeFilter(min_mag=0.05)
    )
    assert set(paths) == set(CHANGE_PRODUCTS)
    yod, _, _ = read_geotiff(paths["yod"])
    mask, _, _ = read_geotiff(paths["mask"])
    mask = mask.astype(bool)
    assert yod.shape == (40, 48)

    disturbed = synth.truth_year >= 0
    # most truly-disturbed pixels are flagged, with yod within 2y of truth
    hit = mask & disturbed
    assert hit.sum() > 0.6 * disturbed.sum()
    err = np.abs(yod[hit] - (synth.truth_year[hit] + 1))
    assert np.median(err) <= 1
    assert (err <= 2).mean() > 0.8
    # flagged-but-undisturbed stays a modest fraction (noise-chased fits)
    assert (mask & ~disturbed).sum() < 0.25 * mask.sum()

    # mmu sieve never adds pixels and only removes whole small patches
    paths2 = write_change_maps(
        cfg.out_dir, os.path.join(tmp_path, "change_mmu"), index="nbr",
        filt=ChangeFilter(min_mag=0.05), mmu=5,
    )
    mask2, _, _ = read_geotiff(paths2["mask"])
    mask2 = mask2.astype(bool)
    assert (mask2 <= mask).all() and mask2.sum() < mask.sum() + 1


def test_fused_change_matches_posthoc(tmp_path):
    """RunConfig.change_filt (on-device selection fused into the tile
    program, assembled as change_*.tif, sieved post-assembly) must produce
    the same maps as the post-hoc write_change_maps over the segment
    rasters — exact for mask/yod, float-tolerance for the f32 products
    (the fused selector runs in the kernel dtype before the f32 cast)."""
    from land_trendr_tpu.ops.change import sieve_change_rasters

    spec = SceneSpec(width=40, height=37, year_start=1992, year_end=2012, seed=5)
    rstack = stack_from_synthetic(make_stack(spec))
    params = LTParams(max_segments=4, vertex_count_overshoot=2)
    filt = ChangeFilter(min_mag=0.05)

    cfg_fused = RunConfig(
        params=params, tile_size=32,
        workdir=os.path.join(tmp_path, "a", "work"),
        out_dir=os.path.join(tmp_path, "a", "out"),
        change_filt=filt,
    )
    run_stack(rstack, cfg_fused)
    paths_fused = assemble_outputs(rstack, cfg_fused)
    assert "change_mask" in paths_fused  # fused products ride the manifest
    sieve_change_rasters(cfg_fused.out_dir, 4)

    cfg_plain = RunConfig(
        params=params, tile_size=32,
        workdir=os.path.join(tmp_path, "b", "work"),
        out_dir=os.path.join(tmp_path, "b", "out"),
    )
    run_stack(rstack, cfg_plain)
    assemble_outputs(rstack, cfg_plain)
    posthoc = write_change_maps(
        cfg_plain.out_dir, os.path.join(tmp_path, "c"), filt=filt, mmu=4
    )

    for k in CHANGE_PRODUCTS:
        a, _, _ = read_geotiff(
            os.path.join(cfg_fused.out_dir, f"change_{k}.tif")
        )
        b, _, _ = read_geotiff(posthoc[k])
        if k in ("mask", "yod"):
            np.testing.assert_array_equal(a, b, err_msg=k)
        else:
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6, err_msg=k)


def test_change_maps_band_split_equivalence(tmp_path):
    """The streamed row-band path (band_px forcing many bands, plus the
    mmu rewrite pass) must produce byte-identical products to a
    single-band run — banding and the windowed sieve rewrite are pure
    implementation choices."""
    spec = SceneSpec(width=40, height=37, year_start=1992, year_end=2012, seed=5)
    rstack = stack_from_synthetic(make_stack(spec))
    cfg = RunConfig(
        params=LTParams(max_segments=4, vertex_count_overshoot=2),
        tile_size=32,
        workdir=os.path.join(tmp_path, "work"),
        out_dir=os.path.join(tmp_path, "out"),
    )
    run_stack(rstack, cfg)
    assemble_outputs(rstack, cfg)

    filt = ChangeFilter(min_mag=0.05)
    one = write_change_maps(
        cfg.out_dir, os.path.join(tmp_path, "one"), filt=filt, mmu=4
    )
    banded = write_change_maps(
        cfg.out_dir, os.path.join(tmp_path, "banded"), filt=filt, mmu=4,
        # 7-row bands over a 37-row raster (ragged tail); alignment off
        # because a 37-row raster cannot split on its 256-row block grid
        band_px=40 * 7, align_bands=False,
    )
    for k in CHANGE_PRODUCTS:
        a, _, _ = read_geotiff(one[k])
        b, _, _ = read_geotiff(banded[k])
        np.testing.assert_array_equal(a, b, err_msg=k)
