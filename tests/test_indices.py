"""Tests for spectral-index math and QA masking (ops/indices.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from land_trendr_tpu.ops import indices as ix


def _bands(rng, shape=(4, 5)):
    return {b: jnp.asarray(rng.uniform(0.01, 0.6, size=shape)) for b in ix.BANDS}


def test_nbr_formula(rng):
    b = _bands(rng)
    got = np.asarray(ix.nbr(b["nir"], b["swir2"]))
    want = (np.asarray(b["nir"]) - np.asarray(b["swir2"])) / (
        np.asarray(b["nir"]) + np.asarray(b["swir2"])
    )
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_ndvi_formula(rng):
    b = _bands(rng)
    got = np.asarray(ix.ndvi(b["nir"], b["red"]))
    want = (np.asarray(b["nir"]) - np.asarray(b["red"])) / (
        np.asarray(b["nir"]) + np.asarray(b["red"])
    )
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_tcw_is_linear_combination(rng):
    b = _bands(rng)
    got = np.asarray(ix.tcw(*(b[k] for k in ix.BANDS)))
    coeffs = [0.0315, 0.2021, 0.3102, 0.1594, -0.6806, -0.6109]
    want = sum(c * np.asarray(b[k]) for c, k in zip(coeffs, ix.BANDS))
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_ratio_indices_zero_denominator_stay_finite():
    z = jnp.zeros((3,))
    assert np.all(np.asarray(ix.nbr(z, z)) == 0.0)
    assert np.all(np.asarray(ix.ndvi(z, z)) == 0.0)


@pytest.mark.parametrize("name", ix.INDEX_NAMES)
def test_disturbance_positive_flip(rng, name):
    b = _bands(rng)
    natural = np.asarray(ix.compute_index(name, b, disturbance_positive=False))
    flipped = np.asarray(ix.compute_index(name, b, disturbance_positive=True))
    np.testing.assert_allclose(flipped, -natural, rtol=1e-12)


def test_compute_index_unknown_name(rng):
    with pytest.raises(ValueError, match="unknown index"):
        ix.compute_index("evi", _bands(rng))


def test_compute_index_disturbance_semantics():
    # burn: NIR drops, SWIR2 rises → natural NBR falls → disturbance-positive
    # NBR must RISE across the event.
    pre = {"nir": jnp.asarray(0.4), "swir2": jnp.asarray(0.1)}
    post = {"nir": jnp.asarray(0.15), "swir2": jnp.asarray(0.3)}
    a = float(ix.compute_index("nbr", pre))
    b = float(ix.compute_index("nbr", post))
    assert b > a


def test_scale_sr_collections():
    dn = jnp.asarray([0, 5000, 10000], dtype=jnp.int16)
    # default is the Collection-2 convention (matches qa_valid_mask's layout)
    np.testing.assert_allclose(
        np.asarray(ix.scale_sr(dn)), [-0.2, -0.0625, 0.075], atol=1e-7
    )
    c1 = np.asarray(ix.scale_sr(dn, scale=1e-4, offset=0.0))
    np.testing.assert_allclose(c1, [0.0, 0.5, 1.0])


def test_qa_valid_mask_bits():
    # bit0 fill, bit3 cloud, bit4 shadow, bit5 snow
    qa = jnp.asarray([0, 1, 1 << 3, 1 << 4, 1 << 5, 1 << 6])
    got = np.asarray(ix.qa_valid_mask(qa))
    # bit6 (clear) is not a reject bit → valid
    np.testing.assert_array_equal(got, [True, False, False, False, False, True])


def test_qa_valid_mask_custom_reject():
    qa = jnp.asarray([1 << 5])
    assert not bool(ix.qa_valid_mask(qa)[0])
    assert bool(ix.qa_valid_mask(qa, reject_bits=1 << 3)[0])


def test_sr_valid_mask_range_and_nan():
    bands = {
        "nir": jnp.asarray([0.5, 1.5, 0.5, 0.5]),
        "red": jnp.asarray([0.2, 0.2, jnp.nan, -0.1]),
    }
    got = np.asarray(ix.sr_valid_mask(bands))
    np.testing.assert_array_equal(got, [True, False, False, False])


def test_sr_valid_mask_requires_known_band():
    with pytest.raises(ValueError):
        ix.sr_valid_mask({"thermal": jnp.zeros(2)})
