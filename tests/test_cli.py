"""CLI tests: params round trip, flag→LTParams mapping, end-to-end segment.

The ``segment`` subcommand is the reference's driver contract (SURVEY.md §2
L5) — stack directory in, segment rasters + JSON run report out.
"""

import json
import os

import numpy as np
import pytest

from land_trendr_tpu.cli import build_parser, main
from land_trendr_tpu.config import LTParams
from land_trendr_tpu.io.geotiff import read_geotiff


def test_params_command_prints_defaults(capsys):
    assert main(["params"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert LTParams.from_dict(out) == LTParams()


def test_params_flags_override(capsys, tmp_path):
    pj = tmp_path / "p.json"
    pj.write_text(LTParams(max_segments=4).to_json())
    assert main([
        "params", "--params-json", str(pj),
        "--spike-threshold", "0.8", "--prevent-one-year-recovery", "false",
    ]) == 0
    got = LTParams.from_dict(json.loads(capsys.readouterr().out))
    assert got.max_segments == 4            # from JSON
    assert got.spike_threshold == 0.8       # flag override
    assert got.prevent_one_year_recovery is False


def test_parser_rejects_unknown_index():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["segment", "x", "--index", "evi"])


def test_synth_then_segment_end_to_end(tmp_path, capsys):
    stack_dir = str(tmp_path / "stack")
    assert main([
        "synth", stack_dir, "--size", "48",
        "--year-start", "1990", "--year-end", "2012", "--seed", "5",
    ]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["files"] == 23

    out_dir = str(tmp_path / "out")
    assert main([
        "segment", stack_dir,
        "--index", "nbr", "--ftv", "ndvi,tcw",
        "--tile-size", "32",
        "--workdir", str(tmp_path / "work"), "--out-dir", out_dir,
        "--max-segments", "4", "--vertex-count-overshoot", "2",
    ]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["summary"]["pixels"] == 48 * 48
    for product in ("vertex_years", "ftv_ndvi", "ftv_tcw", "model_valid"):
        assert os.path.exists(rep["outputs"][product])
    valid, _, _ = read_geotiff(rep["outputs"]["model_valid"])
    assert valid.shape == (48, 48)
    assert 0.0 < valid.mean() <= 1.0

    # rerun resumes: all tiles skipped, same outputs
    assert main([
        "segment", stack_dir,
        "--index", "nbr", "--ftv", "ndvi,tcw",
        "--tile-size", "32",
        "--workdir", str(tmp_path / "work"), "--out-dir", out_dir,
        "--max-segments", "4", "--vertex-count-overshoot", "2",
    ]) == 0
    rep2 = json.loads(capsys.readouterr().out)
    assert rep2["summary"]["tiles_skipped_resume"] == rep["summary"]["tiles"]
