"""CLI tests: params round trip, flag→LTParams mapping, end-to-end segment.

The ``segment`` subcommand is the reference's driver contract (SURVEY.md §2
L5) — stack directory in, segment rasters + JSON run report out.
"""

import json
import os

import numpy as np
import pytest

from land_trendr_tpu.cli import build_parser, main
from land_trendr_tpu.config import LTParams
from land_trendr_tpu.io.geotiff import read_geotiff


def test_params_command_prints_defaults(capsys):
    assert main(["params"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert LTParams.from_dict(out) == LTParams()


def test_params_flags_override(capsys, tmp_path):
    pj = tmp_path / "p.json"
    pj.write_text(LTParams(max_segments=4).to_json())
    assert main([
        "params", "--params-json", str(pj),
        "--spike-threshold", "0.8", "--prevent-one-year-recovery", "false",
    ]) == 0
    got = LTParams.from_dict(json.loads(capsys.readouterr().out))
    assert got.max_segments == 4            # from JSON
    assert got.spike_threshold == 0.8       # flag override
    assert got.prevent_one_year_recovery is False


def test_parser_rejects_unknown_index():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["segment", "x", "--index", "evi"])


def test_synth_then_segment_end_to_end(tmp_path, capsys):
    stack_dir = str(tmp_path / "stack")
    assert main([
        "synth", stack_dir, "--size", "48",
        "--year-start", "1990", "--year-end", "2012", "--seed", "5",
    ]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["files"] == 23

    out_dir = str(tmp_path / "out")
    assert main([
        "segment", stack_dir,
        "--index", "nbr", "--ftv", "ndvi,tcw",
        "--tile-size", "32",
        "--workdir", str(tmp_path / "work"), "--out-dir", out_dir,
        "--max-segments", "4", "--vertex-count-overshoot", "2",
    ]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["summary"]["pixels"] == 48 * 48
    for product in ("vertex_years", "ftv_ndvi", "ftv_tcw", "model_valid"):
        assert os.path.exists(rep["outputs"][product])
    valid, _, _ = read_geotiff(rep["outputs"]["model_valid"])
    assert valid.shape == (48, 48)
    assert 0.0 < valid.mean() <= 1.0

    # rerun resumes: all tiles skipped, same outputs
    assert main([
        "segment", stack_dir,
        "--index", "nbr", "--ftv", "ndvi,tcw",
        "--tile-size", "32",
        "--workdir", str(tmp_path / "work"), "--out-dir", out_dir,
        "--max-segments", "4", "--vertex-count-overshoot", "2",
    ]) == 0
    rep2 = json.loads(capsys.readouterr().out)
    assert rep2["summary"]["tiles_skipped_resume"] == rep["summary"]["tiles"]


def test_pixel_command_parity(tmp_path, capsys):
    """The single-pixel debug path runs both engines and reports parity."""
    import json as _json

    import numpy as np

    ny = 24
    years = list(range(1995, 1995 + ny))
    t = np.arange(ny)
    vals = (0.62 - np.where(t >= 10, 0.3 * np.exp(-0.1 * (t - 10)), 0.0)
            + np.sin(t) * 0.004)
    series = tmp_path / "px.json"
    series.write_text(_json.dumps({
        "years": years, "values": vals.tolist(),
    }))
    rc = main([
        "pixel", str(series), "--index", "nbr",
        "--max-segments", "4", "--vertex-count-overshoot", "2",
    ])
    assert rc == 0
    out = _json.loads(capsys.readouterr().out)
    assert out["oracle"]["model_valid"] and out["jax"]["model_valid"]
    assert out["parity"]["vertex_indices_equal"]
    assert out["parity"]["max_abs_fitted_delta"] < 1e-9
    # disturbance year (index 10) is among the oracle's vertices
    assert 10 in out["oracle"]["vertex_indices"]


def test_pixel_from_stack(tmp_path, capsys):
    """--from-stack pulls a real pixel's series through the standard
    index/masking path and runs the parity engines on it."""
    import json as _json

    assert main(["synth", str(tmp_path / "stack"), "--size", "24",
                 "--year-start", "1990", "--year-end", "2013"]) == 0
    capsys.readouterr()
    rc = main([
        "pixel", "--from-stack", str(tmp_path / "stack"),
        "--x", "5", "--y", "7", "--index", "nbr",
        "--max-segments", "4", "--vertex-count-overshoot", "2",
    ])
    assert rc == 0
    out = _json.loads(capsys.readouterr().out)
    assert "parity" in out and "oracle" in out and "jax" in out
    assert len(out["oracle"]["fitted"]) == 24
    # natural-orientation output: the BULK of a vegetated pixel's NBR
    # series is positive (a bare max>0 would pass on a negated series too)
    import numpy as np

    assert np.median(out["oracle"]["despiked"]) > 0

    # exactly one source; coordinates validated
    import pytest

    with pytest.raises(SystemExit):
        main(["pixel", "--from-stack", str(tmp_path / "stack")])
    with pytest.raises(SystemExit):
        main(["pixel", "--from-stack", str(tmp_path / "stack"),
              "--x", "999", "--y", "0"])
    with pytest.raises(SystemExit):
        main(["pixel", "a.json", "--from-stack", str(tmp_path / "stack"),
              "--x", "1", "--y", "1"])


def test_pixel_command_stdin_nofit(monkeypatch, capsys):
    """Insufficient observations → clean no-fit result via stdin."""
    import io as _io
    import json as _json

    payload = _json.dumps({
        "years": [2000, 2001, 2002],
        "values": [0.5, 0.6, 0.4],
    })
    monkeypatch.setattr("sys.stdin", _io.StringIO(payload))
    rc = main(["pixel", "-", "--engine", "oracle"])
    assert rc == 0
    out = _json.loads(capsys.readouterr().out)
    assert out["oracle"]["model_valid"] is False
    assert out["oracle"]["n_vertices"] == 0


def test_segment_trace_flag(tmp_path):
    """--trace captures a profiler trace of the run (xplane.pb on disk)."""
    import glob
    import subprocess
    import sys

    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack

    d = str(tmp_path / "stack")
    write_stack(d, make_stack(SceneSpec(width=16, height=16, year_start=2000, year_end=2012)))
    logdir = str(tmp_path / "trace")
    r = subprocess.run(
        [sys.executable, "-m", "land_trendr_tpu", "--platform", "cpu",
         "segment", d, "--out-dir", str(tmp_path / "out"),
         "--workdir", str(tmp_path / "work"), "--tile-size", "16",
         "--trace", logdir],
        capture_output=True, text=True,
        env=dict(os.environ, PYTHONPATH=os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + os.environ.get("PYTHONPATH", "").split(os.pathsep))),
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True)


def test_kitchen_sink_cli_chain(tmp_path, capsys):
    """synth → segment with every round-3 option engaged (band-subset
    loader, FTV, parallel writers, uncompressed manifest, overview
    pyramids) → change maps with filters + MMU: the cross-feature
    interfaces hold in one chained run."""
    import json as _json

    assert main(["synth", str(tmp_path / "stack"), "--size", "64",
                 "--year-start", "1990", "--year-end", "2013"]) == 0
    capsys.readouterr()
    assert main([
        "segment", str(tmp_path / "stack"),
        "--workdir", str(tmp_path / "work"),
        "--out-dir", str(tmp_path / "out"),
        "--tile-size", "32", "--ftv", "ndvi",
        "--write-workers", "2", "--manifest-compress", "deflate",
        "--out-overviews", "1",
        "--max-segments", "4", "--vertex-count-overshoot", "2",
    ]) == 0
    seg_out = _json.loads(capsys.readouterr().out)
    assert seg_out["summary"]["pixels"] == 64 * 64
    assert "ftv_ndvi" in seg_out["outputs"]

    assert main([
        "change", str(tmp_path / "out"), "--dest", str(tmp_path / "chg"),
        "--min-mag", "0.05", "--max-dur", "15", "--mmu", "3",
    ]) == 0
    chg_out = _json.loads(capsys.readouterr().out)
    assert set(chg_out["outputs"]) == {
        "mask", "yod", "mag", "dur", "rate", "preval", "dsnr"
    }
    from tests.test_geotiff import _walk_pages

    # overview page rides on the segment rasters
    assert [p[2] for p in _walk_pages(str(tmp_path / "out" / "rmse.tif"))] == [0, 1]


def test_info_command(tmp_path, capsys):
    """`info` reports header facts without decoding; --window adds bounded
    value stats that match a direct read of the same region."""
    import numpy as np

    from land_trendr_tpu.io.geotiff import GeoMeta, write_geotiff

    a = (np.arange(80 * 60, dtype=np.float32) / 100.0).reshape(80, 60)
    p = str(tmp_path / "r.tif")
    write_geotiff(
        p, a,
        geo=GeoMeta(pixel_scale=(30.0, 30.0, 0.0), tiepoint=(0, 0, 0, 1e5, 2e6, 0)),
        compress="lzw",
    )
    assert main(["info", p, "--window", "10,10,20,20"]) == 0
    rec = json.loads(capsys.readouterr().out)[p]
    assert (rec["height"], rec["width"], rec["bands"]) == (80, 60, 1)
    assert rec["dtype"] == "float32" and rec["compression"] == "lzw"
    assert rec["geotransform"][0] == 1e5 and rec["geotransform"][5] == -30.0
    win = a[10:30, 10:30]
    assert abs(rec["window"]["mean"] - float(win.mean())) < 1e-6
    assert rec["window"]["finite_frac"] == 1.0
    # malformed window: clean error, not a traceback
    assert main(["info", p, "--window", "oops"]) == 2


def test_segment_products_and_f16_flags(tmp_path, capsys):
    """round-5 fetch-economy flags: --products subsets the outputs,
    --fetch-f16 round-trips, and bad product names fail loudly."""
    stack_dir = str(tmp_path / "stack")
    assert main(["synth", stack_dir, "--size", "32",
                 "--year-start", "1990", "--year-end", "2005"]) == 0
    capsys.readouterr()
    out_dir = str(tmp_path / "out")
    assert main([
        "segment", stack_dir, "--index", "nbr", "--tile-size", "32",
        "--workdir", str(tmp_path / "work"), "--out-dir", out_dir,
        "--products", "n_vertices,seg_magnitude,model_valid", "--fetch-f16",
    ]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert set(rep["outputs"]) == {"n_vertices", "seg_magnitude", "model_valid"}

    # an invalid products list is an ARGUMENT error: clean exit code 2 with
    # the message on stderr, not a RunConfig traceback (ADVICE round 5)
    assert main([
        "segment", stack_dir, "--tile-size", "32",
        "--workdir", str(tmp_path / "w2"), "--out-dir", out_dir,
        "--products", "bogus",
    ]) == 2
    assert "unknown products" in capsys.readouterr().err
