"""GeoTIFF codec round-trip + cross-validation against Pillow."""

import numpy as np
import pytest

from land_trendr_tpu.io.geotiff import GeoMeta, read_geotiff, write_geotiff

DTYPES = ["u1", "u2", "i2", "i4", "f4", "f8"]


def _rand(rng, dtype, shape):
    if np.dtype(dtype).kind == "f":
        return rng.normal(size=shape).astype(dtype)
    info = np.iinfo(dtype)
    return rng.integers(info.min, info.max, size=shape, endpoint=True).astype(dtype)


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("compress", ["deflate", "none"])
def test_roundtrip_single_band_tiled(tmp_path, rng, dtype, compress):
    arr = _rand(rng, dtype, (70, 53))  # deliberately not tile-aligned
    p = str(tmp_path / "x.tif")
    write_geotiff(p, arr, compress=compress, tile=32)
    got, _, info = read_geotiff(p)
    np.testing.assert_array_equal(got, arr)
    assert info.bands == 1 and info.tiled and info.dtype == np.dtype(dtype)


@pytest.mark.parametrize("dtype", ["i2", "f4"])
def test_roundtrip_multiband_stripped(tmp_path, rng, dtype):
    arr = _rand(rng, dtype, (7, 130, 41))
    p = str(tmp_path / "x.tif")
    write_geotiff(p, arr, compress="deflate", tile=None)
    got, _, info = read_geotiff(p)
    np.testing.assert_array_equal(got, arr)
    assert info.bands == 7 and not info.tiled


def test_roundtrip_predictor_off(tmp_path, rng):
    arr = _rand(rng, "i2", (64, 64))
    p = str(tmp_path / "x.tif")
    write_geotiff(p, arr, predictor=False)
    got, _, _ = read_geotiff(p)
    np.testing.assert_array_equal(got, arr)


def test_predictor_improves_smooth_raster_compression(tmp_path):
    y, x = np.mgrid[0:256, 0:256]
    smooth = (y * 13 + x * 7).astype(np.int16)
    p1, p2 = str(tmp_path / "p.tif"), str(tmp_path / "np.tif")
    write_geotiff(p1, smooth, predictor=True)
    write_geotiff(p2, smooth, predictor=False)
    import os

    assert os.path.getsize(p1) < os.path.getsize(p2)
    np.testing.assert_array_equal(read_geotiff(p1)[0], smooth)


def test_geo_metadata_roundtrip(tmp_path, rng):
    geo = GeoMeta(
        pixel_scale=(30.0, 30.0, 0.0),
        tiepoint=(0.0, 0.0, 0.0, 512345.0, 5001234.0, 0.0),
        geo_key_directory=(1, 1, 0, 3, 1024, 0, 1, 1, 1025, 0, 1, 1, 3072, 0, 1, 32610),
        geo_double_params=(6378137.0,),
        geo_ascii_params="WGS 84 / UTM zone 10N|",
        nodata=-9999.0,
    )
    arr = _rand(rng, "i2", (32, 32))
    p = str(tmp_path / "x.tif")
    write_geotiff(p, arr, geo=geo)
    _, got, _ = read_geotiff(p)
    assert got.pixel_scale == geo.pixel_scale
    assert got.tiepoint == geo.tiepoint
    assert got.geo_key_directory == geo.geo_key_directory
    assert got.geo_double_params == geo.geo_double_params
    assert got.geo_ascii_params == geo.geo_ascii_params
    assert got.nodata == geo.nodata
    gt = got.geotransform()
    assert gt == (512345.0, 30.0, 0.0, 5001234.0, 0.0, -30.0)


def test_pillow_reads_our_files(tmp_path, rng):
    from PIL import Image

    arr = _rand(rng, "u1", (48, 60))
    p = str(tmp_path / "x.tif")
    write_geotiff(p, arr, compress="deflate", tile=32)
    with Image.open(p) as im:
        got = np.asarray(im)
    np.testing.assert_array_equal(got, arr)


@pytest.mark.parametrize("mode_dtype", [("L", "u1"), ("I", "i4"), ("F", "f4")])
def test_we_read_pillow_files(tmp_path, rng, mode_dtype):
    from PIL import Image

    mode, dtype = mode_dtype
    arr = _rand(rng, dtype, (33, 47))
    p = str(tmp_path / "x.tif")
    Image.fromarray(arr, mode=mode).save(p, compression="tiff_adobe_deflate")
    got, _, _ = read_geotiff(p)
    np.testing.assert_array_equal(got, arr)


def test_reject_garbage_header(tmp_path):
    p = str(tmp_path / "bad.tif")
    with open(p, "wb") as f:
        f.write(b"XX\x00\x00")
    with pytest.raises(ValueError, match="byte-order"):
        read_geotiff(p)


def test_read_big_endian_file(tmp_path, rng):
    # hand-built MM (big-endian) stripped uncompressed uint16 file
    import struct

    arr = _rand(rng, "u2", (5, 7))
    data = arr.astype(">u2").tobytes()
    entries = [
        (256, 3, 1, 7),       # width
        (257, 3, 1, 5),       # height
        (258, 3, 1, 16),      # bits
        (259, 3, 1, 1),       # no compression
        (262, 3, 1, 1),       # photometric
        (273, 4, 1, 8),       # strip offset (data right after header)
        (277, 3, 1, 1),       # samples/pixel
        (278, 3, 1, 5),       # rows/strip
        (279, 4, 1, len(data)),
        (339, 3, 1, 1),       # unsigned
    ]
    ifd_off = 8 + len(data)
    buf = struct.pack(">2sHI", b"MM", 42, ifd_off) + data
    buf += struct.pack(">H", len(entries))
    for tag, ftype, count, val in entries:
        if ftype == 3:
            buf += struct.pack(">HHIHH", tag, ftype, count, val, 0)
        else:
            buf += struct.pack(">HHII", tag, ftype, count, val)
    buf += struct.pack(">I", 0)
    p = str(tmp_path / "be.tif")
    with open(p, "wb") as f:
        f.write(buf)
    got, _, info = read_geotiff(p)
    np.testing.assert_array_equal(got, arr)
    assert info.dtype == np.dtype("u2")


def test_read_rational_resolution_tags(tmp_path, rng):
    # Pillow writes X/YResolution RATIONAL tags with dpi set — the reader
    # must skip over them without miscounting their payload size.
    from PIL import Image

    arr = _rand(rng, "u1", (9, 11))
    p = str(tmp_path / "dpi.tif")
    Image.fromarray(arr, mode="L").save(p, dpi=(72, 72))
    got, _, _ = read_geotiff(p)
    np.testing.assert_array_equal(got, arr)


def test_reject_bigtiff(tmp_path):
    import struct

    p = str(tmp_path / "big.tif")
    with open(p, "wb") as f:
        f.write(struct.pack("<2sHI", b"II", 43, 0))
    with pytest.raises(ValueError, match="BigTIFF"):
        read_geotiff(p)
