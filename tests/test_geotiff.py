"""GeoTIFF codec round-trip + cross-validation against Pillow."""

import numpy as np
import pytest

from land_trendr_tpu.io.geotiff import (
    GeoMeta,
    read_geotiff,
    read_geotiff_info,
    read_geotiff_window,
    write_geotiff,
)

DTYPES = ["u1", "u2", "i2", "i4", "f4", "f8"]


def _rand(rng, dtype, shape):
    if np.dtype(dtype).kind == "f":
        return rng.normal(size=shape).astype(dtype)
    info = np.iinfo(dtype)
    return rng.integers(info.min, info.max, size=shape, endpoint=True).astype(dtype)


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("compress", ["deflate", "none"])
def test_roundtrip_single_band_tiled(tmp_path, rng, dtype, compress):
    arr = _rand(rng, dtype, (70, 53))  # deliberately not tile-aligned
    p = str(tmp_path / "x.tif")
    write_geotiff(p, arr, compress=compress, tile=32)
    got, _, info = read_geotiff(p)
    np.testing.assert_array_equal(got, arr)
    assert info.bands == 1 and info.tiled and info.dtype == np.dtype(dtype)


@pytest.mark.parametrize("dtype", ["i2", "f4"])
def test_roundtrip_multiband_stripped(tmp_path, rng, dtype):
    arr = _rand(rng, dtype, (7, 130, 41))
    p = str(tmp_path / "x.tif")
    write_geotiff(p, arr, compress="deflate", tile=None)
    got, _, info = read_geotiff(p)
    np.testing.assert_array_equal(got, arr)
    assert info.bands == 7 and not info.tiled


def test_roundtrip_predictor_off(tmp_path, rng):
    arr = _rand(rng, "i2", (64, 64))
    p = str(tmp_path / "x.tif")
    write_geotiff(p, arr, predictor=False)
    got, _, _ = read_geotiff(p)
    np.testing.assert_array_equal(got, arr)


def test_predictor_improves_smooth_raster_compression(tmp_path):
    y, x = np.mgrid[0:256, 0:256]
    smooth = (y * 13 + x * 7).astype(np.int16)
    p1, p2 = str(tmp_path / "p.tif"), str(tmp_path / "np.tif")
    write_geotiff(p1, smooth, predictor=True)
    write_geotiff(p2, smooth, predictor=False)
    import os

    assert os.path.getsize(p1) < os.path.getsize(p2)
    np.testing.assert_array_equal(read_geotiff(p1)[0], smooth)


def test_geo_metadata_roundtrip(tmp_path, rng):
    geo = GeoMeta(
        pixel_scale=(30.0, 30.0, 0.0),
        tiepoint=(0.0, 0.0, 0.0, 512345.0, 5001234.0, 0.0),
        geo_key_directory=(1, 1, 0, 3, 1024, 0, 1, 1, 1025, 0, 1, 1, 3072, 0, 1, 32610),
        geo_double_params=(6378137.0,),
        geo_ascii_params="WGS 84 / UTM zone 10N|",
        nodata=-9999.0,
    )
    arr = _rand(rng, "i2", (32, 32))
    p = str(tmp_path / "x.tif")
    write_geotiff(p, arr, geo=geo)
    _, got, _ = read_geotiff(p)
    assert got.pixel_scale == geo.pixel_scale
    assert got.tiepoint == geo.tiepoint
    assert got.geo_key_directory == geo.geo_key_directory
    assert got.geo_double_params == geo.geo_double_params
    assert got.geo_ascii_params == geo.geo_ascii_params
    assert got.nodata == geo.nodata
    gt = got.geotransform()
    assert gt == (512345.0, 30.0, 0.0, 5001234.0, 0.0, -30.0)


def test_pillow_reads_our_files(tmp_path, rng):
    from PIL import Image

    arr = _rand(rng, "u1", (48, 60))
    p = str(tmp_path / "x.tif")
    write_geotiff(p, arr, compress="deflate", tile=32)
    with Image.open(p) as im:
        got = np.asarray(im)
    np.testing.assert_array_equal(got, arr)


@pytest.mark.parametrize("mode_dtype", [("L", "u1"), ("I", "i4"), ("F", "f4")])
def test_we_read_pillow_files(tmp_path, rng, mode_dtype):
    from PIL import Image

    mode, dtype = mode_dtype
    arr = _rand(rng, dtype, (33, 47))
    p = str(tmp_path / "x.tif")
    Image.fromarray(arr, mode=mode).save(p, compression="tiff_adobe_deflate")
    got, _, _ = read_geotiff(p)
    np.testing.assert_array_equal(got, arr)


def test_reject_garbage_header(tmp_path):
    p = str(tmp_path / "bad.tif")
    with open(p, "wb") as f:
        f.write(b"XX\x00\x00\x00\x00\x00\x00")  # 8 bytes, wrong magic
    with pytest.raises(ValueError, match="byte-order"):
        read_geotiff(p)
    with open(p, "wb") as f:
        f.write(b"II")  # shorter than any TIFF header
    with pytest.raises(ValueError, match="truncated"):
        read_geotiff(p)


def test_read_big_endian_file(tmp_path, rng):
    # hand-built MM (big-endian) stripped uncompressed uint16 file
    import struct

    arr = _rand(rng, "u2", (5, 7))
    data = arr.astype(">u2").tobytes()
    entries = [
        (256, 3, 1, 7),       # width
        (257, 3, 1, 5),       # height
        (258, 3, 1, 16),      # bits
        (259, 3, 1, 1),       # no compression
        (262, 3, 1, 1),       # photometric
        (273, 4, 1, 8),       # strip offset (data right after header)
        (277, 3, 1, 1),       # samples/pixel
        (278, 3, 1, 5),       # rows/strip
        (279, 4, 1, len(data)),
        (339, 3, 1, 1),       # unsigned
    ]
    ifd_off = 8 + len(data)
    buf = struct.pack(">2sHI", b"MM", 42, ifd_off) + data
    buf += struct.pack(">H", len(entries))
    for tag, ftype, count, val in entries:
        if ftype == 3:
            buf += struct.pack(">HHIHH", tag, ftype, count, val, 0)
        else:
            buf += struct.pack(">HHII", tag, ftype, count, val)
    buf += struct.pack(">I", 0)
    p = str(tmp_path / "be.tif")
    with open(p, "wb") as f:
        f.write(buf)
    got, _, info = read_geotiff(p)
    np.testing.assert_array_equal(got, arr)
    assert info.dtype == np.dtype("u2")


def test_read_rational_resolution_tags(tmp_path, rng):
    # Pillow writes X/YResolution RATIONAL tags with dpi set — the reader
    # must skip over them without miscounting their payload size.
    from PIL import Image

    arr = _rand(rng, "u1", (9, 11))
    p = str(tmp_path / "dpi.tif")
    Image.fromarray(arr, mode="L").save(p, dpi=(72, 72))
    got, _, _ = read_geotiff(p)
    np.testing.assert_array_equal(got, arr)


def test_reject_bigtiff_bad_offsize(tmp_path):
    import struct

    p = str(tmp_path / "big.tif")
    with open(p, "wb") as f:
        f.write(struct.pack("<2sHHHQ", b"II", 43, 4, 0, 16))
    with pytest.raises(ValueError, match="BigTIFF"):
        read_geotiff(p)


# ---------------------------------------------------------------------------
# LZW read (VERDICT round-1 missing item #5)
# ---------------------------------------------------------------------------


def test_lzw_decode_pinned_fixtures():
    """Hand-pinned TIFF-LZW streams (MSB-first, clear=256, KwKwK case)."""
    from land_trendr_tpu.io.geotiff import _lzw_decode

    assert (
        _lzw_decode(b'\x80\x15\t\xe4")<\xa4N\'\x95 PH4.\x0b\x07\x84\xc0@')
        == b"TOBEORNOTTOBEORTOBEORNOT"
    )
    # runs of one symbol exercise the KwKwK (code == next_code) path
    assert _lzw_decode(b"\x80\x18`P8$\x16\x02") == b"a" * 15


def test_lzw_decode_rejects_garbage():
    from land_trendr_tpu.io.geotiff import _lzw_decode

    with pytest.raises(ValueError, match="LZW"):
        _lzw_decode(b"\x00\x80\x00")  # no leading clear code


@pytest.mark.parametrize("dtype", ["u1", "i4", "f4"])
def test_we_read_pillow_lzw_files(tmp_path, rng, dtype):
    """Known-good LZW fixtures straight from Pillow's encoder."""
    from PIL import Image

    mode = {"u1": "L", "i4": "I", "f4": "F"}[dtype]
    arr = _rand(rng, dtype, (70, 83))
    p = str(tmp_path / "lzw.tif")
    Image.fromarray(arr, mode=mode).save(p, compression="tiff_lzw")
    got, _, info = read_geotiff(p)
    assert info.compression == 5
    np.testing.assert_array_equal(got, arr)


def test_lzw_native_matches_python(tmp_path, rng):
    """The C++ LZW fast path and the NumPy/Python path agree byte-for-byte
    on the same file (incompressible data → long literal runs; smooth data
    → deep table chains)."""
    from PIL import Image

    from land_trendr_tpu.io import native

    if not native.available():
        pytest.skip("native library not built")
    smooth = np.add.outer(
        np.arange(128, dtype=np.int32), np.arange(131, dtype=np.int32)
    ) % 255
    noisy = rng.integers(0, 256, size=(128, 131)).astype(np.uint8)
    for name, arr, mode in (("smooth", smooth.astype(np.uint8), "L"), ("noisy", noisy, "L")):
        p = str(tmp_path / f"{name}.tif")
        Image.fromarray(arr, mode=mode).save(p, compression="tiff_lzw")
        got_nat, _, _ = read_geotiff(p)
        # native.available() is consulted per call, so nulling _LIB forces
        # the pure-Python path for the comparison read
        saved = native._LIB
        try:
            native._LIB = None
            got_py, _, _ = read_geotiff(p)
        finally:
            native._LIB = saved
        np.testing.assert_array_equal(got_nat, got_py)
        np.testing.assert_array_equal(got_nat, arr)


# ---------------------------------------------------------------------------
# BigTIFF (VERDICT round-1 missing item #4)
# ---------------------------------------------------------------------------


def test_bigtiff_forced_roundtrip(tmp_path, rng):
    """bigtiff=True writes the 43-magic layout end-to-end (u64 IFD, LONG8
    offsets) and reads back identically, with geo metadata intact."""
    arr = _rand(rng, "i2", (3, 90, 77))
    geo = GeoMeta(
        pixel_scale=(30.0, 30.0, 0.0),
        tiepoint=(0.0, 0.0, 0.0, 512000.0, 5300000.0, 0.0),
        nodata=-9999.0,
    )
    p = str(tmp_path / "big.tif")
    write_geotiff(p, arr, geo=geo, bigtiff=True)
    with open(p, "rb") as f:
        assert f.read(4) == b"II+\x00"  # magic 43
    got, geo2, info = read_geotiff(p)
    assert info.big
    np.testing.assert_array_equal(got, arr)
    assert geo2.pixel_scale == geo.pixel_scale
    assert geo2.tiepoint == geo.tiepoint
    assert geo2.nodata == geo.nodata


@pytest.mark.parametrize("compress", ["deflate", "none"])
def test_bigtiff_stripped_roundtrip(tmp_path, rng, compress):
    arr = _rand(rng, "f4", (65, 49))
    p = str(tmp_path / "big.tif")
    write_geotiff(p, arr, compress=compress, tile=None, bigtiff=True)
    got, _, info = read_geotiff(p)
    assert info.big and not info.tiled
    np.testing.assert_array_equal(got, arr)


def test_bigtiff_auto_stays_classic_when_small(tmp_path, rng):
    arr = _rand(rng, "u2", (40, 40))
    p = str(tmp_path / "small.tif")
    write_geotiff(p, arr)  # bigtiff="auto" default
    _, _, info = read_geotiff(p)
    assert not info.big


def test_bigtiff_offsets_beyond_4gb(tmp_path, rng):
    """A sparse BigTIFF whose single strip sits past the 4 GB boundary —
    the layout classic TIFF cannot address (VERDICT: 'round-trip tests for
    >4 GB-offset layouts (can be sparse/synthetic)')."""
    import struct

    from land_trendr_tpu.io.geotiff import _IfdBuilder

    arr = _rand(rng, "u2", (32, 41))
    payload = arr.tobytes()
    data_off = 5 * 2**30 + 128  # > 4 GB
    ifd = _IfdBuilder(big=True)
    ifd.add(256, 4, (41,))            # ImageWidth
    ifd.add(257, 4, (32,))            # ImageLength
    ifd.add(258, 3, (16,))            # BitsPerSample
    ifd.add(259, 3, (1,))             # Compression: none
    ifd.add(262, 3, (1,))             # Photometric
    ifd.add(273, 16, (data_off,))     # StripOffsets (LONG8, >4GB)
    ifd.add(277, 3, (1,))             # SamplesPerPixel
    ifd.add(278, 3, (32,))            # RowsPerStrip
    ifd.add(279, 16, (len(payload),)) # StripByteCounts
    ifd.add(339, 3, (1,))             # SampleFormat

    p = str(tmp_path / "sparse.tif")
    ifd_off = 16
    with open(p, "wb") as f:
        f.write(struct.pack("<2sHHHQ", b"II", 43, 8, 0, ifd_off))
        f.write(ifd.serialize(ifd_off))
        f.seek(data_off)  # sparse hole — apparent size ~5 GB, tiny on disk
        f.write(payload)

    got, _, info = read_geotiff(p)
    assert info.big
    np.testing.assert_array_equal(got, arr)


def test_classic_overflow_forced_raises(tmp_path, rng, monkeypatch):
    """Forcing bigtiff=False on an oversized encode raises instead of
    writing a corrupt file (offsets are checked before serialization)."""
    import land_trendr_tpu.io.geotiff as gt

    arr = _rand(rng, "u2", (64, 64))
    real_encode = gt._encode_all

    def fake_encode(blocks, comp_id, use_pred):
        out = real_encode(blocks, comp_id, use_pred)

        class HugeBytes(bytes):
            def __len__(self):
                return 2**31  # two of these overflow 2**32

        return [HugeBytes(b) for b in out] * 2

    monkeypatch.setattr(gt, "_encode_all", fake_encode)
    with pytest.raises(ValueError, match="4 GB"):
        gt.write_geotiff(str(tmp_path / "x.tif"), arr, bigtiff=False)


# ---------------------------------------------------------------------------
# Round-2 advisor hardening (ADVICE.md r2)
# ---------------------------------------------------------------------------


def _pack_lzw(codes, width=9):
    """MSB-first bit-pack fixed-width LZW codes (all test streams stay 9-bit)."""
    bits = "".join(format(c, f"0{width}b") for c in codes)
    bits += "0" * (-len(bits) % 8)
    return bytes(int(bits[i : i + 8], 2) for i in range(0, len(bits), 8))


def test_lzw_consecutive_clear_codes():
    """libtiff tolerates Clear immediately followed by another Clear; rare
    but legal streams from other encoders must read (ADVICE r2)."""
    from land_trendr_tpu.io.geotiff import _lzw_decode

    # leading double clear: CLEAR CLEAR 'A' 'B' EOI
    assert _lzw_decode(_pack_lzw([256, 256, 65, 66, 257])) == b"AB"
    # mid-stream double clear: CLEAR 'A' CLEAR CLEAR 'B' EOI
    assert _lzw_decode(_pack_lzw([256, 65, 256, 256, 66, 257])) == b"AB"


def test_lzw_consecutive_clear_codes_native(tmp_path):
    """Same tolerance in the C++ fast path, exercised through a hand-built
    LZW TIFF read both natively and via the pure-Python reference."""
    import struct

    from land_trendr_tpu.io import native
    from land_trendr_tpu.io.geotiff import _IfdBuilder

    if not native.available():
        pytest.skip("native library not built")

    stream = _pack_lzw([256, 256, 65, 256, 256, 66, 257])  # decodes to b"AB"
    ifd = _IfdBuilder()
    ifd.add(256, 4, (2,))            # ImageWidth
    ifd.add(257, 4, (1,))            # ImageLength
    ifd.add(258, 3, (8,))            # BitsPerSample
    ifd.add(259, 3, (5,))            # Compression: LZW
    ifd.add(262, 3, (1,))            # Photometric
    ifd.add(273, 4, (8,))            # StripOffsets
    ifd.add(277, 3, (1,))            # SamplesPerPixel
    ifd.add(278, 3, (1,))            # RowsPerStrip
    ifd.add(279, 4, (len(stream),))  # StripByteCounts
    ifd.add(339, 3, (1,))            # SampleFormat

    p = str(tmp_path / "dclear.tif")
    ifd_off = 8 + len(stream) + (len(stream) & 1)
    with open(p, "wb") as f:
        f.write(struct.pack("<2sHI", b"II", 42, ifd_off))
        f.write(stream.ljust(ifd_off - 8, b"\0"))
        f.write(ifd.serialize(ifd_off))

    got_nat, _, info = read_geotiff(p)
    assert info.compression == 5
    saved = native._LIB
    try:
        native._LIB = None
        got_py, _, _ = read_geotiff(p)
    finally:
        native._LIB = saved
    np.testing.assert_array_equal(got_nat, np.array([[65, 66]], dtype=np.uint8))
    np.testing.assert_array_equal(got_nat, got_py)


def test_reject_huge_ifd_payload_count(tmp_path):
    """A corrupt entry whose payload exceeds the file size fails with a clean
    parse error, not a multi-GB read attempt (ADVICE r2)."""
    import struct

    p = str(tmp_path / "corrupt.tif")
    with open(p, "wb") as f:
        f.write(struct.pack("<2sHI", b"II", 42, 8))
        f.write(struct.pack("<H", 1))
        # one LONG entry claiming 2^30 values → 4 GB payload in a 26-byte file
        f.write(struct.pack("<HHII", 256, 4, 2**30, 8))
        f.write(struct.pack("<I", 0))
    with pytest.raises(ValueError, match="exceeds"):
        read_geotiff(p)


def test_bigtiff_auto_accounts_for_ifd_payloads(tmp_path, rng, monkeypatch):
    """Near the 4 GB boundary, large out-of-line IFD payloads (e.g. a big
    ascii tag) must flip bigtiff='auto' to the BigTIFF layout instead of
    overflowing classic offsets at serialize time (ADVICE r2)."""
    import struct

    import land_trendr_tpu.io.geotiff as gt

    arr = _rand(rng, "u2", (64, 64))
    real_encode = gt._encode_all

    def fake_encode(blocks, comp_id, use_pred):
        out = real_encode(blocks, comp_id, use_pred)

        class HugeBytes(bytes):
            def __len__(self):
                return 2**32 - 2**20  # data alone still fits classic

        return [HugeBytes(out[0])]

    monkeypatch.setattr(gt, "_encode_all", fake_encode)
    # 2 MB ascii payload pushes the serialized IFD past 2^32
    p = str(tmp_path / "auto.tif")
    gt.write_geotiff(
        p, arr, extra_ascii_tags={42112: "x" * 2**21}, bigtiff="auto"
    )
    with open(p, "rb") as f:
        hdr = f.read(4)
    assert struct.unpack("<H", hdr[2:4])[0] == 43  # switched to BigTIFF


def test_bigtiff_auto_switches_on_block_offset_overflow(tmp_path, rng, monkeypatch):
    """Multiple blocks whose later offsets exceed u32 — the packing of the
    offset ARRAY (not just the IFD tail) must trigger the auto-switch, not
    escape as a raw struct.error (code-review r3)."""
    import struct

    import land_trendr_tpu.io.geotiff as gt

    arr = _rand(rng, "u2", (64, 64))
    real_encode = gt._encode_all

    def fake_encode(blocks, comp_id, use_pred):
        out = real_encode(blocks, comp_id, use_pred)

        class HugeBytes(bytes):
            def __len__(self):
                return 2**31  # three of these put block 3's offset past 2^32

        return [HugeBytes(out[0])] * 3

    monkeypatch.setattr(gt, "_encode_all", fake_encode)
    p = str(tmp_path / "multi.tif")
    gt.write_geotiff(p, arr, bigtiff="auto")
    with open(p, "rb") as f:
        hdr = f.read(4)
    assert struct.unpack("<H", hdr[2:4])[0] == 43  # switched to BigTIFF

    # forcing classic on the same data keeps the friendly error
    with pytest.raises(ValueError, match="4 GB"):
        gt.write_geotiff(str(tmp_path / "forced.tif"), arr, bigtiff=False)


# ---------------------------------------------------------------------------
# Multi-page IFD chains (VERDICT r2 item #3: multi-IFD tolerance)
# ---------------------------------------------------------------------------


def test_multipage_reads_all_pages(tmp_path, rng):
    """A multi-page file (one band per IFD) stacks pages on the band axis
    instead of silently truncating to page 1."""
    from PIL import Image

    pages = [rng.integers(0, 255, size=(33, 47)).astype(np.uint8) for _ in range(3)]
    p = str(tmp_path / "multi.tif")
    ims = [Image.fromarray(a, mode="L") for a in pages]
    ims[0].save(p, save_all=True, append_images=ims[1:])

    got, _, info = read_geotiff(p)
    assert info.bands == 3
    np.testing.assert_array_equal(got, np.stack(pages))


def test_multipage_mismatched_pages_error(tmp_path, rng):
    """Pages of different sizes raise loudly rather than mis-stacking."""
    from PIL import Image

    a = rng.integers(0, 255, size=(16, 16)).astype(np.uint8)
    b = rng.integers(0, 255, size=(8, 24)).astype(np.uint8)
    p = str(tmp_path / "mismatch.tif")
    Image.fromarray(a, mode="L").save(
        p, save_all=True, append_images=[Image.fromarray(b, mode="L")]
    )
    with pytest.raises(ValueError, match="mismatched pages"):
        read_geotiff(p)
    # the header-only and windowed readers share the same guard — a
    # mismatched chain must not silently cast/truncate into page 0's dtype
    with pytest.raises(ValueError, match="mismatched pages"):
        read_geotiff_info(p)
    with pytest.raises(ValueError, match="mismatched pages"):
        read_geotiff_window(p, 0, 0, 4, 4)


def test_multipage_skips_overview_pages(tmp_path, rng):
    """COG-style files carry reduced-resolution overview IFDs
    (NewSubfileType bit 0x1) — they must be skipped, not stacked or
    mis-matched (code-review r3)."""
    import struct

    from land_trendr_tpu.io.geotiff import _IfdBuilder

    full = rng.integers(0, 255, size=(16, 20)).astype(np.uint8)
    ovr = full[::2, ::2].copy()  # 8×10 overview

    def page(ifd_off, arr, data_off, subtype, next_off):
        ifd = _IfdBuilder()
        if subtype:
            ifd.add(254, 4, (subtype,))     # NewSubfileType
        ifd.add(256, 4, (arr.shape[1],))
        ifd.add(257, 4, (arr.shape[0],))
        ifd.add(258, 3, (8,))
        ifd.add(259, 3, (1,))
        ifd.add(262, 3, (1,))
        ifd.add(273, 4, (data_off,))
        ifd.add(277, 3, (1,))
        ifd.add(278, 4, (arr.shape[0],))
        ifd.add(279, 4, (arr.size,))
        ifd.add(339, 3, (1,))
        body = ifd.serialize(ifd_off)
        # overwrite the next-IFD pointer (serialize writes 0)
        # next-ptr sits right after count + entries, before overflow data
        n = struct.unpack("<H", body[:2])[0]
        ptr_at = 2 + n * 12
        return body[:ptr_at] + struct.pack("<I", next_off) + body[ptr_at + 4 :]

    p = str(tmp_path / "cog.tif")
    d0 = 8
    d1 = d0 + full.size
    ifd0_off = d1 + ovr.size
    # compute page-0 IFD size to place page 1 after it
    probe = page(ifd0_off, full, d0, 0, 0)
    ifd1_off = ifd0_off + len(probe)
    with open(p, "wb") as f:
        f.write(struct.pack("<2sHI", b"II", 42, ifd0_off))
        f.write(full.tobytes())
        f.write(ovr.tobytes())
        f.write(page(ifd0_off, full, d0, 0, ifd1_off))
        f.write(page(ifd1_off, ovr, d0 + full.size, 1, 0))

    got, _, info = read_geotiff(p)
    assert info.bands == 1
    np.testing.assert_array_equal(got, full)


def test_corrupt_next_ifd_pointer(tmp_path, rng):
    """A garbage next-IFD trailer fails with the codec's ValueError
    taxonomy, not struct.error/KeyError (code-review r3)."""
    arr = _rand(rng, "u2", (8, 8))
    p = str(tmp_path / "trailer.tif")
    write_geotiff(p, arr, tile=None, compress="none")
    # classic header: IFD offset at byte 4; patch its next-IFD pointer
    import struct

    with open(p, "r+b") as f:
        (ifd_off,) = struct.unpack("<I", f.read(8)[4:8])
        f.seek(ifd_off)
        (n,) = struct.unpack("<H", f.read(2))
        f.seek(ifd_off + 2 + n * 12)
        f.write(struct.pack("<I", 2**31))  # far past EOF
    with pytest.raises(ValueError, match="next-IFD"):
        read_geotiff(p)


# ---------------------------------------------------------------------------
# LZW write (closes the read-only gap: GDAL write-compression parity)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", ["u1", "u2", "i2", "f4"])
@pytest.mark.parametrize("pred", [True, False])
def test_lzw_write_roundtrip(tmp_path, rng, dtype, pred):
    arr = _rand(rng, dtype, (3, 70, 83))
    p = str(tmp_path / "w.tif")
    write_geotiff(p, arr, compress="lzw", predictor=pred)
    got, _, info = read_geotiff(p)
    assert info.compression == 5
    np.testing.assert_array_equal(got, arr)


def test_pillow_reads_our_lzw(tmp_path, rng):
    from PIL import Image

    arr = rng.integers(0, 255, size=(90, 77)).astype(np.uint8)
    p = str(tmp_path / "ourlzw.tif")
    write_geotiff(p, arr, compress="lzw", predictor=False, tile=None)
    got = np.asarray(Image.open(p))
    np.testing.assert_array_equal(got, arr)


def test_lzw_write_deep_table_clears(tmp_path, rng):
    """A block big enough to fill the 12-bit table exercises the encoder's
    Clear+reset path; both our decoder and the native one must read it."""
    from land_trendr_tpu.io import native

    arr = rng.integers(0, 65535, size=(257, 263), endpoint=True).astype(np.uint16)
    p = str(tmp_path / "deep.tif")
    write_geotiff(p, arr, compress="lzw", tile=256)
    got, _, _ = read_geotiff(p)
    np.testing.assert_array_equal(got, arr)
    if native.available():
        saved = native._LIB
        try:
            native._LIB = None
            got_py, _, _ = read_geotiff(p)
        finally:
            native._LIB = saved
        np.testing.assert_array_equal(got_py, arr)


def test_lzw_encode_terminal_boundary_and_speed():
    """Streams ending exactly at an early-change boundary must emit EOI at
    the widened width (code-review r3: 766-byte all-distinct-pairs case
    decoded to 768 bytes before the fix), and encoding must be linear —
    the unmasked bigint bit-buffer made 256 KiB take ~54 s."""
    import time

    from land_trendr_tpu.io.geotiff import _lzw_decode, _lzw_encode

    # random data has mostly-distinct adjacent pairs (~one table add per
    # byte minus a few collisions), so contiguous length sweeps around the
    # 511/1023/2047 boundaries land the decoder's count exactly on the
    # early-change edge at the trailing code for several lengths — with
    # this seed, the pre-fix encoder fails at n = 771, 772, 774, 1814
    rng = np.random.default_rng(42)
    for n in list(range(740, 790)) + list(range(1770, 1820)):
        data = rng.integers(0, 256, n).astype(np.uint8).tobytes()
        assert _lzw_decode(_lzw_encode(data)) == data, n

    rng = np.random.default_rng(0)
    big = rng.integers(0, 256, 262144).astype(np.uint8).tobytes()
    t0 = time.perf_counter()
    enc = _lzw_encode(big)
    dt = time.perf_counter() - t0
    assert dt < 5.0, f"encode of 256 KiB took {dt:.1f}s — quadratic regression"
    assert len(enc) > 0


def test_lzw_writer_native_and_python_identical_files(tmp_path, rng):
    """The native LZW encode path and the pure-Python reference produce
    byte-identical files (the codec's acceleration-only contract)."""
    from land_trendr_tpu.io import native

    if not native.available():
        pytest.skip("native library not built")
    arr = _rand(rng, "u2", (3, 90, 77))
    p_nat = str(tmp_path / "nat.tif")
    p_py = str(tmp_path / "py.tif")
    write_geotiff(p_nat, arr, compress="lzw")
    saved = native._LIB
    try:
        native._LIB = None
        write_geotiff(p_py, arr, compress="lzw")
    finally:
        native._LIB = saved
    with open(p_nat, "rb") as a, open(p_py, "rb") as b:
        assert a.read() == b.read()
    got, _, info = read_geotiff(p_nat)
    assert info.compression == 5
    np.testing.assert_array_equal(got, arr)


def test_corrupt_tile_geometry_rejected(tmp_path, rng):
    """Inflated TileWidth/TileLength tags must fail as a corrupt-TIFF
    ValueError before any decode-path allocation — not a MemoryError from
    np.zeros on garbage dimensions (code-review r3, reproduced under a
    4 GiB rlimit)."""
    import struct

    arr = _rand(rng, "u2", (40, 40))
    p = str(tmp_path / "t.tif")
    write_geotiff(p, arr, tile=32)
    blob = bytearray(open(p, "rb").read())
    # patch TileWidth (322) and TileLength (323) SHORT values to 60000
    for tag in (322, 323):
        i = blob.find(struct.pack("<HH", tag, 3))
        assert i > 0
        blob[i + 8 : i + 10] = struct.pack("<H", 60000)
    open(p, "wb").write(bytes(blob))
    with pytest.raises(ValueError, match="corrupt block geometry"):
        read_geotiff(p)


def _walk_pages(path):
    """(height, width, subfile_type) per IFD page, via raw chain walk."""
    import struct

    with open(path, "rb") as f:
        data = f.read()
    off = struct.unpack("<I", data[4:8])[0]
    pages = []
    while off:
        n = struct.unpack("<H", data[off : off + 2])[0]
        w = h = None
        sub = 0
        for i in range(n):
            e = data[off + 2 + 12 * i : off + 14 + 12 * i]
            tag, ftype, cnt = struct.unpack("<HHI", e[:8])
            if tag == 256:
                w = struct.unpack("<I", e[8:12])[0]
            elif tag == 257:
                h = struct.unpack("<I", e[8:12])[0]
            elif tag == 254:
                sub = struct.unpack("<I", e[8:12])[0]
        pages.append((h, w, sub))
        off = struct.unpack("<I", data[off + 2 + 12 * n : off + 6 + 12 * n])[0]
    return pages


def test_overview_pyramid_pages(tmp_path, rng):
    """overviews=N appends N halved ReducedImage pages; the reader skips
    them, so the full-resolution round trip is unchanged."""
    a = rng.integers(-100, 4000, (2, 130, 97)).astype(np.int16)
    p = str(tmp_path / "ov.tif")
    write_geotiff(p, a, overviews=2, tile=64)
    back, _, _ = read_geotiff(p)
    np.testing.assert_array_equal(back, a)
    pages = _walk_pages(p)
    assert pages == [(130, 97, 0), (65, 49, 1), (33, 25, 1)]


def test_overview_auto_and_resampling(tmp_path, rng):
    """'auto' stops under 256; average-resampled overviews stay in dtype
    and near the full-resolution local means."""
    a = (np.arange(600 * 520, dtype=np.float32).reshape(1, 600, 520) % 97.0)
    p = str(tmp_path / "ov_auto.tif")
    write_geotiff(p, a, overviews="auto", resampling="average")
    pages = _walk_pages(p)
    # 'auto' halves until the smaller dimension drops under 256
    assert [d[:2] for d in pages] == [(600, 520), (300, 260), (150, 130)]
    back, _, _ = read_geotiff(p)
    np.testing.assert_array_equal(back, a[0])  # single band reads 2-D
    with pytest.raises(ValueError, match="resampling"):
        write_geotiff(p, a, overviews=1, resampling="cubic")
    with pytest.raises(ValueError, match="overviews"):
        write_geotiff(p, a, overviews=-2)


def test_overview_strips_and_single_page_unchanged(tmp_path, rng):
    """Strip layout carries overviews too; overviews=0 writes a single
    page byte-identical to the pre-overview writer's output shape."""
    a = rng.integers(0, 255, (1, 70, 40)).astype(np.uint8)
    p = str(tmp_path / "ov_strips.tif")
    write_geotiff(p, a, overviews=1, tile=None)
    assert [d[2] for d in _walk_pages(p)] == [0, 1]
    back, _, _ = read_geotiff(p)
    np.testing.assert_array_equal(back, a[0])  # single band reads 2-D

    p0 = str(tmp_path / "ov_none.tif")
    write_geotiff(p0, a, overviews=0, tile=None)
    assert _walk_pages(p0) == [(70, 40, 0)]  # default path: single page
    back0, _, _ = read_geotiff(p0)
    np.testing.assert_array_equal(back0, a[0])


def test_read_geotiff_info_header_only(tmp_path, rng):
    """read_geotiff_info answers shape/layout/geo questions from the IFD
    alone — same facts read_geotiff reports, without decoding a block."""
    a = rng.integers(0, 255, size=(2, 90, 130)).astype(np.uint8)
    geo = GeoMeta(
        pixel_scale=(30.0, 30.0, 0.0),
        tiepoint=(0, 0, 0, 512000.0, 4.2e6, 0),
        nodata=255.0,
    )
    p = str(tmp_path / "i.tif")
    write_geotiff(p, a, geo=geo, overviews=2, tile=64)
    g, i = read_geotiff_info(p)
    _, g_ref, i_ref = read_geotiff(p)
    assert (i.height, i.width, i.bands) == (90, 130, 2)
    assert i.dtype == np.uint8 and i.tiled and not i.big
    assert g.pixel_scale == g_ref.pixel_scale == geo.pixel_scale
    assert g.tiepoint == g_ref.tiepoint
    assert g.nodata == 255.0
    # multi-page band stacking counts every full-res page, skips overviews
    from PIL import Image

    pages = [Image.fromarray(x, mode="L") for x in a]
    mp = str(tmp_path / "mp.tif")
    pages[0].save(mp, save_all=True, append_images=pages[1:])
    _, i_mp = read_geotiff_info(mp)
    assert i_mp.bands == 2


@pytest.mark.parametrize("tile", [64, None])
@pytest.mark.parametrize("compress", ["deflate", "lzw", "none"])
def test_read_geotiff_window(tmp_path, rng, tile, compress):
    """Window reads decode only intersecting blocks and agree with the
    full-read slice for interior, edge, and single-pixel windows across
    every layout × codec combination (both native and NumPy paths are
    exercised by the native suite's LT_NO_NATIVE runs)."""
    a = rng.integers(0, 4000, size=(3, 150, 211)).astype(np.uint16)
    p = str(tmp_path / "w.tif")
    write_geotiff(p, a, compress=compress, tile=tile)
    for (y0, x0, h, w) in (
        (0, 0, 150, 211),      # the whole raster
        (10, 20, 70, 99),      # interior, block-straddling
        (149, 210, 1, 1),      # bottom-right corner pixel
        (0, 200, 150, 11),     # right edge column band
    ):
        win = read_geotiff_window(p, y0, x0, h, w)
        np.testing.assert_array_equal(win, a[:, y0 : y0 + h, x0 : x0 + w])
    with pytest.raises(ValueError, match="window"):
        read_geotiff_window(p, 100, 0, 100, 10)  # past the bottom edge


def test_read_geotiff_window_multipage_and_single_band(tmp_path, rng):
    from PIL import Image

    a = rng.integers(0, 255, size=(3, 77, 91)).astype(np.uint8)
    mp = str(tmp_path / "mp.tif")
    ims = [Image.fromarray(x, mode="L") for x in a]
    ims[0].save(mp, save_all=True, append_images=ims[1:])
    win = read_geotiff_window(mp, 30, 40, 20, 25)
    np.testing.assert_array_equal(win, a[:, 30:50, 40:65])

    p1 = str(tmp_path / "one.tif")
    write_geotiff(p1, a[0], tile=64)
    win = read_geotiff_window(p1, 5, 6, 30, 30)
    assert win.shape == (30, 30)
    np.testing.assert_array_equal(win, a[0, 5:35, 6:36])


def test_read_geotiff_window_bigtiff(tmp_path, rng):
    """Window reads work identically on the BigTIFF layout (u64 offsets in
    the block tables — the CONUS-scale mosaic case)."""
    a = rng.normal(size=(95, 140)).astype(np.float32)
    p = str(tmp_path / "big.tif")
    write_geotiff(p, a, tile=64, bigtiff=True)
    _, info = read_geotiff_info(p)
    assert info.big and info.block_rows == 64
    for (y0, x0, h, w) in ((0, 0, 95, 140), (30, 50, 40, 60), (94, 139, 1, 1)):
        win = read_geotiff_window(p, y0, x0, h, w)
        np.testing.assert_array_equal(win, a[y0 : y0 + h, x0 : x0 + w])
