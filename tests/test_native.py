"""Native C++ raster codec (native/lt_native.cc + io/native.py).

The native path must be a pure acceleration of the NumPy codec: identical
decoded arrays, byte-identical encoded files.  Tests build the library on
demand (skipped when no C++ toolchain is available).
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

from land_trendr_tpu.io import geotiff as gt
from land_trendr_tpu.io import native

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")


@pytest.fixture(scope="session", autouse=True)
def built_lib():
    """(Re)build liblt_native.so if a toolchain exists; reload the binding.

    ``make`` is mtime-incremental, so this also refreshes a stale .so left
    over from an older ABI (which ``_load`` would refuse).
    """
    if shutil.which("make") is not None and shutil.which("g++") is not None:
        subprocess.run(["make", "-C", NATIVE_DIR], check=True, capture_output=True)
    elif not os.path.exists(os.path.join(NATIVE_DIR, "liblt_native.so")):
        pytest.skip("no C++ toolchain; native codec untestable")
    if not native.available():
        native._LIB, native._LIB_PATH = native._load()
    if not native.available():
        pytest.skip("native library failed to load")
    yield


@pytest.fixture()
def no_native(monkeypatch):
    """Force the pure-NumPy path."""
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_LIB_PATH", None)


def _img(rng, shape, dtype):
    if np.dtype(dtype).kind == "f":
        return rng.normal(0, 1000, size=shape).astype(dtype)
    info = np.iinfo(dtype)
    return rng.integers(info.min, info.max, size=shape, dtype=dtype)


@pytest.mark.parametrize("dtype", ["i2", "u2", "u1", "i4", "f4"])
@pytest.mark.parametrize("tile", [64, None])
def test_native_read_matches_numpy(tmp_path, rng, dtype, tile):
    """Files written by the reference NumPy path decode identically through
    the native path, across dtypes, tiled/stripped, ragged edges."""
    arr = _img(rng, (3, 100, 75), dtype)  # ragged vs 64-tiles and 64-strips
    path = str(tmp_path / "t.tif")
    gt.write_geotiff(path, arr, tile=tile)

    assert native.available()
    got_native, _, _ = gt.read_geotiff(path)

    import unittest.mock as mock

    with mock.patch.object(native, "_LIB", None):
        got_numpy, _, _ = gt.read_geotiff(path)
    np.testing.assert_array_equal(got_native, got_numpy)
    np.testing.assert_array_equal(got_native, arr)


@pytest.mark.parametrize("predictor", [True, False])
@pytest.mark.parametrize("compress", ["deflate", "none"])
def test_native_write_byte_identical(tmp_path, rng, predictor, compress):
    """Native and NumPy writers produce byte-identical files (same zlib
    level, same predictor arithmetic)."""
    arr = _img(rng, (2, 90, 130), "i2")
    p_nat = str(tmp_path / "nat.tif")
    p_ref = str(tmp_path / "ref.tif")
    gt.write_geotiff(p_nat, arr, compress=compress, predictor=predictor)

    import unittest.mock as mock

    with mock.patch.object(native, "_LIB", None):
        gt.write_geotiff(p_ref, arr, compress=compress, predictor=predictor)
    assert open(p_nat, "rb").read() == open(p_ref, "rb").read()


def test_native_write_stripped_equal_blocks(tmp_path, rng):
    """Strip layout with height % 64 == 0 → equal blocks → native path."""
    arr = _img(rng, (128, 50), "u2")
    p_nat = str(tmp_path / "nat.tif")
    p_ref = str(tmp_path / "ref.tif")
    gt.write_geotiff(p_nat, arr, tile=None)
    import unittest.mock as mock

    with mock.patch.object(native, "_LIB", None):
        gt.write_geotiff(p_ref, arr, tile=None)
    assert open(p_nat, "rb").read() == open(p_ref, "rb").read()
    back, _, _ = gt.read_geotiff(p_nat)
    np.testing.assert_array_equal(back, arr)


def test_decode_blocks_multithreaded(rng):
    """Thread count changes scheduling, never results."""
    blocks = _img(rng, (16, 32, 32, 2), "i2")
    payload = native.encode_blocks(blocks, predictor=2)
    offsets, counts, data = [], [], b""
    for b in payload:
        offsets.append(len(data))
        counts.append(len(b))
        data += b
    kw = dict(
        compression=8, predictor=2, rows=32, width=32, spp=2,
        dtype=np.dtype("i2"),
    )
    one = native.decode_blocks(
        data, np.array(offsets), np.array(counts), n_threads=1, **kw
    )
    many = native.decode_blocks(
        data, np.array(offsets), np.array(counts), n_threads=8, **kw
    )
    np.testing.assert_array_equal(one, many)
    np.testing.assert_array_equal(one, blocks)


def test_decode_blocks_rejects_garbage():
    data = b"certainly not deflate"
    with pytest.raises(native.NativeCodecError):
        native.decode_blocks(
            data,
            np.array([0]),
            np.array([len(data)]),
            compression=8,
            predictor=1,
            rows=4,
            width=4,
            spp=1,
            dtype=np.dtype("u1"),
        )


def test_decode_blocks_rejects_out_of_bounds():
    with pytest.raises(native.NativeCodecError):
        native.decode_blocks(
            b"\0" * 16,
            np.array([8]),
            np.array([100]),  # runs past the file image
            compression=1,
            predictor=1,
            rows=4,
            width=4,
            spp=1,
            dtype=np.dtype("u1"),
        )


def test_reader_falls_back_when_native_off(tmp_path, rng, no_native):
    arr = _img(rng, (40, 40), "i2")
    path = str(tmp_path / "t.tif")
    gt.write_geotiff(path, arr)
    assert not native.available()
    back, _, _ = gt.read_geotiff(path)
    np.testing.assert_array_equal(back, arr)


def test_roundtrip_through_driver_products(tmp_path, rng):
    """Float32 multi-band product rasters (the driver's output shape) run
    the native encode+decode path and round-trip exactly."""
    arr = rng.normal(0, 1, size=(7, 96, 64)).astype(np.float32)
    path = str(tmp_path / "p.tif")
    gt.write_geotiff(path, arr)
    back, _, info = gt.read_geotiff(path)
    np.testing.assert_array_equal(back, arr)
    assert info.bands == 7


def test_truncated_deflate_block_raises(tmp_path, rng):
    """A deflate stream that inflates short of its expected size is corrupt
    and must raise — not silently zero-fill (parity with NumPy frombuffer)."""
    import zlib

    good = rng.integers(-500, 500, size=(8, 8, 1), dtype=np.int16)
    full = zlib.compress(good.tobytes(), 6)
    short = zlib.compress(good.tobytes()[: good.nbytes // 2], 6)
    data = full + short
    offsets = np.array([0, len(full)])
    counts = np.array([len(full), len(short)])
    with pytest.raises(native.NativeCodecError):
        native.decode_blocks(
            data, offsets, counts,
            compression=8, predictor=1, rows=8, width=8, spp=1,
            dtype=np.dtype("i2"),
        )


def test_short_last_strip_deflate_roundtrip(tmp_path, rng):
    """Legally-short deflate last strip (height not a strip multiple) still
    decodes through the native path."""
    arr = rng.integers(-999, 999, size=(70, 33), dtype=np.int16)  # 64+6 rows
    path = str(tmp_path / "s.tif")
    gt.write_geotiff(path, arr, tile=None)
    assert native.available()
    back, _, info = gt.read_geotiff(path)
    assert not info.tiled
    np.testing.assert_array_equal(back, arr)


def test_gather_tile_matches_numpy(rng):
    """The threaded feed-path gather equals the NumPy slice+transpose on
    interior, edge, and single-row windows, all dtypes."""
    from land_trendr_tpu.io import native

    if not native.available():
        pytest.skip("native library not built")
    for dtype in (np.uint16, np.int16, np.uint8, np.float32):
        if np.dtype(dtype).kind == "f":
            cube = rng.normal(size=(11, 60, 70)).astype(dtype)
        else:
            cube = rng.integers(0, 200, size=(11, 60, 70)).astype(dtype)
        for (y0, x0, h, w) in ((0, 0, 32, 32), (28, 38, 32, 32), (5, 7, 13, 29), (59, 0, 1, 70)):
            ref = np.ascontiguousarray(
                cube[:, y0 : y0 + h, x0 : x0 + w].reshape(11, h * w).T
            )
            got = native.gather_tile(cube, y0, x0, h, w)
            np.testing.assert_array_equal(got, ref, err_msg=str((dtype, y0, x0)))


def test_gather_tile_rejects_out_of_bounds(rng):
    from land_trendr_tpu.io import native

    if not native.available():
        pytest.skip("native library not built")
    cube = np.zeros((4, 16, 16), np.int16)
    with pytest.raises(native.NativeCodecError):
        native.gather_tile(cube, 8, 8, 16, 16)  # window past the edge


def test_write_store_zip_reads_like_savez(tmp_path, rng):
    """The native store-zip artifact is a valid zip np.load reads exactly
    like np.savez output — same members, same arrays, member-for-member."""
    from land_trendr_tpu.io import native

    if not native.available():
        pytest.skip("native library not built")
    arrays = {
        "rmse": rng.normal(size=4096).astype(np.float32),
        "model_valid": rng.uniform(size=4096) > 0.5,
        "vertex_indices": rng.integers(0, 40, size=(4096, 7)).astype(np.int32),
        "empty": np.zeros((0, 3), np.float64),
        "noncontig": np.asarray(rng.normal(size=(64, 64)).T),
    }
    p_native = str(tmp_path / "native.npz")
    p_ref = str(tmp_path / "ref.npz")
    native.write_store_zip(p_native, arrays)
    np.savez(p_ref, **arrays)

    import zipfile

    zf = zipfile.ZipFile(p_native)
    assert zf.testzip() is None  # CRCs verified member by member
    assert all(i.compress_type == zipfile.ZIP_STORED for i in zf.infolist())
    with np.load(p_native) as got, np.load(p_ref) as ref:
        assert set(got.files) == set(ref.files) == set(arrays)
        for k in arrays:
            np.testing.assert_array_equal(got[k], ref[k])
            np.testing.assert_array_equal(got[k], arrays[k])


def test_manifest_none_artifacts_use_native_writer(tmp_path, rng):
    """TileManifest.record(compress='none') routes through the native
    store-zip writer and load_tile reads it back unchanged; with the
    library disabled the fallback produces an equally-readable artifact."""
    from land_trendr_tpu.io import native
    from land_trendr_tpu.runtime.manifest import TileManifest

    if not native.available():
        pytest.skip("native library not built")
    arrays = {
        "rmse": rng.normal(size=1024).astype(np.float32),
        "fitted": rng.normal(size=(1024, 16)).astype(np.float32),
    }
    m = TileManifest(str(tmp_path / "w"), "a" * 16)
    m.open(resume=False)
    m.record(0, arrays, {}, compress="none")
    got = m.load_tile(0)
    for k in arrays:
        np.testing.assert_array_equal(got[k], arrays[k])

    orig = native._LIB
    native._LIB = None
    try:
        m.record(1, arrays, {}, compress="none")
    finally:
        native._LIB = orig
    got = m.load_tile(1)
    for k in arrays:
        np.testing.assert_array_equal(got[k], arrays[k])
