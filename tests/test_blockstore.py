"""Persistent ingest-store tests: parity matrix, fingerprint
invalidation, budget eviction, torn-segment recovery, and the
driver-level "ingest once, serve many" rerun.

The contract under test (io/blockstore.py + the blockcache store tier):
store-served window reads are byte-identical to store-off reads across
the codec matrix, warm/restart passes skip TIFF decode entirely, and a
rewritten input file can never serve its predecessor's bytes.
"""

import glob
import json
import os
import sys
import time

import numpy as np
import pytest

from land_trendr_tpu.io import blockcache
from land_trendr_tpu.io.blockstore import BlockStore
from land_trendr_tpu.io.geotiff import read_geotiff_window, write_geotiff

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


@pytest.fixture(autouse=True)
def _clean_blockcache():
    """Every test starts and ends with an unconfigured cache/store."""
    blockcache.configure(0, None)
    blockcache.cache_clear()
    yield
    blockcache.configure(0, None)
    blockcache.cache_clear()


def _scene(tmp_path, name, compress, predictor, tile, size=400, seed=7):
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:size, 0:size]
    arr = ((yy * 3 + xx * 2) % 4096 + rng.integers(0, 64, (size, size))).astype(
        np.uint16
    )
    p = os.path.join(tmp_path, f"{name}.tif")
    write_geotiff(p, arr, compress=compress, tile=tile, predictor=predictor)
    return p, arr


WINDOWS = [(0, 0, 180, 180), (100, 100, 250, 250), (300, 250, 100, 150)]


@pytest.mark.parametrize(
    "compress,predictor,tile",
    [
        ("none", False, 256),
        ("deflate", False, 256),
        ("deflate", True, 256),
        ("deflate", True, None),  # stripped layout
        ("lzw", True, 256),
    ],
)
def test_store_parity_matrix(tmp_path, compress, predictor, tile):
    """Codec × predictor × layout under store off/cold/warm/restart:
    every mode's window reads are byte-identical, and warm/restart
    serve with zero misses (decode fully skipped)."""
    p, arr = _scene(str(tmp_path), "s", compress, predictor, tile)
    ref = {w: read_geotiff_window(p, *w) for w in WINDOWS}  # store off

    store = BlockStore(str(tmp_path / "store"), budget_bytes=64 << 20)
    blockcache.configure(0, 1, store=store)
    cold = {w: read_geotiff_window(p, *w) for w in WINDOWS}
    store.flush()
    base = store.stats_snapshot()
    warm = {w: read_geotiff_window(p, *w) for w in WINDOWS}
    d = store.stats_delta(base)
    assert d["misses"] == 0 and d["hits"] > 0
    store.close()

    store2 = BlockStore(str(tmp_path / "store"), budget_bytes=64 << 20)
    blockcache.configure(0, 1, store=store2)
    base = store2.stats_snapshot()
    restart = {w: read_geotiff_window(p, *w) for w in WINDOWS}
    d = store2.stats_delta(base)
    assert d["misses"] == 0 and d["hits"] > 0
    store2.close()

    for w in WINDOWS:
        for mode, got in (("cold", cold), ("warm", warm), ("restart", restart)):
            assert got[w].dtype == ref[w].dtype
            assert got[w].tobytes() == ref[w].tobytes(), (mode, w)


def test_fingerprint_invalidation(tmp_path):
    """A touched mtime_ns/size drops the stale entry and re-decodes —
    the store can never serve a rewritten file's predecessor bytes."""
    p, _arr = _scene(str(tmp_path), "s", "deflate", True, 256)
    store = BlockStore(str(tmp_path / "store"), budget_bytes=64 << 20)
    blockcache.configure(0, 1, store=store)
    read_geotiff_window(p, 0, 0, 300, 300)
    store.flush()

    time.sleep(0.02)  # ensure a distinct mtime_ns
    rng = np.random.default_rng(9)
    arr2 = rng.integers(0, 4096, (400, 400)).astype(np.uint16)
    write_geotiff(p, arr2, compress="deflate", tile=256, predictor=True)
    blockcache.cache_clear()  # the RAM tier has its own mtime guard

    base = store.stats_snapshot()
    got = read_geotiff_window(p, 0, 0, 300, 300)
    d = store.stats_delta(base)
    assert np.array_equal(got, arr2[:300, :300])
    assert d["hits"] == 0
    assert d["stale_dropped"] >= 1
    store.close()


def test_budget_evicts_whole_segments(tmp_path):
    """On-disk bytes stay within the budget by dropping oldest segments;
    evicted blocks simply re-decode."""
    p, arr = _scene(str(tmp_path), "s", "deflate", True, 256)
    # tiny budget: one 256² uint16 block is 128 KiB; 2 blocks fit
    store = BlockStore(
        str(tmp_path / "store"), budget_bytes=256 << 10, segment_bytes=1
    )  # segment_bytes=1: every put flushes its own segment
    blockcache.configure(0, 1, store=store)
    read_geotiff_window(p, 0, 0, 400, 400)  # 4 blocks -> evictions
    s = store.stats_snapshot()
    assert s["evicted_segments"] >= 2
    assert s["bytes"] <= 256 << 10
    # reads stay correct through the churn
    got = read_geotiff_window(p, 100, 100, 200, 200)
    assert np.array_equal(got, arr[100:300, 100:300])
    store.close()


def test_torn_segment_recovery(tmp_path):
    """A truncated segment data file (crash/bit rot) is dropped at open
    — reads fall back to decode, nothing raises."""
    p, arr = _scene(str(tmp_path), "s", "deflate", True, 256)
    root = str(tmp_path / "store")
    store = BlockStore(root, budget_bytes=64 << 20)
    blockcache.configure(0, 1, store=store)
    read_geotiff_window(p, 0, 0, 400, 400)
    store.close()

    bins = glob.glob(os.path.join(root, "seg-*.bin"))
    assert bins
    with open(bins[0], "r+b") as f:
        f.truncate(10)  # torn far short of the index's claim

    store2 = BlockStore(root, budget_bytes=64 << 20)
    assert store2.stats_snapshot()["corrupt_dropped"] >= 1
    blockcache.configure(0, 1, store=store2)
    got = read_geotiff_window(p, 0, 0, 400, 400)
    assert np.array_equal(got, arr)
    store2.close()


def test_orphan_and_tmp_gc(tmp_path):
    """A STALE .bin with no committed index (crash between the two
    renames) and stale leftover .tmp files are garbage-collected at
    open; FRESH ones are left alone — in a shared store directory they
    may be a live sibling process mid-commit."""
    root = str(tmp_path / "store")
    os.makedirs(root)
    stale = ("seg-1-000000.bin", "seg-1-000001.bin.tmp", "x.tmp")
    fresh = ("seg-2-000000.bin", "seg-2-000001.bin.tmp")
    for name in (*stale, *fresh):
        with open(os.path.join(root, name), "wb") as f:
            f.write(b"garbage")
    old = time.time() - 3600
    for name in stale:
        os.utime(os.path.join(root, name), (old, old))
    store = BlockStore(root, budget_bytes=1 << 20)
    left = sorted(os.path.basename(p) for p in glob.glob(os.path.join(root, "*")))
    assert left == sorted(fresh)
    store.close()


def test_unopenable_segment_drops_whole_segment(tmp_path):
    """A deleted segment data file (a sibling's eviction) costs ONE
    whole-segment drop — not a failed open + corruption count per
    sibling entry."""
    p, arr = _scene(str(tmp_path), "s", "deflate", True, 256)
    root = str(tmp_path / "store")
    store = BlockStore(root, budget_bytes=64 << 20)
    blockcache.configure(0, 1, store=store)
    read_geotiff_window(p, 0, 0, 400, 400)  # 4 blocks, one segment
    store.flush()
    for b in glob.glob(os.path.join(root, "seg-*.bin")):
        os.unlink(b)
    store2_stats = store.stats_snapshot()
    got = read_geotiff_window(p, 0, 0, 400, 400)
    d = store.stats_delta(store2_stats)
    assert np.array_equal(got, arr)
    assert d["corrupt_dropped"] == 1  # one drop for the whole segment
    store.close()


def test_store_with_ram_tier_promotion(tmp_path):
    """With both tiers on, a restart serves from the store ONCE per
    block and promotes into RAM — subsequent reads are RAM hits."""
    p, _arr = _scene(str(tmp_path), "s", "deflate", True, 256)
    store = BlockStore(str(tmp_path / "store"), budget_bytes=64 << 20)
    blockcache.configure(64 << 20, 1, store=store)
    read_geotiff_window(p, 0, 0, 400, 400)
    store.flush()
    store.close()
    blockcache.cache_clear()

    store2 = BlockStore(str(tmp_path / "store"), budget_bytes=64 << 20)
    blockcache.configure(64 << 20, 1, store=store2)
    cb = blockcache.stats_snapshot()
    sb = store2.stats_snapshot()
    read_geotiff_window(p, 0, 0, 400, 400)
    read_geotiff_window(p, 0, 0, 400, 400)
    cd = blockcache.stats_delta(cb)
    sd = store2.stats_delta(sb)
    assert sd["hits"] == 4  # one store hit per block, first pass only
    assert cd["hits"] == 4  # second pass served from RAM
    store2.close()


def test_driver_ingest_once_serve_many(tmp_path):
    """The service-mode workload: two driver runs over the same lazy
    stack share one store directory; the second run decodes nothing new
    and produces byte-identical rasters."""
    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack_c2
    from land_trendr_tpu.runtime import RunConfig, run_stack
    from land_trendr_tpu.runtime.stack import open_stack_dir_c2_lazy

    c2 = str(tmp_path / "c2")
    write_stack_c2(
        c2, make_stack(SceneSpec(width=96, height=96, year_start=2000,
                                 year_end=2006, seed=7))
    )
    stack = open_stack_dir_c2_lazy(c2, bands=("nir", "swir2"))
    store_dir = str(tmp_path / "shared_store")
    kw = dict(
        params=LTParams(max_segments=4, vertex_count_overshoot=2),
        tile_size=48, feed_cache_mb=0, ingest_store_mb=64,
        ingest_store_dir=store_dir, retry_backoff_s=0.0,
    )
    s1 = run_stack(stack, RunConfig(
        workdir=str(tmp_path / "w1"), out_dir=str(tmp_path / "o1"), **kw
    ))
    assert s1["ingest_store"]["put_blocks"] > 0
    # fresh workdir, same store: every block served persistently
    blockcache.cache_clear()
    s2 = run_stack(stack, RunConfig(
        workdir=str(tmp_path / "w2"), out_dir=str(tmp_path / "o2"), **kw
    ))
    assert s2["ingest_store"]["misses"] == 0
    assert s2["ingest_store"]["hits"] > 0
    assert s2["ingest_store"]["put_blocks"] == 0

    for p in sorted(glob.glob(os.path.join(str(tmp_path / "w1"), "tile_*.npz"))):
        q = os.path.join(str(tmp_path / "w2"), os.path.basename(p))
        with np.load(p) as a, np.load(q) as b:
            for k in a.files:
                assert a[k].tobytes() == b[k].tobytes()


def test_ingest_store_telemetry_and_rollup(tmp_path):
    """The ingest_store event passes schema + value lint, advances the
    lt_ingest_store_* instruments, and folds into obs_report with the
    derived hit_rate."""
    import check_events_schema
    import obs_report

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack_c2
    from land_trendr_tpu.runtime import RunConfig, run_stack
    from land_trendr_tpu.runtime.stack import open_stack_dir_c2_lazy

    c2 = str(tmp_path / "c2")
    write_stack_c2(
        c2, make_stack(SceneSpec(width=96, height=96, year_start=2000,
                                 year_end=2004, seed=3))
    )
    stack = open_stack_dir_c2_lazy(c2, bands=("nir", "swir2"))
    cfg = RunConfig(
        workdir=str(tmp_path / "w"), out_dir=str(tmp_path / "o"),
        params=LTParams(max_segments=4, vertex_count_overshoot=2),
        tile_size=48, feed_cache_mb=0, ingest_store_mb=64, telemetry=True,
    )
    summary = run_stack(stack, cfg)
    assert check_events_schema.main([cfg.workdir]) == 0

    report, _spans = obs_report.fold([summary["telemetry"]["events"]])
    st = report["ingest_store"]
    assert st["put_blocks"] == summary["ingest_store"]["put_blocks"] > 0
    assert st["hit_rate"] is not None

    prom = open(summary["telemetry"]["metrics"]).read()
    for name in ("lt_ingest_store_hits_total", "lt_ingest_store_put_bytes_total",
                 "lt_ingest_store_bytes"):
        assert name in prom


def test_store_corrupt_seam_recovers(tmp_path):
    """The store.corrupt fault seam: a poisoned store-served block is
    invalidated in both tiers and re-decoded — reads stay correct and
    the drop is counted."""
    from land_trendr_tpu.runtime import faults

    p, arr = _scene(str(tmp_path), "s", "deflate", True, 256)
    store = BlockStore(str(tmp_path / "store"), budget_bytes=64 << 20)
    blockcache.configure(0, 1, store=store)
    read_geotiff_window(p, 0, 0, 400, 400)
    store.flush()

    plan = faults.activate(faults.parse_schedule("seed=1,store.corrupt@1"))
    try:
        got = read_geotiff_window(p, 0, 0, 400, 400)
    finally:
        faults.deactivate()
    assert np.array_equal(got, arr)
    assert [s for s, _i, _k in plan.injected()] == ["store.corrupt"]
    assert store.stats_snapshot()["corrupt_dropped"] >= 1
    store.close()
