"""Span model + pod tracing tests: cross-host clock alignment, trace
assembly, critical-path attribution, and the live straggler detector.

Pins the tracing contract end to end — the :class:`StragglerDetector`
rules (rolling-median window, k threshold, no false positive before
``min_tiles``, flag-once), the ``span``/``tile_straggler`` schema and
value lints, the pod-trace assembler over the committed two-host
skewed-clock fixtures (monotone, offset-corrected, byte-stable across
folds), ``tools/lt_trace.py``, ``tools/obs_report.py``'s per-host
rollups, and a real CPU-backend driver run where an injected ``slow``
fault produces a ``tile_straggler`` in the stream.
"""

import json
import os

import pytest

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
from land_trendr_tpu.obs import EventLog, validate_event
from land_trendr_tpu.obs.spans import (
    StragglerDetector,
    assemble_pod_trace,
    busy_union_s,
    critical_path,
    tail_ratio,
)
from land_trendr_tpu.runtime import RunConfig, run_stack, stack_from_synthetic
from tools import check_events_schema, lt_top, lt_trace, obs_report

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
POD_FIXTURE = [
    os.path.join(FIXTURES, "podtrace_skew.p0.events.jsonl"),
    os.path.join(FIXTURES, "podtrace_skew.p1.events.jsonl"),
]

#: the wall skew baked into the committed p1 fixture (host-b's clock
#: reads this many seconds ahead of host-a's at run_start)
FIXTURE_SKEW_S = 1800.5


# ---------------------------------------------------------------------------
# StragglerDetector
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def make_detector(**kw):
    clock = FakeClock()
    verdicts = []

    def on_straggler(tile_id, dur, thr, med, in_flight, attempt):
        verdicts.append(
            {"tile": tile_id, "dur": dur, "thr": thr, "med": med,
             "in_flight": in_flight, "attempt": attempt}
        )

    kw.setdefault("k", 2.0)
    kw.setdefault("min_tiles", 3)
    det = StragglerDetector(on_straggler=on_straggler, clock=clock, **kw)
    return det, clock, verdicts


def run_tile(det, clock, tile_id, duration):
    det.start(tile_id)
    clock.t += duration
    return det.finish(tile_id)


def test_no_false_positive_before_min_tiles():
    """The first tiles — including a slow compile-carrying tile 0 —
    must never flag: there is no median to judge against yet."""
    det, clock, verdicts = make_detector(min_tiles=3)
    run_tile(det, clock, 0, 30.0)  # the compile tile: huge, NOT a straggler
    run_tile(det, clock, 1, 1.0)
    run_tile(det, clock, 2, 1.0)
    assert verdicts == []
    assert det.stats()["stragglers"] == 0


def test_completion_flagging_k_threshold():
    det, clock, verdicts = make_detector(k=2.0, min_tiles=3)
    for i, d in enumerate((1.0, 1.0, 1.0)):
        run_tile(det, clock, i, d)
    # at threshold (2 x median 1.0 = 2.0): NOT over — strict inequality
    run_tile(det, clock, 3, 2.0)
    assert verdicts == []
    run_tile(det, clock, 4, 2.5)
    assert [v["tile"] for v in verdicts] == [4]
    v = verdicts[0]
    assert v["dur"] == pytest.approx(2.5)
    assert v["thr"] == pytest.approx(2.0)
    assert v["med"] == pytest.approx(1.0)
    assert v["in_flight"] is False
    assert det.stats()["stragglers"] == 1


def test_rolling_window_median():
    """The median is over the last ``window`` completions only — a run
    whose tiles slow down re-baselines instead of flagging forever."""
    det, clock, verdicts = make_detector(k=2.0, min_tiles=2, window=4)
    for i in range(4):
        run_tile(det, clock, i, 1.0)
    # four slow-but-steady tiles push the old fast baseline out...
    for i in range(4, 8):
        run_tile(det, clock, i, 1.9)  # under 2x the evolving median
    assert verdicts == []
    assert det.stats()["median_s"] == pytest.approx(1.9)
    # ...so 3.0s is now under the refreshed 3.8s threshold
    run_tile(det, clock, 8, 3.0)
    assert verdicts == []


def test_scan_flags_in_flight_once():
    det, clock, verdicts = make_detector(k=2.0, min_tiles=2)
    for i in range(3):
        run_tile(det, clock, i, 1.0)
    det.start(99)
    clock.t += 5.0
    assert det.scan() == [99]
    assert verdicts[-1]["in_flight"] is True
    # already flagged: neither a re-scan nor the completion re-fires
    assert det.scan() == []
    det.finish(99)
    assert [v["tile"] for v in verdicts] == [99]
    assert det.stats()["stragglers"] == 1


def test_drop_and_retry_restart():
    det, clock, verdicts = make_detector(k=2.0, min_tiles=2)
    for i in range(3):
        run_tile(det, clock, i, 1.0)
    # quarantine path: a dropped tile gets no verdict however long it ran
    det.start(50)
    clock.t += 10.0
    det.drop(50)
    assert det.scan() == []
    # retry path: re-start resets the in-flight clock
    det.start(51, attempt=1)
    clock.t += 10.0
    det.start(51, attempt=2)
    clock.t += 0.5
    det.finish(51)
    assert verdicts == []


def test_failed_callback_unflags_for_retry():
    """A verdict whose callback raised never landed anywhere (the sampler
    swallows probe errors) — the tile must stay eligible so a later scan
    retries instead of losing its only verdict forever."""
    calls = []

    def flaky(tile_id, *rest):
        calls.append(tile_id)
        if len(calls) == 1:
            raise OSError("telemetry emit failed")

    clock = FakeClock()
    det = StragglerDetector(k=2.0, min_tiles=2, on_straggler=flaky,
                            clock=clock)
    for tid in (0, 1):
        det.start(tid)
        clock.t += 1.0
        det.finish(tid)
    det.start(9)
    clock.t += 10.0
    with pytest.raises(OSError):
        det.scan()
    assert det.stats()["stragglers"] == 0  # un-flagged: verdict not lost
    assert det.scan() == [9]  # the retry lands
    assert calls == [9, 9]
    assert det.scan() == []  # then flags-once as usual


def test_detector_validation():
    with pytest.raises(ValueError, match="k=0.5"):
        StragglerDetector(k=0.5)
    with pytest.raises(ValueError, match="min_tiles=0"):
        StragglerDetector(min_tiles=0)


# ---------------------------------------------------------------------------
# schema + value lints
# ---------------------------------------------------------------------------


def test_span_and_straggler_events_validate():
    span = {"ev": "span", "t_wall": 1.0, "t_mono": 2.0, "name": "feed",
            "tile_id": 3, "start": 1.5, "end": 2.0}
    assert validate_event(span) == []
    assert validate_event({**span, "attempt": 2}) == []
    assert validate_event({k: v for k, v in span.items() if k != "end"})
    strag = {"ev": "tile_straggler", "t_wall": 1.0, "t_mono": 2.0,
             "tile_id": 3, "duration_s": 5.0, "threshold_s": 2.0,
             "median_s": 1.0, "in_flight": True}
    assert validate_event(strag) == []
    assert validate_event({**strag, "in_flight": "yes"})  # type error


def test_span_value_lint_end_before_start():
    errs = check_events_schema.span_value_errors(
        {"ev": "span", "name": "feed", "tile_id": 1,
         "start": 5.0, "end": 4.0}, 7)
    assert errs and "end 4.0 precedes start 5.0" in errs[0]
    assert check_events_schema.span_value_errors(
        {"ev": "span", "name": "feed", "tile_id": 1,
         "start": 4.0, "end": 4.0}, 7) == []


def test_straggler_value_lint_duration_vs_threshold():
    bad = {"ev": "tile_straggler", "tile_id": 1, "duration_s": 1.0,
           "threshold_s": 2.0, "median_s": 1.0}
    errs = check_events_schema.tile_straggler_value_errors(bad, 3)
    assert errs and "below threshold_s" in errs[0]
    ok = {**bad, "duration_s": 2.5}
    assert check_events_schema.tile_straggler_value_errors(ok, 3) == []
    inverted = {**ok, "threshold_s": 0.5}
    errs = check_events_schema.tile_straggler_value_errors(inverted, 3)
    assert errs and "below median_s" in errs[0]


def test_run_start_stamps_anchor_pair(tmp_path):
    log = EventLog(str(tmp_path / "events.jsonl"))
    rec = log.run_start(
        fingerprint="f", process_index=0, process_count=1, tiles_total=1,
        tiles_todo=1, tiles_skipped_resume=0, mesh_devices=1, impl="xla",
    )
    log.close()
    assert validate_event(rec) == []
    assert isinstance(rec["run_id"], str) and rec["run_id"]
    # the anchor pair is sampled back to back with the emit's own stamps
    assert abs(rec["anchor_wall"] - rec["t_wall"]) < 1.0
    assert abs(rec["anchor_mono"] - rec["t_mono"]) < 1.0


# ---------------------------------------------------------------------------
# helpers: busy union, tail ratio, critical path
# ---------------------------------------------------------------------------


def test_busy_union_merges_overlaps():
    assert busy_union_s([]) == 0.0
    assert busy_union_s([(0, 1), (0.5, 2), (3, 4)]) == pytest.approx(3.0)


def test_tail_ratio():
    assert tail_ratio([1.0]) is None
    assert tail_ratio([1.0] * 19 + [5.0]) == pytest.approx(5.0)


def test_critical_path_two_sided_bound():
    cp = critical_path({"compute": 8.0, "feed": 3.0, "write": 1.0}, 10.0)
    assert cp["bound_stage"] == "compute"
    # removing compute: serial view saves 8 -> wall 2, but feed's 3s
    # still bounds the pipeline
    assert cp["if_free"]["compute"]["est_wall_s"] == pytest.approx(3.0)
    assert cp["if_free"]["compute"]["faster_pct"] == pytest.approx(70.0)
    # removing feed saves at most its own 3s
    assert cp["if_free"]["feed"]["est_wall_s"] == pytest.approx(8.0)
    # attempt spans overlap the others and must not enter the path
    assert "attempt" not in critical_path(
        {"compute": 8.0, "attempt": 9.0}, 10.0
    )["if_free"]


# ---------------------------------------------------------------------------
# pod-trace assembly over the committed two-host skewed fixtures
# ---------------------------------------------------------------------------


def test_fixture_assembles_offset_corrected():
    trace = assemble_pod_trace(POD_FIXTURE)
    assert trace["files"] == 2 and trace["malformed"] == 0
    h0, h1 = trace["hosts"]
    assert (h0["host"], h1["host"]) == ("host-a", "host-b")
    # the alignment reports the skew it removed, and removes it: both
    # hosts' activity overlaps on the pod timeline despite the half-hour
    # wall-clock disagreement baked into the fixture
    assert h0["wall_skew_s"] == pytest.approx(0.0)
    assert h1["wall_skew_s"] == pytest.approx(FIXTURE_SKEW_S)
    span_range = {}
    for fileno in (0, 1):
        ts = [s["t0"] for s in trace["spans"] if s["file"] == fileno]
        span_range[fileno] = (min(ts), max(ts))
    assert span_range[0][0] < span_range[1][1]
    assert span_range[1][0] < span_range[0][1]
    # monotone: causally ordered output
    t0s = [s["t0"] for s in trace["spans"]]
    assert t0s == sorted(t0s)
    assert all(s["dur"] >= 0 for s in trace["spans"])
    # correlation IDs ride every span: one pod run = ONE run_id (agreed
    # through the shared manifest header), hosts distinguished by
    # host/process_index
    assert {s["run_id"] for s in trace["spans"]} == {"fixturerun000"}
    assert {s["host"] for s in trace["spans"]} == {"host-a", "host-b"}


def test_fixture_assembly_byte_stable():
    a = json.dumps(assemble_pod_trace(POD_FIXTURE), sort_keys=True)
    b = json.dumps(assemble_pod_trace(POD_FIXTURE), sort_keys=True)
    assert a == b


def test_fixture_critical_path_and_imbalance():
    trace = assemble_pod_trace(POD_FIXTURE)
    pod = trace["pod"]
    # host-b (wall 6.2) lags host-a (4.4): the pod ends with host-b
    assert pod["wall_s"] == pytest.approx(6.2)
    assert pod["host_imbalance"] == pytest.approx(6.2 / 5.3, rel=1e-3)
    cp = pod["critical_path"]
    assert cp["bound_stage"] == "compute"
    # compute-free still pays the slower host's next-binding stage
    assert 0 < cp["if_free"]["compute"]["est_wall_s"] < 6.2
    assert cp["if_free"]["compute"]["faster_pct"] > 50
    # the fixture's straggler lands in markers and the host rollup
    assert [m["tile"] for m in trace["markers"]] == [5]
    assert h_by_name(trace, "host-b")["stragglers"] == 1
    assert h_by_name(trace, "host-a")["stragglers"] == 0
    assert h_by_name(trace, "host-b")["tail_ratio"] == pytest.approx(2.5)


def h_by_name(trace, name):
    return next(h for h in trace["hosts"] if h["host"] == name)


def test_lt_trace_cli(tmp_path, capsys):
    out = str(tmp_path / "pod_trace.json")
    assert lt_trace.main([*POD_FIXTURE, "--trace", out]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["files"] == 2
    assert report["pod"]["critical_path"]["bound_stage"] == "compute"
    assert report["trace"]["events"] > 0
    chrome = json.load(open(out))
    evs = chrome["traceEvents"]
    # one trace process per host, stage names as threads, ts rebased >= 0
    assert {e["args"]["name"] for e in evs if e.get("name") == "process_name"} \
        == {"proc 0 @ host-a", "proc 1 @ host-b"}
    xs = [e for e in evs if e["ph"] == "X"]
    assert xs and all(e["ts"] >= 0 for e in xs)
    assert any(e["name"].startswith("STRAGGLER") for e in evs if e["ph"] == "i")


def test_lt_trace_cli_missing_path(tmp_path, capsys):
    assert lt_trace.main([str(tmp_path / "nope")]) == 2


def test_obs_report_per_host_section():
    report, _spans = obs_report.fold(POD_FIXTURE)
    assert report["stragglers"] == 1
    ph = report["per_host"]
    assert [p["host"] for p in ph] == ["host-a", "host-b"]
    assert [p["stragglers"] for p in ph] == [0, 1]
    # per-host stage shares alongside the run-level rollup: the pod-sum
    # stage_s hid which host a stage bound — these must be per host
    for p in ph:
        assert p["stage_s"] and abs(sum(p["stage_share"].values()) - 1.0) < 0.01
        assert p["idle_gap_s"] >= 0
        assert p["span_s"]["feed"] == pytest.approx(0.6, abs=0.01)
    assert ph[1]["tail_ratio"] == pytest.approx(2.5)
    assert report["event_counts"]["span"] == 18
    assert report["event_counts"]["tile_straggler"] == 1


def test_lt_top_renders_straggler_column():
    snap = {
        "healthz": {"uptime_s": 5.0, "queue_depth": 0, "running": "j1",
                    "jobs_terminal": 0, "jobs_total": 1,
                    "warm_program_count": 1},
        "metrics": [],
        "jobs": [{
            "job_id": "j1", "state": "running", "tenant": "t", "priority": 0,
            "submitted_t": 0.0,
            "progress": {"phase": "pipeline", "tiles_done": 3,
                         "tiles_total": 6, "retries": 0, "stragglers": 2,
                         "feed_backlog": 1, "write_backlog": 0,
                         "fetch_backlog": 0, "upload_backlog": 0},
        }],
    }
    view = lt_top.render(snap)
    assert "STRAG" in view
    row = [ln for ln in view.splitlines() if ln.startswith("j1")][0]
    assert " 2 " in row  # the straggler count renders in the job row


# ---------------------------------------------------------------------------
# driver integration: injected slow fault -> tile_straggler in the stream
# ---------------------------------------------------------------------------


def test_driver_slow_fault_emits_straggler(tmp_path):
    stack = stack_from_synthetic(make_stack(
        SceneSpec(width=48, height=40, year_start=1990, year_end=2013, seed=11)
    ))
    cfg = RunConfig(
        workdir=str(tmp_path / "w"), out_dir=str(tmp_path / "o"),
        params=LTParams(max_segments=4, vertex_count_overshoot=2),
        tile_size=20, telemetry=True,
        fault_schedule="seed=1,compute.wait@4=slow:0.8",
        straggler_k=3.0, straggler_min_tiles=2,
    )
    summary = run_stack(stack, cfg)
    assert summary["stragglers"] >= 1
    ev_file = summary["telemetry"]["events"]
    # stream is schema-valid INCLUDING the new value lints
    assert check_events_schema.main([ev_file]) == 0
    recs = [json.loads(ln) for ln in open(ev_file)]
    stragglers = [r for r in recs if r["ev"] == "tile_straggler"]
    # the slow-faulted tile (compute.wait invocation 4 = tile 4) flagged
    assert 4 in {r["tile_id"] for r in stragglers}
    for r in stragglers:
        assert r["duration_s"] >= r["threshold_s"] >= r["median_s"]
    # explicit spans rode the stream with correlation ids intact
    spans = [r for r in recs if r["ev"] == "span"]
    assert {"feed", "upload"} <= {r["name"] for r in spans}
    assert all(r["end"] >= r["start"] for r in spans)
    # straggler events precede the scope's terminal run_done
    assert recs[-1]["ev"] == "run_done"
    # the whole workdir assembles into a one-host pod trace
    trace = assemble_pod_trace([ev_file])
    assert trace["hosts"][0]["stragglers"] == len(stragglers)
    assert trace["pod"]["critical_path"] is not None
    # the clock anchor is mirrored into the shared manifest
    from land_trendr_tpu.runtime.manifest import TileManifest

    anchors = [
        r for r in TileManifest(cfg.workdir, "x").iter_records()
        if r.get("kind") == "clock_anchor"
    ]
    assert len(anchors) == 1
    rs = next(r for r in recs if r["ev"] == "run_start")
    assert anchors[0]["run_id"] == rs["run_id"]
    assert anchors[0]["anchor_wall"] == pytest.approx(rs["anchor_wall"])
    assert anchors[0]["anchor_mono"] == pytest.approx(rs["anchor_mono"])
    # run_id is the POD-WIDE id the manifest header carries — the stream
    # stamped the manifest's id, not a private per-process one
    hdr = next(
        r for r in TileManifest(cfg.workdir, "x").iter_records()
        if r.get("kind") == "header"
    )
    assert rs["run_id"] == hdr["run_id"]


def test_manifest_header_agrees_run_id_across_processes(tmp_path):
    """The pod-wide run_id channel: one process wins the exclusive header
    create and stamps the id; every other process of the pod (and every
    resume) reads the SAME id back — no collective involved."""
    from land_trendr_tpu.runtime.manifest import TileManifest

    wd = str(tmp_path / "w")
    primary = TileManifest(wd, "samefp")
    primary.open(resume=True)
    assert isinstance(primary.run_id, str) and primary.run_id
    peer = TileManifest(wd, "samefp")
    peer.open(resume=True)
    assert peer.run_id == primary.run_id
    # resume=False rewrites the header: a NEW logical run, new id
    fresh = TileManifest(wd, "samefp")
    fresh.open(resume=False)
    assert fresh.run_id != primary.run_id


def test_run_start_rejects_half_anchor_pair(tmp_path):
    """The (anchor_wall, anchor_mono) pair is atomic: half a pair would
    silently pair two clock reads taken at different instants, shifting
    every assembled span by the gap."""
    log_ = EventLog(str(tmp_path / "events.jsonl"))
    try:
        with pytest.raises(ValueError, match="anchor_wall and anchor_mono"):
            log_.run_start(schema=1, fingerprint="x", anchor_wall=1.0)
        with pytest.raises(ValueError, match="anchor_wall and anchor_mono"):
            log_.run_start(schema=1, fingerprint="x", anchor_mono=2.0)
        rec = log_.run_start(
            schema=1, fingerprint="x", anchor_wall=1.0, anchor_mono=2.0
        )
        assert (rec["anchor_wall"], rec["anchor_mono"]) == (1.0, 2.0)
    finally:
        log_.close()


def test_runconfig_straggler_validation(tmp_path):
    with pytest.raises(ValueError, match="straggler_k"):
        RunConfig(workdir=str(tmp_path), straggler_k=0.5)
    with pytest.raises(ValueError, match="straggler_min_tiles"):
        RunConfig(workdir=str(tmp_path), straggler_min_tiles=0)
