"""Vertex-for-vertex parity: JAX kernel vs CPU oracle (the north-star
correctness metric, BASELINE.json).

Runs the kernel in float64 on CPU (exact-parity mode, SURVEY.md §7 step 2)
over the synthetic-series matrix and a randomized fuzz sweep, asserting
*exact* vertex placement and tight-tolerance floats.
"""

import numpy as np
import pytest

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.models import oracle
from land_trendr_tpu.ops.segment import jax_segment_pixels

YEARS = np.arange(1984, 2022, dtype=np.float64)
NY = len(YEARS)
ALL = np.ones(NY, dtype=bool)


def run_both(values, mask=None, params=LTParams()):
    mask = ALL if mask is None else mask
    ref = oracle.segment_series(YEARS, values, mask, params)
    out = jax_segment_pixels(
        YEARS, values[None, :].astype(np.float64), mask[None, :], params
    )
    return ref, jax_tree_to_np_row(out)


def jax_tree_to_np_row(out):
    return {k: np.asarray(v)[0] for k, v in out._asdict().items()}


def assert_parity(ref, got, atol=1e-8, ctx=""):
    assert got["model_valid"] == ref.model_valid, f"{ctx} model_valid"
    assert got["n_vertices"] == ref.n_vertices, f"{ctx} n_vertices"
    np.testing.assert_array_equal(
        got["vertex_indices"], ref.vertex_indices, err_msg=f"{ctx} vertex_indices"
    )
    for field in (
        "vertex_years",
        "vertex_src_vals",
        "vertex_fit_vals",
        "seg_magnitude",
        "seg_duration",
        "seg_rate",
        "fitted",
        "despiked",
    ):
        np.testing.assert_allclose(
            got[field], getattr(ref, field), atol=atol, rtol=1e-7,
            err_msg=f"{ctx} {field}",
        )
    np.testing.assert_allclose(got["rmse"], ref.rmse, atol=atol, err_msg=f"{ctx} rmse")
    np.testing.assert_allclose(
        got["p_of_f"], ref.p_of_f, atol=1e-9, err_msg=f"{ctx} p_of_f"
    )


# ---------------------------------------------------------------------------
# structured synthetic matrix (SURVEY.md §7 step 2)
# ---------------------------------------------------------------------------


def _noisy(y, seed, sd=0.01):
    return y + np.random.default_rng(seed).normal(0.0, sd, NY)


CASES = {
    "flat": np.full(NY, 0.3),
    "flat_noisy": _noisy(np.full(NY, 0.3), 1),
    "step": _noisy(np.where(YEARS < 2000, 0.1, 0.8), 2),
    "ramp": _noisy(0.02 * (YEARS - 1984), 3),
    "disturbance_recovery": _noisy(
        np.where(YEARS < 1996, 0.15, np.maximum(0.85 - 0.03 * (YEARS - 1996), 0.15)), 4
    ),
    "spike": _noisy(np.where(YEARS == 2000, 0.9, 0.2), 5),
    "double_disturbance": _noisy(
        np.where(YEARS < 1992, 0.1, np.where(YEARS < 2008, 0.5, 0.9)), 6
    ),
    "noise_only": np.random.default_rng(7).normal(0.0, 1.0, NY),
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_case_parity(name):
    ref, got = run_both(CASES[name])
    assert_parity(ref, got, ctx=name)


def test_masked_parity():
    mask = ALL.copy()
    mask[3:25:4] = False
    ref, got = run_both(CASES["step"], mask)
    assert_parity(ref, got, ctx="masked step")


def test_leading_trailing_masked():
    mask = ALL.copy()
    mask[:4] = False
    mask[-5:] = False
    ref, got = run_both(CASES["disturbance_recovery"], mask)
    assert_parity(ref, got, ctx="trimmed")


def test_below_min_obs_parity():
    mask = np.zeros(NY, dtype=bool)
    mask[:5] = True
    ref, got = run_both(CASES["ramp"], mask)
    assert_parity(ref, got, ctx="below min obs")


def test_all_masked_parity():
    ref, got = run_both(CASES["ramp"], np.zeros(NY, dtype=bool))
    assert_parity(ref, got, ctx="all masked")


@pytest.mark.parametrize(
    "params",
    [
        LTParams(max_segments=4),
        LTParams(spike_threshold=0.5),
        LTParams(vertex_count_overshoot=0),
        LTParams(recovery_threshold=10.0),
        LTParams(prevent_one_year_recovery=False),
        LTParams(p_val_threshold=1.0, best_model_proportion=1.0),
    ],
)
def test_param_sweep_parity(params):
    ref, got = run_both(CASES["disturbance_recovery"], params=params)
    assert_parity(ref, got, ctx=str(params))


# ---------------------------------------------------------------------------
# randomized fuzz
# ---------------------------------------------------------------------------


def test_fuzz_parity(rng):
    n_total = 120
    for trial in range(n_total):
        kind = trial % 4
        if kind == 0:  # random walk
            y = np.cumsum(rng.normal(0, 0.1, NY))
        elif kind == 1:  # step + noise
            yr = rng.integers(1988, 2018)
            y = np.where(YEARS < yr, 0.0, rng.uniform(0.3, 1.0)) + rng.normal(
                0, 0.05, NY
            )
        elif kind == 2:  # disturbance + recovery + spikes
            yr = rng.integers(1988, 2012)
            y = np.where(
                YEARS < yr, 0.2, np.maximum(0.9 - 0.04 * (YEARS - yr), 0.2)
            ) + rng.normal(0, 0.03, NY)
            y[rng.integers(0, NY)] += rng.uniform(0.3, 1.0)
        else:  # smooth trend
            y = 0.01 * (YEARS - 2000) + 0.3 * np.sin((YEARS - 1984) / 6.0)
            y = y + rng.normal(0, 0.02, NY)
        mask = rng.random(NY) > rng.uniform(0.0, 0.35)
        ref, got = run_both(y, mask)
        assert_parity(ref, got, ctx=f"fuzz {trial}")


def test_batch_matches_per_pixel(rng):
    ys = np.stack([CASES[k] for k in sorted(CASES)])
    masks = np.ones_like(ys, dtype=bool)
    out = jax_segment_pixels(YEARS, ys, masks, LTParams())
    for i, k in enumerate(sorted(CASES)):
        ref = oracle.segment_series(YEARS, ys[i], masks[i], LTParams())
        got = {kk: np.asarray(v)[i] for kk, v in out._asdict().items()}
        assert_parity(ref, got, ctx=f"batch {k}")


def test_chunked_matches_unchunked(rng):
    """lax.map chunking is pure scheduling: per-pixel decisions are identical.

    Discrete fields (vertex placement, counts, validity) must match exactly;
    float fields may differ only by compilation-order rounding (lax.map
    re-fuses reductions), so they are compared at ~last-ulp tolerance.
    """
    from land_trendr_tpu.ops.segment import (
        jax_segment_pixels,
        jax_segment_pixels_chunked,
    )

    ny, px = 18, 24
    years = np.arange(2000, 2000 + ny, dtype=np.int32)
    t = np.arange(ny)
    d = rng.integers(4, ny - 4, size=(px, 1))
    vals = -(0.6 - np.where(t[None, :] >= d, 0.25, 0.0)
             + rng.normal(0, 0.01, (px, ny)))
    mask = rng.uniform(size=(px, ny)) > 0.1
    params = LTParams(max_segments=3, vertex_count_overshoot=2)
    ref = jax_segment_pixels(years, vals, mask, params)
    chunked = jax_segment_pixels_chunked(years, vals, mask, params, chunk=8)
    exact = {"n_vertices", "vertex_indices", "model_valid"}
    for name, a, b in zip(ref._fields, ref, chunked):
        a, b = np.asarray(a), np.asarray(b)
        if name in exact:
            np.testing.assert_array_equal(a, b, err_msg=name)
        else:
            np.testing.assert_allclose(
                a, b, rtol=1e-12, atol=1e-14, err_msg=name
            )


def test_chunked_rejects_indivisible(rng):
    from land_trendr_tpu.ops.segment import jax_segment_pixels_chunked

    years = np.arange(2000, 2018, dtype=np.int32)
    vals = rng.normal(size=(10, 18))
    mask = np.ones((10, 18), bool)
    with pytest.raises(ValueError, match="not a multiple"):
        jax_segment_pixels_chunked(years, vals, mask, LTParams(), chunk=4)
