"""Segmentation-as-a-service tests (ISSUE 7).

Pins the serve subsystem's contracts:

* two jobs submitted concurrently produce artifacts **byte-identical**
  to two sequential CLI runs of the same request (server mode is a pure
  execution strategy, never a numerics change), with the second job
  admitted **warm** (``program_cache.misses == 0`` — zero jit compiles);
* admission control: queue-depth and per-tenant 429-style rejections,
  with ``job_rejected`` telemetry;
* cancel mid-job leaves a **resumable** manifest (recorded tiles stay
  durable; a plain resume completes to the clean digests), and a job
  timeout reports the ``stalled`` state;
* the new ``job_*`` / ``program_cache`` events schema-lint clean in the
  server scope, the job scopes (with ``job_id`` threaded onto every
  event), and the committed fixture stream;
* priority scheduling drains higher-priority jobs first;
* config/request validation fails fast (loopback-only API included).

Scene shapes are shared across tests so the process-wide jit cache makes
every server after the first warm — the suite exercises exactly the
residency the subsystem exists to provide.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from land_trendr_tpu.cli import main as cli_main
from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack
from land_trendr_tpu.serve import (
    EXIT_CODE_FOR_STATE,
    JobRequest,
    Rejection,
    SegmentationServer,
    ServeConfig,
    TERMINAL_STATES,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

#: one scene shape for the whole module: identical program-cache keys
#: across tests keep every server after the first warm
_PARAM_FLAGS = ["--max-segments", "4", "--vertex-count-overshoot", "2"]
_PARAMS = {"max_segments": 4, "vertex_count_overshoot": 2}
_TILE = 20


@pytest.fixture(scope="module")
def stack_dir(tmp_path_factory) -> str:
    d = str(tmp_path_factory.mktemp("serve_stack") / "stack")
    write_stack(
        d,
        make_stack(
            SceneSpec(width=40, height=40, year_start=2000, year_end=2008,
                      seed=3)
        ),
    )
    return d


def _digest_workdir(workdir: str) -> dict:
    out: dict = {}
    for p in sorted(Path(workdir).glob("tile_*.npz")):
        with np.load(p) as z:
            out[p.name] = {
                name: hashlib.sha256(
                    np.ascontiguousarray(z[name]).tobytes()
                ).hexdigest()
                for name in sorted(z.files)
            }
    return out


def _job(stack_dir: str, **kw) -> dict:
    return {
        "stack_dir": stack_dir,
        "tile_size": _TILE,
        "params": dict(_PARAMS),
        **kw,
    }


def _post(port: int, path: str, payload) -> tuple[int, dict]:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(port: int, path: str) -> tuple[int, dict]:
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# ---------------------------------------------------------------------------
# config / request validation


def test_serve_config_validation(tmp_path):
    with pytest.raises(ValueError, match="loopback"):
        ServeConfig(serve_host="0.0.0.0")
    with pytest.raises(ValueError, match="serve_queue_depth"):
        ServeConfig(serve_queue_depth=0)
    with pytest.raises(ValueError, match="job_timeout_s"):
        ServeConfig(job_timeout_s=0)
    with pytest.raises(ValueError, match="ingest_store_dir"):
        ServeConfig(ingest_store_dir=str(tmp_path))
    with pytest.raises(ValueError):  # typo'd seam = config error NOW
        ServeConfig(fault_schedule="serve.submitt@0")
    with pytest.raises(ValueError, match="metrics_port"):
        ServeConfig(telemetry=False, metrics_port=0)
    # the CLI maps the same failures to the documented exit 2
    assert cli_main(["serve", "--serve-host", "0.0.0.0",
                     "--workdir", str(tmp_path / "srv")]) == 2


def test_job_request_validation():
    with pytest.raises(ValueError, match="stack_dir"):
        JobRequest.from_payload({})
    with pytest.raises(ValueError, match="unknown job request field"):
        JobRequest.from_payload({"stack_dir": "s", "nope": 1})
    with pytest.raises(ValueError, match="server-owned"):
        JobRequest.from_payload(
            {"stack_dir": "s", "run_overrides": {"telemetry": False}}
        )
    with pytest.raises(ValueError, match="priority"):
        JobRequest.from_payload({"stack_dir": "s", "priority": 1000})
    req = JobRequest.from_payload(
        {"stack_dir": "s", "ftv": "ndvi,tcw", "priority": 3}
    )
    assert req.ftv == ("ndvi", "tcw") and req.priority == 3
    # every terminal state maps onto the documented exit-code contract
    assert set(EXIT_CODE_FOR_STATE) == set(TERMINAL_STATES)


def test_program_cache_failed_probe_is_not_resident():
    """A miss whose warm probe FAILED compiled nothing: the key must not
    be registered, or the next run is falsely admitted warm while it
    actually compiles inline on tile 0."""
    from land_trendr_tpu.serve import ProgramCache

    pc = ProgramCache()
    key = pc.key_for(fingerprint="f", backend="cpu")
    assert not pc.admit(key)
    pc.record(key, hit=False, compile_s=1.0, ok=False)  # probe failed
    assert not pc.admit(key), "failed probe must not register the key"
    pc.record(key, hit=False, compile_s=2.0)  # later successful compile
    assert pc.admit(key)
    stats = pc.stats()
    assert stats == {
        "hits": 0, "misses": 2, "compile_s": 3.0, "keys": 1,
    }


# ---------------------------------------------------------------------------
# the headline contract: concurrent jobs ≡ sequential CLI runs, warm admission


def test_concurrent_jobs_match_cli_and_second_is_warm(stack_dir, tmp_path):
    srv_dir = str(tmp_path / "srv")
    server = SegmentationServer(
        ServeConfig(workdir=srv_dir, max_jobs=2, feed_cache_mb=32)
    )
    # both jobs queued over the API BEFORE the dispatcher starts — truly
    # concurrent submissions (different tenants dodge the in-flight cap)
    st1, j1 = _post(server.port, "/jobs", _job(stack_dir))
    st2, j2 = _post(server.port, "/jobs", _job(stack_dir, tenant="b"))
    assert st1 == st2 == 200
    server.serve_forever()  # drains both, then shuts down

    s1 = server.job_status(j1["job_id"])
    s2 = server.job_status(j2["job_id"])
    assert s1["state"] == s2["state"] == "done"
    assert s1["exit_code"] == 0
    # warm admission: the second job ran ZERO jit compiles
    assert s1["summary"]["program_cache"]["misses"] in (0, 1)
    assert s2["summary"]["program_cache"] == {
        "hits": 1, "misses": 0, "compile_s": 0.0,
    }

    # two sequential CLI runs of the same request are the reference
    cli = []
    for i in (1, 2):
        wd, od = str(tmp_path / f"cli{i}_w"), str(tmp_path / f"cli{i}_o")
        assert cli_main(["segment", stack_dir, "--tile-size", str(_TILE),
                         "--workdir", wd, "--out-dir", od,
                         *_PARAM_FLAGS]) == 0
        cli.append((wd, od))
    ref = _digest_workdir(cli[0][0])
    assert _digest_workdir(cli[1][0]) == ref
    assert _digest_workdir(s1["workdir"]) == ref
    assert _digest_workdir(s2["workdir"]) == ref
    # assembled rasters byte-identical too (server mode is pure strategy)
    for snap in (s1, s2):
        for name, path in snap["outputs"].items():
            want = Path(cli[0][1], Path(path).name).read_bytes()
            assert Path(path).read_bytes() == want, name

    # the new events schema-lint clean: server scope + both job scopes
    # (job_id threaded onto every job-scope event)
    from check_events_schema import main as lint_main

    assert lint_main([srv_dir]) == 0
    for snap in (s1, s2):
        assert lint_main([snap["workdir"]]) == 0
        evs = [
            json.loads(l)
            for l in open(os.path.join(snap["workdir"], "events.jsonl"))
        ]
        assert evs and all(e["job_id"] == snap["job_id"] for e in evs)
        assert [e for e in evs if e["ev"] == "program_cache"]
    server_evs = [
        json.loads(l) for l in open(os.path.join(srv_dir, "events.jsonl"))
    ]
    kinds = [e["ev"] for e in server_evs]
    assert kinds.count("job_submitted") == 2
    assert kinds.count("job_done") == 2
    assert kinds[-1] == "run_done" and "program_cache" in kinds

    # obs_report folds the serve scope into its rollup
    import obs_report

    report, _spans = obs_report.fold(
        [os.path.join(srv_dir, "events.jsonl")]
    )
    assert report["serve"]["submitted"] == 2
    assert report["serve"]["by_status"] == {"done": 2}
    assert report["program_cache"]["keys"] == 1


def test_fixture_stream_lints_clean():
    """The committed fixture (precommit's schema-drift guard) stays
    valid against the live schema."""
    from check_events_schema import main as lint_main

    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "serve.events.jsonl"
    )
    assert lint_main([fixture]) == 0


# ---------------------------------------------------------------------------
# admission control


def test_admission_rejections(stack_dir, tmp_path):
    srv_dir = str(tmp_path / "srv")
    server = SegmentationServer(
        ServeConfig(
            workdir=srv_dir,
            serve_queue_depth=2,
            tenant_max_inflight=1,
        )
    )
    try:
        st, _ = _post(server.port, "/jobs", _job(stack_dir, tenant="a"))
        assert st == 200
        # tenant cap: a's second submission is refused, b's proceeds
        st, body = _post(server.port, "/jobs", _job(stack_dir, tenant="a"))
        assert st == 429 and body["error"] == "tenant_cap"
        st, _ = _post(server.port, "/jobs", _job(stack_dir, tenant="b"))
        assert st == 200
        # queue full: depth 2 reached, tenant c is refused anyway
        st, body = _post(server.port, "/jobs", _job(stack_dir, tenant="c"))
        assert st == 429 and body["error"] == "queue_full"
        # malformed request: 400, not a server error
        st, body = _post(server.port, "/jobs", {"nope": 1})
        assert st == 400 and body["error"] == "bad_request"
        st, h = _get(server.port, "/healthz")
        assert st == 200 and h["queue_depth"] == 2
        # load-balancer-grade facts ride /healthz directly — no
        # Prometheus scrape/parse needed for an LB check
        assert h["ok"] is True
        assert h["running"] is None  # dispatcher not started yet
        assert h["jobs_total"] == 2
        assert isinstance(h["warm_program_count"], int)
        assert h["uptime_s"] >= 0
    finally:
        server.stop()
        server.serve_forever()  # immediate drain-free shutdown
    evs = [
        json.loads(l) for l in open(os.path.join(srv_dir, "events.jsonl"))
    ]
    rejected = [e for e in evs if e["ev"] == "job_rejected"]
    assert sorted(e["reason"] for e in rejected) == [
        "bad_request", "queue_full", "tenant_cap",
    ]


def test_direct_submit_rejection_raises(stack_dir, tmp_path):
    server = SegmentationServer(
        ServeConfig(workdir=str(tmp_path / "srv"), serve_queue_depth=1)
    )
    try:
        server.submit(_job(stack_dir))
        with pytest.raises(Rejection) as exc:
            server.submit(_job(stack_dir, tenant="b"))
        assert exc.value.reason == "queue_full"
        assert exc.value.http_status == 429
    finally:
        server.stop()
        server.serve_forever()


# ---------------------------------------------------------------------------
# cancel / timeout — the resumable-manifest contract


def test_cancel_mid_job_leaves_resumable_manifest(stack_dir, tmp_path):
    # pace the job with a deterministic slow fault so the cancel lands
    # mid-run: every dispatch sleeps 0.4s, and the warm probe plus four
    # tiles make the job take >2s
    server = SegmentationServer(
        ServeConfig(
            workdir=str(tmp_path / "srv"),
            max_jobs=1,
            fault_schedule="seed=1,dispatch%1.0=slow:0.4",
        )
    )
    snap = server.submit(_job(stack_dir))
    job_id = snap["job_id"]

    def cancel_after_first_tile():
        deadline = time.monotonic() + 30
        wd = Path(snap["workdir"])
        while time.monotonic() < deadline:
            if list(wd.glob("tile_*.npz")):
                break
            time.sleep(0.05)
        _post(server.port, f"/jobs/{job_id}/cancel", {})

    t = threading.Thread(target=cancel_after_first_tile)
    t.start()
    server.serve_forever()
    t.join(timeout=30)

    s = server.job_status(job_id)
    assert s["state"] == "cancelled"
    assert s["exit_code"] == EXIT_CODE_FOR_STATE["cancelled"] == 3
    done = _digest_workdir(s["workdir"])
    assert 1 <= len(done) < 4, "cancel must land mid-run"
    # the job's own stream records the aborted scope
    evs = [
        json.loads(l)
        for l in open(os.path.join(s["workdir"], "events.jsonl"))
    ]
    assert evs[-1]["ev"] == "run_done" and evs[-1]["status"] == "aborted"

    # a plain resume (the CLI path a resubmitted job also takes)
    # completes exactly the remaining tiles, byte-identical to clean
    assert cli_main(["segment", stack_dir, "--tile-size", str(_TILE),
                     "--workdir", s["workdir"],
                     "--out-dir", str(tmp_path / "resume_o"),
                     *_PARAM_FLAGS]) == 0
    resumed = _digest_workdir(s["workdir"])
    assert len(resumed) == 4
    clean_wd = str(tmp_path / "clean_w")
    assert cli_main(["segment", stack_dir, "--tile-size", str(_TILE),
                     "--workdir", clean_wd,
                     "--out-dir", str(tmp_path / "clean_o"),
                     *_PARAM_FLAGS]) == 0
    assert resumed == _digest_workdir(clean_wd)
    # the tiles recorded before the cancel were not recomputed
    assert all(resumed[k] == v for k, v in done.items())


def test_job_timeout_reports_stalled(stack_dir, tmp_path):
    server = SegmentationServer(
        ServeConfig(
            workdir=str(tmp_path / "srv"),
            max_jobs=1,
            job_timeout_s=0.6,
            fault_schedule="seed=1,dispatch%1.0=slow:0.4",
        )
    )
    snap = server.submit(_job(stack_dir))
    server.serve_forever()
    s = server.job_status(snap["job_id"])
    assert s["state"] == "stalled", s.get("error")
    assert s["exit_code"] == EXIT_CODE_FOR_STATE["stalled"] == 4
    assert "timeout" in s["error"]
    # a per-request override beats the server default (and 'timeout_s'
    # rides request validation)
    with pytest.raises(ValueError, match="timeout_s"):
        JobRequest.from_payload({"stack_dir": "s", "timeout_s": 0})


# ---------------------------------------------------------------------------
# scheduling


def test_priority_drains_before_fifo(stack_dir, tmp_path):
    srv_dir = str(tmp_path / "srv")
    server = SegmentationServer(
        ServeConfig(workdir=srv_dir, max_jobs=3, feed_cache_mb=32)
    )
    lo1 = server.submit(_job(stack_dir, tenant="a"))
    lo2 = server.submit(_job(stack_dir, tenant="b"))
    hi = server.submit(_job(stack_dir, tenant="c", priority=5))
    server.serve_forever()
    started = {
        s["job_id"]: s["started_t"]
        for s in (server.job_status(j["job_id"]) for j in (lo1, lo2, hi))
    }
    assert started[hi["job_id"]] < started[lo1["job_id"]]
    # FIFO within a priority
    assert started[lo1["job_id"]] < started[lo2["job_id"]]
