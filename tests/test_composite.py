"""Annual medoid compositing (ops/composite.py + C2 loader integration).

Unit tests pin the selection semantics (masked median, distance argmin,
first-index ties, fill on all-cloudy); the loader tests pin the
multi-acquisition C2 path end to end, including the default loud error.
"""

import os

import numpy as np
import pytest

from land_trendr_tpu.io.geotiff import GeoMeta, write_geotiff
from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack_c2
from land_trendr_tpu.ops.composite import medoid_composite, medoid_indices
from land_trendr_tpu.runtime import load_stack_dir, load_stack_dir_c2


def idx_of(vals, valid=None):
    """medoid_indices on a (nd, px=1, nb=1) column."""
    sr = np.asarray(vals, np.float32)[:, None, None]
    v = np.ones(sr.shape[:2], bool) if valid is None else np.asarray(valid)[:, None]
    c, ok = medoid_indices(sr, v)
    return int(np.asarray(c)[0]), bool(np.asarray(ok)[0])


def test_medoid_picks_median_observation():
    assert idx_of([0.0, 1.0, 10.0]) == (1, True)


def test_medoid_tie_breaks_to_first():
    # sorted [0,2,2] -> median 2; dates 1 and 2 both at distance 0
    assert idx_of([0.0, 2.0, 2.0]) == (1, True)


def test_medoid_excludes_invalid_dates():
    # date 1 invalid: median of {0,10} = 5, both remaining tie -> first valid
    assert idx_of([0.0, 1.0, 10.0], valid=[True, False, True]) == (0, True)


def test_medoid_all_invalid_flags_pixel():
    assert idx_of([1.0, 2.0, 3.0], valid=[False, False, False]) == (0, False)


def test_medoid_multiband_distance():
    # band sums decide: date0 = (0,0), date1 = (3,3), date2 = (4,4)
    # median = (3,3) -> date1 exact
    sr = np.asarray(
        [[[0.0, 0.0]], [[3.0, 3.0]], [[4.0, 4.0]]], np.float32
    )  # (3, 1, 2)
    c, ok = medoid_indices(sr, np.ones((3, 1), bool))
    assert int(np.asarray(c)[0]) == 1


def test_medoid_composite_copies_observation():
    """Composite values come verbatim from the chosen acquisition; QA is
    the chosen date's QA; all-cloudy pixels get the fill QA."""
    rng = np.random.default_rng(5)
    nd, h, w = 3, 4, 4
    base = rng.integers(7500, 9000, (h, w)).astype(np.uint16)
    dn = {
        "nir": np.stack([base, base, base + 500]),
        "swir2": np.stack([base + 1, base + 1, base + 700]),
    }
    qa = np.zeros((nd, h, w), np.uint16)  # all clear
    qa[0, 0, 0] = 1 << 3  # date0 cloudy at (0,0)
    qa[:, 1, 1] = 1 << 3  # all dates cloudy at (1,1)

    out_dn, out_qa = medoid_composite(dn, qa)
    # typical pixel: dates 0/1 identical and median -> first (date 0)
    assert out_dn["nir"][2, 2] == base[2, 2]
    assert out_dn["nir"].dtype == np.uint16
    # (0,0): date0 excluded; among {1,2} tie -> date1 -> still base
    assert out_dn["nir"][0, 0] == base[0, 0]
    assert out_qa[0, 0] == 0
    # (1,1): nothing valid -> fill QA, DN 0
    assert out_qa[1, 1] == 1 and out_dn["nir"][1, 1] == 0
    # chosen QA propagates (clear everywhere else)
    assert (out_qa[2:, :] == 0).all()


def test_medoid_excludes_saturated_qa_clear_dates():
    """A QA-clear but radiometrically saturated acquisition (reflectance
    outside [0,1] — sr_valid_mask's job in the segmentation feed) must not
    win the medoid over a usable acquisition."""
    nd, h, w = 2, 2, 2
    sat = np.full((h, w), 60000, np.uint16)       # 60000*2.75e-5-0.2 = 1.45
    good = np.full((h, w), 20000, np.uint16)      # 0.35 reflectance
    dn = {"nir": np.stack([sat, good]), "swir2": np.stack([sat, good])}
    qa = np.zeros((nd, h, w), np.uint16)          # both QA-clear
    out_dn, out_qa = medoid_composite(dn, qa)
    np.testing.assert_array_equal(out_dn["nir"], good)
    assert (out_qa == 0).all()


def test_c2_mixed_dtype_within_year_rejected(tmp_path):
    """One year with an int16 and a uint16 acquisition must error loudly,
    not silently promote the composite stack to int32."""
    d = str(tmp_path / "arc")
    os.makedirs(d)
    geo = GeoMeta(
        pixel_scale=(30.0, 30.0, 0.0),
        tiepoint=(0.0, 0.0, 0.0, 500000.0, 5000000.0, 0.0),
    )
    base = np.full((4, 4), 9000, np.int16)
    for date, dtype in (("20100610", np.int16), ("20100712", np.uint16)):
        stem = f"LT05_L2SP_045030_{date}_{date}_02_T1"
        for n in (4, 7):
            write_geotiff(
                os.path.join(d, f"{stem}_SR_B{n}.TIF"),
                base.astype(dtype), geo=geo,
            )
        write_geotiff(
            os.path.join(d, f"{stem}_QA_PIXEL.TIF"),
            np.zeros((4, 4), np.uint16), geo=geo,
        )
    with pytest.raises(ValueError, match="mixed DN dtypes across year"):
        load_stack_dir_c2(d, bands=("nir", "swir2"), composite="medoid")


def _write_multidate_archive(d, h=6, w=8):
    """Year 2010 with 3 acquisitions (2 identical + 1 outlier), year 2011
    with 1.  Returns the base DN grid for assertions."""
    os.makedirs(d, exist_ok=True)
    geo = GeoMeta(
        pixel_scale=(30.0, 30.0, 0.0),
        tiepoint=(0.0, 0.0, 0.0, 500000.0, 5000000.0, 0.0),
    )
    rng = np.random.default_rng(9)
    base = rng.integers(7500, 9000, (h, w)).astype(np.int16)
    nums = {"nir": 4, "swir2": 7}  # TM numbering (LT05)
    qa_clear = np.zeros((h, w), np.uint16)
    qa_cloud = np.full((h, w), 1 << 3, np.uint16)

    def write_acq(date, dn_delta, qa):
        stem = f"LT05_L2SP_045030_{date}_{date}_02_T1"
        for b, n in nums.items():
            write_geotiff(
                os.path.join(d, f"{stem}_SR_B{n}.TIF"),
                (base + dn_delta).astype(np.int16), geo=geo,
            )
        write_geotiff(os.path.join(d, f"{stem}_QA_PIXEL.TIF"), qa, geo=geo)

    write_acq("20100610", 0, qa_clear)
    write_acq("20100712", 0, qa_clear)
    write_acq("20100830", 500, qa_cloud)  # outlier AND cloudy everywhere
    write_acq("20110715", 7, qa_clear)
    return base


def test_c2_multidate_requires_composite(tmp_path):
    d = str(tmp_path / "arc")
    _write_multidate_archive(d)
    with pytest.raises(ValueError, match="composite"):
        load_stack_dir_c2(d, bands=("nir", "swir2"))


def test_c2_medoid_composite_end_to_end(tmp_path):
    d = str(tmp_path / "arc")
    base = _write_multidate_archive(d)
    s = load_stack_dir(d, bands=("nir", "swir2"), composite="medoid")
    np.testing.assert_array_equal(s.years, [2010, 2011])
    # 2010 composite = the identical clear acquisitions' values
    np.testing.assert_array_equal(s.dn_bands["nir"][0], base)
    assert (np.asarray(s.qa[0]) == 0).all()
    # 2011 passthrough (single acquisition)
    np.testing.assert_array_equal(s.dn_bands["nir"][1], base + 7)
    # composite rejected for the pre-stacked layout and for bad values
    with pytest.raises(ValueError, match="not None"):
        load_stack_dir_c2(d, composite="mean")


def test_composite_rejected_for_prestacked(tmp_path):
    from land_trendr_tpu.io.synthetic import write_stack

    scene = make_stack(SceneSpec(width=8, height=6, year_start=2010, year_end=2012))
    d = str(tmp_path / "stacked")
    write_stack(d, scene)
    with pytest.raises(ValueError, match="pre-stacked"):
        load_stack_dir(d, composite="medoid")
