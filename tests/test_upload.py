"""Upload-subsystem tests: packed ≡ per-array byte parity, async fault
retry + demotion, CLI knobs, telemetry/lint/rollup wiring, and the
upload_bench + perf_gate smokes (tier-1).

The contract under test (runtime/feed.py): ``upload_packed`` is a pure
execution strategy — packed and per-array runs must produce
byte-identical tile artifacts, with the packed path costing ONE
host→device transfer per tile instead of ``bands+1``.
"""

import json
import os
import sys

import numpy as np
import pytest

from land_trendr_tpu.cli import main as cli_main
from land_trendr_tpu.config import LTParams
from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
from land_trendr_tpu.runtime import (
    RunConfig,
    run_stack,
    stack_from_synthetic,
)
from land_trendr_tpu.runtime import feed as feedmod

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

SPEC = SceneSpec(width=48, height=40, year_start=1990, year_end=2005, seed=11)
PARAMS = LTParams(max_segments=4, vertex_count_overshoot=2)


@pytest.fixture(scope="module")
def rstack():
    return stack_from_synthetic(make_stack(SPEC))


def make_cfg(tmp, **kw):
    kw.setdefault("params", PARAMS)
    kw.setdefault("tile_size", 32)  # 48x40 scene -> edge tiles in both axes
    kw.setdefault("retry_backoff_s", 0.0)
    return RunConfig(
        workdir=os.path.join(tmp, "work"), out_dir=os.path.join(tmp, "out"),
        **kw,
    )


def load_artifacts(cfg, n_tiles):
    out = []
    for tid in range(n_tiles):
        with np.load(os.path.join(cfg.workdir, f"tile_{tid:05d}.npz")) as z:
            out.append({k: z[k] for k in z.files})
    return out


def test_packed_per_array_byte_parity(tmp_path, rstack):
    """The tentpole claim: packed upload is one transfer per tile (vs
    bands+1) and the artifacts are byte-identical to the per-array run."""
    cfg_p = make_cfg(str(tmp_path / "p"), upload_packed=True)
    cfg_u = make_cfg(str(tmp_path / "u"), upload_packed=False)
    sp = run_stack(rstack, cfg_p)
    su = run_stack(rstack, cfg_u)

    assert sp["upload"]["packed"] is True
    assert su["upload"]["packed"] is False
    assert sp["upload"]["transfers"] == sp["tiles"]
    # per-array: 2 NBR bands + QA = 3 transfers per tile
    assert su["upload"]["transfers"] == su["tiles"] * 3
    assert sp["upload"]["bytes"] == su["upload"]["bytes"] > 0
    assert sp["fit_rate"] == su["fit_rate"]

    for tid, (a, b) in enumerate(
        zip(load_artifacts(cfg_p, sp["tiles"]), load_artifacts(cfg_u, su["tiles"]))
    ):
        assert sorted(a) == sorted(b)
        for k in a:
            assert a[k].tobytes() == b[k].tobytes(), (
                f"tile {tid} product {k} differs between packed and per-array"
            )


def test_pack_unpack_roundtrip_dtypes():
    """The wire format is a bit-exact inverse across the element sizes
    the codebase feeds (1/2/4/8-byte), odd pixel counts included."""
    import jax

    rng = np.random.default_rng(5)
    for dt in (np.uint8, np.int16, np.uint16, np.int32, np.float64):
        px, ny = 17, 7  # odd on purpose: sub-word tails must zero-pad
        dn = {"b": rng.integers(0, 100, (px, ny)).astype(dt)}
        qa = rng.integers(0, 2, (px, ny)).astype(np.uint16)
        plan = feedmod.build_plan(dn, qa)
        words = feedmod.pack_inputs(dn, qa, plan)
        assert words.nbytes == feedmod.plan_wire_bytes(plan)
        u_dn, u_qa = feedmod.unpack_inputs(jax.device_put(words), plan=plan)
        assert np.asarray(u_dn["b"]).tobytes() == dn["b"].tobytes()
        assert np.asarray(u_qa).tobytes() == qa.tobytes()


def test_upload_auto_keeps_per_array_on_cpu(tmp_path, rstack):
    """"auto" resolves to the per-array path on the CPU backend, where
    device_put is near zero-copy and packing would be pure overhead."""
    assert feedmod.resolve_packed("auto") is False
    summary = run_stack(rstack, make_cfg(str(tmp_path)))
    assert summary["upload"]["packed"] is False


def test_packed_upload_mesh_conflict(tmp_path, rstack):
    """Forcing packed upload with a sharded mesh is a config conflict
    (placement is per-array); 'auto' silently keeps the per-array path."""
    import jax

    from land_trendr_tpu.parallel import make_mesh

    mesh = make_mesh(jax.local_devices())
    with pytest.raises(ValueError, match="upload_packed"):
        run_stack(rstack, make_cfg(str(tmp_path / "f"), upload_packed=True),
                  mesh=mesh)
    summary = run_stack(rstack, make_cfg(str(tmp_path / "a")), mesh=mesh)
    assert summary["upload"]["packed"] is False


def test_upload_fault_reenters_retry_ladder(tmp_path, rstack):
    """An error surfacing through the packed upload wait re-enters the
    retry ladder (per-array re-dispatch from the retained host inputs)
    and the run completes with clean-run artifacts."""
    clean = make_cfg(str(tmp_path / "clean"), upload_packed=True)
    run_stack(rstack, clean)
    cfg = make_cfg(
        str(tmp_path / "f"), upload_packed=True, telemetry=True,
        fault_schedule="seed=1,upload.wait@1",
    )
    summary = run_stack(rstack, cfg)
    assert summary["pixels"] == SPEC.height * SPEC.width
    assert [f["seam"] for f in summary["faults_injected"]] == ["upload.wait"]
    evs = [json.loads(l) for l in open(summary["telemetry"]["events"])]
    retries = [e for e in evs if e["ev"] == "tile_retry"]
    assert len(retries) == 1 and "upload.wait" in retries[0]["error"]
    for a, b in zip(
        load_artifacts(clean, summary["tiles"]),
        load_artifacts(cfg, summary["tiles"]),
    ):
        for k in a:
            assert a[k].tobytes() == b[k].tobytes()


def test_upload_demotion_after_consecutive_failures(tmp_path, rstack):
    """Three consecutive upload failures demote the run to per-array
    sync dispatch for the rest of the run (artifacts unaffected)."""
    cfg = make_cfg(
        str(tmp_path), upload_packed=True, max_retries=4, telemetry=True,
        fault_schedule="seed=1,upload.wait@0*3",
    )
    summary = run_stack(rstack, cfg)
    assert summary["upload"]["demoted"] is True
    assert summary["upload"]["packed"] is False
    evs = [json.loads(l) for l in open(summary["telemetry"]["events"])]
    dem = [e for e in evs if e["ev"] == "upload_demoted"]
    assert len(dem) == 1 and dem[0]["failures"] == 3


def test_runconfig_validates_upload_knobs(tmp_path):
    with pytest.raises(ValueError, match="upload_depth"):
        make_cfg(str(tmp_path), upload_depth=0)
    with pytest.raises(ValueError, match="upload_packed"):
        make_cfg(str(tmp_path), upload_packed="yes")
    with pytest.raises(ValueError, match="ingest_store_mb"):
        make_cfg(str(tmp_path), ingest_store_mb=-1)
    with pytest.raises(ValueError, match="ingest_store_dir"):
        make_cfg(str(tmp_path), ingest_store_dir=str(tmp_path))


def test_upload_cli_knobs(tmp_path, capsys):
    stack_dir = str(tmp_path / "stack")
    assert cli_main(["synth", stack_dir, "--size", "32",
                     "--year-start", "1990", "--year-end", "2001"]) == 0
    capsys.readouterr()
    assert cli_main([
        "segment", stack_dir, "--tile-size", "32",
        "--workdir", str(tmp_path / "work"), "--out-dir",
        str(tmp_path / "out"), "--max-segments", "4",
        "--vertex-count-overshoot", "2", "--packed-upload",
        "--upload-depth", "3",
    ]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["summary"]["upload"]["packed"] is True
    assert rep["summary"]["upload"]["transfers"] == rep["summary"]["upload"]["tiles"]

    # forcing both directions at once is an argument conflict
    assert cli_main([
        "segment", stack_dir, "--tile-size", "32",
        "--workdir", str(tmp_path / "w2"), "--out-dir",
        str(tmp_path / "o2"), "--packed-upload", "--no-packed-upload",
    ]) == 2
    assert "--no-packed-upload" in capsys.readouterr().err


def test_upload_telemetry_schema_metrics_and_rollup(tmp_path, rstack):
    """The upload event passes the schema + value lint, advances the
    lt_upload_* instruments, and folds into obs_report with the derived
    transfers_per_tile."""
    import check_events_schema
    import obs_report

    cfg = make_cfg(str(tmp_path), upload_packed=True, telemetry=True)
    summary = run_stack(rstack, cfg)
    assert check_events_schema.main([cfg.workdir]) == 0

    report, _spans = obs_report.fold([summary["telemetry"]["events"]])
    up = report["upload"]
    assert up["tiles"] == summary["tiles"]
    assert up["transfers_per_tile"] == 1.0
    assert up["packed"] is True
    assert up["bytes"] == summary["upload"]["bytes"] > 0

    prom = open(summary["telemetry"]["metrics"]).read()
    for name in ("lt_upload_bytes_total", "lt_upload_transfers_total",
                 "lt_upload_wait_seconds_total", "lt_upload_backlog_max"):
        assert name in prom


def test_upload_value_lint_catches_drift(tmp_path):
    """The value-level upload lint: negative counters and transfers
    below tiles are producer drift a type check alone cannot catch."""
    from check_events_schema import main as lint_main

    from land_trendr_tpu.obs.events import EventLog

    def write_events(path, upload_fields):
        log = EventLog(path)
        log.run_start(
            fingerprint="x", process_index=0, process_count=1,
            tiles_total=1, tiles_todo=1, tiles_skipped_resume=0,
            mesh_devices=1, impl="xla",
        )
        log.emit("upload", **upload_fields)
        log.emit(
            "run_done", status="ok", tiles_done=1, pixels=1, wall_s=1.0,
            px_per_s=1.0, fit_rate=1.0,
        )
        log.close()

    ok = dict(tiles=2, transfers=2, bytes=10, pack_s=0.1, wait_s=0.1,
              unpack_s=0.1)
    good = str(tmp_path / "good")
    write_events(os.path.join(good, "events.jsonl"), ok)
    assert lint_main([good]) == 0

    for name, bad in (
        ("neg", {**ok, "bytes": -1}),
        ("short", {**ok, "transfers": 1}),
    ):
        d = str(tmp_path / name)
        write_events(os.path.join(d, "events.jsonl"), bad)
        assert lint_main([d]) == 1, name


def test_upload_bench_smoke(tmp_path):
    """Tier-1 upload_bench smoke: runs end to end, parity holds, the
    packed path is one transfer per tile, and the warm/restart store
    passes skip decode entirely."""
    import upload_bench

    out = str(tmp_path / "upload_smoke.json")
    assert upload_bench.main(["--smoke", "--out", out]) == 0
    rep = json.load(open(out))
    assert rep["parity"]["ok"] is True
    assert rep["workload"]["transfers_per_tile_packed"] == 1
    assert rep["workload"]["transfers_per_tile_per_array"] == 3
    assert rep["speedup_packed_sync"] > 0
    assert rep["speedup_packed_async"] > 0
    store = rep["ingest_store"]
    assert store["parity_ok"] is True
    assert store["store_warm"]["hit_rate"] == 1.0
    assert store["store_restart"]["hit_rate"] == 1.0
    assert store["store_warm"]["stats"]["misses"] == 0


def test_perf_gate_smoke(tmp_path, capsys):
    """The tier-1 perf-regression gate: the three bench smokes must meet
    the bands derived from the committed artifacts.  The elastic
    scheduler leg is skipped here — it spawns two 2-process jax pods
    (minutes-scale, timing-sensitive under suite load); CLI gate runs
    carry it, and the lease invariants stay tier-1-covered by
    tests/test_leases.py + fault_soak's lease case.  The fleet-router
    leg is skipped for the same reason (seven jax replica processes);
    tests/test_fleet_serve.py covers its invariants in-process."""
    import perf_gate

    rc = perf_gate.main(["--keep", str(tmp_path / "gate"),
                         "--skip-scheduler", "--skip-router"])
    out = capsys.readouterr()
    assert rc == 0, f"perf gate regressions:\n{out.out}\n{out.err}"
