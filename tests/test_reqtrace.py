"""End-to-end request tracing tests (ISSUE 15).

Pins the request-tracing plane's contracts:

* **propagation**: a ``trace_id`` minted at router admission crosses
  the real router→replica HTTP path into the job's run scope — every
  event of the journey (router request spans, serve lifecycle, per-tile
  run events) carries ONE id, and the second (warm) job's trace is just
  as complete as the cold one's;
* **blame algebra**: the priority-sweep partition assigns every instant
  of the window to exactly one component, so the components sum to the
  window length by construction — overlap, clipping, and gap cases;
* **exemplars**: histogram observations carry trace ids into bounded
  per-bucket rings, exposed as ``/metrics``-adjacent JSON, and a tail
  bucket's exemplar resolves to an assemblable trace;
* **lints**: the ``request_span``/``request_done`` value lints and the
  stateful orphan-trace referential check (positives AND negatives);
* the committed two-hop fixture stays schema-clean and assembles; the
  ``lt_request``/``lt top`` CLIs smoke.

Scene shape and params are shared with ``tests/test_serve.py`` /
``tests/test_fleet_serve.py`` so the process-wide jit cache keeps the
in-process replica warm across the suite.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack
from land_trendr_tpu.obs.events import (
    REQUEST_SPAN_STAGES,
    validate_events_file,
)
from land_trendr_tpu.obs.metrics import EXEMPLAR_RING, MetricsRegistry
from land_trendr_tpu.obs.reqtrace import (
    BLAME_PRIORITY,
    assemble_request,
    blame_partition,
    discover_request_files,
    list_requests,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

_FIXTURE = os.path.join(
    os.path.dirname(__file__), "fixtures", "reqtrace.events.jsonl"
)
_FIXTURE_TRACE = "tr2hop0fixture01"

_PARAMS = {"max_segments": 4, "vertex_count_overshoot": 2}
_TILE = 20


@pytest.fixture(scope="module")
def stack_dir(tmp_path_factory) -> str:
    d = str(tmp_path_factory.mktemp("reqtrace_stack") / "stack")
    write_stack(
        d,
        make_stack(
            SceneSpec(width=40, height=40, year_start=2000, year_end=2008,
                      seed=3)
        ),
    )
    return d


# ---------------------------------------------------------------------------
# blame algebra


def test_blame_partition_sums_exactly():
    """The partition property: whatever the interval soup, the
    components sum to the window length — it is a partition, not a sum
    of overlapping stage totals."""
    iv = [
        ("forward", 1.0, 2.0),
        ("compute", 1.5, 4.0),     # overlaps forward: forward wins 1.5-2
        ("feed", 3.5, 6.0),        # overlaps compute: compute wins to 4
        ("write", 100.0, 101.0),   # outside the window: clipped away
    ]
    b = blame_partition(iv, 0.0, 8.0)
    assert abs(sum(b.values()) - 8.0) < 1e-12
    assert b["forward"] == pytest.approx(1.0)
    assert b["compute"] == pytest.approx(2.0)   # 2.0-4.0
    assert b["feed"] == pytest.approx(2.0)      # 4.0-6.0
    assert "write" in b or b.get("write") is None  # clipped → absent
    assert "write" not in b
    # uncovered instants are 'other': [0,1) + [6,8) = 3s
    assert b["other"] == pytest.approx(3.0)


def test_blame_partition_priority_and_edges():
    # higher-priority component claims the overlap regardless of order
    b = blame_partition(
        [("feed", 0.0, 10.0), ("compute", 2.0, 4.0)], 0.0, 10.0
    )
    assert b["compute"] == pytest.approx(2.0)
    assert b["feed"] == pytest.approx(8.0)
    # empty/degenerate windows
    assert blame_partition([], 5.0, 5.0) == {}
    assert blame_partition([("feed", 0, 1)], 5.0, 4.0) == {}
    # unknown components are ignored, not crashed on
    b = blame_partition([("martian", 0.0, 1.0)], 0.0, 1.0)
    assert b == {"other": pytest.approx(1.0)}
    # every documented component is rankable
    for comp in BLAME_PRIORITY:
        assert blame_partition([(comp, 0.0, 1.0)], 0.0, 1.0) == {
            comp: pytest.approx(1.0)
        }


# ---------------------------------------------------------------------------
# histogram exemplars


def test_histogram_exemplar_buckets_and_ring_bound():
    reg = MetricsRegistry()
    h = reg.histogram("lt_t_seconds", "t", buckets=(1.0, 10.0))
    h.observe(0.5, exemplar="t-low")
    h.observe(5.0, exemplar="t-mid")
    h.observe(50.0, exemplar="t-inf")
    ex = h.exemplars()
    assert ex["1.0"][0]["trace_id"] == "t-low"
    assert ex["10.0"][0]["trace_id"] == "t-mid"
    assert ex["+Inf"][0]["trace_id"] == "t-inf"
    # the ring is bounded: only the newest EXEMPLAR_RING survive
    for i in range(EXEMPLAR_RING + 3):
        h.observe(0.5, exemplar=f"t-{i}")
    ring = h.exemplars()["1.0"]
    assert len(ring) == EXEMPLAR_RING
    assert ring[-1]["trace_id"] == f"t-{EXEMPLAR_RING + 2}"
    # counts unaffected by exemplars; a plain observe records none
    assert h.count == 3 + EXEMPLAR_RING + 3
    h2 = reg.histogram("lt_plain_seconds", "p", buckets=(1.0,))
    h2.observe(0.5)
    assert h2.exemplars() is None
    # registry-level dump lists only exemplar'd histograms
    names = {e["name"] for e in reg.exemplars()}
    assert names == {"lt_t_seconds"}


# ---------------------------------------------------------------------------
# schema + value lints


def _lint(lines: list) -> list:
    import tempfile

    from check_events_schema import value_lints

    with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                     delete=False) as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
        path = f.name
    try:
        return validate_events_file(path, extra=value_lints())
    finally:
        os.unlink(path)


def _rs(**extra) -> dict:
    return {
        "ev": "run_start", "t_wall": 1.0, "t_mono": 1.0, "schema": 1,
        "fingerprint": "route", "pid": 1, "host": "h",
        "process_index": 0, "process_count": 1, "tiles_total": 0,
        "tiles_todo": 0, "tiles_skipped_resume": 0, "mesh_devices": 0,
        "impl": "route", **extra,
    }


def test_request_value_lints_positive_and_negative():
    sub = {"ev": "job_submitted", "t_wall": 2.0, "t_mono": 2.0,
           "job_id": "j1", "trace_id": "t1", "tenant": "a",
           "priority": 0, "queue_depth": 1}
    span = {"ev": "request_span", "t_wall": 3.0, "t_mono": 3.0,
            "trace_id": "t1", "name": "forward", "start": 2.0,
            "end": 3.0, "replica": "r0", "attempt": 1, "ok": True}
    done = {"ev": "request_done", "t_wall": 4.0, "t_mono": 4.0,
            "trace_id": "t1", "status": "done", "latency_s": 2.0,
            "hops": 1,
            "blame": {"forward": 1.0, "route_queue": 0.5,
                      "replica": 0.5}}
    assert _lint([_rs(), sub, span, done]) == []
    # a span closing before it opens flags
    bad = dict(span, start=5.0, end=4.0)
    assert any("precedes start" in e for e in _lint([_rs(), sub, bad]))
    # blame components NOT summing to the latency flag
    bad = dict(done, blame={"forward": 0.1})
    assert any("partition" in e for e in _lint([_rs(), sub, span, bad]))
    # a routed request with no forward component flags
    bad = dict(done, blame={"replica": 2.0})
    assert any("'forward'" in e for e in _lint([_rs(), sub, span, bad]))
    # negative blame components flag
    bad = dict(done, blame={"forward": 3.0, "replica": -1.0})
    assert any("negative" in e for e in _lint([_rs(), sub, span, bad]))


def test_orphan_trace_lint():
    span = {"ev": "request_span", "t_wall": 3.0, "t_mono": 3.0,
            "trace_id": "t-orphan", "name": "forward", "start": 2.0,
            "end": 3.0}
    # an un-introduced trace_id on a span is an orphan
    errs = _lint([_rs(), span])
    assert any("orphan" in e for e in errs)
    # introduction via job_submitted clears it
    sub = {"ev": "job_submitted", "t_wall": 2.0, "t_mono": 2.0,
           "job_id": "j1", "trace_id": "t-orphan", "tenant": "a",
           "priority": 0, "queue_depth": 1}
    assert _lint([_rs(), sub, span]) == []
    # introduction via route_decision clears it too
    rd = {"ev": "route_decision", "t_wall": 2.0, "t_mono": 2.0,
          "job_id": "j1", "trace_id": "t-orphan", "tenant": "a",
          "replica": "r0", "warm": False}
    assert _lint([_rs(), rd, span]) == []
    # a run scope's common-field stamp introduces via run_start (the
    # job-run stream case: tile spans carry the id, run_start admits it)
    tile_span = {"ev": "span", "t_wall": 3.0, "t_mono": 3.0,
                 "trace_id": "t-run", "name": "feed", "tile_id": 0,
                 "start": 2.0, "end": 3.0}
    assert _lint([_rs(trace_id="t-run"), tile_span]) == []
    assert any("orphan" in e for e in _lint([_rs(), tile_span]))
    # a NEW scope resets the known set — the stale id orphans again
    errs = _lint([_rs(), sub, span, _rs(), span])
    assert any("orphan" in e for e in errs)


# ---------------------------------------------------------------------------
# the committed fixture + CLI smokes


def test_fixture_lints_clean_and_assembles_two_hops():
    from check_events_schema import main as lint_main

    assert lint_main([_FIXTURE]) == 0
    rec = assemble_request([_FIXTURE], _FIXTURE_TRACE)
    assert rec["found"]
    assert [h["replica"] for h in rec["hops"]] == ["r0", "r1"]
    assert rec["hops"][0]["ok"] is False
    assert rec["hops"][1]["ok"] is True
    assert rec["latency_s"] == pytest.approx(5.1)
    assert rec["blame_sum_s"] == pytest.approx(rec["latency_s"])
    assert rec["router_blame"]["forward"] == pytest.approx(0.5)
    # router-only streams assemble but are not COMPLETE (no run events)
    assert rec["complete"] is False
    # the request_done index finds it (slowest-first contract)
    idx = list_requests([_FIXTURE])
    assert idx[0]["trace_id"] == _FIXTURE_TRACE


def test_lt_request_cli_smokes(tmp_path, capsys):
    import lt_request

    # assemble by id
    assert lt_request.main([_FIXTURE_TRACE, _FIXTURE]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["trace_id"] == _FIXTURE_TRACE
    assert len(rec["hops"]) == 2
    # --list and --slowest need no id
    assert lt_request.main(["--list", _FIXTURE]) == 0
    idx = json.loads(capsys.readouterr().out)["requests"]
    assert idx and idx[0]["trace_id"] == _FIXTURE_TRACE
    chrome = str(tmp_path / "req_trace.json")
    assert lt_request.main(["--slowest", _FIXTURE, "--trace", chrome]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["trace"]["events"] > 0
    exported = json.loads(Path(chrome).read_text())
    assert any(e.get("ph") == "X" for e in exported["traceEvents"])
    # unknown trace → exit 1; missing path → exit 2
    assert lt_request.main(["nope", _FIXTURE]) == 1
    capsys.readouterr()
    assert lt_request.main(["nope", str(tmp_path / "absent")]) == 2


def test_obs_report_request_rollup():
    import obs_report

    report, spans = obs_report.fold([_FIXTURE])
    rq = report["request"]
    assert rq["requests"] == 1
    assert rq["rerouted"] == 1
    assert rq["by_status"] == {"done": 1}
    assert rq["latency_s"]["p99"] == pytest.approx(5.1)
    assert rq["by_component"]["forward"]["p50"] == pytest.approx(0.5)
    # request spans ride the Chrome trace as req:* slices
    tids = {s.get("tid") for s in spans}
    assert "req:forward" in tids and "req:route_queue" in tids


def test_lt_top_renders_trace_column():
    import lt_top

    view = lt_top.render_router({
        "healthz": {"router": True, "uptime_s": 1.0, "queue_depth": 0,
                    "routed": 0, "jobs_total": 1, "jobs_terminal": 1,
                    "tenants": {}, "replicas": [], "scaler": None},
        "metrics": [],
        "jobs": [{"job_id": "rt-1-00001", "trace_id": _FIXTURE_TRACE,
                  "state": "done", "tenant": "a", "replica": "r0",
                  "attempts": 2, "submitted_t": time.time()}],
        "requests": [{"trace_id": _FIXTURE_TRACE, "status": "done",
                      "latency_s": 5.1, "hops": 2,
                      "blame": {"forward": 0.5, "replica": 4.6}}],
    })
    assert "TRACE" in view
    assert _FIXTURE_TRACE[:10] in view
    assert "SLOWEST REQUESTS" in view and "forward=0.50s" in view


def test_perf_gate_reqtrace_leg(tmp_path):
    """The CI leg end-to-end: synthetic fleet streams lint clean, the
    re-routed trace assembles two-hop with an exact blame sum, the
    exemplar resolves, stamping stays inside the noise band."""
    import perf_gate

    checks: list = []
    perf_gate.run_reqtrace_leg(
        str(tmp_path),
        lambda name, ok, detail: checks.append(
            {"check": name, "ok": bool(ok), "detail": detail}
        ),
    )
    failed = [c for c in checks if not c["ok"]]
    assert not failed, failed
    assert len(checks) == 7


# ---------------------------------------------------------------------------
# propagation end-to-end over the real router+replica HTTP path


def test_request_propagation_end_to_end(stack_dir, tmp_path):
    """Two same-shape jobs through a real FleetRouter over a real
    (in-process) replica: ONE trace_id per request crosses router →
    forward payload → serve admission → run scope; the warm second
    job's trace is complete too; exemplars and /debug/requests resolve;
    every stream lints clean (orphan lint included)."""
    import threading as _threading

    from check_events_schema import main as lint_main

    from land_trendr_tpu.fleet import FleetRouter, RouterConfig
    from land_trendr_tpu.serve import SegmentationServer, ServeConfig

    server = SegmentationServer(ServeConfig(
        workdir=str(tmp_path / "replica0"), feed_cache_mb=32,
    ))
    srv_thread = _threading.Thread(target=server.serve_forever)
    srv_thread.start()
    rt_dir = str(tmp_path / "rt")
    router = FleetRouter(RouterConfig(
        workdir=rt_dir,
        replicas=(f"http://127.0.0.1:{server.port}",),
        health_interval_s=0.2,
    ))
    rt_thread = _threading.Thread(target=router.serve_forever)
    rt_thread.start()
    job = {"stack_dir": stack_dir, "tile_size": _TILE,
           "params": dict(_PARAMS),
           "run_overrides": {"retry_backoff_s": 0.0}}
    try:
        snaps = []
        for _ in range(2):
            snap = router.submit(dict(job))
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                s = router.job_status(snap["job_id"])
                if s["state"] not in ("queued", "routed"):
                    break
                time.sleep(0.05)
            snaps.append(s)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/metrics/exemplars",
            timeout=10,
        ) as r:
            exemplars = json.loads(r.read())["exemplars"]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}/debug/requests", timeout=10
        ) as r:
            recent = json.loads(r.read())["requests"]
    finally:
        router.stop()
        rt_thread.join(timeout=300)
        server.stop()
        srv_thread.join(timeout=120)

    assert [s["state"] for s in snaps] == ["done", "done"]
    traces = [s["trace_id"] for s in snaps]
    assert len(set(traces)) == 2 and all(traces)
    # every stream of the journey lints clean — the orphan-trace lint
    # proves every stamped span resolves to its introduction
    streams = [rt_dir, str(tmp_path / "replica0"),
               *(s["workdir"] for s in snaps)]
    assert lint_main(streams) == 0

    files = [
        f for root in streams for f in discover_request_files(root)
    ]
    for s in snaps:
        rec = assemble_request(files, s["trace_id"])
        assert rec["complete"], rec
        assert len(rec["hops"]) == 1 and rec["hops"][0]["ok"] is True
        # components are individually rounded to 6 dp, so the sum can
        # sit a few microseconds off the independently-rounded latency
        assert rec["blame_sum_s"] == pytest.approx(
            rec["latency_s"], abs=1e-3
        )
        assert rec["tiles_done"] >= 1
        # the run scope contributed pipeline components
        assert {"compute", "forward"} <= set(rec["blame"])
    # the WARM job (second) ran zero compiles yet its trace is complete
    warm = snaps[1]["result"]["summary"]["program_cache"]
    assert warm["misses"] == 0 and warm["hits"] == 1
    warm_rec = assemble_request(files, traces[1])
    assert warm_rec["complete"] and "compile" not in warm_rec["blame"]
    # the run scope stamped the id on EVERY event (common-field check)
    run_events = [
        json.loads(line)
        for line in Path(snaps[0]["workdir"], "events.jsonl")
        .read_text().splitlines()
    ]
    assert run_events and all(
        e.get("trace_id") == traces[0] for e in run_events
    )
    # exemplars: every ring entry is one of our traces, and the ring's
    # trace assembles
    ids = {
        e2["trace_id"]
        for entry in exemplars
        for ring in entry["exemplars"].values()
        for e2 in ring
    }
    assert ids and ids <= set(traces)
    # /debug/requests: slowest-first rows with router blame splits
    assert {r["trace_id"] for r in recent} == set(traces)
    assert all(
        abs(sum(r["blame"].values()) - r["latency_s"]) < 5e-3
        for r in recent
    )
    lats = [r["latency_s"] for r in recent]
    assert lats == sorted(lats, reverse=True)
    # the request-span vocabulary showed up in the router stream
    router_events = [
        json.loads(line)
        for line in Path(rt_dir, "events.jsonl").read_text().splitlines()
    ]
    span_names = {
        e["name"] for e in router_events if e["ev"] == "request_span"
    }
    assert {"route_queue", "forward", "relay"} <= span_names
    assert span_names <= set(REQUEST_SPAN_STAGES)
    dones = [e for e in router_events if e["ev"] == "request_done"]
    assert {e["trace_id"] for e in dones} == set(traces)
