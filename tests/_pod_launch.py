"""Shared launcher for true multi-process ``jax.distributed`` pod runs.

Used by the two-process tests in ``tests/test_multihost.py`` and by
``tools/multihost_bench.py`` so the ephemeral-port pick, process reaping,
and bind-race retry classification live in exactly one place.

The bind/close/reuse port pick is a TOCTOU race — another process can
claim the port between the probe's ``close()`` and worker 0's bind — so
that outcome raises :class:`PodBindRace` for the caller to retry on a
fresh port; any other failure raises ``RuntimeError`` with the worker's
stderr tail.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from typing import Callable, Sequence

__all__ = ["PodBindRace", "launch_pod", "pod_env"]


class PodBindRace(RuntimeError):
    """A worker lost the ephemeral-port race; retry on a fresh port."""


def pod_env(devices_per_proc: int = 4) -> dict:
    """Env for a worker: N virtual CPU devices + repo on PYTHONPATH."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices_per_proc}"
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _launch_once(
    worker: str,
    argv_for: Callable[[int], Sequence[str]],
    n_procs: int,
    env: dict,
    timeout: float,
) -> None:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    coordinator = f"localhost:{port}"
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coordinator, *map(str, argv_for(i))],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(n_procs)
    ]

    def reap_all() -> None:
        for q in procs:
            if q.poll() is None:
                # the sibling may still be dialing a coordinator that will
                # never exist — kill it before any retry races it on outputs
                q.kill()
            q.communicate()  # drain pipes so nothing blocks on PIPE

    for i, p in enumerate(procs):
        try:
            _, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            reap_all()
            raise RuntimeError(f"worker {i} timed out after {timeout:.0f}s")
        if p.returncode != 0:
            reap_all()
            lowered = err.lower()
            if "address already in use" in lowered or "bind" in lowered:
                # keep the stderr tail: if this classification misfires (or
                # retries exhaust), the real error must still be readable
                raise PodBindRace(
                    f"worker {i} lost the port race:\n{err[-4000:]}"
                )
            raise RuntimeError(f"worker {i} failed:\n{err[-4000:]}")


def launch_pod(
    worker: str,
    argv_for: Callable[[int], Sequence[str]],
    n_procs: int = 2,
    env: dict | None = None,
    timeout: float = 600.0,
    attempts: int = 3,
    before_attempt: Callable[[], None] | None = None,
) -> None:
    """Run ``n_procs`` workers to completion, retrying port races.

    ``argv_for(i)`` returns process ``i``'s argv AFTER the coordinator
    address (which is always argv[1]).  ``before_attempt`` (if given) runs
    before every attempt — e.g. to reset a shared workdir a failed
    attempt may have partially written.
    """
    env = pod_env() if env is None else env
    last: Exception | None = None
    for _ in range(attempts):
        if before_attempt is not None:
            before_attempt()
        try:
            _launch_once(worker, argv_for, n_procs, env, timeout)
            return
        except PodBindRace as e:
            last = e
    raise RuntimeError(f"all {attempts} coordinator port attempts raced") from last
