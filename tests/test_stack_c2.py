"""Collection-2 per-band layout ingestion (VERDICT r2 item #3).

The real USGS distribution ships one file per band (``*_SR_B5.TIF``,
``*_QA_PIXEL.TIF``); these tests pin the per-band loader against the
pre-stacked loader on the same synthetic scene, the mixed-sensor band
mapping (TM vs OLI numbering), auto-detection, and the loud-error paths.
"""

import os

import numpy as np
import pytest

from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack, write_stack_c2
from land_trendr_tpu.ops.indices import BANDS
from land_trendr_tpu.runtime import load_stack_dir, load_stack_dir_c2


@pytest.fixture(scope="module")
def scene():
    # spans the 2013 sensor switch so both band numberings are exercised
    return make_stack(SceneSpec(width=16, height=12, year_start=2009, year_end=2016))


def test_c2_matches_prestacked(tmp_path, scene):
    d_stacked = str(tmp_path / "stacked")
    d_c2 = str(tmp_path / "c2")
    write_stack(d_stacked, scene)
    write_stack_c2(d_c2, scene)

    a = load_stack_dir(d_stacked)
    b = load_stack_dir_c2(d_c2)
    np.testing.assert_array_equal(a.years, b.years)
    np.testing.assert_array_equal(a.qa, b.qa)
    for band in BANDS:
        np.testing.assert_array_equal(a.dn_bands[band], b.dn_bands[band])
    assert b.geo is not None and b.geo.pixel_scale == (30.0, 30.0, 0.0)


def test_band_subset_loading(tmp_path, scene):
    """bands=... loads only the requested cubes (plus QA) in BOTH layouts,
    identical to the full load's cubes; unknown names error."""
    d_stacked = str(tmp_path / "stacked")
    d_c2 = str(tmp_path / "c2")
    write_stack(d_stacked, scene)
    write_stack_c2(d_c2, scene)
    full = load_stack_dir(d_stacked)

    for d in (d_stacked, d_c2):
        sub = load_stack_dir(d, bands=("nir", "swir2"))
        assert set(sub.dn_bands) == {"nir", "swir2"}
        for band in ("nir", "swir2"):
            np.testing.assert_array_equal(sub.dn_bands[band], full.dn_bands[band])
        np.testing.assert_array_equal(sub.qa, full.qa)
    with pytest.raises(ValueError, match="unknown band"):
        load_stack_dir(d_stacked, bands=("nir", "thermal"))


def test_c2_band_subset_skips_unused_files(tmp_path, scene):
    """With a subset, the C2 loader never opens the unused bands' files —
    a download containing ONLY the needed bands loads fine."""
    d_c2 = str(tmp_path / "c2")
    write_stack_c2(d_c2, scene)
    keep = ("nir", "swir2")
    # corrupt every file of an unused band: the loader must not read them
    for n in os.listdir(d_c2):
        up = n.upper()
        # red band: TM numbering B3, OLI numbering B4
        if ("LT05" in up and "_SR_B3" in up) or ("LC08" in up and "_SR_B4" in up):
            with open(os.path.join(d_c2, n), "wb") as f:
                f.write(b"not a tiff")
    sub = load_stack_dir_c2(d_c2, bands=keep)
    assert set(sub.dn_bands) == set(keep)


def test_c2_autodetected_by_load_stack_dir(tmp_path, scene):
    d = str(tmp_path / "c2auto")
    write_stack_c2(d, scene)
    got = load_stack_dir(d)  # no explicit c2 call
    np.testing.assert_array_equal(got.years, scene.years)


def test_c2_missing_band_errors(tmp_path, scene):
    d = str(tmp_path / "c2gap")
    paths = write_stack_c2(d, scene)
    os.remove([p for p in paths if p.endswith("_SR_B4.TIF")][0])  # a TM nir
    with pytest.raises(ValueError, match="missing bands.*nir"):
        load_stack_dir_c2(d)


def test_c2_multiple_acquisitions_per_year_error(tmp_path, scene):
    d = str(tmp_path / "c2dup")
    paths = write_stack_c2(d, scene)
    # duplicate one band under a second acquisition date in the same year
    src = paths[0]
    dup = os.path.join(d, os.path.basename(src).replace("0715", "0816"))
    with open(src, "rb") as f, open(dup, "wb") as g:
        g.write(f.read())
    with pytest.raises(ValueError, match="multiple acquisitions"):
        load_stack_dir_c2(d)


def test_c2_empty_dir_errors(tmp_path):
    d = str(tmp_path / "empty")
    os.makedirs(d)
    with pytest.raises(FileNotFoundError):
        load_stack_dir_c2(d)


def test_c2_unused_bands_ignored(tmp_path, scene):
    """OLI's coastal B1 (and thermal-era extras) are skipped, not errors."""
    d = str(tmp_path / "c2extra")
    write_stack_c2(d, scene)
    extra = os.path.join(d, "LC08_L2SP_045030_20160715_20160715_02_T1_SR_B1.TIF")
    from land_trendr_tpu.io.geotiff import write_geotiff

    write_geotiff(extra, np.zeros((12, 16), dtype=np.int16))
    got = load_stack_dir_c2(d)
    np.testing.assert_array_equal(got.years, scene.years)


def test_c2_cli_segment_runs(tmp_path, scene):
    """End-to-end: the segment CLI ingests a per-band C2 directory."""
    import json
    import subprocess
    import sys

    d = str(tmp_path / "c2cli")
    out = str(tmp_path / "out")
    write_stack_c2(d, scene)
    r = subprocess.run(
        [
            sys.executable, "-m", "land_trendr_tpu", "--platform", "cpu",
            "segment", d, "--out-dir", out,
            "--workdir", str(tmp_path / "work"), "--tile-size", "16",
        ],
        capture_output=True,
        text=True,
        env=dict(
            os.environ,
            PYTHONPATH=os.pathsep.join(
                [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
                + os.environ.get("PYTHONPATH", "").split(os.pathsep)
            ),
        ),
    )
    assert r.returncode == 0, r.stderr[-3000:]
    payload = json.loads(r.stdout)
    assert payload["summary"]["tiles"] >= 1
    assert payload["summary"]["pixels"] == 16 * 12
    assert os.path.exists(os.path.join(out, "rmse.tif"))


def test_c2_uint16_sr_preserved(tmp_path):
    """Real C2 SR files are uint16 with valid DNs up to 43636 — the loader
    must keep the dtype, not wrap bright pixels negative (code-review r3)."""
    from land_trendr_tpu.io.geotiff import write_geotiff
    from land_trendr_tpu.ops.indices import scale_sr

    d = str(tmp_path / "u16")
    os.makedirs(d)
    stem = "LC08_L2SP_045030_20200715_20200912_02_T1"
    nums = {"blue": 2, "green": 3, "red": 4, "nir": 5, "swir1": 6, "swir2": 7}
    bright = np.full((4, 4), 43636, dtype=np.uint16)  # reflectance ~1.0
    for b in BANDS:
        write_geotiff(os.path.join(d, f"{stem}_SR_B{nums[b]}.TIF"), bright)
    write_geotiff(
        os.path.join(d, f"{stem}_QA_PIXEL.TIF"),
        np.zeros((4, 4), dtype=np.uint16),
    )
    got = load_stack_dir_c2(d)
    assert got.dn_bands["nir"].dtype == np.uint16
    sr = np.asarray(scale_sr(got.dn_bands["nir"]))
    np.testing.assert_allclose(sr, 43636 * 2.75e-5 - 0.2, rtol=1e-5)  # ~1.0


def test_c2_qa_dtype_whitelist_both_loaders(tmp_path, scene):
    """A wider-than-uint16 QA_PIXEL file must error loudly in BOTH the
    eager and the lazy loader — a blind uint16 cast silently truncates
    the CFMask bit flags (ADVICE round 5; loaders must not diverge)."""
    from land_trendr_tpu.io.geotiff import write_geotiff
    from land_trendr_tpu.runtime.stack import open_stack_dir_c2_lazy

    d = str(tmp_path / "wide_qa")
    write_stack_c2(d, scene)
    qa = next(n for n in os.listdir(d) if "QA_PIXEL" in n)
    write_geotiff(
        os.path.join(d, qa), np.zeros((12, 16), dtype=np.uint32)
    )
    with pytest.raises(ValueError, match="QA_PIXEL dtype"):
        load_stack_dir_c2(d)
    with pytest.raises(ValueError, match="QA_PIXEL dtype"):
        open_stack_dir_c2_lazy(d)


def test_c2_rt_tier_accepted(tmp_path, scene):
    """The USGS RT (real-time) collection tier must not silently vanish."""
    d = str(tmp_path / "rt")
    paths = write_stack_c2(d, scene)
    for p in paths:
        os.rename(p, p.replace("_T1_", "_RT_"))
    got = load_stack_dir_c2(d)
    np.testing.assert_array_equal(got.years, scene.years)


def test_c2_mixed_pathrows_error_and_pattern_select(tmp_path, scene):
    """Two WRS-2 scenes in one directory error loudly; a pattern filter
    selects one (code-review r3: pathrow was captured but unused)."""
    d = str(tmp_path / "two_scenes")
    paths = write_stack_c2(d, scene)
    for p in paths:  # duplicate every file under the adjacent path/row
        dst = p.replace("_045030_", "_045031_")
        with open(p, "rb") as fsrc, open(dst, "wb") as fdst:
            fdst.write(fsrc.read())
    with pytest.raises(ValueError, match="path/rows"):
        load_stack_dir_c2(d)
    got = load_stack_dir_c2(d, pattern=r"_045030_")
    np.testing.assert_array_equal(got.years, scene.years)
    # and through the auto-detecting entry point with the same pattern
    got2 = load_stack_dir(str(d), pattern=r"_045030_.*\.tif$")
    np.testing.assert_array_equal(got2.years, scene.years)


def test_c2_mixed_dtype_years_rejected(tmp_path):
    """int16 and uint16 SR files across years must not silently promote to
    int32 at np.stack (code-review r3)."""
    from land_trendr_tpu.io.geotiff import write_geotiff

    d = str(tmp_path / "mixdt")
    os.makedirs(d)
    nums_tm = {"blue": 1, "green": 2, "red": 3, "nir": 4, "swir1": 5, "swir2": 7}
    nums_oli = {"blue": 2, "green": 3, "red": 4, "nir": 5, "swir1": 6, "swir2": 7}
    for year, sensor, nums, dt in (
        (2010, "LT05", nums_tm, np.int16),
        (2014, "LC08", nums_oli, np.uint16),
    ):
        stem = f"{sensor}_L2SP_045030_{year}0715_{year}0715_02_T1"
        for b in BANDS:
            write_geotiff(
                os.path.join(d, f"{stem}_SR_B{nums[b]}.TIF"),
                np.full((4, 4), 9000, dtype=dt),
            )
        write_geotiff(
            os.path.join(d, f"{stem}_QA_PIXEL.TIF"),
            np.zeros((4, 4), dtype=np.uint16),
        )
    with pytest.raises(ValueError, match="mixed DN dtypes"):
        load_stack_dir_c2(d)
