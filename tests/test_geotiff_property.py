"""Property-based GeoTIFF codec fuzz: any array the writer accepts must
round-trip bit-exactly through every (compression, predictor, layout)
combination, via both the native C++ fast path and the pure-Python
reference."""

import numpy as np
import pytest

pytest.importorskip(
    "hypothesis", reason="hypothesis not installed in this environment"
)
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from land_trendr_tpu.io.geotiff import read_geotiff, write_geotiff

DTYPES = ("u1", "u2", "i2", "i4", "f4", "f8")


@st.composite
def rasters(draw):
    dtype = np.dtype(draw(st.sampled_from(DTYPES)))
    bands = draw(st.integers(1, 4))
    h = draw(st.integers(1, 70))
    w = draw(st.integers(1, 70))
    seed = draw(st.integers(0, 2**31 - 1))
    rng = np.random.default_rng(seed)
    if dtype.kind == "f":
        arr = rng.normal(size=(bands, h, w)).astype(dtype)
    else:
        info = np.iinfo(dtype)
        arr = rng.integers(
            info.min, info.max, size=(bands, h, w), endpoint=True
        ).astype(dtype)
    return arr


@settings(
    max_examples=40,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)
@given(
    arr=rasters(),
    compress=st.sampled_from(["deflate", "lzw", "none"]),
    predictor=st.booleans(),
    tile=st.sampled_from([None, 16, 64]),
)
def test_roundtrip_property(tmp_path_factory, arr, compress, predictor, tile):
    p = str(tmp_path_factory.mktemp("prop") / "x.tif")
    write_geotiff(p, arr, compress=compress, predictor=predictor, tile=tile)
    got, _, info = read_geotiff(p)
    if arr.shape[0] == 1:
        arr = arr[0]
    np.testing.assert_array_equal(got, arr)
    assert info.bands == (1 if arr.ndim == 2 else arr.shape[0])


@settings(max_examples=25, deadline=None)
@given(data=st.binary(min_size=0, max_size=4096))
def test_lzw_codec_roundtrip_property(data):
    from land_trendr_tpu.io.geotiff import _lzw_decode, _lzw_encode

    assert _lzw_decode(_lzw_encode(data)) == data


@settings(max_examples=60, deadline=None)
@given(
    prefix=st.sampled_from(
        [b"", b"II*\x00", b"MM\x00*", b"II+\x00\x08\x00\x00\x00"]
    ),
    blob=st.binary(min_size=0, max_size=256),
)
def test_reader_never_crashes_unhandled(tmp_path_factory, prefix, blob):
    """Arbitrary garbage — bare or behind a valid classic/BigTIFF magic so
    the IFD parser is reached — must fail with ValueError (the codec's
    corrupt-file taxonomy) or decode; never struct.error/KeyError/
    MemoryError/OverflowError."""
    p = str(tmp_path_factory.mktemp("junk") / "junk.tif")
    with open(p, "wb") as f:
        f.write(prefix + blob)
    try:
        read_geotiff(p)
    except ValueError:
        pass


@st.composite
def corruptions(draw):
    """(offset, replacement-bytes) mutations to apply to a valid file."""
    n = draw(st.integers(1, 6))
    muts = []
    for _ in range(n):
        off = draw(st.integers(0, 700))
        val = draw(st.binary(min_size=1, max_size=8))
        muts.append((off, val))
    return muts


@settings(max_examples=80, deadline=None)
@given(
    muts=corruptions(),
    compress=st.sampled_from(["deflate", "lzw", "none"]),
    seed=st.integers(0, 2**31 - 1),
)
def test_structured_corruption_never_crashes_unhandled(
    tmp_path_factory, muts, compress, seed
):
    """Mutated VALID files reach deep parser/decoder paths (IFD entries,
    counts, block tables, compressed payloads); every outcome must be a
    clean decode or a ValueError — never struct.error / KeyError /
    IndexError / zlib.error / OSError / MemoryError (code-review r3)."""
    rng = np.random.default_rng(seed)
    arr = rng.integers(0, 65535, size=(2, 9, 11), endpoint=True).astype(np.uint16)
    d = tmp_path_factory.mktemp("mut")
    p = str(d / "good.tif")
    write_geotiff(p, arr, compress=compress, tile=None)
    blob = bytearray(open(p, "rb").read())
    for off, val in muts:
        off %= max(1, len(blob))
        blob[off : off + len(val)] = val
    q = str(d / "mut.tif")
    with open(q, "wb") as f:
        f.write(bytes(blob))
    try:
        read_geotiff(q)
    except ValueError:
        pass


@st.composite
def window_partitions(draw):
    """A raster plus a random rectangular partition of it: random column
    cuts per row-band, so windows are ragged, unaligned, and exhaustive."""
    h = draw(st.integers(1, 80))
    w = draw(st.integers(1, 80))
    bands = draw(st.integers(1, 3))
    seed = draw(st.integers(0, 2**31 - 1))
    rng = np.random.default_rng(seed)
    arr = rng.integers(0, 256, size=(h, w, bands)).astype(np.uint8)

    def cuts(n, lo=1, hi=40):
        out, pos = [0], 0
        while pos < n:
            pos = min(n, pos + int(rng.integers(lo, hi + 1)))
            out.append(pos)
        return out

    wins = []
    ys = cuts(h)
    for y0, y1 in zip(ys, ys[1:]):
        xs = cuts(w)
        for x0, x1 in zip(xs, xs[1:]):
            wins.append((y0, x0, y1 - y0, x1 - x0))
    order = rng.permutation(len(wins))
    return arr, [wins[i] for i in order]


@settings(
    max_examples=30,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)
@given(
    data=window_partitions(),
    compress=st.sampled_from(["deflate", "none"]),
    tile=st.sampled_from([16, 64]),
    overviews=st.sampled_from([0, 2]),
)
def test_stream_writer_partition_property(
    tmp_path_factory, data, compress, tile, overviews
):
    """ANY exhaustive rectangular partition, pushed in ANY order, decodes
    identically to the one-shot writer — including the overview pages
    (checked via the multi-page walker, since read_geotiff skips them)."""
    from land_trendr_tpu.io.geotiff import GeoTiffStreamWriter

    from test_geotiff import _walk_pages

    arr, wins = data
    h, w, bands = arr.shape
    d = tmp_path_factory.mktemp("sprop")
    ps, po = str(d / "s.tif"), str(d / "o.tif")
    with GeoTiffStreamWriter(
        ps, h, w, bands, np.uint8, compress=compress, tile=tile,
        overviews=overviews,
    ) as wr:
        for y0, x0, wh, ww in wins:
            wr.write(y0, x0, arr[y0 : y0 + wh, x0 : x0 + ww])
    write_geotiff(
        po, np.moveaxis(arr, -1, 0), compress=compress, tile=tile,
        overviews=overviews, resampling="nearest",
    )
    got_s, _, _ = read_geotiff(ps)
    got_o, _, _ = read_geotiff(po)
    np.testing.assert_array_equal(got_s, got_o)
    assert _walk_pages(ps) == _walk_pages(po)
