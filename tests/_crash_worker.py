"""Worker process for the crash-resume test (SIGKILL mid-run).

Run as: ``python _crash_worker.py <workdir>``.  Builds the SAME
deterministic synthetic stack as ``tests/test_faults.py``'s parent and
runs the real production driver over it, with a ``slow`` fault schedule
that paces every dispatch from tile 2 on — giving the parent a wide,
reliable window to SIGKILL the process after the first artifacts have
landed but before the run completes.  The parent then resumes in-process
and asserts the merged artifacts are byte-identical to an uninterrupted
run (the manifest-is-the-checkpoint contract under a hard crash).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# Must beat the sitecustomize's jax_platforms="axon,cpu" config selection
# *before* any device/backend touch, or a down TPU tunnel hangs the worker.
jax.config.update("jax_platforms", "cpu")


def main() -> int:
    workdir = sys.argv[1]

    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.io.synthetic import SceneSpec, make_stack
    from land_trendr_tpu.runtime import RunConfig, run_stack, stack_from_synthetic

    spec = SceneSpec(width=48, height=40, year_start=1990, year_end=2013, seed=11)
    rs = stack_from_synthetic(make_stack(spec))
    cfg = RunConfig(
        params=LTParams(max_segments=4, vertex_count_overshoot=2),
        tile_size=20,
        workdir=workdir,
        out_dir=workdir + "_o",
        retry_backoff_s=0.0,
        # every dispatch from tile 2 on sleeps 0.6s then proceeds: the
        # kill window after the first artifact is >= 2s wide
        fault_schedule="seed=1,dispatch@2*999=slow:0.6",
    )
    run_stack(rs, cfg)
    print("DONE")
    return 0


if __name__ == "__main__":
    sys.exit(main())
