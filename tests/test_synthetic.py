"""Synthetic-stack generator: physical plausibility + file round-trip."""

import numpy as np

from land_trendr_tpu.io.geotiff import read_geotiff
from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack
from land_trendr_tpu.ops.indices import BANDS


def test_stack_shapes_and_truth():
    spec = SceneSpec(width=64, height=48, cloud_fraction=0.05)
    st = make_stack(spec)
    ny = spec.year_end - spec.year_start + 1
    assert st.years.shape == (ny,)
    for b in BANDS:
        assert st.bands[b].shape == (ny, 48, 64)
    assert st.qa.shape == (ny, 48, 64)
    frac = (st.truth_year >= 0).mean()
    assert 0.2 < frac < 0.4  # ~disturbance_fraction
    assert (st.truth_magnitude[st.truth_year >= 0] > 0).all()
    assert (st.truth_magnitude[st.truth_year < 0] == 0).all()


def test_disturbance_drops_nbr():
    st = make_stack(SceneSpec(width=64, height=64, cloud_fraction=0.0, noise=0.0))
    nir, swir2 = st.bands["nir"], st.bands["swir2"]
    nbr = (nir - swir2) / (nir + swir2)
    dist = st.truth_year >= 0
    # pick disturbed pixels whose event is mid-series
    yy = st.truth_year[dist]
    sel = (yy > st.years[5]) & (yy < st.years[-5])
    pre = nbr[0][dist][sel]
    # NBR immediately after event (year index of event per pixel)
    yidx = np.searchsorted(st.years, yy[sel])
    cols = np.flatnonzero(dist.ravel())[sel]
    post = nbr.reshape(len(st.years), -1)[yidx, cols]
    assert (pre - post > 0.2).mean() > 0.95


def test_fill_margins_marked_and_nodata():
    st = make_stack(SceneSpec(width=128, height=32))
    fill = (st.qa & 1) != 0
    assert fill.any()  # some years have nonzero margins
    # fill pixels carry the nodata reflectance (DN 0 after C2 encoding)
    assert (st.dn("nir")[fill] == np.round(0.2 / 2.75e-5)).all() or (
        st.bands["nir"][fill] == np.float32(-0.2)
    ).all()


def test_cloud_qa_marks_bright_pixels():
    st = make_stack(SceneSpec(width=32, height=32, cloud_fraction=0.2))
    cloudy = (st.qa & (1 << 3)) != 0
    assert 0.15 < cloudy.mean() < 0.25
    assert st.bands["blue"][cloudy].mean() > 10 * st.bands["blue"][~cloudy].mean()


def test_dn_encoding_roundtrip():
    st = make_stack(SceneSpec(width=16, height=16))
    dn = st.dn("nir")
    assert dn.dtype == np.int16
    back = dn.astype(np.float32) * 2.75e-5 - 0.2
    in_range = st.bands["nir"] <= 32767 * 2.75e-5 - 0.2  # clouds can saturate
    assert in_range.mean() > 0.9
    np.testing.assert_allclose(back[in_range], st.bands["nir"][in_range], atol=2.75e-5)


def test_write_stack_roundtrip(tmp_path):
    spec = SceneSpec(width=40, height=24, year_start=2000, year_end=2005)
    st = make_stack(spec)
    paths = write_stack(str(tmp_path), st, tile=16)
    assert len(paths) == 6
    arr, geo, info = read_geotiff(paths[0])
    assert arr.shape == (7, 24, 40)  # 6 SR bands + QA
    assert info.dtype == np.dtype("i2")
    np.testing.assert_array_equal(arr[:6], np.stack([st.dn(b)[0] for b in BANDS]))
    np.testing.assert_array_equal(arr[6].astype(np.uint16), st.qa[0])
    assert geo.pixel_scale == (30.0, 30.0, 0.0)
