"""Capacity-planner tests (ISSUE 16): the load rig's deterministic
half, the knee algebra, and the offline replay equivalence.

Pins the contracts the live capacity bench rests on, without spawning a
fleet (everything here is seconds-scale and jax-free):

* **seeded traces**: the same :class:`LoadConfig` regenerates the same
  trace byte for byte — arrival times, tenant sequence, pinned trace
  ids — and the heavy-tail / diurnal-wave shape knobs do what they say;
* **knee algebra**: the Kneedle construction on synthetic curves — a
  hockey stick knees at the bend, a straight line has no knee, and
  :func:`mark_knee` stamps the blame name from the assembled split;
* **offline replay**: a scripted decision history replays
  byte-identically through the same pure machines, and ONE tampered
  byte is caught with its seq pinned — the simulator is an equivalence
  check, not a formality;
* **vocabulary non-drift**: the events-lint copies of the mode and
  blame vocabularies stay equal to their owning modules';
* **fleet-top aggregation**: histogram reconstruction from exposition
  text round-trips through :func:`merge_instruments` into the
  :func:`histogram_quantile` header numbers;
* the committed fixture stays schema-clean and the committed
  ``CAPACITY_r*.json`` validates (the perf gate's curve leg re-checks
  the replay claims against the live simulator).
"""

import json
import os
import sys

import pytest

from land_trendr_tpu.fleet.capacity import (
    REPORT_SCHEMA,
    assemble_sweep,
    dominant_blame,
    find_knee,
    mark_knee,
    percentile,
    replay_decisions,
    validate_report,
    write_scripted_history,
)
from land_trendr_tpu.loadgen import LoadConfig, build_trace
from land_trendr_tpu.loadgen.config import LOAD_MODES as CFG_LOAD_MODES
from land_trendr_tpu.loadgen.trace import SHAPE_PARAMS, SHAPES, rate_at, tenant_weights
from land_trendr_tpu.obs.aggregate import (
    histogram_quantile,
    merge_instruments,
)
from land_trendr_tpu.obs.events import validate_events_file
from land_trendr_tpu.obs.reqtrace import BLAME_PRIORITY

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import check_events_schema as ces  # noqa: E402
import lt_top  # noqa: E402

REPO = os.path.join(os.path.dirname(__file__), "..")
FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "capacity.events.jsonl")


# -- seeded traces ---------------------------------------------------------
def test_trace_regenerates_byte_identical():
    cfg = LoadConfig(
        mode="open", duration_s=120.0, qps=3.0, seed=42, tenants=4,
        tenant_skew=1.0, wave_amp=0.4, wave_period_s=30.0,
    )
    assert build_trace(cfg) == build_trace(cfg)
    # a different seed is a different trace (ids AND arrivals)
    other = build_trace(LoadConfig(**{
        **{f.name: getattr(cfg, f.name) for f in cfg.__dataclass_fields__.values()},
        "seed": 43,
    }))
    assert other != build_trace(cfg)


def test_trace_ids_pin_seed_and_ordinal():
    cfg = LoadConfig(mode="closed", duration_s=5.0, requests=10, seed=0xBEEF)
    trace = build_trace(cfg)
    assert len(trace) == 10
    assert [r.trace_id for r in trace] == [
        f"lg0000beef{i:06x}" for i in range(10)
    ]
    assert len({r.trace_id for r in trace}) == 10
    # closed-loop entries arrive when a worker frees up, not on a clock
    assert all(r.at_s == 0.0 for r in trace)


def test_open_loop_arrivals_sorted_inside_window():
    cfg = LoadConfig(mode="open", duration_s=200.0, qps=2.0, seed=7)
    trace = build_trace(cfg)
    ats = [r.at_s for r in trace]
    assert ats == sorted(ats)
    assert all(0.0 <= t < cfg.duration_s for t in ats)
    # a Poisson window this long lands near its mean offered count
    assert 0.5 * cfg.qps * cfg.duration_s < len(trace) < 1.5 * cfg.qps * cfg.duration_s
    # the requests budget truncates, preserving the prefix
    cut = build_trace(LoadConfig(
        mode="open", duration_s=200.0, qps=2.0, seed=7, requests=5,
    ))
    assert cut == trace[:5]


def test_tenant_mix_heavy_tailed():
    cfg = LoadConfig(mode="closed", duration_s=5.0, requests=400,
                     seed=3, tenants=4, tenant_skew=1.0)
    counts: dict = {}
    for r in build_trace(cfg):
        counts[r.tenant] = counts.get(r.tenant, 0) + 1
    # 1/k weights: t0 strictly dominates, the tail is still present
    assert counts["t0"] > counts["t3"]
    assert set(counts) == {"t0", "t1", "t2", "t3"}
    assert tenant_weights(cfg) == [1.0, 0.5, 1.0 / 3.0, 0.25]
    uniform = LoadConfig(mode="closed", duration_s=5.0, tenants=4,
                         tenant_skew=0.0)
    assert tenant_weights(uniform) == [1.0] * 4


def test_diurnal_wave_bounds_and_flat_schedule():
    cfg = LoadConfig(mode="open", qps=4.0, wave_amp=0.5, wave_period_s=60.0)
    rates = [rate_at(cfg, t) for t in range(0, 120, 5)]
    assert all(cfg.qps * 0.5 <= r <= cfg.qps * 1.5 for r in rates)
    assert max(rates) > cfg.qps * 1.3 and min(rates) < cfg.qps * 0.7
    flat = LoadConfig(mode="open", qps=4.0, wave_amp=0.0)
    assert all(rate_at(flat, t) == 4.0 for t in range(0, 120, 7))


def test_config_rejects_nonsense():
    with pytest.raises(ValueError):
        LoadConfig(mode="bursty")
    with pytest.raises(ValueError):
        LoadConfig(wave_amp=1.0)  # negative trough rate
    with pytest.raises(ValueError):
        LoadConfig(qps=0.0)
    with pytest.raises(ValueError):
        LoadConfig(workers=0)


def test_shape_vocabulary_maps_to_params():
    assert set(SHAPES) == set(SHAPE_PARAMS)
    assert all("max_segments" in p for p in SHAPE_PARAMS.values())


# -- knee algebra ----------------------------------------------------------
def test_find_knee_hockey_stick():
    # flat then exploding p99: the knee is the last flat point
    pts = [(0.5, 1.0), (1.0, 1.1), (2.0, 1.3), (4.0, 9.0)]
    assert find_knee(pts) == 2


def test_find_knee_degenerate_cases():
    assert find_knee([(1.0, 1.0), (2.0, 2.0)]) is None  # < 3 points
    assert find_knee([(1.0, 5.0), (2.0, 5.0), (3.0, 5.0)]) is None  # flat
    # straight line: no interior point rises above the chord
    assert find_knee([(1.0, 1.0), (2.0, 2.0), (3.0, 3.0)]) is None


def test_mark_knee_stamps_blame_from_split():
    points = [
        {"offered_qps": 0.5, "p99_s": 1.0, "blame": {"compute": 3.0}},
        {"offered_qps": 1.0, "p99_s": 1.1,
         "blame": {"replica_queue": 9.0, "compute": 2.0}},
        {"offered_qps": 2.0, "p99_s": 1.3, "blame": {"compute": 2.0}},
        {"offered_qps": 4.0, "p99_s": 9.0, "blame": {"compute": 2.0}},
    ]
    idx = mark_knee(points)
    assert idx == 2
    assert points[2]["knee"] is True
    assert points[2]["knee_blame"] == "compute"
    assert "knee" not in points[1]


def test_dominant_blame_priority_tiebreak():
    assert dominant_blame({}) == "other"
    assert dominant_blame({"compute": 5.0, "fetch": 1.0}) == "compute"
    # equal seconds: the earlier PR-15 priority component wins
    assert dominant_blame({"compute": 2.0, "route_queue": 2.0}) == "route_queue"


def test_percentile_interpolates():
    assert percentile([], 99.0) == 0.0
    assert percentile([4.0], 50.0) == 4.0
    assert percentile([1.0, 2.0, 3.0, 4.0], 50.0) == 2.5
    assert percentile([1.0, 2.0, 3.0, 4.0], 100.0) == 4.0


# -- offline replay --------------------------------------------------------
def test_scripted_history_replays_byte_identical(tmp_path):
    path = str(tmp_path / "decisions.jsonl")
    meta = write_scripted_history(path, seed=23, events=400)
    assert meta["records"] == 400
    rep = replay_decisions(path)
    assert rep.match and rep.mismatch_seq is None
    assert rep.decisions == rep.matched > 0
    assert rep.recorded_span_s > 0
    # same seed → same log, byte for byte
    path2 = str(tmp_path / "again.jsonl")
    write_scripted_history(path2, seed=23, events=400)
    assert open(path).read() == open(path2).read()


def test_tampered_history_caught_with_seq(tmp_path):
    path = str(tmp_path / "decisions.jsonl")
    write_scripted_history(path, seed=5, events=300)
    recs = [json.loads(line) for line in open(path)]
    victim = next(r for r in recs if r["kind"] == "pick")
    victim["job_id"] += "-tampered"
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    rep = replay_decisions(path)
    assert not rep.match
    assert rep.mismatch_seq == victim["seq"]
    assert rep.mismatch["kind"] == "pick"


def test_assemble_sweep_empty_store(tmp_path):
    # no trace files at all: nothing assembles, nothing crashes
    out = assemble_sweep(str(tmp_path), ["lg00000000000000"])
    assert out == {"assembled": 0, "latencies": [], "blame": {}}


# -- vocabulary non-drift --------------------------------------------------
def test_lint_vocabularies_track_owners():
    assert ces.LOAD_MODES == CFG_LOAD_MODES
    assert ces.KNEE_BLAME_COMPONENTS == (*BLAME_PRIORITY, "other")


def test_capacity_value_lints_positive_and_negative():
    ok = {"ev": "sweep_point", "replicas": 2, "offered_qps": 1.0,
          "achieved_qps": 1.0, "p50_s": 1.0, "p99_s": 2.0,
          "goodput_qps": 1.0, "done": 5, "failed": 0, "rejected": 0}
    assert ces.capacity_value_errors(ok, 1) == []
    bad_q = dict(ok, p99_s=0.5)
    assert any("p99_s" in e for e in ces.capacity_value_errors(bad_q, 1))
    bad_b = dict(ok, knee_blame="gremlins")
    assert any("vocabulary" in e for e in ces.capacity_value_errors(bad_b, 1))
    zero = {"ev": "load_phase", "phase": "x_start", "mode": "open",
            "offered_qps": 0.0}
    assert any("strictly positive" in e
               for e in ces.capacity_value_errors(zero, 1))
    lying = {"ev": "sim_replay", "decisions": 10, "matched": 9,
             "match": True, "speedup_x": 5.0}
    assert ces.capacity_value_errors(lying, 1)


def test_capacity_fixture_schema_clean():
    assert validate_events_file(FIXTURE, extra=ces.value_lints()) == []


# -- report schema ---------------------------------------------------------
def _minimal_point(**over):
    p = {"replicas": 1, "offered_qps": 1.0, "achieved_qps": 1.0,
         "p50_s": 1.0, "p99_s": 2.0, "goodput_qps": 1.0,
         "done": 3, "failed": 0, "rejected": 0}
    p.update(over)
    return p


def test_validate_report_positive_and_negative():
    good = {
        "schema": REPORT_SCHEMA,
        "curves": [{"replicas": 1, "points": [_minimal_point()]}],
        "replay": {"decisions": 1, "matched": 1, "match": True,
                   "speedup_x": 500.0},
    }
    assert validate_report(good) == []
    assert validate_report({"schema": "nope"})
    assert any("p99_s below" in e for e in validate_report({
        "schema": REPORT_SCHEMA,
        "curves": [{"replicas": 1,
                    "points": [_minimal_point(p99_s=0.1)]}],
        "replay": good["replay"],
    }))
    assert any("knee_blame" in e for e in validate_report({
        "schema": REPORT_SCHEMA,
        "curves": [{"replicas": 1,
                    "points": [_minimal_point(knee_blame="gremlins")]}],
        "replay": good["replay"],
    }))
    assert any("replay" in e for e in validate_report({
        "schema": REPORT_SCHEMA,
        "curves": [{"replicas": 1, "points": [_minimal_point()]}],
    }))


def test_committed_capacity_report_validates():
    path = os.path.join(REPO, "CAPACITY_r17.json")
    report = json.load(open(path))
    assert validate_report(report) == []
    replicas = [c["replicas"] for c in report["curves"]]
    assert len(set(replicas)) >= 3
    for curve in report["curves"]:
        knees = [p for p in curve["points"] if p.get("knee")]
        assert knees and all(
            p["knee_blame"] in (*BLAME_PRIORITY, "other") for p in knees
        )
    assert report["replay"]["match"] is True
    assert report["scripted_replay"]["match"] is True
    assert report["scripted_replay"]["speedup_x"] >= 100.0


# -- fleet-top histogram aggregation ---------------------------------------
_EXPO = """\
# TYPE lt_serve_job_seconds histogram
lt_serve_job_seconds_bucket{le="0.5"} 1
lt_serve_job_seconds_bucket{le="2.0"} 3
lt_serve_job_seconds_bucket{le="+Inf"} 4
lt_serve_job_seconds_sum 5.5
lt_serve_job_seconds_count 4
"""


def test_prom_instruments_reconstructs_histogram():
    insts = lt_top.prom_instruments(_EXPO)
    hist = next(m for m in insts if m["kind"] == "histogram")
    assert hist["name"] == "lt_serve_job_seconds"
    assert hist["bounds"] == [0.5, 2.0]
    assert hist["buckets"] == [1, 2, 1]  # de-cumulated, +Inf last
    assert hist["count"] == 4 and hist["sum"] == 5.5


def test_prom_instruments_drops_torn_series():
    torn = _EXPO.replace('le="2.0"} 3', 'le="2.0"} 0')  # cum must not dip
    assert not [m for m in lt_top.prom_instruments(torn)
                if m["kind"] == "histogram"]


def test_merged_histogram_quantiles():
    insts = lt_top.prom_instruments(_EXPO)
    merged, conflicts = merge_instruments([(1.0, insts), (2.0, insts)])
    assert conflicts == []
    hist = next(m for m in merged if m["kind"] == "histogram")
    assert hist["count"] == 8 and hist["buckets"] == [2, 4, 2]
    p50 = histogram_quantile(hist, 0.50)
    assert 0.5 <= p50 <= 2.0
    # the +Inf bucket answers with the highest finite bound
    assert histogram_quantile(hist, 0.99) == 2.0


def test_lt_load_cli_parses_shape_flags():
    from land_trendr_tpu.cli import build_parser

    args = build_parser().parse_args([
        "load", "--router-url", "http://127.0.0.1:1", "--stack-dir", "x",
        "--mode", "open", "--qps", "3", "--wave-amp", "0.4",
        "--tenant-skew", "1.5", "--seed", "7",
    ])
    assert args.cmd == "load"
    assert (args.mode, args.qps, args.wave_amp) == ("open", 3.0, 0.4)


@pytest.mark.slow
def test_capacity_bench_smoke_cli(tmp_path):
    """The full smoke leg: live 2-fleet sweep + knees + replay.  Slow
    (spawned jax replica processes) — CLI gate runs carry it."""
    import capacity_bench

    out = tmp_path / "cap.json"
    assert capacity_bench.main([
        "--smoke", "--keep", str(tmp_path / "wd"), "--out", str(out),
    ]) == 0
    rep = json.loads(out.read_text())
    assert rep["ok"] is True and rep["smoke"] is True
    assert validate_report(rep) == []


def test_histogram_quantile_edge_cases():
    assert histogram_quantile({"bounds": [], "buckets": [], "count": 0},
                              0.5) is None
    assert histogram_quantile({"bounds": [1.0], "buckets": [2],
                               "count": 2}, 0.5) is None  # shape mismatch
    one = {"bounds": [1.0, 2.0], "buckets": [0, 4, 0], "count": 4}
    assert histogram_quantile(one, 0.5) == pytest.approx(1.5)
