"""Contract tests for the repo-root entry points (bench.py, __graft_entry__).

The driver consumes both: bench.py must print exactly one JSON line with the
agreed schema; entry() must be jittable single-chip; dryrun_multichip(n)
must compile and run the fully-sharded step (here on the virtual 8-device
CPU mesh the conftest provides).
"""

import json
import math
import subprocess
import sys

import jax
import numpy as np
import pytest


def test_entry_compiles_and_runs():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out.fitted.shape == (args[1].shape[0], args[1].shape[1])
    assert np.asarray(out.model_valid).mean() > 0.5


@pytest.mark.parametrize("n", [2, 8])
def test_dryrun_multichip(n):
    import __graft_entry__ as g

    g.dryrun_multichip(n)  # asserts internally


def test_dryrun_rejects_oversized_mesh():
    import __graft_entry__ as g

    with pytest.raises(RuntimeError, match="need 64 devices"):
        g.dryrun_multichip(64)


def test_bench_emits_single_json_line():
    proc = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True,
        text=True,
        timeout=300,
        env={
            "PATH": "/usr/bin:/bin",
            "JAX_PLATFORMS": "cpu",
            "LT_BENCH_PX": "64",
            "LT_BENCH_YEARS": "12",
            "LT_BENCH_REPS": "1",
        },
        cwd=".",
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must be one JSON line, got: {proc.stdout!r}"
    rec = json.loads(lines[0])
    # required schema; provenance extras (px, platform, chunked) allowed
    assert {"metric", "value", "unit", "vs_baseline"} <= set(rec)
    assert rec["unit"] == "pixels/sec/chip"
    assert rec["value"] > 0
    # both fields are independently rounded (value to 0.1, ratio to 1e-4)
    assert rec["vs_baseline"] == pytest.approx(rec["value"] / 10e6, abs=1.1e-4)


def test_bench_chain_mode_emits_single_json_line():
    """The accelerator-default chain mode (lax.fori_loop of data-dependent
    kernel applications with a TRACED length, so the paired-K long and
    short windows share one compiled cache entry) must run end to end;
    the driver's round-end TPU bench takes this path."""
    proc = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True,
        text=True,
        timeout=300,
        env={
            "PATH": "/usr/bin:/bin",
            "JAX_PLATFORMS": "cpu",
            "LT_BENCH_PX": "64",
            "LT_BENCH_YEARS": "12",
            "LT_BENCH_REPS": "2",
            "LT_BENCH_MODE": "chain",
            "LT_BENCH_CHAIN_K": "3",
        },
        cwd=".",
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must be one JSON line, got: {proc.stdout!r}"
    rec = json.loads(lines[0])
    assert {"metric", "value", "unit", "vs_baseline"} <= set(rec)
    assert rec["mode"] == "chain" and rec["chain_k"] == 3
    assert rec["value"] > 0
    # the paired-K reporting contract round artifacts/tools consume:
    # both rates, the delta provenance, and a methodology note
    assert rec["value_lower_bound"] > 0
    assert rec["k_short"] == 1  # max(1, 3 // 8)
    # presence + finiteness only: at px=64/K=3 the delta magnitude is
    # ~2 ms, and one scheduler stall inside a short window can
    # legitimately drive it <= 0 (bench falls back to the lower bound
    # by design) — the sign is not a contract
    assert isinstance(rec["median_delta_s"], float)
    assert math.isfinite(rec["median_delta_s"])
    assert "note" in rec
    # the reported value never contradicts the proven window bound
    # (clamped or not, value >= value_lower_bound by construction;
    # both round to 0.1 so the comparison survives rounding)
    assert rec["value"] >= rec["value_lower_bound"]


def test_bench_chain_mode_through_chunked_kernel():
    """Chain mode over the CHUNKED kernel — the exact configuration the
    driver's round-end bench hits with its 1M-px default (px > chunk)."""
    proc = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True,
        text=True,
        timeout=300,
        env={
            "PATH": "/usr/bin:/bin",
            "JAX_PLATFORMS": "cpu",
            "LT_BENCH_PX": "1024",
            "LT_BENCH_CHUNK": "256",
            "LT_BENCH_YEARS": "12",
            "LT_BENCH_REPS": "1",
            "LT_BENCH_MODE": "chain",
            "LT_BENCH_CHAIN_K": "2",
        },
        cwd=".",
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["chunked"] is True and rec["mode"] == "chain"
    assert rec["value"] > 0
