"""Flight recorder + live debug surface tests (ISSUE 9).

Pins the introspection layer's contracts:

* the **ring** mirrors every telemetry emit, stays bounded, and dumps as
  a schema-valid ``events.jsonl`` slice even after the scope's
  ``run_start`` has been evicted (the sticky-header property);
* the **sampler** emits schema-valid ``flight_sample`` events with the
  process vitals required and host probe gauges merged in — and a sick
  probe degrades the sample, never the run;
* a standalone ``--flight`` run dumps ``flight.jsonl`` under the
  workdir, lint-clean, with a non-empty sampler series;
* the serve ``/debug`` surface: ``/debug/stacks`` answers (showing the
  wedged frame) **while a hang fault is armed**, ``POST /debug/profile``
  against a server running a real job produces a loadable profiler
  trace under the workdir, a ``debug.profile`` fault fails the capture
  (``ok=false``) but never the job, ``/debug/jobs`` exposes live run
  progress, and ``debug_endpoints=False`` is a 404 wall;
* per-job SLO: ``deadline_s`` is accounting (``job_slo`` events,
  ``lt_slo_*`` instruments, ``deadline_exceeded`` in the snapshot) —
  the job still runs to its natural terminal state;
* ``/healthz`` carries the load-balancer facts, and ``lt top --once``
  renders a live server;
* the new value lints catch a broken SLO split and negative sampler
  gauges; ``obs_report`` folds the SLO and resource sections.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from land_trendr_tpu.cli import main as cli_main
from land_trendr_tpu.io.synthetic import SceneSpec, make_stack, write_stack
from land_trendr_tpu.obs.events import EventLog, validate_events_file
from land_trendr_tpu.obs.flight import (
    FlightRecorder,
    ResourceSampler,
    thread_stacks,
)
from land_trendr_tpu.serve import SegmentationServer, ServeConfig

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

#: same scene shape as tests/test_serve.py, so the process-wide jit
#: cache keeps every server after the first warm
_PARAM_FLAGS = ["--max-segments", "4", "--vertex-count-overshoot", "2"]
_PARAMS = {"max_segments": 4, "vertex_count_overshoot": 2}
_TILE = 20


@pytest.fixture(scope="module")
def stack_dir(tmp_path_factory) -> str:
    d = str(tmp_path_factory.mktemp("flight_stack") / "stack")
    write_stack(
        d,
        make_stack(
            SceneSpec(width=40, height=40, year_start=2000, year_end=2008,
                      seed=3)
        ),
    )
    return d


def _job(stack_dir: str, **kw) -> dict:
    return {
        "stack_dir": stack_dir,
        "tile_size": _TILE,
        "params": dict(_PARAMS),
        **kw,
    }


def _get(port: int, path: str) -> tuple[int, dict]:
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            return e.code, json.loads(body)
        except json.JSONDecodeError:
            return e.code, {}


def _post(port: int, path: str, payload) -> tuple[int, dict]:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            return e.code, json.loads(body)
        except json.JSONDecodeError:
            return e.code, {}


# ---------------------------------------------------------------------------
# the ring


def test_ring_mirrors_emits_and_dumps_schema_valid(tmp_path):
    ring = FlightRecorder(capacity=8)
    log = EventLog(str(tmp_path / "events.jsonl"), mirror=ring.record)
    log.run_start(
        fingerprint="t", process_index=0, process_count=1, tiles_total=99,
        tiles_todo=99, tiles_skipped_resume=0, mesh_devices=1, impl="xla",
    )
    for i in range(20):  # far past capacity: run_start evicted
        log.emit("tile_start", tile_id=i, attempt=1)
    log.close()

    stats = ring.stats()
    assert stats["capacity"] == 8
    assert stats["events"] == 8
    assert stats["recorded_total"] == 21
    assert stats["dropped"] == 13
    # snapshot: bounded window, oldest first, n-limit honored
    snap = ring.snapshot()
    assert len(snap) == 8 and snap[-1]["tile_id"] == 19
    assert [r["tile_id"] for r in ring.snapshot(3)] == [17, 18, 19]

    # dump: the sticky run_start re-heads the slice, so the dump passes
    # the SAME schema lint as a real stream — the acceptance property
    dump = tmp_path / "flight.jsonl"
    n = ring.dump(str(dump))
    assert n == 9  # 8 ring entries + the re-headed run_start
    assert validate_events_file(str(dump)) == []
    first = json.loads(dump.read_text().splitlines()[0])
    assert first["ev"] == "run_start" and first["tiles_total"] == 99


def test_ring_dump_trims_orphaned_tail_instead_of_duplicating_header(
    tmp_path,
):
    """Multi-scope ring (the serve shared-ring shape): when a later
    scope's ``run_start`` is still IN the ring, the dump must open at it
    — prepending the sticky copy above the previous scope's tail would
    duplicate the header and re-anchor that tail under the wrong scope's
    clocks."""
    ring = FlightRecorder(capacity=8)
    log = EventLog(str(tmp_path / "events.jsonl"), mirror=ring.record)
    rs = dict(
        fingerprint="t", process_index=0, process_count=1, tiles_total=1,
        tiles_todo=1, tiles_skipped_resume=0, mesh_devices=1, impl="xla",
    )
    log.run_start(**rs)
    for i in range(6):  # scope 1 traffic; its run_start gets evicted
        log.emit("tile_start", tile_id=i, attempt=1)
    log.run_start(**rs)  # scope 2 opens mid-ring
    log.emit("tile_start", tile_id=100, attempt=1)
    log.close()

    dump = ring.dump_records()
    assert [r["ev"] for r in dump].count("run_start") == 1
    assert dump[0]["ev"] == "run_start"
    assert dump[-1]["tile_id"] == 100
    path = tmp_path / "flight.jsonl"
    ring.dump(str(path))
    assert validate_events_file(str(path)) == []


def test_ring_capacity_validation():
    with pytest.raises(ValueError, match="capacity"):
        FlightRecorder(capacity=1)


# ---------------------------------------------------------------------------
# the sampler


def test_sampler_emits_schema_valid_samples_with_probes(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path)
    log.run_start(
        fingerprint="t", process_index=0, process_count=1, tiles_total=0,
        tiles_todo=0, tiles_skipped_resume=0, mesh_devices=1, impl="xla",
    )
    sampler = ResourceSampler(
        log.emit, interval_s=60.0,
        probes=lambda: {"queue_depth": 3, "cache_bytes": 123,
                        "skipped": None},
    )
    fields = sampler.sample()
    assert fields["threads"] >= 1
    assert fields["rss_bytes"] >= 0 and fields["open_fds"] >= 0
    assert fields["queue_depth"] == 3 and fields["cache_bytes"] == 123
    assert "skipped" not in fields  # None-valued probe gauges drop out

    # a sick probe degrades to the base sample — never raises
    def bad_probes():
        raise RuntimeError("probe exploded")

    sampler._probes = bad_probes
    fields = sampler.sample()
    assert fields["threads"] >= 1 and "queue_depth" not in fields
    log.close()
    assert validate_events_file(path) == []

    with pytest.raises(ValueError, match="interval_s"):
        ResourceSampler(log.emit, interval_s=0)


def test_thread_stacks_sees_other_threads():
    gate = threading.Event()
    started = threading.Event()

    def parked():
        started.set()
        gate.wait(30)

    t = threading.Thread(target=parked, name="lt-test-parked", daemon=True)
    t.start()
    try:
        assert started.wait(10)
        stacks = thread_stacks()
        mine = [k for k in stacks if "lt-test-parked" in k]
        assert mine, f"parked thread missing from {list(stacks)}"
        frames = stacks[mine[0]]
        assert any("parked" in line for line in frames)
        # the caller's own thread is visible too
        assert any("MainThread" in k for k in stacks)
    finally:
        gate.set()
        t.join(timeout=10)


# ---------------------------------------------------------------------------
# standalone --flight runs


def test_run_flight_dumps_and_lints_clean(stack_dir, tmp_path):
    from land_trendr_tpu.config import LTParams
    from land_trendr_tpu.runtime import RunConfig, load_stack_dir, run_stack
    from land_trendr_tpu.ops.indices import required_bands

    wd = str(tmp_path / "w")
    cfg = RunConfig(
        params=LTParams(**_PARAMS), tile_size=_TILE,
        workdir=wd, out_dir=str(tmp_path / "o"),
        telemetry=True, flight=True,
        sampler_interval_s=0.05, flight_ring_events=64,
    )
    stack = load_stack_dir(stack_dir, bands=required_bands("nbr", ()))
    summary = run_stack(stack, cfg)
    flight_file = summary["telemetry"]["flight"]
    assert flight_file == os.path.join(wd, "flight.jsonl")

    from check_events_schema import main as lint_main

    # both the stream AND the ring dump pass the full value-lint chain
    assert lint_main([wd]) == 0
    assert lint_main([flight_file]) == 0
    stream = [json.loads(l) for l in open(summary["telemetry"]["events"])]
    dump = [json.loads(l) for l in open(flight_file)]
    assert dump[0]["ev"] == "run_start"
    assert any(e["ev"] == "flight_sample" for e in stream)
    assert any(e["ev"] == "flight_sample" for e in dump)
    # the dump's tail is the stream's tail (the ring mirrors the log)
    assert dump[-1]["ev"] == "run_done"
    sample = next(e for e in stream if e["ev"] == "flight_sample")
    for req in ("rss_bytes", "open_fds", "threads"):
        assert req in sample
    assert "feed_backlog" in sample and "cache_bytes" in sample


def test_flight_config_validation():
    from land_trendr_tpu.runtime import RunConfig

    with pytest.raises(ValueError, match="flight requires telemetry"):
        RunConfig(flight=True)
    with pytest.raises(ValueError, match="flight_ring_events"):
        RunConfig(telemetry=True, flight=True, flight_ring_events=1)
    with pytest.raises(ValueError, match="sampler_interval_s"):
        RunConfig(telemetry=True, flight=True, sampler_interval_s=0)
    with pytest.raises(ValueError, match="flight_ring_events"):
        ServeConfig(flight_ring_events=1)
    with pytest.raises(ValueError, match="sampler_interval_s"):
        ServeConfig(sampler_interval_s=0)
    # 0 disables the ring + sampler on BOTH surfaces (the serve
    # convention) — run mode must not reject the same spelling
    RunConfig(telemetry=True, flight=True, flight_ring_events=0)
    RunConfig(flight_ring_events=0)
    ServeConfig(flight_ring_events=0)


# ---------------------------------------------------------------------------
# the serve /debug surface — live server, real job, armed hang fault


def test_debug_surface_on_live_wedged_server(stack_dir, tmp_path):
    """The acceptance scenario end to end: while a ``hang`` fault wedges
    the dispatcher mid-job, ``/debug/stacks`` answers and shows the
    wedged frame; ``POST /debug/profile`` captures a loadable trace
    under the workdir; a ``debug.profile`` fault fails a capture with
    ``ok=false``; ``/debug/jobs`` exposes live progress; and the job
    still finishes ``done`` with its SLO accounted."""
    srv_dir = str(tmp_path / "srv")
    server = SegmentationServer(
        ServeConfig(
            workdir=srv_dir,
            feed_cache_mb=32,
            sampler_interval_s=0.1,
            # dispatch#0 is the warm probe; hanging the first two
            # dispatches holds the debug window open.  debug.profile@1
            # fails the SECOND capture only.
            fault_schedule="seed=1,dispatch@0*2=hang:1.0,debug.profile@1",
        )
    )
    snap = server.submit(
        _job(stack_dir, deadline_s=0.001)  # SLO miss by construction
    )
    t = threading.Thread(target=server.serve_forever, name="lt-dispatcher")
    t.start()
    try:
        # /debug/stacks responds WHILE the hang fault is armed and shows
        # the dispatcher wedged inside the injected hang
        deadline = time.monotonic() + 60
        wedged = False
        while time.monotonic() < deadline and not wedged:
            st, body = _get(server.port, "/debug/stacks")
            assert st == 200
            wedged = any(
                any("_hang" in line for line in frames)
                for frames in body["threads"].values()
            )
            if not wedged:
                time.sleep(0.05)
        assert wedged, "dispatcher never seen wedged in the armed hang"

        # live job state with run progress
        st, body = _get(server.port, "/debug/jobs")
        assert st == 200
        job = body["jobs"][0]
        assert job["state"] == "running"
        assert job["progress"]["tiles_total"] == 4
        assert job["progress"]["phase"] in (
            "setup", "warmup", "pipeline", "drain"
        )

        # the flight ring shows the live story (server + job events)
        st, body = _get(server.port, "/debug/flight?n=100")
        assert st == 200
        kinds = [e["ev"] for e in body["events"]]
        assert "job_submitted" in kinds or "job_start" in kinds
        assert body["capacity"] == 2048
        # ring occupancy survives beside the (possibly n-truncated) list
        assert body["held"] >= len(body["events"])

        # on-demand profile of the RUNNING job: loadable trace under the
        # workdir (the capture may outlast duration_s while an XLA
        # compile holds the profiler's flush — that is the documented
        # synchronous contract)
        st, prof = _post(server.port, "/debug/profile", {"duration_s": 0.2})
        assert st == 200 and prof["ok"] is True, prof
        assert prof["path"].startswith(srv_dir)
        assert prof["bytes"] > 0
        xplanes = list(Path(prof["path"]).rglob("*.xplane.pb"))
        assert xplanes and all(p.stat().st_size > 0 for p in xplanes)
        try:  # loadable, when the protobuf runtime is present
            sys.path.insert(
                0,
                os.path.join(
                    os.path.dirname(__file__), "..", "tools", "_proto"
                ),
            )
            import lt_xplane_pb2

            xs = lt_xplane_pb2.XSpace()
            xs.ParseFromString(xplanes[0].read_bytes())
            assert len(xs.planes) >= 1
        except ImportError:
            pass  # bytes + naming already prove the capture wrote a trace

        # the second capture hits the armed debug.profile fault: the
        # CAPTURE fails, the job (still running or finishing) does not
        st, prof2 = _post(server.port, "/debug/profile", {"duration_s": 0.1})
        assert st == 200 and prof2["ok"] is False
        assert "injected fault" in prof2["error"]

        # malformed profile requests are 400s, never captures or 500s
        st, body = _post(server.port, "/debug/profile", {"duration_s": -1})
        assert st == 400
        st, body = _post(server.port, "/debug/profile", {"duration_s": None})
        assert st == 400
        st, body = _post(server.port, "/debug/profile", [1, 2])
        assert st == 400
    finally:
        server.stop()
        t.join(timeout=300)

    s = server.job_status(snap["job_id"])
    assert s["state"] == "done", s.get("error")
    assert s["deadline_exceeded"] is True  # SLO surfaced, job unharmed

    # the server stream carries the new events, lint-clean end to end
    from check_events_schema import main as lint_main

    assert lint_main([srv_dir]) == 0
    flight_dump = os.path.join(srv_dir, "flight.jsonl")
    assert os.path.exists(flight_dump)
    assert lint_main([flight_dump]) == 0

    evs = [json.loads(l) for l in open(os.path.join(srv_dir, "events.jsonl"))]
    slo = [e for e in evs if e["ev"] == "job_slo"]
    assert len(slo) == 1
    assert slo[0]["met"] is False and slo[0]["deadline_s"] == 0.001
    assert slo[0]["queue_wait_s"] + slo[0]["exec_s"] <= slo[0]["latency_s"] + 5e-3
    captures = [e for e in evs if e["ev"] == "profile_captured"]
    assert [c["ok"] for c in captures] == [True, False]
    assert captures[1]["error"]
    assert any(e["ev"] == "flight_sample" for e in evs)

    # the job's OWN stream mirrored into the server ring: the dump holds
    # job-scope events (tile traffic) beside the server's
    dump = [json.loads(l) for l in open(flight_dump)]
    assert any(e.get("job_id") == snap["job_id"] for e in dump)

    # obs_report folds the SLO + resources sections from the server scope
    import obs_report

    report, spans = obs_report.fold([os.path.join(srv_dir, "events.jsonl")])
    assert report["slo"]["jobs"] == 1 and report["slo"]["missed"] == 1
    tenant = report["slo"]["by_tenant"]["default"]
    assert tenant["deadline"] == {
        "with_deadline": 1, "met": 0, "missed": 1, "hit_rate": 0.0,
    }
    assert tenant["queue_wait_s"]["p99"] >= 0
    assert report["resources"]["samples"] >= 1
    assert report["resources"]["rss_bytes_max"] > 0
    counters = [s for s in spans if s["kind"] == "counter"]
    assert any(s["name"] == "resources" for s in counters)
    assert any(s["name"] == "sampler_backlog" for s in counters)
    trace_out = str(tmp_path / "trace.json")
    n = obs_report.export_trace(spans, report["hosts"], trace_out)
    assert n > 0

    # lt top renders the finished server's story... from files we can't
    # (server is down) — lt top is covered by its own live test below.


def test_shutdown_drains_inflight_profile_capture(tmp_path):
    """A drain-mode server exiting mid-capture used to tear the process
    down while a handler thread was inside the native profiler session
    (observed SIGSEGV + lost response).  The shutdown must wait out the
    capture — and refuse captures that arrive after teardown began."""
    server = SegmentationServer(
        ServeConfig(workdir=str(tmp_path / "srv"), telemetry=False)
    )
    result: dict = {}

    def capture():
        result.update(server.capture_profile(1.0))

    t = threading.Thread(target=capture)
    t.start()
    time.sleep(0.2)  # let the capture open the profiler session
    server.stop()
    server.serve_forever()  # tears down — must WAIT for the capture
    t.join(timeout=30)
    assert result.get("ok") is True, result
    assert result["bytes"] > 0
    # past teardown, a new capture is refused rather than racing exit
    late = server.capture_profile(0.1)
    assert late["ok"] is False and "shutting_down" in late["error"]


def test_debug_endpoints_disabled_is_404(tmp_path):
    server = SegmentationServer(
        ServeConfig(workdir=str(tmp_path / "srv"), debug_endpoints=False)
    )
    try:
        for path in ("/debug/flight", "/debug/stacks", "/debug/jobs"):
            st, _ = _get(server.port, path)
            assert st == 404, path
        st, _ = _post(server.port, "/debug/profile", {"duration_s": 0.1})
        assert st == 404
    finally:
        server.stop()
        server.serve_forever()


def test_deadline_met_and_slo_instruments(stack_dir, tmp_path):
    srv_dir = str(tmp_path / "srv")
    server = SegmentationServer(
        ServeConfig(workdir=srv_dir, max_jobs=1, feed_cache_mb=32)
    )
    snap = server.submit(_job(stack_dir, deadline_s=3600.0))
    server.serve_forever()
    s = server.job_status(snap["job_id"])
    assert s["state"] == "done"
    assert "deadline_exceeded" not in s
    evs = [json.loads(l) for l in open(os.path.join(srv_dir, "events.jsonl"))]
    slo = [e for e in evs if e["ev"] == "job_slo"]
    assert len(slo) == 1 and slo[0]["met"] is True
    # the metrics exposition carried the SLO instruments
    prom = (Path(srv_dir) / "metrics.prom").read_text()
    assert "lt_slo_met_total 1" in prom
    assert "lt_slo_missed_total 0" in prom
    assert "lt_serve_queue_wait_seconds_count 1" in prom
    assert "lt_serve_exec_seconds_count 1" in prom

    # deadline_s rides request validation like every other knob
    from land_trendr_tpu.serve import JobRequest

    with pytest.raises(ValueError, match="deadline_s"):
        JobRequest.from_payload({"stack_dir": "s", "deadline_s": 0})


# ---------------------------------------------------------------------------
# healthz + lt top


def test_healthz_and_lt_top_once(stack_dir, tmp_path, capsys):
    server = SegmentationServer(
        ServeConfig(workdir=str(tmp_path / "srv"), feed_cache_mb=32)
    )
    try:
        snap = server.submit(_job(stack_dir, tenant="topper"))
        st, h = _get(server.port, "/healthz")
        assert st == 200 and h["ok"] is True
        # the load-balancer facts ride /healthz directly (no Prometheus
        # parse needed): queue depth, running, warm programs, uptime
        assert h["queue_depth"] == 1
        assert h["running"] is None  # dispatcher not started
        assert isinstance(h["warm_program_count"], int)
        assert h["uptime_s"] >= 0

        import lt_top

        assert lt_top.main(["--port", str(server.port), "--once"]) == 0
        out = capsys.readouterr().out
        assert "lt top" in out and "queue 1" in out
        assert snap["job_id"] in out and "topper" in out

        assert lt_top.main(["--port", str(server.port), "--json"]) == 0
        parsed = json.loads(capsys.readouterr().out)
        assert parsed["healthz"]["queue_depth"] == 1
        assert isinstance(parsed["jobs"], list) and parsed["jobs"]
    finally:
        server.stop()
        server.serve_forever()
    # a downed server is exit 2, not a traceback
    assert lt_top.main(["--port", str(server.port), "--once"]) == 2


# ---------------------------------------------------------------------------
# value lints


def test_job_slo_and_flight_sample_value_lints(tmp_path):
    from check_events_schema import main as lint_main

    head = {
        "ev": "run_start", "t_wall": 1.0, "t_mono": 1.0, "schema": 1,
        "fingerprint": "f", "pid": 1, "host": "h", "process_index": 0,
        "process_count": 1, "tiles_total": 0, "tiles_todo": 0,
        "tiles_skipped_resume": 0, "mesh_devices": 1, "impl": "xla",
    }

    def stream(*recs) -> str:
        p = tmp_path / f"s{len(list(tmp_path.iterdir()))}.jsonl"
        p.write_text("\n".join(json.dumps(r) for r in (head, *recs)) + "\n")
        return str(p)

    ok_slo = {
        "ev": "job_slo", "t_wall": 2.0, "t_mono": 2.0, "job_id": "j",
        "tenant": "t", "queue_wait_s": 1.0, "exec_s": 2.0,
        "latency_s": 3.0, "met": True,
    }
    assert lint_main([stream(ok_slo)]) == 0
    # the split must fit inside the end-to-end latency
    bad_split = {**ok_slo, "latency_s": 2.0}
    assert lint_main([stream(bad_split)]) == 1
    # negative durations are producer bugs
    assert lint_main([stream({**ok_slo, "queue_wait_s": -1.0})]) == 1

    ok_sample = {
        "ev": "flight_sample", "t_wall": 2.0, "t_mono": 2.0,
        "rss_bytes": 10, "open_fds": 3, "threads": 2,
    }
    assert lint_main([stream(ok_sample)]) == 0
    assert lint_main([stream({**ok_sample, "rss_bytes": -5})]) == 1
    assert lint_main([stream({**ok_sample, "queue_depth": -1})]) == 1

    ok_prof = {
        "ev": "profile_captured", "t_wall": 2.0, "t_mono": 2.0,
        "ok": True, "duration_s": 0.5, "path": "/p", "bytes": 10,
    }
    assert lint_main([stream(ok_prof)]) == 0
    assert lint_main([stream({**ok_prof, "bytes": -1})]) == 1


def test_burn_rate_window_survives_ring_flood(tmp_path):
    """lt_slo_burn_rate is a fraction of the last N terminal JOBS — a
    busy job flooding the flight ring with tile events must not shrink
    the burn denominator to just the job that ended last."""
    from types import SimpleNamespace

    from land_trendr_tpu.serve.server import _ServeTelemetry

    tel = _ServeTelemetry(
        ServeConfig(
            workdir=str(tmp_path / "srv"),
            flight_ring_events=16,  # tiny ring, easy to flood
            sampler_interval_s=60.0,
        )
    )
    try:
        def job(i):
            return SimpleNamespace(
                job_id=f"j{i}", trace_id=f"trace{i:012d}",
                request=SimpleNamespace(tenant="default"),
            )

        def slo(met, deadline=True):
            out = {
                "queue_wait_s": 0.0, "exec_s": 0.1, "latency_s": 0.1,
                "met": met,
            }
            if deadline:
                out["deadline_s"] = 0.05 if not met else 60.0
            return out

        tel.job_slo(job(0), slo(False))
        tel.job_slo(job(1), slo(True))
        tel.job_slo(job(2), slo(True))
        # flood: one busy job's traffic evicts every job_slo record
        # from the 16-slot ring
        for _ in range(64):
            tel.events.emit(
                "flight_sample", rss_bytes=1, open_fds=1, threads=1
            )
        assert not any(
            r.get("ev") == "job_slo" for r in tel.flight.snapshot()
        )
        tel.job_slo(job(3), slo(True))
        assert tel._slo_burn.value == pytest.approx(1 / 4)
        # deadline-scoped: a flood of no-deadline jobs (met by
        # definition) must not dilute the burn window
        for i in range(4, 20):
            tel.job_slo(job(i), slo(True, deadline=False))
        assert tel._slo_burn.value == pytest.approx(1 / 4)
    finally:
        tel.close("done", 0.0, {})


def test_store_bytes_probe(monkeypatch):
    """flight_sample's store_bytes gauge: attached-store occupancy, or
    absent (not 0, not an error) without a store."""
    from land_trendr_tpu.io import blockcache

    class FakeStore:
        def stats_snapshot(self):
            return {"bytes": 123}

    monkeypatch.setattr(blockcache, "_store", FakeStore())
    assert blockcache.store_bytes_snapshot() == 123
    monkeypatch.setattr(blockcache, "_store", None)
    assert blockcache.store_bytes_snapshot() is None
