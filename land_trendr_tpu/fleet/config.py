"""Serving-fleet configuration: everything that defines one router process.

:class:`RouterConfig` is the fleet-layer sibling of
:class:`~land_trendr_tpu.serve.config.ServeConfig`: the one configuration
surface of ``lt route``, projected to the ``route`` CLI subcommand and to
README's ``## Fleet configuration`` table (the LT004 coupling rule checks
all three — the third triangle, after RunConfig and ServeConfig).

Security posture mirrors the job API's: the router front door accepts
arbitrary segmentation work for the whole fleet, so it is loopback-ONLY
(``route_host`` must name a loopback address).  The replicas it talks to
are loopback servers on the same machine — a multi-machine fleet fronts
each machine's router with an authenticated proxy, exactly like a single
server.
"""

from __future__ import annotations

import dataclasses

from land_trendr_tpu.serve.config import LOOPBACK_HOSTS

__all__ = ["RouterConfig", "parse_tenant_weights"]


def parse_tenant_weights(spec: "str | None") -> "dict[str, float]":
    """``"a=3,b=1.5"`` → ``{"a": 3.0, "b": 1.5}`` (fair-share weights;
    tenants not named weigh 1).  Raises ``ValueError`` on any typo — a
    misspelled weight is a config error at startup, not a silently
    unweighted tenant discovered after the starvation incident."""
    out: "dict[str, float]" = {}
    if not spec:
        return out
    for raw in spec.split(","):
        item = raw.strip()
        if not item:
            continue
        name, sep, val = item.partition("=")
        if not sep or not name:
            raise ValueError(
                f"tenant weight {raw!r} is not NAME=WEIGHT"
            )
        try:
            w = float(val)
        except ValueError:
            raise ValueError(
                f"tenant weight {raw!r}: {val!r} is not a number"
            ) from None
        if w <= 0:
            raise ValueError(
                f"tenant weight {raw!r}: weight must be > 0"
            )
        if name in out:
            raise ValueError(f"duplicate tenant weight for {name!r}")
        out[name] = w
    return out


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Everything that defines one ``lt route`` router process."""

    #: router root: the router's own events/metrics stream, the pinned
    #: per-job ``jobs/<id>/{work,out}`` directories every replica
    #: resumes from, and spawned replicas' workdirs live here
    workdir: str = "lt_route"
    #: loopback HTTP JSON API port of the front door (0 = ephemeral,
    #: reported in the startup line)
    route_port: int = 0
    #: bind address for the front door — loopback only (the router
    #: submits arbitrary work to the whole fleet; see the module
    #: docstring)
    route_host: str = "127.0.0.1"
    #: replicas to ADOPT: base URLs of already-running ``lt serve``
    #: processes (``http://127.0.0.1:PORT``).  Adopted replicas are
    #: health-checked and routed to but never spawned, drained, or
    #: killed by the autoscaler.
    replicas: "tuple[str, ...]" = ()
    #: replicas to SPAWN at startup via the ``lt serve`` CLI (workdirs
    #: under ``<workdir>/replicas/``, ephemeral ports read from the
    #: startup line); spawned replicas are the autoscaler's pool
    spawn_replicas: int = 0
    #: extra ``lt serve`` flags passed through to every spawned replica
    #: (e.g. ``--ingest-store-mb 256``); the router always pins
    #: ``--workdir``/``--serve-port`` and, with a telemetry dir, the
    #: ``--publish`` trio
    replica_args: "tuple[str, ...]" = ()
    #: per-replica in-flight bound at the ROUTER: how many routed jobs
    #: may be queued+running on one replica before the router looks
    #: elsewhere (small keeps fair-share responsive; 2 lets a warm
    #: replica pipeline the next same-shape job behind the current one)
    replica_inflight: int = 2
    #: router-wide queue bound: a submission that would grow the unsent
    #: queue past this is throttled with HTTP 429 + Retry-After
    route_queue_depth: int = 64
    #: per-tenant quota: queued + routed (not yet terminal) jobs one
    #: tenant may hold; at the quota the submission is throttled with
    #: HTTP 429 + Retry-After while other tenants' traffic proceeds
    tenant_quota: int = 16
    #: weighted fair share, ``"tenant=weight,..."`` — the deficit
    #: round-robin scheduler gives each tenant queue bandwidth
    #: proportional to its weight (unnamed tenants weigh 1)
    tenant_weights: "str | None" = None
    #: warm-affinity routing: route a job to a replica whose warm/sticky
    #: key set contains its affinity key (least-loaded fallback).
    #: ``False`` routes purely least-loaded — the bench baseline
    #: ``tools/fleet_bench.py`` measures against
    affinity: bool = True
    #: re-routes per job: a job whose replica died (or whose forward
    #: failed) re-enters the queue and routes to another replica at
    #: most this many extra times before going terminal ``error``
    route_retries: int = 2
    #: health-probe + job-poll period, seconds
    health_interval_s: float = 1.0
    #: consecutive failed health probes before a replica is marked
    #: unready (``replica_down`` reason="health"); its accepted jobs
    #: keep polling — they are never failed by a probe
    unhealthy_after: int = 3
    #: SLO-driven autoscaling over the SPAWNED pool: consume the pod
    #: ``lt_slo_burn_rate`` from the shared telemetry directory
    #: (``obs.aggregate.fold_dir`` over replica snapshots) through the
    #: alert engine, and scale between ``min_replicas`` and
    #: ``max_replicas`` with hold-down timers and drain-before-kill
    autoscale: bool = False
    #: autoscaler floor (spawned replicas)
    min_replicas: int = 1
    #: autoscaler ceiling (spawned replicas)
    max_replicas: int = 4
    #: scale UP when the pod burn rate holds at or above this
    scale_up_burn: float = 0.5
    #: scale DOWN when the pod burn rate holds at or below this AND the
    #: router queue is empty
    scale_down_burn: float = 0.05
    #: the burn condition must hold this long before a scale action
    scale_for_s: float = 0.0
    #: hold-down between scale actions, seconds (no flapping)
    scale_hold_s: float = 30.0
    #: router telemetry: its own ``events.jsonl`` scope
    #: (``route_decision`` / ``replica_up`` / ``replica_down`` /
    #: ``tenant_throttled`` / ``scale_decision``) and ``lt_router_*``
    #: metrics under ``workdir``
    telemetry: bool = True
    #: shared fleet telemetry directory (default
    #: ``<workdir>/telemetry``): spawned replicas publish their
    #: snapshots here, the autoscaler folds it for the burn signal, and
    #: the router publishes its own ``kind="route"`` snapshot so
    #: ``lt_fleet`` / ``lt top --dir`` render the router state
    telemetry_dir: "str | None" = None
    #: router ``metrics.prom`` refresh period, seconds
    metrics_interval_s: float = 5.0
    #: request-tracing recency bound: how many recent TERMINAL requests
    #: (trace id, router blame split, hops) ``GET /debug/requests``
    #: serves, slowest-first; the ``/metrics/exemplars`` JSON is the
    #: machine half of the same loop.  0 disables the ring.
    request_ring: int = 64
    #: write-ahead admission journal under ``<workdir>/journal/``: every
    #: accepted job is durably recorded BEFORE the client sees 200, and
    #: a restart on the same workdir replays it — queues rebuilt in
    #: admission order, non-terminal jobs reconciled against their
    #: replicas, duplicates deduplicated by idempotency key.  Off trades
    #: crash-safety for zero admission-path I/O (bench baselines only).
    journal: bool = True
    #: journal segment rotation size, MiB; at rotation (and restart) the
    #: fully-terminal segment prefix is compacted away, bounding replay
    #: cost by the live working set
    journal_segment_mb: int = 4
    #: record every dispatcher/autoscaler decision (inputs AND outputs)
    #: to ``<workdir>/decisions.jsonl`` — the capacity planner's replay
    #: source (``land_trendr_tpu.fleet.capacity``); off by default: the
    #: log grows with traffic and exists for soak/bench runs
    decision_log: bool = False
    #: deterministic fault injection for soak runs (``router.forward``
    #: / ``replica.health`` seams plus everything in-process);
    #: production routers leave this unset
    fault_schedule: "str | None" = None

    def __post_init__(self) -> None:
        if not (0 <= self.route_port <= 65535):
            raise ValueError(
                f"route_port={self.route_port} outside 0..65535"
            )
        if self.route_host not in LOOPBACK_HOSTS:
            raise ValueError(
                f"route_host={self.route_host!r} is not a loopback "
                f"address {LOOPBACK_HOSTS}: the router front door is an "
                "unauthenticated control surface for the whole fleet "
                "and never binds a routable interface"
            )
        for base in self.replicas:
            if not isinstance(base, str) or not base.startswith("http"):
                raise ValueError(
                    f"replica {base!r} is not a base URL "
                    "(http://127.0.0.1:PORT)"
                )
        if self.spawn_replicas < 0:
            raise ValueError(
                f"spawn_replicas={self.spawn_replicas} must be >= 0"
            )
        if not self.replicas and not self.spawn_replicas:
            raise ValueError(
                "a router needs replicas: pass --replica URLs to adopt "
                "and/or --spawn-replicas N to spawn"
            )
        if self.replica_inflight < 1:
            raise ValueError(
                f"replica_inflight={self.replica_inflight} must be >= 1"
            )
        if self.route_queue_depth < 1:
            raise ValueError(
                f"route_queue_depth={self.route_queue_depth} must be >= 1"
            )
        if self.tenant_quota < 1:
            raise ValueError(
                f"tenant_quota={self.tenant_quota} must be >= 1"
            )
        parse_tenant_weights(self.tenant_weights)  # typo = startup error
        if self.route_retries < 0:
            raise ValueError(
                f"route_retries={self.route_retries} must be >= 0"
            )
        if self.health_interval_s <= 0:
            raise ValueError(
                f"health_interval_s={self.health_interval_s} must be > 0"
            )
        if self.unhealthy_after < 1:
            raise ValueError(
                f"unhealthy_after={self.unhealthy_after} must be >= 1"
            )
        if self.autoscale:
            if not self.spawn_replicas:
                raise ValueError(
                    "autoscale manages SPAWNED replicas only (it must "
                    "own the process to drain and stop it): pass "
                    "--spawn-replicas >= 1"
                )
            if not (1 <= self.min_replicas <= self.max_replicas):
                raise ValueError(
                    f"need 1 <= min_replicas({self.min_replicas}) <= "
                    f"max_replicas({self.max_replicas})"
                )
            if not (self.min_replicas <= self.spawn_replicas
                    <= self.max_replicas):
                raise ValueError(
                    f"spawn_replicas={self.spawn_replicas} outside the "
                    f"autoscale bounds [{self.min_replicas}, "
                    f"{self.max_replicas}]"
                )
            if self.scale_down_burn >= self.scale_up_burn:
                raise ValueError(
                    f"scale_down_burn={self.scale_down_burn} must be "
                    f"below scale_up_burn={self.scale_up_burn} (a "
                    "hysteresis band, or the scaler flaps)"
                )
        if self.scale_for_s < 0 or self.scale_hold_s < 0:
            raise ValueError("scale_for_s/scale_hold_s must be >= 0")
        if self.metrics_interval_s <= 0:
            raise ValueError(
                f"metrics_interval_s={self.metrics_interval_s} must be > 0"
            )
        if self.request_ring < 0:
            raise ValueError(
                f"request_ring={self.request_ring} must be >= 0 (0 = off)"
            )
        if self.journal_segment_mb < 1:
            raise ValueError(
                f"journal_segment_mb={self.journal_segment_mb} must be >= 1"
            )
        if self.fault_schedule is not None:
            # parse NOW: a typo'd seam is a config error at startup (the
            # RunConfig/ServeConfig contract)
            from land_trendr_tpu.runtime import faults

            faults.parse_schedule(self.fault_schedule)
