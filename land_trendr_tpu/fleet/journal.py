"""Write-ahead admission journal: the router's crash-durable job table.

The ``FleetRouter`` keeps its tenant queues and in-flight job table in
memory; this module makes the *admission contract* survive control-plane
death.  Every accepted job appends an ``admitted`` record (full request
payload, trace id, idempotency key) BEFORE the client sees 200, with
``forwarded`` (replica base + replica job id) and ``terminal`` records
following as the job moves.  On restart the router replays the journal to
rebuild its queues in admission order and reconciles every non-terminal
job against its replica (see ``fleet/router.py``).

Durability discipline (the DecisionLog / manifest idiom):

- append-only JSONL segments (``seg-%08d.jsonl``), each record committed
  as ONE ``os.write`` on an ``O_APPEND`` fd — a crash can tear only the
  final line, never interleave two records;
- torn-tail GC at reopen: a half-written LAST line of the LAST segment is
  dropped (tmp + ``os.replace`` rewrite); garbage anywhere else is real
  corruption and raises ``JournalError`` instead of silently losing jobs;
- rotation at ``segment_bytes`` with prefix-only compaction: the oldest
  segments whose every referenced job has a ``terminal`` record anywhere
  in the journal are unlinked — replay cost stays bounded by the live
  working set, not by router uptime;
- a clean-shutdown marker (tmp + ``os.replace``) written after a full
  drain lets the next start skip reconciliation probes; it is consumed
  (removed) at reopen so only an *uninterrupted* drain counts.

Fault seam: every append fires ``router.journal`` first, so the soak can
pin the failure-semantics decision — an append failure must fail that
admission loudly (503 ``journal_error``) rather than accept an un-durable
job.
"""

from __future__ import annotations

import json
import os
import threading
import time

from land_trendr_tpu.runtime import faults as _faults

__all__ = ["AdmissionJournal", "JournalError", "RECORD_KINDS"]

#: the record vocabulary; unknown kinds replay as no-ops (forward compat)
RECORD_KINDS = ("admitted", "forwarded", "terminal")

_SEG_PREFIX = "seg-"
_SEG_SUFFIX = ".jsonl"
_CLEAN_MARKER = "clean"


class JournalError(Exception):
    """An append could not be committed (or the journal is corrupt).

    The router maps this to a 503 ``journal_error`` rejection: a job the
    journal cannot make durable is never admitted.
    """


def _seg_name(index: int) -> str:
    return f"{_SEG_PREFIX}{index:08d}{_SEG_SUFFIX}"


def _seg_index(name: str) -> "int | None":
    if not (name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX)):
        return None
    body = name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)]
    return int(body) if body.isdigit() else None


class AdmissionJournal:
    """Append-only, segment-rotated, crash-tolerant admission journal.

    Thread-safe: appends serialise on an internal lock (the commit is a
    single ``os.write`` regardless).  ``replay()`` folds the full journal
    into per-job state in admission order; ``compact()`` drops the
    fully-terminal segment prefix.
    """

    def __init__(self, root: str, segment_bytes: int = 4 * 2 ** 20):
        self.root = root
        self._segment_bytes = max(int(segment_bytes), 64 * 1024)
        self._lock = threading.Lock()
        self._faults = _faults
        self._fd: "int | None" = None
        self._seg = 0          # active segment index
        self._seg_size = 0     # bytes in the active segment
        self.appends = 0
        os.makedirs(root, exist_ok=True)
        marker = os.path.join(root, _CLEAN_MARKER)
        #: True iff the previous process drained fully and wrote the
        #: marker; consumed here so only an uninterrupted drain counts
        self.was_clean = os.path.exists(marker)
        if self.was_clean:
            os.remove(marker)
        segs = self._segments()
        if segs:
            self._gc_torn_tail(segs[-1])
        self._seg = segs[-1] if segs else 1
        with self._lock:
            self._open_segment_locked()

    # -- segment bookkeeping ---------------------------------------------

    def _segments(self) -> "list[int]":
        out = []
        for name in os.listdir(self.root):
            idx = _seg_index(name)
            if idx is not None:
                out.append(idx)
        return sorted(out)

    def _seg_path(self, index: int) -> str:
        return os.path.join(self.root, _seg_name(index))

    def _open_segment_locked(self) -> None:
        path = self._seg_path(self._seg)
        self._fd = os.open(
            path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644
        )
        self._seg_size = os.fstat(self._fd).st_size

    def _gc_torn_tail(self, index: int) -> None:
        """Drop a half-written final line of the last segment (the only
        damage a crash can inflict on an O_APPEND line-commit journal).
        Garbage anywhere earlier is NOT crash residue — raise."""
        path = self._seg_path(index)
        with open(path, "rb") as f:
            raw = f.read()
        if not raw:
            return
        lines = raw.split(b"\n")
        torn = lines.pop()  # b"" when the file ends with a newline
        good = len(raw) - len(torn)
        for i, line in enumerate(lines):
            if not line:
                continue
            try:
                json.loads(line)
            except ValueError:
                if i == len(lines) - 1 and not torn:
                    # invalid FINAL committed line: a torn write that
                    # happened to end at a newline boundary — droppable
                    good -= len(line) + 1
                    torn = line
                else:
                    raise JournalError(
                        f"corrupt journal segment {_seg_name(index)} "
                        f"line {i + 1}: not crash residue"
                    )
        if not torn:
            return
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            f.write(raw[:good])
        os.replace(tmp, path)

    def _read_segment(self, index: int) -> "list[dict]":
        """Parse one segment; only the LAST segment tolerates a torn
        tail (rotated segments ended on a committed line by
        construction)."""
        last = index == self._seg
        out: "list[dict]" = []
        with open(self._seg_path(index), "rb") as f:
            lines = f.read().split(b"\n")
        for i, line in enumerate(lines):
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                if last and i == len(lines) - 1:
                    break  # torn tail: drop the half-written record
                raise JournalError(
                    f"corrupt journal segment {_seg_name(index)} "
                    f"line {i + 1}"
                )
        return out

    # -- append path ------------------------------------------------------

    def append(self, rec: str, job_id: str, **fields) -> "tuple[int, int]":
        """Durably commit one record; returns ``(segment, bytes)``.

        Fires the ``router.journal`` seam first.  Any failure — seam, fd,
        ENOSPC — surfaces as ``JournalError``: the caller must NOT treat
        the record as written.
        """
        payload = {"rec": rec, "job_id": job_id}
        payload.update(fields)
        line = (json.dumps(payload, separators=(",", ":")) + "\n").encode()
        try:
            with self._lock:
                self._faults.check("router.journal")
                if self._fd is None:
                    raise JournalError("journal is closed")
                if self._seg_size >= self._segment_bytes:
                    self._rotate_locked()
                n = os.write(self._fd, line)
                if n != len(line):
                    raise JournalError(
                        f"short journal write ({n}/{len(line)} bytes)"
                    )
                self._seg_size += n
                self.appends += 1
                return self._seg, n
        except JournalError:
            raise
        except Exception as e:
            raise JournalError(f"journal append failed: {e}") from e

    def _rotate_locked(self) -> None:
        os.close(self._fd)
        self._seg += 1
        self._open_segment_locked()
        self._compact_locked()

    # -- replay / compaction ---------------------------------------------

    def replay(self) -> "dict[str, dict]":
        """Fold the journal into per-job state, in admission order.

        Returns ``{job_id: state}`` where ``state`` carries the original
        ``admitted`` fields plus ``status`` (``admitted`` | ``forwarded``
        | ``terminal``) and, when present, ``replica_base`` /
        ``replica_job_id`` / ``state`` / ``error``.  Records for jobs
        whose ``admitted`` segment was compacted away fold as no-ops.
        """
        with self._lock:
            return self._replay_locked()

    def _replay_locked(self) -> "dict[str, dict]":
        jobs: "dict[str, dict]" = {}
        for index in self._segments():
            for rec in self._read_segment(index):
                kind = rec.get("rec")
                jid = rec.get("job_id")
                if not isinstance(jid, str):
                    continue
                if kind == "admitted":
                    state = dict(rec)
                    state["status"] = "admitted"
                    jobs[jid] = state
                elif kind == "forwarded":
                    j = jobs.get(jid)
                    if j is not None and j["status"] != "terminal":
                        j["status"] = "forwarded"
                        j["replica_base"] = rec.get("replica_base")
                        j["replica_job_id"] = rec.get("replica_job_id")
                elif kind == "terminal":
                    j = jobs.get(jid)
                    if j is not None:
                        j["status"] = "terminal"
                        j["state"] = rec.get("state")
                        j["error"] = rec.get("error")
        return jobs

    def compact(self) -> int:
        """Unlink the longest prefix of segments whose every referenced
        job is terminal somewhere in the journal; returns the count
        dropped.  Prefix-only: a surviving older segment keeps every
        newer one too, so replay order is never reordered."""
        with self._lock:
            return self._compact_locked()

    def _compact_locked(self) -> int:
        folded = self._replay_locked()
        terminal = {
            jid for jid, j in folded.items() if j["status"] == "terminal"
        }
        dropped = 0
        for index in self._segments():
            if index == self._seg:
                break  # never the active segment
            refs = {
                rec.get("job_id")
                for rec in self._read_segment(index)
                if isinstance(rec.get("job_id"), str)
            }
            # jobs admitted in an already-dropped segment fold to nothing;
            # their trailing records are equally dead
            live = {j for j in refs if j in folded and j not in terminal}
            if live:
                break
            os.remove(self._seg_path(index))
            dropped += 1
        return dropped

    # -- lifecycle ---------------------------------------------------------

    def mark_clean(self) -> None:
        """Record a fully-drained shutdown so the next start can skip
        reconciliation probes.  tmp + rename: the marker either exists
        completely or not at all."""
        path = os.path.join(self.root, _CLEAN_MARKER)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"t": time.time()}, f)
        os.replace(tmp, path)

    def stats(self) -> dict:
        with self._lock:
            segs = self._segments()
            return {
                "segments": len(segs),
                "segment": self._seg,
                "bytes": self._seg_size,
                "appends": self.appends,
            }

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None
