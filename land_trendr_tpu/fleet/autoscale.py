"""SLO-driven autoscaler: the burn-rate signal → bounded scale actions.

The decision core of the fleet router's autoscaling, deliberately split
from the router so it is a **pure function of the observations it is
shown** — the same property :class:`~land_trendr_tpu.obs.alerts.
AlertEngine` has, because the conditions ARE alert rules: ``scale_up``
fires when the pod ``lt_slo_burn_rate`` (the PR-9/PR-11 signal, folded
from replica snapshots by ``obs.aggregate.fold_dir``) holds at or above
``scale_up_burn`` for ``scale_for_s``; ``scale_down`` when it holds at
or below ``scale_down_burn``.  On top of the rule lifecycle this class
adds the ACTUATOR discipline the rules cannot express:

* **bounds** — never below ``min_replicas`` or above ``max_replicas``;
* **hold-down** — at most one action per ``scale_hold_s`` window, so a
  burn spike cannot flap the pool;
* **quiesce gate** — scale-down additionally requires an empty router
  queue (shrinking a backlogged fleet only moves the burn up).

All timing comes from the caller's ``now`` — no internal clock reads —
so a scripted burn-rate history replays to byte-identical decisions,
which is exactly what ``tests/test_fleet_serve.py`` pins and the router
soak replays.  Stdlib-only, jax-free.
"""

from __future__ import annotations

from land_trendr_tpu.obs.alerts import AlertEngine, AlertRule

__all__ = ["Autoscaler"]

#: the sample key the decision rules evaluate (the pod-max fold of the
#: per-replica burn gauges — obs.aggregate's GAUGE default policy)
BURN_METRIC = "lt_slo_burn_rate"


class Autoscaler:
    """Deterministic scale-decision state machine (see module doc).

    Single-owner like :class:`~land_trendr_tpu.obs.alerts.AlertEngine`:
    the router's control loop calls :meth:`decide` each beat; other
    threads read :meth:`state` snapshots the owner refreshed (the
    router serializes both under its lock).
    """

    def __init__(
        self,
        *,
        min_replicas: int,
        max_replicas: int,
        up_burn: float,
        down_burn: float,
        for_s: float = 0.0,
        hold_s: float = 30.0,
    ) -> None:
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.hold_s = float(hold_s)
        self.engine = AlertEngine((
            AlertRule(
                name="scale_up", kind="threshold", metric=BURN_METRIC,
                op=">=", value=float(up_burn), for_s=float(for_s),
            ),
            AlertRule(
                name="scale_down", kind="threshold", metric=BURN_METRIC,
                op="<=", value=float(down_burn), for_s=float(for_s),
            ),
        ))
        self._last_action_t: "float | None" = None
        self._last_burn: "float | None" = None
        self._decisions = 0

    def decide(
        self,
        burn: "float | None",
        queue_depth: int,
        replicas: int,
        now: float,
    ) -> "str | None":
        """Advance the rules with one observation; return ``"up"`` /
        ``"down"`` / ``None``.

        ``burn`` is the pod burn rate (``None`` — a dark telemetry
        plane — advances nothing: scaling blind is worse than holding),
        ``queue_depth`` the router's unsent queue, ``replicas`` the
        CURRENT spawned-pool size the bounds apply to.
        """
        self._last_burn = burn
        self._decisions += 1
        if burn is None:
            return None
        self.engine.evaluate(
            [{"t": now, "metrics": {BURN_METRIC: float(burn)}}], now
        )
        active = {a["rule"] for a in self.engine.active()}
        held = (
            self._last_action_t is not None
            and now - self._last_action_t < self.hold_s
        )
        if held:
            return None
        if "scale_up" in active and replicas < self.max_replicas:
            self._last_action_t = now
            return "up"
        if (
            "scale_down" in active
            and queue_depth == 0
            and replicas > self.min_replicas
        ):
            self._last_action_t = now
            return "down"
        return None

    def state(self) -> dict:
        """JSON-safe snapshot for ``/healthz`` and the router's fleet
        snapshot (``lt top`` / ``lt_fleet`` render it)."""
        return {
            "burn": self._last_burn,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "hold_s": self.hold_s,
            "last_action_t": self._last_action_t,
            "decisions": self._decisions,
            "firing": sorted(a["rule"] for a in self.engine.active()),
        }
