"""Pure scheduling cores + the recorded decision log they replay from.

The router's two decision mechanisms — deficit-round-robin tenant
scheduling and warm-affinity replica choice — are deliberately pure
functions of their visible state: no wall clock, no I/O, no randomness.
This module is the ONE copy of each, used live by
:class:`~land_trendr_tpu.fleet.router.FleetRouter` and offline by the
capacity replay simulator (:mod:`land_trendr_tpu.fleet.capacity`), so
"the simulator models the dispatcher" is enforced by construction
rather than by keeping two implementations in sync.

:class:`DecisionLog` is the recording half of that contract: a router
started with ``decision_log=True`` appends one JSONL record per
decision *input* and *output* (autoscaler ticks, DRR enqueues/picks,
replica choices) to ``<workdir>/decisions.jsonl``.  The simulator
replays the inputs through fresh instances of the SAME classes below
and byte-compares the outputs — the live-vs-replay equivalence proof
``CAPACITY_r17.json`` carries.
"""

from __future__ import annotations

import collections
import json
import os
import threading

__all__ = [
    "DECISIONS_NAME",
    "PURE_MACHINES",
    "DecisionLog",
    "DrrQueue",
    "choose_replica",
    "read_decisions",
]

#: the decision-log file name under the router workdir
DECISIONS_NAME = "decisions.jsonl"

#: The pure decision machines of the fleet replay contract, as
#: ``(file, symbol)`` data — lt-lint LT009's single source (the
#: ``NONNEG_FIELDS`` shared-table pattern): everything listed here must
#: stay a pure function of its arguments (``now`` and seeds included),
#: transitively — no clock reads, no randomness, no environment, no
#: file IO, no global mutation — or the byte-identity replay proof
#: (``CAPACITY_r17.json``) silently stops meaning anything.  A class
#: name covers every method; ``obs/alerts.py`` exports the
#: observability-side half of the registry in the same shape.
#: ``tests/test_lint.py`` pins this table against the symbols
#: ``fleet/capacity.py::replay_decisions`` actually dispatches to.
#: NOTE: :class:`DecisionLog` is deliberately absent — it is the
#: *recording* half (O_APPEND file IO by design), never replayed — and
#: so is ``replay_decisions`` itself: it is the replay *shell* (reads
#: the log file, stamps the replay's own wall time, emits telemetry);
#: the machines it re-derives decisions THROUGH are what must stay pure.
PURE_MACHINES = (
    ("land_trendr_tpu/fleet/scheduling.py", "DrrQueue"),
    ("land_trendr_tpu/fleet/scheduling.py", "choose_replica"),
    ("land_trendr_tpu/fleet/autoscale.py", "Autoscaler.decide"),
    ("land_trendr_tpu/fleet/capacity.py", "find_knee"),
)


class DrrQueue:
    """Deficit round-robin over per-tenant FIFO queues.

    Each ring visit banks the tenant's weight; a banked deficit >= 1
    buys one entry (cost 1).  Bandwidth is therefore proportional to
    weight, and any non-empty queue is served within a bounded number
    of rotations — a heavy tenant cannot starve a light one.  An
    emptied queue leaves the ring and forfeits its bank (DRR's
    anti-burst rule).

    Pure state machine: no clocks, no locks (the caller serializes),
    no randomness — the same enqueue/pick/remove call sequence always
    yields the same pick sequence, which is what makes the recorded
    dispatcher history offline-replayable.
    """

    def __init__(self, weights: "dict[str, float] | None" = None) -> None:
        self._tq: "dict[str, collections.deque]" = {}
        self._deficit: "dict[str, float]" = {}
        self._ring: "collections.deque[str]" = collections.deque()
        self._weights = dict(weights or {})
        self._depth = 0

    @property
    def depth(self) -> int:
        """Entries currently queued (all tenants)."""
        return self._depth

    @property
    def pending(self) -> bool:
        """Any tenant with a non-empty queue?"""
        return bool(self._ring)

    def weight(self, tenant: str) -> float:
        return self._weights.get(tenant, 1.0)

    def deficit(self, tenant: str) -> float:
        return self._deficit.get(tenant, 0.0)

    def queued(self, tenant: str) -> int:
        q = self._tq.get(tenant)
        return len(q) if q else 0

    def tenants(self) -> "list[str]":
        return sorted(t for t, q in self._tq.items() if q)

    def known_tenants(self) -> "list[str]":
        """Every tenant that ever enqueued (empty queues included) —
        the stats-view domain."""
        return sorted(self._tq)

    def remove(self, tenant: str, entry: str) -> bool:
        """Drop one queued entry (cancel-while-queued).  Returns False
        when the entry is not in the tenant's queue — the cancel raced
        the enqueue; the caller treats the entry as dead so a later
        enqueue of it is skipped at pick time."""
        q = self._tq.get(tenant)
        if q is None:
            return False
        try:
            q.remove(entry)
        except ValueError:
            return False
        self._depth -= 1
        return True

    def enqueue(self, tenant: str, entry: str, front: bool = False) -> None:
        q = self._tq.get(tenant)
        if q is None:
            q = self._tq[tenant] = collections.deque()
        if not q and tenant not in self._ring:
            self._ring.append(tenant)
        (q.appendleft if front else q.append)(entry)
        self._depth += 1

    def pick(self, live=None) -> "tuple[str, str] | None":
        """Next ``(tenant, entry)`` under DRR, or None when everything
        is drained.  ``live`` (optional predicate) skips dead entries —
        a job cancelled while queued keeps its queue slot but must not
        be picked; the skip still consumes the slot, exactly like the
        live dispatcher."""
        guard = 0
        while self._ring:
            guard += 1
            if guard > 100_000:  # pure defense; unreachable for w > 0
                break
            tenant = self._ring[0]
            q = self._tq.get(tenant)
            if not q:
                self._ring.popleft()
                self._deficit[tenant] = 0.0
                continue
            if self._deficit.get(tenant, 0.0) < 1.0:
                # bank one quantum per ring visit; a sub-1 balance
                # means this visit buys nothing yet — move on (a
                # low-weight tenant is served every ceil(1/w) rotations)
                self._deficit[tenant] = (
                    self._deficit.get(tenant, 0.0) + self.weight(tenant)
                )
                if self._deficit[tenant] < 1.0:
                    self._ring.rotate(-1)
                    continue
            self._deficit[tenant] -= 1.0
            entry = q.popleft()
            self._depth -= 1
            if not q:
                # an emptied queue leaves the ring (and forfeits its
                # bank — DRR's anti-burst rule)
                self._ring.popleft()
                self._deficit[tenant] = 0.0
            elif self._deficit[tenant] < 1.0:
                # the visit's bank is spent: rotate so the NEXT pick
                # serves the next tenant (without this, a weight-1
                # tenant would re-bank on the same visit and be served
                # continuously — the exact starvation DRR prevents)
                self._ring.rotate(-1)
            if live is not None and not live(entry):
                continue
            return tenant, entry
        return None


def choose_replica(
    candidates: "list[tuple[str, int, bool]]", affinity: bool
) -> "tuple[str | None, bool]":
    """Warm-affinity replica choice over routable candidates.

    ``candidates`` is ``[(rid, inflight, warm), ...]`` — the already
    health/backoff/inflight-filtered routable set, with ``warm`` true
    when the replica holds the job's affinity key.  Returns
    ``(rid, warm)``: the least-loaded warm candidate when affinity is
    on and any is warm, else the least-loaded overall; ties break on
    rid, so the choice is a pure function of its arguments (the replay
    simulator's requirement).
    """
    if not candidates:
        return None, False
    if affinity:
        warm = [c for c in candidates if c[2]]
        if warm:
            warm.sort(key=lambda c: (c[1], c[0]))
            return warm[0][0], True
    ranked = sorted(candidates, key=lambda c: (c[1], c[0]))
    return ranked[0][0], False


class DecisionLog:
    """Append-only JSONL recorder for router decision inputs+outputs.

    One record per line, each carrying ``seq`` (a per-log monotone
    ordinal — the replay compares streams in seq order) and ``kind``:

    * ``config`` — the first record: the autoscaler parameters, tenant
      weights and affinity flag a replay needs to rebuild the pure
      state machines;
    * ``autoscale`` — one ``scale_tick``: the ``(burn, queue_depth,
      replicas, now)`` inputs and the ``decision`` output;
    * ``enqueue`` / ``remove`` — DRR input stream (``remove`` marks a
      cancel-while-queued: the entry stays in its queue and the replay
      must skip it exactly like the live pick loop);
    * ``pick`` — one DRR output: the ``(tenant, job_id)`` served;
    * ``choose`` — one replica choice: the routable ``candidates``
      snapshot, the ``affinity`` flag and the ``chosen`` rid.

    Writes are line-atomic (single ``write`` on an O_APPEND handle,
    the EventLog discipline) and serialized by a lock.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fd = os.open(
            path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
        )
        self._lock = threading.Lock()
        self._seq = 0

    def record(self, kind: str, **fields) -> None:
        with self._lock:
            rec = {"seq": self._seq, "kind": kind, **fields}
            self._seq += 1
            os.write(
                self._fd,
                (json.dumps(rec, sort_keys=True) + "\n").encode(),
            )

    def close(self) -> None:
        with self._lock:
            if self._fd >= 0:
                os.close(self._fd)
                self._fd = -1


def read_decisions(path: str) -> "tuple[dict, list[dict]]":
    """Load one decision log → ``(config, records)`` in seq order.
    Torn tail lines (a SIGKILLed router) are dropped, mid-stream torn
    lines are an error — the log is append-only, so only the last line
    can legitimately be incomplete."""
    recs: "list[dict]" = []
    config: dict = {}
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            if i == len(lines) - 1:
                break  # torn tail: the crash-consistency contract
            raise ValueError(f"{path}:{i + 1}: torn mid-stream record")
        if rec.get("kind") == "config":
            config = rec
        else:
            recs.append(rec)
    recs.sort(key=lambda r: r.get("seq", 0))
    return config, recs
