"""Serving fleet: warm-affinity router, tenant fair share, autoscaling.

The horizontal-scale layer over :mod:`land_trendr_tpu.serve` — one
:class:`FleetRouter` front door owns N ``lt serve`` replicas (spawned or
adopted), routes repeat shapes to warm replicas, schedules tenants
fairly under quotas, re-routes around replica death, and scales the
pool on the fleet telemetry plane's SLO burn-rate signal.  See
``README.md`` §Serving fleet.
"""

from land_trendr_tpu.fleet.autoscale import Autoscaler
from land_trendr_tpu.fleet.config import RouterConfig, parse_tenant_weights
from land_trendr_tpu.fleet.journal import AdmissionJournal, JournalError
from land_trendr_tpu.fleet.router import DOWN_REASONS, FleetRouter, RouterJob

__all__ = [
    "AdmissionJournal",
    "Autoscaler",
    "DOWN_REASONS",
    "FleetRouter",
    "JournalError",
    "RouterConfig",
    "RouterJob",
    "parse_tenant_weights",
]
