"""Serving fleet: the warm-affinity router / front door over N replicas.

One :class:`FleetRouter` process owns a pool of ``lt serve`` replicas
(spawned through the CLI or adopted by base URL), health-checks them
through ``/healthz``, and shards submitted jobs across them:

* **warm-affinity routing** — every request hashes to its
  :meth:`~land_trendr_tpu.serve.jobs.JobRequest.affinity_key`; the
  router keeps a per-replica warm-key table (seeded from ``/healthz``'s
  ``warm_keys`` list, confirmed by routing feedback, and extended
  *optimistically* at forward time so the very next same-shape job
  already sticks) and routes repeat shapes to the replica that holds
  the compiled programs.  Fallback is least-loaded.  Warm decodes need
  no affinity at all: the ingest store's ``(path, mtime_ns, ...)``
  keying makes them safely shareable across replicas on one FS.
* **tenant fair share + quotas** — jobs queue per tenant and drain
  through deficit round-robin (``tenant_weights``), so a heavy tenant
  cannot starve a light one; a tenant at its ``tenant_quota`` (or a
  full router queue) is throttled with HTTP 429 + ``Retry-After``
  (``tenant_throttled`` event) instead of building unbounded backlog.
* **retry-on-replica-death** — the router pins every job's
  ``workdir``/``out_dir`` under ITS workdir and submits with
  ``resume=true``, so when a replica dies mid-job the re-routed
  submission resumes the same manifest on a sibling and completes
  byte-identically (recorded tiles stay durable; duplicate execution
  resolves at the manifest's first-write-wins rename).  Zero accepted
  jobs are lost to a replica SIGKILL — the invariant
  ``tools/fleet_bench.py`` and the fault soak pin.
* **SLO-driven autoscaling** — the control loop folds the shared
  telemetry directory (``obs.aggregate.fold_dir`` over replica
  snapshots — the PR-11 plane) for the pod ``lt_slo_burn_rate`` and
  feeds :class:`~land_trendr_tpu.fleet.autoscale.Autoscaler`
  (AlertEngine rules + bounds + hold-down); scale-up spawns a replica,
  scale-down **drains before killing**: the victim stops receiving
  routes, its in-flight jobs finish, then SIGINT gives the ``lt
  serve`` process its documented clean shutdown — manifests stay
  resumable throughout.
* **crash-safe admission** — every accepted job is appended to a
  write-ahead journal (:class:`~land_trendr_tpu.fleet.journal.
  AdmissionJournal`) BEFORE the client sees 200, with ``forwarded`` and
  ``terminal`` records following.  A restart on the same workdir
  replays the journal (queues rebuilt in admission order, duplicate
  idempotency keys answered with the existing job), re-adopts live
  spawned replicas from ``replicas/*/replica.json`` + ``/healthz``,
  and reconciles each non-terminal job against its replica: terminal →
  relay the result, running → re-attach, unknown → requeue with the
  pinned workdir so the resumed run completes byte-identically under
  the preserved trace id.  Submissions during the reconciliation
  window answer 503 + Retry-After; an uninterrupted drain leaves a
  clean-shutdown marker so the next start skips the probes.

Failure semantics: a failed forward (``router.forward`` seam) or a
dead/unready replica re-enters the job into its tenant queue (bounded
by ``route_retries``); a health-probe failure (``replica.health``
seam) marks the replica unready WITHOUT failing any accepted job — its
jobs keep polling and finish wherever they run.  A journal append
failure at admission (``router.journal`` seam) fails THAT submission
loudly (503 ``journal_error``) rather than accept a job a crash would
orphan; a reconciliation probe failure (``router.recover`` seam)
requeues the replayed job — resume makes the fallback safe.  The
router's own telemetry (``route_decision`` / ``replica_up`` /
``replica_down`` / ``tenant_throttled`` / ``scale_decision`` /
``journal_append`` / ``router_recovered`` events, ``lt_router_*``
metrics) rides the normal schema/registry, so schema lint,
``obs_report``, ``lt top`` and ``lt_fleet`` cover the routing plane
like every other subsystem.
"""

from __future__ import annotations

import collections
import dataclasses
import http.server
import json
import logging
import os
import select
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Any

from land_trendr_tpu.fleet.autoscale import Autoscaler
from land_trendr_tpu.fleet.config import RouterConfig, parse_tenant_weights
from land_trendr_tpu.fleet.journal import AdmissionJournal, JournalError
from land_trendr_tpu.fleet.scheduling import (
    DECISIONS_NAME,
    DecisionLog,
    DrrQueue,
    choose_replica,
)
from land_trendr_tpu.obs.events import EventLog
from land_trendr_tpu.obs.metrics import MetricsRegistry, PromFileExporter
from land_trendr_tpu.runtime import faults
from land_trendr_tpu.serve.jobs import TERMINAL_STATES, JobRequest
from land_trendr_tpu.serve.server import Rejection

__all__ = ["DOWN_REASONS", "FleetRouter", "RouterJob"]

log = logging.getLogger("land_trendr_tpu.fleet")

#: replica_down reason vocabulary (value-linted by
#: ``tools/check_events_schema.py`` — the two tables are asserted equal
#: in tests/test_fleet_serve.py)
DOWN_REASONS = ("health", "dead", "scale_down", "shutdown")

#: router job-latency histogram buckets (the serve buckets)
_JOB_BUCKETS = (0.5, 1, 2, 5, 10, 30, 60, 300, 1800, 7200, 43200)

#: per-replica warm/sticky key table bound (recency-evicted)
_WARM_KEYS_MAX = 128

#: HTTP timeout for health probes and job polls, seconds
_PROBE_TIMEOUT_S = 10.0
#: HTTP timeout for job forwards (the replica answers from its
#: admission path — queueing, not execution)
_FORWARD_TIMEOUT_S = 30.0
#: how long a spawned replica may take to print its startup line (cold
#: jax import + port bind)
_SPAWN_TIMEOUT_S = 180.0
#: clean-shutdown drain bound: in-flight jobs get this long to finish
#: before spawned replicas are stopped anyway
_DRAIN_TIMEOUT_S = 600.0


def _http_json(
    method: str, url: str, payload: "dict | None" = None,
    timeout: float = _PROBE_TIMEOUT_S,
) -> "tuple[int, Any]":
    """One JSON round-trip; returns ``(status, body)``.  4xx/5xx with a
    JSON body return normally (admission verdicts); transport errors
    (refused, reset, timeout) raise ``OSError``/``URLError``."""
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except (ValueError, OSError):
            return e.code, {}


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    return True


@dataclasses.dataclass
class RouterJob:
    """One accepted job's router-side record (mutated under the router
    lock; snapshots are JSON-safe copies)."""

    job_id: str
    payload: dict
    tenant: str
    priority: int
    key: str
    workdir: str
    out_dir: str
    source: str = "http"
    state: str = "queued"  # queued | routed | TERMINAL_STATES
    replica: "str | None" = None
    replica_job_id: "str | None" = None
    #: the request-tracing correlation id, minted at router admission
    #: and carried through every forward payload (re-routes keep it)
    trace_id: str = ""
    #: the client's resubmission token, remembered in the admission
    #: journal: a duplicate submission (before OR after a router
    #: restart) returns THIS job instead of double-running
    idempotency_key: "str | None" = None
    #: forward attempts so far (1 = first route; > 1 = re-routed).
    #: NOT the trace's hop count: a replica-side 429 deliberately
    #: refunds the attempt (saturation is not a route failure), so the
    #: retry-budget counter can move backwards — ``hops`` below is the
    #: monotone forward-try count the tracing plane reports
    attempts: int = 0
    #: forward tries EVER made (monotone): the ``request_span`` hop
    #: ordinal and ``request_done.hops`` — >= 2 means re-routed
    hops: int = 0
    submitted_t: float = dataclasses.field(default_factory=time.time)
    routed_t: "float | None" = None
    finished_t: "float | None" = None
    error: "str | None" = None
    #: the replica's last job snapshot (carries summary/outputs at
    #: terminal — the client's result body)
    snap: "dict | None" = None
    poll_fails: int = 0
    cancel_requested: bool = False
    # -- request-tracing bookkeeping (mutated under the router lock) ------
    #: when the CURRENT queue wait began (the t_mono clock — the same
    #: perf_counter the event log stamps, so spans anchor cleanly)
    queue_enter_mono: float = dataclasses.field(
        default_factory=time.perf_counter
    )
    #: the pending queue wait is a 429 backoff, not a plain queue wait
    backoff_pending: bool = False
    #: router-side blame accumulators (seconds) — the request_done
    #: split derives from these, replica time is the exact residual
    blame_acc: dict = dataclasses.field(
        default_factory=lambda: {
            "route_queue": 0.0, "throttle_backoff": 0.0,
            "forward": 0.0, "relay": 0.0,
        }
    )

    def status_locked(self) -> dict:
        out = {
            "job_id": self.job_id,
            "trace_id": self.trace_id,
            "state": self.state,
            "tenant": self.tenant,
            "priority": self.priority,
            "key": self.key,
            "replica": self.replica,
            "replica_job_id": self.replica_job_id,
            "attempts": self.attempts,
            "submitted_t": self.submitted_t,
            "routed_t": self.routed_t,
            "finished_t": self.finished_t,
            "workdir": self.workdir,
            "out_dir": self.out_dir,
        }
        if self.idempotency_key is not None:
            out["idempotency_key"] = self.idempotency_key
        if self.error is not None:
            out["error"] = self.error
        if self.snap is not None:
            out["result"] = self.snap
        return out


class _Replica:
    """One pool member (mutated under the router lock except where
    noted; the HTTP traffic to it happens outside the lock)."""

    def __init__(
        self, rid: str, base: str, spawned: bool,
        proc: "subprocess.Popen | None" = None,
        workdir: "str | None" = None,
    ) -> None:
        self.rid = rid
        self.base = base.rstrip("/")
        self.spawned = spawned
        self.proc = proc
        self.workdir = workdir
        #: starting → ready ⇄ unready, draining → stopped
        self.state = "starting"
        #: affinity keys warm (confirmed via /healthz or a completed
        #: job) or sticky (optimistically assigned at forward time) on
        #: this replica — recency-ordered, bounded
        self.warm: "collections.OrderedDict[str, float]" = (
            collections.OrderedDict()
        )
        #: router job ids currently routed here
        self.inflight: "set[str]" = set()
        #: a re-adopted replica's recorded pid (the previous router
        #: incarnation spawned it; this one owns no Popen handle)
        self.adopted_pid: "int | None" = None
        self.fails = 0
        self.last_health: "dict | None" = None
        self.last_health_t: "float | None" = None
        #: saturation cooldown (monotonic deadline): set when the
        #: replica answers 429 from its own admission — the router
        #: skips it until then instead of sleeping the dispatcher
        self.backoff_until = 0.0

    def note_key_locked(self, key: str) -> None:
        self.warm[key] = time.time()
        self.warm.move_to_end(key)
        while len(self.warm) > _WARM_KEYS_MAX:
            self.warm.popitem(last=False)

    def row_locked(self) -> dict:
        h = self.last_health or {}
        return {
            "replica": self.rid,
            "base": self.base,
            "state": self.state,
            "spawned": self.spawned,
            "inflight": len(self.inflight),
            "warm_keys": len(self.warm),
            "fails": self.fails,
            "queue_depth": h.get("queue_depth"),
            "running": h.get("running"),
            "warm_program_count": h.get("warm_program_count"),
            "health_age_s": (
                round(time.time() - self.last_health_t, 3)
                if self.last_health_t is not None else None
            ),
        }


class _RouterTelemetry:
    """The router's own events scope + ``lt_router_*`` instruments
    (the serve telemetry bundle's thin sibling: event log, registry,
    ``metrics.prom`` exporter, optional fleet publisher)."""

    def __init__(self, cfg: RouterConfig, publish_probes=None) -> None:
        os.makedirs(cfg.workdir, exist_ok=True)
        # every teardown-touched handle predeclared (the LT008 lesson):
        # _release() must be callable from any construction depth
        self._exporter: "PromFileExporter | None" = None
        self._publisher = None
        self.events = EventLog(os.path.join(cfg.workdir, "events.jsonl"))
        try:
            self.registry = MetricsRegistry()
            r = self.registry
            self._routed = r.counter(
                "lt_router_jobs_routed_total",
                "job forwards to a replica (re-routes included)",
            )
            self._warm_routed = r.counter(
                "lt_router_warm_routed_total",
                "forwards whose replica choice was warm-affinity-driven",
            )
            self._rerouted = r.counter(
                "lt_router_rerouted_total",
                "re-forwards after a failed forward or a dead/unready "
                "replica (attempt >= 2)",
            )
            self._throttled = r.counter(
                "lt_router_throttled_total",
                "submissions throttled 429 (tenant quota / queue full)",
            )
            self._queue_depth = r.gauge(
                "lt_router_queue_depth",
                "jobs queued at the router awaiting a replica",
            )
            self._replicas_ready = r.gauge(
                "lt_router_replicas_ready", "replicas currently routable"
            )
            self._replicas_total = r.gauge(
                "lt_router_replicas",
                "pool members not yet stopped (spawned + adopted)",
            )
            self._queue_wait_hist = r.histogram(
                "lt_router_queue_wait_seconds",
                "router queue wait, submit to first forward",
                buckets=_JOB_BUCKETS,
            )
            self._job_hist = r.histogram(
                "lt_router_job_seconds",
                "job latency through the router, submit to terminal",
                buckets=_JOB_BUCKETS,
            )
            self._jobs_done: "dict[str, Any]" = {}
            self._scales: "dict[str, Any]" = {}
            self.events.run_start(
                fingerprint="route",
                process_index=0,
                process_count=1,
                tiles_total=0,
                tiles_todo=0,
                tiles_skipped_resume=0,
                mesh_devices=0,
                impl="route",
            )
            self._exporter = PromFileExporter(
                self.registry,
                os.path.join(cfg.workdir, "metrics.prom"),
                interval_s=cfg.metrics_interval_s,
            ).start()
            if cfg.telemetry_dir is not None or cfg.spawn_replicas:
                from land_trendr_tpu.obs.publish import (
                    TelemetryPublisher,
                    telemetry_dir,
                )

                self._publisher = TelemetryPublisher(
                    cfg.telemetry_dir or telemetry_dir(cfg.workdir),
                    self.registry,
                    probes=publish_probes,
                    interval_s=cfg.health_interval_s * 2,
                    kind="route",
                )
                self._publisher.start()
        except BaseException:
            self._release()
            raise

    def _release(self) -> None:
        try:
            if self._publisher is not None:
                self._publisher.stop()
                self._publisher = None
        finally:
            try:
                if self._exporter is not None:
                    self._exporter.stop()
                    self._exporter = None
            finally:
                self.events.close()

    def _done_counter(self, status: str):
        c = self._jobs_done.get(status)
        if c is None:
            c = self._jobs_done[status] = self.registry.counter(
                "lt_router_jobs_done_total",
                "router jobs reaching a terminal state, by status",
                labels={"status": status},
            )
        return c

    def _scale_counter(self, direction: str):
        c = self._scales.get(direction)
        if c is None:
            c = self._scales[direction] = self.registry.counter(
                "lt_router_scale_total",
                "autoscaler actions, by direction",
                labels={"direction": direction},
            )
        return c

    # -- router hooks ------------------------------------------------------
    def job_submitted(self, job: RouterJob, queue_depth: int) -> None:
        self.events.emit(
            "job_submitted",
            job_id=job.job_id,
            trace_id=job.trace_id,
            tenant=job.tenant,
            priority=job.priority,
            queue_depth=queue_depth,
            source=job.source,
        )
        self._queue_depth.set(queue_depth)

    def request_span(
        self,
        job: RouterJob,
        name: str,
        start: float,
        end: float,
        replica: "str | None" = None,
        attempt: "int | None" = None,
        ok: "bool | None" = None,
    ) -> None:
        """One router-side segment of the request's journey (``start``/
        ``end`` on the t_mono clock, the ``span`` convention): queue
        waits, throttle backoffs, each forward HOP (failed ones too —
        the re-route story needs both), the terminal result relay."""
        fields: dict = {}
        if replica is not None:
            fields["replica"] = replica
        if attempt is not None:
            fields["attempt"] = attempt
        if ok is not None:
            fields["ok"] = bool(ok)
        self.events.emit(
            "request_span",
            trace_id=job.trace_id,
            job_id=job.job_id,
            name=name,
            start=round(start, 6),
            end=round(end, 6),
            tenant=job.tenant,
            **fields,
        )

    def request_done(
        self, job: RouterJob, latency_s: float, blame: dict, hops: int
    ) -> None:
        """The request's terminal record: the router-observed latency
        and its router-side blame partition (components sum to
        ``latency_s`` by construction — the value lint pins it)."""
        self.events.emit(
            "request_done",
            trace_id=job.trace_id,
            job_id=job.job_id,
            status=job.state,
            latency_s=round(latency_s, 6),
            tenant=job.tenant,
            hops=hops,
            blame=blame,
        )

    def job_rejected(self, reason: str, queue_depth: int) -> None:
        self.events.emit(
            "job_rejected", reason=reason, queue_depth=queue_depth
        )

    def journal_append(
        self, rec: str, segment: int, nbytes: int,
        job_id: "str | None" = None, trace_id: "str | None" = None,
    ) -> None:
        """One durably-committed admission-journal record."""
        fields: dict = {}
        if job_id:
            fields["job_id"] = job_id
        if trace_id:
            fields["trace_id"] = trace_id
        self.events.emit(
            "journal_append",
            rec=rec,
            segment=segment,
            bytes=nbytes,
            **fields,
        )

    def router_recovered(
        self, replayed: int, relayed: int, requeued: int,
        reattached: int, deduped: int, recovery_s: float, clean: bool,
    ) -> None:
        """The restart-reconciliation summary: every replayed
        non-terminal job landed in exactly one of relay / re-attach /
        requeue (the value lint pins the arithmetic)."""
        self.events.emit(
            "router_recovered",
            replayed=replayed,
            relayed=relayed,
            requeued=requeued,
            reattached=reattached,
            deduped=deduped,
            recovery_s=round(max(0.0, recovery_s), 6),
            clean=bool(clean),
        )

    # the capacity rig's emitters, borrowed from the serve Telemetry
    # bundle (they only touch ``self.events``): the load runner and
    # sweep analyzer report through whichever plane drives them, and
    # the single emit-site definition stays under the LT005 producer
    # check in obs/telemetry.py
    from land_trendr_tpu.obs.telemetry import Telemetry as _T

    load_phase = _T.load_phase
    sweep_point = _T.sweep_point
    sim_replay = _T.sim_replay
    del _T

    def tenant_throttled(
        self, tenant: str, reason: str, queue_depth: int
    ) -> None:
        self.events.emit(
            "tenant_throttled",
            tenant=tenant,
            reason=reason,
            queue_depth=queue_depth,
        )
        self._throttled.inc()

    def route_decision(
        self, job: RouterJob, replica: str, warm: bool,
        queue_depth: int, wait_s: float,
    ) -> None:
        self.events.emit(
            "route_decision",
            job_id=job.job_id,
            trace_id=job.trace_id,
            tenant=job.tenant,
            replica=replica,
            warm=bool(warm),
            key=job.key,
            attempt=job.attempts,
            queue_wait_s=round(max(0.0, wait_s), 6),
            queue_depth=queue_depth,
        )
        self._routed.inc()
        if warm:
            self._warm_routed.inc()
        if job.attempts > 1:
            self._rerouted.inc()
        else:
            self._queue_wait_hist.observe(
                max(0.0, wait_s), exemplar=job.trace_id or None
            )
        self._queue_depth.set(queue_depth)

    def replica_up(self, replica: _Replica) -> None:
        self.events.emit(
            "replica_up",
            replica=replica.rid,
            base=replica.base,
            spawned=replica.spawned,
        )

    def replica_down(self, replica: _Replica, reason: str) -> None:
        self.events.emit(
            "replica_down",
            replica=replica.rid,
            reason=reason,
            base=replica.base,
            inflight=len(replica.inflight),
        )

    def scale_decision(
        self, direction: str, burn: float, replicas: int,
        queue_depth: int, replica: "str | None" = None,
    ) -> None:
        fields: dict = {}
        if replica is not None:
            fields["replica"] = replica
        self.events.emit(
            "scale_decision",
            direction=direction,
            burn=round(max(0.0, float(burn)), 6),
            replicas=replicas,
            queue_depth=queue_depth,
            **fields,
        )
        self._scale_counter(direction).inc()

    def job_done(self, job: RouterJob, wall_s: float) -> None:
        fields: dict = {}
        if job.error:
            fields["error"] = job.error
        self.events.emit(
            "job_done",
            job_id=job.job_id,
            trace_id=job.trace_id,
            status=job.state,
            wall_s=round(wall_s, 6),
            **fields,
        )
        # the exemplar closes the metrics→traces loop: the bucket this
        # request landed in remembers its trace_id, so the p99 bucket
        # names requests lt_request can assemble
        self._job_hist.observe(wall_s, exemplar=job.trace_id or None)
        self._done_counter(job.state).inc()

    def pool_gauges(self, ready: int, total: int) -> None:
        self._replicas_ready.set(ready)
        self._replicas_total.set(total)

    def close(self, status: str, wall_s: float) -> None:
        try:
            self.events.emit(
                "run_done",
                status=status,
                tiles_done=0,
                pixels=0,
                wall_s=round(wall_s, 3),
                px_per_s=0.0,
                fit_rate=0.0,
            )
        finally:
            self._release()


class FleetRouter:
    """The serving fleet's front door (see the module docstring)."""

    def __init__(self, cfg: RouterConfig) -> None:
        self.cfg = cfg
        os.makedirs(cfg.workdir, exist_ok=True)
        self._lock = threading.Lock()
        # the condition WRAPS self._lock (the serve-server discipline)
        self._cond = threading.Condition(self._lock)
        self._jobs: "dict[str, RouterJob]" = {}
        #: tenant fair-share scheduling: the shared pure DRR core
        #: (fleet/scheduling.py — the capacity simulator replays the
        #: SAME class from the recorded decision log)
        self._weights = parse_tenant_weights(cfg.tenant_weights)
        self._drr = DrrQueue(self._weights)
        self._terminal = 0
        self._seq = 0
        self._rid_seq = 0
        self._stopping = False
        #: recovery-window gate: while a restarted router reconciles
        #: its journal, submissions answer 503 + Retry-After
        self._recovering = False
        #: idempotency-key → job_id (journal-replayed: survives restarts)
        self._idempotency: "dict[str, str]" = {}
        #: replayed non-terminal jobs awaiting reconciliation, in
        #: admission order: (job, folded journal record)
        self._pending_recovery: "list[tuple[RouterJob, dict]]" = []
        #: set by _replay_journal when the journal held any state —
        #: {"replayed": n, "deduped": keys_restored}
        self._replay_stats: "dict | None" = None
        #: the last recovery's summary (stats() serves it; lt top
        #: renders the RECOVERY line from it)
        self.recovery: "dict | None" = None
        self.pool: "list[_Replica]" = []
        #: recent TERMINAL requests (trace id, router blame split,
        #: hops) — the /debug/requests window, newest last, bounded
        #: (mutated under the router lock; 0 = an always-empty ring)
        self._recent_requests: "collections.deque" = collections.deque(
            maxlen=cfg.request_ring
        )

        from land_trendr_tpu.obs.publish import telemetry_dir

        self._telemetry_dir = cfg.telemetry_dir or telemetry_dir(cfg.workdir)
        self.scaler = (
            Autoscaler(
                min_replicas=cfg.min_replicas,
                max_replicas=cfg.max_replicas,
                up_burn=cfg.scale_up_burn,
                down_burn=cfg.scale_down_burn,
                for_s=cfg.scale_for_s,
                hold_s=cfg.scale_hold_s,
            )
            if cfg.autoscale else None
        )

        # every teardown-touched handle predeclared, so _shutdown is
        # callable from any depth of a failed construction (LT008)
        self.telemetry: "_RouterTelemetry | None" = None
        self._decisions: "DecisionLog | None" = None
        self._journal: "AdmissionJournal | None" = None
        self._fault_plan = None
        self._httpd = None
        self._http_thread = None
        self._control_stop = threading.Event()
        self._control_thread: "threading.Thread | None" = None
        self._t0 = time.time()

        try:
            if cfg.telemetry:
                self.telemetry = _RouterTelemetry(
                    cfg, publish_probes=self._fleet_probes
                )
            if cfg.decision_log:
                # recorded decision inputs+outputs — what the capacity
                # replay simulator re-executes byte-identically
                self._decisions = DecisionLog(
                    os.path.join(cfg.workdir, DECISIONS_NAME)
                )
                self._decisions.record(
                    "config",
                    weights=self._weights,
                    affinity=cfg.affinity,
                    autoscale=(
                        {
                            "min_replicas": cfg.min_replicas,
                            "max_replicas": cfg.max_replicas,
                            "up_burn": cfg.scale_up_burn,
                            "down_burn": cfg.scale_down_burn,
                            "for_s": cfg.scale_for_s,
                            "hold_s": cfg.scale_hold_s,
                        }
                        if cfg.autoscale else None
                    ),
                )
            if cfg.fault_schedule:
                self._fault_plan = faults.activate(
                    faults.parse_schedule(cfg.fault_schedule)
                )
                log.warning(
                    "router fault injection ACTIVE (%s) — this is a "
                    "soak run", cfg.fault_schedule,
                )
            if cfg.journal:
                # the journal opens AFTER the fault plan activates (its
                # appends fire the router.journal seam) and replays
                # BEFORE any admission can land
                self._journal = AdmissionJournal(
                    os.path.join(cfg.workdir, "journal"),
                    segment_bytes=cfg.journal_segment_mb * 2 ** 20,
                )
                self._replay_journal()
            self._readopt_replicas()
            for base in cfg.replicas:
                self._adopt_replica(base)
            if cfg.spawn_replicas:
                self._spawn_replicas(cfg.spawn_replicas)

            self._httpd = _RouterAPIServer(
                (cfg.route_host, cfg.route_port), self
            )
            self.port = int(self._httpd.server_address[1])
            http_thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="lt-route-http",
                daemon=True,
            )
            # bound only AFTER a successful start: shutdown() keys on it
            http_thread.start()
            self._http_thread = http_thread

            self._control_thread = threading.Thread(
                target=self._control_loop,
                name="lt-route-control",
                daemon=True,
            )
            self._control_thread.start()
            # reconciliation runs with the front door ALREADY serving
            # (503 + Retry-After during the window): by the time the
            # constructor returns, replayed jobs are relayed,
            # re-attached, or requeued-with-resume
            self._recover()
        except BaseException:
            self._shutdown(status="aborted")
            raise
        log.info(
            "routing on %s:%d over %d replica(s)%s",
            cfg.route_host, self.port, len(self.pool),
            " (autoscale on)" if self.scaler is not None else "",
        )

    # -- pool construction -------------------------------------------------
    def _next_rid_locked(self) -> str:
        self._rid_seq += 1
        return f"r{self._rid_seq - 1}"

    def _adopt_replica(self, base: str) -> None:
        with self._lock:
            rid = self._next_rid_locked()
            replica = _Replica(rid, base, spawned=False)
            self.pool.append(replica)
        # first health probe promotes it to ready (and emits replica_up)
        self._probe_replica(replica)

    def _spawn_replicas(self, n: int) -> None:
        """Spawn ``n`` replicas via the ``lt serve`` CLI: launch every
        process first (their cold jax imports overlap), then read each
        startup line for the bound port."""
        started = [self._launch_replica_proc() for _ in range(n)]
        for replica in started:
            self._await_replica_start(replica)

    def _launch_replica_proc(self) -> _Replica:
        with self._lock:
            rid = self._next_rid_locked()
        rdir = os.path.join(self.cfg.workdir, "replicas", rid)
        os.makedirs(rdir, exist_ok=True)
        cmd = [
            sys.executable, "-m", "land_trendr_tpu", "serve",
            "--workdir", rdir, "--serve-port", "0",
            "--publish", "--telemetry-dir", self._telemetry_dir,
            "--publish-interval-s", str(max(1.0, self.cfg.health_interval_s)),
            *self.cfg.replica_args,
        ]
        logf = open(os.path.join(rdir, "serve.log"), "ab")
        try:
            proc = subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=logf, text=True,
            )
        finally:
            # the child inherited the fd; the parent's handle is done
            logf.close()
        replica = _Replica(
            rid, base="pending", spawned=True, proc=proc, workdir=rdir
        )
        with self._lock:
            self.pool.append(replica)
        return replica

    def _await_replica_start(self, replica: _Replica) -> None:
        """Read the spawned replica's startup line (``{"serving": true,
        "port": N, ...}``) and point its base URL at the bound port."""
        proc = replica.proc
        assert proc is not None and proc.stdout is not None
        deadline = time.monotonic() + _SPAWN_TIMEOUT_S
        line = ""
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break
            ready, _, _ = select.select(
                [proc.stdout], [], [], min(1.0, deadline - time.monotonic())
            )
            if ready:
                line = proc.stdout.readline()
                break
        try:
            startup = json.loads(line) if line else None
        except json.JSONDecodeError:
            startup = None
        if not startup or not startup.get("serving"):
            tail = self._replica_log_tail(replica)
            raise RuntimeError(
                f"spawned replica {replica.rid} never reported its port "
                f"(exit={proc.poll()}); serve.log tail:\n{tail}"
            )
        with self._lock:
            replica.base = f"http://127.0.0.1:{int(startup['port'])}"
        self._persist_replica_meta(replica)
        self._probe_replica(replica)

    def _replica_log_tail(self, replica: _Replica, n: int = 2000) -> str:
        if not replica.workdir:
            return ""
        try:
            with open(os.path.join(replica.workdir, "serve.log"), "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - n))
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def _persist_replica_meta(self, replica: _Replica) -> None:
        """Record the spawned replica's base URL + pid (tmp + rename) so
        a restarted router can re-adopt the still-running process."""
        if not replica.workdir:
            return
        path = os.path.join(replica.workdir, "replica.json")
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(
                    {
                        "base": replica.base,
                        "pid": (
                            replica.proc.pid if replica.proc is not None
                            else replica.adopted_pid
                        ),
                    },
                    f,
                )
            os.replace(tmp, path)
        except OSError as e:
            log.warning(
                "replica meta persist failed for %s: %s", replica.rid, e
            )

    def _readopt_replicas(self) -> None:
        """Re-adopt live spawned replicas a crashed router left behind:
        scan ``replicas/*/replica.json``, keep the members whose
        recorded pid is alive AND whose ``/healthz`` answers, under
        their original rids.  The rid sequence advances past every
        existing dir first, so fresh spawns never collide with a
        re-adopted member's workdir."""
        root = os.path.join(self.cfg.workdir, "replicas")
        try:
            names = sorted(os.listdir(root))
        except OSError:
            return
        with self._lock:
            for name in names:
                if name.startswith("r") and name[1:].isdigit():
                    self._rid_seq = max(self._rid_seq, int(name[1:]) + 1)
        for name in names:
            rdir = os.path.join(root, name)
            try:
                with open(
                    os.path.join(rdir, "replica.json"), encoding="utf-8"
                ) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                continue
            base, pid = meta.get("base"), meta.get("pid")
            if not isinstance(base, str) or not isinstance(pid, int):
                continue
            if not _pid_alive(pid):
                continue
            try:
                status, _body = _http_json("GET", base + "/healthz")
            except Exception:
                continue
            if status != 200:
                continue
            replica = _Replica(
                name, base, spawned=True, proc=None, workdir=rdir
            )
            replica.adopted_pid = pid
            with self._lock:
                self.pool.append(replica)
            self._probe_replica(replica)
            log.info(
                "re-adopted replica %s at %s (pid %d)", name, base, pid
            )

    # -- crash recovery (journal replay + reconciliation) ------------------
    def _replay_journal(self) -> None:
        """Fold the journal into the job table: terminal jobs
        re-register (status GETs and idempotency dedupe keep answering
        across the restart), non-terminal ones queue for reconciliation
        in admission order."""
        folded = self._journal.replay()
        if not folded:
            return
        pending: "list[tuple[RouterJob, dict]]" = []
        keys = 0
        with self._lock:
            for jid, rec in folded.items():
                payload = rec.get("payload")
                if not isinstance(payload, dict):
                    continue
                job = RouterJob(
                    job_id=jid,
                    payload=payload,
                    tenant=str(rec.get("tenant") or "default"),
                    priority=int(rec.get("priority") or 0),
                    key=str(rec.get("key") or ""),
                    workdir=str(rec.get("workdir") or ""),
                    out_dir=str(rec.get("out_dir") or ""),
                    source=str(rec.get("source") or "journal"),
                    trace_id=str(rec.get("trace_id") or jid),
                )
                ikey = rec.get("idempotency_key")
                if isinstance(ikey, str) and ikey:
                    job.idempotency_key = ikey
                    self._idempotency[ikey] = jid
                    keys += 1
                t = rec.get("t")
                if isinstance(t, (int, float)):
                    job.submitted_t = float(t)
                if rec["status"] == "terminal":
                    job.state = str(rec.get("state") or "error")
                    job.error = rec.get("error")
                    self._terminal += 1
                else:
                    job.replica_job_id = rec.get("replica_job_id")
                    if rec["status"] == "forwarded":
                        # one forward happened in the previous life —
                        # the trace's hop ordinal continues from it
                        job.attempts = job.hops = 1
                    pending.append((job, rec))
                self._jobs[jid] = job
            self._pending_recovery = pending
            self._replay_stats = {"replayed": len(pending), "deduped": keys}
            self._recovering = bool(pending)
        log.info(
            "journal replay: %d job(s), %d non-terminal to reconcile",
            len(folded), len(pending),
        )

    def _recover(self) -> None:
        """Reconcile every replayed non-terminal job against the pool;
        the recovery-window 503 lifts when this returns.  Per job:
        terminal at its replica (status poll, or the durable
        ``jobs/<id>/result.json`` of a dead spawned replica) → relay
        the result; still running → re-attach (the poll loop takes
        over); unknown/unreachable (or an injected ``router.recover``
        fault) → requeue with the pinned workdir, so the resumed run
        completes byte-identically under the preserved trace id."""
        with self._lock:
            pending = self._pending_recovery
            self._pending_recovery = []
        if self._journal is None or self._replay_stats is None:
            with self._lock:
                self._recovering = False
            return
        t0 = time.perf_counter()
        counts = {"relayed": 0, "requeued": 0, "reattached": 0}
        try:
            for job, rec in pending:
                if self.telemetry is not None:
                    # re-introduce the trace id in THIS run's stream
                    # before any span can land under it
                    with self._lock:
                        depth = self._drr.depth
                    self.telemetry.job_submitted(job, depth)
                try:
                    outcome = self._reconcile_job(job, rec)
                except Exception as e:
                    log.warning(
                        "reconciliation of %s failed (%s); requeue+resume",
                        job.job_id, e,
                    )
                    outcome = self._requeue_recovered(job)
                counts[outcome] += 1
        finally:
            with self._lock:
                self._recovering = False
                self._cond.notify_all()
            summary = {
                "replayed": self._replay_stats["replayed"],
                "deduped": self._replay_stats["deduped"],
                "recovery_s": round(time.perf_counter() - t0, 6),
                "clean": bool(self._journal.was_clean),
                **counts,
            }
            self.recovery = summary
            if self.telemetry is not None:
                self.telemetry.router_recovered(**summary)
            log.info("recovery complete: %s", summary)
        try:
            # compaction now bounds the NEXT restart's replay
            self._journal.compact()
        except (OSError, JournalError) as e:
            log.warning("journal compaction failed: %s", e)

    def _reconcile_job(self, job: RouterJob, rec: dict) -> str:
        """One job's reconciliation; returns its outcome bucket
        (``relayed`` | ``reattached`` | ``requeued``)."""
        if self._journal.was_clean:
            # an uninterrupted drain left nothing running: route without
            # probing (a drained restart normally has no pending jobs at
            # all — this is the belt under that suspender)
            return self._requeue_recovered(job)
        try:
            faults.check("router.recover")
            replica, snap, p0, p1 = self._probe_recovered(rec)
        except Exception as e:
            log.warning(
                "recovery probe for %s failed (%s); requeue+resume",
                job.job_id, e,
            )
            return self._requeue_recovered(job)
        if snap is None:
            return self._requeue_recovered(job)
        terminal = snap.get("state") in TERMINAL_STATES
        if not terminal and replica is None:
            return self._requeue_recovered(job)
        with self._lock:
            job.snap = snap
            job.state = "routed"
            job.routed_t = time.time()
            if replica is not None:
                job.replica = replica.rid
            if terminal:
                # the probe that answered IS the result relay
                job.blame_acc["relay"] += max(0.0, p1 - p0)
            else:
                replica.inflight.add(job.job_id)
        if terminal:
            if self.telemetry is not None and replica is not None:
                self.telemetry.request_span(
                    job, "relay", p0, p1, replica=replica.rid,
                )
            self._finish_job(
                job, snap["state"], snap.get("error"),
                from_replica=replica, snap=snap,
            )
            return "relayed"
        log.info(
            "re-attached %s to %s (replica job %s)",
            job.job_id, job.replica, job.replica_job_id,
        )
        return "reattached"

    def _probe_recovered(
        self, rec: dict
    ) -> "tuple[_Replica | None, dict | None, float, float]":
        """Ask the journal's recorded replica what became of a job;
        falls back to the dead spawned replica's durable
        ``jobs/<id>/result.json``.  Returns ``(replica, snap, p0, p1)``
        with ``snap=None`` for unknown."""
        base = rec.get("replica_base")
        rjid = rec.get("replica_job_id")
        p0 = p1 = time.perf_counter()
        if not base or not rjid:
            return None, None, p0, p1  # never forwarded: plain requeue
        with self._lock:
            replica = next(
                (
                    r for r in self.pool
                    if r.base == base and r.state != "stopped"
                ),
                None,
            )
        if replica is not None:
            status, snap = _http_json("GET", f"{replica.base}/jobs/{rjid}")
            p1 = time.perf_counter()
            if status == 200 and isinstance(snap, dict):
                return replica, snap, p0, p1
            return replica, None, p0, p1
        snap = self._result_from_disk(rjid)
        p1 = time.perf_counter()
        return None, snap, p0, p1

    def _result_from_disk(self, rjid: str) -> "dict | None":
        """A dead spawned replica's terminal verdict, if it got as far
        as the atomic ``result.json`` write before dying."""
        root = os.path.join(self.cfg.workdir, "replicas")
        try:
            names = sorted(os.listdir(root))
        except OSError:
            return None
        for name in names:
            path = os.path.join(root, name, "jobs", rjid, "result.json")
            try:
                with open(path, encoding="utf-8") as f:
                    snap = json.load(f)
            except (OSError, ValueError):
                continue
            if (
                isinstance(snap, dict)
                and snap.get("state") in TERMINAL_STATES
            ):
                return snap
        return None

    def _requeue_recovered(self, job: RouterJob) -> str:
        """Queue a replayed job for (re-)routing with a fresh retry
        budget.  Back-enqueue ON PURPOSE: recovery iterates in admission
        order into queues no new submission can reach (the 503 window),
        so FIFO here IS front-of-line relative to post-recovery traffic
        — a front-enqueue would reverse the replayed order instead."""
        with self._lock:
            job.state = "queued"
            job.replica = None
            job.replica_job_id = None
            job.attempts = 0
            job.poll_fails = 0
            job.queue_enter_mono = time.perf_counter()
            job.backoff_pending = False
            self._enqueue_locked(job)
            self._cond.notify_all()
        return "requeued"

    def _journal_record(self, kind: str, job: RouterJob, **fields) -> None:
        """Append one journal record + its ``journal_append`` event.
        ``admitted`` failures propagate (the admission must fail
        loudly); ``forwarded``/``terminal`` failures degrade to a log
        line — the job is already durable, and recovery treats a
        missing record as unknown → requeue + resume."""
        if self._journal is None:
            return
        try:
            seg, nbytes = self._journal.append(kind, job.job_id, **fields)
        except JournalError:
            if kind == "admitted":
                raise
            log.warning(
                "journal %s append failed for %s (recovery degrades to "
                "requeue+resume)", kind, job.job_id,
            )
            return
        if self.telemetry is not None:
            self.telemetry.journal_append(
                kind, seg, nbytes,
                job_id=job.job_id, trace_id=job.trace_id,
            )

    # -- admission ---------------------------------------------------------
    def submit(self, payload: dict, source: str = "http") -> dict:
        """One submission through router admission; returns the queued
        job's snapshot or raises :class:`~land_trendr_tpu.serve.server.
        Rejection` (429 carries Retry-After at the HTTP layer)."""
        try:
            if not isinstance(payload, dict):
                raise ValueError(
                    f"job request must be a JSON object, got "
                    f"{type(payload).__name__}"
                )
            req = JobRequest.from_payload(payload)
        except ValueError as e:
            if self.telemetry is not None:
                with self._lock:
                    depth = self._drr.depth
                self.telemetry.job_rejected("bad_request", depth)
            raise Rejection(400, "bad_request", str(e)) from None
        key = req.affinity_key()
        throttle = None
        snap = depth = job = None
        dedup = False
        with self._lock:
            depth = self._drr.depth
            prior = (
                self._jobs.get(self._idempotency.get(req.idempotency_key))
                if req.idempotency_key else None
            )
            if prior is not None:
                # idempotent resubmission: the journal remembered the
                # key (across restarts too) — answer with the EXISTING
                # job instead of double-running, whatever else is going
                # on (dedupe costs no queue slot, so no ladder applies)
                snap = prior.status_locked()
                snap["deduped"] = True
                dedup = True
            elif self._recovering:
                throttle = (
                    503, "recovering",
                    "router is reconciling its admission journal after "
                    "a restart; retry shortly",
                )
            elif self._stopping:
                throttle = (503, "shutting_down", "router is draining")
            elif depth >= self.cfg.route_queue_depth:
                throttle = (
                    429, "queue_full",
                    f"router queue depth {depth} at the configured "
                    f"bound {self.cfg.route_queue_depth}; retry later",
                )
            else:
                held = sum(
                    1 for j in self._jobs.values()
                    if j.tenant == req.tenant
                    and j.state in ("queued", "routed")
                )
                if held >= self.cfg.tenant_quota:
                    throttle = (
                        429, "tenant_quota",
                        f"tenant {req.tenant!r} holds {held} job(s) at "
                        f"the configured quota {self.cfg.tenant_quota}; "
                        "retry later",
                    )
            if throttle is None and not dedup:
                self._seq += 1
                job_id = f"rt-{os.getpid()}-{self._seq:05d}"
                job_root = os.path.join(self.cfg.workdir, "jobs", job_id)
                job = RouterJob(
                    job_id=job_id,
                    payload=dict(payload),
                    tenant=req.tenant,
                    priority=req.priority,
                    key=key,
                    # the request-tracing id is minted HERE, at the
                    # fleet's admission edge; the client may also pin
                    # its own (a proxy threading an upstream id)
                    trace_id=req.trace_id or uuid.uuid4().hex[:16],
                    # the router pins the dirs (unless the client pinned
                    # its own — the explicit-resume path), so a re-route
                    # RESUMES the same manifest on the next replica
                    workdir=req.workdir
                    or os.path.join(job_root, "work"),
                    out_dir=req.out_dir or os.path.join(job_root, "out"),
                    source=source,
                )
                job.idempotency_key = req.idempotency_key
                # the WRITE-AHEAD contract: the admitted record commits
                # BEFORE the job is registered or the client sees 200 —
                # a job the journal cannot make durable is never
                # admitted (503 journal_error), and a crash after this
                # line replays the job instead of orphaning it
                try:
                    self._journal_record(
                        "admitted", job,
                        payload=job.payload,
                        tenant=job.tenant,
                        priority=job.priority,
                        key=job.key,
                        trace_id=job.trace_id,
                        idempotency_key=job.idempotency_key,
                        workdir=job.workdir,
                        out_dir=job.out_dir,
                        source=job.source,
                        t=job.submitted_t,
                    )
                except JournalError as e:
                    throttle = (
                        503, "journal_error",
                        f"admission journal append failed ({e}); the "
                        "job was NOT accepted — retry later",
                    )
                    job = None
            if job is not None and throttle is None and not dedup:
                if job.idempotency_key:
                    self._idempotency[job.idempotency_key] = job.job_id
                # registered but NOT yet enqueued: the job becomes
                # routable only after job_submitted is durably in the
                # stream, or the dispatcher's first request_span could
                # land ahead of the trace's introduction (the orphan
                # the referential lint flags)
                self._jobs[job.job_id] = job
                depth = self._drr.depth + 1  # the enqueue below joins it
                snap = job.status_locked()
        if dedup:
            log.info(
                "idempotent resubmission answered with %s (key=%s)",
                snap["job_id"], req.idempotency_key,
            )
            return snap
        if throttle is not None:
            status, reason, detail = throttle
            log.warning(
                "submission throttled (%s, tenant=%s)", reason, req.tenant
            )
            if self.telemetry is not None:
                if status == 429:
                    self.telemetry.tenant_throttled(req.tenant, reason, depth)
                else:
                    self.telemetry.job_rejected(reason, depth)
            raise Rejection(status, reason, detail)
        try:
            if self.telemetry is not None:
                self.telemetry.job_submitted(job, depth)
        finally:
            # enqueue even when the emit raised (full disk): an
            # accepted job must never be orphaned un-routable.  A
            # cancel that landed in the gap already marked the job
            # terminal — the pick loop skips non-queued entries.
            with self._lock:
                self._enqueue_locked(job)
                self._cond.notify_all()
        return snap

    def _enqueue_locked(self, job: RouterJob, front: bool = False) -> None:
        self._drr.enqueue(job.tenant, job.job_id, front=front)
        if self._decisions is not None:
            # decision records stamp WALL time: the autoscale loop's
            # convention, so one log's nows share a clock domain and the
            # replay's recorded-span/speedup math is meaningful
            self._decisions.record(
                "enqueue", tenant=job.tenant, job_id=job.job_id,
                front=front, now=time.time(),
            )

    # -- fair-share scheduling (deficit round-robin) -----------------------
    def _pick_job_locked(self) -> "RouterJob | None":
        """Deficit round-robin over the non-empty tenant queues —
        delegated to the shared pure core
        (:class:`~land_trendr_tpu.fleet.scheduling.DrrQueue`, the one
        copy the capacity replay simulator also runs).  Entries whose
        job is no longer ``queued`` (cancelled in the submit gap) are
        skipped; the skip consumes the queue slot."""
        picked = self._drr.pick(
            live=lambda jid: self._jobs[jid].state == "queued"
        )
        if picked is None:
            return None
        tenant, job_id = picked
        if self._decisions is not None:
            self._decisions.record(
                "pick", tenant=tenant, job_id=job_id, now=time.time()
            )
        return self._jobs[job_id]

    # -- replica choice ----------------------------------------------------
    def _routable_locked(self, r: _Replica, now: float) -> bool:
        return (
            r.state == "ready"
            and len(r.inflight) < self.cfg.replica_inflight
            and r.backoff_until <= now
        )

    def _choose_replica_locked(
        self, key: str
    ) -> "tuple[_Replica | None, bool]":
        now = time.monotonic()
        ready = [r for r in self.pool if self._routable_locked(r, now)]
        # the choice itself is the shared pure function over the
        # routable-candidate snapshot (fleet/scheduling.py) — the
        # capacity simulator replays the SAME function on the recorded
        # candidates
        cands = [(r.rid, len(r.inflight), key in r.warm) for r in ready]
        rid, warm = choose_replica(cands, self.cfg.affinity)
        if self._decisions is not None and cands:
            self._decisions.record(
                "choose", key=key, affinity=self.cfg.affinity,
                candidates=[list(c) for c in cands],
                chosen=rid, warm=warm, now=time.time(),
            )
        if rid is None:
            return None, False
        return next(r for r in ready if r.rid == rid), warm

    # -- the dispatcher ----------------------------------------------------
    def serve_forever(self) -> None:
        """Route jobs on THIS thread until stopped, then shut the pool
        and telemetry down (drain first on a clean stop)."""
        status = "ok"
        try:
            while True:
                picked = self._next_route()
                if picked is None:
                    break
                self._route_job(*picked)
        except KeyboardInterrupt:
            # Ctrl-C — and SIGTERM, which ``lt route`` maps here — IS
            # the orchestrator's clean stop: keep status "ok" so
            # _shutdown drains routed jobs and the journal earns its
            # clean marker (a second interrupt aborts the drain itself)
            pass
        except BaseException:
            status = "aborted"
            raise
        finally:
            self._shutdown(status=status)

    def _next_route(self) -> "tuple[RouterJob, _Replica, bool] | None":
        with self._lock:
            while True:
                if self._stopping:
                    return None
                job = None
                if self._drr.pending:
                    # peek capacity BEFORE consuming a queue entry: a
                    # popped job with no replica to take it would lose
                    # its DRR slot
                    now = time.monotonic()
                    head_ready = any(
                        self._routable_locked(r, now) for r in self.pool
                    )
                    if head_ready:
                        job = self._pick_job_locked()
                if job is not None:
                    replica, warm = self._choose_replica_locked(job.key)
                    if replica is None:
                        # capacity vanished between peek and pick: put
                        # the job back at its queue front and wait
                        self._enqueue_locked(job, front=True)
                    else:
                        job.attempts += 1
                        job.state = "routed"
                        job.replica = replica.rid
                        # optimistic stickiness: the NEXT same-shape job
                        # must prefer this replica even while this one
                        # is still compiling there
                        replica.note_key_locked(job.key)
                        replica.inflight.add(job.job_id)
                        return job, replica, warm
                self._cond.wait(timeout=0.2)

    def _close_queue_span(self, job: RouterJob, now_m: float) -> None:
        """Close the job's pending queue wait (route_queue, or
        throttle_backoff when a replica 429 re-queued it): fold the
        seconds into the blame accumulator under the lock, emit the
        ``request_span`` outside it."""
        with self._lock:
            q0 = job.queue_enter_mono
            comp = (
                "throttle_backoff" if job.backoff_pending else "route_queue"
            )
            job.backoff_pending = False
            job.blame_acc[comp] += max(0.0, now_m - q0)
        if self.telemetry is not None:
            self.telemetry.request_span(job, comp, q0, now_m)

    def _route_job(self, job: RouterJob, replica: _Replica, warm: bool) -> None:
        """One forward (no lock held during HTTP).  Failure paths:
        transport error / injected ``router.forward`` fault → the job
        re-enters its tenant queue (front) bounded by ``route_retries``;
        a replica-side 429 → requeue without burning a retry (the
        replica is saturated, not broken); a replica-side 400 → the
        job is terminally ``config_error`` (no replica will take it)."""
        self._close_queue_span(job, time.perf_counter())
        payload = dict(job.payload)
        payload["workdir"] = job.workdir
        payload["out_dir"] = job.out_dir
        payload["resume"] = True
        # the trace context crosses the wire IN the job payload: the
        # replica's admission validates it into JobRequest.trace_id and
        # the job's whole run scope carries it — re-route hops forward
        # the SAME id, so both hops assemble under one trace
        payload["trace_id"] = job.trace_id
        err: "str | None" = None
        body = None
        status = None
        f0 = time.perf_counter()
        try:
            faults.check("router.forward")
            status, body = _http_json(
                "POST", replica.base + "/jobs", payload,
                timeout=_FORWARD_TIMEOUT_S,
            )
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
        f1 = time.perf_counter()
        forward_ok = err is None and status == 200
        with self._lock:
            job.blame_acc["forward"] += max(0.0, f1 - f0)
            # the monotone hop ordinal — job.attempts moves backwards
            # on a 429 refund, so it cannot number the trace's hops
            job.hops += 1
            hop = job.hops
        if self.telemetry is not None:
            # every forward TRY is a hop span — a failed first hop plus
            # a succeeded second is exactly the re-route story
            self.telemetry.request_span(
                job, "forward", f0, f1,
                replica=replica.rid, attempt=hop, ok=forward_ok,
            )
        now = time.time()
        if err is None and status == 200 and isinstance(body, dict):
            with self._lock:
                job.replica_job_id = body.get("job_id")
                job.routed_t = now
                job.snap = body
                depth = self._drr.depth
                # a cancel that landed while the forward was in flight
                # (replica_job_id still None) had nowhere to go — honor
                # it now that the replica id exists
                relay_cancel = job.cancel_requested
            # durable AFTER the replica accepted, BEFORE anything else:
            # a crash past this line reconciles by asking THIS replica
            self._journal_record(
                "forwarded", job,
                replica_base=replica.base,
                replica_job_id=job.replica_job_id,
                replica=replica.rid,
                t=now,
            )
            if relay_cancel:
                try:
                    _http_json(
                        "POST",
                        f"{replica.base}/jobs/{job.replica_job_id}/cancel",
                        {},
                    )
                except Exception as e:
                    log.warning("deferred cancel forward failed: %s", e)
            if self.telemetry is not None:
                self.telemetry.route_decision(
                    job, replica.rid, warm, depth,
                    wait_s=now - job.submitted_t,
                )
            log.info(
                "job %s → %s (%s, tenant=%s, attempt %d)",
                job.job_id, replica.rid, "warm" if warm else "cold",
                job.tenant, job.attempts,
            )
            return
        if err is None and status == 429:
            # saturated replica (its own admission): not a route retry —
            # the job returns to its queue front and the REPLICA gets a
            # cooldown the choosers skip (never a dispatcher sleep: one
            # saturated replica must not head-of-line-block routing for
            # every other tenant and replica)
            with self._lock:
                replica.inflight.discard(job.job_id)
                replica.backoff_until = time.monotonic() + min(
                    0.5, self.cfg.health_interval_s
                )
                if job.state == "routed":  # vs a racing death sweep
                    job.state = "queued"
                    job.replica = None
                    job.attempts -= 1
                    # the wait until the next forward is a THROTTLE
                    # backoff, not a plain queue wait — blame it as such
                    job.queue_enter_mono = time.perf_counter()
                    job.backoff_pending = True
                    self._enqueue_locked(job, front=True)
                self._cond.notify_all()
            return
        if err is None and status is not None and 400 <= status < 500:
            detail = (body or {}).get("detail") or (body or {}).get("error")
            self._finish_job(
                job, "config_error",
                f"replica {replica.rid} refused the request "
                f"({status}): {detail}",
                from_replica=replica,
            )
            return
        # transport failure / 5xx / injected fault: the replica is
        # suspect, the job is NOT lost — re-route it
        reason = err or f"HTTP {status}"
        log.warning(
            "forward of %s to %s failed (%s)", job.job_id, replica.rid,
            reason,
        )
        self._note_replica_failure(replica)
        self._requeue_job(job, replica, reason)

    def _requeue_job(
        self, job: RouterJob, replica: "_Replica | None", reason: str
    ) -> None:
        """Return a routed job to its tenant queue (front), or finish
        it ``error`` when its route retries are exhausted."""
        exhausted = False
        with self._lock:
            if replica is not None:
                replica.inflight.discard(job.job_id)
            if job.state != "routed":
                # terminal, or ALREADY requeued by a racing path (the
                # dispatcher's forward failure vs the control thread's
                # replica-death sweep): a second enqueue would route the
                # job twice
                return
            if job.attempts >= 1 + self.cfg.route_retries:
                exhausted = True
            else:
                job.state = "queued"
                job.replica = None
                job.replica_job_id = None
                job.poll_fails = 0
                # a fresh queue wait opens for the re-route hop
                job.queue_enter_mono = time.perf_counter()
                job.backoff_pending = False
                self._enqueue_locked(job, front=True)
                self._cond.notify_all()
        if exhausted:
            self._finish_job(
                job, "error",
                f"route retries exhausted after {job.attempts} "
                f"attempt(s); last: {reason} — resubmit with "
                f"\"workdir\": {job.workdir!r} to resume",
                from_replica=None,
            )

    @staticmethod
    def _blame_split(acc: dict, latency_s: float) -> dict:
        """The router-observed blame partition: the accumulated
        router-side components (queue waits, backoffs, forward hops,
        the result relay), with the REPLICA's share the exact residual
        — so the components sum to ``latency_s`` by construction (the
        ``request_done`` value lint pins it).  A wall-clock step that
        leaves the monotonic accumulators over the wall latency scales
        them down proportionally rather than emitting a negative
        residual."""
        comps = {k: v for k, v in acc.items() if v > 1e-9}
        used = sum(comps.values())
        latency_s = max(0.0, latency_s)
        if used > latency_s:
            scale = latency_s / used if used > 0 else 0.0
            comps = {k: v * scale for k, v in comps.items()}
            used = latency_s
        comps["replica"] = latency_s - used
        return {k: round(v, 6) for k, v in sorted(comps.items())}

    def _finish_job(
        self,
        job: RouterJob,
        state: str,
        error: "str | None",
        from_replica: "_Replica | None",
        snap: "dict | None" = None,
    ) -> None:
        open_queue: "tuple[float, float, str] | None" = None
        with self._lock:
            if job.state in TERMINAL_STATES:
                return
            if job.state == "queued":
                # terminal while still queued (cancel / shutdown): the
                # open queue wait closes into the blame here — nothing
                # else ever will
                now_m = time.perf_counter()
                comp = (
                    "throttle_backoff" if job.backoff_pending
                    else "route_queue"
                )
                job.blame_acc[comp] += max(
                    0.0, now_m - job.queue_enter_mono
                )
                open_queue = (job.queue_enter_mono, now_m, comp)
            job.state = state
            job.error = error if error is not None else job.error
            if snap is not None:
                job.snap = snap
            job.finished_t = time.time()
            self._terminal += 1
            if from_replica is not None:
                from_replica.inflight.discard(job.job_id)
            wall_s = job.finished_t - job.submitted_t
            blame = self._blame_split(job.blame_acc, wall_s)
            hops = job.hops
            self._recent_requests.append({
                "trace_id": job.trace_id,
                "job_id": job.job_id,
                "tenant": job.tenant,
                "status": state,
                "latency_s": round(wall_s, 6),
                "blame": blame,
                "hops": hops,
                "replica": job.replica,
                "finished_t": job.finished_t,
            })
            self._cond.notify_all()
        self._journal_record(
            "terminal", job, state=state, error=job.error,
            t=job.finished_t,
        )
        log.info(
            "job %s %s in %.2fs%s",
            job.job_id, state, wall_s,
            f" ({job.error})" if job.error else "",
        )
        if self.telemetry is not None:
            if open_queue is not None:
                self.telemetry.request_span(job, open_queue[2],
                                            open_queue[0], open_queue[1])
            self.telemetry.request_done(job, wall_s, blame, hops)
            self.telemetry.job_done(job, wall_s)

    # -- the control loop (health, polls, autoscale) -----------------------
    def _control_loop(self) -> None:
        while not self._control_stop.wait(self.cfg.health_interval_s):
            try:
                self.control_beat()
            except Exception:
                # the control plane must never take down the router
                log.debug("control beat failed", exc_info=True)

    def control_beat(self, now: "float | None" = None) -> None:
        """One control beat: probe every replica, poll every routed
        job, feed the autoscaler.  Called from the control thread (and
        directly by tests, with a pinned ``now``)."""
        if now is None:
            now = time.time()
        with self._lock:
            replicas = list(self.pool)
        for replica in replicas:
            self._probe_replica(replica)
        with self._lock:
            routed = [
                j for j in self._jobs.values() if j.state == "routed"
            ]
            ready = sum(1 for r in self.pool if r.state == "ready")
            total = sum(1 for r in self.pool if r.state != "stopped")
        for job in routed:
            self._poll_job(job)
        if self.telemetry is not None:
            self.telemetry.pool_gauges(ready, total)
        if self.scaler is not None:
            self.scale_tick(self._pod_burn(now), now)
        self._reap_draining()

    def _note_replica_failure(self, replica: _Replica) -> None:
        emit = None
        with self._lock:
            replica.fails += 1
            if (
                replica.fails >= self.cfg.unhealthy_after
                and replica.state == "ready"
            ):
                # unready ≠ failed jobs: accepted jobs keep polling and
                # finish wherever they actually run
                replica.state = "unready"
                emit = replica
        if emit is not None and self.telemetry is not None:
            self.telemetry.replica_down(emit, "health")

    def _probe_replica(self, replica: _Replica) -> None:
        if replica.state == "stopped":
            return
        proc = replica.proc
        if proc is not None and proc.poll() is not None:
            self._replica_died(replica, f"process exited {proc.poll()}")
            return
        if (
            proc is None
            and replica.adopted_pid is not None
            and not _pid_alive(replica.adopted_pid)
        ):
            self._replica_died(replica, "re-adopted process exited")
            return
        failed = False
        health: "dict | None" = None
        try:
            if faults.fired("replica.health"):
                failed = True
            else:
                status, health = _http_json(
                    "GET", replica.base + "/healthz"
                )
                failed = status != 200 or not isinstance(health, dict)
        except Exception:
            failed = True
        if failed:
            self._note_replica_failure(replica)
            return
        emit_up = None
        with self._lock:
            replica.fails = 0
            replica.last_health = health
            replica.last_health_t = time.time()
            for key in health.get("warm_keys") or []:
                if isinstance(key, str):
                    replica.note_key_locked(key)
            if replica.state in ("starting", "unready"):
                replica.state = "ready"
                emit_up = replica
                self._cond.notify_all()
        if emit_up is not None and self.telemetry is not None:
            self.telemetry.replica_up(emit_up)

    def _replica_died(self, replica: _Replica, reason: str) -> None:
        """A spawned replica's process is gone: mark it stopped and
        re-route every job it held — recorded tiles are durable in the
        router-pinned workdirs, so the re-routed submissions resume."""
        orphans: "list[RouterJob]" = []
        emit = None
        with self._lock:
            if replica.state == "stopped":
                return
            was_draining = replica.state == "draining"
            replica.state = "stopped"
            emit = replica
            for job_id in sorted(replica.inflight):
                job = self._jobs.get(job_id)
                if job is not None and job.state == "routed":
                    orphans.append(job)
        if self.telemetry is not None and emit is not None:
            self.telemetry.replica_down(
                emit, "scale_down" if was_draining else "dead"
            )
        log.warning(
            "replica %s down (%s); re-routing %d job(s)",
            replica.rid, reason, len(orphans),
        )
        for job in orphans:
            self._requeue_job(job, replica, f"replica {replica.rid} died")

    def _poll_job(self, job: RouterJob) -> None:
        with self._lock:
            if job.state != "routed" or job.replica_job_id is None:
                return
            replica = self._replica_locked(job.replica)
        if replica is None:
            self._requeue_job(job, None, "replica record vanished")
            return
        p0 = time.perf_counter()
        try:
            status, snap = _http_json(
                "GET", f"{replica.base}/jobs/{job.replica_job_id}"
            )
        except Exception as e:
            dead = replica.proc is not None and replica.proc.poll() is not None
            with self._lock:
                job.poll_fails += 1
                fails = job.poll_fails
                state = replica.state
            if dead:
                self._replica_died(replica, f"poll failed: {e}")
            elif (
                state in ("unready", "stopped")
                and fails >= self.cfg.unhealthy_after
            ):
                self._requeue_job(
                    job, replica,
                    f"replica {replica.rid} unreachable ({e})",
                )
            return
        if status == 404:
            # the replica restarted (or never accepted it): re-route
            self._requeue_job(
                job, replica, f"replica {replica.rid} lost the job"
            )
            return
        if status != 200 or not isinstance(snap, dict):
            return
        p1 = time.perf_counter()
        terminal = snap.get("state") in TERMINAL_STATES
        relayed = False
        with self._lock:
            job.poll_fails = 0
            job.snap = snap
            if terminal and job.state == "routed":
                # routing FEEDBACK: the shape ran here, its programs
                # are resident — confirm the sticky key as warm
                replica.note_key_locked(job.key)
                # the poll that DISCOVERED the terminal state is the
                # result relay — the last router-side hop of the journey
                job.blame_acc["relay"] += max(0.0, p1 - p0)
                relayed = True
        if terminal:
            if relayed and self.telemetry is not None:
                self.telemetry.request_span(
                    job, "relay", p0, p1, replica=replica.rid,
                )
            self._finish_job(
                job, snap["state"], snap.get("error"),
                from_replica=replica, snap=snap,
            )

    def _replica_locked(self, rid: "str | None") -> "_Replica | None":
        for r in self.pool:
            if r.rid == rid:
                return r
        return None

    # -- autoscaling -------------------------------------------------------
    def _pod_burn(self, now: float) -> "float | None":
        """The pod ``lt_slo_burn_rate`` from the shared telemetry
        directory (the PR-11 fleet plane: replicas publish snapshots,
        ``fold_dir`` merges them, gauges default to the pod-max policy
        — the alerting-relevant fold)."""
        from land_trendr_tpu.obs import aggregate

        try:
            view = aggregate.fold_dir(
                self._telemetry_dir, now=now, newer_than=now - 600.0
            )
        except Exception:
            return None
        for inst in view.get("metrics", []):
            if inst["name"] == "lt_slo_burn_rate" and not inst.get("labels"):
                v = inst.get("value")
                return None if v is None else float(v)
        return None

    def scale_tick(self, burn: "float | None", now: float) -> "str | None":
        """Feed one burn observation to the autoscaler and ACT on the
        decision (spawn / begin a drain).  Split from the control loop
        so tests and the soak can drive a scripted burn history
        deterministically; returns the action taken."""
        if self.scaler is None:
            return None
        with self._lock:
            queue_depth = self._drr.depth
            spawned_live = [
                r for r in self.pool
                if r.spawned and r.state in ("starting", "ready", "unready")
            ]
            decision = self.scaler.decide(
                burn, queue_depth, len(spawned_live), now
            )
            if self._decisions is not None:
                self._decisions.record(
                    "autoscale", burn=burn, queue_depth=queue_depth,
                    replicas=len(spawned_live), now=now,
                    decision=decision,
                )
        if decision == "up":
            replica = self._launch_replica_proc()
            if self.telemetry is not None:
                with self._lock:
                    n = len([
                        r for r in self.pool
                        if r.spawned and r.state != "stopped"
                    ])
                self.telemetry.scale_decision(
                    "up", burn or 0.0, n, queue_depth, replica=replica.rid
                )
            # await the startup line OFF the control thread: a cold jax
            # replica start takes tens of seconds, and blocking here
            # would stall every health probe, job poll and drain reap
            # for the duration
            threading.Thread(
                target=self._await_scale_up,
                args=(replica,),
                name=f"lt-route-spawn-{replica.rid}",
                daemon=True,
            ).start()
            return "up"
        if decision == "down":
            victim = None
            with self._lock:
                candidates = sorted(
                    (r for r in spawned_live if r.state == "ready"),
                    key=lambda r: (len(r.inflight), len(r.warm), r.rid),
                )
                if candidates:
                    victim = candidates[0]
                    # drain-before-kill: no new routes land here; the
                    # reaper stops the process once inflight hits zero
                    victim.state = "draining"
                    n = len([
                        r for r in self.pool
                        if r.spawned and r.state != "stopped"
                    ])
            if victim is not None and self.telemetry is not None:
                self.telemetry.scale_decision(
                    "down", burn or 0.0, n - 1, queue_depth,
                    replica=victim.rid,
                )
            return "down" if victim is not None else None
        return None

    def _await_scale_up(self, replica: _Replica) -> None:
        try:
            self._await_replica_start(replica)
        except RuntimeError as e:
            log.error("scale-up replica failed to start: %s", e)
            self._replica_died(replica, str(e))

    def _reap_draining(self) -> None:
        """Stop drained replicas: a ``draining`` member with zero
        in-flight jobs gets the ``lt serve`` process's documented clean
        shutdown (SIGINT — its dispatcher finishes teardown, manifests
        stay resumable)."""
        with self._lock:
            drained = [
                r for r in self.pool
                if r.state == "draining" and not r.inflight
            ]
        for replica in drained:
            self._stop_replica_proc(replica)
            with self._lock:
                replica.state = "stopped"
            if self.telemetry is not None:
                self.telemetry.replica_down(replica, "scale_down")

    @staticmethod
    def _stop_replica_proc(replica: _Replica) -> None:
        proc = replica.proc
        if proc is None:
            # re-adopted after a restart: not our child — send the
            # recorded pid the same documented clean shutdown
            if replica.adopted_pid is not None:
                try:
                    os.kill(replica.adopted_pid, signal.SIGINT)
                except OSError:
                    pass
            return
        if proc.poll() is not None:
            return
        try:
            proc.send_signal(signal.SIGINT)
            proc.wait(timeout=60)
        except (ProcessLookupError, subprocess.TimeoutExpired):
            try:
                proc.kill()
                proc.wait(timeout=10)
            except (ProcessLookupError, subprocess.TimeoutExpired):
                pass

    # -- status / cancel ---------------------------------------------------
    def job_status(self, job_id: str) -> "dict | None":
        with self._lock:
            job = self._jobs.get(job_id)
            return job.status_locked() if job is not None else None

    def jobs(self) -> list:
        with self._lock:
            return [j.status_locked() for j in self._jobs.values()]

    def cancel(self, job_id: str) -> "dict | None":
        """Cancel one router job: a queued job goes terminal here; a
        routed one has the cancel forwarded to its replica (the poll
        picks up the terminal state)."""
        forward_to = None
        finished = None
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            job.cancel_requested = True
            if job.state == "queued":
                removed = self._drr.remove(job.tenant, job_id)
                if self._decisions is not None:
                    self._decisions.record(
                        "remove", tenant=job.tenant, job_id=job_id,
                        removed=removed, now=time.time(),
                    )
                finished = job
            elif job.state == "routed" and job.replica_job_id is not None:
                replica = self._replica_locked(job.replica)
                if replica is not None:
                    forward_to = (replica, job.replica_job_id)
            snap = job.status_locked()
        if finished is not None:
            self._finish_job(
                finished, "cancelled", "cancelled while queued",
                from_replica=None,
            )
            snap = self.job_status(job_id)
        if forward_to is not None:
            replica, rjid = forward_to
            try:
                _http_json("POST", f"{replica.base}/jobs/{rjid}/cancel", {})
            except Exception as e:
                log.warning("cancel forward failed: %s", e)
        return snap

    def debug_requests(self) -> list:
        """Recent terminal requests, slowest first: each row's
        ``trace_id`` + router blame split is assemblable into the full
        cross-layer trace via ``tools/lt_request.py``."""
        with self._lock:
            recent = list(self._recent_requests)
        recent.sort(
            key=lambda r: -(
                r["latency_s"]
                if isinstance(r["latency_s"], (int, float)) else 0.0
            )
        )
        return recent

    def stats(self) -> dict:
        """The router ``/healthz`` body (``"router": true`` marks it so
        ``lt top`` renders the router view)."""
        with self._lock:
            tenants = {
                t: {
                    "queued": self._drr.queued(t),
                    "routed": sum(
                        1 for j in self._jobs.values()
                        if j.tenant == t and j.state == "routed"
                    ),
                    "weight": self._drr.weight(t),
                    "deficit": round(self._drr.deficit(t), 3),
                }
                for t in self._drr.known_tenants()
            }
            for j in self._jobs.values():
                if j.state == "routed" and j.tenant not in tenants:
                    tenants[j.tenant] = {
                        "queued": 0,
                        "routed": sum(
                            1 for x in self._jobs.values()
                            if x.tenant == j.tenant and x.state == "routed"
                        ),
                        "weight": self._drr.weight(j.tenant),
                        "deficit": round(self._drr.deficit(j.tenant), 3),
                    }
            snap = {
                "ok": True,
                "router": True,
                "queue_depth": self._drr.depth,
                "routed": sum(
                    1 for j in self._jobs.values() if j.state == "routed"
                ),
                "jobs_total": len(self._jobs),
                "jobs_terminal": self._terminal,
                "tenants": tenants,
                "replicas": [r.row_locked() for r in self.pool],
                "recovering": self._recovering,
                "recovery": self.recovery,
                # under the lock: scale_tick mutates the engine's alert
                # state under this same lock, and the Autoscaler's
                # single-owner contract is exactly that serialization
                "scaler": self.scaler.state() if self.scaler else None,
            }
        snap["uptime_s"] = round(time.time() - self._t0, 3)
        # the journal keeps its own (leaf) lock — read it outside ours
        journal = self._journal
        snap["journal"] = journal.stats() if journal is not None else None
        return snap

    def _fleet_probes(self) -> dict:
        """The ``state`` block of the router's own fleet snapshot
        (kind="route"): ``lt_fleet`` / ``lt top --dir`` render the
        router aggregate straight from the shared directory."""
        s = self.stats()
        return {
            "progress": {
                "queue_depth": s["queue_depth"],
                "routed": s["routed"],
                "jobs_total": s["jobs_total"],
                "jobs_terminal": s["jobs_terminal"],
            },
            "router": {
                "tenants": s["tenants"],
                "replicas": s["replicas"],
                "scaler": s["scaler"],
            },
        }

    def stop(self) -> None:
        """Ask the dispatcher to shut down (clean drain)."""
        with self._lock:
            self._stopping = True
            self._cond.notify_all()

    # -- shutdown ----------------------------------------------------------
    def _drain_routed(self, deadline_s: float) -> None:
        """Quiesce: poll routed jobs until none remain (or the bound
        expires) — replicas finish what they accepted, so a clean stop
        loses nothing."""
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            with self._lock:
                routed = [
                    j for j in self._jobs.values() if j.state == "routed"
                ]
            if not routed:
                return
            for job in routed:
                self._poll_job(job)
            time.sleep(min(0.5, self.cfg.health_interval_s))

    def _shutdown(self, status: str) -> None:
        """Idempotent reverse-of-construction teardown."""
        with self._lock:
            self._stopping = True
            self._cond.notify_all()
            queued = [
                j for j in self._jobs.values() if j.state == "queued"
            ]
        self._control_stop.set()
        if self._control_thread is not None:
            self._control_thread.join(timeout=30)
            self._control_thread = None
        httpd = getattr(self, "_httpd", None)
        thread = getattr(self, "_http_thread", None)
        if httpd is not None:
            if thread is not None:
                httpd.shutdown()
            httpd.server_close()
            self._httpd = None
        if thread is not None:
            thread.join(timeout=10)
            self._http_thread = None
        for job in queued:
            self._finish_job(
                job, "cancelled", "router shut down before routing",
                from_replica=None,
            )
        if status == "ok":
            # the drain contract: in-flight jobs finish on their
            # replicas before anything is stopped (an abort skips this
            # — manifests are resumable either way)
            self._drain_routed(_DRAIN_TIMEOUT_S)
        with self._lock:
            spawned = [r for r in self.pool if r.spawned]
        for replica in spawned:
            alive = (
                replica.proc is not None and replica.proc.poll() is None
            ) or (
                replica.proc is None
                and replica.adopted_pid is not None
                and _pid_alive(replica.adopted_pid)
            )
            self._stop_replica_proc(replica)
            with self._lock:
                was_stopped = replica.state == "stopped"
                replica.state = "stopped"
            if alive and not was_stopped and self.telemetry is not None:
                self.telemetry.replica_down(replica, "shutdown")
        if self._journal is not None:
            with self._lock:
                all_terminal = all(
                    j.state in TERMINAL_STATES
                    for j in self._jobs.values()
                ) and not self._pending_recovery
            if status == "ok" and all_terminal:
                # the clean-shutdown marker: the next start on this
                # workdir skips reconciliation probes.  Only a FULLY
                # drained stop earns it — anything non-terminal means
                # the restart must reconcile.
                try:
                    self._journal.mark_clean()
                except OSError as e:
                    log.warning("clean-shutdown marker failed: %s", e)
            self._journal.close()
            self._journal = None
        if self._fault_plan is not None:
            faults.deactivate()
            self._fault_plan = None
        if self._decisions is not None:
            self._decisions.close()
            self._decisions = None
        if self.telemetry is not None:
            try:
                self.telemetry.close(status, time.time() - self._t0)
            except Exception as exc:
                log.error("router telemetry close failed: %s", exc)
            self.telemetry = None


class _RouterAPIServer(http.server.ThreadingHTTPServer):
    """The loopback front door: thin JSON routing over the router."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, router: FleetRouter) -> None:
        self.lt_router = router
        super().__init__(addr, _RouterAPIHandler)

    def handle_error(self, request, client_address) -> None:
        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
            return
        super().handle_error(request, client_address)


class _RouterAPIHandler(http.server.BaseHTTPRequestHandler):
    """Routes::

        POST /jobs              submit (JSON body → job snapshot |
                                429 + Retry-After / 400)
        GET  /jobs              every router job's snapshot
        GET  /jobs/<id>         one job (includes the replica's last
                                snapshot under "result")
        POST /jobs/<id>/cancel  cancel (queued → terminal; routed →
                                forwarded to the replica)
        GET  /healthz           router state: tenant queues, replica
                                table, scaler state ("router": true)
        GET  /metrics           the lt_router_* exposition
        GET  /metrics/exemplars histogram bucket → recent trace_id rings
        GET  /debug/requests    recent terminal requests, slowest first
                                (trace_id, router blame split, hops)
    """

    server: _RouterAPIServer

    def _send_json(self, status: int, payload) -> None:
        body = json.dumps(payload, default=str).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if status in (429, 503):
            # 503s are transient here too: recovery window, drain,
            # journal hiccup — the client should come back
            self.send_header("Retry-After", "1")
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 - stdlib API name
        rt = self.server.lt_router
        path = self.path.split("?")[0].rstrip("/")
        if path == "/healthz":
            self._send_json(200, rt.stats())
        elif path == "/metrics/exemplars":
            if rt.telemetry is None:
                self.send_error(404)
                return
            self._send_json(
                200, {"exemplars": rt.telemetry.registry.exemplars()}
            )
        elif path == "/debug/requests":
            self._send_json(200, {"requests": rt.debug_requests()})
        elif path == "/metrics":
            if rt.telemetry is None:
                self.send_error(404)
                return
            body = rt.telemetry.registry.render().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/jobs":
            self._send_json(200, {"jobs": rt.jobs()})
        elif path.startswith("/jobs/"):
            snap = rt.job_status(path[len("/jobs/"):])
            if snap is None:
                self._send_json(404, {"error": "no such job"})
            else:
                self._send_json(200, snap)
        else:
            self.send_error(404)

    def do_POST(self) -> None:  # noqa: N802 - stdlib API name
        rt = self.server.lt_router
        path = self.path.split("?")[0].rstrip("/")
        if path == "/jobs":
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
            except (ValueError, json.JSONDecodeError) as e:
                self._send_json(
                    400, {"error": "bad_request", "detail": f"bad JSON: {e}"}
                )
                return
            try:
                snap = rt.submit(payload, source="http")
            except Rejection as e:
                self._send_json(
                    e.http_status, {"error": e.reason, "detail": e.detail}
                )
                return
            self._send_json(200, snap)
        elif path.startswith("/jobs/") and path.endswith("/cancel"):
            job_id = path[len("/jobs/"):-len("/cancel")]
            snap = rt.cancel(job_id)
            if snap is None:
                self._send_json(404, {"error": "no such job"})
            else:
                self._send_json(200, snap)
        else:
            self.send_error(404)

    def log_message(self, *a) -> None:  # quiet: no per-request stderr
        pass
