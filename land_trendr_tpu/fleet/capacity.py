"""Trace-driven capacity planner: scaling curves + offline replay.

Two consumers of the same recorded truth live here.

**The scaling-curve analyzer** takes the load rig's pinned trace ids
(:mod:`land_trendr_tpu.loadgen`) and assembles each request through
the PR-15 request-trace store (:mod:`land_trendr_tpu.obs.reqtrace`) —
latency truth comes from the fleet's own event streams, not client
clocks.  A sweep over replica counts × offered rates becomes a
replicas-vs-QPS-vs-{p50, p99, goodput} curve; :func:`find_knee` marks
where each curve bends (max perpendicular distance to the chord — the
Kneedle construction on a normalized curve) and :func:`dominant_blame`
names the blame component that owns the knee, in the PR-15 vocabulary.

**The offline replay simulator** re-drives a recorded decision log
(:class:`~land_trendr_tpu.fleet.scheduling.DecisionLog`) through fresh
instances of the SAME pure machines the router used live —
:class:`~land_trendr_tpu.fleet.scheduling.DrrQueue`,
:func:`~land_trendr_tpu.fleet.scheduling.choose_replica`,
:class:`~land_trendr_tpu.fleet.autoscale.Autoscaler` — and
byte-compares every recorded output.  Because the machines take all
timing from the recorded ``now``, replay runs as fast as the CPU can
iterate records: the ≥100× real-time bound ``tools/perf_gate.py``
enforces is loose by orders of magnitude.

Stdlib-only, jax-free: capacity planning must run on the laptop that
holds yesterday's workdir.
"""

from __future__ import annotations

import dataclasses
import json
import random
import time

from land_trendr_tpu.fleet.autoscale import Autoscaler
from land_trendr_tpu.fleet.scheduling import (
    DecisionLog,
    DrrQueue,
    choose_replica,
    read_decisions,
)
from land_trendr_tpu.obs.reqtrace import (
    BLAME_PRIORITY,
    assemble_request,
    discover_request_files,
)

__all__ = [
    "ReplayReport",
    "assemble_sweep",
    "dominant_blame",
    "find_knee",
    "mark_knee",
    "percentile",
    "replay_decisions",
    "validate_report",
    "write_scripted_history",
]

#: the CAPACITY_r*.json report schema this module emits and validates
REPORT_SCHEMA = "lt-capacity-v1"


def percentile(values: "list[float]", q: float) -> float:
    """Linear-interpolated percentile (``q`` in [0, 100]) — the
    fleet-bench convention, shared so curve points and bench reports
    can never disagree on what "p99" means."""
    if not values:
        return 0.0
    xs = sorted(values)
    if len(xs) == 1:
        return float(xs[0])
    pos = (len(xs) - 1) * (q / 100.0)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    frac = pos - lo
    return float(xs[lo] * (1.0 - frac) + xs[hi] * frac)


# -- offline replay --------------------------------------------------------
@dataclasses.dataclass
class ReplayReport:
    """One decision-log replay verdict."""

    #: recorded OUTPUT records compared (pick/choose/remove/autoscale)
    decisions: int
    #: how many replayed byte-identically
    matched: int
    #: seq of the first divergence (None when everything matched)
    mismatch_seq: "int | None" = None
    #: ``{"kind", "recorded", "replayed"}`` of the first divergence
    mismatch: "dict | None" = None
    #: recorded wall span (max ``now`` − min ``now`` across records)
    recorded_span_s: float = 0.0
    #: replay CPU wall
    replay_wall_s: float = 0.0

    @property
    def match(self) -> bool:
        return self.decisions > 0 and self.matched == self.decisions

    @property
    def speedup_x(self) -> float:
        """Recorded span over replay wall — how much faster than real
        time the simulator re-derived the decisions."""
        return self.recorded_span_s / max(self.replay_wall_s, 1e-9)

    def to_json(self) -> dict:
        return {
            "decisions": self.decisions,
            "matched": self.matched,
            "match": self.match,
            "mismatch_seq": self.mismatch_seq,
            "mismatch": self.mismatch,
            "recorded_span_s": round(self.recorded_span_s, 6),
            "replay_wall_s": round(self.replay_wall_s, 6),
            "speedup_x": round(self.speedup_x, 3),
        }


def _canon(obj) -> str:
    return json.dumps(obj, sort_keys=True)


def replay_decisions(path: str, telemetry=None) -> ReplayReport:
    """Replay one recorded decision log through fresh pure machines.

    Input records (``enqueue``) advance state; output records
    (``pick`` / ``choose`` / ``remove`` / ``autoscale``) are re-derived
    and byte-compared against what the live router recorded.  A
    ``remove`` is both: its ``removed`` verdict is compared AND the
    entry joins the dead set the replayed pick loop skips — the same
    cancel-races-enqueue discipline the live dispatcher gets from job
    state.
    """
    config, records = read_decisions(path)
    drr = DrrQueue(config.get("weights") or {})
    scaler = None
    asc = config.get("autoscale")
    if asc:
        scaler = Autoscaler(
            min_replicas=asc["min_replicas"],
            max_replicas=asc["max_replicas"],
            up_burn=asc["up_burn"],
            down_burn=asc["down_burn"],
            for_s=asc.get("for_s", 0.0),
            hold_s=asc.get("hold_s", 30.0),
        )
    dead: set = set()
    nows = [r["now"] for r in records if isinstance(r.get("now"), (int, float))]
    rep = ReplayReport(decisions=0, matched=0)
    t0 = time.monotonic()
    for rec in records:
        kind = rec.get("kind")
        recorded = replayed = None
        if kind == "enqueue":
            drr.enqueue(
                rec["tenant"], rec["job_id"], front=bool(rec.get("front"))
            )
            continue
        if kind == "pick":
            out = drr.pick(live=lambda jid: jid not in dead)
            recorded = {"tenant": rec["tenant"], "job_id": rec["job_id"]}
            replayed = (
                None if out is None
                else {"tenant": out[0], "job_id": out[1]}
            )
        elif kind == "choose":
            rid, warm = choose_replica(
                [tuple(c) for c in rec.get("candidates", [])],
                bool(rec.get("affinity")),
            )
            recorded = {"chosen": rec["chosen"], "warm": rec["warm"]}
            replayed = {"chosen": rid, "warm": warm}
        elif kind == "remove":
            removed = drr.remove(rec["tenant"], rec["job_id"])
            dead.add(rec["job_id"])
            recorded = {"removed": rec["removed"]}
            replayed = {"removed": removed}
        elif kind == "autoscale":
            if scaler is None:
                recorded = {"decision": rec.get("decision")}
                replayed = {"decision": "<no autoscale config>"}
            else:
                decision = scaler.decide(
                    rec["burn"], rec["queue_depth"], rec["replicas"],
                    rec["now"],
                )
                recorded = {"decision": rec.get("decision")}
                replayed = {"decision": decision}
        else:
            continue  # unknown kinds are forward-compatible no-ops
        rep.decisions += 1
        if _canon(recorded) == _canon(replayed):
            rep.matched += 1
        elif rep.mismatch_seq is None:
            rep.mismatch_seq = rec.get("seq")
            rep.mismatch = {
                "kind": kind, "recorded": recorded, "replayed": replayed,
            }
    rep.replay_wall_s = time.monotonic() - t0
    rep.recorded_span_s = (max(nows) - min(nows)) if len(nows) > 1 else 0.0
    if telemetry is not None:
        telemetry.sim_replay(
            decisions=rep.decisions, matched=rep.matched, match=rep.match,
            speedup_x=rep.speedup_x, recorded_span_s=rep.recorded_span_s,
            replay_wall_s=rep.replay_wall_s,
            mismatch_seq=rep.mismatch_seq,
        )
    return rep


def write_scripted_history(
    path: str, seed: int = 0, events: int = 400
) -> dict:
    """Write a seeded synthetic decision log by DRIVING the live pure
    machines — the no-fleet-required fixture the perf gate and tests
    replay.  The writer uses exactly the state discipline
    :func:`replay_decisions` assumes (dead-set pick skipping), so a
    matching replay is a real equivalence check of the machines, not a
    tautology over the generator.  Returns ``{"records", "span_s"}``.
    """
    rng = random.Random(seed)
    weights = {"t0": 3.0, "t1": 1.5}
    asc = {
        "min_replicas": 1, "max_replicas": 4, "up_burn": 0.5,
        "down_burn": 0.05, "for_s": 0.0, "hold_s": 2.0,
    }
    drr = DrrQueue(weights)
    scaler = Autoscaler(**asc)
    dead: set = set()
    owner: "dict[str, str]" = {}  # job_id -> tenant (for removes)
    tenants = ("t0", "t1", "t2")
    log = DecisionLog(path)
    try:
        return _drive_script(
            log, rng, drr, scaler, dead, owner, tenants, weights, asc,
            events,
        )
    finally:
        log.close()


def _drive_script(
    log, rng, drr, scaler, dead, owner, tenants, weights, asc, events
) -> dict:
    replicas, now, jid, written = 1, 0.0, 0, 0
    log.record("config", weights=weights, affinity=True, autoscale=asc)
    for _ in range(events):
        now = round(now + rng.uniform(0.05, 0.5), 6)
        r = rng.random()
        if r < 0.40:
            jid += 1
            job = f"sj-{jid:05d}"
            tenant = rng.choice(tenants)
            front = rng.random() < 0.1
            owner[job] = tenant
            drr.enqueue(tenant, job, front=front)
            log.record(
                "enqueue", tenant=tenant, job_id=job, front=front, now=now
            )
        elif r < 0.65:
            out = drr.pick(live=lambda j: j not in dead)
            if out is not None:
                log.record(
                    "pick", tenant=out[0], job_id=out[1], now=now
                )
        elif r < 0.78:
            cands = [
                [f"r{k}", rng.randrange(3), rng.random() < 0.4]
                for k in range(rng.randrange(1, 5))
            ]
            rid, warm = choose_replica([tuple(c) for c in cands], True)
            log.record(
                "choose", key=f"k{rng.randrange(3)}", affinity=True,
                candidates=cands, chosen=rid, warm=warm, now=now,
            )
        elif r < 0.88 and owner:
            job = rng.choice(sorted(owner))
            removed = drr.remove(owner[job], job)
            dead.add(job)
            log.record(
                "remove", tenant=owner.pop(job), job_id=job,
                removed=removed, now=now,
            )
        else:
            burn = round(rng.uniform(0.0, 1.0), 3)
            decision = scaler.decide(burn, drr.depth, replicas, now)
            log.record(
                "autoscale", burn=burn, queue_depth=drr.depth,
                replicas=replicas, now=now, decision=decision,
            )
            if decision == "up":
                replicas += 1
            elif decision == "down":
                replicas -= 1
        written += 1
    return {"records": written, "span_s": now}


# -- curve assembly --------------------------------------------------------
def assemble_sweep(workdir: str, trace_ids: "list[str]") -> dict:
    """Fold one sweep cell's requests through the request-trace store.

    Returns ``{"assembled", "latencies", "blame"}`` — only requests
    whose ``request_done`` landed (``status == "done"``) contribute a
    latency; ``blame`` sums the per-component seconds across them, the
    input :func:`dominant_blame` ranks.
    """
    paths = discover_request_files(workdir)
    latencies: "list[float]" = []
    blame: "dict[str, float]" = {}
    assembled = 0
    for tid in trace_ids:
        rec = assemble_request(paths, tid)
        if not rec.get("found"):
            continue
        assembled += 1
        if rec.get("status") != "done":
            continue
        lat = rec.get("latency_s")
        if isinstance(lat, (int, float)) and not isinstance(lat, bool):
            latencies.append(float(lat))
        for comp, secs in (rec.get("blame") or {}).items():
            blame[comp] = blame.get(comp, 0.0) + float(secs)
    return {
        "assembled": assembled,
        "latencies": latencies,
        "blame": {k: round(v, 6) for k, v in sorted(blame.items())},
    }


def dominant_blame(blame: "dict[str, float]") -> str:
    """The component owning the most seconds; ties break by the PR-15
    priority order (the same earlier-wins rule the partition uses).
    An empty split names ``other`` — no evidence, no blame."""
    order = {c: i for i, c in enumerate((*BLAME_PRIORITY, "other"))}
    best, best_s = "other", 0.0
    for comp, secs in blame.items():
        if secs > best_s or (secs == best_s and best_s > 0.0
                             and order.get(comp, 99) < order.get(best, 99)):
            best, best_s = comp, float(secs)
    return best


def find_knee(points: "list[tuple[float, float]]") -> "int | None":
    """Index of the knee of an (x, y) curve — max perpendicular
    distance to the first→last chord after normalizing both axes to
    [0, 1] (the Kneedle construction).  Needs >= 3 points and a
    non-degenerate span; returns None otherwise, and None again when
    no interior point rises above the chord (a straight line has no
    knee — stamping one would be blame theater)."""
    if len(points) < 3:
        return None
    xs = [float(p[0]) for p in points]
    ys = [float(p[1]) for p in points]
    dx, dy = max(xs) - min(xs), max(ys) - min(ys)
    if dx <= 0 or dy <= 0:
        return None
    nx = [(x - min(xs)) / dx for x in xs]
    ny = [(y - min(ys)) / dy for y in ys]
    best_i, best_d = None, 1e-9
    for i in range(1, len(points) - 1):
        # distance from (nx, ny) to the chord (0-index -> last index)
        t = (
            (nx[i] - nx[0]) * (nx[-1] - nx[0])
            + (ny[i] - ny[0]) * (ny[-1] - ny[0])
        ) / ((nx[-1] - nx[0]) ** 2 + (ny[-1] - ny[0]) ** 2)
        px = nx[0] + t * (nx[-1] - nx[0])
        py = ny[0] + t * (ny[-1] - ny[0])
        d = ((nx[i] - px) ** 2 + (ny[i] - py) ** 2) ** 0.5
        if d > best_d:
            best_i, best_d = i, d
    return best_i


def mark_knee(points: "list[dict]") -> "int | None":
    """Annotate one replica count's curve in place: find the knee over
    ``(offered_qps, p99_s)`` and stamp ``knee=True`` plus the
    ``knee_blame`` naming that point's dominant component.  Returns
    the knee index."""
    idx = find_knee([
        (p["offered_qps"], p["p99_s"]) for p in points
    ])
    if idx is None:
        return None
    points[idx]["knee"] = True
    points[idx]["knee_blame"] = dominant_blame(points[idx].get("blame") or {})
    return idx


# -- report schema ---------------------------------------------------------
_POINT_NUM = (
    "offered_qps", "achieved_qps", "p50_s", "p99_s", "goodput_qps",
)
_POINT_INT = ("replicas", "done", "failed", "rejected")


def validate_report(report: dict) -> "list[str]":
    """Exact-schema check of a ``CAPACITY_r*.json`` — the perf gate's
    curve-JSON leg.  Returns human-readable problems (empty = valid)."""
    errs: "list[str]" = []
    if not isinstance(report, dict):
        return ["report is not an object"]
    if report.get("schema") != REPORT_SCHEMA:
        errs.append(
            f"schema {report.get('schema')!r} != {REPORT_SCHEMA!r}"
        )
    curves = report.get("curves")
    if not isinstance(curves, list) or not curves:
        return errs + ["curves missing or empty"]
    for ci, curve in enumerate(curves):
        where = f"curves[{ci}]"
        if not isinstance(curve, dict):
            errs.append(f"{where}: not an object")
            continue
        if not isinstance(curve.get("replicas"), int):
            errs.append(f"{where}: replicas missing")
        pts = curve.get("points")
        if not isinstance(pts, list) or not pts:
            errs.append(f"{where}: points missing or empty")
            continue
        for pi, p in enumerate(pts):
            pw = f"{where}.points[{pi}]"
            if not isinstance(p, dict):
                errs.append(f"{pw}: not an object")
                continue
            for k in _POINT_NUM:
                v = p.get(k)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    errs.append(f"{pw}: {k} missing or non-numeric")
            for k in _POINT_INT:
                if not isinstance(p.get(k), int):
                    errs.append(f"{pw}: {k} missing or non-int")
            if isinstance(p.get("p50_s"), (int, float)) and isinstance(
                p.get("p99_s"), (int, float)
            ) and p["p99_s"] < p["p50_s"]:
                errs.append(f"{pw}: p99_s below p50_s")
            blame = p.get("knee_blame")
            if blame is not None and blame not in (*BLAME_PRIORITY, "other"):
                errs.append(f"{pw}: knee_blame {blame!r} not in vocabulary")
    rep = report.get("replay")
    if not isinstance(rep, dict):
        errs.append("replay missing")
    else:
        for k in ("decisions", "matched", "match", "speedup_x"):
            if k not in rep:
                errs.append(f"replay.{k} missing")
    return errs
