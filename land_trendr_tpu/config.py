"""Algorithm and job configuration for the TPU-native LandTrendr framework.

``LTParams`` mirrors the reference's algorithm parameters (SURVEY.md §3.1
table; names follow the canonical published LandTrendr parameterisation that
the reference's configs confirm: ``max_segments=6``, a despike stage, and a
recovery-rate filter — BASELINE.json north_star).  It is a frozen, hashable
dataclass so it can be passed as a *static* argument to jit-compiled kernels:
every distinct parameter set compiles exactly once, and no parameter ever
becomes a traced value (XLA sees them as compile-time constants and folds
them into the kernel).

Provenance note: the reference mount was empty during the survey session
(SURVEY.md §0), so parameter *names and defaults* follow the published
algorithm (Kennedy, Yang & Cohen 2010, RSE 114(12):2897-2910) and the
driver-written BASELINE.json; the CPU oracle in
``land_trendr_tpu.models.oracle`` is the normative semantic spec.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Mapping


@dataclasses.dataclass(frozen=True)
class LTParams:
    """LandTrendr temporal-segmentation parameters (static / hashable).

    Attributes
    ----------
    max_segments:
        Maximum number of piecewise-linear segments in the fitted model;
        the model has at most ``max_segments + 1`` vertices.
    spike_threshold:
        Despike severity threshold in [0, 1].  ``1.0`` disables dampening
        entirely; lower values dampen more aggressively.  A point whose
        spike proportion (see oracle Stage 1) *exceeds* this threshold is
        dampened toward the neighbour interpolation.
    vertex_count_overshoot:
        Extra candidate vertices found by the deviation search before the
        angle-based cull reduces the set back to ``max_segments + 1``.
    recovery_threshold:
        Recovery-rate filter: a segment whose fitted recovery rate exceeds
        ``recovery_threshold`` × (pixel spectral range) per year — i.e. a
        full-range recovery faster than ``1 / recovery_threshold`` years —
        is disallowed (the anchored-fit slope is clamped to the limit).
    prevent_one_year_recovery:
        If true, recovery segments of duration ≤ 1 year are disallowed
        outright (slope clamped to 0 for that segment).
    p_val_threshold:
        Maximum acceptable p-of-F for the selected model; if no candidate
        model passes, the pixel is flagged no-fit and a flat (mean) model
        is returned.
    best_model_proportion:
        Model-selection leniency: among candidate models, prefer the one
        with the *most* segments whose p-value satisfies
        ``p <= p_best / best_model_proportion``.
    min_observations_needed:
        Minimum number of valid (unmasked) years required to attempt a fit.
    """

    max_segments: int = 6
    spike_threshold: float = 0.9
    vertex_count_overshoot: int = 3
    recovery_threshold: float = 0.25
    prevent_one_year_recovery: bool = True
    p_val_threshold: float = 0.05
    best_model_proportion: float = 0.75
    min_observations_needed: int = 6

    def __post_init__(self) -> None:
        if self.max_segments < 1:
            raise ValueError("max_segments must be >= 1")
        if not (0.0 <= self.spike_threshold <= 1.0):
            raise ValueError("spike_threshold must be in [0, 1]")
        if self.vertex_count_overshoot < 0:
            raise ValueError("vertex_count_overshoot must be >= 0")
        if self.recovery_threshold <= 0.0:
            raise ValueError("recovery_threshold must be > 0")
        if not (0.0 < self.p_val_threshold <= 1.0):
            raise ValueError("p_val_threshold must be in (0, 1]")
        if not (0.0 < self.best_model_proportion <= 1.0):
            raise ValueError("best_model_proportion must be in (0, 1]")
        if self.min_observations_needed < 3:
            raise ValueError("min_observations_needed must be >= 3")

    # -- sizes derived from the static parameters --------------------------

    @property
    def max_vertices(self) -> int:
        """Vertex capacity of the final model (``max_segments + 1``)."""
        return self.max_segments + 1

    @property
    def max_candidates(self) -> int:
        """Vertex capacity during the overshoot search."""
        return self.max_segments + 1 + self.vertex_count_overshoot

    # -- (de)serialisation --------------------------------------------------

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "LTParams":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown LTParams keys: {sorted(unknown)}")
        return cls(**dict(d))

    @classmethod
    def from_json(cls, text: str) -> "LTParams":
        return cls.from_dict(json.loads(text))

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)


DEFAULT_PARAMS = LTParams()
