"""land_trendr_tpu — TPU-native LandTrendr temporal-segmentation framework.

A from-scratch JAX/XLA rebuild of the capabilities of the reference repo
``vicchu/land_trendr`` (a Hadoop-MapReduce, one-map-task-per-pixel Python
implementation — SURVEY.md §2): per-pixel piecewise-linear temporal
segmentation of Landsat spectral-index time series (despike → candidate
vertex search → anchored least-squares fit → F-statistic model selection),
executed as vmapped, jit-compiled kernels over HBM-resident
``(tile_px, year)`` arrays, sharded data-parallel over a TPU mesh with no
cross-pixel collectives (BASELINE.json north_star).
"""

from land_trendr_tpu.config import DEFAULT_PARAMS, LTParams

__version__ = "0.1.0"

__all__ = ["LTParams", "DEFAULT_PARAMS", "__version__"]
