"""The soak runner: drive a live fleet with a seeded trace.

:class:`LoadRunner` executes one :func:`~land_trendr_tpu.loadgen.
trace.build_trace` trace against a router — in-process
(:class:`InProcClient` around a :class:`~land_trendr_tpu.fleet.router.
FleetRouter`) or over HTTP (:class:`HttpClient`) — and returns a
:class:`LoadReport` with every request's trace id and outcome.  The
report is deliberately raw: the capacity analyzer
(:mod:`land_trendr_tpu.fleet.capacity`) re-derives latency from the
request-trace store, not from client-side clocks, so the rig only has
to know WHICH requests were its own.

Closed vs open loop is the whole point of having both: a closed loop's
arrival rate collapses to the fleet's completion rate (coordinated
omission — the bench can never overload what it measures), while an
open loop keeps offering the scheduled rate as queues grow, which is
where knees live.

Churn rides the ``loadgen.tick`` fault seam: every scheduler tick asks
:func:`land_trendr_tpu.runtime.faults.fired` and, on a firing tick,
invokes the host's ``churn`` hook (SIGKILL a replica, flip a health
probe, ...).  The seam keeps soak churn on the same seeded,
deterministic schedule as every other injected fault.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Callable

from land_trendr_tpu.loadgen.config import LoadConfig
from land_trendr_tpu.loadgen.trace import TraceRequest, build_trace
from land_trendr_tpu.runtime import faults
from land_trendr_tpu.serve.jobs import TERMINAL_STATES
from land_trendr_tpu.serve.server import Rejection

__all__ = [
    "HttpClient",
    "InProcClient",
    "LoadReport",
    "LoadRunner",
    "RequestOutcome",
]


class InProcClient:
    """Submit/poll against a :class:`FleetRouter` in this process."""

    def __init__(self, router) -> None:
        self._router = router

    def submit(self, payload: dict) -> "tuple[str | None, str | None]":
        """→ (job_id, None) accepted, (None, reason) rejected."""
        try:
            snap = self._router.submit(payload, source="loadgen")
        except Rejection as e:
            return None, e.reason
        return snap["job_id"], None

    def status(self, job_id: str) -> "str | None":
        snap = self._router.job_status(job_id)
        return None if snap is None else snap.get("state")


class HttpClient:
    """Submit/poll a router (or a bare ``lt serve``) over its JSON API."""

    def __init__(self, base_url: str, timeout_s: float = 10.0) -> None:
        self._base = base_url.rstrip("/")
        self._timeout = timeout_s

    def submit(self, payload: dict) -> "tuple[str | None, str | None]":
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self._base + "/jobs", data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as r:
                return json.loads(r.read())["job_id"], None
        except urllib.error.HTTPError as e:
            try:
                reason = json.loads(e.read()).get("error", "http_error")
            except Exception:
                reason = "http_error"
            return None, reason
        except (urllib.error.URLError, OSError):
            return None, "unreachable"

    def status(self, job_id: str) -> "str | None":
        try:
            with urllib.request.urlopen(
                self._base + "/jobs/" + job_id, timeout=self._timeout
            ) as r:
                return json.loads(r.read()).get("state")
        except Exception:
            return None


@dataclasses.dataclass
class RequestOutcome:
    """One trace request's fate, as the client saw it."""

    trace_id: str
    tenant: str
    shape: str
    #: terminal verdict: ``done`` / any non-done terminal state /
    #: ``rejected`` (admission refused) / ``timeout`` (patience ran
    #: out) / ``lost`` (status polling found no such job)
    outcome: str
    #: admission rejection reason, when ``outcome == "rejected"``
    reason: "str | None" = None
    #: client-observed submit→terminal wall seconds (None unless the
    #: job reached a terminal state) — a sanity cross-check only; the
    #: analyzer's latency truth is the request-trace store
    latency_s: "float | None" = None


@dataclasses.dataclass
class LoadReport:
    """One load phase, summarized.  ``offered`` counts scheduled
    arrivals (open loop) or issued submissions (closed loop)."""

    mode: str
    offered: int
    done: int
    failed: int
    rejected: int
    wall_s: float
    outcomes: "list[RequestOutcome]"
    #: loadgen.tick churn firings during the phase
    churned: int = 0

    @property
    def trace_ids(self) -> "list[str]":
        return [o.trace_id for o in self.outcomes]


#: scheduler/poll granularity, seconds — also the loadgen.tick cadence
_TICK_S = 0.05


class LoadRunner:
    """Drive one seeded trace against one client.

    ``payload_fn(req)`` maps a :class:`TraceRequest` to the job payload
    to submit; it MUST pass ``req.trace_id`` through as the payload's
    ``trace_id`` (the runner asserts this) — the pinned id is how the
    analyzer finds the rig's requests in the trace store afterwards.
    ``churn`` is invoked on each firing ``loadgen.tick``.
    """

    def __init__(
        self,
        cfg: LoadConfig,
        client,
        payload_fn: "Callable[[TraceRequest], dict]",
        telemetry=None,
        churn: "Callable[[], None] | None" = None,
    ) -> None:
        self.cfg = cfg
        self.client = client
        self.payload_fn = payload_fn
        self.telemetry = telemetry
        self.churn = churn
        self._lock = threading.Lock()
        self._outcomes: "list[RequestOutcome]" = []
        self._churned = 0

    # -- plumbing ----------------------------------------------------------
    def _payload(self, req: TraceRequest) -> dict:
        payload = self.payload_fn(req)
        if payload.get("trace_id") != req.trace_id:
            raise ValueError(
                "payload_fn must pin the trace id: payload trace_id "
                f"{payload.get('trace_id')!r} != {req.trace_id!r}"
            )
        return payload

    def _tick(self) -> None:
        """One scheduler heartbeat: the churn seam's invocation point."""
        if faults.fired("loadgen.tick"):
            with self._lock:
                self._churned += 1
            if self.churn is not None:
                self.churn()

    def _record(self, out: RequestOutcome) -> None:
        with self._lock:
            self._outcomes.append(out)

    def _run_one(self, req: TraceRequest) -> None:
        """Submit one request and poll it to a terminal state."""
        payload = self._payload(req)
        t0 = time.monotonic()
        job_id, reason = self.client.submit(payload)
        if job_id is None:
            self._record(RequestOutcome(
                req.trace_id, req.tenant, req.shape, "rejected",
                reason=reason,
            ))
            return
        deadline = t0 + self.cfg.timeout_s
        while True:
            state = self.client.status(job_id)
            if state in TERMINAL_STATES:
                self._record(RequestOutcome(
                    req.trace_id, req.tenant, req.shape, state,
                    latency_s=time.monotonic() - t0,
                ))
                return
            if state is None:
                self._record(RequestOutcome(
                    req.trace_id, req.tenant, req.shape, "lost",
                ))
                return
            if time.monotonic() >= deadline:
                self._record(RequestOutcome(
                    req.trace_id, req.tenant, req.shape, "timeout",
                ))
                return
            time.sleep(_TICK_S)

    # -- the two loops -----------------------------------------------------
    def _run_open(self, trace: "tuple[TraceRequest, ...]") -> int:
        """Offered arrivals on the schedule's clock: each request fires
        at its ``at_s`` on its own thread (bounded by joining at the
        end, not by a pool — an overloaded fleet must not push back on
        arrivals, that is the whole open-loop point)."""
        start = time.monotonic()
        threads: "list[threading.Thread]" = []
        offered = 0
        for req in trace:
            while True:
                now = time.monotonic() - start
                if now >= req.at_s:
                    break
                self._tick()
                time.sleep(min(_TICK_S, req.at_s - now))
            t = threading.Thread(
                target=self._run_one, args=(req,), daemon=True
            )
            t.start()
            threads.append(t)
            offered += 1
        # drain: patience per request already bounds each thread
        for t in threads:
            t.join(timeout=self.cfg.timeout_s + 5.0)
        return offered

    def _run_closed(self, trace: "tuple[TraceRequest, ...]") -> int:
        """``workers`` virtual clients chewing through the shared pool
        until the window closes or the pool drains."""
        start = time.monotonic()
        cursor = {"i": 0}
        offered = {"n": 0}

        def next_req() -> "TraceRequest | None":
            with self._lock:
                if cursor["i"] >= len(trace):
                    return None
                req = trace[cursor["i"]]
                cursor["i"] += 1
                offered["n"] += 1
                return req

        def worker() -> None:
            while time.monotonic() - start < self.cfg.duration_s:
                self._tick()
                req = next_req()
                if req is None:
                    return
                self._run_one(req)
                if self.cfg.think_s:
                    time.sleep(self.cfg.think_s)

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(self.cfg.workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.cfg.duration_s + self.cfg.timeout_s + 5.0)
        return offered["n"]

    def run(self, phase: str = "load") -> LoadReport:
        """Execute the trace; returns the phase report."""
        cfg = self.cfg
        trace = build_trace(cfg)
        if self.telemetry is not None:
            self.telemetry.load_phase(
                phase=f"{phase}_start", mode=cfg.mode,
                offered_qps=cfg.qps if cfg.mode == "open" else None,
                requests=len(trace), workers=cfg.workers,
                duration_s=cfg.duration_s, seed=cfg.seed,
            )
        t0 = time.monotonic()
        offered = (
            self._run_open(trace) if cfg.mode == "open"
            else self._run_closed(trace)
        )
        wall = time.monotonic() - t0
        with self._lock:
            outcomes = list(self._outcomes)
            churned = self._churned
            self._outcomes = []
            self._churned = 0
        done = sum(1 for o in outcomes if o.outcome == "done")
        rejected = sum(1 for o in outcomes if o.outcome == "rejected")
        failed = len(outcomes) - done - rejected
        if self.telemetry is not None:
            self.telemetry.load_phase(
                phase=f"{phase}_done", mode=cfg.mode,
                offered_qps=cfg.qps if cfg.mode == "open" else None,
                requests=offered, workers=cfg.workers,
                duration_s=wall, seed=cfg.seed,
            )
        return LoadReport(
            mode=cfg.mode, offered=offered, done=done, failed=failed,
            rejected=rejected, wall_s=wall, outcomes=outcomes,
            churned=churned,
        )
