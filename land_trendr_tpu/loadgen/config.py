"""Load-rig configuration: everything that defines one ``lt load`` run.

:class:`LoadConfig` is the load harness's one configuration surface,
projected to the ``load`` CLI subcommand and to README's ``## Load
configuration`` table (the LT004 coupling rule checks all three — the
fourth triangle, after RunConfig, ServeConfig and RouterConfig).

The config describes the SHAPE of offered load only — arrival process,
tenant mix, rate schedule, concurrency, seed.  What each request *does*
(the job payload) and where it goes (an in-process router or a base
URL) are the driver's arguments, not load shape, so they live on
:class:`~land_trendr_tpu.loadgen.runner.LoadRunner`.
"""

from __future__ import annotations

import dataclasses

__all__ = ["LOAD_MODES", "LoadConfig"]

#: the arrival-process vocabulary.  ``open``: arrivals follow the
#: seeded schedule regardless of completions (offered rate is a fact
#: about the world — the regime where queues actually grow).
#: ``closed``: each of ``workers`` virtual clients submits, waits for
#: the terminal state, thinks, repeats (arrival rate = completion
#: rate; the regime every naive bench accidentally measures).
LOAD_MODES = ("open", "closed")


@dataclasses.dataclass(frozen=True)
class LoadConfig:
    """Everything that defines one load-rig run's offered traffic."""

    #: arrival process: ``open`` (seeded Poisson schedule, offered rate
    #: independent of completions) or ``closed`` (each worker submits →
    #: awaits terminal → thinks → repeats)
    mode: str = "closed"
    #: run length, seconds — the open-loop schedule spans exactly this
    #: window; a closed-loop run stops issuing new requests after it
    duration_s: float = 10.0
    #: open-loop mean offered rate, requests/second (the diurnal wave
    #: modulates around this mean); unused by closed loops
    qps: float = 2.0
    #: total request budget; 0 = unbounded (open loops stop at
    #: ``duration_s``, closed loops issue until the window closes)
    requests: int = 0
    #: concurrency: closed-loop virtual clients, and the dispatch-pool
    #: width an open loop uses so a slow fleet cannot stall arrivals
    workers: int = 2
    #: trace seed: the same (seed, config) pair regenerates the same
    #: arrival times, tenant sequence and trace ids, byte for byte
    seed: int = 0
    #: tenant population size (tenants are named ``t0``..``tN-1``)
    tenants: int = 3
    #: heavy-tail exponent of the tenant mix: tenant ``k`` (1-based by
    #: popularity) is drawn with weight ``1/k**tenant_skew`` (0 =
    #: uniform; ~1 = the classic Zipf skew where t0 dominates)
    tenant_skew: float = 1.0
    #: diurnal-wave amplitude in [0, 1): the open-loop rate schedule is
    #: ``qps * (1 + wave_amp * sin(2*pi*t/wave_period_s))`` (0 = flat)
    wave_amp: float = 0.0
    #: diurnal-wave period, seconds
    wave_period_s: float = 60.0
    #: closed-loop think time between a completion and the worker's
    #: next submission, seconds
    think_s: float = 0.0
    #: per-request patience: a submitted job not terminal after this
    #: long is counted ``failed`` (the rig stops polling it)
    timeout_s: float = 120.0

    def __post_init__(self) -> None:
        if self.mode not in LOAD_MODES:
            raise ValueError(
                f"mode={self.mode!r} not one of {LOAD_MODES}"
            )
        if self.duration_s <= 0:
            raise ValueError(
                f"duration_s={self.duration_s} must be > 0"
            )
        if self.qps <= 0:
            raise ValueError(f"qps={self.qps} must be > 0")
        if self.requests < 0:
            raise ValueError(
                f"requests={self.requests} must be >= 0 (0 = unbounded)"
            )
        if self.workers < 1:
            raise ValueError(f"workers={self.workers} must be >= 1")
        if self.tenants < 1:
            raise ValueError(f"tenants={self.tenants} must be >= 1")
        if self.tenant_skew < 0:
            raise ValueError(
                f"tenant_skew={self.tenant_skew} must be >= 0"
            )
        if not (0.0 <= self.wave_amp < 1.0):
            # amp >= 1 would schedule a negative offered rate at the
            # trough — not a wave, a config typo
            raise ValueError(
                f"wave_amp={self.wave_amp} outside [0, 1)"
            )
        if self.wave_period_s <= 0:
            raise ValueError(
                f"wave_period_s={self.wave_period_s} must be > 0"
            )
        if self.think_s < 0:
            raise ValueError(f"think_s={self.think_s} must be >= 0")
        if self.timeout_s <= 0:
            raise ValueError(f"timeout_s={self.timeout_s} must be > 0")
