"""Fleet-scale load harness: seeded traces driving a live router.

The rig splits in two along the determinism line.  The TRACE
(:mod:`~land_trendr_tpu.loadgen.trace`) is pure: a
:class:`~land_trendr_tpu.loadgen.config.LoadConfig` maps to one
arrival/tenant/shape/trace-id schedule, byte-stable run over run.  The
RUNNER (:mod:`~land_trendr_tpu.loadgen.runner`) is the wall-clock
half: it executes the trace against a live fleet — open- or
closed-loop — and records every request's pinned trace id so the
capacity planner (:mod:`land_trendr_tpu.fleet.capacity`) can assemble
latency truth from the request-trace store instead of client clocks.
"""

from land_trendr_tpu.loadgen.config import LOAD_MODES, LoadConfig
from land_trendr_tpu.loadgen.runner import (
    HttpClient,
    InProcClient,
    LoadReport,
    LoadRunner,
    RequestOutcome,
)
from land_trendr_tpu.loadgen.trace import TraceRequest, build_trace, rate_at

__all__ = [
    "LOAD_MODES",
    "HttpClient",
    "InProcClient",
    "LoadConfig",
    "LoadReport",
    "LoadRunner",
    "RequestOutcome",
    "TraceRequest",
    "build_trace",
    "rate_at",
]
