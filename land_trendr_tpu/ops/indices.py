"""Spectral-index math and QA masking for Landsat stacks.

The reference computes NBR, NDVI and TCW from Landsat surface-reflectance
bands on the driver side before dispatching per-pixel series (SURVEY.md §2
layer L1, provenance ``[B]`` — index names confirmed by the reference's
configs; the reference mount was empty, SURVEY.md §0, so formulas follow the
standard published definitions the reference necessarily implements).

Everything here is elementwise ``jax.numpy`` math over arrays of any shape
(band images, whole stacks, per-pixel series) so it fuses into the
surrounding jitted pipeline — on TPU the index computation is
bandwidth-bound and XLA folds it into the same HBM pass that assembles the
``(tile_px, year)`` kernel input.

Sign convention (SURVEY.md §3.1 orientation note): LandTrendr fits
*disturbance-positive* series.  NBR/NDVI/TCW all *decrease* under
disturbance, so :func:`compute_index` flips their sign by default; the
segment rasters the driver writes undo the flip where the reference's
outputs are in natural orientation.
"""

from __future__ import annotations

from typing import Mapping

import jax.numpy as jnp

__all__ = [
    "BANDS",
    "INDEX_NAMES",
    "DISTURBANCE_SIGN",
    "INDEX_BANDS",
    "required_bands",
    "nbr",
    "ndvi",
    "tcw",
    "compute_index",
    "scale_sr",
    "qa_valid_mask",
    "sr_valid_mask",
]

#: Canonical Landsat surface-reflectance band names used throughout the
#: framework (TM/ETM+/OLI harmonised six-band set).
BANDS = ("blue", "green", "red", "nir", "swir1", "swir2")

#: Tasseled-cap wetness coefficients for surface reflectance
#: (Crist 1985, TM reflectance-factor coefficients — the set classic
#: LandTrendr uses), in :data:`BANDS` order.
_TCW_COEFFS = (0.0315, 0.2021, 0.3102, 0.1594, -0.6806, -0.6109)

#: Sign multiplier that makes each index disturbance-positive.
DISTURBANCE_SIGN = {"nbr": -1.0, "ndvi": -1.0, "tcw": -1.0}

INDEX_NAMES = tuple(DISTURBANCE_SIGN)

#: Bands each index actually reads.  Callers that feed the device (the
#: runtime driver) ship only the union of the bands their index selection
#: needs — masking on an unused band would drop usable observations, and
#: every unused band is wasted host→HBM bandwidth.
INDEX_BANDS = {
    "nbr": ("nir", "swir2"),
    "ndvi": ("nir", "red"),
    "tcw": BANDS,
}


def required_bands(index: str, ftv_indices: tuple[str, ...] = ()) -> tuple[str, ...]:
    """Union of bands needed by a primary index + FTV indices, BANDS-ordered."""
    need: set[str] = set()
    for name in (index, *ftv_indices):
        key = name.lower()
        if key not in INDEX_BANDS:
            raise ValueError(f"unknown index {name!r}; expected one of {INDEX_NAMES}")
        need.update(INDEX_BANDS[key])
    return tuple(b for b in BANDS if b in need)


def _safe_ratio(num: jnp.ndarray, den: jnp.ndarray) -> jnp.ndarray:
    """``num / den`` with 0 where ``den`` is 0 (masked pixels stay finite)."""
    ok = den != 0
    return jnp.where(ok, num / jnp.where(ok, den, 1.0), 0.0)


def nbr(nir: jnp.ndarray, swir2: jnp.ndarray) -> jnp.ndarray:
    """Normalized Burn Ratio: (NIR − SWIR2) / (NIR + SWIR2)."""
    return _safe_ratio(nir - swir2, nir + swir2)


def ndvi(nir: jnp.ndarray, red: jnp.ndarray) -> jnp.ndarray:
    """Normalized Difference Vegetation Index: (NIR − RED) / (NIR + RED)."""
    return _safe_ratio(nir - red, nir + red)


def tcw(
    blue: jnp.ndarray,
    green: jnp.ndarray,
    red: jnp.ndarray,
    nir: jnp.ndarray,
    swir1: jnp.ndarray,
    swir2: jnp.ndarray,
) -> jnp.ndarray:
    """Tasseled-cap wetness (Crist 1985 reflectance coefficients)."""
    bands = (blue, green, red, nir, swir1, swir2)
    out = _TCW_COEFFS[0] * bands[0]
    for c, b in zip(_TCW_COEFFS[1:], bands[1:]):
        out = out + c * b
    return out


def compute_index(
    name: str,
    bands: Mapping[str, jnp.ndarray],
    disturbance_positive: bool = True,
) -> jnp.ndarray:
    """Compute a named spectral index from a band-name → array mapping.

    Parameters
    ----------
    name : one of ``"nbr"``, ``"ndvi"``, ``"tcw"`` (case-insensitive).
    bands : mapping with the required :data:`BANDS` entries; arrays of any
        (mutually broadcastable) shape, reflectance-scaled floats.
    disturbance_positive : flip the sign so disturbance is an increase
        (LandTrendr's fitting convention).  Default True.
    """
    key = name.lower()
    if key == "nbr":
        out = nbr(bands["nir"], bands["swir2"])
    elif key == "ndvi":
        out = ndvi(bands["nir"], bands["red"])
    elif key == "tcw":
        out = tcw(*(bands[b] for b in BANDS))
    else:
        raise ValueError(f"unknown index {name!r}; expected one of {INDEX_NAMES}")
    if disturbance_positive:
        out = DISTURBANCE_SIGN[key] * out
    return out


def scale_sr(
    dn: jnp.ndarray, scale: float = 2.75e-5, offset: float = -0.2
) -> jnp.ndarray:
    """Scale integer surface-reflectance DNs to reflectance floats.

    Defaults to the Landsat Collection-2 convention (consistent with
    :func:`qa_valid_mask`'s C2 QA_PIXEL layout); Collection-1 style data
    uses ``scale=1e-4, offset=0.0``.
    """
    return dn.astype(jnp.float32) * scale + offset


#: QA_PIXEL (CFMask) bit positions, Landsat Collection 2 layout.
_QA_FILL = 1 << 0
_QA_DILATED_CLOUD = 1 << 1
_QA_CIRRUS = 1 << 2
_QA_CLOUD = 1 << 3
_QA_CLOUD_SHADOW = 1 << 4
_QA_SNOW = 1 << 5

#: Default rejection set: fill, cloud (incl. dilated + cirrus), shadow, snow.
DEFAULT_QA_REJECT = (
    _QA_FILL | _QA_DILATED_CLOUD | _QA_CIRRUS | _QA_CLOUD | _QA_CLOUD_SHADOW | _QA_SNOW
)


def qa_valid_mask(
    qa: jnp.ndarray, reject_bits: int = DEFAULT_QA_REJECT
) -> jnp.ndarray:
    """True where the QA_PIXEL bitfield marks a usable observation.

    An observation is valid when *none* of ``reject_bits`` are set.
    """
    return (qa.astype(jnp.int32) & reject_bits) == 0


def sr_valid_mask(
    bands: Mapping[str, jnp.ndarray],
    lo: float = 0.0,
    hi: float = 1.0,
) -> jnp.ndarray:
    """True where every reflectance band is finite and inside ``[lo, hi]``.

    Catches saturated / fill values that slip past QA; ANDs across the
    standard six bands present in ``bands``.
    """
    mask = None
    for name in BANDS:
        if name not in bands:
            continue
        b = bands[name]
        ok = jnp.isfinite(b) & (b >= lo) & (b <= hi)
        mask = ok if mask is None else (mask & ok)
    if mask is None:
        raise ValueError("sr_valid_mask needs at least one known band")
    return mask
