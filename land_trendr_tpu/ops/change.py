"""Change-map products derived from segmentation rasters.

The reference pipeline stops at segment rasters (SURVEY.md §3.1 outputs:
vertices, per-segment magnitude/duration/rate, rmse, p-of-F); what users
of LandTrendr outputs overwhelmingly consume downstream are **change
maps** — per-pixel "greatest disturbance" / "greatest recovery" layers
(year of detection, magnitude, duration, rate, pre-change value, signal
to noise) with magnitude/duration/p filters and a minimum-mapping-unit
sieve.  This module is that standard post-processing layer, an
*extension* beyond the reference's surface (clearly marked as such —
SURVEY.md's inventory does not list it), following the de-facto semantics
of the public LandTrendr change-mapper tooling.

Design: the per-pixel segment selection is a tiny fixed-shape jitted op
over ``(px, NM)`` arrays — elementwise masks + one argmax over the
segment axis, the same no-collectives batched shape as the segmentation
kernel, so it runs on TPU or CPU and can fuse into future on-device
pipelines.  The minimum-mapping-unit sieve is inherently spatial
(connected components) and runs on host over the assembled 2-D mask,
exactly where the GDAL-era pipelines did it.

All values are in the index's **natural** orientation (the convention of
the written rasters — driver._tile_arrays): a disturbance is a fitted
*drop* for NBR/NDVI/TCW, and the reported magnitude keeps its natural
sign (negative for an NBR disturbance).  Filters are expressed on the
positive "change size" ``|mag|``.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np

from land_trendr_tpu.ops import indices as idx

__all__ = ["ChangeFilter", "select_change", "write_change_maps", "CHANGE_PRODUCTS"]

CHANGE_PRODUCTS = ("mask", "yod", "mag", "dur", "rate", "preval", "dsnr")

#: rasters (from assemble_outputs) the selection needs
_REQUIRED = (
    "vertex_years",
    "vertex_fit_vals",
    "seg_magnitude",
    "seg_duration",
    "seg_rate",
    "model_valid",
    "p_of_f",
    "rmse",
)


@dataclasses.dataclass(frozen=True)
class ChangeFilter:
    """Which segments qualify as "the change", and how to pick among them.

    Frozen/hashable so it is a static argument of the jitted selector —
    changing a filter recompiles a trivially small program.

    ``kind``: ``"disturbance"`` selects segments moving in the index's
    disturbance direction (fitted drop for NBR/NDVI/TCW),
    ``"recovery"`` the opposite direction.
    ``sort``: among qualifying segments — ``"greatest"`` picks max
    ``|mag|``, ``"newest"``/``"oldest"`` pick by year of detection.
    Ties break to the earliest segment slot, deterministically.
    ``min_mag``: minimum ``|mag|`` (natural index units).
    ``min_dur``/``max_dur``: bounds on segment duration in years (the
    classic "fast disturbance" filter is ``max_dur=4``).
    ``min_preval``: minimum fitted value at the segment's start vertex
    (e.g. require pre-disturbance NBR ≥ 0.3 to exclude bare ground).
    ``max_p``: additional p-of-F cap on top of the run's own
    ``p_val_threshold`` (1.0 = off).
    ``year_min``/``year_max``: bounds on the year of detection.
    """

    kind: str = "disturbance"
    sort: str = "greatest"
    min_mag: float = 0.0
    min_dur: float = 0.0
    max_dur: float = math.inf
    min_preval: float = -math.inf
    max_p: float = 1.0
    year_min: float = -math.inf
    year_max: float = math.inf

    def __post_init__(self) -> None:
        if self.kind not in ("disturbance", "recovery"):
            raise ValueError(f"kind={self.kind!r} not 'disturbance'|'recovery'")
        if self.sort not in ("greatest", "newest", "oldest"):
            raise ValueError(
                f"sort={self.sort!r} not 'greatest'|'newest'|'oldest'"
            )


@functools.partial(jax.jit, static_argnames=("sign", "filt"))
def select_change(
    vertex_years: jnp.ndarray,   # (px, NV) natural years, 0 in dead slots
    vertex_fit_vals: jnp.ndarray,  # (px, NV) fitted value at each vertex
    seg_magnitude: jnp.ndarray,  # (px, NM) natural-orientation fit delta
    seg_duration: jnp.ndarray,   # (px, NM) years, 0 in dead slots
    seg_rate: jnp.ndarray,       # (px, NM)
    model_valid: jnp.ndarray,    # (px,) bool
    p_of_f: jnp.ndarray,         # (px,)
    rmse: jnp.ndarray,           # (px,)
    *,
    sign: float,                 # idx.DISTURBANCE_SIGN[index]
    filt: ChangeFilter,
) -> dict[str, jnp.ndarray]:
    """Pick each pixel's change segment; returns per-pixel product arrays.

    ``yod`` (year of detection) is the first year AFTER the segment's
    start vertex — the year the change first shows in the fitted
    trajectory, matching common LandTrendr change-map convention.  0
    where no segment qualifies.
    """
    dtype = seg_magnitude.dtype
    nm = seg_magnitude.shape[1]

    live = seg_duration > 0.0
    # disturbance-positive size of each segment's change
    dmag = jnp.asarray(sign, dtype) * seg_magnitude
    want = dmag > 0.0 if filt.kind == "disturbance" else dmag < 0.0
    size = jnp.abs(seg_magnitude)
    start_year = vertex_years[:, :nm]
    preval = vertex_fit_vals[:, :nm]
    yod = start_year + 1.0

    ok = (
        live
        & want
        & model_valid[:, None]
        & (p_of_f[:, None] <= filt.max_p)
        & (size >= filt.min_mag)
        & (seg_duration >= filt.min_dur)
        & (seg_duration <= filt.max_dur)
        & (preval >= filt.min_preval)
        & (yod >= filt.year_min)
        & (yod <= filt.year_max)
    )

    if filt.sort == "greatest":
        key = size
    elif filt.sort == "newest":
        key = yod
    else:  # oldest: argmax of negated year
        key = -yod
    neg_inf = jnp.asarray(-jnp.inf, dtype)
    chosen = jnp.argmax(jnp.where(ok, key, neg_inf), axis=1)
    changed = jnp.any(ok, axis=1)

    def pick(a):
        return jnp.where(changed, jnp.take_along_axis(a, chosen[:, None], 1)[:, 0], 0.0)

    mag = pick(seg_magnitude)
    dur = pick(seg_duration)
    rmse_safe = jnp.where(rmse > 0.0, rmse, 1.0)
    return {
        "mask": changed,
        "yod": pick(yod).astype(jnp.int32),
        "mag": mag,
        "dur": dur,
        "rate": pick(seg_rate),
        "preval": pick(preval),
        # disturbance signal-to-noise: change size in units of model rmse
        "dsnr": jnp.where(rmse > 0.0, jnp.abs(mag) / rmse_safe, 0.0),
    }


def mmu_sieve(mask: np.ndarray, mmu: int) -> np.ndarray:
    """Drop 4-connected changed patches smaller than ``mmu`` pixels."""
    if mmu <= 1:
        return mask
    from scipy import ndimage

    labels, n = ndimage.label(mask, structure=[[0, 1, 0], [1, 1, 1], [0, 1, 0]])
    if n == 0:
        return mask
    counts = np.bincount(labels.ravel())
    keep = counts >= mmu
    keep[0] = False
    return keep[labels]


def write_change_maps(
    seg_dir: str,
    dest: str,
    index: str = "nbr",
    filt: ChangeFilter = ChangeFilter(),
    mmu: int = 1,
) -> dict[str, str]:
    """Segment rasters (assemble_outputs' out_dir) → change-map rasters.

    Reads the required products from ``seg_dir``, runs the jitted
    selector per pixel, applies the minimum-mapping-unit sieve on the
    changed mask (``mmu`` > 1), and writes one single-band GeoTIFF per
    product in ``dest`` (``change_yod.tif`` …), on the input grid.
    Returns product → path.
    """
    from land_trendr_tpu.io.geotiff import read_geotiff, write_geotiff

    index = index.lower()
    if index not in idx.DISTURBANCE_SIGN:
        raise ValueError(f"unknown index {index!r} (one of {idx.INDEX_NAMES})")

    arrs = {}
    geo = None
    for name in _REQUIRED:
        path = os.path.join(seg_dir, f"{name}.tif")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"{path} missing — run `segment` (assemble_outputs) first; "
                f"change maps need {_REQUIRED}"
            )
        a, g, _ = read_geotiff(path)
        arrs[name] = a
        geo = geo or g
    h, w = arrs["model_valid"].shape[-2:]
    px = h * w

    def flat(a):
        return np.moveaxis(a.reshape(-1, h, w), 0, -1).reshape(px, -1)

    out = select_change(
        flat(arrs["vertex_years"]).astype(np.float32),
        flat(arrs["vertex_fit_vals"]).astype(np.float32),
        flat(arrs["seg_magnitude"]).astype(np.float32),
        flat(arrs["seg_duration"]).astype(np.float32),
        flat(arrs["seg_rate"]).astype(np.float32),
        flat(arrs["model_valid"]).astype(bool)[:, 0],
        flat(arrs["p_of_f"]).astype(np.float32)[:, 0],
        flat(arrs["rmse"]).astype(np.float32)[:, 0],
        sign=idx.DISTURBANCE_SIGN[index],
        filt=filt,
    )
    out = {k: np.asarray(v).reshape(h, w) for k, v in out.items()}

    mask = mmu_sieve(out["mask"], mmu)
    out["mask"] = mask
    for k in CHANGE_PRODUCTS:
        if k != "mask":
            out[k] = np.where(mask, out[k], 0)

    os.makedirs(dest, exist_ok=True)
    paths = {}
    for k in CHANGE_PRODUCTS:
        a = out[k]
        if a.dtype == np.bool_:
            a = a.astype(np.uint8)
        path = os.path.join(dest, f"change_{k}.tif")
        write_geotiff(path, a[None], geo=geo)
        paths[k] = path
    return paths
