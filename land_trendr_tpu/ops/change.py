"""Change-map products derived from segmentation rasters.

The reference pipeline stops at segment rasters (SURVEY.md §3.1 outputs:
vertices, per-segment magnitude/duration/rate, rmse, p-of-F); what users
of LandTrendr outputs overwhelmingly consume downstream are **change
maps** — per-pixel "greatest disturbance" / "greatest recovery" layers
(year of detection, magnitude, duration, rate, pre-change value, signal
to noise) with magnitude/duration/p filters and a minimum-mapping-unit
sieve.  This module is that standard post-processing layer, an
*extension* beyond the reference's surface (clearly marked as such —
SURVEY.md's inventory does not list it), following the de-facto semantics
of the public LandTrendr change-mapper tooling.

Design: the per-pixel segment selection is a tiny fixed-shape jitted op
over ``(px, NM)`` arrays — elementwise masks + one argmax over the
segment axis, the same no-collectives batched shape as the segmentation
kernel, so it runs on TPU or CPU and can fuse into future on-device
pipelines.  The minimum-mapping-unit sieve is inherently spatial
(connected components) and runs on host over the assembled 2-D mask,
exactly where the GDAL-era pipelines did it.

All values are in the index's **natural** orientation (the convention of
the written rasters — driver._tile_arrays): a disturbance is a fitted
*drop* for NBR/NDVI/TCW, and the reported magnitude keeps its natural
sign (negative for an NBR disturbance).  Filters are expressed on the
positive "change size" ``|mag|``.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np

from land_trendr_tpu.ops import indices as idx

__all__ = [
    "ChangeFilter",
    "select_change",
    "write_change_maps",
    "sieve_change_rasters",
    "CHANGE_PRODUCTS",
]

CHANGE_PRODUCTS = ("mask", "yod", "mag", "dur", "rate", "preval", "dsnr")

#: rasters (from assemble_outputs) the selection needs
_REQUIRED = (
    "vertex_years",
    "vertex_fit_vals",
    "seg_magnitude",
    "seg_duration",
    "seg_rate",
    "model_valid",
    "p_of_f",
    "rmse",
)


@dataclasses.dataclass(frozen=True)
class ChangeFilter:
    """Which segments qualify as "the change", and how to pick among them.

    Frozen/hashable so it is a static argument of the jitted selector —
    changing a filter recompiles a trivially small program.

    ``kind``: ``"disturbance"`` selects segments moving in the index's
    disturbance direction (fitted drop for NBR/NDVI/TCW),
    ``"recovery"`` the opposite direction.
    ``sort``: among qualifying segments — ``"greatest"`` picks max
    ``|mag|``, ``"newest"``/``"oldest"`` pick by year of detection.
    Ties break to the earliest segment slot, deterministically.
    ``min_mag``: minimum ``|mag|`` (natural index units).
    ``min_dur``/``max_dur``: bounds on segment duration in years (the
    classic "fast disturbance" filter is ``max_dur=4``).
    ``min_preval``: minimum fitted value at the segment's start vertex
    (e.g. require pre-disturbance NBR ≥ 0.3 to exclude bare ground).
    ``max_p``: additional p-of-F cap on top of the run's own
    ``p_val_threshold`` (1.0 = off).
    ``year_min``/``year_max``: bounds on the year of detection.
    """

    kind: str = "disturbance"
    sort: str = "greatest"
    min_mag: float = 0.0
    min_dur: float = 0.0
    max_dur: float = math.inf
    min_preval: float = -math.inf
    max_p: float = 1.0
    year_min: float = -math.inf
    year_max: float = math.inf

    def __post_init__(self) -> None:
        if self.kind not in ("disturbance", "recovery"):
            raise ValueError(f"kind={self.kind!r} not 'disturbance'|'recovery'")
        if self.sort not in ("greatest", "newest", "oldest"):
            raise ValueError(
                f"sort={self.sort!r} not 'greatest'|'newest'|'oldest'"
            )


@functools.partial(jax.jit, static_argnames=("sign", "filt"))
def select_change(
    vertex_years: jnp.ndarray,   # (px, NV) natural years, 0 in dead slots
    vertex_fit_vals: jnp.ndarray,  # (px, NV) fitted value at each vertex
    seg_magnitude: jnp.ndarray,  # (px, NM) natural-orientation fit delta
    seg_duration: jnp.ndarray,   # (px, NM) years, 0 in dead slots
    seg_rate: jnp.ndarray,       # (px, NM)
    model_valid: jnp.ndarray,    # (px,) bool
    p_of_f: jnp.ndarray,         # (px,)
    rmse: jnp.ndarray,           # (px,)
    *,
    sign: float,                 # idx.DISTURBANCE_SIGN[index]
    filt: ChangeFilter,
) -> dict[str, jnp.ndarray]:
    """Pick each pixel's change segment; returns per-pixel product arrays.

    ``yod`` (year of detection) is the first year AFTER the segment's
    start vertex — the year the change first shows in the fitted
    trajectory, matching common LandTrendr change-map convention.  0
    where no segment qualifies.
    """
    dtype = seg_magnitude.dtype
    nm = seg_magnitude.shape[1]

    live = seg_duration > 0.0
    # disturbance-positive size of each segment's change
    dmag = jnp.asarray(sign, dtype) * seg_magnitude
    want = dmag > 0.0 if filt.kind == "disturbance" else dmag < 0.0
    size = jnp.abs(seg_magnitude)
    start_year = vertex_years[:, :nm]
    preval = vertex_fit_vals[:, :nm]
    yod = start_year + 1.0

    ok = (
        live
        & want
        & model_valid[:, None]
        & (p_of_f[:, None] <= filt.max_p)
        & (size >= filt.min_mag)
        & (seg_duration >= filt.min_dur)
        & (seg_duration <= filt.max_dur)
        & (preval >= filt.min_preval)
        & (yod >= filt.year_min)
        & (yod <= filt.year_max)
    )

    if filt.sort == "greatest":
        key = size
    elif filt.sort == "newest":
        key = yod
    else:  # oldest: argmax of negated year
        key = -yod
    neg_inf = jnp.asarray(-jnp.inf, dtype)
    chosen = jnp.argmax(jnp.where(ok, key, neg_inf), axis=1)
    changed = jnp.any(ok, axis=1)
    # one-hot where-sum instead of take_along_axis: batched dynamic picks
    # serialize on TPU (TPU_KERNEL_DIAG_r04.md §3); adding explicit zeros
    # is identical up to the sign of zero (-0.0 picks as +0.0) and NaN-safe
    # against garbage in unselected segments
    oh = chosen[:, None] == jnp.arange(seg_magnitude.shape[1])[None, :]

    def pick(a):
        sel = jnp.sum(jnp.where(oh, a, jnp.zeros((), a.dtype)), axis=1)
        return jnp.where(changed, sel, 0.0)

    mag = pick(seg_magnitude)
    dur = pick(seg_duration)
    rmse_safe = jnp.where(rmse > 0.0, rmse, 1.0)
    return {
        "mask": changed,
        "yod": pick(yod).astype(jnp.int32),
        "mag": mag,
        "dur": dur,
        "rate": pick(seg_rate),
        "preval": pick(preval),
        # disturbance signal-to-noise: change size in units of model rmse
        "dsnr": jnp.where(rmse > 0.0, jnp.abs(mag) / rmse_safe, 0.0),
    }


def _run_lengths_arange(lengths: np.ndarray) -> np.ndarray:
    """``[0..l0-1, 0..l1-1, ...]`` without a Python loop."""
    csum = np.cumsum(lengths)
    ids = np.arange(int(csum[-1]), dtype=np.int64)
    return ids - np.repeat(csum - lengths, lengths)


def _runs4(
    mask: np.ndarray,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]:
    """4-connected components as horizontal runs, pure NumPy (no scipy
    dependency — ADVICE r3: the lazy ``scipy.ndimage`` import was the
    repo's only undeclared dependency).

    Horizontal True-runs are found vectorized from row-wise sign changes
    (row-chunked, so temporaries stay O(chunk) even on a CONUS-scale
    mask); a union-find merges runs that overlap column-wise in adjacent
    rows (4-connectivity).  Python-side work is O(runs + overlaps) on run
    *endpoints* — never per pixel.

    Returns ``(rows, starts, ends, component_of_run, n_components)``;
    components are numbered 0..n-1 in first-run order.
    """
    h, w = mask.shape
    rows_l: list[np.ndarray] = []
    s_l: list[np.ndarray] = []
    e_l: list[np.ndarray] = []
    chunk_rows = max(1, (1 << 22) // max(w, 1))
    for r0 in range(0, h, chunk_rows):
        d = np.diff(
            np.pad(mask[r0 : r0 + chunk_rows].astype(np.int8), ((0, 0), (1, 1))),
            axis=1,
        )
        st = np.argwhere(d == 1)
        if len(st) == 0:
            continue
        rows_l.append((st[:, 0] + r0).astype(np.int64))
        s_l.append(st[:, 1].astype(np.int32))
        e_l.append(np.argwhere(d == -1)[:, 1].astype(np.int32))
    if not rows_l:
        z = np.zeros(0, np.int64)
        return z, z, z, z, 0
    rows = np.concatenate(rows_l)
    s = np.concatenate(s_l)
    e = np.concatenate(e_l)  # row-major ⇒ pairs with starts 1:1
    n = len(s)

    parent = np.arange(n, dtype=np.int64)

    def find(i: int) -> int:
        while parent[i] != i:
            parent[i] = parent[parent[i]]  # path halving
            i = parent[i]
        return i

    row_start = np.searchsorted(rows, np.arange(h + 1))
    for r in range(1, h):
        a0, a1 = row_start[r - 1], row_start[r]
        b0, b1 = row_start[r], row_start[r + 1]
        if a0 == a1 or b0 == b1:
            continue
        # runs within a row are sorted and disjoint: run a overlaps run b
        # iff  s_a < e_b  and  e_a > s_b  — a contiguous index range
        lo = np.searchsorted(e[a0:a1], s[b0:b1], side="right")
        hi = np.searchsorted(s[a0:a1], e[b0:b1], side="left")
        for j in range(b1 - b0):
            for ai in range(lo[j], hi[j]):
                ra, rb = find(a0 + ai), find(b0 + j)
                if ra != rb:
                    parent[max(ra, rb)] = min(ra, rb)

    roots = np.fromiter((find(i) for i in range(n)), np.int64, n)
    _, lab = np.unique(roots, return_inverse=True)
    return rows, s, e, lab, int(lab.max()) + 1


def _paint_runs(
    out_flat: np.ndarray,
    w: int,
    rows: np.ndarray,
    s: np.ndarray,
    e: np.ndarray,
    values: np.ndarray,
    budget_px: int = 1 << 24,
) -> None:
    """Scatter per-run ``values`` onto the flat image, in run groups of at
    most ``budget_px`` painted pixels — the index temporaries stay ~100 MB
    instead of scaling with the mask's total True count (the round-4
    memory spike at mosaic scale: 77M True px → several 600 MB int64
    repeats at once)."""
    lengths = (e - s).astype(np.int64)
    idx0 = rows * w + s
    csum = np.cumsum(lengths)
    n = len(lengths)
    start = 0
    while start < n:
        base = csum[start - 1] if start else 0
        stop = min(n, int(np.searchsorted(csum, base + budget_px)) + 1)
        ln = lengths[start:stop]
        fi = np.repeat(idx0[start:stop], ln) + _run_lengths_arange(ln)
        out_flat[fi] = np.repeat(values[start:stop], ln)
        start = stop


def label4(mask: np.ndarray) -> tuple[np.ndarray, int]:
    """4-connected component labeling via :func:`_runs4`.

    Returns ``(labels, n)`` with background 0 and components 1..n,
    matching ``scipy.ndimage.label`` with the 4-connected structure.
    """
    h, w = mask.shape
    rows, s, e, lab, n = _runs4(mask)
    out = np.zeros(h * w, np.int32)
    if n:
        _paint_runs(out, w, rows, s, e, lab.astype(np.int32) + 1)
    return out.reshape(h, w), n


def mmu_sieve(mask: np.ndarray, mmu: int) -> np.ndarray:
    """Drop 4-connected changed patches smaller than ``mmu`` pixels.

    Works entirely on the run representation — per-component pixel counts
    come from a bincount over runs and the kept runs paint a fresh boolean
    mask, so no full int32 label image (1 GB at 16k²) ever exists.
    """
    if mmu <= 1:
        return mask
    mask = np.asarray(mask)
    h, w = mask.shape
    rows, s, e, lab, n = _runs4(mask)
    if n == 0:
        return mask
    counts = np.bincount(lab, weights=(e - s).astype(np.float64))
    keep_run = counts[lab] >= mmu
    out = np.zeros(h * w, bool)
    if keep_run.any():
        k = keep_run.nonzero()[0]
        _paint_runs(out, w, rows[k], s[k], e[k], np.ones(len(k), bool))
    return out.reshape(h, w)


def write_change_maps(
    seg_dir: str,
    dest: str,
    index: str = "nbr",
    filt: ChangeFilter = ChangeFilter(),
    mmu: int = 1,
    band_px: int = 1 << 21,
    align_bands: bool = True,
) -> dict[str, str]:
    """Segment rasters (assemble_outputs' out_dir) → change-map rasters.

    STREAMING: the required products are window-read in row bands
    (``read_geotiff_window``), the jitted selector runs per band, and each
    change product streams into a :class:`GeoTiffStreamWriter` — host
    memory is O(row band × products) plus ONE full-raster boolean mask
    (1 byte/px; 1.6 GB even at a 40k×40k CONUS mosaic), which the
    minimum-mapping-unit sieve needs whole because patch connectivity is
    global.  With ``mmu`` > 1, pixels the sieve removes are zeroed by a
    second windowed pass over the just-written products (window-read →
    zero → stream-rewrite → atomic replace), so peak memory never grows
    with raster size.  Writes one single-band GeoTIFF per product in
    ``dest`` (``change_yod.tif`` …), on the input grid.  Returns
    product → path.
    """
    from land_trendr_tpu.io.geotiff import (
        GeoTiffStreamWriter,
        read_geotiff_info,
        read_geotiff_window,
    )

    index = index.lower()
    if index not in idx.DISTURBANCE_SIGN:
        raise ValueError(f"unknown index {index!r} (one of {idx.INDEX_NAMES})")

    src = {}
    for name in _REQUIRED:
        path = os.path.join(seg_dir, f"{name}.tif")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"{path} missing — run `segment` (assemble_outputs) first; "
                f"change maps need {_REQUIRED}"
            )
        src[name] = path
    geo, info = read_geotiff_info(src["model_valid"])
    h, w = info.height, info.width
    # Chunk the raster in TWO dimensions, aligned to the source rasters'
    # block grid (so no source block is decoded by more than one chunk):
    # row bands of the block height, split column-wise into ~band_px-pixel
    # chunks.  Memory is then bounded by band_px (inputs ~130 B/px, the
    # jitted selector's XLA transients ~1 kB/px) INDEPENDENT of raster
    # width — a single full-width block row of a 40k-wide mosaic alone
    # would be 10M px.  Strip sources don't column-split (a column chunk
    # would re-decode the full-width strip it slices).
    blk_r = (info.block_rows or 1) if align_bands else 1
    blk_c = (info.block_cols or w) if align_bands else 1
    band_rows = _aligned_band_rows(h, w, band_px, blk_r)
    if info.tiled and band_rows * w > band_px:
        cw = max(1, band_px // max(band_rows, 1))
        cw = min(w, max(blk_c, cw // blk_c * blk_c))
    else:
        cw = w
    # one compiled selector shape serves every chunk: ragged edge chunks
    # pad up with model_valid=False rows (all outputs zero there)
    chunk_px = band_rows * cw

    out_dtypes = {
        k: np.dtype(np.uint8) if k == "mask"
        else np.dtype(np.int32) if k == "yod"
        else np.dtype(np.float32)
        for k in CHANGE_PRODUCTS
    }
    os.makedirs(dest, exist_ok=True)
    paths = {k: os.path.join(dest, f"change_{k}.tif") for k in CHANGE_PRODUCTS}
    writers = {
        k: GeoTiffStreamWriter(paths[k], h, w, 1, out_dtypes[k], geo=geo)
        for k in CHANGE_PRODUCTS
    }
    # the sieve needs global connectivity, so with mmu > 1 ONE full-raster
    # boolean (1 byte/px) is held; the default mmu=1 path stays O(row band)
    mask_full = np.zeros((h, w), bool) if mmu > 1 else None
    try:
        for y0 in range(0, h, band_rows):
            hb = min(band_rows, h - y0)
            for x0 in range(0, w, cw):
                wb = min(cw, w - x0)
                arrs = {
                    name: np.asarray(
                        read_geotiff_window(src[name], y0, x0, hb, wb)
                    )
                    for name in _REQUIRED
                }
                px = hb * wb

                def flat(a):
                    fl = np.moveaxis(a.reshape(-1, hb, wb), 0, -1)
                    fl = fl.reshape(px, -1)
                    if px < chunk_px:  # ragged edge → canonical shape
                        fl = np.pad(fl, ((0, chunk_px - px), (0, 0)))
                    return fl

                out = select_change(
                    flat(arrs["vertex_years"]).astype(np.float32),
                    flat(arrs["vertex_fit_vals"]).astype(np.float32),
                    flat(arrs["seg_magnitude"]).astype(np.float32),
                    flat(arrs["seg_duration"]).astype(np.float32),
                    flat(arrs["seg_rate"]).astype(np.float32),
                    flat(arrs["model_valid"]).astype(bool)[:, 0],
                    flat(arrs["p_of_f"]).astype(np.float32)[:, 0],
                    flat(arrs["rmse"]).astype(np.float32)[:, 0],
                    sign=idx.DISTURBANCE_SIGN[index],
                    filt=filt,
                )
                out = {
                    k: np.asarray(v)[:px].reshape(hb, wb)
                    for k, v in out.items()
                }
                if mask_full is not None:
                    mask_full[y0 : y0 + hb, x0 : x0 + wb] = out["mask"]
                for k in CHANGE_PRODUCTS:
                    writers[k].write(
                        y0, x0, out[k].astype(out_dtypes[k], copy=False)
                    )
        for wr in writers.values():
            wr.close()
    except BaseException:
        for wr in writers.values():
            try:
                wr.abort()
            except Exception:
                pass
        raise

    if mmu > 1:
        removed = mask_full & ~mmu_sieve(mask_full, mmu)
        if removed.any():
            for k in CHANGE_PRODUCTS:
                _zero_removed_rewrite(
                    paths[k], h, w, out_dtypes[k], removed, geo, band_rows
                )
    return paths


def _aligned_band_rows(h: int, w: int, band_px: int, blk: int) -> int:
    """Row-band height targeting ~band_px pixels, rounded to the source
    block height so no block row is decoded by more than one band."""
    band_rows = max(1, min(h, band_px // max(w, 1)))
    return min(h, max(blk, band_rows // blk * blk))


def sieve_change_rasters(
    out_dir: str, mmu: int, band_px: int = 1 << 21
) -> None:
    """Apply the minimum-mapping-unit sieve to ALREADY-ASSEMBLED change
    rasters (``change_mask.tif`` + friends in ``out_dir``) — the spatial
    stage of the fused on-device change path (``RunConfig.change_filt``),
    which computes per-pixel selection on device but cannot see patch
    connectivity across tiles.  Windowed row-band reads keep memory at one
    full-raster boolean plus O(band); products rewrite atomically."""
    if mmu <= 1:
        return
    from land_trendr_tpu.io.geotiff import read_geotiff_info, read_geotiff_window

    mask_path = os.path.join(out_dir, "change_mask.tif")
    if not os.path.exists(mask_path):
        raise FileNotFoundError(
            f"{mask_path} missing — sieve_change_rasters needs an assembled "
            "change_filt run (RunConfig.change_filt + assemble_outputs)"
        )
    geo, info = read_geotiff_info(mask_path)
    h, w = info.height, info.width
    band_rows = _aligned_band_rows(h, w, band_px, info.block_rows or 1)
    mask = np.zeros((h, w), bool)
    for y0 in range(0, h, band_rows):
        hb = min(band_rows, h - y0)
        mask[y0 : y0 + hb] = (
            np.asarray(read_geotiff_window(mask_path, y0, 0, hb, w)) > 0
        )
    removed = mask & ~mmu_sieve(mask, mmu)
    if not removed.any():
        return
    # mask LAST: a crash mid-pass must leave the mask still showing the
    # unsieved state, so a re-run recomputes the same `removed` and
    # self-heals — mask-first would make the retry a silent no-op while
    # the value products keep sieved-out pixels
    for k in sorted(CHANGE_PRODUCTS, key=lambda k: k == "mask"):
        path = os.path.join(out_dir, f"change_{k}.tif")
        p_info = read_geotiff_info(path)[1]
        _zero_removed_rewrite(
            path, h, w, p_info.dtype, removed, geo, band_rows,
            compress=p_info.compression_name(),
            overviews=p_info.overview_pages,
        )


def _zero_removed_rewrite(
    path: str,
    h: int,
    w: int,
    dtype: np.dtype,
    removed: np.ndarray,
    geo,
    band_rows: int,
    compress: str = "deflate",
    overviews: int = 0,
) -> None:
    """Zero sieve-removed pixels of one just-written product, windowed:
    read → mask → stream into a sibling tmp → atomic replace.  The
    rewrite reproduces the source's compression/overview layout so a
    sieved raster keeps whatever pyramid/codec the run configured."""
    from land_trendr_tpu.io.geotiff import GeoTiffStreamWriter, read_geotiff_window

    tmp = f"{path}.{os.getpid()}.tmp"
    with GeoTiffStreamWriter(
        tmp, h, w, 1, dtype, geo=geo, compress=compress, overviews=overviews
    ) as wr:
        for y0 in range(0, h, band_rows):
            hb = min(band_rows, h - y0)
            a = np.asarray(read_geotiff_window(path, y0, 0, hb, w))
            a = np.where(removed[y0 : y0 + hb], 0, a).astype(dtype, copy=False)
            wr.write(y0, 0, a)
    os.replace(tmp, path)
