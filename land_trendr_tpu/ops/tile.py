"""Fused tile-processing op: raw Landsat DNs in, segmentation out.

The reference's driver computes the spectral index host-side before the
per-pixel map tasks see the data (SURVEY.md §4 call stack (1): "read Landsat
stack, compute index, mask" happens in the driver, through GDAL).  On TPU
that order is wrong: HBM feeding is the projected bottleneck (SURVEY.md §7
hard-part 4 — ~1.5 GB/s of int16 per chip at the 10M px/s target), so the
framework ships the *narrowest* representation across PCIe/DCN — int16
surface-reflectance DNs plus the uint16 QA bitfield — and fuses
DN→reflectance scaling, index math, QA+range masking, and the full
segmentation pipeline into one jitted program.  XLA folds the scaling and
index arithmetic into the despike stage's first pass over the series; the
bands never round-trip to HBM as float32.

Feeding cost per pixel-year: 6 bands × 2 B + 2 B QA = 14 B as DNs versus
8 B as a precomputed float32 index+mask — but the DN path lets one transfer
serve *several* indices (NBR segmentation + NDVI/TCW FTV outputs), which
the float path cannot, and keeps all math on device.  Both entry points are
provided; the runtime driver uses the fused DN path.
"""

from __future__ import annotations

import functools
from typing import Mapping, NamedTuple

import jax
import jax.numpy as jnp

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.ops import indices as idx
from land_trendr_tpu.ops.change import ChangeFilter, select_change
from land_trendr_tpu.ops.ftv import jax_fit_to_vertices
from land_trendr_tpu.ops.segment import (
    SegOutputs,
    jax_segment_pixels,
    jax_segment_pixels_chunked,
)
from land_trendr_tpu.parallel.mesh import pad_to_multiple

__all__ = ["TileOutputs", "process_tile_dn", "process_tile_index"]


class TileOutputs(NamedTuple):
    """Segmentation of the primary index plus FTV fits of secondary indices."""

    seg: SegOutputs
    #: index name → (PX, NY) fitted-trajectory values (disturbance-positive
    #: convention, matching the segmentation input sign).
    ftv: dict[str, jnp.ndarray]
    #: fused change-map products (ops/change.CHANGE_PRODUCTS → (PX,)
    #: arrays, natural orientation) when the run asked for them; the
    #: spatial mmu sieve cannot run here (per-tile, no global
    #: connectivity) and applies post-assembly.
    change: "dict[str, jnp.ndarray] | None" = None


#: lane-axis block of the Pallas family kernel (segment_pallas); tile
#: pixel counts are padded up to a multiple of this, and chunk sizes used
#: with impl="pallas" must divide by it.  256 measured fastest on TPU v5
#: lite for the round-5 fused kernel (23.2M px/s vs 16.7M at 1024 — the
#: (NY, 256) working set relieves VMEM/register pressure; >=2048 fails to
#: compile outright), see tools/tpu_probe.py block sweep.
PALLAS_BLOCK = 256


def resolve_impl(impl: str) -> str:
    """Resolve an ``impl`` choice ("auto"/"pallas"/"xla") to a concrete one.

    "auto" picks the Pallas family kernel only where its compiled form can
    actually run: a TPU backend without ``jax_enable_x64`` (Mosaic is
    f32-only and its x64-mode lowering is broken — see
    ``segment_pallas.family_stats_pallas``).  The resolved value — not
    "auto" — is what the driver records in the manifest EXECUTION CONTEXT
    (not the run fingerprint: assembly stays impl-blind —
    ``RunConfig.fingerprint`` / ``test_impl_resume_context_rejected``), so
    a compute resume cannot mix implementations across backends.
    """
    if impl == "auto":
        import jax as _jax

        return (
            "pallas"
            if _jax.default_backend() == "tpu"
            and not _jax.config.jax_enable_x64
            else "xla"
        )
    if impl not in ("pallas", "xla"):
        raise ValueError(f"impl={impl!r} not one of 'auto', 'pallas', 'xla'")
    return impl


@functools.partial(
    jax.jit,
    static_argnames=(
        "index", "ftv_indices", "params", "scale", "offset", "reject_bits",
        "chunk", "change_filt", "impl",
    ),
)
def process_tile_dn(
    years: jnp.ndarray,
    dn_bands: Mapping[str, jnp.ndarray],
    qa: jnp.ndarray,
    index: str = "nbr",
    ftv_indices: tuple[str, ...] = (),
    params: LTParams = LTParams(),
    scale: float = 2.75e-5,
    offset: float = -0.2,
    reject_bits: int = idx.DEFAULT_QA_REJECT,
    chunk: int | None = None,
    change_filt: ChangeFilter | None = None,
    impl: str = "auto",
) -> TileOutputs:
    """Segment one tile straight from Collection-2 style DNs.

    Parameters
    ----------
    years : (NY,) shared year axis.
    dn_bands : band name → (PX, NY) int16/uint16 DN arrays; must contain
        whatever bands ``index`` and ``ftv_indices`` need (all six for TCW).
    qa : (PX, NY) uint16 QA_PIXEL bitfield.
    index : primary index driving the segmentation.
    ftv_indices : secondary indices fitted to the chosen vertices
        (classic LandTrendr FTV outputs, SURVEY.md §3.1 outputs).
    params, scale, offset, reject_bits : static knobs; one compile per
        combination.
    chunk : when set and PX > chunk, the segmentation runs through
        :func:`jax_segment_pixels_chunked` so transient HBM is bounded by
        ``chunk`` pixels (large tiles, e.g. tile_size >= 1024 — the kernel's
        working set is linear in PX).  PX is padded to the next chunk
        multiple with fully-masked rows and cropped back, so results are
        identical to the unchunked path (see the chunked kernel's
        contract).
    impl : segmentation kernel implementation — "auto" (Pallas family
        kernel on a TPU backend, XLA elsewhere; the round-4 measured
        default), "pallas", or "xla".  The two are decision-identical
        (tests/test_pallas.py; PARITY_f32_tpu_pallas.json); Pallas is
        ~3.3x faster on TPU v5 lite (BENCH_r04.json).
    """
    sr = {name: idx.scale_sr(dn, scale, offset) for name, dn in dn_bands.items()}
    mask = idx.qa_valid_mask(qa, reject_bits) & idx.sr_valid_mask(sr)
    primary = idx.compute_index(index, sr)
    px = primary.shape[0]
    impl = resolve_impl(impl)
    if impl == "pallas":
        from land_trendr_tpu.ops.segment_pallas import (
            jax_segment_pixels_pallas,
            jax_segment_pixels_pallas_chunked,
        )

        # the Pallas grid needs PX % block == 0; pad with masked rows
        # (padded rows come back model_valid=False and are cropped).
        # Mosaic only compiles on TPU — an explicit impl="pallas" on any
        # other backend runs interpret mode (slow; for debugging parity).
        blk = PALLAS_BLOCK
        interp = jax.default_backend() != "tpu"
        if interp:
            import warnings

            # advisor finding (round 4): a misconfigured production run
            # (impl="pallas", non-TPU backend) would otherwise look hung —
            # interpret mode is orders of magnitude slower than impl="xla"
            warnings.warn(
                f"impl='pallas' on backend {jax.default_backend()!r} runs "
                "Mosaic INTERPRET mode (debug-only, ~1000x slower than "
                "impl='xla'); use impl='auto' or 'xla' for production",
                RuntimeWarning,
                stacklevel=2,
            )
        primary_p, mask_p, _ = pad_to_multiple(primary, mask, blk)
        if chunk is not None and primary_p.shape[0] > chunk:
            if chunk > blk and chunk % blk:
                raise ValueError(
                    f"chunk={chunk} must be a multiple of the Pallas block "
                    f"({blk}) when impl='pallas' — adjust chunk_px or use "
                    "impl='xla'"
                )
            primary_p, mask_p, _ = pad_to_multiple(primary_p, mask_p, chunk)
            seg = jax_segment_pixels_pallas_chunked(
                years, primary_p, mask_p, params, chunk, blk, interp
            )
        else:
            seg = jax_segment_pixels_pallas(
                years, primary_p, mask_p, params, blk, interp
            )
        if primary_p.shape[0] != px:
            seg = SegOutputs(*(o[:px] for o in seg))
    elif chunk is not None and px > chunk:
        primary_p, mask_p, _ = pad_to_multiple(primary, mask, chunk)
        seg = jax_segment_pixels_chunked(years, primary_p, mask_p, params, chunk)
        if primary_p.shape[0] != px:
            seg = SegOutputs(*(o[:px] for o in seg))
    else:
        seg = jax_segment_pixels(years, primary, mask, params)
    ftv = {}
    for name in ftv_indices:
        series = idx.compute_index(name, sr)
        ftv[name] = jax_fit_to_vertices(
            years, series, mask, seg.vertex_indices, seg.n_vertices, params
        )
    change = None
    if change_filt is not None:
        # fused on-device change selection (the TPU-first ordering: the
        # selector is a tiny elementwise+argmax program over arrays
        # ALREADY in HBM — fusing it here costs nothing vs a second
        # host pass over assembled rasters).  The kernel fits in the
        # disturbance-positive orientation; the selector's contract is
        # natural orientation, so flip by DISTURBANCE_SIGN first.  The
        # spatial mmu sieve needs global connectivity and runs
        # post-assembly (runtime.driver.assemble_outputs callers).
        sign = idx.DISTURBANCE_SIGN[index.lower()]
        change = select_change(
            seg.vertex_years,
            sign * seg.vertex_fit_vals,
            sign * seg.seg_magnitude,
            seg.seg_duration,
            sign * seg.seg_rate,
            seg.model_valid,
            seg.p_of_f,
            seg.rmse,
            sign=sign,
            filt=change_filt,
        )
    return TileOutputs(seg=seg, ftv=ftv, change=change)


@functools.partial(jax.jit, static_argnames=("params",))
def process_tile_index(
    years: jnp.ndarray,
    values: jnp.ndarray,
    mask: jnp.ndarray,
    params: LTParams = LTParams(),
) -> SegOutputs:
    """Segment a tile from a precomputed index series (debug / parity path)."""
    return jax_segment_pixels(years, values, mask, params)
