"""Fused tile-processing op: raw Landsat DNs in, segmentation out.

The reference's driver computes the spectral index host-side before the
per-pixel map tasks see the data (SURVEY.md §4 call stack (1): "read Landsat
stack, compute index, mask" happens in the driver, through GDAL).  On TPU
that order is wrong: HBM feeding is the projected bottleneck (SURVEY.md §7
hard-part 4 — ~1.5 GB/s of int16 per chip at the 10M px/s target), so the
framework ships the *narrowest* representation across PCIe/DCN — int16
surface-reflectance DNs plus the uint16 QA bitfield — and fuses
DN→reflectance scaling, index math, QA+range masking, and the full
segmentation pipeline into one jitted program.  XLA folds the scaling and
index arithmetic into the despike stage's first pass over the series; the
bands never round-trip to HBM as float32.

Feeding cost per pixel-year: 6 bands × 2 B + 2 B QA = 14 B as DNs versus
8 B as a precomputed float32 index+mask — but the DN path lets one transfer
serve *several* indices (NBR segmentation + NDVI/TCW FTV outputs), which
the float path cannot, and keeps all math on device.  Both entry points are
provided; the runtime driver uses the fused DN path.
"""

from __future__ import annotations

import functools
from typing import Mapping, NamedTuple

import jax
import jax.numpy as jnp

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.ops import indices as idx
from land_trendr_tpu.ops.change import ChangeFilter, select_change
from land_trendr_tpu.ops.ftv import jax_fit_to_vertices
from land_trendr_tpu.ops.segment import (
    SegOutputs,
    jax_segment_pixels,
    jax_segment_pixels_chunked,
)
from land_trendr_tpu.parallel.mesh import pad_to_multiple

__all__ = ["TileOutputs", "process_tile_dn", "process_tile_index"]


class TileOutputs(NamedTuple):
    """Segmentation of the primary index plus FTV fits of secondary indices."""

    seg: SegOutputs
    #: index name → (PX, NY) fitted-trajectory values (disturbance-positive
    #: convention, matching the segmentation input sign).
    ftv: dict[str, jnp.ndarray]
    #: fused change-map products (ops/change.CHANGE_PRODUCTS → (PX,)
    #: arrays, natural orientation) when the run asked for them; the
    #: spatial mmu sieve cannot run here (per-tile, no global
    #: connectivity) and applies post-assembly.
    change: "dict[str, jnp.ndarray] | None" = None


@functools.partial(
    jax.jit,
    static_argnames=(
        "index", "ftv_indices", "params", "scale", "offset", "reject_bits",
        "chunk", "change_filt",
    ),
)
def process_tile_dn(
    years: jnp.ndarray,
    dn_bands: Mapping[str, jnp.ndarray],
    qa: jnp.ndarray,
    index: str = "nbr",
    ftv_indices: tuple[str, ...] = (),
    params: LTParams = LTParams(),
    scale: float = 2.75e-5,
    offset: float = -0.2,
    reject_bits: int = idx.DEFAULT_QA_REJECT,
    chunk: int | None = None,
    change_filt: ChangeFilter | None = None,
) -> TileOutputs:
    """Segment one tile straight from Collection-2 style DNs.

    Parameters
    ----------
    years : (NY,) shared year axis.
    dn_bands : band name → (PX, NY) int16/uint16 DN arrays; must contain
        whatever bands ``index`` and ``ftv_indices`` need (all six for TCW).
    qa : (PX, NY) uint16 QA_PIXEL bitfield.
    index : primary index driving the segmentation.
    ftv_indices : secondary indices fitted to the chosen vertices
        (classic LandTrendr FTV outputs, SURVEY.md §3.1 outputs).
    params, scale, offset, reject_bits : static knobs; one compile per
        combination.
    chunk : when set and PX > chunk, the segmentation runs through
        :func:`jax_segment_pixels_chunked` so transient HBM is bounded by
        ``chunk`` pixels (large tiles, e.g. tile_size >= 1024 — the kernel's
        working set is linear in PX).  PX is padded to the next chunk
        multiple with fully-masked rows and cropped back, so results are
        identical to the unchunked path (see the chunked kernel's
        contract).
    """
    sr = {name: idx.scale_sr(dn, scale, offset) for name, dn in dn_bands.items()}
    mask = idx.qa_valid_mask(qa, reject_bits) & idx.sr_valid_mask(sr)
    primary = idx.compute_index(index, sr)
    px = primary.shape[0]
    if chunk is not None and px > chunk:
        primary_p, mask_p, _ = pad_to_multiple(primary, mask, chunk)
        seg = jax_segment_pixels_chunked(years, primary_p, mask_p, params, chunk)
        if primary_p.shape[0] != px:
            seg = SegOutputs(*(o[:px] for o in seg))
    else:
        seg = jax_segment_pixels(years, primary, mask, params)
    ftv = {}
    for name in ftv_indices:
        series = idx.compute_index(name, sr)
        ftv[name] = jax_fit_to_vertices(
            years, series, mask, seg.vertex_indices, seg.n_vertices, params
        )
    change = None
    if change_filt is not None:
        # fused on-device change selection (the TPU-first ordering: the
        # selector is a tiny elementwise+argmax program over arrays
        # ALREADY in HBM — fusing it here costs nothing vs a second
        # host pass over assembled rasters).  The kernel fits in the
        # disturbance-positive orientation; the selector's contract is
        # natural orientation, so flip by DISTURBANCE_SIGN first.  The
        # spatial mmu sieve needs global connectivity and runs
        # post-assembly (runtime.driver.assemble_outputs callers).
        sign = idx.DISTURBANCE_SIGN[index.lower()]
        change = select_change(
            seg.vertex_years,
            sign * seg.vertex_fit_vals,
            sign * seg.seg_magnitude,
            seg.seg_duration,
            sign * seg.seg_rate,
            seg.model_valid,
            seg.p_of_f,
            seg.rmse,
            sign=sign,
            filt=change_filt,
        )
    return TileOutputs(seg=seg, ftv=ftv, change=change)


@functools.partial(jax.jit, static_argnames=("params",))
def process_tile_index(
    years: jnp.ndarray,
    values: jnp.ndarray,
    mask: jnp.ndarray,
    params: LTParams = LTParams(),
) -> SegOutputs:
    """Segment a tile from a precomputed index series (debug / parity path)."""
    return jax_segment_pixels(years, values, mask, params)
