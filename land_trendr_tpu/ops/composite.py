"""Annual medoid compositing: many acquisitions per year → one composite.

LandTrendr is an annual-series algorithm; the loaders therefore take one
image per year (SURVEY.md §1 — the reference consumes pre-built annual
stacks and tells multi-acquisition users to composite first).  Real
Collection-2 archives, however, ship every acquisition, so this module
closes that usability gap — an *extension* beyond the reference's
surface, following the de-facto standard of public LandTrendr tooling:
the **medoid** composite (per pixel, pick the clear-sky acquisition whose
spectral vector is closest to the per-band median of the year's clear-sky
acquisitions).  Medoid beats mean/median composites for trend work
because the output is an ACTUAL observation (no synthetic mixing of
dates), and beats max-NDVI because it is less biased toward peak
greenness.

TPU-shaped by construction: selection is a fixed-shape, branchless
``(dates, px, bands)`` program — masked per-band median via sort, one
squared-distance reduction, one argmin — jitted and chunked over the
pixel axis, with the same no-cross-pixel-collectives property as the
segmentation kernel.  The distance metric is computed on raw DN floats:
the C2 DN→reflectance transform is affine and identical across a year's
acquisitions, so it rescales all distances by the same factor and cannot
change any argmin (scaling is therefore skipped, exactly).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from land_trendr_tpu.ops import indices as idx

__all__ = ["medoid_indices", "medoid_composite"]


@jax.jit
def medoid_indices(
    sr: jnp.ndarray,     # (nd, px, nb) float — the year's acquisitions
    valid: jnp.ndarray,  # (nd, px) bool — clear-sky & finite per date
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-pixel medoid date index among valid acquisitions.

    Returns ``(choice, any_valid)``: ``choice[px]`` is the date index of
    the acquisition minimizing the squared distance to the per-band
    masked median (ties → lowest date index, deterministically); pixels
    with no valid date return index 0 with ``any_valid`` False.
    """
    valid = valid.astype(bool)
    sr = sr.astype(jnp.float32)
    inf = jnp.asarray(jnp.inf, sr.dtype)

    # masked per-(pixel, band) median: invalid dates sort to the top
    vals = jnp.where(valid[:, :, None], sr, inf)
    svals = jnp.sort(vals, axis=0)
    n = jnp.sum(valid, axis=0)  # (px,)
    lo_i = jnp.maximum((n - 1) // 2, 0)[None, :, None]
    hi_i = jnp.maximum(n // 2, 0)[None, :, None]
    nb = sr.shape[2]
    lo = jnp.take_along_axis(svals, jnp.broadcast_to(lo_i, (1, n.shape[0], nb)), axis=0)
    hi = jnp.take_along_axis(svals, jnp.broadcast_to(hi_i, (1, n.shape[0], nb)), axis=0)
    med = 0.5 * (lo + hi)  # (1, px, nb); +inf where the pixel has no valid date

    dist = jnp.sum((sr - med) ** 2, axis=-1)  # (nd, px); garbage where invalid
    dist = jnp.where(valid, dist, inf)
    choice = jnp.argmin(dist, axis=0).astype(jnp.int32)  # first-index ties
    any_valid = n > 0
    return jnp.where(any_valid, choice, 0).astype(jnp.int32), any_valid


def medoid_composite(
    dn: dict[str, np.ndarray],  # band -> (nd, H, W) int16/uint16 DNs
    qa: np.ndarray,             # (nd, H, W) uint16 QA_PIXEL
    reject_bits: int = idx.DEFAULT_QA_REJECT,
    scale: float = 2.75e-5,
    offset: float = -0.2,
    chunk_px: int = 1 << 21,
) -> tuple[dict[str, np.ndarray], np.ndarray]:
    """One year's acquisitions → (composite DN bands, composite QA).

    The composite keeps each band's original integer dtype and copies the
    CHOSEN acquisition's values verbatim (medoid = a real observation);
    QA is the chosen date's QA, so downstream masking still applies.
    A date is selectable only when it is BOTH QA-clear
    (``qa_valid_mask(reject_bits)``) and radiometrically valid
    (``sr_valid_mask`` on the ``scale``/``offset``-scaled reflectances) —
    the same two masks the segmentation feed applies (ops/tile.py), so a
    saturated-but-QA-clear acquisition cannot out-compete a usable one.
    Pixels with no valid acquisition get QA = 1 (the fill bit — exactly
    what the tile feed's padding uses) and DN 0.  Distances use whichever
    bands were loaded (the band-subset loaders pass only the run's
    required bands); ``chunk_px`` bounds device memory.
    """
    bands = sorted(dn)
    nd, h, w = qa.shape
    px_total = h * w
    qa_flat = qa.reshape(nd, px_total)
    dn_flat = {b: dn[b].reshape(nd, px_total) for b in bands}

    choice = np.empty(px_total, dtype=np.int32)
    ok = np.empty(px_total, dtype=bool)
    for start in range(0, px_total, chunk_px):
        end = min(start + chunk_px, px_total)
        sr = np.stack([dn_flat[b][:, start:end] for b in bands], axis=-1)
        scaled = {
            b: idx.scale_sr(
                jnp.asarray(dn_flat[b][:, start:end]), scale, offset
            )
            for b in bands
        }
        valid = np.asarray(
            idx.qa_valid_mask(qa_flat[:, start:end], reject_bits=reject_bits)
            & idx.sr_valid_mask(scaled)
        )
        n_real = end - start
        if start and n_real < chunk_px:
            # pad the ragged FINAL chunk (fully masked, sliced off below) so
            # one compiled shape serves the whole loop — otherwise every
            # distinct raster size costs an extra XLA compile (ADVICE r3)
            pad = chunk_px - n_real
            sr = np.pad(sr, ((0, 0), (0, pad), (0, 0)))
            valid = np.pad(valid, ((0, 0), (0, pad)))
        c, o = medoid_indices(jnp.asarray(sr, jnp.float32), jnp.asarray(valid))
        choice[start:end] = np.asarray(c)[:n_real]
        ok[start:end] = np.asarray(o)[:n_real]

    out_dn = {}
    for b in bands:
        picked = np.take_along_axis(dn_flat[b], choice[None, :], axis=0)[0]
        out_dn[b] = np.where(ok, picked, 0).astype(dn[b].dtype).reshape(h, w)
    qa_picked = np.take_along_axis(qa_flat, choice[None, :], axis=0)[0]
    out_qa = np.where(ok, qa_picked, 1).astype(np.uint16).reshape(h, w)
    return out_dn, out_qa
