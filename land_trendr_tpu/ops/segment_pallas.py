"""Pallas TPU kernel for the LandTrendr heavy middle (stages 1–4a).

Why this exists (measured, TPU_KERNEL_DIAG_r04.md): after the round-4
one-hot rewrite the XLA kernel is *instruction-bound* at ~3.4M px/s — and
its ceiling is set by layout, not math.  In the ``(px, NY)`` layout every
vector register carries NY=40 useful lanes out of 128 (3.2× instruction
inflation), and the stage boundaries (while-loop carries, reductions)
force HBM round trips between fused groups.  This kernel flips the block
layout to ``(NY, BLK)`` — years on sublanes (40 = 5 exact f32 sublane
tiles, zero padding), pixels on lanes — and keeps each block VMEM-resident
across ALL stages, so the whole per-pixel pipeline costs one HBM read and
one write.  A despike-only prototype measured 24.1M px/s against the XLA
stage's 3.8M on the same chip with bit-identical output.

Division of labour
------------------
Since round 5 the ENTIRE pipeline is fused: despike, vertex search, the
model family, F-stat scoring (fixed-trip Lentz with the shared
:func:`segment._lgamma_fixed` — ``lax.lgamma``/``betainc`` have no Mosaic
lowering), model selection, the chosen-model refit, and full output
assembly all run inside the one ``(NY, BLK)`` kernel, so the
``(PX, NM, NY)`` family intermediates never touch HBM and the second XLA
program the round-4 split needed (``_select_and_assemble`` over a
round-tripped family batch — ~35% of end-to-end step time on chip)
disappears.  The f64 interpret path scores with the exact
``jax.scipy.special.betainc`` (:func:`segment._f_stat_p`), keeping the
oracle bit-parity contract; the f32 paths (compiled and interpret) score
with the same :func:`segment._f_stat_p_and_logp` the XLA kernel uses, so
XLA-vs-Pallas f32 identity is structural.
:func:`family_stats_pallas` still exposes the unfused stage-1–4a kernel
for tests and stage probes.

Semantics
---------
Decision-for-decision the same pipeline as :mod:`.segment` (which is the
parity-tested re-expression of the oracle).  Dynamic per-pixel reads use
the same two gather-free forms as the XLA kernel, re-expressed in the
year-major layout:

* nearest/previous-valid and vertex-cache reads → log-doubling
  forward/backward fills along the sublane (year) axis;
* vertex-slot reads (``t[vpos[k]]``) → rank-keyed masked reductions,
  where the rank is an exact int32 prefix sum of the vertex mask.

Fill/rank reads are *selected* elements (never arithmetic combinations),
and every arithmetic expression replicates the slot-space kernel's
operation order, so float results match the XLA kernel bit-for-bit on the
same platform up to reduction-order-neutral sums (verified by the parity
suites; the despike prototype matched exactly).  Mosaic portability notes:
boolean concatenate hits an ``i1`` vreg-cast bug in the tunnel's Mosaic,
so fill carries are f32 0/1; 1-D iota is illegal on TPU, so all index
vectors are ``broadcasted_iota``; argmax/argmin tie-breaks are expressed
as min-index-over-equal-to-extremum, which reproduces the oracle's
first-index rule in year order (== rank order, since vertex positions are
sorted).

Float64: Mosaic has no f64, so the compiled kernel is f32-only.  The
``interpret=True`` path executes the same trace with stock JAX ops on CPU
— dtype-generic, used by the f64 oracle-parity tests in
``tests/test_pallas.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.ops.segment import (
    SegOutputs,
    _f_stat_p,
    _f_stat_p_and_logp,
    _lentz_iters,
)

__all__ = [
    "jax_segment_pixels_pallas",
    "jax_segment_pixels_pallas_chunked",
    "family_stats_pallas",
]


def _shift(a: jnp.ndarray, sh: int, *, reverse: bool) -> jnp.ndarray:
    """Shift along the year (sublane) axis by a static amount, zero-filling."""
    if reverse:
        return jnp.concatenate([a[sh:], jnp.zeros_like(a[:sh])], axis=0)
    return jnp.concatenate([jnp.zeros_like(a[:sh]), a[:-sh]], axis=0)


def _fill(vals, valid_f, *, exclusive: bool, reverse: bool):
    """``(filled, has_f)`` nearest-valid fill along years; f32 0/1 carries."""
    ny = vals.shape[0]
    zero = jnp.zeros((), vals.dtype)
    v = jnp.where(valid_f > 0, vals, zero)
    has = valid_f
    if exclusive:
        v, has = _shift(v, 1, reverse=reverse), _shift(has, 1, reverse=reverse)
    sh = 1
    while sh < ny:
        hb = has > 0
        v = jnp.where(hb, v, _shift(v, sh, reverse=reverse))
        has = jnp.maximum(has, _shift(has, sh, reverse=reverse))
        sh *= 2
    return v, has


def _fill2(vals_a, vals_b, valid_f, *, exclusive: bool, reverse: bool):
    """Two fills sharing one has-chain (same valid mask)."""
    ny = vals_a.shape[0]
    zero_a = jnp.zeros((), vals_a.dtype)
    zero_b = jnp.zeros((), vals_b.dtype)
    va = jnp.where(valid_f > 0, vals_a, zero_a)
    vb = jnp.where(valid_f > 0, vals_b, zero_b)
    has = valid_f
    if exclusive:
        va = _shift(va, 1, reverse=reverse)
        vb = _shift(vb, 1, reverse=reverse)
        has = _shift(has, 1, reverse=reverse)
    sh = 1
    while sh < ny:
        hb = has > 0
        va = jnp.where(hb, va, _shift(va, sh, reverse=reverse))
        vb = jnp.where(hb, vb, _shift(vb, sh, reverse=reverse))
        has = jnp.maximum(has, _shift(has, sh, reverse=reverse))
        sh *= 2
    return va, vb, has


def _prefix_sum_incl(a_i32: jnp.ndarray) -> jnp.ndarray:
    """Inclusive int32 prefix sum along years (log-shift adds — exact)."""
    ny = a_i32.shape[0]
    s = a_i32
    sh = 1
    while sh < ny:
        s = s + _shift(s, sh, reverse=False)
        sh *= 2
    return s


def _prefix_max_incl(a_i32: jnp.ndarray) -> jnp.ndarray:
    """Inclusive int32 prefix max along years (log-shift — exact).

    Zero-fill shifts would corrupt negative carries, so shift a biased
    non-negative copy instead.
    """
    ny = a_i32.shape[0]
    s = a_i32 + ny  # bias: values in [-1, ny) -> [ny-1, 2ny)
    sh = 1
    while sh < ny:
        s = jnp.maximum(s, _shift(s, sh, reverse=False))
        sh *= 2
    return s - ny


def _first_true_idx(b, iota, ny):
    """Smallest year index where ``b`` (bool) holds; NY when none. (1, BLK)."""
    return jnp.min(jnp.where(b, iota, ny), axis=0, keepdims=True)


def _last_true_idx(b, iota):
    """Largest year index where ``b`` holds; -1 when none. (1, BLK)."""
    return jnp.max(jnp.where(b, iota, -1), axis=0, keepdims=True)


def _pick_at(a, iota, idx):
    """Value of ``a`` at year index ``idx`` ((1, BLK)); 0 when idx == NY.

    Where-sum pick: identical to a gather up to the sign of zero (a picked
    -0.0 comes back +0.0) — same caveat as ``segment._gather_oh``.
    """
    zero = jnp.zeros((), a.dtype)
    return jnp.sum(jnp.where(iota == idx, a, zero), axis=0, keepdims=True)


def _masked_ols_ys(t, y, member_f):
    """(intercept, slope) (1, BLK) — replicates segment._masked_ols exactly."""
    dtype = t.dtype
    one = jnp.ones((), dtype)
    zero = jnp.zeros((), dtype)
    n = jnp.sum(member_f, axis=0, keepdims=True)
    n_safe = jnp.maximum(n, one)
    tm = jnp.sum(member_f * t, axis=0, keepdims=True) / n_safe
    ym = jnp.sum(member_f * y, axis=0, keepdims=True) / n_safe
    tc = (t - tm) * member_f
    stt = jnp.sum(tc * (t - tm), axis=0, keepdims=True)
    sty = jnp.sum(tc * (y - ym), axis=0, keepdims=True)
    ok = (n >= 2.0) & (stt > zero)
    slope = jnp.where(ok, sty / jnp.where(ok, stt, one), zero)
    intercept = ym - slope * tm
    return intercept, slope


def _clamp_slope_ys(slope, duration, y_range, params: LTParams):
    """Recovery-rate constraints — replicates segment._clamp_slope."""
    dtype = slope.dtype
    zero = jnp.zeros((), dtype)
    limit = -jnp.asarray(params.recovery_threshold, dtype) * y_range
    clamped = jnp.maximum(slope, limit)
    if params.prevent_one_year_recovery:
        clamped = jnp.where(duration <= 1.0, zero, clamped)
    active = (slope < zero) & (y_range > zero)
    return jnp.where(active, clamped, slope)


# Mosaic has no atan lowering; the angle cull needs one.  Degree-10-in-z²
# Chebyshev-fitted odd polynomial on [0,1] + the |x|>1 reciprocal reduction:
# measured max error 1.5e-7 (~2 ulp at atan scale; the [0,1] poly is
# 1.0e-7 and the reciprocal branch adds one rounding step) against
# np.arctan over a 2M-point grid (gated by tests/test_pallas.py).  Used ONLY in
# compiled mode — interpret mode keeps jnp.arctan so the f64 parity tests
# bit-match the oracle; compiled-mode f32 angle comparisons may flip at
# 1-2-ulp knife edges, which the f32 tolerance contract covers (measured:
# see tests/test_pallas.py and PARITY_f32_tpu.json methodology).
_ATAN_COEFS = (
    0.9999999996147207,
    -0.3333332366695538,
    0.19999595880653254,
    -0.14279048657228555,
    0.11053785942171465,
    -0.08796121057076967,
    0.0671012036450899,
    -0.04427374044156659,
    0.022203503960703006,
    -0.007166183020119105,
    0.0010844955030828492,
)


def _atan_poly(x: jnp.ndarray) -> jnp.ndarray:
    dtype = x.dtype
    one = jnp.ones((), dtype)
    ax = jnp.abs(x)
    big = ax > one
    z = jnp.where(big, one / jnp.maximum(ax, jnp.asarray(1e-30, dtype)), ax)
    u = z * z
    acc = jnp.asarray(_ATAN_COEFS[-1], dtype) + jnp.zeros_like(u)
    for c in _ATAN_COEFS[-2::-1]:
        acc = acc * u + jnp.asarray(c, dtype)
    r = z * acc
    half_pi = jnp.asarray(1.5707963267948966, dtype)
    r = jnp.where(big, half_pi - r, r)
    return jnp.where(x < 0, -r, r)


def _vertex_angle(xs_v, ys_v, xp_v, yp_v, xq_v, yq_v, interior, exact_atan: bool):
    """Angle at a vertex given its own and neighbour-vertex scaled coords.

    ONE definition serves both the full build (_angle_state_init, whole
    (NY, BLK) block) and the incremental patches (_remove_weakest_ys,
    (1, BLK) rows) — the bit-identity between them is structural.
    """
    dtype = xs_v.dtype
    one = jnp.ones((), dtype)
    big = jnp.asarray(1e30, dtype)  # > pi; replaces slot-space +inf sentinel
    dx1 = jnp.where(interior, xs_v - xp_v, one)
    dx2 = jnp.where(interior, xq_v - xs_v, one)
    s1 = (ys_v - yp_v) / dx1
    s2 = (yq_v - ys_v) / dx2
    atan = jnp.arctan if exact_atan else _atan_poly
    return jnp.where(interior, jnp.abs(atan(s2) - atan(s1)), big)


def _angle_state_init(xs, ys, vmask_f, exact_atan: bool):
    """Neighbour-fill tables + per-vertex angle table for the cull chains.

    ``(xp, yp, hasp, xq, yq, hasq, ang)`` — the scaled coords of each
    slot's previous/next VERTEX, and the angle at every vertex slot (BIG
    sentinel elsewhere).  A removal changes this state at O(1) slots per
    pixel, so the 8-deep remove chain (angle cull + model family) carries
    it across calls instead of re-filling and re-atan-ing the whole block
    each time (the removes were ~22% of kernel time — TPU_KERNEL_DIAG §7).
    """
    xp, yp, hasp = _fill2(xs, ys, vmask_f, exclusive=True, reverse=False)
    xq, yq, hasq = _fill2(xs, ys, vmask_f, exclusive=True, reverse=True)
    interior = (vmask_f > 0) & (hasp > 0) & (hasq > 0)
    ang = _vertex_angle(xs, ys, xp, yp, xq, yq, interior, exact_atan)
    return xp, yp, hasp, xq, yq, hasq, ang


def _remove_weakest_ys(
    vmask_f, state, xs, ys, iota, keep_above: int, exact_atan: bool
):
    """Drop the min-angle interior vertex while count > keep_above.

    Returns ``(vmask_new, state_new)``.  Incremental form: removing the
    interior vertex at ``pos`` changes the forward tables exactly on
    ``(pos, next_vertex]`` (their previous vertex was ``pos``), the
    backward tables exactly on ``[prev_vertex, pos)``, and the angle table
    only at ``prev_vertex``/``next_vertex`` (recomputed from the updated
    tables with the identical formula — bit-identical to a full rebuild,
    gated by the interpret bit-exact suite) plus the BIG sentinel at
    ``pos``.  ``prev/next_vertex`` exist whenever a removal fires: the
    argmin is masked to interior vertices.
    """
    dtype = xs.dtype
    ny = xs.shape[0]
    big = jnp.asarray(1e30, dtype)
    xp, yp, hasp, xq, yq, hasq, ang = state
    mn = jnp.min(ang, axis=0, keepdims=True)
    pos = _first_true_idx(ang == mn, iota, ny)
    n_verts = jnp.sum(vmask_f, axis=0, keepdims=True)
    do = n_verts > float(keep_above)
    vb = vmask_f > 0
    prv = _last_true_idx(vb & (iota < pos), iota)
    nxt = _first_true_idx(vb & (iota > pos), iota, ny)
    vmask_new = jnp.where(do & (iota == pos), jnp.zeros((), dtype), vmask_f)

    # table patches (picks taken from the PRE-update tables; pos itself is
    # outside both ranges, so order is immaterial)
    rngf = do & (iota > pos) & (iota <= nxt)
    rngb = do & (iota >= prv) & (iota < pos)
    xp_p = _pick_at(xp, iota, pos)
    yp_p = _pick_at(yp, iota, pos)
    hp_p = _pick_at(hasp, iota, pos)
    xq_p = _pick_at(xq, iota, pos)
    yq_p = _pick_at(yq, iota, pos)
    hq_p = _pick_at(hasq, iota, pos)
    xp = jnp.where(rngf, xp_p, xp)
    yp = jnp.where(rngf, yp_p, yp)
    hasp = jnp.where(rngf, hp_p, hasp)
    xq = jnp.where(rngb, xq_p, xq)
    yq = jnp.where(rngb, yq_p, yq)
    hasq = jnp.where(rngb, hq_p, hasq)

    def ang_at(j):
        # angle at vertex slot j from the UPDATED tables — the shared
        # _vertex_angle formula applied to (1, BLK) rows
        interior_j = (_pick_at(hasp, iota, j) > 0) & (_pick_at(hasq, iota, j) > 0)
        return _vertex_angle(
            _pick_at(xs, iota, j),
            _pick_at(ys, iota, j),
            _pick_at(xp, iota, j),
            _pick_at(yp, iota, j),
            _pick_at(xq, iota, j),
            _pick_at(yq, iota, j),
            interior_j,
            exact_atan,
        )

    ang = jnp.where(do & (iota == pos), big, ang)
    ang = jnp.where(do & (iota == prv), ang_at(prv), ang)
    ang = jnp.where(do & (iota == nxt), ang_at(nxt), ang)
    return vmask_new, (xp, yp, hasp, xq, yq, hasq, ang)


def _pick_rank(a, rank, vb, key):
    """Value of ``a`` at the vertex whose rank equals ``key`` ((1, BLK) i32).

    Rank-keyed masked reduction — the dynamic-key analogue of the static
    ``rank == k`` picks in :func:`_fit_model_ys`; 0 when no vertex has that
    rank.  Bit-exact: a selected element, never an arithmetic combination.
    """
    zero = jnp.zeros((), a.dtype)
    return jnp.sum(jnp.where(vb & (rank == key), a, zero), axis=0, keepdims=True)


def _fit_model_ys(t, y, m_f, vmask_f, y_range, iota, params: LTParams):
    """One model's anchored fit + p2p fallback; ``(sse, fitted) `` (1, BLK)/(NY, BLK).

    Year-major re-expression of segment._fit_model with identical
    arithmetic per decision; vertex-slot reads become rank-keyed masked
    reductions and seg-of-year reads become fills.  ``fitted`` is the
    post-p2p-choice trajectory (``segment._fit_model``'s first return);
    the family loop discards it (one dead select per model), the fused
    tail's chosen-model refit consumes it.
    """
    dtype = t.dtype
    ny = t.shape[0]
    nv = params.max_vertices
    one = jnp.ones((), dtype)
    zero = jnp.zeros((), dtype)
    vb = vmask_f > 0
    m = m_f > 0

    n_verts = jnp.sum(vmask_f, axis=0, keepdims=True)
    cincl = _prefix_sum_incl(vmask_f.astype(jnp.int32))  # vertices at/before i
    rank = cincl - 1                                     # rank of a vertex AT i
    cexcl = cincl - vb.astype(jnp.int32)                 # vertices strictly before i

    # vertex-slot values: tv[k] == t[vpos[k]] via rank-keyed masked sums.
    # Slot POSITIONS are never materialised: segment membership is a rank
    # compare — a year belongs to segment k (years in (a_k, a_{k+1}])
    # exactly when cexcl == k+1, and to the closed [a_0, a_1] span when
    # cincl >= 1 & cexcl <= 1 — identical sets to the position compares
    # they replace, without the per-slot first-index reductions.
    tv = []
    for k in range(nv):
        sel = vb & (rank == k)
        tv.append(jnp.sum(jnp.where(sel, t, zero), axis=0, keepdims=True))

    # --- segment 0: OLS over closed [v0, v1] ---
    member0 = (cincl >= 1) & (cexcl <= 1) & m
    m0 = member0.astype(dtype)
    c0, c1 = _masked_ols_ys(t, y, m0)
    dur0 = tv[1] - tv[0]
    c1c = _clamp_slope_ys(c1, dur0, y_range, params)
    n0 = jnp.maximum(jnp.sum(m0, axis=0, keepdims=True), one)
    c0 = jnp.sum(m0 * y, axis=0, keepdims=True) / n0 - c1c * (
        jnp.sum(m0 * t, axis=0, keepdims=True) / n0
    )
    fitted = jnp.where(member0, c0 + c1c * t, zero)
    anchor_t = tv[1]
    anchor_y = c0 + c1c * anchor_t

    # --- segments 1..: slope-only regression through the anchor ---
    for k in range(1, nv - 1):
        active = (k + 1.0) < n_verts
        member = (cexcl == k + 1) & m & active
        mf = member.astype(dtype)
        dt = (t - anchor_t) * mf
        denom = jnp.sum(dt * dt, axis=0, keepdims=True)
        slope = jnp.where(
            denom > zero,
            jnp.sum(dt * (y - anchor_y), axis=0, keepdims=True)
            / jnp.where(denom > zero, denom, one),
            zero,
        )
        slope = _clamp_slope_ys(slope, tv[k + 1] - anchor_t, y_range, params)
        fitted = jnp.where(member, anchor_y + slope * (t - anchor_t), fitted)
        new_anchor_y = anchor_y + slope * (tv[k + 1] - anchor_t)
        anchor_t = jnp.where(active, tv[k + 1], anchor_t)
        anchor_y = jnp.where(active, new_anchor_y, anchor_y)

    # --- point-to-point fallback ---
    # per-year segment quantities: value at year i = value of the segment
    # whose START vertex is the largest vertex <= i, where the last vertex
    # belongs to the segment *ending* at it (slot-space min(rank, n-2))
    tnx, ynx, hasnx = _fill2(t, y, vmask_f, exclusive=True, reverse=True)
    dy_f = ynx - y
    dur_f = tnx - t
    viol = (dy_f < zero) & (y_range > zero) & (dur_f > zero)
    if params.prevent_one_year_recovery:
        fast = dur_f <= 1.0
    else:
        fast = jnp.zeros_like(viol)
    eps_rate = jnp.asarray(1e-12, dtype)  # segment._EPS_RATE
    viol = viol & (
        fast
        | (
            (-dy_f) / jnp.where(dur_f > zero, dur_f, one)
            > jnp.asarray(params.recovery_threshold, dtype) * y_range + eps_rate
        )
    )
    startv = vb & (hasnx > 0)  # vertices that start a segment
    p2p_ok = ~jnp.any(viol & startv, axis=0, keepdims=True)
    rate_f = jnp.where(dur_f > zero, dy_f / jnp.where(dur_f > zero, dur_f, one), zero)

    a0_pos = _first_true_idx(vb, iota, ny)
    last_pos = _last_true_idx(vb, iota)
    vmask_nl = jnp.where(iota == last_pos, zero, vmask_f)  # drop last vertex
    t_a, y_a, has_a = _fill2(t, y, vmask_nl, exclusive=False, reverse=False)
    rate_of, _ = _fill(rate_f, vmask_nl, exclusive=False, reverse=False)
    member_y = (iota >= a0_pos) & (iota <= last_pos) & m & (has_a > 0)
    p2p0 = jnp.where((iota == a0_pos) & m, y, zero)
    p2p = jnp.where(member_y, y_a + rate_of * (t - t_a), p2p0)

    span = m & (iota >= a0_pos) & (iota <= last_pos)
    sse_reg = jnp.sum(jnp.where(span, (y - fitted) ** 2, zero), axis=0, keepdims=True)
    sse_p2p = jnp.sum(jnp.where(span, (y - p2p) ** 2, zero), axis=0, keepdims=True)
    use_p2p = p2p_ok & (sse_p2p < sse_reg)
    sse = jnp.where(use_p2p, sse_p2p, sse_reg)
    return sse, jnp.where(use_p2p, p2p, fitted)


def _run_stages(t, raw, m_f, ny: int, blk: int, params: LTParams, exact_atan: bool):
    """Stages 1–4a on one ``(NY, BLK)`` block of values.

    Pure function of block VALUES (no refs) shared by both kernel builders
    (:func:`_make_family_kernel` for the unfused stats path,
    :func:`_make_fused_kernel` for the production fused path).  Returns
    ``(y, vmask_list, sse_list, fitted_list, aux)`` where ``y`` is the
    despiked series, the lists hold the NM family members' vertex masks
    (f32 0/1), fit SSEs, and fitted trajectories in pruning order, and
    ``aux`` carries the shared per-block
    scalars the fused tail reuses (same expressions as the XLA tail, so
    reuse is bit-exact).
    """
    nv, nc, nm = params.max_vertices, params.max_candidates, params.max_segments
    dtype = raw.dtype
    one = jnp.ones((), dtype)
    zero = jnp.zeros((), dtype)
    m = m_f > 0
    y = jnp.where(m, raw, zero)
    iota = lax.broadcasted_iota(jnp.int32, (ny, blk), 0)
    n_valid = jnp.sum(m_f, axis=0, keepdims=True)
    # ---- Stage 1: despike (early-exit per BLOCK, not per batch) ----
    if params.spike_threshold < 1.0:
        tp, hasp = _fill(t, m_f, exclusive=True, reverse=False)
        tq, hasq = _fill(t, m_f, exclusive=True, reverse=True)
        interior = m & (hasp > 0) & (hasq > 0)
        dtp = t - tp
        denom = jnp.where(interior, tq - tp, one)
        # the neighbour VALUE tables are carried incrementally: each
        # iteration modifies y at exactly one (valid, interior) slot i
        # per pixel, which changes yp only at the nearest valid slot
        # after i and yq only at the nearest valid slot before i — a
        # single selected write each, replacing two full fills per
        # trip (the fills are ~60% of the despike body's ops).  The
        # carried tables equal the per-trip fills at every slot the
        # body can read (interior slots; garbage between valid slots
        # matches the fills' don't-care regions), so results are
        # bit-identical — gated by tests/test_pallas.py's interpret
        # bit-exact suite.
        yp0, _ = _fill(y, m_f, exclusive=True, reverse=False)
        yq0, _ = _fill(y, m_f, exclusive=True, reverse=True)

        def body(carry):
            it, y, yp, yq, _ = carry
            itp = yp + (yq - yp) * dtp / denom
            dev = jnp.abs(y - itp)
            crossing = jnp.abs(yq - yp)
            prop = jnp.where(
                dev > zero,
                jnp.maximum(zero, one - crossing / jnp.where(dev > zero, dev, one)),
                zero,
            )
            prop = jnp.where(interior, prop, -one)
            mx = jnp.max(prop, axis=0, keepdims=True)
            i_first = _first_true_idx(prop == mx, iota, ny)
            do = (mx > params.spike_threshold) & (it < n_valid)
            oh = iota == i_first
            delta = jnp.where(
                do, (_pick_at(itp, iota, i_first) - _pick_at(y, iota, i_first)) * mx, zero
            )
            y_new = y + jnp.where(oh, delta, zero)
            y_i_new = _pick_at(y_new, iota, i_first)
            # when do holds, i is a valid interior slot, so these ARE
            # the only slots whose nearest-valid neighbour is i
            j_next = _first_true_idx(m & (iota > i_first), iota, ny)
            j_prev = _last_true_idx(m & (iota < i_first), iota)
            yp = jnp.where(do & (iota == j_next), y_i_new, yp)
            yq = jnp.where(do & (iota == j_prev), y_i_new, yq)
            return it + one, y_new, yp, yq, jnp.any(do)

        def cond(carry):
            it, _, _, _, cont = carry
            return cont & (it[0, 0] < ny)

        _, y, _, _, _ = lax.while_loop(
            cond,
            body,
            (jnp.zeros((1, blk), dtype), y, yp0, yq0, jnp.asarray(True)),
        )

    # ---- shared scalars ----
    big = jnp.asarray(jnp.finfo(dtype).max, dtype)
    y_lo = jnp.min(jnp.where(m, y, big), axis=0, keepdims=True)
    y_hi = jnp.max(jnp.where(m, y, -big), axis=0, keepdims=True)
    y_range = jnp.maximum(y_hi - y_lo, zero)
    first_v = _first_true_idx(m, iota, ny)
    last_v = _last_true_idx(m, iota)
    t_lo = _pick_at(t, iota, first_v)
    t_hi = _pick_at(t, iota, last_v)

    # ---- Stage 2: candidate vertices (max-deviation insertion) ----
    # The per-year segment-coefficient table and seg_start map are
    # CARRIED across insertion trips: inserting a vertex at i into
    # [lo, hi] changes them exactly on [lo, i) (refit left half) and
    # [i, hi) (right half) — range selects of freshly fit values,
    # bit-identical to the forward fills over a slot cache they
    # replace.  first/last vertex are loop-invariant (insertions are
    # strictly interior), so the per-trip first/last reductions and
    # the seg_start prefix-max rebuild go away too.
    vmask_f = jnp.where(m & ((iota == first_v) | (iota == last_v)), one, zero)
    lo0 = _first_true_idx(vmask_f > 0, iota, ny)
    member_i = (iota >= lo0) & (iota <= _last_true_idx(vmask_f > 0, iota)) & m
    c0i, c1i = _masked_ols_ys(t, y, member_i.astype(dtype))
    c0_at = c0i + jnp.zeros((ny, blk), dtype)
    c1_at = c1i + jnp.zeros((ny, blk), dtype)
    seg_start = jnp.clip(
        _prefix_max_incl(jnp.where(vmask_f > 0, iota, -1)), 0, ny - 1
    )

    for _ in range(nc - 2):
        dev = jnp.abs(y - (c0_at + c1_at * t))
        eligible = m & ~(vmask_f > 0) & (iota > first_v) & (iota < last_v)
        dev = jnp.where(eligible, dev, -one)
        mx = jnp.max(dev, axis=0, keepdims=True)
        i_first = _first_true_idx(dev == mx, iota, ny)
        do = mx >= zero
        lo = jnp.sum(
            jnp.where(iota == i_first, seg_start, 0), axis=0, keepdims=True
        )
        hi_raw = jnp.min(
            jnp.where((vmask_f > 0) & (iota > i_first), iota, ny),
            axis=0,
            keepdims=True,
        )
        hi = jnp.clip(hi_raw, 0, ny - 1)
        mem_a = (iota >= lo) & (iota <= i_first) & m
        mem_b = (iota >= i_first) & (iota <= hi) & m
        c0a, c1a = _masked_ols_ys(t, y, mem_a.astype(dtype))
        c0b, c1b = _masked_ols_ys(t, y, mem_b.astype(dtype))
        # right half wins the j == i slot, mirroring the slot cache's
        # .at[lo].set(·).at[i].set(·) overwrite order
        rng_a = do & (iota >= lo) & (iota < i_first)
        rng_b = do & (iota >= i_first) & (iota < hi_raw)
        c0_at = jnp.where(rng_b, c0b, jnp.where(rng_a, c0a, c0_at))
        c1_at = jnp.where(rng_b, c1b, jnp.where(rng_a, c1a, c1_at))
        seg_start = jnp.where(rng_b, i_first, seg_start)
        vmask_f = jnp.where(do & (iota == i_first), one, vmask_f)

    # ---- Stage 2b + 4a: the remove chain carries one angle state ----
    # (scaled coordinates replicate the slot-space scaling arithmetic)
    t_rng = jnp.where(t_hi > t_lo, t_hi - t_lo, one)
    y_rng_s = jnp.where(y_hi > y_lo, y_hi - y_lo, one)
    xsc = (t - t_lo) / t_rng
    ysc = (y - y_lo) / y_rng_s
    state = _angle_state_init(xsc, ysc, vmask_f, exact_atan)
    for _ in range(params.vertex_count_overshoot):
        vmask_f, state = _remove_weakest_ys(
            vmask_f, state, xsc, ysc, iota, nv, exact_atan
        )

    # ---- Stage 4a: model family (fit SSE, then prune weakest) ----
    # fitted trajectories are KEPT per member (≈ NM·NY·BLK·4 B ≈ 1 MB of
    # VMEM at the default block): the fused tail then *selects* the chosen
    # model's fit instead of refitting it — measured 2.6 ms/step saved at
    # 262144 px (the refit was the single largest tail cost), and bit-exact
    # because _fit_model_ys is deterministic in its inputs.
    vmask_list, sse_list, fitted_list = [], [], []
    for k in range(nm):
        vmask_list.append(vmask_f)
        sse, fitted_k = _fit_model_ys(t, y, m_f, vmask_f, y_range, iota, params)
        sse_list.append(sse)
        fitted_list.append(fitted_k)
        if k + 1 < nm:
            vmask_f, state = _remove_weakest_ys(
                vmask_f, state, xsc, ysc, iota, 2, exact_atan
            )

    aux = dict(
        m=m, iota=iota, n_valid=n_valid, y_lo=y_lo, y_hi=y_hi,
        y_range=y_range, first_v=first_v, last_v=last_v, t_lo=t_lo, t_hi=t_hi,
    )
    return y, vmask_list, sse_list, fitted_list, aux


def _make_family_kernel(ny: int, blk: int, params: LTParams, exact_atan: bool):
    """Unfused kernel body (stages 1–4a): despiked + family vmasks/SSEs.

    Kept for :func:`family_stats_pallas` (tests, stage probes); production
    runs use :func:`_make_fused_kernel`.
    """

    def kernel(t_ref, v_ref, m_ref, desp_ref, vm_ref, sse_ref):
        dtype = v_ref.dtype
        t = t_ref[:, 0:1] + jnp.zeros((ny, blk), dtype)  # broadcast year axis
        y, vmask_list, sse_list, _, _ = _run_stages(
            t, v_ref[:], m_ref[:], ny, blk, params, exact_atan
        )
        desp_ref[:] = y
        for k in range(params.max_segments):
            vm_ref[k] = vmask_list[k]
            sse_ref[k] = sse_list[k][0]

    return kernel


def _fused_tail(t, raw, y, vmask_list, sse_list, fitted_list, aux,
                ny: int, blk: int, params: LTParams):
    """Scoring → selection → chosen-model refit → output assembly, year-major.

    Line-for-line re-expression of ``segment._select_and_assemble`` on
    ``(NY, BLK)`` blocks: per-pixel scalars become ``(1, BLK)`` rows,
    vertex-slot reads become rank-keyed masked reductions
    (:func:`_pick_rank`), and ``np.interp`` through the chosen vertices
    becomes fills + the slot-index case analysis below.  Float arithmetic
    replicates the slot-space tail expression for expression, so f64
    interpret output is bit-identical to the XLA kernel (gated by
    ``tests/test_pallas.py``) and compiled f32 shares
    ``segment._f_stat_p_and_logp`` — the scoring path itself — with the
    XLA kernel.  Scoring: f64 uses the exact ``betainc``
    (``segment._f_stat_p``, interpret-only — no Mosaic lowering); f32
    uses the fixed-trip Lentz with the shared ``_lgamma_fixed``.
    """
    dtype = t.dtype
    nv, nm = params.max_vertices, params.max_segments
    exact_mode = dtype == jnp.float64
    one = jnp.ones((), dtype)
    zero = jnp.zeros((), dtype)
    m = aux["m"]
    iota = aux["iota"]
    n_valid = aux["n_valid"]
    y_range = aux["y_range"]
    last_v = aux["last_v"]
    t_hi = aux["t_hi"]

    enough = n_valid >= params.min_observations_needed
    n_safe = jnp.maximum(n_valid, one)
    mean0 = jnp.sum(jnp.where(m, y, zero), axis=0, keepdims=True) / n_safe
    ss0 = jnp.sum(jnp.where(m, (y - mean0) ** 2, zero), axis=0, keepdims=True)

    # --- scores per family member (selection: linear p in f64, log p in f32) ---
    iters = _lentz_iters(ny)
    ms_list = [jnp.sum(vm, axis=0, keepdims=True) - one for vm in vmask_list]
    if exact_mode:
        # XLA CPU's betainc expansion is not bit-stable across layouts (its
        # last-ulp rounding tracks the minormost-dim extent), so the exact
        # path evaluates at the SAME (pixels, NM) layout the vmapped XLA
        # tail uses — bit-identity with the oracle-parity anchor is layout-
        # borrowed, not assumed.  Interpret-only (f64 never compiles), so
        # the transposes never reach Mosaic.
        sse_T = jnp.concatenate(sse_list, axis=0).T          # (BLK, NM)
        ms_T = jnp.concatenate(ms_list, axis=0).T
        p_T = _f_stat_p(ss0[0][:, None], sse_T, n_valid[0][:, None], ms_T)
        ps_list = [p_T[:, k][None, :] for k in range(nm)]
        score_list = ps_list
    else:
        # sublane-pack the family axis: (1, BLK) per-pixel rows use 1/8 of
        # every f32 vreg, so running the div/log-heavy Lentz+lgamma scorer
        # once on an (NM, BLK) stack costs ~NM× fewer vector ops than NM
        # row evaluations — same expression per element, so identical bits
        sse_mat = jnp.concatenate(sse_list, axis=0)   # (NM, BLK)
        ms_mat = jnp.concatenate(ms_list, axis=0)
        p_mat, s_mat = _f_stat_p_and_logp(
            ss0, sse_mat, n_valid, ms_mat, iters=iters
        )
        ps_list = [p_mat[k:k + 1] for k in range(nm)]
        score_list = [s_mat[k:k + 1] for k in range(nm)]
    best = score_list[0]
    for k in range(1, nm):
        best = jnp.minimum(best, score_list[k])
    if exact_mode:
        thresh = best / params.best_model_proportion
    else:
        thresh = best - jnp.log(jnp.asarray(params.best_model_proportion, dtype))
    # first (= most segments) qualifying model; best always qualifies itself
    chosen = jnp.full((1, blk), nm - 1, jnp.int32)
    for k in range(nm - 1, -1, -1):
        chosen = jnp.where(score_list[k] <= thresh, k, chosen)
    # chosen-model quantities are SELECTS over the family loop's carried
    # results — _fit_model_ys is deterministic, so selecting its stored
    # (sse, fitted) is bit-identical to the XLA tail's refit of the chosen
    # vertex set, without re-running a seventh fit
    vmask_c = vmask_list[0]
    p_c = ps_list[0]
    sse_c = sse_list[0]
    fitted_c = fitted_list[0]
    for k in range(1, nm):
        sel = chosen == k
        vmask_c = jnp.where(sel, vmask_list[k], vmask_c)
        p_c = jnp.where(sel, ps_list[k], p_c)
        sse_c = jnp.where(sel, sse_list[k], sse_c)
        fitted_c = jnp.where(sel, fitted_list[k], fitted_c)

    model_valid = enough & (y_range > zero) & (p_c <= params.p_val_threshold)
    mv = model_valid

    # --- flat no-fit model statistics (raw values when data insufficient) ---
    has_any = n_valid > zero
    mean_desp = jnp.where(
        has_any, jnp.sum(jnp.where(m, y, zero), axis=0, keepdims=True) / n_safe, zero
    )
    mean_raw = jnp.where(
        has_any, jnp.sum(jnp.where(m, raw, zero), axis=0, keepdims=True) / n_safe, zero
    )
    mean = jnp.where(enough, mean_desp, mean_raw)
    flat_src = jnp.where(enough, y, raw)

    # --- vertex-slot outputs: rank-keyed picks over the chosen mask ---
    vb_c = vmask_c > 0
    cincl_c = _prefix_sum_incl(vmask_c.astype(jnp.int32))
    rank_c = cincl_c - 1
    k_live = jnp.sum(vmask_c.astype(jnp.int32), axis=0, keepdims=True)
    pos_l, tv_l, yv_l, fv_l = [], [], [], []
    for j in range(nv):
        sel = vb_c & (rank_c == j)
        pos_l.append(jnp.sum(jnp.where(sel, iota, 0), axis=0, keepdims=True))
        tv_l.append(jnp.sum(jnp.where(sel, t, zero), axis=0, keepdims=True))
        yv_l.append(jnp.sum(jnp.where(sel, y, zero), axis=0, keepdims=True))
        fv_l.append(jnp.sum(jnp.where(sel, fitted_c, zero), axis=0, keepdims=True))
    vidx_rows, vyear_rows, vsrc_rows, vfit_rows = [], [], [], []
    for j in range(nv):
        live_j = (j < k_live) & mv
        vidx_rows.append(jnp.where(live_j, pos_l[j], -1))
        vyear_rows.append(jnp.where(live_j, tv_l[j], zero))
        vsrc_rows.append(jnp.where(live_j, yv_l[j], zero))
        vfit_rows.append(jnp.where(live_j, fv_l[j], zero))
    smag_rows, sdur_rows, srate_rows = [], [], []
    for j in range(nm):
        seg_live = (j < k_live - 1) & mv
        mag = jnp.where(seg_live, fv_l[j + 1] - fv_l[j], zero)
        dur = jnp.where(seg_live, tv_l[j + 1] - tv_l[j], zero)
        rate = jnp.where(
            seg_live & (dur > zero), mag / jnp.where(dur > zero, dur, one), zero
        )
        smag_rows.append(mag)
        sdur_rows.append(dur)
        srate_rows.append(rate)

    # --- fitted_full: np.interp replica through the chosen vertices ---
    # segment._interp_through_vertices pads dead slots with (pad_t = t_hi,
    # last live fit) and reads xp/fp at i = clip(count(xp <= t), 1, NV-1).
    # Year-major case analysis of that slot index (equalities verified
    # against the slot form by the f64 bit-exact suite):
    #   cincl == 0 (before the first vertex): the computed f is discarded
    #     by the t < xp[0] clamp, so any finite stand-in works — use the
    #     rank-0 vertex (dx = 0 ⇒ f = fp[0], the clamp value itself);
    #   0 < cincl, iota < last_v: xp[i-1] = previous vertex at-or-before,
    #     xp[i] = next vertex strictly after (both exist);
    #   iota >= last_v: count saturates ⇒ i = NV-1.  With k < NV slots
    #     live, xp[NV-2] and xp[NV-1] are both pads (or the last vertex)
    #     at t_hi ⇒ dx = 0 ⇒ f = last fit.  With ALL NV slots live,
    #     xp[NV-2] is the PENULTIMATE vertex: delta == dx exactly, so
    #     f = penult_fit + 1.0 * (last_fit - penult_fit) — replicated, not
    #     shortcut to last_fit (a + (b-a) != b in float).
    tp_v, fp_v, _ = _fill2(t, fitted_c, vmask_c, exclusive=False, reverse=False)
    tn_v, fn_v, _ = _fill2(t, fitted_c, vmask_c, exclusive=True, reverse=True)
    first_t, first_f = tv_l[0], fv_l[0]
    last_f = _pick_rank(fitted_c, rank_c, vb_c, k_live - 1)
    penult_t = _pick_rank(t, rank_c, vb_c, k_live - 2)
    penult_f = _pick_rank(fitted_c, rank_c, vb_c, k_live - 2)
    full = k_live == nv
    tzone = iota >= last_v
    below = cincl_c == 0
    xp_im1 = jnp.where(below, first_t, tp_v)
    fp_im1 = jnp.where(below, first_f, fp_v)
    xp_im1 = jnp.where(tzone, jnp.where(full, penult_t, t_hi), xp_im1)
    fp_im1 = jnp.where(tzone, jnp.where(full, penult_f, last_f), fp_im1)
    xp_i = jnp.where(tzone, t_hi, tn_v)
    fp_i = jnp.where(tzone, last_f, fn_v)
    df_i = fp_i - fp_im1
    dx = xp_i - xp_im1
    delta = t - xp_im1
    eps_g = jnp.asarray(np.spacing(np.finfo(np.dtype(dtype)).eps), dtype)
    dx0 = jnp.abs(dx) <= eps_g
    f = jnp.where(dx0, fp_im1, fp_im1 + (delta / jnp.where(dx0, one, dx)) * df_i)
    f = jnp.where(t < first_t, first_f, f)
    f = jnp.where(t > t_hi, last_f, f)
    fitted_full = jnp.where(mv, f, mean + jnp.zeros((ny, blk), dtype))

    # --- scalars + despiked output ---
    rmse_fit = jnp.sqrt(sse_c / n_safe)
    rmse_flat = jnp.sqrt(
        jnp.sum(jnp.where(m, (flat_src - mean) ** 2, zero), axis=0, keepdims=True)
        / n_safe
    )
    rmse = jnp.where(mv, rmse_fit, jnp.where(has_any, rmse_flat, zero))
    p_of_f = jnp.where(mv, p_c, one)
    n_vertices = jnp.where(mv, k_live, 0)
    despiked_fit = jnp.where(m, y, raw)
    despiked_flat = jnp.where(m, flat_src, mean)
    despiked = jnp.where(mv, despiked_fit, despiked_flat)

    return dict(
        n_vertices=n_vertices,
        vertex_indices=jnp.concatenate(vidx_rows, axis=0),
        vertex_years=jnp.concatenate(vyear_rows, axis=0),
        vertex_src_vals=jnp.concatenate(vsrc_rows, axis=0),
        vertex_fit_vals=jnp.concatenate(vfit_rows, axis=0),
        seg_magnitude=jnp.concatenate(smag_rows, axis=0),
        seg_duration=jnp.concatenate(sdur_rows, axis=0),
        seg_rate=jnp.concatenate(srate_rows, axis=0),
        rmse=rmse,
        p_of_f=p_of_f,
        model_valid=mv,
        fitted=fitted_full,
        despiked=despiked,
    )


def _make_fused_kernel(ny: int, blk: int, params: LTParams, exact_atan: bool):
    """Fused kernel body: stages 1–4a + scoring/selection/assembly in VMEM.

    The production path — one HBM read and one write per block for the
    whole pipeline; the family's ``(NM, NY, BLK)`` vertex masks live only
    as in-kernel values (register/VMEM), never as an HBM tensor.
    """

    def kernel(
        t_ref, v_ref, m_ref,
        desp_ref, fit_ref, nvert_ref, vidx_ref, vyear_ref, vsrc_ref, vfit_ref,
        smag_ref, sdur_ref, srate_ref, rmse_ref, pof_ref, mv_ref,
    ):
        dtype = v_ref.dtype
        t = t_ref[:, 0:1] + jnp.zeros((ny, blk), dtype)  # broadcast year axis
        raw = v_ref[:]
        m_f = m_ref[:]
        y, vmask_list, sse_list, fitted_list, aux = _run_stages(
            t, raw, m_f, ny, blk, params, exact_atan
        )
        outs = _fused_tail(
            t, raw, y, vmask_list, sse_list, fitted_list, aux, ny, blk, params
        )
        desp_ref[:] = outs["despiked"]
        fit_ref[:] = outs["fitted"]
        nvert_ref[:] = outs["n_vertices"]
        vidx_ref[:] = outs["vertex_indices"]
        vyear_ref[:] = outs["vertex_years"]
        vsrc_ref[:] = outs["vertex_src_vals"]
        vfit_ref[:] = outs["vertex_fit_vals"]
        smag_ref[:] = outs["seg_magnitude"]
        sdur_ref[:] = outs["seg_duration"]
        srate_ref[:] = outs["seg_rate"]
        rmse_ref[:] = outs["rmse"]
        pof_ref[:] = outs["p_of_f"]
        mv_ref[:] = outs["model_valid"].astype(jnp.int32)

    return kernel



def _prep_kernel_inputs(years, values, mask, ny: int, interpret: bool):
    """Shared wrapper preamble: x64 guard + ``(NY, ·)`` input layout.

    One definition for both entry points so the Mosaic-x64 workaround and
    the lane layout can never diverge between the test path
    (:func:`family_stats_pallas`) and the production fused path.
    """
    dtype = jnp.result_type(values.dtype, jnp.float32)
    if not interpret and jax.config.jax_enable_x64:
        # Mosaic's 64-bit-emulation convert_element_type lowering recurses
        # into itself (observed: infinite jaxpr_subcomp <-> convert loop
        # when tracing this kernel under jax_enable_x64), and re-tracing
        # under a nested enable_x64(False) context inside an outer x64
        # trace still leaks 64-bit weak types into the kernel.  Fail loud
        # with the working recipe instead of hanging the compiler.
        raise RuntimeError(
            "compiled Pallas kernel cannot trace under jax_enable_x64; "
            "wrap the call in `with jax.enable_x64(False):` at top level "
            "(f32 inputs), or pass interpret=True for the f64 path"
        )
    t_col = jnp.broadcast_to(years.astype(dtype)[:, None], (ny, 128))
    mask_b = mask.astype(bool) & jnp.isfinite(values)
    return dtype, t_col, values.astype(dtype).T, mask_b.astype(dtype).T


@functools.partial(
    jax.jit, static_argnames=("params", "block", "interpret")
)
def family_stats_pallas(
    years: jnp.ndarray,
    values: jnp.ndarray,
    mask: jnp.ndarray,
    params: LTParams = LTParams(),
    block: int = 256,
    interpret: bool = False,
):
    """Run the Pallas family kernel over a ``(PX, NY)`` batch.

    Returns ``(despiked (PX, NY), vmasks (PX, NM, NY) bool, sses (PX, NM))``
    — the inputs :func:`segment._select_and_assemble` needs.  PX must be a
    multiple of ``block`` (pad with fully-masked rows first).
    """
    px, ny = values.shape
    block = min(block, px)  # small batches: one block per batch
    if px % block:
        raise ValueError(f"pixel count {px} not a multiple of block {block}")
    nm = params.max_segments
    dtype, t_col, v_T, m_T = _prep_kernel_inputs(years, values, mask, ny, interpret)

    kernel = _make_family_kernel(ny, block, params, exact_atan=interpret)
    grid = (px // block,)
    desp_T, vm_T, sse_T = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((ny, 128), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((ny, block), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((ny, block), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((ny, block), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((nm, ny, block), lambda i: (0, 0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((nm, block), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((ny, px), dtype),
            jax.ShapeDtypeStruct((nm, ny, px), dtype),
            jax.ShapeDtypeStruct((nm, px), dtype),
        ],
        interpret=interpret,
    )(t_col, v_T, m_T)
    despiked = desp_T.T
    vmasks = jnp.transpose(vm_T, (2, 0, 1)) > 0
    sses = sse_T.T
    return despiked, vmasks, sses


@functools.partial(
    jax.jit, static_argnames=("params", "chunk", "block", "interpret")
)
def jax_segment_pixels_pallas_chunked(
    years: jnp.ndarray,
    values: jnp.ndarray,
    mask: jnp.ndarray,
    params: LTParams = LTParams(),
    chunk: int = 262144,
    block: int = 256,
    interpret: bool = False,
) -> SegOutputs:
    """:func:`jax_segment_pixels_pallas` with HBM bounded by ``chunk`` pixels.

    Same contract as :func:`segment.jax_segment_pixels_chunked`: the pixel
    count must be a multiple of ``chunk`` (pad with fully-masked rows), and
    ``lax.map`` streams the chunks through one compiled program.  Since the
    round-5 fusion the family intermediates never leave VMEM, so ``chunk``
    bounds only the ``(chunk, NY)`` input/despiked/fitted and per-pixel
    output buffers in HBM.
    """
    px = values.shape[0]
    if px % chunk:
        raise ValueError(
            f"pixel count {px} not a multiple of chunk {chunk}; pad first"
        )
    v = values.reshape(px // chunk, chunk, values.shape[1])
    m = mask.reshape(px // chunk, chunk, mask.shape[1])
    out = lax.map(
        lambda vm: jax_segment_pixels_pallas(
            years, vm[0], vm[1], params, block, interpret
        ),
        (v, m),
    )
    return SegOutputs(*(o.reshape(px, *o.shape[2:]) for o in out))


@functools.partial(
    jax.jit, static_argnames=("params", "block", "interpret")
)
def jax_segment_pixels_pallas(
    years: jnp.ndarray,
    values: jnp.ndarray,
    mask: jnp.ndarray,
    params: LTParams = LTParams(),
    block: int = 256,
    interpret: bool = False,
) -> SegOutputs:
    """:func:`segment.jax_segment_pixels` fully fused into one Pallas kernel.

    Same signature and output contract; PX must be a multiple of ``block``
    (use :func:`land_trendr_tpu.parallel.pad_to_multiple`).  On CPU pass
    ``interpret=True`` (Mosaic is TPU-only); interpret mode is
    dtype-generic, which is how the f64 oracle-parity tests drive it.
    The whole pipeline — despike through output assembly — runs inside the
    ``(NY, BLK)`` kernel (round 5; the round-4 split handed the family
    intermediates to an XLA ``_select_and_assemble`` tail over HBM).
    """
    px, ny = values.shape
    block = min(block, px)  # small batches: one block per batch
    if px % block:
        raise ValueError(f"pixel count {px} not a multiple of block {block}")
    nv, nm = params.max_vertices, params.max_segments
    dtype, t_col, v_T, m_T = _prep_kernel_inputs(years, values, mask, ny, interpret)

    kernel = _make_fused_kernel(ny, block, params, exact_atan=interpret)
    grid = (px // block,)

    def out(rows, dt):
        return (
            pl.BlockSpec((rows, block), lambda i: (0, i), memory_space=pltpu.VMEM),
            jax.ShapeDtypeStruct((rows, px), dt),
        )

    specs = [
        out(ny, dtype),          # despiked
        out(ny, dtype),          # fitted
        out(1, jnp.int32),       # n_vertices
        out(nv, jnp.int32),      # vertex_indices
        out(nv, dtype),          # vertex_years
        out(nv, dtype),          # vertex_src_vals
        out(nv, dtype),          # vertex_fit_vals
        out(nm, dtype),          # seg_magnitude
        out(nm, dtype),          # seg_duration
        out(nm, dtype),          # seg_rate
        out(1, dtype),           # rmse
        out(1, dtype),           # p_of_f
        out(1, jnp.int32),       # model_valid
    ]
    desp, fit, nvert, vidx, vyear, vsrc, vfit, smag, sdur, srate, rmse, pof, mv = (
        pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((ny, 128), lambda i: (0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((ny, block), lambda i: (0, i), memory_space=pltpu.VMEM),
                pl.BlockSpec((ny, block), lambda i: (0, i), memory_space=pltpu.VMEM),
            ],
            out_specs=[s for s, _ in specs],
            out_shape=[o for _, o in specs],
            interpret=interpret,
        )(t_col, v_T, m_T)
    )
    return SegOutputs(
        n_vertices=nvert[0],
        vertex_indices=vidx.T,
        vertex_years=vyear.T,
        vertex_src_vals=vsrc.T,
        vertex_fit_vals=vfit.T,
        seg_magnitude=smag.T,
        seg_duration=sdur.T,
        seg_rate=srate.T,
        rmse=rmse[0],
        p_of_f=pof[0],
        model_valid=mv[0] > 0,
        fitted=fit.T,
        despiked=desp.T,
    )
