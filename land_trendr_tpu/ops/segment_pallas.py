"""Pallas TPU kernel for the LandTrendr heavy middle (stages 1–4a).

Why this exists (measured, TPU_KERNEL_DIAG_r04.md): after the round-4
one-hot rewrite the XLA kernel is *instruction-bound* at ~3.4M px/s — and
its ceiling is set by layout, not math.  In the ``(px, NY)`` layout every
vector register carries NY=40 useful lanes out of 128 (3.2× instruction
inflation), and the stage boundaries (while-loop carries, reductions)
force HBM round trips between fused groups.  This kernel flips the block
layout to ``(NY, BLK)`` — years on sublanes (40 = 5 exact f32 sublane
tiles, zero padding), pixels on lanes — and keeps each block VMEM-resident
across ALL stages, so the whole per-pixel pipeline costs one HBM read and
one write.  A despike-only prototype measured 24.1M px/s against the XLA
stage's 3.8M on the same chip with bit-identical output.

Division of labour
------------------
The Pallas kernel computes the despiked series, the NM model-family vertex
masks, and each model's fitted SSE.  Everything from F-stat scoring onward
(betainc, selection, chosen-model refit, output assembly) stays in XLA via
:func:`land_trendr_tpu.ops.segment._select_and_assemble` — the single
shared tail both execution paths use.  ``jax.scipy.special.betainc`` has
no Mosaic lowering, and the tail is a small fraction of kernel time.

Semantics
---------
Decision-for-decision the same pipeline as :mod:`.segment` (which is the
parity-tested re-expression of the oracle).  Dynamic per-pixel reads use
the same two gather-free forms as the XLA kernel, re-expressed in the
year-major layout:

* nearest/previous-valid and vertex-cache reads → log-doubling
  forward/backward fills along the sublane (year) axis;
* vertex-slot reads (``t[vpos[k]]``) → rank-keyed masked reductions,
  where the rank is an exact int32 prefix sum of the vertex mask.

Fill/rank reads are *selected* elements (never arithmetic combinations),
and every arithmetic expression replicates the slot-space kernel's
operation order, so float results match the XLA kernel bit-for-bit on the
same platform up to reduction-order-neutral sums (verified by the parity
suites; the despike prototype matched exactly).  Mosaic portability notes:
boolean concatenate hits an ``i1`` vreg-cast bug in the tunnel's Mosaic,
so fill carries are f32 0/1; 1-D iota is illegal on TPU, so all index
vectors are ``broadcasted_iota``; argmax/argmin tie-breaks are expressed
as min-index-over-equal-to-extremum, which reproduces the oracle's
first-index rule in year order (== rank order, since vertex positions are
sorted).

Float64: Mosaic has no f64, so the compiled kernel is f32-only.  The
``interpret=True`` path executes the same trace with stock JAX ops on CPU
— dtype-generic, used by the f64 oracle-parity tests in
``tests/test_pallas.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from land_trendr_tpu.config import LTParams
from land_trendr_tpu.ops.segment import SegOutputs, _select_and_assemble

__all__ = [
    "jax_segment_pixels_pallas",
    "jax_segment_pixels_pallas_chunked",
    "family_stats_pallas",
]


def _shift(a: jnp.ndarray, sh: int, *, reverse: bool) -> jnp.ndarray:
    """Shift along the year (sublane) axis by a static amount, zero-filling."""
    if reverse:
        return jnp.concatenate([a[sh:], jnp.zeros_like(a[:sh])], axis=0)
    return jnp.concatenate([jnp.zeros_like(a[:sh]), a[:-sh]], axis=0)


def _fill(vals, valid_f, *, exclusive: bool, reverse: bool):
    """``(filled, has_f)`` nearest-valid fill along years; f32 0/1 carries."""
    ny = vals.shape[0]
    zero = jnp.zeros((), vals.dtype)
    v = jnp.where(valid_f > 0, vals, zero)
    has = valid_f
    if exclusive:
        v, has = _shift(v, 1, reverse=reverse), _shift(has, 1, reverse=reverse)
    sh = 1
    while sh < ny:
        hb = has > 0
        v = jnp.where(hb, v, _shift(v, sh, reverse=reverse))
        has = jnp.maximum(has, _shift(has, sh, reverse=reverse))
        sh *= 2
    return v, has


def _fill2(vals_a, vals_b, valid_f, *, exclusive: bool, reverse: bool):
    """Two fills sharing one has-chain (same valid mask)."""
    ny = vals_a.shape[0]
    zero_a = jnp.zeros((), vals_a.dtype)
    zero_b = jnp.zeros((), vals_b.dtype)
    va = jnp.where(valid_f > 0, vals_a, zero_a)
    vb = jnp.where(valid_f > 0, vals_b, zero_b)
    has = valid_f
    if exclusive:
        va = _shift(va, 1, reverse=reverse)
        vb = _shift(vb, 1, reverse=reverse)
        has = _shift(has, 1, reverse=reverse)
    sh = 1
    while sh < ny:
        hb = has > 0
        va = jnp.where(hb, va, _shift(va, sh, reverse=reverse))
        vb = jnp.where(hb, vb, _shift(vb, sh, reverse=reverse))
        has = jnp.maximum(has, _shift(has, sh, reverse=reverse))
        sh *= 2
    return va, vb, has


def _prefix_sum_incl(a_i32: jnp.ndarray) -> jnp.ndarray:
    """Inclusive int32 prefix sum along years (log-shift adds — exact)."""
    ny = a_i32.shape[0]
    s = a_i32
    sh = 1
    while sh < ny:
        s = s + _shift(s, sh, reverse=False)
        sh *= 2
    return s


def _prefix_max_incl(a_i32: jnp.ndarray) -> jnp.ndarray:
    """Inclusive int32 prefix max along years (log-shift — exact).

    Zero-fill shifts would corrupt negative carries, so shift a biased
    non-negative copy instead.
    """
    ny = a_i32.shape[0]
    s = a_i32 + ny  # bias: values in [-1, ny) -> [ny-1, 2ny)
    sh = 1
    while sh < ny:
        s = jnp.maximum(s, _shift(s, sh, reverse=False))
        sh *= 2
    return s - ny


def _first_true_idx(b, iota, ny):
    """Smallest year index where ``b`` (bool) holds; NY when none. (1, BLK)."""
    return jnp.min(jnp.where(b, iota, ny), axis=0, keepdims=True)


def _last_true_idx(b, iota):
    """Largest year index where ``b`` holds; -1 when none. (1, BLK)."""
    return jnp.max(jnp.where(b, iota, -1), axis=0, keepdims=True)


def _pick_at(a, iota, idx):
    """Value of ``a`` at year index ``idx`` ((1, BLK)); 0 when idx == NY."""
    zero = jnp.zeros((), a.dtype)
    return jnp.sum(jnp.where(iota == idx, a, zero), axis=0, keepdims=True)


def _masked_ols_ys(t, y, member_f):
    """(intercept, slope) (1, BLK) — replicates segment._masked_ols exactly."""
    dtype = t.dtype
    one = jnp.ones((), dtype)
    zero = jnp.zeros((), dtype)
    n = jnp.sum(member_f, axis=0, keepdims=True)
    n_safe = jnp.maximum(n, one)
    tm = jnp.sum(member_f * t, axis=0, keepdims=True) / n_safe
    ym = jnp.sum(member_f * y, axis=0, keepdims=True) / n_safe
    tc = (t - tm) * member_f
    stt = jnp.sum(tc * (t - tm), axis=0, keepdims=True)
    sty = jnp.sum(tc * (y - ym), axis=0, keepdims=True)
    ok = (n >= 2.0) & (stt > zero)
    slope = jnp.where(ok, sty / jnp.where(ok, stt, one), zero)
    intercept = ym - slope * tm
    return intercept, slope


def _clamp_slope_ys(slope, duration, y_range, params: LTParams):
    """Recovery-rate constraints — replicates segment._clamp_slope."""
    dtype = slope.dtype
    zero = jnp.zeros((), dtype)
    limit = -jnp.asarray(params.recovery_threshold, dtype) * y_range
    clamped = jnp.maximum(slope, limit)
    if params.prevent_one_year_recovery:
        clamped = jnp.where(duration <= 1.0, zero, clamped)
    active = (slope < zero) & (y_range > zero)
    return jnp.where(active, clamped, slope)


# Mosaic has no atan lowering; the angle cull needs one.  Degree-10-in-z²
# Chebyshev-fitted odd polynomial on [0,1] + the |x|>1 reciprocal reduction:
# measured max error 1.5e-7 (~2 ulp at atan scale; the [0,1] poly is
# 1.0e-7 and the reciprocal branch adds one rounding step) against
# np.arctan over a 2M-point grid (gated by tests/test_pallas.py).  Used ONLY in
# compiled mode — interpret mode keeps jnp.arctan so the f64 parity tests
# bit-match the oracle; compiled-mode f32 angle comparisons may flip at
# 1-2-ulp knife edges, which the f32 tolerance contract covers (measured:
# see tests/test_pallas.py and PARITY_f32_tpu.json methodology).
_ATAN_COEFS = (
    0.9999999996147207,
    -0.3333332366695538,
    0.19999595880653254,
    -0.14279048657228555,
    0.11053785942171465,
    -0.08796121057076967,
    0.0671012036450899,
    -0.04427374044156659,
    0.022203503960703006,
    -0.007166183020119105,
    0.0010844955030828492,
)


def _atan_poly(x: jnp.ndarray) -> jnp.ndarray:
    dtype = x.dtype
    one = jnp.ones((), dtype)
    ax = jnp.abs(x)
    big = ax > one
    z = jnp.where(big, one / jnp.maximum(ax, jnp.asarray(1e-30, dtype)), ax)
    u = z * z
    acc = jnp.asarray(_ATAN_COEFS[-1], dtype) + jnp.zeros_like(u)
    for c in _ATAN_COEFS[-2::-1]:
        acc = acc * u + jnp.asarray(c, dtype)
    r = z * acc
    half_pi = jnp.asarray(1.5707963267948966, dtype)
    r = jnp.where(big, half_pi - r, r)
    return jnp.where(x < 0, -r, r)


def _vertex_angle(xs_v, ys_v, xp_v, yp_v, xq_v, yq_v, interior, exact_atan: bool):
    """Angle at a vertex given its own and neighbour-vertex scaled coords.

    ONE definition serves both the full build (_angle_state_init, whole
    (NY, BLK) block) and the incremental patches (_remove_weakest_ys,
    (1, BLK) rows) — the bit-identity between them is structural.
    """
    dtype = xs_v.dtype
    one = jnp.ones((), dtype)
    big = jnp.asarray(1e30, dtype)  # > pi; replaces slot-space +inf sentinel
    dx1 = jnp.where(interior, xs_v - xp_v, one)
    dx2 = jnp.where(interior, xq_v - xs_v, one)
    s1 = (ys_v - yp_v) / dx1
    s2 = (yq_v - ys_v) / dx2
    atan = jnp.arctan if exact_atan else _atan_poly
    return jnp.where(interior, jnp.abs(atan(s2) - atan(s1)), big)


def _angle_state_init(xs, ys, vmask_f, exact_atan: bool):
    """Neighbour-fill tables + per-vertex angle table for the cull chains.

    ``(xp, yp, hasp, xq, yq, hasq, ang)`` — the scaled coords of each
    slot's previous/next VERTEX, and the angle at every vertex slot (BIG
    sentinel elsewhere).  A removal changes this state at O(1) slots per
    pixel, so the 8-deep remove chain (angle cull + model family) carries
    it across calls instead of re-filling and re-atan-ing the whole block
    each time (the removes were ~22% of kernel time — TPU_KERNEL_DIAG §7).
    """
    xp, yp, hasp = _fill2(xs, ys, vmask_f, exclusive=True, reverse=False)
    xq, yq, hasq = _fill2(xs, ys, vmask_f, exclusive=True, reverse=True)
    interior = (vmask_f > 0) & (hasp > 0) & (hasq > 0)
    ang = _vertex_angle(xs, ys, xp, yp, xq, yq, interior, exact_atan)
    return xp, yp, hasp, xq, yq, hasq, ang


def _remove_weakest_ys(
    vmask_f, state, xs, ys, iota, keep_above: int, exact_atan: bool
):
    """Drop the min-angle interior vertex while count > keep_above.

    Returns ``(vmask_new, state_new)``.  Incremental form: removing the
    interior vertex at ``pos`` changes the forward tables exactly on
    ``(pos, next_vertex]`` (their previous vertex was ``pos``), the
    backward tables exactly on ``[prev_vertex, pos)``, and the angle table
    only at ``prev_vertex``/``next_vertex`` (recomputed from the updated
    tables with the identical formula — bit-identical to a full rebuild,
    gated by the interpret bit-exact suite) plus the BIG sentinel at
    ``pos``.  ``prev/next_vertex`` exist whenever a removal fires: the
    argmin is masked to interior vertices.
    """
    dtype = xs.dtype
    ny = xs.shape[0]
    big = jnp.asarray(1e30, dtype)
    xp, yp, hasp, xq, yq, hasq, ang = state
    mn = jnp.min(ang, axis=0, keepdims=True)
    pos = _first_true_idx(ang == mn, iota, ny)
    n_verts = jnp.sum(vmask_f, axis=0, keepdims=True)
    do = n_verts > float(keep_above)
    vb = vmask_f > 0
    prv = _last_true_idx(vb & (iota < pos), iota)
    nxt = _first_true_idx(vb & (iota > pos), iota, ny)
    vmask_new = jnp.where(do & (iota == pos), jnp.zeros((), dtype), vmask_f)

    # table patches (picks taken from the PRE-update tables; pos itself is
    # outside both ranges, so order is immaterial)
    rngf = do & (iota > pos) & (iota <= nxt)
    rngb = do & (iota >= prv) & (iota < pos)
    xp_p = _pick_at(xp, iota, pos)
    yp_p = _pick_at(yp, iota, pos)
    hp_p = _pick_at(hasp, iota, pos)
    xq_p = _pick_at(xq, iota, pos)
    yq_p = _pick_at(yq, iota, pos)
    hq_p = _pick_at(hasq, iota, pos)
    xp = jnp.where(rngf, xp_p, xp)
    yp = jnp.where(rngf, yp_p, yp)
    hasp = jnp.where(rngf, hp_p, hasp)
    xq = jnp.where(rngb, xq_p, xq)
    yq = jnp.where(rngb, yq_p, yq)
    hasq = jnp.where(rngb, hq_p, hasq)

    def ang_at(j):
        # angle at vertex slot j from the UPDATED tables — the shared
        # _vertex_angle formula applied to (1, BLK) rows
        interior_j = (_pick_at(hasp, iota, j) > 0) & (_pick_at(hasq, iota, j) > 0)
        return _vertex_angle(
            _pick_at(xs, iota, j),
            _pick_at(ys, iota, j),
            _pick_at(xp, iota, j),
            _pick_at(yp, iota, j),
            _pick_at(xq, iota, j),
            _pick_at(yq, iota, j),
            interior_j,
            exact_atan,
        )

    ang = jnp.where(do & (iota == pos), big, ang)
    ang = jnp.where(do & (iota == prv), ang_at(prv), ang)
    ang = jnp.where(do & (iota == nxt), ang_at(nxt), ang)
    return vmask_new, (xp, yp, hasp, xq, yq, hasq, ang)


def _fit_model_ys(t, y, m_f, vmask_f, y_range, iota, params: LTParams):
    """One model's anchored fit + p2p fallback; returns SSE (1, BLK).

    Year-major re-expression of segment._fit_model with identical
    arithmetic per decision; vertex-slot reads become rank-keyed masked
    reductions and seg-of-year reads become fills.
    """
    dtype = t.dtype
    ny = t.shape[0]
    nv = params.max_vertices
    one = jnp.ones((), dtype)
    zero = jnp.zeros((), dtype)
    vb = vmask_f > 0
    m = m_f > 0

    n_verts = jnp.sum(vmask_f, axis=0, keepdims=True)
    cincl = _prefix_sum_incl(vmask_f.astype(jnp.int32))  # vertices at/before i
    rank = cincl - 1                                     # rank of a vertex AT i
    cexcl = cincl - vb.astype(jnp.int32)                 # vertices strictly before i

    # vertex-slot values: tv[k] == t[vpos[k]] via rank-keyed masked sums.
    # Slot POSITIONS are never materialised: segment membership is a rank
    # compare — a year belongs to segment k (years in (a_k, a_{k+1}])
    # exactly when cexcl == k+1, and to the closed [a_0, a_1] span when
    # cincl >= 1 & cexcl <= 1 — identical sets to the position compares
    # they replace, without the per-slot first-index reductions.
    tv = []
    for k in range(nv):
        sel = vb & (rank == k)
        tv.append(jnp.sum(jnp.where(sel, t, zero), axis=0, keepdims=True))

    # --- segment 0: OLS over closed [v0, v1] ---
    member0 = (cincl >= 1) & (cexcl <= 1) & m
    m0 = member0.astype(dtype)
    c0, c1 = _masked_ols_ys(t, y, m0)
    dur0 = tv[1] - tv[0]
    c1c = _clamp_slope_ys(c1, dur0, y_range, params)
    n0 = jnp.maximum(jnp.sum(m0, axis=0, keepdims=True), one)
    c0 = jnp.sum(m0 * y, axis=0, keepdims=True) / n0 - c1c * (
        jnp.sum(m0 * t, axis=0, keepdims=True) / n0
    )
    fitted = jnp.where(member0, c0 + c1c * t, zero)
    anchor_t = tv[1]
    anchor_y = c0 + c1c * anchor_t

    # --- segments 1..: slope-only regression through the anchor ---
    for k in range(1, nv - 1):
        active = (k + 1.0) < n_verts
        member = (cexcl == k + 1) & m & active
        mf = member.astype(dtype)
        dt = (t - anchor_t) * mf
        denom = jnp.sum(dt * dt, axis=0, keepdims=True)
        slope = jnp.where(
            denom > zero,
            jnp.sum(dt * (y - anchor_y), axis=0, keepdims=True)
            / jnp.where(denom > zero, denom, one),
            zero,
        )
        slope = _clamp_slope_ys(slope, tv[k + 1] - anchor_t, y_range, params)
        fitted = jnp.where(member, anchor_y + slope * (t - anchor_t), fitted)
        new_anchor_y = anchor_y + slope * (tv[k + 1] - anchor_t)
        anchor_t = jnp.where(active, tv[k + 1], anchor_t)
        anchor_y = jnp.where(active, new_anchor_y, anchor_y)

    # --- point-to-point fallback ---
    # per-year segment quantities: value at year i = value of the segment
    # whose START vertex is the largest vertex <= i, where the last vertex
    # belongs to the segment *ending* at it (slot-space min(rank, n-2))
    tnx, ynx, hasnx = _fill2(t, y, vmask_f, exclusive=True, reverse=True)
    dy_f = ynx - y
    dur_f = tnx - t
    viol = (dy_f < zero) & (y_range > zero) & (dur_f > zero)
    if params.prevent_one_year_recovery:
        fast = dur_f <= 1.0
    else:
        fast = jnp.zeros_like(viol)
    eps_rate = jnp.asarray(1e-12, dtype)  # segment._EPS_RATE
    viol = viol & (
        fast
        | (
            (-dy_f) / jnp.where(dur_f > zero, dur_f, one)
            > jnp.asarray(params.recovery_threshold, dtype) * y_range + eps_rate
        )
    )
    startv = vb & (hasnx > 0)  # vertices that start a segment
    p2p_ok = ~jnp.any(viol & startv, axis=0, keepdims=True)
    rate_f = jnp.where(dur_f > zero, dy_f / jnp.where(dur_f > zero, dur_f, one), zero)

    a0_pos = _first_true_idx(vb, iota, ny)
    last_pos = _last_true_idx(vb, iota)
    vmask_nl = jnp.where(iota == last_pos, zero, vmask_f)  # drop last vertex
    t_a, y_a, has_a = _fill2(t, y, vmask_nl, exclusive=False, reverse=False)
    rate_of, _ = _fill(rate_f, vmask_nl, exclusive=False, reverse=False)
    member_y = (iota >= a0_pos) & (iota <= last_pos) & m & (has_a > 0)
    p2p0 = jnp.where((iota == a0_pos) & m, y, zero)
    p2p = jnp.where(member_y, y_a + rate_of * (t - t_a), p2p0)

    span = m & (iota >= a0_pos) & (iota <= last_pos)
    sse_reg = jnp.sum(jnp.where(span, (y - fitted) ** 2, zero), axis=0, keepdims=True)
    sse_p2p = jnp.sum(jnp.where(span, (y - p2p) ** 2, zero), axis=0, keepdims=True)
    use_p2p = p2p_ok & (sse_p2p < sse_reg)
    return jnp.where(use_p2p, sse_p2p, sse_reg)


def _make_family_kernel(ny: int, blk: int, params: LTParams, exact_atan: bool):
    """Build the Pallas kernel body for static (NY, BLK, params)."""
    nv, nc, nm = params.max_vertices, params.max_candidates, params.max_segments

    def kernel(t_ref, v_ref, m_ref, desp_ref, vm_ref, sse_ref):
        dtype = v_ref.dtype
        one = jnp.ones((), dtype)
        zero = jnp.zeros((), dtype)
        t = t_ref[:, 0:1] + jnp.zeros((ny, blk), dtype)  # broadcast year axis
        m_f = m_ref[:]
        m = m_f > 0
        y = jnp.where(m, v_ref[:], zero)
        iota = lax.broadcasted_iota(jnp.int32, (ny, blk), 0)
        n_valid = jnp.sum(m_f, axis=0, keepdims=True)

        # ---- Stage 1: despike (early-exit per BLOCK, not per batch) ----
        if params.spike_threshold < 1.0:
            tp, hasp = _fill(t, m_f, exclusive=True, reverse=False)
            tq, hasq = _fill(t, m_f, exclusive=True, reverse=True)
            interior = m & (hasp > 0) & (hasq > 0)
            dtp = t - tp
            denom = jnp.where(interior, tq - tp, one)
            # the neighbour VALUE tables are carried incrementally: each
            # iteration modifies y at exactly one (valid, interior) slot i
            # per pixel, which changes yp only at the nearest valid slot
            # after i and yq only at the nearest valid slot before i — a
            # single selected write each, replacing two full fills per
            # trip (the fills are ~60% of the despike body's ops).  The
            # carried tables equal the per-trip fills at every slot the
            # body can read (interior slots; garbage between valid slots
            # matches the fills' don't-care regions), so results are
            # bit-identical — gated by tests/test_pallas.py's interpret
            # bit-exact suite.
            yp0, _ = _fill(y, m_f, exclusive=True, reverse=False)
            yq0, _ = _fill(y, m_f, exclusive=True, reverse=True)

            def body(carry):
                it, y, yp, yq, _ = carry
                itp = yp + (yq - yp) * dtp / denom
                dev = jnp.abs(y - itp)
                crossing = jnp.abs(yq - yp)
                prop = jnp.where(
                    dev > zero,
                    jnp.maximum(zero, one - crossing / jnp.where(dev > zero, dev, one)),
                    zero,
                )
                prop = jnp.where(interior, prop, -one)
                mx = jnp.max(prop, axis=0, keepdims=True)
                i_first = _first_true_idx(prop == mx, iota, ny)
                do = (mx > params.spike_threshold) & (it < n_valid)
                oh = iota == i_first
                delta = jnp.where(
                    do, (_pick_at(itp, iota, i_first) - _pick_at(y, iota, i_first)) * mx, zero
                )
                y_new = y + jnp.where(oh, delta, zero)
                y_i_new = _pick_at(y_new, iota, i_first)
                # when do holds, i is a valid interior slot, so these ARE
                # the only slots whose nearest-valid neighbour is i
                j_next = _first_true_idx(m & (iota > i_first), iota, ny)
                j_prev = _last_true_idx(m & (iota < i_first), iota)
                yp = jnp.where(do & (iota == j_next), y_i_new, yp)
                yq = jnp.where(do & (iota == j_prev), y_i_new, yq)
                return it + one, y_new, yp, yq, jnp.any(do)

            def cond(carry):
                it, _, _, _, cont = carry
                return cont & (it[0, 0] < ny)

            _, y, _, _, _ = lax.while_loop(
                cond,
                body,
                (jnp.zeros((1, blk), dtype), y, yp0, yq0, jnp.asarray(True)),
            )
        desp_ref[:] = y

        # ---- shared scalars ----
        big = jnp.asarray(jnp.finfo(dtype).max, dtype)
        y_lo = jnp.min(jnp.where(m, y, big), axis=0, keepdims=True)
        y_hi = jnp.max(jnp.where(m, y, -big), axis=0, keepdims=True)
        y_range = jnp.maximum(y_hi - y_lo, zero)
        first_v = _first_true_idx(m, iota, ny)
        last_v = _last_true_idx(m, iota)
        t_lo = _pick_at(t, iota, first_v)
        t_hi = _pick_at(t, iota, last_v)

        # ---- Stage 2: candidate vertices (max-deviation insertion) ----
        # The per-year segment-coefficient table and seg_start map are
        # CARRIED across insertion trips: inserting a vertex at i into
        # [lo, hi] changes them exactly on [lo, i) (refit left half) and
        # [i, hi) (right half) — range selects of freshly fit values,
        # bit-identical to the forward fills over a slot cache they
        # replace.  first/last vertex are loop-invariant (insertions are
        # strictly interior), so the per-trip first/last reductions and
        # the seg_start prefix-max rebuild go away too.
        vmask_f = jnp.where(m & ((iota == first_v) | (iota == last_v)), one, zero)
        lo0 = _first_true_idx(vmask_f > 0, iota, ny)
        member_i = (iota >= lo0) & (iota <= _last_true_idx(vmask_f > 0, iota)) & m
        c0i, c1i = _masked_ols_ys(t, y, member_i.astype(dtype))
        c0_at = c0i + jnp.zeros((ny, blk), dtype)
        c1_at = c1i + jnp.zeros((ny, blk), dtype)
        seg_start = jnp.clip(
            _prefix_max_incl(jnp.where(vmask_f > 0, iota, -1)), 0, ny - 1
        )

        for _ in range(nc - 2):
            dev = jnp.abs(y - (c0_at + c1_at * t))
            eligible = m & ~(vmask_f > 0) & (iota > first_v) & (iota < last_v)
            dev = jnp.where(eligible, dev, -one)
            mx = jnp.max(dev, axis=0, keepdims=True)
            i_first = _first_true_idx(dev == mx, iota, ny)
            do = mx >= zero
            lo = jnp.sum(
                jnp.where(iota == i_first, seg_start, 0), axis=0, keepdims=True
            )
            hi_raw = jnp.min(
                jnp.where((vmask_f > 0) & (iota > i_first), iota, ny),
                axis=0,
                keepdims=True,
            )
            hi = jnp.clip(hi_raw, 0, ny - 1)
            mem_a = (iota >= lo) & (iota <= i_first) & m
            mem_b = (iota >= i_first) & (iota <= hi) & m
            c0a, c1a = _masked_ols_ys(t, y, mem_a.astype(dtype))
            c0b, c1b = _masked_ols_ys(t, y, mem_b.astype(dtype))
            # right half wins the j == i slot, mirroring the slot cache's
            # .at[lo].set(·).at[i].set(·) overwrite order
            rng_a = do & (iota >= lo) & (iota < i_first)
            rng_b = do & (iota >= i_first) & (iota < hi_raw)
            c0_at = jnp.where(rng_b, c0b, jnp.where(rng_a, c0a, c0_at))
            c1_at = jnp.where(rng_b, c1b, jnp.where(rng_a, c1a, c1_at))
            seg_start = jnp.where(rng_b, i_first, seg_start)
            vmask_f = jnp.where(do & (iota == i_first), one, vmask_f)

        # ---- Stage 2b + 4a: the remove chain carries one angle state ----
        # (scaled coordinates replicate the slot-space scaling arithmetic)
        t_rng = jnp.where(t_hi > t_lo, t_hi - t_lo, one)
        y_rng_s = jnp.where(y_hi > y_lo, y_hi - y_lo, one)
        xsc = (t - t_lo) / t_rng
        ysc = (y - y_lo) / y_rng_s
        state = _angle_state_init(xsc, ysc, vmask_f, exact_atan)
        for _ in range(params.vertex_count_overshoot):
            vmask_f, state = _remove_weakest_ys(
                vmask_f, state, xsc, ysc, iota, nv, exact_atan
            )

        # ---- Stage 4a: model family (fit SSE, then prune weakest) ----
        for k in range(nm):
            vm_ref[k] = vmask_f
            sse = _fit_model_ys(t, y, m_f, vmask_f, y_range, iota, params)
            sse_ref[k] = sse[0]
            if k + 1 < nm:
                vmask_f, state = _remove_weakest_ys(
                    vmask_f, state, xsc, ysc, iota, 2, exact_atan
                )

    return kernel


@functools.partial(
    jax.jit, static_argnames=("params", "block", "interpret")
)
def family_stats_pallas(
    years: jnp.ndarray,
    values: jnp.ndarray,
    mask: jnp.ndarray,
    params: LTParams = LTParams(),
    block: int = 1024,
    interpret: bool = False,
):
    """Run the Pallas family kernel over a ``(PX, NY)`` batch.

    Returns ``(despiked (PX, NY), vmasks (PX, NM, NY) bool, sses (PX, NM))``
    — the inputs :func:`segment._select_and_assemble` needs.  PX must be a
    multiple of ``block`` (pad with fully-masked rows first).
    """
    px, ny = values.shape
    block = min(block, px)  # small batches: one block per batch
    if px % block:
        raise ValueError(f"pixel count {px} not a multiple of block {block}")
    nm = params.max_segments
    dtype = jnp.result_type(values.dtype, jnp.float32)
    if not interpret and jax.config.jax_enable_x64:
        # Mosaic's 64-bit-emulation convert_element_type lowering recurses
        # into itself (observed: infinite jaxpr_subcomp <-> convert loop
        # when tracing this kernel under jax_enable_x64), and re-tracing
        # under a nested enable_x64(False) context inside an outer x64
        # trace still leaks 64-bit weak types into the kernel.  Fail loud
        # with the working recipe instead of hanging the compiler.
        raise RuntimeError(
            "compiled Pallas kernel cannot trace under jax_enable_x64; "
            "wrap the call in `with jax.enable_x64(False):` at top level "
            "(f32 inputs), or pass interpret=True for the f64 path"
        )

    t_col = jnp.broadcast_to(years.astype(dtype)[:, None], (ny, 128))
    mask_b = mask.astype(bool) & jnp.isfinite(values)
    v_T = values.astype(dtype).T
    m_T = mask_b.astype(dtype).T

    kernel = _make_family_kernel(ny, block, params, exact_atan=interpret)
    grid = (px // block,)
    desp_T, vm_T, sse_T = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((ny, 128), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((ny, block), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((ny, block), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((ny, block), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((nm, ny, block), lambda i: (0, 0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((nm, block), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((ny, px), dtype),
            jax.ShapeDtypeStruct((nm, ny, px), dtype),
            jax.ShapeDtypeStruct((nm, px), dtype),
        ],
        interpret=interpret,
    )(t_col, v_T, m_T)
    despiked = desp_T.T
    vmasks = jnp.transpose(vm_T, (2, 0, 1)) > 0
    sses = sse_T.T
    return despiked, vmasks, sses


@functools.partial(
    jax.jit, static_argnames=("params", "chunk", "block", "interpret")
)
def jax_segment_pixels_pallas_chunked(
    years: jnp.ndarray,
    values: jnp.ndarray,
    mask: jnp.ndarray,
    params: LTParams = LTParams(),
    chunk: int = 262144,
    block: int = 1024,
    interpret: bool = False,
) -> SegOutputs:
    """:func:`jax_segment_pixels_pallas` with HBM bounded by ``chunk`` pixels.

    Same contract as :func:`segment.jax_segment_pixels_chunked`: the pixel
    count must be a multiple of ``chunk`` (pad with fully-masked rows), and
    ``lax.map`` streams the chunks through one compiled program.  Bounding
    the chunk also bounds the (chunk, NM, NY) family intermediates the
    Pallas path materialises between its kernel and the XLA tail.
    """
    px = values.shape[0]
    if px % chunk:
        raise ValueError(
            f"pixel count {px} not a multiple of chunk {chunk}; pad first"
        )
    v = values.reshape(px // chunk, chunk, values.shape[1])
    m = mask.reshape(px // chunk, chunk, mask.shape[1])
    out = lax.map(
        lambda vm: jax_segment_pixels_pallas(
            years, vm[0], vm[1], params, block, interpret
        ),
        (v, m),
    )
    return SegOutputs(*(o.reshape(px, *o.shape[2:]) for o in out))


@functools.partial(
    jax.jit, static_argnames=("params", "block", "interpret")
)
def jax_segment_pixels_pallas(
    years: jnp.ndarray,
    values: jnp.ndarray,
    mask: jnp.ndarray,
    params: LTParams = LTParams(),
    block: int = 1024,
    interpret: bool = False,
) -> SegOutputs:
    """:func:`segment.jax_segment_pixels` with the heavy middle on Pallas.

    Same signature and output contract; PX must be a multiple of ``block``
    (use :func:`land_trendr_tpu.parallel.pad_to_multiple`).  On CPU pass
    ``interpret=True`` (Mosaic is TPU-only); interpret mode is
    dtype-generic, which is how the f64 oracle-parity tests drive it.
    """
    dtype = jnp.result_type(values.dtype, jnp.float32)
    despiked, vmasks, sses = family_stats_pallas(
        years, values, mask, params, block, interpret
    )
    t = years.astype(dtype)
    mask_b = mask.astype(bool) & jnp.isfinite(values)
    raw = values.astype(dtype)
    return jax.vmap(
        lambda r, mb, y, vms, ss: _select_and_assemble(t, r, mb, y, vms, ss, params)
    )(raw, mask_b, despiked, vmasks, sses)
